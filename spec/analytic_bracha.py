"""Exact rounds-to-decision law for Bracha n=4, f=1 — Byzantine and adaptive_min.

Second closed-form anchor (VERDICT r2 #8), companion to spec/analytic.py's
Ben-Or chain: Bracha's three-step round with §5.1b message validation is the
subtlest logic in the repo (models/validation.py and its three independent
re-implementations), and cross-implementation equality cannot catch a shared
misreading — an exact constant derived from the *spec text* can.

Model (spec/PROTOCOL.md §5.2 + §5.1b + §4b/§4 + §6.3; n=4, f=1,
adversary="byzantine", both delivery models):

- One faulty replica (the FAULTY_RANK draw is independent of everything else
  and replicas are exchangeable, so it is fixed w.l.o.g.; its initial estimate
  is still uniform). Correct replicas: 3. Initial estimates iid uniform.
- Per step, the Byzantine sender's RBC outcome is iid uniform over
  {silent, 0, 1, honest} (spec §6.3: ``b = prf & 3`` with b=0 silent,
  b=1 value 0, b=2 value 1, b=3 the honest machine's value). The faulty
  replica runs the honest state machine internally (spec §5.1 last ¶) — its
  internal m/d/est evolve from its own deliveries; its own-message delivery
  carries its *wire* value (silent outcome ⇒ wire = honest value, spec §4b
  "own value = vals(v)").
- Validation (spec §5.1b, independent re-derivation): with q = n−f = 3,
  step-1 value 1 needs G0_1 ≥ ⌈q/2⌉ = 2; value 0 needs G0_0 ≥ ⌊q/2⌋+1 = 2;
  step-2 value y∈{0,1} needs G1_y ≥ ⌊n/2⌋+1 = 3; step-2 ⊥ needs
  max(0, q−G1_0, q−⌊n/2⌋) ≤ min(G1_1, q, ⌊n/2⌋). Invalid senders are
  silenced *before* delivery and drop out of the wait quota. Correct senders
  are provably never invalid — asserted during enumeration.
- Delivery (spec §4b / §4 — identical distribution at this config, which is
  why one chain covers both delivery models' laws): every receiver gets its
  own wire value plus min(L, n−f−1 = 2) of its L live others; when L = 3 the
  single dropped message is uniform over the live others (urn: stratum-
  uniform by remaining class counts ≡ uniform over live senders; keys: the
  largest of three exchangeable PRF keys), independent across receivers and
  steps. No scheduling bias: the Byzantine adversary sets none (spec §6.3).
- Round body per receiver (spec §5.2): m = majority of delivered step-0
  (ties→1); d = 1 if 2·S1_1 > n else 0 if 2·S1_0 > n else ⊥; step-2 over
  delivered non-⊥: w = 1 if D1 ≥ D0 else 0, c = D_w; decide iff c ≥ 2f+1 = 3,
  adopt est=w iff f+1 = 2 ≤ c ≤ 2f = 2, else est = coin. Decided replicas
  keep sending (est frozen) but never update.
- Termination: the instance's rounds-to-decision is the round in which the
  last *correct* replica decides (spec §1).

State between rounds: (faulty (est, decided), sorted multiset of correct
(est, decided)). Within a round the joint law over receivers factorizes given
the wire/silence profile (delivery draws are independent per receiver), so the
enumeration propagates a distribution over canonical trajectory multisets —
receivers are exchangeable given (own state, own derived values so far).

Exact constants (float64 on the 18-state chain; Monte-Carlo-resolution-proof)
are pinned in spec/PROTOCOL.md §8b and asserted against the vectorized numpy
backend for both delivery models and both coins in tests/test_statistics.py
(the cross-implementation bit-match web extends the pin to every other
backend).
"""

from __future__ import annotations

import itertools
from functools import lru_cache

import numpy as np

N, F = 4, 1
Q = N - F            # 3: the wait quota / validation witness size
K = N - F - 1        # 2: delivered others on top of own

# Byzantine per-step RBC outcomes (spec §6.3), each probability 1/4.
OUT_SILENT, OUT_ZERO, OUT_ONE, OUT_HONEST = range(4)
BOT = 2

# Third anchor (round 4): adversary="adaptive_min" (spec §6.4b) on the same
# chain skeleton. The injection is *deterministic* given the honest profile
# (faulty sends the observed minority, never silent), and delivery gains the
# minority-first strata: the single drop at L = 3 comes uniformly from the
# biased stratum (value != minority, or bot) when it is nonempty. The §4/§4b
# law equality argument extends: under the keys model biased messages carry
# bit 30, so the largest combined key — the dropped one — is uniform over the
# biased stratum by key exchangeability within it; exactly the urn's
# stratum-first class-proportional draw.


def _valid(step: int, value: int, g) -> bool:
    """spec §5.1b at n=4, f=1. ``g`` = (G_0, G_1) of the previous step."""
    g0, g1 = g
    if step == 1:
        if value == 1:
            return g1 >= (Q + 1) // 2
        if value == 0:
            return g0 >= Q // 2 + 1
        return True  # ⊥ never occurs at step 1, but is unconstrained
    if value == 1:
        return g1 >= N // 2 + 1
    if value == 0:
        return g0 >= N // 2 + 1
    return max(0, Q - g0, Q - N // 2) <= min(g1, Q, N // 2)


def _wire(step_vals, o):
    """Wire values + silence after Byzantine injection: ``step_vals`` are the
    honest machine values (faulty's first), ``o`` the faulty outcome."""
    vals = list(step_vals)
    silent = [False] * N
    if o == OUT_SILENT:
        silent[0] = True
    elif o == OUT_ZERO:
        vals[0] = 0
    elif o == OUT_ONE:
        vals[0] = 1
    return vals, silent


def _observed_minority(step_vals):
    """spec §6.4: minority among the correct replicas' non-bot values this
    step (ties -> 1). ``step_vals`` are the honest machine values, faulty
    first — the observation excludes index 0."""
    h1 = sum(1 for v in step_vals[1:] if v == 1)
    h0 = sum(1 for v in step_vals[1:] if v == 0)
    return 1 if h1 <= h0 else 0


def _wire_adaptive_min(step_vals):
    """spec §6.4b injection: deterministic — faulty sends the minority, never
    silent. Returns (vals, silent, minority)."""
    minority = _observed_minority(step_vals)
    vals = list(step_vals)
    vals[0] = minority
    return vals, [False] * N, minority


def _apply_validation(step, vals, silent, g_prev):
    """Silence invalid senders (spec §5.2: merged into the silent set before
    the delivery draw). Correct senders must never be invalid (§5.1b claim)."""
    out = list(silent)
    for u in range(N):
        if not _valid(step, vals[u], g_prev):
            assert u == 0, (
                f"spec §5.1b broken: correct sender {u} invalid "
                f"(step={step}, value={vals[u]}, g={g_prev})")
            out[u] = True
    return out


def _live_counts(vals, silent):
    """(G_0, G_1) over live senders — the next step's validation input."""
    return (sum(1 for u in range(N) if not silent[u] and vals[u] == 0),
            sum(1 for u in range(N) if not silent[u] and vals[u] == 1))


def _deliver_dist(own_val, others, minority=None):
    """{(c0, c1): p} — delivered counts at one receiver (spec §4b).

    ``others``: [cnt_0, cnt_1, cnt_⊥] of live other senders. L ≤ 3 others;
    at L = 3 one message is dropped, at L ≤ 2 everything live is delivered.
    Own message always on top. ``minority=None``: unbiased — the drop is
    uniform over live others (class probability proportional to class count —
    the single-stratum urn). ``minority`` set (spec §6.4b): the drop comes
    from the biased stratum (value != minority, or bot) when nonempty,
    uniformly within it.
    """
    L = sum(others)
    own = (1 if own_val == 0 else 0, 1 if own_val == 1 else 0)
    if L <= K:
        return {(others[0] + own[0], others[1] + own[1]): 1.0}
    if minority is None:
        pool = [0, 1, 2]
    else:
        pool = [w for w in (0, 1, 2) if (w == 2 or w != minority) and others[w]]
        if not pool:          # no biased message live: uniform over the rest
            pool = [0, 1, 2]
    tot = sum(others[w] for w in pool)
    out = {}
    for w in pool:
        if others[w] == 0:
            continue
        rem = list(others)
        rem[w] -= 1
        key = (rem[0] + own[0], rem[1] + own[1])
        out[key] = out.get(key, 0.0) + others[w] / tot
    return out


def _derive(step, counts):
    """Receiver update from delivered (c0, c1) (spec §5.2)."""
    c0, c1 = counts
    if step == 0:
        return 1 if c1 >= c0 else 0                      # m: ties → 1
    if step == 1:
        return 1 if 2 * c1 > N else (0 if 2 * c0 > N else BOT)   # d
    w = 1 if c1 >= c0 else 0
    c = c1 if w else c0
    if c >= 2 * F + 1:
        return ("decide", w)
    if c >= F + 1:
        return ("adopt", w)
    return ("coin", None)


def _product_over_receivers(recv_dists):
    """Joint law over the N receivers' outcomes — delivery draws are
    independent per receiver (spec §4b), so the joint is the product.
    Profiles stay ordered (index 0 = faulty); canonicalization happens only
    at round end."""
    out = {}
    for combo in itertools.product(*(d.items() for d in recv_dists)):
        vals = tuple(v for v, _ in combo)
        p = 1.0
        for _, pi in combo:
            p *= pi
        out[vals] = out.get(vals, 0.0) + p
    return out


def _round_transitions(state, coin, adversary="byzantine"):
    """{(next_state, all_correct_decided): prob} for one round."""
    f_state, c_states = state
    states = [f_state] + list(c_states)          # index 0 = faulty
    ests = [s[0] for s in states]
    decided = [s[1] for s in states]
    out = {}

    if adversary not in ("byzantine", "adaptive_min"):
        # "adaptive" (the class rule) is NOT enumerated here — a typo must not
        # silently return the adaptive_min chain's constants for it.
        raise ValueError(f"no exact chain for adversary {adversary!r}")
    if adversary == "byzantine":
        o_vecs = [(o, 0.25 ** 3) for o in itertools.product(range(4), repeat=3)]
    else:                 # adaptive_min: deterministic injection per step
        o_vecs = [((None,) * 3, 1.0)]

    def wire(step_vals, o):
        if adversary == "byzantine":
            vals, silent = _wire(step_vals, o)
            return vals, silent, None
        return _wire_adaptive_min(step_vals)

    for o_vec, p_o in o_vecs:
        # ---- step 0: honest values are the (frozen) estimates.
        vals0, silent0, min0 = wire(ests, o_vec[0])
        g0 = _live_counts(vals0, silent0)
        # Per-receiver m distribution.
        m_dists = []
        for v in range(N):
            others = [0, 0, 0]
            for u in range(N):
                if u != v and not silent0[u]:
                    others[vals0[u]] += 1
            dist_v = {}
            for cnts, pc in _deliver_dist(vals0[v], others, min0).items():
                m = _derive(0, cnts)
                dist_v[m] = dist_v.get(m, 0.0) + pc
            m_dists.append(dist_v)
        for m_prof, p_m in _product_over_receivers(m_dists).items():
            # ---- step 1: honest values are the m's; validation vs g0.
            vals1, silent1, min1 = wire(m_prof, o_vec[1])
            silent1 = _apply_validation(1, vals1, silent1, g0)
            g1 = _live_counts(vals1, silent1)
            d_dists = []
            for v in range(N):
                others = [0, 0, 0]
                for u in range(N):
                    if u != v and not silent1[u]:
                        others[vals1[u]] += 1
                dist_v = {}
                for cnts, pc in _deliver_dist(vals1[v], others, min1).items():
                    d = _derive(1, cnts)
                    dist_v[d] = dist_v.get(d, 0.0) + pc
                d_dists.append(dist_v)
            for d_prof, p_d in _product_over_receivers(d_dists).items():
                # ---- step 2: honest values are the d's; validation vs g1.
                vals2, silent2, min2 = wire(d_prof, o_vec[2])
                silent2 = _apply_validation(2, vals2, silent2, g1)
                act_dists = []
                for v in range(N):
                    others = [0, 0, 0]
                    for u in range(N):
                        if u != v and not silent2[u]:
                            others[vals2[u]] += 1
                    dist_v = {}
                    for cnts, pc in _deliver_dist(vals2[v], others, min2).items():
                        act = _derive(2, cnts)
                        dist_v[act] = dist_v.get(act, 0.0) + pc
                    act_dists.append(dist_v)
                for acts, p_a in _product_over_receivers(act_dists).items():
                    p_base = p_o * p_m * p_d * p_a
                    # ---- end of round: coin branches.
                    users = [v for v in range(N)
                             if not decided[v] and acts[v][0] == "coin"]
                    if coin == "shared":
                        coin_branches = [((b,) * N, 0.5) for b in (0, 1)] \
                            if users else [((0,) * N, 1.0)]
                    else:
                        coin_branches = []
                        for bits in itertools.product((0, 1), repeat=len(users)):
                            full = [0] * N
                            for v, b in zip(users, bits):
                                full[v] = b
                            coin_branches.append((tuple(full), 0.5 ** len(users)))
                    for coins, p_c in coin_branches:
                        nest, ndec = list(ests), list(decided)
                        for v in range(N):
                            if decided[v]:
                                continue
                            kind, w = acts[v]
                            if kind == "decide":
                                ndec[v] = True
                                nest[v] = w
                            elif kind == "adopt":
                                nest[v] = w
                            else:
                                nest[v] = coins[v]
                        ns = ((nest[0], ndec[0]),
                              tuple(sorted(zip(nest[1:], ndec[1:]))))
                        done = all(ndec[1:])
                        key = (ns, done)
                        out[key] = out.get(key, 0.0) + p_base * p_c
    return out


@lru_cache(maxsize=8)
def rounds_law(coin: str = "shared", adversary: str = "byzantine"):
    """Solve the chain exactly: returns (E_by_state, P1_by_state) where
    E is E[rounds to all-correct-decided | state] and P1 the probability the
    correct replicas' common decision is 1."""
    initial = set()
    for bits in itertools.product((0, 1), repeat=N):
        initial.add(((bits[0], False), tuple(sorted((e, False) for e in bits[1:]))))
    todo = list(initial)
    trans = {}
    while todo:
        s = todo.pop()
        if s in trans:
            continue
        t = _round_transitions(s, coin, adversary)
        trans[s] = t
        for (ns, done) in t:
            if not done and ns not in trans:
                todo.append(ns)
    states = sorted(trans)
    idx = {s: k for k, s in enumerate(states)}
    n = len(states)
    A = np.eye(n)
    b = np.ones(n)       # E[rounds]: +1 per round taken
    b1 = np.zeros(n)     # P[decide 1]: terminal mass on decision 1
    for s, ts in trans.items():
        i = idx[s]
        for (ns, done), p in ts.items():
            if done:
                # Terminal this round: rounds contribution already in b;
                # decision value = the correct replicas' common decided_val.
                vals = {e for e, d in ns[1]}
                assert len(vals) == 1, f"agreement violation in chain: {ns}"
                if vals.pop() == 1:
                    b1[i] += p
            else:
                A[i, idx[ns]] -= p
    # Same transition matrix for both first-step systems: one solve, two RHS.
    sol = np.linalg.solve(A, np.stack([b, b1], axis=1))
    E, P1 = sol[:, 0], sol[:, 1]
    return ({s: float(E[idx[s]]) for s in states},
            {s: float(P1[idx[s]]) for s in states})


@lru_cache(maxsize=8)
def expected_rounds_bracha_n4(coin: str = "shared",
                              adversary: str = "byzantine") -> float:
    """E[rounds], initial estimates iid uniform (incl. the faulty one)."""
    E, _ = rounds_law(coin, adversary)
    tot = 0.0
    for bits in itertools.product((0, 1), repeat=N):
        s = ((bits[0], False), tuple(sorted((e, False) for e in bits[1:])))
        tot += E[s]
    return tot / 2 ** N


@lru_cache(maxsize=8)
def p_decide_one_bracha_n4(coin: str = "shared",
                           adversary: str = "byzantine") -> float:
    """P[common decision = 1], initial estimates iid uniform. Exactly 1/2:
    at n=4 the delivered step-0/1 count is always 3 (odd — the m/d ties→1
    rules never fire) and a step-2 tie forces c ≤ 1 (the coin branch), so
    every ties→1 rule is outcome-irrelevant and the chain is 0↔1 symmetric
    (spec §8b). At larger n the tie-breaks do bias toward 1."""
    _, P1 = rounds_law(coin, adversary)
    tot = 0.0
    for bits in itertools.product((0, 1), repeat=N):
        s = ((bits[0], False), tuple(sorted((e, False) for e in bits[1:])))
        tot += P1[s]
    return tot / 2 ** N


if __name__ == "__main__":
    for adversary in ("byzantine", "adaptive_min"):
        for coin in ("shared", "local"):
            E, P1 = rounds_law(coin, adversary)
            print(f"{adversary}/{coin}: reachable undecided states: {len(E)}")
            print(f"  E[rounds]  (uniform init) = "
                  f"{expected_rounds_bracha_n4(coin, adversary):.6f}")
            print(f"  P[decide 1](uniform init) = "
                  f"{p_decide_one_bracha_n4(coin, adversary):.6f}")
