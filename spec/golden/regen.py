"""Regenerate the frozen golden vectors (spec/PROTOCOL.md §8).

Run as ``python -m spec.golden.regen`` from the repo root. Any diff in the committed
``golden.npz`` is a *spec change* and must be called out in review — these arrays are
the arbiter for both backends.
"""

from __future__ import annotations

import pathlib

import numpy as np

from byzantinerandomizedconsensus_tpu import SimConfig, Simulator

GOLDEN_CONFIGS = {
    "benor_n4": SimConfig(protocol="benor", n=4, f=1, instances=200, adversary="none",
                          coin="local", round_cap=128, seed=0),
    "benor_crash": SimConfig(protocol="benor", n=8, f=3, instances=100, adversary="crash",
                             coin="local", round_cap=256, seed=2),
    "bracha_byz": SimConfig(protocol="bracha", n=10, f=3, instances=100,
                            adversary="byzantine", coin="shared", round_cap=64, seed=1),
    "bracha_adaptive": SimConfig(protocol="bracha", n=13, f=4, instances=100,
                                 adversary="adaptive", coin="shared", round_cap=64, seed=3),
    # Urn delivery (spec §4b) — one per adversary family, incl. two-faced byz.
    "urn_benor_byz": SimConfig(protocol="benor", n=16, f=3, instances=100,
                               adversary="byzantine", coin="local", round_cap=64,
                               seed=4, delivery="urn"),
    "urn_bracha_crash": SimConfig(protocol="bracha", n=10, f=3, instances=100,
                                  adversary="crash", coin="shared", round_cap=64,
                                  seed=5, delivery="urn"),
    "urn_bracha_adaptive": SimConfig(protocol="bracha", n=13, f=4, instances=100,
                                     adversary="adaptive", coin="shared",
                                     round_cap=64, seed=6, delivery="urn"),
    # adaptive_min (spec §6.4b, added round 4) — both delivery models.
    "bracha_adaptive_min": SimConfig(protocol="bracha", n=13, f=4, instances=100,
                                     adversary="adaptive_min", coin="shared",
                                     round_cap=64, seed=7),
    "urn_bracha_adaptive_min": SimConfig(protocol="bracha", n=13, f=4,
                                         instances=100, adversary="adaptive_min",
                                         coin="shared", round_cap=64, seed=8,
                                         delivery="urn"),
    # Urn inversion (spec §4b-v2, added round 5) — one per adversary family,
    # incl. the two-faced Ben-Or Byzantine pairing and both adaptive strata.
    "urn2_benor_byz": SimConfig(protocol="benor", n=16, f=3, instances=100,
                                adversary="byzantine", coin="local", round_cap=64,
                                seed=9, delivery="urn2"),
    "urn2_bracha_crash": SimConfig(protocol="bracha", n=10, f=3, instances=100,
                                   adversary="crash", coin="shared", round_cap=64,
                                   seed=10, delivery="urn2"),
    "urn2_bracha_adaptive": SimConfig(protocol="bracha", n=13, f=4, instances=100,
                                      adversary="adaptive", coin="shared",
                                      round_cap=64, seed=11, delivery="urn2"),
    "urn2_bracha_adaptive_min": SimConfig(protocol="bracha", n=13, f=4,
                                          instances=100, adversary="adaptive_min",
                                          coin="shared", round_cap=64, seed=12,
                                          delivery="urn2"),
    # Cheap delivery law (spec §4c, added round 6) — one per adversary family,
    # incl. the two-faced Ben-Or Byzantine pairing and both adaptive strata.
    # §4c is a different delivery *distribution*, so these vectors pin the law
    # itself, not agreement with the §4b family.
    "urn3_benor_byz": SimConfig(protocol="benor", n=16, f=3, instances=100,
                                adversary="byzantine", coin="local", round_cap=64,
                                seed=13, delivery="urn3"),
    "urn3_bracha_crash": SimConfig(protocol="bracha", n=10, f=3, instances=100,
                                   adversary="crash", coin="shared", round_cap=64,
                                   seed=14, delivery="urn3"),
    "urn3_bracha_adaptive": SimConfig(protocol="bracha", n=13, f=4, instances=100,
                                      adversary="adaptive", coin="shared",
                                      round_cap=64, seed=15, delivery="urn3"),
    "urn3_bracha_adaptive_min": SimConfig(protocol="bracha", n=13, f=4,
                                          instances=100, adversary="adaptive_min",
                                          coin="shared", round_cap=64, seed=16,
                                          delivery="urn3"),
    "urn3_benor_none": SimConfig(protocol="benor", n=4, f=1, instances=100,
                                 adversary="none", coin="local", round_cap=128,
                                 seed=17, delivery="urn3"),
}

PATH = pathlib.Path(__file__).parent / "golden.npz"


def main() -> None:
    out = {}
    for name, cfg in GOLDEN_CONFIGS.items():
        res = Simulator(cfg, "cpu").run()
        out[f"{name}__rounds"] = res.rounds
        out[f"{name}__decision"] = res.decision
        print(f"{name}: mean_rounds={res.rounds.mean():.3f} "
              f"decisions={np.bincount(res.decision, minlength=3).tolist()}")
    np.savez_compressed(PATH, **out)
    print(f"wrote {PATH}")


if __name__ == "__main__":
    main()
