"""Message-level Bracha reliable-broadcast oracle — the spec §5.2 validation instrument.

The production simulator models RBC at the *count level*: per (sender, step) the
adversary picks an outcome in {silent, 0, 1, honest} and every correct receiver
observes that common outcome, subject only to §4 delivery timing. That abstraction
(SURVEY.md §7 hard-part 5) is the one assumption the cross-implementation bit-match
web cannot check — all four backends (oracle, numpy, jax, C++) *share* it — so this
module validates it from below with an independent per-message implementation of
Bracha's echo/ready/accept protocol [Bracha, Information & Computation 75, 1987]:

- :class:`Engine` simulates every protocol message (init/echo/ready) of up to n
  concurrent RBC broadcasts under an adversarial message scheduler with eventual
  delivery. Byzantine replicas send arbitrary scripted or reactive messages: full
  per-receiver equivocation, targeted sends, threshold teasing, rushing.
- The **quotient theorem** the count level relies on is asserted on every run:
  at every delivery prefix no two correct receivers have accepted different values
  from one sender (:meth:`Engine.check_safety`), and at quiescence acceptance is
  all-or-nothing with one common value per sender, with protocol-honest senders
  always accepted with the value they sent (:meth:`Engine.check_quiescence`).
  Those two facts are exactly guarantees (1)/(2) of spec §5.2.
- :func:`run_message_instance` re-runs the full §5.2 consensus round body on top
  of message-level RBC — message-level §5.1b validation included — and must
  reproduce the count-level oracle (backends/cpu.py) exactly: per-step RBC
  outcomes equal the count-level wire, the per-receiver deliveries equal the
  count-level model under the delivery-realizing schedule (the §4 mask rows via
  :func:`_make_mask_hold`, or the §4b/§4b-v2/§4c per-class delivered-count vectors
  via :func:`_make_counts_hold` — VERDICT r4 #3), and the final
  (rounds, decision) equals ``CpuBackend.run``.

Driven by tests/test_rbc_message.py: achievability (every count-level knob has a
message-level strategy realizing it, and only those outcomes ever occur), attack
strategies (split-brain init/echo/ready equivocation under adversarial schedules,
reactive rushing), the threshold boundary, and the instance-level oracle match at
n ∈ {4, 7, 10, 13, 16} across all three delivery models and every non-crash
adversary (crash included on the urn leg).

Pure scalar Python: this is an oracle-layer instrument (like spec/analytic_bracha),
never a performance path.
"""

from __future__ import annotations

import random
from collections import namedtuple
from typing import Callable, Iterable, Optional

import numpy as np

INIT, ECHO, READY = 0, 1, 2
KIND_NAMES = ("init", "echo", "ready")

# One point-to-point protocol message. ``inst`` identifies which sender's RBC
# broadcast the message belongs to (Bracha tags messages with the originating
# broadcast); ``src`` is authenticated by the channel, so INIT is only honored
# when src == inst (a Byzantine replica cannot forge another's init).
Msg = namedtuple("Msg", "inst kind value src dst")


class _View:
    """One receiver's Bracha bookkeeping for one RBC instance."""

    __slots__ = ("echoed", "ready_sent", "accepted", "echo_from", "ready_from")

    def __init__(self):
        self.echoed = None      # value this receiver echoed (first init wins)
        self.ready_sent = None  # the single ready value (Bracha: one per replica)
        self.accepted = None    # accepted value; None until 2f+1 readys
        self.echo_from = {}     # value -> set of distinct echo senders
        self.ready_from = {}    # value -> set of distinct ready senders


class Engine:
    """n concurrent message-level RBC broadcasts under one adversarial scheduler.

    Every replica — correct or faulty — runs the receiver bookkeeping (a faulty
    replica's internal honest machine observes the same wire; that is the §6.3
    convention the count-level model encodes). Rule-driven *sends* happen only for
    (replica, inst) pairs in protocol mode: correct replicas everywhere, faulty
    replicas only where a strategy marks them protocol-honest (the §6.3 b=3
    outcome). All other faulty output is owned by the strategy via :meth:`inject`
    (scripted) or :meth:`add_reactive` (rushing: observes every state-changing
    delivery — forged inits and duplicate echo/ready deliveries are inert and
    invisible to hooks).

    Scheduling: each :meth:`run` step delivers one uniformly random pending
    message (seeded ``rng``), or the minimum of ``priority`` when given. An
    optional ``hold`` predicate models adversarial withholding: held messages are
    deferred and re-examined whenever the pending queue drains — every message is
    still delivered in the end (eventual delivery), which is what makes
    quiescence-time assertions meaningful.
    """

    def __init__(self, n: int, f: int, faulty, rng: random.Random,
                 priority: Optional[Callable[["Engine", Msg], tuple]] = None,
                 hold: Optional[Callable[["Engine", Msg], bool]] = None,
                 check_every: int = 0):
        self.n, self.f = n, f
        self.faulty = [bool(x) for x in faulty]
        self.rng = rng
        self.priority = priority
        self.hold = hold
        self.check_every = check_every
        self.views = [[_View() for _inst in range(n)] for _recv in range(n)]
        self.protocol_send = [[not self.faulty[j]] * n for j in range(n)]
        self.pending: list[Msg] = []
        self.held: list[Msg] = []
        self.accept_order: list[list[tuple[int, int]]] = [[] for _ in range(n)]
        self.honest_sent: dict[int, int] = {}   # inst -> value, protocol-honest senders
        self.reactive: list[Callable[["Engine", Msg], Optional[Iterable[Msg]]]] = []
        self.delivered = 0

    # -- wiring ---------------------------------------------------------------
    def mark_protocol_honest(self, replica: int, inst: int) -> None:
        self.protocol_send[replica][inst] = True

    def start_broadcast(self, inst: int, value: int) -> None:
        """Protocol-honest INIT from ``inst`` for its own broadcast."""
        assert self.protocol_send[inst][inst], "sender not in protocol mode"
        self.honest_sent[inst] = int(value)
        self._broadcast(inst, INIT, int(value), inst)

    def inject(self, msgs: Iterable[Msg]) -> None:
        self.pending.extend(msgs)

    def add_reactive(self, hook) -> None:
        self.reactive.append(hook)

    def _broadcast(self, inst: int, kind: int, value: int, src: int) -> None:
        self.pending.extend(Msg(inst, kind, value, src, d) for d in range(self.n))

    # -- delivery -------------------------------------------------------------
    def _pick(self) -> int:
        if self.priority is None:
            return self.rng.randrange(len(self.pending))
        best_i, best_p = 0, None
        for i, m in enumerate(self.pending):
            p = self.priority(self, m)
            if best_p is None or p < best_p:
                best_i, best_p = i, p
        return best_i

    def _deliver(self, msg: Msg) -> None:
        self.delivered += 1
        n, f = self.n, self.f
        view = self.views[msg.dst][msg.inst]
        if msg.kind == INIT:
            if msg.src != msg.inst:
                return  # authenticated channels: forged init is inert
            if view.echoed is None:
                view.echoed = msg.value
                if self.protocol_send[msg.dst][msg.inst]:
                    self._broadcast(msg.inst, ECHO, msg.value, msg.dst)
        elif msg.kind == ECHO:
            s = view.echo_from.setdefault(msg.value, set())
            if msg.src in s:
                return
            s.add(msg.src)
            if 2 * len(s) > n + f and view.ready_sent is None:
                view.ready_sent = msg.value
                if self.protocol_send[msg.dst][msg.inst]:
                    self._broadcast(msg.inst, READY, msg.value, msg.dst)
        else:
            s = view.ready_from.setdefault(msg.value, set())
            if msg.src in s:
                return
            s.add(msg.src)
            if len(s) >= f + 1 and view.ready_sent is None:
                view.ready_sent = msg.value  # amplification
                if self.protocol_send[msg.dst][msg.inst]:
                    self._broadcast(msg.inst, READY, msg.value, msg.dst)
            if len(s) >= 2 * f + 1 and view.accepted is None:
                view.accepted = msg.value
                self.accept_order[msg.dst].append((msg.inst, msg.value))
        for hook in self.reactive:
            extra = hook(self, msg)
            if extra:
                self.pending.extend(extra)

    def run(self) -> None:
        """Deliver every message (eventual delivery), honoring holds."""
        while self.pending or self.held:
            if not self.pending:
                keep, release = [], []
                for m in self.held:
                    (keep if self.hold(self, m) else release).append(m)
                if not release:
                    raise AssertionError(
                        "scheduler deadlock: only held messages remain")
                self.held = keep
                self.pending.extend(release)
                continue
            msg = self.pending.pop(self._pick())
            if self.hold is not None and self.hold(self, msg):
                self.held.append(msg)
                continue
            self._deliver(msg)
            if self.check_every and self.delivered % self.check_every == 0:
                self.check_safety()
        self.check_safety()

    # -- invariants (the quotient theorem) ------------------------------------
    def check_safety(self) -> None:
        """Prefix-closed safety: per sender, no two correct receivers accept
        different values; no correct receiver accepts a value a protocol-honest
        sender didn't send; no two correct replicas send different readys."""
        for u in range(self.n):
            acc, rdy = set(), set()
            for v in range(self.n):
                if self.faulty[v]:
                    continue
                view = self.views[v][u]
                if view.accepted is not None:
                    acc.add(view.accepted)
                if view.ready_sent is not None:
                    rdy.add(view.ready_sent)
            assert len(acc) <= 1, f"split acceptance for sender {u}: {sorted(acc)}"
            assert len(rdy) <= 1, f"split readys for sender {u}: {sorted(rdy)}"
            if u in self.honest_sent:
                assert acc <= {self.honest_sent[u]}, (
                    f"honest sender {u} sent {self.honest_sent[u]}, accepted {sorted(acc)}")

    def check_quiescence(self) -> list[Optional[int]]:
        """At quiescence: acceptance is uniform across *all* bookkeeping receivers
        (faulty replicas' internal honest machines included — §6.3), and every
        protocol-honest sender is accepted with the value it sent. Returns the
        common outcome per sender (None = silent)."""
        assert not self.pending and not self.held
        outs: list[Optional[int]] = []
        for u in range(self.n):
            vals = {self.views[v][u].accepted for v in range(self.n)}
            assert len(vals) == 1, (
                f"acceptance not all-or-nothing for sender {u}: "
                f"{[self.views[v][u].accepted for v in range(self.n)]}")
            w = next(iter(vals))
            if u in self.honest_sent:
                assert w == self.honest_sent[u], (
                    f"honest sender {u}: sent {self.honest_sent[u]}, outcome {w}")
            outs.append(w)
        return outs

    def outcomes(self) -> list[Optional[int]]:
        return self.check_quiescence()


# -- scripted Byzantine strategies (the count-level knobs, and attacks on them) --

def scripted_push(eng: Engine, s: int, value: int, targets=None,
                  self_support: bool = False) -> None:
    """Faulty sender ``s`` pushes ``value``: INIT to ``targets`` (default: all);
    optionally adds its own echo+ready support. With targets ⊇ the correct set
    this realizes the count-level outcome ``value`` (2(n−f) > n+f ⟺ n > 3f)."""
    tg = range(eng.n) if targets is None else targets
    msgs = [Msg(s, INIT, value, s, d) for d in tg]
    if self_support:
        msgs += [Msg(s, ECHO, value, s, d) for d in range(eng.n)]
        msgs += [Msg(s, READY, value, s, d) for d in range(eng.n)]
    eng.inject(msgs)


def scripted_tease(eng: Engine, s: int, value: int, k: int,
                   helpers: Iterable[int] = ()) -> None:
    """INIT ``value`` to the first ``k`` correct replicas only, with ``helpers``
    (other faulty replicas) echoing ``value`` to everyone. Drives the echo count
    to exactly k + |helpers|: the outcome is ``value`` iff 2(k+|helpers|) > n+f,
    else silent — the threshold boundary probe."""
    correct = [j for j in range(eng.n) if not eng.faulty[j]]
    msgs = [Msg(s, INIT, value, s, d) for d in correct[:k]]
    for h in helpers:
        msgs += [Msg(s, ECHO, value, h, d) for d in range(eng.n)]
    eng.inject(msgs)


def scripted_split(eng: Engine, s: int, part0, part1,
                   helpers: Iterable[int] = (), dual_ready: bool = False) -> None:
    """Split-brain attack: INIT 0 to ``part0``, INIT 1 to ``part1``; helpers echo
    0 to part0 / 1 to part1 (full equivocation), optionally dual-ready both
    values everywhere. The outcome is schedule-dependent — exactly the freedom
    the count-level knob quotients — but must never split acceptance."""
    msgs = [Msg(s, INIT, 0, s, d) for d in part0]
    msgs += [Msg(s, INIT, 1, s, d) for d in part1]
    for h in helpers:
        msgs += [Msg(s, ECHO, 0, h, d) for d in part0]
        msgs += [Msg(s, ECHO, 1, h, d) for d in part1]
        if dual_ready:
            msgs += [Msg(s, READY, 0, h, d) for d in range(eng.n)]
            msgs += [Msg(s, READY, 1, h, d) for d in range(eng.n)]
    eng.inject(msgs)


def reactive_tipper(helpers: Iterable[int]):
    """Rushing adversary: whenever a correct replica is one echo short of the
    ready quorum for some value, every helper immediately echoes *the other*
    value to it — trying to race the replica's single ready to the wrong side
    and split the network."""
    helpers = list(helpers)

    def hook(eng: Engine, msg: Msg):
        if msg.kind != ECHO:
            return None
        view = eng.views[msg.dst][msg.inst]
        if view.ready_sent is not None:
            return None
        need = (eng.n + eng.f) // 2 + 1  # smallest c with 2c > n+f
        extra = []
        for value, senders in view.echo_from.items():
            if len(senders) == need - 1:
                other = 1 - value if value in (0, 1) else 0
                extra += [Msg(msg.inst, ECHO, other, h, msg.dst) for h in helpers
                          if h not in view.echo_from.get(other, set())]
        return extra
    return hook


# -- schedulers ---------------------------------------------------------------

def priority_value_first(value: int):
    """Deliver messages carrying ``value`` before everything else (random within
    a class): steers which side of a split-brain attack reaches quorum first."""
    def pri(eng: Engine, m: Msg):
        return (0 if m.value == value else 1, eng.rng.random())
    return pri


def priority_starve(receivers) -> Callable:
    """Deliver messages to ``receivers`` last — models a partition that heals."""
    rs = set(receivers)

    def pri(eng: Engine, m: Msg):
        return (1 if m.dst in rs else 0, eng.rng.random())
    return pri


# -- full consensus instance on message-level RBC (the oracle match) -----------

def _make_mask_hold(mask) -> Callable[[Engine, Msg], bool]:
    """Scheduler realizing the §4 delivery mask at message level: the final
    (accept-causing) READY of every non-target (receiver, sender) pair is
    withheld until the receiver's target accepts have all fired, so each
    receiver's first n−f−1 valid non-own accepts are exactly its mask row.
    Withholding only ever *defers* — :meth:`Engine.run` flushes all holds, so
    eventual delivery (and with it the §5.2 totality guarantee) is preserved."""
    targets = [set(int(u) for u in np.flatnonzero(row)) for row in mask]

    def hold(eng: Engine, msg: Msg) -> bool:
        if msg.kind != READY:
            return False
        v, u = msg.dst, msg.inst
        if u in targets[v]:
            return False
        view = eng.views[v][u]
        if view.accepted is not None:
            return False
        s = view.ready_from.get(msg.value, set())
        if msg.src in s or len(s) + 1 < 2 * eng.f + 1:
            return False  # not the accept-causing delivery
        return not all(w == v or eng.views[v][w].accepted is not None
                       for w in targets[v])
    return hold


def _make_counts_hold(values, silent_all, targets) -> Callable[[Engine, Msg], bool]:
    """Scheduler realizing a count-level delivered-count vector at message level
    — the count-domain analog of :func:`_make_mask_hold` (VERDICT r4 #3): the
    accept-causing READY of a live non-own sender whose wire-value class is
    already full at the receiver is withheld until the receiver's whole quota
    has accepted, so the first min(L, n−f−1) valid non-own accepts carry
    exactly the urn's per-class counts. ``targets[v]`` is the per-receiver
    non-own delivered count per wire value class (0, 1, ⊥) — feasible by
    construction (``r_w ≤ m_w``), so no deadlock: an under-target class always
    has a live sender left to admit. Withholding only ever defers —
    :meth:`Engine.run` flushes all holds, preserving eventual delivery."""

    def hold(eng: Engine, msg: Msg) -> bool:
        if msg.kind != READY:
            return False
        v, u = msg.dst, msg.inst
        if u == v or silent_all[u]:
            return False
        view = eng.views[v][u]
        if view.accepted is not None:
            return False
        s = view.ready_from.get(msg.value, set())
        if msg.src in s or len(s) + 1 < 2 * eng.f + 1:
            return False  # not the accept-causing delivery
        admitted = [0, 0, 0]
        for w in range(eng.n):
            if w != v and not silent_all[w] \
                    and eng.views[v][w].accepted is not None:
                admitted[int(values[w])] += 1
        if sum(admitted) >= sum(targets[v]):
            return False  # quota realized; later accepts sit beyond it
        return admitted[int(values[u])] >= targets[v][int(values[u])]

    return hold


def _urn_counts_and_targets(cfg, net, adv, r: int, t: int, honest, values,
                            silent_all):
    """Count-level §4b/§4b-v2/§4c delivery for one step: the (c0, c1) arrays from
    the oracle's urn sampler (strata per adversary, mirroring backends/cpu.py)
    plus the per-receiver non-own per-class targets they induce."""
    n, f = cfg.n, cfg.f
    if cfg.adversary == "adaptive":
        strata, minority = "class", 0
    elif cfg.adversary == "adaptive_min":
        strata, minority = "minority", adv.observed_minority(honest)
    else:
        strata, minority = "none", 0
    # The hold machinery (:func:`_make_counts_hold`) is law-agnostic: it
    # realizes ANY feasible per-class count vector (t_w ≤ m_w, Σ t_w =
    # min(L, n−f−1)). The §4c cheap law's support clamp guarantees exactly
    # that feasibility (d_w ∈ [max(0, Dr−(Lr−m_w)), min(m_w, Dr)], so the
    # remaining drops always fit the remaining classes) — the §4c-aware hold
    # is therefore the same hold fed §4c counts (ROADMAP r5 next #7).
    counts = {"urn": net.urn_counts, "urn2": net.urn2_counts,
              "urn3": net.urn3_counts}[cfg.delivery]
    c0, c1 = counts(r, t, [values, values], silent_all,
                    strata=strata, minority=minority)
    targets = []
    for v in range(n):
        own = int(values[v])
        live_no = sum(1 for u in range(n)
                      if u != v and not silent_all[u])
        quota = min(live_no, n - f - 1)
        # own message is always delivered, silence-exempt (spec §4/§4b) — the
        # urn counts include it unconditionally, the non-own targets never do.
        t0 = int(c0[v]) - (1 if own == 0 else 0)
        t1 = int(c1[v]) - (1 if own == 1 else 0)
        targets.append([t0, t1, quota - t0 - t1])
    return c0, c1, targets


def _realize_faulty_sender(eng: Engine, rng: random.Random, u: int,
                           wire_silent: bool, wire_value: int, honest_value: int) -> None:
    """Realize one count-level knob (silent, or common value ``wire_value``) for
    faulty sender ``u`` at message level, choosing a random realization variant.
    The asserted outcome is variant-invariant — that invariance is itself part of
    what the integration run validates."""
    n, f = eng.n, eng.f
    if wire_silent:
        if rng.random() < 0.5:
            return  # say nothing at all
        # below-threshold tease: k correct inits, no helpers — k ≤ (n+f)//2
        # by construction of the draw, so 2k ≤ n+f and no ready can fire
        k = rng.randrange(0, min(n - f, (n + f) // 2) + 1)
        scripted_tease(eng, u, rng.choice((0, 1)), k)
        return
    variant = rng.randrange(3 if wire_value != honest_value else 4)
    if variant == 3:
        # §6.3 b=3: behave honestly this step — full protocol participation
        eng.mark_protocol_honest(u, u)
        eng.start_broadcast(u, wire_value)
    elif variant == 2:
        correct = [j for j in range(n) if not eng.faulty[j]]
        scripted_push(eng, u, wire_value, targets=correct, self_support=False)
    else:
        scripted_push(eng, u, wire_value, self_support=bool(variant))


def run_message_instance(cfg, instance: int, rng: random.Random,
                         realize_rng: Optional[random.Random] = None):
    """Run one full §5.2 consensus instance on message-level RBC and assert,
    step by step, that it reproduces the count-level model exactly.

    Per (round, step): every sender's RBC is simulated message-by-message (the
    count-level adversary knob realized by a random message-level strategy); the
    engine invariants prove the quotient; the common outcomes are asserted equal
    to the count-level wire ``(values, silent)`` from ``Adversary.inject``;
    receiver-local §5.1b validation over the accepted outcomes is asserted equal
    to the global count-level predicate; and under the delivery-realizing
    schedule each receiver's wait-quota (first n−f valid accepts, own message
    in-head) is asserted equal to the count-level delivery — the §4 mask row
    under ``delivery="keys"`` (:func:`_make_mask_hold`), or the §4b/§4b-v2/§4c
    per-class delivered-count vector under ``delivery="urn"``/``"urn2"``/``"urn3"``
    (:func:`_make_counts_hold`, VERDICT r4 #3). State then evolves through the
    same ``Replica`` machine as backends/cpu.py; the caller compares the
    returned ``(rounds, decision)`` with ``CpuBackend.run``.
    """
    from byzantinerandomizedconsensus_tpu.backends.cpu import CpuBackend
    from byzantinerandomizedconsensus_tpu.core.adversary import make_adversary
    from byzantinerandomizedconsensus_tpu.core.network import Network
    from byzantinerandomizedconsensus_tpu.core.replica import Replica
    from byzantinerandomizedconsensus_tpu.ops import prf

    cfg = cfg.validate()
    assert cfg.protocol == "bracha", \
        "message-level validation targets the bracha protocol"
    count_level = cfg.delivery in ("urn", "urn2", "urn3")
    if realize_rng is None:
        realize_rng = random.Random(rng.randrange(1 << 30))
    n, f = cfg.n, cfg.f
    est0 = CpuBackend._initial_estimates(cfg, instance)
    reps = [Replica(cfg, j, int(est0[j])) for j in range(n)]
    net = Network(cfg, cfg.seed, instance)
    adv = make_adversary(cfg, cfg.seed, instance)
    faulty = adv.faulty
    correct = [j for j in range(n) if not faulty[j]]

    for r in range(cfg.round_cap):
        g_prev = None       # count-level live-valid counts of the previous step
        g_prev_msg = None   # same, recomputed from message-level outcomes
        for t in range(cfg.steps_per_round):
            honest = np.array([rep.send_value(t) for rep in reps], dtype=np.uint8)
            values, silent, bias = adv.inject(r, t, honest)
            invalid = np.zeros(n, dtype=bool)
            if t > 0:
                invalid = CpuBackend._invalid(cfg, t, values, g_prev)
            silent_all = silent | invalid
            g_prev = (int(np.count_nonzero(~silent_all & (values == 0))),
                      int(np.count_nonzero(~silent_all & (values == 1))))

            # ---- message level: n concurrent RBCs under the delivery-
            # realizing schedule (mask row / per-class count targets) ----
            if count_level:
                c0, c1, targets = _urn_counts_and_targets(
                    cfg, net, adv, r, t, honest, values, silent_all)
                eng = Engine(n, f, faulty, rng=rng,
                             hold=_make_counts_hold(values, silent_all, targets))
            else:
                mask = net.delivery_mask(r, t, silent_all, bias)
                eng = Engine(n, f, faulty, rng=rng, hold=_make_mask_hold(mask))
            for u in range(n):
                if not faulty[u]:
                    eng.start_broadcast(u, int(honest[u]))
                else:
                    _realize_faulty_sender(eng, realize_rng, u, bool(silent[u]),
                                           int(values[u]), int(honest[u]))
            eng.run()
            out = eng.check_quiescence()

            # RBC outcomes == the count-level wire (the §5.2 abstraction, leg 1)
            for u in range(n):
                expect = None if silent[u] else int(values[u])
                assert out[u] == expect, (
                    f"sender {u} outcome {out[u]} != count-level {expect} "
                    f"(r={r} t={t} inst={instance})")

            # receiver-local §5.1b validation over accepted outcomes == the
            # global count-level predicate (leg 2)
            if t > 0:
                out_vals = np.array([2 if o is None else o for o in out],
                                    dtype=np.uint8)
                inv_msg = CpuBackend._invalid(cfg, t, out_vals, g_prev_msg)
                live = ~silent
                assert np.array_equal(inv_msg[live], invalid[live]), (
                    f"message-level validation diverged (r={r} t={t})")
            g_prev_msg = (
                sum(1 for u in range(n)
                    if out[u] == 0 and not silent_all[u]),
                sum(1 for u in range(n)
                    if out[u] == 1 and not silent_all[u]))
            assert g_prev_msg == g_prev

            # wait-quota == the count-level delivery (leg 3): the first
            # n−f−1 valid non-own accepts in message-arrival order, plus the
            # own message in-head — set-equal to the §4 mask row (keys), or
            # class-count-equal to the count-level delivered-count vector (urn*).
            if count_level:
                for v in range(n):
                    seq = [u for (u, _w) in eng.accept_order[v]
                           if u != v and not silent_all[u]]
                    got = [0, 0, 0]
                    for u in seq[: n - f - 1]:
                        got[int(values[u])] += 1
                    assert got == targets[v], (
                        f"delivered class counts diverged at receiver {v} "
                        f"(r={r} t={t}): {got} != {targets[v]}")
                for rep in reps:
                    rep.on_counts(t, int(c0[rep.index]), int(c1[rep.index]))
            else:
                for v in range(n):
                    seq = [u for (u, _w) in eng.accept_order[v]
                           if u != v and not silent_all[u]]
                    quota = {v} | set(seq[: n - f - 1])
                    assert quota == set(int(u) for u in np.flatnonzero(mask[v])), (
                        f"delivered set diverged at receiver {v} (r={r} t={t})")
                vmat = np.broadcast_to(values, (n, n))
                for rep in reps:
                    rep.on_deliver(t, vmat[rep.index], mask[rep.index])

        if cfg.coin == "shared":
            shared = int(prf.prf_bit(cfg.seed, instance, r, prf.COIN_STEP, 0, 0,
                                     prf.SHARED_COIN, xp=np,
                                     pack=cfg.pack_version))
            coin = [shared] * n
        else:
            replica = np.arange(n, dtype=np.uint32)
            coin = prf.prf_bit(cfg.seed, instance, r, prf.COIN_STEP, replica, 0,
                               prf.LOCAL_COIN, xp=np, pack=cfg.pack_version)
        for rep in reps:
            rep.end_round(int(coin[rep.index]))
        if all(reps[j].decided for j in correct):
            vals = {reps[j].decided_val for j in correct}
            assert len(vals) == 1, f"Agreement violation: {sorted(vals)}"
            return r + 1, reps[correct[0]].decided_val
    # Agreement binds partial decided sets at the cap too (as in CpuBackend).
    vals = {reps[j].decided_val for j in correct if reps[j].decided}
    assert len(vals) <= 1, f"Agreement violation at round cap: {sorted(vals)}"
    return cfg.round_cap, 2


def run_message_instance_free(cfg, instance: int, rng: random.Random,
                              realize_rng: Optional[random.Random] = None):
    """Message-level consensus with NO count-level scheduling input at all: wait
    quotas are each receiver's first n−f−1 valid non-own accepts in raw
    message-arrival order under a free random schedule, and §5.1b validation is
    computed from message-level outcomes only. The delivered sets therefore
    differ from the §4 mask — per-instance results are *not* comparable to the
    count-level oracle — but the protocol's Agreement (asserted here) and
    Validity/liveness (asserted by the caller) must survive, which is the
    semantic-soundness half of the §5.2 abstraction argument."""
    from byzantinerandomizedconsensus_tpu.backends.cpu import CpuBackend
    from byzantinerandomizedconsensus_tpu.core.adversary import make_adversary
    from byzantinerandomizedconsensus_tpu.core.replica import Replica
    from byzantinerandomizedconsensus_tpu.ops import prf

    cfg = cfg.validate()
    assert cfg.protocol == "bracha"
    if realize_rng is None:
        realize_rng = random.Random(rng.randrange(1 << 30))
    n, f = cfg.n, cfg.f
    est0 = CpuBackend._initial_estimates(cfg, instance)
    reps = [Replica(cfg, j, int(est0[j])) for j in range(n)]
    adv = make_adversary(cfg, cfg.seed, instance)
    faulty = adv.faulty
    correct = [j for j in range(n) if not faulty[j]]

    def check_agreement():
        vals = {reps[j].decided_val for j in correct if reps[j].decided}
        assert len(vals) <= 1, f"Agreement violation: {sorted(vals)}"

    for r in range(cfg.round_cap):
        g_msg = None
        for t in range(cfg.steps_per_round):
            honest = np.array([rep.send_value(t) for rep in reps], dtype=np.uint8)
            values, silent, _bias = adv.inject(r, t, honest)
            eng = Engine(n, f, faulty, rng=rng)
            for u in range(n):
                if not faulty[u]:
                    eng.start_broadcast(u, int(honest[u]))
                else:
                    _realize_faulty_sender(eng, realize_rng, u, bool(silent[u]),
                                           int(values[u]), int(honest[u]))
            eng.run()
            out = eng.check_quiescence()
            out_vals = np.array([2 if o is None else o for o in out], dtype=np.uint8)
            dead = np.array([o is None for o in out], dtype=bool)
            invalid = np.zeros(n, dtype=bool)
            if t > 0:
                invalid = CpuBackend._invalid(cfg, t, out_vals, g_msg)
            skip = dead | invalid
            g_msg = (int(np.count_nonzero(~skip & (out_vals == 0))),
                     int(np.count_nonzero(~skip & (out_vals == 1))))
            mask = np.zeros((n, n), dtype=bool)
            for v in range(n):
                seq = [u for (u, _w) in eng.accept_order[v] if u != v and not skip[u]]
                mask[v, [v] + seq[: n - f - 1]] = True
            vmat = np.broadcast_to(values, (n, n))
            for rep in reps:
                rep.on_deliver(t, vmat[rep.index], mask[rep.index])
        if cfg.coin == "shared":
            shared = int(prf.prf_bit(cfg.seed, instance, r, prf.COIN_STEP, 0, 0,
                                     prf.SHARED_COIN, xp=np,
                                     pack=cfg.pack_version))
            coin = [shared] * n
        else:
            replica = np.arange(n, dtype=np.uint32)
            coin = prf.prf_bit(cfg.seed, instance, r, prf.COIN_STEP, replica, 0,
                               prf.LOCAL_COIN, xp=np, pack=cfg.pack_version)
        for rep in reps:
            rep.end_round(int(coin[rep.index]))
        check_agreement()
        if all(reps[j].decided for j in correct):
            return r + 1, reps[correct[0]].decided_val
    check_agreement()
    return cfg.round_cap, 2
