"""Exact expected rounds-to-decision for Ben-Or n=4, f=1 (SURVEY.md §4.4).

Closed-form anchor for the statistical suite (VERDICT r1 #5): a subtly wrong
protocol can pass cross-seed stability checks, but not an exact constant.

Model (spec/PROTOCOL.md §5.1 Protocol A, adversary="none", coin="local",
n=4, f=1 — benchmark config 1):

- Delivery: every receiver gets its own message plus exactly n−f−1 = 2 of the
  other 3, the dropped sender uniform over the 3 and independent across
  receivers and steps. The keys (§4) and urn (§4b) samplers both realize
  exactly this distribution at n=4, f=1 with no silent senders, so one chain
  covers both delivery models' *means* (bit-level draws differ).
- Step 0 (report): receiver with seen counts (c0, c1), c0+c1 = 3, proposes
  1 if 2·c1 > 4, 0 if 2·c0 > 4, else ⊥  (replica.py on_counts t=0).
- Step 1 (proposal): w = 1 if c1 ≥ c0 else 0 over non-⊥ proposals seen;
  decide iff c_w ≥ f+1 = 2; adopt est=w iff c_w ≥ 1; else est = fair coin
  (independent per replica — local coin).
- Decided replicas keep sending with est frozen (spec §1); the instance
  terminates at the end of the round in which the last replica decides.

State: multiset of per-replica (est, decided); replica exchangeability under
the uniform delivery makes the sorted tuple canonical. The absorbing state is
all-decided. E[rounds] solves the first-step linear system exactly (fractions
avoided — float64 on a ~25-state chain is exact to well below Monte-Carlo
resolution).

The resulting constant is pinned in spec/PROTOCOL.md §8a and asserted against
simulation in tests/test_statistics.py.
"""

from __future__ import annotations

import itertools
from functools import lru_cache

import numpy as np

N = 4


def _propose(ests, dropped_by):
    """Per-receiver proposals after step 0. ``dropped_by[i]`` = sender index
    whose message receiver i loses (never i itself)."""
    props = []
    for i in range(N):
        c1 = sum(ests[k] for k in range(N) if k != dropped_by[i])
        c0 = 3 - c1
        props.append(1 if 2 * c1 > N else (0 if 2 * c0 > N else 2))
    return props


def _step1(props, dropped_by):
    """Per-receiver (w, decide, adopt) after step 1."""
    out = []
    for i in range(N):
        c1 = sum(1 for k in range(N) if k != dropped_by[i] and props[k] == 1)
        c0 = sum(1 for k in range(N) if k != dropped_by[i] and props[k] == 0)
        w = 1 if c1 >= c0 else 0
        c = c1 if w else c0
        out.append((w, c >= 2, c >= 1))
    return out


def _round_transitions(state):
    """{next_state: probability} for one round from ``state`` (tuple of
    (est, decided) pairs, canonically sorted)."""
    drops = [tuple(j for j in range(N) if j != i) for i in range(N)]
    ests = [e for e, _ in state]
    decided = [d for _, d in state]
    out: dict = {}
    combos = list(itertools.product(*drops))
    p_combo = (1.0 / 3 ** N) ** 2
    for d0 in combos:
        props = _propose(ests, d0)
        for d1 in combos:
            acts = _step1(props, d1)
            # Coin branches: replicas that neither decide nor adopt.
            coin_users = [i for i in range(N)
                          if not decided[i] and not acts[i][1] and not acts[i][2]]
            for coins in itertools.product((0, 1), repeat=len(coin_users)):
                p = p_combo * 0.5 ** len(coin_users)
                nest, ndec = list(ests), list(decided)
                ci = iter(coins)
                for i in range(N):
                    if decided[i]:
                        continue
                    w, dec, adopt = acts[i]
                    if dec:
                        ndec[i] = True
                        nest[i] = w
                    elif adopt:
                        nest[i] = w
                    else:
                        nest[i] = next(ci)
                ns = tuple(sorted(zip(nest, ndec)))
                out[ns] = out.get(ns, 0.0) + p
    return out


def _solve_chain(round_transitions):
    """Solve E[rounds | state] for every state reachable from the 16 initial
    estimate vectors, under the given one-round transition function."""
    initial = [tuple(sorted((e, False) for e in bits))
               for bits in itertools.product((0, 1), repeat=N)]
    todo = list(dict.fromkeys(initial))
    trans: dict = {}
    while todo:
        s = todo.pop()
        if s in trans or all(d for _, d in s):
            continue
        trans[s] = round_transitions(s)
        for ns in trans[s]:
            if ns not in trans and not all(d for _, d in ns):
                todo.append(ns)
    states = sorted(trans)
    idx = {s: k for k, s in enumerate(states)}
    n = len(states)
    A = np.eye(n)
    b = np.ones(n)
    for s, ts in trans.items():
        for ns, p in ts.items():
            if ns in idx:
                A[idx[s], idx[ns]] -= p
    E = np.linalg.solve(A, b)
    return {s: float(E[idx[s]]) for s in states}


@lru_cache(maxsize=1)
def expected_rounds_by_state():
    """Solve E[rounds | state] exactly (uniform single-drop delivery — the
    law §4, §4b and §4b-v2 all realize at n=4, f=1 with no silent senders)."""
    return _solve_chain(_round_transitions)


@lru_cache(maxsize=1)
def expected_rounds_benor_n4() -> float:
    """E[rounds to all-decided], initial estimates uniform on {0,1}^4."""
    E = expected_rounds_by_state()
    total = 0.0
    for bits in itertools.product((0, 1), repeat=N):
        s = tuple(sorted((e, False) for e in bits))
        total += E.get(s, 0.0)  # absorbing (impossible initially) would be 0
    return total / 2 ** N


# ---------------------------------------------------------------------------
# Spec §4c ("urn3") anchor — same chain skeleton, different delivery law.
#
# §4c is NOT an exact sampler of the uniform-drop family above: its
# per-receiver drop is the mode-anchored bounded-correction law. At n=4, f=1
# with no silent senders the whole law reduces to ONE dropped value class per
# receiver-step (L=3, D=1), whose pmf is exactly computable by enumerating
# the two correction nibbles (segments 2 and 3; Binomial(4,1/2)−2 each, 16
# equally likely nibble values ⇒ all probabilities are multiples of 1/256).
# The §8d constant pins this law end-to-end through the Protocol-A round
# body, the way §8a pins the exact-family models.

# Binomial(4, 1/2) − 2 weights for the §4c correction, j = −2 … +2.
_URN3_CORR = tuple(zip(range(-2, 3), (1, 4, 6, 4, 1)))


def urn3_cheap_d(m: int, Lr: int, Dr: int, j: int) -> int:
    """One §4c segment evaluated at correction j (spec §4c): clamp(base + j,
    HG support). Mirrors ops/urn3.py::_cheap with the nibble popcount
    replaced by its value — the enumeration form."""
    den = max(Lr, 1)
    base = (2 * Dr * m + den) // (2 * den)
    lo = max(0, Dr - (Lr - m))
    hi = min(m, Dr)
    return min(max(base + j, lo), hi)


def urn3_segment_pmf(m: int, Lr: int, Dr: int) -> dict:
    """Exact pmf {d: probability} of one §4c segment (16 equally likely
    nibbles grouped through the popcount weights). The chain-level law test
    (tests/test_urn3.py) asserts the sampler against this closed form."""
    out: dict = {}
    for j, w in _URN3_CORR:
        d = urn3_cheap_d(m, Lr, Dr, j)
        out[d] = out.get(d, 0.0) + w / 16.0
    return out


@lru_cache(maxsize=None)
def urn3_drop_pmf(m0: int, m1: int, m2: int):
    """Exact dropped-class pmf {w: p} of the §4c law at L=3, D=1 (the n=4,
    f=1, no-silent shape): segment 2 samples d0 from (m0, 3, 1); on d0=0
    segment 3 samples d1 from (m1, 3−m0, 1); the remainder drops ⊥. The two
    corrections come from disjoint nibbles of one PRF word ⇒ independent."""
    assert m0 + m1 + m2 == 3
    pmf = {0: 0.0, 1: 0.0, 2: 0.0}
    for j2, w2 in _URN3_CORR:
        d0 = urn3_cheap_d(m0, 3, 1, j2)
        if d0 == 1:
            pmf[0] += w2 / 16.0
            continue
        for j3, w3 in _URN3_CORR:
            d1 = urn3_cheap_d(m1, 3 - m0, 1, j3)
            pmf[1 if d1 == 1 else 2] += (w2 / 16.0) * (w3 / 16.0)
    return pmf


def _urn3_receiver_pmfs(vals):
    """Per-receiver dropped-class pmf under §4c for one step's wire values."""
    out = []
    for i in range(N):
        m = [0, 0, 0]
        for k in range(N):
            if k != i:
                m[vals[k]] += 1
        out.append(urn3_drop_pmf(*m))
    return out


def _support(pmf):
    return [(w, p) for w, p in pmf.items() if p > 0.0]


def _round_transitions_urn3(state):
    """{next_state: probability} for one §4c round from ``state``. Unlike
    the uniform-drop chain (which enumerates dropped *senders*), §4c drops
    resolve only to value classes, so the enumeration is over per-receiver
    dropped classes weighted by the exact §4c pmf."""
    ests = [e for e, _ in state]
    decided = [d for _, d in state]
    t0_1 = sum(ests)        # step-0 wire totals, own message included
    t0_0 = N - t0_1
    out: dict = {}
    pmfs0 = _urn3_receiver_pmfs(ests)
    for drops0 in itertools.product(*[_support(p) for p in pmfs0]):
        p0 = 1.0
        props = []
        for i in range(N):
            w, pw = drops0[i]
            p0 *= pw
            c1 = t0_1 - (1 if w == 1 else 0)
            c0 = t0_0 - (1 if w == 0 else 0)
            props.append(1 if 2 * c1 > N else (0 if 2 * c0 > N else 2))
        t1_1 = sum(1 for x in props if x == 1)
        t1_0 = sum(1 for x in props if x == 0)
        pmfs1 = _urn3_receiver_pmfs(props)
        for drops1 in itertools.product(*[_support(p) for p in pmfs1]):
            p1 = p0
            acts = []
            for i in range(N):
                w, pw = drops1[i]
                p1 *= pw
                c1 = t1_1 - (1 if w == 1 else 0)
                c0 = t1_0 - (1 if w == 0 else 0)
                sel = 1 if c1 >= c0 else 0
                c = c1 if sel else c0
                acts.append((sel, c >= 2, c >= 1))
            coin_users = [i for i in range(N)
                          if not decided[i] and not acts[i][1] and not acts[i][2]]
            for coins in itertools.product((0, 1), repeat=len(coin_users)):
                p = p1 * 0.5 ** len(coin_users)
                nest, ndec = list(ests), list(decided)
                ci = iter(coins)
                for i in range(N):
                    if decided[i]:
                        continue
                    sel, dec, adopt = acts[i]
                    if dec:
                        ndec[i] = True
                        nest[i] = sel
                    elif adopt:
                        nest[i] = sel
                    else:
                        nest[i] = next(ci)
                ns = tuple(sorted(zip(nest, ndec)))
                out[ns] = out.get(ns, 0.0) + p
    return out


@lru_cache(maxsize=1)
def expected_rounds_by_state_urn3():
    """Solve E[rounds | state] exactly under the §4c delivery law."""
    return _solve_chain(_round_transitions_urn3)


@lru_cache(maxsize=1)
def expected_rounds_benor_n4_urn3() -> float:
    """E[rounds to all-decided] under §4c, initial estimates uniform on
    {0,1}^4 — the spec §8d constant."""
    E = expected_rounds_by_state_urn3()
    total = 0.0
    for bits in itertools.product((0, 1), repeat=N):
        s = tuple(sorted((e, False) for e in bits))
        total += E.get(s, 0.0)
    return total / 2 ** N


if __name__ == "__main__":
    E = expected_rounds_by_state()
    print(f"reachable undecided states: {len(E)}")
    for s, v in sorted(E.items(), key=lambda kv: kv[1]):
        print(f"  {s}: {v:.6f}")
    print(f"E[rounds] (uniform init) = {expected_rounds_benor_n4():.6f}")
    E3 = expected_rounds_by_state_urn3()
    print(f"§4c reachable undecided states: {len(E3)}")
    uni3 = tuple(sorted((e, False) for e in (0, 0, 0, 0)))
    split31 = tuple(sorted((e, False) for e in (0, 0, 0, 1)))
    split22 = tuple(sorted((e, False) for e in (0, 0, 1, 1)))
    print(f"§4c unanimous: {E3.get(uni3, 0.0):.6f}  3-1: {E3[split31]:.6f}  "
          f"2-2: {E3[split22]:.6f}")
    print(f"§4c E[rounds] (uniform init) = {expected_rounds_benor_n4_urn3():.6f}")
