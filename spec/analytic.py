"""Exact expected rounds-to-decision for Ben-Or n=4, f=1 (SURVEY.md §4.4).

Closed-form anchor for the statistical suite (VERDICT r1 #5): a subtly wrong
protocol can pass cross-seed stability checks, but not an exact constant.

Model (spec/PROTOCOL.md §5.1 Protocol A, adversary="none", coin="local",
n=4, f=1 — benchmark config 1):

- Delivery: every receiver gets its own message plus exactly n−f−1 = 2 of the
  other 3, the dropped sender uniform over the 3 and independent across
  receivers and steps. The keys (§4) and urn (§4b) samplers both realize
  exactly this distribution at n=4, f=1 with no silent senders, so one chain
  covers both delivery models' *means* (bit-level draws differ).
- Step 0 (report): receiver with seen counts (c0, c1), c0+c1 = 3, proposes
  1 if 2·c1 > 4, 0 if 2·c0 > 4, else ⊥  (replica.py on_counts t=0).
- Step 1 (proposal): w = 1 if c1 ≥ c0 else 0 over non-⊥ proposals seen;
  decide iff c_w ≥ f+1 = 2; adopt est=w iff c_w ≥ 1; else est = fair coin
  (independent per replica — local coin).
- Decided replicas keep sending with est frozen (spec §1); the instance
  terminates at the end of the round in which the last replica decides.

State: multiset of per-replica (est, decided); replica exchangeability under
the uniform delivery makes the sorted tuple canonical. The absorbing state is
all-decided. E[rounds] solves the first-step linear system exactly (fractions
avoided — float64 on a ~25-state chain is exact to well below Monte-Carlo
resolution).

The resulting constant is pinned in spec/PROTOCOL.md §8a and asserted against
simulation in tests/test_statistics.py.
"""

from __future__ import annotations

import itertools
from functools import lru_cache

import numpy as np

N = 4


def _propose(ests, dropped_by):
    """Per-receiver proposals after step 0. ``dropped_by[i]`` = sender index
    whose message receiver i loses (never i itself)."""
    props = []
    for i in range(N):
        c1 = sum(ests[k] for k in range(N) if k != dropped_by[i])
        c0 = 3 - c1
        props.append(1 if 2 * c1 > N else (0 if 2 * c0 > N else 2))
    return props


def _step1(props, dropped_by):
    """Per-receiver (w, decide, adopt) after step 1."""
    out = []
    for i in range(N):
        c1 = sum(1 for k in range(N) if k != dropped_by[i] and props[k] == 1)
        c0 = sum(1 for k in range(N) if k != dropped_by[i] and props[k] == 0)
        w = 1 if c1 >= c0 else 0
        c = c1 if w else c0
        out.append((w, c >= 2, c >= 1))
    return out


def _round_transitions(state):
    """{next_state: probability} for one round from ``state`` (tuple of
    (est, decided) pairs, canonically sorted)."""
    drops = [tuple(j for j in range(N) if j != i) for i in range(N)]
    ests = [e for e, _ in state]
    decided = [d for _, d in state]
    out: dict = {}
    combos = list(itertools.product(*drops))
    p_combo = (1.0 / 3 ** N) ** 2
    for d0 in combos:
        props = _propose(ests, d0)
        for d1 in combos:
            acts = _step1(props, d1)
            # Coin branches: replicas that neither decide nor adopt.
            coin_users = [i for i in range(N)
                          if not decided[i] and not acts[i][1] and not acts[i][2]]
            for coins in itertools.product((0, 1), repeat=len(coin_users)):
                p = p_combo * 0.5 ** len(coin_users)
                nest, ndec = list(ests), list(decided)
                ci = iter(coins)
                for i in range(N):
                    if decided[i]:
                        continue
                    w, dec, adopt = acts[i]
                    if dec:
                        ndec[i] = True
                        nest[i] = w
                    elif adopt:
                        nest[i] = w
                    else:
                        nest[i] = next(ci)
                ns = tuple(sorted(zip(nest, ndec)))
                out[ns] = out.get(ns, 0.0) + p
    return out


@lru_cache(maxsize=1)
def expected_rounds_by_state():
    """Solve E[rounds | state] for every reachable state exactly."""
    # Reachable exploration from all 16 initial estimate vectors.
    initial = [tuple(sorted((e, False) for e in bits))
               for bits in itertools.product((0, 1), repeat=N)]
    todo = list(dict.fromkeys(initial))
    trans: dict = {}
    while todo:
        s = todo.pop()
        if s in trans or all(d for _, d in s):
            continue
        trans[s] = _round_transitions(s)
        for ns in trans[s]:
            if ns not in trans and not all(d for _, d in ns):
                todo.append(ns)
    states = sorted(trans)
    idx = {s: k for k, s in enumerate(states)}
    n = len(states)
    A = np.eye(n)
    b = np.ones(n)
    for s, ts in trans.items():
        for ns, p in ts.items():
            if ns in idx:
                A[idx[s], idx[ns]] -= p
    E = np.linalg.solve(A, b)
    return {s: float(E[idx[s]]) for s in states}


@lru_cache(maxsize=1)
def expected_rounds_benor_n4() -> float:
    """E[rounds to all-decided], initial estimates uniform on {0,1}^4."""
    E = expected_rounds_by_state()
    total = 0.0
    for bits in itertools.product((0, 1), repeat=N):
        s = tuple(sorted((e, False) for e in bits))
        total += E.get(s, 0.0)  # absorbing (impossible initially) would be 0
    return total / 2 ** N


if __name__ == "__main__":
    E = expected_rounds_by_state()
    print(f"reachable undecided states: {len(E)}")
    for s, v in sorted(E.items(), key=lambda kv: kv[1]):
        print(f"  {s}: {v:.6f}")
    print(f"E[rounds] (uniform init) = {expected_rounds_benor_n4():.6f}")
