// Native simulation core — C++ implementation of the full protocol semantics
// (spec/PROTOCOL.md §§2-6), exposed through a C ABI and loaded via ctypes by
// byzantinerandomizedconsensus_tpu/backends/native_backend.py.
//
// Role in the framework (SURVEY.md §2): the reference's performance core is a
// CPU loop; ours is the JAX/TPU backend. This file is the *native runtime* leg:
// a multithreaded, allocation-free-per-round oracle accelerator that bit-matches
// the Python CPU oracle (tests/test_native.py) and makes large-n bit-match
// validation and host-side baselines cheap. It is deliberately a third,
// independent implementation of the spec (object oracle / vectorized-array /
// scalar C++): a semantic bug must now survive three codebases to go unnoticed.
//
// Randomness: the same Threefry-2x32 counter PRF as ops/prf.py, addressed by
// (seed, instance, round, step, recv, send, purpose) coordinates — draw order
// never matters, which is what makes cross-implementation bit-matching possible.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------- PRF (spec §2)

constexpr uint32_t kParity = 0x1BD11BDA;
constexpr int kRot[8] = {13, 15, 26, 6, 17, 29, 16, 24};

inline uint32_t rotl32(uint32_t x, int r) { return (x << r) | (x >> (32 - r)); }

// Threefry-2x32, 20 rounds; returns the first output word (matches
// jax._src.prng.threefry_2x32 word 0 — validated transitively through
// ops/prf.py in tests/test_native.py).
inline uint32_t threefry2x32(uint32_t k0, uint32_t k1, uint32_t x0, uint32_t x1) {
  const uint32_t ks[3] = {k0, k1, k0 ^ k1 ^ kParity};
  x0 += ks[0];
  x1 += ks[1];
  const uint32_t inj0[5] = {ks[1], ks[2], ks[0], ks[1], ks[2]};
  const uint32_t inj1[5] = {ks[2], ks[0], ks[1], ks[2], ks[0]};
  for (int g = 0; g < 5; ++g) {
    const int* rots = &kRot[(g % 2) * 4];
    for (int i = 0; i < 4; ++i) {
      x0 += x1;
      x1 = rotl32(x1, rots[i]);
      x1 ^= x0;
    }
    x0 += inj0[g];
    x1 += inj1[g] + static_cast<uint32_t>(g + 1);
  }
  return x0;
}

enum Purpose : uint32_t {
  kInitEst = 0,
  kLocalCoin = 1,
  kSharedCoin = 2,
  kFaultyRank = 3,
  kCrashRound = 4,
  kByzValue = 5,
  kSched = 6,
  kUrn = 7,
  kUrn2 = 8,
  kUrn3 = 9,
};

constexpr uint32_t kCoinStep = 3;

// Urn-delivery LCG (spec §4b): full period mod 2^32.
constexpr uint32_t kUrnLcgA = 0x915F77F5u;
constexpr uint32_t kUrnLcgC = 0x6A09E667u;

// The key carries the spec §2 packing version (1 or 2) alongside the split
// seed, so every prf_u32 call site stays a pure function of (key, coords)
// without threading an extra argument through the whole round body.
struct Key {
  uint32_t k0, k1;
  uint32_t pack;  // spec §2 packing law: 1 (n <= 1024, frozen) or 2 (§2 v2)
};

// Field packing per spec §2.
//   v1: x0 = (send << 17) | instance,
//       x1 = (rnd << 16) | (recv << 6) | (step << 4) | purpose
//   v2 (spec §2 v2, configs with n > 1024):
//       x0 = (send << 19) | instance,
//       x1 = (rnd << 20) | (recv << 8) | (step << 4) | purpose
inline uint32_t prf_u32(Key k, uint32_t instance, uint32_t rnd, uint32_t step,
                        uint32_t recv, uint32_t send, uint32_t purpose) {
  const uint32_t x0 = (k.pack == 2) ? (send << 19) | instance
                                    : (send << 17) | instance;
  const uint32_t x1 = (k.pack == 2)
      ? (rnd << 20) | (recv << 8) | (step << 4) | purpose
      : (rnd << 16) | (recv << 6) | (step << 4) | purpose;
  return threefry2x32(k.k0, k.k1, x0, x1);
}

inline uint32_t prf_bit(Key k, uint32_t instance, uint32_t rnd, uint32_t step,
                        uint32_t recv, uint32_t send, uint32_t purpose) {
  return prf_u32(k, instance, rnd, step, recv, send, purpose) & 1u;
}

// Sub-laws widened with the v2 packing (spec §2 v2; ops/prf.py RED_SHIFTS /
// KEY_LOW_BITS): the urn range reduction (v1 needs R < 2^10 to keep the
// product in uint32; v2 uses 12/20 for R < 2^12) and the packed sort keys'
// index field width (sender/replica: 10 | 12 bits).
inline uint32_t range_reduce(Key k, uint32_t u, uint32_t R) {
  return (k.pack == 2) ? ((u >> 12) * R) >> 20 : ((u >> 10) * R) >> 22;
}

inline int key_low_bits(Key k) { return (k.pack == 2) ? 12 : 10; }

// ------------------------------------------------------------------- config

enum Protocol { kBenor = 0, kBracha = 1 };
enum AdversaryKind { kNone = 0, kCrash = 1, kByzantine = 2, kAdaptive = 3,
                     kAdaptiveMin = 4 };
enum CoinKind { kLocal = 0, kShared = 1 };
enum InitKind { kRandom = 0, kAll0 = 1, kAll1 = 2, kSplit = 3 };
enum DeliveryKind { kKeys = 0, kUrnDelivery = 1, kUrn2Delivery = 2,
                    kUrn3Delivery = 3 };

struct Cfg {
  int protocol;
  int n;
  int f;
  int adversary;
  int coin;
  int init;
  uint64_t seed;
  int round_cap;
  int crash_window;
  int delivery;
};

inline bool lying_adversary(const Cfg& c) {
  return c.adversary == kByzantine || c.adversary == kAdaptive ||
         c.adversary == kAdaptiveMin;
}

// Count-level delivery models (spec §4b / §4b-v2): class-granular adversary
// structure, no per-receiver matrices.
inline bool count_level(const Cfg& c) {
  return c.delivery == kUrnDelivery || c.delivery == kUrn2Delivery ||
         c.delivery == kUrn3Delivery;
}

// ------------------------------------------------------------ per-thread state

// All scratch sized once per thread; the per-round hot path does no allocation.
struct Scratch {
  std::vector<uint8_t> est, decided, decided_val, prop, m, d, w_tmp;
  std::vector<uint8_t> honest, values, silent;           // per-sender (n)
  std::vector<uint8_t> vclass0, vclass1;                 // per-class values (§4b)
  std::vector<uint8_t> vmat;                             // per-(recv,send) (n*n)
  std::vector<uint8_t> bias;                             // per-(recv,send) (n*n)
  std::vector<uint8_t> faulty;
  std::vector<int32_t> crash_round;
  std::vector<uint32_t> combined, keys;                  // selection buffers (n)
  std::vector<int32_t> c0, c1;                           // per-receiver counts
  std::vector<uint8_t> decide_now, adopt;
  std::vector<uint8_t> coin;
  bool values_per_recv = false;  // vmat active (plain-Ben-Or Byzantine, spec §6.3)
  bool bias_per_recv = false;    // bias matrix active (adaptive, spec §6.4)
  bool two_faced = false;        // vclass0/1 active (urn Byzantine, spec §4b)

  explicit Scratch(int n)
      : est(n), decided(n), decided_val(n), prop(n), m(n), d(n), w_tmp(n),
        honest(n), values(n), silent(n), vclass0(n), vclass1(n),
        vmat(size_t(n) * n), bias(size_t(n) * n),
        faulty(n), crash_round(n), combined(n), keys(n), c0(n), c1(n),
        decide_now(n), adopt(n), coin(n) {}
};

// ------------------------------------------------------- setup (spec §3)

void setup_instance(const Cfg& cfg, Key k, uint32_t inst, Scratch& s) {
  const int n = cfg.n;
  // Initial estimates (spec §3.1).
  for (int j = 0; j < n; ++j) {
    switch (cfg.init) {
      case kAll0: s.est[j] = 0; break;
      case kAll1: s.est[j] = 1; break;
      case kSplit: s.est[j] = uint8_t(j & 1); break;
      default:
        s.est[j] = uint8_t(prf_bit(k, inst, 0, 0, uint32_t(j), 0, kInitEst));
    }
    s.decided[j] = 0;
    s.decided_val[j] = 0;
    s.prop[j] = 2;
    s.m[j] = 0;
    s.d[j] = 2;
    s.decide_now[j] = 0;
    s.adopt[j] = 0;
  }
  // Faulty set: the f smallest (rank | replica) keys (spec §3.2).
  if (cfg.adversary == kNone || cfg.f == 0) {
    std::fill(s.faulty.begin(), s.faulty.end(), uint8_t(0));
  } else {
    for (int j = 0; j < n; ++j) {
      const uint32_t rank =
          prf_u32(k, inst, 0, 0, uint32_t(j), 0, kFaultyRank);
      s.keys[j] = (rank & ((0xFFFFFFFFu >> key_low_bits(k)) << key_low_bits(k)))
                  | uint32_t(j);
    }
    s.combined = s.keys;  // scratch copy for nth_element
    std::nth_element(s.combined.begin(), s.combined.begin() + (cfg.f - 1),
                     s.combined.end());
    const uint32_t kth = s.combined[cfg.f - 1];
    for (int j = 0; j < n; ++j) s.faulty[j] = uint8_t(s.keys[j] <= kth);
  }
  // Crash rounds (spec §3.3).
  if (cfg.adversary == kCrash) {
    for (int j = 0; j < n; ++j) {
      const uint32_t c = prf_u32(k, inst, 0, 0, uint32_t(j), 0, kCrashRound);
      s.crash_round[j] = int32_t(c % uint32_t(cfg.crash_window));
    }
  }
}

// spec §6.4: minority among live honest non-bot votes this step (ties -> 1).
inline uint8_t observed_minority(const Scratch& s, int n) {
  int h0 = 0, h1 = 0;
  for (int j = 0; j < n; ++j) {
    if (s.faulty[j] || s.honest[j] == 2) continue;
    if (s.honest[j] == 1) ++h1;
    else ++h0;
  }
  return (h1 <= h0) ? 1 : 0;
}

// ------------------------------------------------- adversary inject (spec §6)

void inject(const Cfg& cfg, Key k, uint32_t inst, uint32_t rnd, uint32_t t,
            Scratch& s) {
  const int n = cfg.n;
  s.values_per_recv = false;
  s.bias_per_recv = false;
  s.two_faced = false;
  std::fill(s.silent.begin(), s.silent.end(), uint8_t(0));
  std::memcpy(s.values.data(), s.honest.data(), size_t(n));

  switch (cfg.adversary) {
    case kNone:
      return;
    case kCrash:
      for (int j = 0; j < n; ++j)
        s.silent[j] = uint8_t(s.faulty[j] && int32_t(rnd) >= s.crash_round[j]);
      return;
    case kByzantine:
      if (cfg.protocol == kBracha) {
        // RBC count-level outcome, common to all receivers (spec §6.3).
        for (int j = 0; j < n; ++j) {
          if (!s.faulty[j]) continue;
          const uint32_t b =
              prf_u32(k, inst, rnd, t, 0, uint32_t(j), kByzValue) & 3u;
          s.silent[j] = uint8_t(b == 0);
          if (b == 1) s.values[j] = 0;
          else if (b == 2) s.values[j] = 1;
          // b == 0 or 3: honest value retained.
        }
      } else if (count_level(cfg)) {
        // §4b two-faced equivocation: one value per receiver class.
        s.two_faced = true;
        for (int h = 0; h < 2; ++h) {
          uint8_t* vc = h ? s.vclass1.data() : s.vclass0.data();
          for (int j = 0; j < n; ++j) {
            if (s.faulty[j]) {
              const uint32_t e = prf_u32(k, inst, rnd, t, uint32_t(h),
                                         uint32_t(j), kByzValue);
              vc[j] = uint8_t(e % 3u);
            } else {
              vc[j] = s.honest[j];
            }
          }
        }
      } else {
        // Plain Ben-Or pairing: per-receiver equivocation matrix (spec §6.3).
        s.values_per_recv = true;
        for (int v = 0; v < n; ++v) {
          uint8_t* row = &s.vmat[size_t(v) * n];
          for (int j = 0; j < n; ++j) {
            if (s.faulty[j]) {
              const uint32_t e = prf_u32(k, inst, rnd, t, uint32_t(v),
                                         uint32_t(j), kByzValue);
              row[j] = uint8_t(e % 3u);  // {0, 1, 2 = silent-to-this-recv}
            } else {
              row[j] = s.honest[j];
            }
          }
        }
      }
      return;
    case kAdaptive: {
      // spec §6.4 — observe honest votes, push the minority value, bias delivery.
      const uint8_t minority = observed_minority(s, n);
      for (int j = 0; j < n; ++j)
        if (s.faulty[j]) s.values[j] = minority;
      if (count_level(cfg)) return;  // strata derived in-urn (§4b/§4b-v2)
      s.bias_per_recv = true;
      for (int v = 0; v < n; ++v) {
        const uint8_t pref = (v >= (n + 1) / 2) ? 1 : 0;
        uint8_t* row = &s.bias[size_t(v) * n];
        for (int j = 0; j < n; ++j) {
          const uint8_t vv = s.values[j];
          row[j] = uint8_t(vv == 2 || vv != pref);
        }
      }
      return;
    }
    case kAdaptiveMin: {
      // spec §6.4b — same value attack; global-minority-first scheduling.
      const uint8_t minority = observed_minority(s, n);
      for (int j = 0; j < n; ++j)
        if (s.faulty[j]) s.values[j] = minority;
      if (count_level(cfg)) return;  // strata derived in-urn (§4b/§4b-v2)
      // Receiver-independent bias: compute one row, replicate it.
      s.bias_per_recv = true;
      uint8_t* row0 = s.bias.data();
      for (int j = 0; j < n; ++j) {
        const uint8_t vv = s.values[j];
        row0[j] = uint8_t(vv == 2 || vv != minority);
      }
      for (int v = 1; v < n; ++v)
        std::memcpy(&s.bias[size_t(v) * n], row0, size_t(n));
      return;
    }
  }
}

// --------------------------------- Bracha count-level validation (spec §5.1b)

// Per-sender invalidity from the previous step's global live-valid counts;
// merged into the silent set before the delivery mask is drawn.
void silence_invalid(const Cfg& cfg, uint32_t t, int g0, int g1, Scratch& s) {
  const int n = cfg.n, f = cfg.f, q = n - f;
  bool ok[3];
  if (t == 1) {
    ok[1] = g1 >= (q + 1) / 2;
    ok[0] = g0 >= q / 2 + 1;
    ok[2] = true;
  } else {
    const int lo = std::max({0, q - g0, q - n / 2});
    const int hi = std::min({g1, q, n / 2});
    ok[1] = g1 >= n / 2 + 1;
    ok[0] = g0 >= n / 2 + 1;
    ok[2] = lo <= hi;
  }
  for (int j = 0; j < n; ++j)
    if (!ok[s.values[j]]) s.silent[j] = 1;
}

// --------------------------------------- delivery mask + tallies (spec §4)

// Per receiver: deliver the n-f live senders with the smallest combined key
// silent(1)|bias(1)|prf_top20(20)|sender(10); own message always delivered.
// Fused with the tally: c0/c1 per receiver, bot (=2) never counted.
void deliver_and_tally(const Cfg& cfg, Key k, uint32_t inst, uint32_t rnd,
                       uint32_t t, Scratch& s) {
  const int n = cfg.n, f = cfg.f;
  const int n_deliver = n - f;
  for (int v = 0; v < n; ++v) {
    const uint8_t* bias_row = s.bias_per_recv ? &s.bias[size_t(v) * n] : nullptr;
    const int low = key_low_bits(k);      // sender field: 10 | 12 bits (§2 v2)
    const int top = 30 - low;             // prf field: 20 | 18 bits
    for (int j = 0; j < n; ++j) {
      const uint32_t sched =
          prf_u32(k, inst, rnd, t, uint32_t(v), uint32_t(j), kSched);
      const uint32_t b = bias_row ? bias_row[j] : 0u;
      s.combined[j] = (uint32_t(s.silent[j]) << 31) | (b << 30) |
                      (((sched >> (32 - top)) & ((1u << top) - 1u)) << low) |
                      uint32_t(j);
    }
    s.combined[v] = uint32_t(v);  // own message always delivered (spec §4)
    s.keys = s.combined;          // keep original keys; nth_element permutes
    std::nth_element(s.keys.begin(), s.keys.begin() + (n_deliver - 1),
                     s.keys.end());
    const uint32_t kth = s.keys[n_deliver - 1];
    const uint8_t* vals = s.values_per_recv ? &s.vmat[size_t(v) * n] : s.values.data();
    int c0 = 0, c1 = 0;
    for (int j = 0; j < n; ++j) {
      const bool own = (j == v);
      const bool delivered = own || (s.combined[j] <= kth && !s.silent[j]);
      if (!delivered) continue;
      if (vals[j] == 0) ++c0;
      else if (vals[j] == 1) ++c1;
    }
    s.c0[v] = c0;
    s.c1[v] = c1;
  }
}

// ------------------------------------- urn delivery + tallies (spec §4b)

// Count-level scheduling: the D = L-(n-f-1) dropped messages are drawn from a
// per-receiver urn of (stratum, value)-classed live messages, biased stratum
// first. Mirrors ops/urn.py draw-for-draw (the spec's D-iteration form).
void urn_deliver_and_tally(const Cfg& cfg, Key k, uint32_t inst, uint32_t rnd,
                           uint32_t t, Scratch& s) {
  const int n = cfg.n, f = cfg.f;
  const int half = (n + 1) / 2;
  const int quota = n - f - 1;
  const bool adaptive = cfg.adversary == kAdaptive;
  const bool adaptive_min = cfg.adversary == kAdaptiveMin;
  const uint8_t minority = adaptive_min ? observed_minority(s, n) : 0;
  for (int v = 0; v < n; ++v) {
    const int h = (v >= half) ? 1 : 0;
    const uint8_t* vals =
        s.two_faced ? (h ? s.vclass1.data() : s.vclass0.data()) : s.values.data();
    int rem[3] = {0, 0, 0};
    for (int j = 0; j < n; ++j)
      if (j != v && !s.silent[j]) ++rem[vals[j]];
    const int total = rem[0] + rem[1] + rem[2];
    const int drops = std::max(0, total - quota);
    // biased(w) per spec §4b (class rule) / §6.4b (minority-first).
    const bool st[3] = {(adaptive && h != 0) || (adaptive_min && minority != 0),
                        (adaptive && h != 1) || (adaptive_min && minority != 1),
                        adaptive || adaptive_min};
    uint32_t state = prf_u32(k, inst, rnd, t, uint32_t(v), 0, kUrn);
    for (int dr = 0; dr < drops; ++dr) {
      state = state * kUrnLcgA + kUrnLcgC;
      const uint32_t u = state ^ (state >> 16);
      const int b_rem = (st[0] ? rem[0] : 0) + (st[1] ? rem[1] : 0) +
                        (st[2] ? rem[2] : 0);
      const bool in_biased = b_rem > 0;
      const int r_cur = in_biased ? b_rem : (rem[0] + rem[1] + rem[2]) - b_rem;
      const uint32_t d = range_reduce(k, u, uint32_t(r_cur));
      const uint32_t e0 = (st[0] == in_biased) ? uint32_t(rem[0]) : 0u;
      const uint32_t e1 = (st[1] == in_biased) ? uint32_t(rem[1]) : 0u;
      const int w = (d < e0) ? 0 : ((d < e0 + e1) ? 1 : 2);
      --rem[w];
    }
    const uint8_t own = vals[v];
    s.c0[v] = rem[0] + (own == 0 ? 1 : 0);
    s.c1[v] = rem[1] + (own == 1 ? 1 : 0);
  }
}

// ------------------------------- urn-v2 delivery + tallies (spec §4b-v2)

// d ~ HG(Lr, m, Dr) via the corner-minimal conditional-Bernoulli chain
// (spec §4b-v2): walk the smallest of {class items, drops, complement items},
// each step an exact exchangeability Bernoulli realized by the §4b
// range-reduction primitive. Seeded per (receiver, step, segment).
inline int hg_chain(Key k, uint32_t inst, uint32_t rnd, uint32_t t, uint32_t v,
                    uint32_t seg, int m, int Lr, int Dr) {
  const int comp = Lr - m;
  bool is_comp = false;
  int K, P;
  if (m <= comp && m <= Dr) {
    K = m;
    P = Dr;  // ITEM
  } else if (Dr <= comp) {
    K = Dr;
    P = m;  // DRAW
  } else {
    is_comp = true;
    K = comp;
    P = Dr;  // COMP
  }
  uint32_t s = prf_u32(k, inst, rnd, t, v, seg, kUrn2);
  int a = 0;
  for (int j = 0; j < K; ++j) {
    s = s * kUrnLcgA + kUrnLcgC;
    const uint32_t u = s ^ (s >> 16);
    const uint32_t q = range_reduce(k, u, uint32_t(Lr - j));
    if (q < uint32_t(P - a)) ++a;
  }
  return is_comp ? (Dr - a) : a;
}

// Direct dropped-count inversion: stratum split deterministic (biased first),
// within-stratum class split via nested hypergeometric chains. Mirrors
// ops/urn2.py segment-for-segment; same class/stratum state as
// urn_deliver_and_tally.
void urn2_deliver_and_tally(const Cfg& cfg, Key k, uint32_t inst, uint32_t rnd,
                            uint32_t t, Scratch& s) {
  const int n = cfg.n, f = cfg.f;
  const int half = (n + 1) / 2;
  const int quota = n - f - 1;
  const bool adaptive = cfg.adversary == kAdaptive;
  const bool adaptive_min = cfg.adversary == kAdaptiveMin;
  const uint8_t minority = adaptive_min ? observed_minority(s, n) : 0;
  for (int v = 0; v < n; ++v) {
    const int h = (v >= half) ? 1 : 0;
    const uint8_t* vals =
        s.two_faced ? (h ? s.vclass1.data() : s.vclass0.data()) : s.values.data();
    int m[3] = {0, 0, 0};
    for (int j = 0; j < n; ++j)
      if (j != v && !s.silent[j]) ++m[vals[j]];
    const int L = m[0] + m[1] + m[2];
    const int D = std::max(0, L - quota);
    const bool st[3] = {(adaptive && h != 0) || (adaptive_min && minority != 0),
                        (adaptive && h != 1) || (adaptive_min && minority != 1),
                        adaptive || adaptive_min};
    const int mb[3] = {st[0] ? m[0] : 0, st[1] ? m[1] : 0, st[2] ? m[2] : 0};
    const int Lb = mb[0] + mb[1] + mb[2];
    const int Db = std::min(D, Lb);
    int d[2] = {0, 0};
    int Lr = Lb, Dr = Db;
    for (int w = 0; w < 2; ++w) {  // segments 0-1: biased stratum
      const int dw = hg_chain(k, inst, rnd, t, uint32_t(v), uint32_t(w),
                              mb[w], Lr, Dr);
      d[w] += dw;
      Lr -= mb[w];
      Dr -= dw;
    }
    Lr = L - Lb;
    Dr = D - Db;
    for (int w = 0; w < 2; ++w) {  // segments 2-3: unbiased stratum
      const int mu = m[w] - mb[w];
      const int dw = hg_chain(k, inst, rnd, t, uint32_t(v), uint32_t(2 + w),
                              mu, Lr, Dr);
      d[w] += dw;
      Lr -= mu;
      Dr -= dw;
    }
    const uint8_t own = vals[v];
    s.c0[v] = m[0] - d[0] + (own == 0 ? 1 : 0);
    s.c1[v] = m[1] - d[1] + (own == 1 ? 1 : 0);
  }
}

// -------------------------------- urn-v3 delivery + tallies (spec §4c)

// Mode-anchored cheap drop law: d = clamp(round(Dr·m/Lr) + (popcount(nibble)
// − 2), HG support). One PRF word per receiver-step; segment g owns nibble
// bits [8g, 8g+4). O(1) integer work per receiver-step, no loop. NOT an
// exact sampler of the §4b family — a deliberate distribution-level change
// (spec §4c); the support clamp keeps every §5 count guarantee and collapses
// to the exact law on homogeneous strata. Mirrors ops/urn3.py
// segment-for-segment; same class/stratum state as the §4b/§4b-v2 legs.
inline int cheap_drop(uint32_t word, uint32_t seg, int m, int Lr, int Dr) {
  const uint32_t nib = (word >> (8 * seg)) & 0xFu;
  const int corr = int((nib & 1u) + ((nib >> 1) & 1u) + ((nib >> 2) & 1u) +
                       ((nib >> 3) & 1u)) - 2;
  const int den = std::max(Lr, 1);
  const int base = (2 * Dr * m + den) / (2 * den);
  const int lo = std::max(0, Dr - (Lr - m));
  const int hi = std::min(m, Dr);
  return std::min(std::max(base + corr, lo), hi);
}

void urn3_deliver_and_tally(const Cfg& cfg, Key k, uint32_t inst, uint32_t rnd,
                            uint32_t t, Scratch& s) {
  const int n = cfg.n, f = cfg.f;
  const int half = (n + 1) / 2;
  const int quota = n - f - 1;
  const bool adaptive = cfg.adversary == kAdaptive;
  const bool adaptive_min = cfg.adversary == kAdaptiveMin;
  const uint8_t minority = adaptive_min ? observed_minority(s, n) : 0;
  for (int v = 0; v < n; ++v) {
    const int h = (v >= half) ? 1 : 0;
    const uint8_t* vals =
        s.two_faced ? (h ? s.vclass1.data() : s.vclass0.data()) : s.values.data();
    int m[3] = {0, 0, 0};
    for (int j = 0; j < n; ++j)
      if (j != v && !s.silent[j]) ++m[vals[j]];
    const int L = m[0] + m[1] + m[2];
    const int D = std::max(0, L - quota);
    const bool st[3] = {(adaptive && h != 0) || (adaptive_min && minority != 0),
                        (adaptive && h != 1) || (adaptive_min && minority != 1),
                        adaptive || adaptive_min};
    const int mb[3] = {st[0] ? m[0] : 0, st[1] ? m[1] : 0, st[2] ? m[2] : 0};
    const int Lb = mb[0] + mb[1] + mb[2];
    const int Db = std::min(D, Lb);
    const uint32_t word = prf_u32(k, inst, rnd, t, uint32_t(v), 0, kUrn3);
    int d[2] = {0, 0};
    int Lr = Lb, Dr = Db;
    for (int w = 0; w < 2; ++w) {  // segments 0-1: biased stratum
      const int dw = cheap_drop(word, uint32_t(w), mb[w], Lr, Dr);
      d[w] += dw;
      Lr -= mb[w];
      Dr -= dw;
    }
    Lr = L - Lb;
    Dr = D - Db;
    for (int w = 0; w < 2; ++w) {  // segments 2-3: unbiased stratum
      const int mu = m[w] - mb[w];
      const int dw = cheap_drop(word, uint32_t(2 + w), mu, Lr, Dr);
      d[w] += dw;
      Lr -= mu;
      Dr -= dw;
    }
    const uint8_t own = vals[v];
    s.c0[v] = m[0] - d[0] + (own == 0 ? 1 : 0);
    s.c1[v] = m[1] - d[1] + (own == 1 ? 1 : 0);
  }
}

// ----------------------------------------------- protocol round (spec §5)

// One full round for one instance; updates Scratch state in place.
void run_round(const Cfg& cfg, Key k, uint32_t inst, uint32_t rnd, Scratch& s) {
  const int n = cfg.n, f = cfg.f;
  const bool lying = lying_adversary(cfg);
  const int steps = (cfg.protocol == kBenor) ? 2 : 3;
  int g0 = 0, g1 = 0;  // previous step's global live-valid counts (bracha)

  for (int t = 0; t < steps; ++t) {
    // Honest wire values (decided replicas keep participating — spec §1).
    for (int j = 0; j < n; ++j) {
      if (t == 0) s.honest[j] = s.est[j];
      else if (cfg.protocol == kBenor) s.honest[j] = s.prop[j];
      else s.honest[j] = (t == 1) ? s.m[j] : s.d[j];
    }
    inject(cfg, k, inst, rnd, uint32_t(t), s);
    if (cfg.protocol == kBracha) {
      if (t > 0) silence_invalid(cfg, uint32_t(t), g0, g1, s);
      g0 = g1 = 0;
      for (int j = 0; j < n; ++j) {
        if (s.silent[j]) continue;
        if (s.values[j] == 0) ++g0;
        else if (s.values[j] == 1) ++g1;
      }
    }
    if (cfg.delivery == kUrnDelivery)
      urn_deliver_and_tally(cfg, k, inst, rnd, uint32_t(t), s);
    else if (cfg.delivery == kUrn2Delivery)
      urn2_deliver_and_tally(cfg, k, inst, rnd, uint32_t(t), s);
    else if (cfg.delivery == kUrn3Delivery)
      urn3_deliver_and_tally(cfg, k, inst, rnd, uint32_t(t), s);
    else
      deliver_and_tally(cfg, k, inst, rnd, uint32_t(t), s);

    // Per-replica state-machine step (mirrors core/replica.py::on_deliver).
    for (int v = 0; v < n; ++v) {
      const int c0 = s.c0[v], c1 = s.c1[v];
      if (cfg.protocol == kBenor) {
        const int qrhs = lying ? n + f : n;
        if (t == 0) {
          s.prop[v] = (2 * c1 > qrhs) ? 1 : ((2 * c0 > qrhs) ? 0 : 2);
        } else {
          const uint8_t w = (c1 >= c0) ? 1 : 0;
          const int c = w ? c1 : c0;
          s.w_tmp[v] = w;
          s.decide_now[v] = lying ? uint8_t(2 * c > n + f) : uint8_t(c >= f + 1);
          s.adopt[v] = uint8_t(c >= (lying ? f + 1 : 1));
        }
      } else {
        if (t == 0) {
          s.m[v] = (c1 >= c0) ? 1 : 0;
        } else if (t == 1) {
          s.d[v] = (2 * c1 > n) ? 1 : ((2 * c0 > n) ? 0 : 2);
        } else {
          const uint8_t w = (c1 >= c0) ? 1 : 0;
          const int c = w ? c1 : c0;
          s.w_tmp[v] = w;
          s.decide_now[v] = uint8_t(c >= 2 * f + 1);
          s.adopt[v] = uint8_t(c >= f + 1);
        }
      }
    }
  }

  // Coin + end-of-round update (spec §5.3, §6.3 eligibility).
  if (cfg.coin == kShared) {
    const uint8_t bit =
        uint8_t(prf_bit(k, inst, rnd, kCoinStep, 0, 0, kSharedCoin));
    std::fill(s.coin.begin(), s.coin.end(), bit);
  } else {
    for (int j = 0; j < n; ++j)
      s.coin[j] =
          uint8_t(prf_bit(k, inst, rnd, kCoinStep, uint32_t(j), 0, kLocalCoin));
  }
  for (int j = 0; j < n; ++j) {
    if (s.decided[j]) continue;
    if (s.decide_now[j]) {
      s.decided[j] = 1;
      s.decided_val[j] = s.w_tmp[j];
      s.est[j] = s.w_tmp[j];
    } else if (s.adopt[j]) {
      s.est[j] = s.w_tmp[j];
    } else {
      s.est[j] = s.coin[j];
    }
  }
}

// --------------------------------------------------------------- instance

void run_instance(const Cfg& cfg, Key k, uint32_t inst, Scratch& s,
                  int32_t* rounds_out, uint8_t* decision_out) {
  setup_instance(cfg, k, inst, s);
  const int n = cfg.n;
  int first_correct = 0;
  while (first_correct < n && s.faulty[first_correct]) ++first_correct;

  for (int r = 0; r < cfg.round_cap; ++r) {
    run_round(cfg, k, inst, uint32_t(r), s);
    bool all_done = true;
    for (int j = 0; j < n; ++j) {
      if (!s.faulty[j] && !s.decided[j]) {
        all_done = false;
        break;
      }
    }
    if (all_done) {
      *rounds_out = r + 1;
      *decision_out = s.decided_val[first_correct];
      return;
    }
  }
  *rounds_out = cfg.round_cap;
  *decision_out = 2;  // overflow bucket (spec §1)
}

}  // namespace

// ------------------------------------------------------------------- C ABI

extern "C" {

// Simulate `count` instances (ids given explicitly — any subset, same contract
// as SimulatorBackend.run) across `n_threads` OS threads. Outputs are
// rounds_out (int32) and decision_out (uint8), both length `count`.
void sim_run(int protocol, int n, int f, int adversary, int coin, int init,
             uint64_t seed, int round_cap, int crash_window, int delivery,
             int pack, const int64_t* ids, int64_t count, int n_threads,
             int32_t* rounds_out, uint8_t* decision_out) {
  const Cfg cfg{protocol, n,    f,         adversary,   coin,
                init,     seed, round_cap, crash_window, delivery};
  const Key k{uint32_t(seed & 0xFFFFFFFFu), uint32_t((seed >> 32) & 0xFFFFFFFFu),
              uint32_t(pack)};

  if (n_threads < 1) n_threads = 1;
  if (int64_t(n_threads) > count) n_threads = int(count);

  auto worker = [&](int64_t lo, int64_t hi) {
    Scratch s(cfg.n);
    for (int64_t i = lo; i < hi; ++i)
      run_instance(cfg, k, uint32_t(ids[i]), s, &rounds_out[i], &decision_out[i]);
  };

  if (n_threads == 1) {
    worker(0, count);
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  const int64_t per = (count + n_threads - 1) / n_threads;
  for (int tix = 0; tix < n_threads; ++tix) {
    const int64_t lo = tix * per;
    const int64_t hi = std::min(count, lo + per);
    if (lo >= hi) break;
    threads.emplace_back(worker, lo, hi);
  }
  for (auto& th : threads) th.join();
}

// ABI version stamp so the Python loader can detect stale cached builds.
// v4: delivery enum grew kUrn3Delivery (spec §4c).
// v5: sim_run takes the spec §2 packing version (1 = frozen original law for
//     n <= 1024, 2 = §2 v2 wide-recv/send law) in the call contract.
int sim_abi_version() { return 5; }

}  // extern "C"
