"""Headline benchmark (BASELINE.json:2): config 4 — Bracha + shared coin, n=512,
f=170, 100k instances — run to termination on the JAX backend, reporting
consensus-instances/sec.

The north-star target (BASELINE.json:5) is 100k instances in < 60 s on a v4-8,
i.e. ~1,667 inst/s; ``vs_baseline`` is measured-throughput / that target. The
reference publishes no numbers of its own (BASELINE.json "published": {}), so the
driver-set target is the baseline.

Prints exactly ONE JSON line on stdout.
"""

from __future__ import annotations

import json
import sys


TARGET_INST_PER_SEC = 100_000 / 60.0  # north-star: 100k instances < 60 s


def _prev_round_headline():
    """(artifact_name, inst/s) from the previous round's BENCH_r*.json.

    The driver records bench output per round; comparing against the previous
    round's artifact is the perf-regression guard (VERDICT r2 #4): tunnel
    variance is ±10-15% (docs/PERF.md), so |vs_prev_round - 1| > 0.15 means a
    real change, not noise, and must be explained in PERF.md. Round anchoring
    and the unparseable-VERDICT warning live in utils/rounds.py.
    """
    from byzantinerandomizedconsensus_tpu.utils.rounds import prev_round_artifact

    def _value(doc):
        try:
            return float(doc.get("parsed", doc).get("value"))
        except (AttributeError, TypeError, ValueError):
            return None

    # Fall back to older rounds past dead captures (no usable value).
    found = prev_round_artifact("BENCH", usable=lambda d: _value(d) is not None)
    if not found:
        return None
    name, _rnd, doc = found
    return (name, _value(doc))


def main() -> int:
    import os

    from byzantinerandomizedconsensus_tpu import preset

    from byzantinerandomizedconsensus_tpu.backends import get_backend

    # Headless resilience: if the TPU tunnel is dead, fall back to CPU (with a
    # stderr warning + the platform recorded below) instead of hanging forever.
    from byzantinerandomizedconsensus_tpu.utils.devices import ensure_live_backend

    ensure_live_backend()

    instances = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    # The headline is the preset as shipped: config4 pins delivery="urn"
    # (spec §4b — count-level scheduling, O(n·f) per instance-step) on the
    # plain jax backend. BENCH_BACKEND (jax | jax_pallas | jax_sharded[:p])
    # and BENCH_DELIVERY=keys (spec §4 O(n²)-mask validation model, where
    # the fused Pallas kernel is the TPU fast path) remain for A/B runs.
    backend = sys.argv[2] if len(sys.argv) > 2 else os.environ.get("BENCH_BACKEND", "")
    delivery = os.environ.get("BENCH_DELIVERY", None)
    if not backend:
        import jax

        if delivery == "keys":
            backend = "jax_pallas" if jax.default_backend() == "tpu" else "jax"
        else:
            backend = "jax"
    overrides = {"instances": instances}
    if delivery is not None:
        overrides["delivery"] = delivery
    cfg = preset("config4", **overrides)

    # Warm-up compile at the exact run shape + best-of-five timed runs — the
    # shared measurement discipline (utils/timing.py; docs/PERF.md).
    from byzantinerandomizedconsensus_tpu.utils.timing import spread, timed_best_of

    res, walls = timed_best_of(get_backend(backend), cfg)
    wall = min(walls)

    inst_per_sec = instances / wall
    undecided = int((res.decision == 2).sum())
    prev = _prev_round_headline()
    print(json.dumps({
        "metric": "consensus_instances_per_sec@n512_f170_shared_coin",
        "value": round(inst_per_sec, 1),
        "unit": "instances/s",
        "vs_baseline": round(inst_per_sec / TARGET_INST_PER_SEC, 3),
        **({"vs_prev_round": round(inst_per_sec / prev[1], 3),
            "prev_round_artifact": prev[0]} if prev else {}),
        "detail": {
            "platform": __import__("jax").default_backend(),
            "instances": instances,
            "wall_s": round(wall, 2),
            "walls_s": [round(w, 3) for w in walls],
            "walls_spread": round(spread(walls), 3),
            "mean_rounds_to_decision": round(float(res.rounds.mean()), 4),
            "undecided": undecided,
        },
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
