"""Headline benchmark (BASELINE.json:2): config 4 — Bracha + shared coin, n=512,
f=170, 100k instances — run to termination on the JAX backend, reporting
consensus-instances/sec.

The north-star target (BASELINE.json:5) is 100k instances in < 60 s on a v4-8,
i.e. ~1,667 inst/s; ``vs_baseline`` is measured-throughput / that target. The
reference publishes no numbers of its own (BASELINE.json "published": {}), so the
driver-set target is the baseline.

Prints exactly ONE JSON line on stdout.
"""

from __future__ import annotations

import json
import sys


TARGET_INST_PER_SEC = 100_000 / 60.0  # north-star: 100k instances < 60 s


def _prev_round_headline():
    """(artifact_name, inst/s, device_busy_s|None) from the previous round's
    BENCH_r*.json.

    The driver records bench output per round; comparing against the previous
    round's artifact is the perf-regression guard (VERDICT r2 #4): tunnel
    variance is ±10-15% (docs/PERF.md), so |vs_prev_round - 1| > 0.15 means a
    real change, not noise, and must be explained in PERF.md — and when the
    capture window is noisier than that, the device-busy comparison is the
    authoritative signal (VERDICT r4 #2; utils/timing.regression_verdict).
    Round anchoring and the unparseable-VERDICT warning live in
    utils/rounds.py.
    """
    from byzantinerandomizedconsensus_tpu.utils.rounds import prev_round_artifact

    def _doc(doc):
        return doc.get("parsed", doc) if isinstance(doc, dict) else {}

    def _value(doc):
        try:
            return float(_doc(doc).get("value"))
        except (AttributeError, TypeError, ValueError):
            return None

    # Fall back to older rounds past dead captures (no usable value).
    found = prev_round_artifact("BENCH", usable=lambda d: _value(d) is not None)
    if not found:
        return None
    name, _rnd, doc = found
    detail = _doc(doc).get("detail", {})
    dev = detail.get("device_busy_s") if isinstance(detail, dict) else None
    return (name, _value(doc), dev)


def main() -> int:
    import os

    from byzantinerandomizedconsensus_tpu import preset

    from byzantinerandomizedconsensus_tpu.backends import get_backend

    # Headless resilience: if the TPU tunnel is dead, fall back to CPU (with a
    # stderr warning + the platform recorded below) instead of hanging forever.
    from byzantinerandomizedconsensus_tpu.utils.devices import ensure_live_backend

    ensure_live_backend()

    instances = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    # The headline is the preset as shipped: config4 pins the product
    # scheduling model (config.PRODUCT_DELIVERY — spec §4b-v2 "urn2" since
    # round 5) on the plain jax backend. BENCH_BACKEND
    # (jax | jax_pallas | jax_sharded[:p]) and BENCH_DELIVERY
    # (urn = the §4b cross-check sampler; keys = the spec-§4 O(n²)-mask
    # validation model, where the fused Pallas kernel is the TPU fast path)
    # remain for A/B runs.
    backend = sys.argv[2] if len(sys.argv) > 2 else os.environ.get("BENCH_BACKEND", "")
    delivery = os.environ.get("BENCH_DELIVERY", None)
    # BENCH_COMPACTION=1 (or a policy spelling like "width=4096,segment=1")
    # swaps in the decision-driven lane-compaction runner
    # (backends/compaction.py; docs/PERF.md round 11) — bit-identical results,
    # straggler-free device schedule. The record then carries the schema-v1.2
    # ``compaction`` block (occupancy, wasted-lane-rounds, policy).
    compaction_spec = os.environ.get("BENCH_COMPACTION", "")
    if compaction_spec and compaction_spec != "0" and not backend:
        backend = ("jax_compact" if compaction_spec == "1"
                   else f"jax_compact:{compaction_spec}")
    # BENCH_TRACE=DIR (round 12): host-side telemetry (obs/trace.py) for the
    # whole bench run — dispatch/compile/compaction spans land in
    # DIR/trace-bench.jsonl and the record gains the schema-v1.3 ``trace``
    # block. The timed windows below stay inside the traced region on
    # purpose: the overhead is measured and bounded (docs/PERF.md round 12),
    # and results are bit-identical by construction.
    trace_dir = os.environ.get("BENCH_TRACE")
    bench_tracer = None
    if trace_dir:
        from byzantinerandomizedconsensus_tpu.obs import trace as _trace

        bench_tracer = _trace.configure(trace_dir, role="bench")
    # BENCH_PROGRAMS=1 (round 13): the compiled-program census
    # (obs/programs.py) — the headline program's XLA cost/memory analysis,
    # HLO fingerprint and compile wall land in the record's schema-v1.4
    # ``programs`` block next to the compile-cache and trace blocks.
    # Capture happens at the warm-up compile, so the timed windows below
    # stay census-steady-state (bit-identical results by construction).
    bench_census = os.environ.get("BENCH_PROGRAMS", "0") not in ("", "0")
    if bench_census:
        from byzantinerandomizedconsensus_tpu.obs import (
            programs as _programs)

        _programs.configure()
    if not backend:
        import jax

        if delivery == "keys":
            backend = "jax_pallas" if jax.default_backend() == "tpu" else "jax"
        else:
            backend = "jax"
    overrides = {"instances": instances}
    if delivery is not None:
        overrides["delivery"] = delivery
    elif "pallas" in backend:
        # The Pallas kernels implement keys + §4b urn only (any spelling:
        # jax_pallas, jax:pallas, jax_sharded:2,pallas); the urn2/urn3 product
        # default would make the warm-up raise (check_pallas_delivery). A bare
        # pallas A/B therefore measures the §4b cross-check kernel; set
        # BENCH_DELIVERY=keys for the keys-model Pallas path. Announce the
        # override on stderr (ADVICE r5 #2, mirroring
        # cli._announce_default_delivery): the headline metric name does not
        # change, so without the notice a §4b cross-check measurement could
        # be mistaken for the product path at run time.
        from byzantinerandomizedconsensus_tpu.config import PRODUCT_DELIVERY

        print(f"[bench] backend {backend!r} has no "
              f"'{PRODUCT_DELIVERY}' kernel: overriding the product delivery "
              "to 'urn' (spec §4b cross-check path); set BENCH_DELIVERY to "
              "pin one explicitly", file=sys.stderr)
        overrides["delivery"] = "urn"
    cfg = preset("config4", **overrides)

    # Warm-up compile at the exact run shape + best-of-five timed runs — the
    # shared measurement discipline (utils/timing.py; docs/PERF.md) — plus the
    # noise-immune device-busy leg and the machine-readable regression verdict
    # (VERDICT r4 #2).
    from byzantinerandomizedconsensus_tpu.utils.timing import (
        device_busy, regression_verdict, timed_best_of)

    be = get_backend(backend)
    res, walls = timed_best_of(be, cfg)
    wall = min(walls)
    dev = device_busy(be, cfg)
    if "device_busy_suspect" in dev:
        # Absence-of-signal 0.0 (no device pids / op-naming drift) must not
        # enter the regression chain as a measurement (VERDICT r5 weak #1).
        dev = {"error": dev["device_busy_suspect"]}

    # Opt-in protocol-counter leg (obs/counters.py): one extra *untimed* run
    # — the timed window above stays counter-free — harvesting the kernel
    # internals (delivered/dropped per phase, chain trips, coin draws).
    # Off by default: the headline bench must stay cheap on a tunnelled TPU.
    counters = None
    if os.environ.get("BENCH_COUNTERS", "0") not in ("", "0"):
        from byzantinerandomizedconsensus_tpu.obs import record as obs_record

        counters = obs_record.collect_counters(be, cfg)

    inst_per_sec = instances / wall
    undecided = int((res.decision == 2).sum())
    prev = _prev_round_headline()
    verdict = regression_verdict(
        walls, rate=inst_per_sec,
        prev_wall_rate=prev[1] if prev else None,
        device_busy_s=dev.get("device_busy_s"),
        prev_device_busy_s=prev[2] if prev else None)
    # The device-of-record chain rule (VERDICT r5 next #8), stated in the
    # record itself: a CPU-only session cannot extend vs_prev_round_device —
    # the chain holds at the newest round that HAS a device leg (r5's
    # 0.1602 s as of round 7), walls measured here are not comparable to it,
    # and the next TPU session must compare against that artifact, not this
    # one. Without this note a CPU round silently looks like a dropped chain.
    # The anchor is looked up by its device leg, NOT by prev's wall-value
    # filter: after one CPU-only round the immediately-previous artifact has
    # no device_busy_s, and the note must still name the real anchor.
    platform = __import__("jax").default_backend()
    if platform != "tpu" and "device_busy_s" not in dev:
        from byzantinerandomizedconsensus_tpu.utils.rounds import (
            prev_round_artifact)

        def _has_device_leg(doc):
            detail = (doc.get("parsed", doc) if isinstance(doc, dict)
                      else {}).get("detail", {})
            return isinstance(detail, dict) and bool(
                detail.get("device_busy_s"))

        anchor = prev_round_artifact("BENCH", usable=_has_device_leg)
        verdict["device_chain_note"] = (
            "CPU-only session: vs_prev_round_device not extendable this "
            "round; the device chain holds at "
            f"{anchor[0] if anchor else 'the newest BENCH_r*.json with a device_busy_s leg (none found)'}"
            " — re-run on the device of record before any perf verdict")
    # The run-record head (obs/record.py): schema version + env fingerprint
    # ride the same one-line artifact the driver captures; every legacy key
    # stays where BENCH_r1-r5 consumers expect it.
    from byzantinerandomizedconsensus_tpu.obs import record as obs_record

    # Schema v1.2 (obs/record.py): the compaction block whenever the run
    # went through the compacted lane grid (jax_compact backend) — the
    # straggler-metric leg of the round-11 runner rides the same one-line
    # artifact. The plain per-chunk path instead reports the standard
    # wasted-lane metric (utils/metrics.py) computed from its own rounds
    # output and chunk size, so BENCH_r11+ always carries the occupancy
    # story, compacted or not.
    compaction = obs_record.compaction_block(be)
    from byzantinerandomizedconsensus_tpu.utils import metrics as _metrics

    trace_block = None
    if bench_tracer is not None:
        from byzantinerandomizedconsensus_tpu.obs import trace as _trace

        trace_block = _trace.finish(bench_tracer)  # flush, close, digest

    programs_block = None
    if bench_census:
        # The v1.4 programs block from whatever the census captured this
        # run (the per-config headline program; the bucket programs too
        # when BENCH_COUNTERS added a counted leg).
        programs_block = obs_record.programs_block()

    chunk = be._chunk_size(cfg) if hasattr(be, "_chunk_size") else None
    straggler = ({
        "chunk": chunk,
        "wasted_lane_fraction": _metrics.wasted_lane_fraction(
            res.rounds, chunk),
        "mean_max_rounds_per_chunk": round(_metrics.mean_max_rounds_per_chunk(
            res.rounds, chunk), 4),
    } if chunk else {})

    print(json.dumps({
        "record_version": obs_record.RECORD_VERSION,
        "record_revision": obs_record.RECORD_REVISION,
        "kind": "bench",
        # Top-level env fingerprint (schema v1+ proper): BENCH_r1-r10
        # consumers keep reading the legacy detail.env copy below.
        "env": obs_record.env_fingerprint(),
        "metric": "consensus_instances_per_sec@n512_f170_shared_coin",
        "value": round(inst_per_sec, 1),
        "unit": "instances/s",
        "vs_baseline": round(inst_per_sec / TARGET_INST_PER_SEC, 3),
        **({"prev_round_artifact": prev[0]} if prev else {}),
        **{k: v for k, v in verdict.items() if k != "walls_spread"},
        "detail": {
            "platform": platform,
            "backend": backend,
            "delivery": cfg.delivery,
            "instances": instances,
            "wall_s": round(wall, 2),
            "walls_s": [round(w, 3) for w in walls],
            "walls_spread": verdict["walls_spread"],
            **({"device_busy_s": dev["device_busy_s"]}
               if "device_busy_s" in dev else
               {"device_busy_error": dev.get("error", "?")}),
            "mean_rounds_to_decision": round(float(res.rounds.mean()), 4),
            "undecided": undecided,
            **straggler,
            **({"counters": counters} if counters is not None else {}),
            "env": obs_record.env_fingerprint(),
        },
        **({"compaction": compaction} if compaction is not None else {}),
        **({"trace": trace_block} if trace_block is not None else {}),
        **({"programs": programs_block} if programs_block is not None
           else {}),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
