"""Property-based tests (hypothesis; SURVEY.md §4.1): protocol invariants and
bit-matching over *randomly drawn* configurations, not just the fixed grid."""

import numpy as np
from hypothesis import given, settings, strategies as st

from byzantinerandomizedconsensus_tpu import SimConfig, Simulator
from byzantinerandomizedconsensus_tpu.ops import prf


@st.composite
def sim_configs(draw):
    protocol = draw(st.sampled_from(["benor", "bracha"]))
    adversary = draw(st.sampled_from(
        ["none", "crash", "byzantine", "adaptive", "adaptive_min"]))
    coin = draw(st.sampled_from(["local", "shared"]))
    n = draw(st.integers(min_value=4, max_value=24))
    if protocol == "bracha":
        fmax = (n - 1) // 3
    elif adversary in ("byzantine", "adaptive", "adaptive_min"):
        fmax = (n - 1) // 5
    else:
        fmax = (n - 1) // 2
    f = draw(st.integers(min_value=0, max_value=max(0, fmax)))
    seed = draw(st.integers(min_value=0, max_value=2**40))
    delivery = draw(st.sampled_from(["keys", "urn", "urn2"]))
    return SimConfig(protocol=protocol, n=n, f=f, instances=12, adversary=adversary,
                     coin=coin, seed=seed, round_cap=48,
                     delivery=delivery).validate()


@settings(max_examples=25, deadline=None)
@given(cfg=sim_configs())
def test_agreement_and_validity_random_configs(cfg):
    """Agreement on every decided instance; decisions only ever 0/1/2."""
    res = Simulator(cfg, "numpy").run()
    assert set(np.unique(res.decision)) <= {0, 1, 2}
    assert ((res.rounds >= 1) & (res.rounds <= cfg.round_cap)).all()
    # undecided instances always sit in the overflow bucket (the converse need
    # not hold: an instance may decide exactly at the cap round)
    assert (res.rounds[res.decision == 2] == cfg.round_cap).all()


@settings(max_examples=12, deadline=None)
@given(cfg=sim_configs())
def test_oracle_bitmatch_random_configs(cfg):
    """The vectorized path bit-matches the object oracle on arbitrary configs."""
    ids = np.arange(4, dtype=np.int64)
    a = Simulator(cfg, "numpy").run(ids)
    b = Simulator(cfg, "cpu").run(ids)
    np.testing.assert_array_equal(a.rounds, b.rounds)
    np.testing.assert_array_equal(a.decision, b.decision)


@settings(max_examples=20, deadline=None)
@given(cfg=sim_configs())
def test_native_differential_random_configs(cfg):
    """Differential fuzz of the C++ core vs the vectorized backend on
    arbitrary configs — the arbiter (tools/acceptance.py) must agree with the
    reference implementations off the fixed grid too. The native run covers
    all 12 instances (cheap), numpy cross-checks them."""
    import shutil

    import pytest

    if shutil.which("g++") is None:
        pytest.skip("no C++ toolchain")
    a = Simulator(cfg, "native").run()
    b = Simulator(cfg, "numpy").run()
    np.testing.assert_array_equal(a.rounds, b.rounds)
    np.testing.assert_array_equal(a.decision, b.decision)


@settings(max_examples=40, deadline=None)
@given(
    seed=st.integers(min_value=0, max_value=2**63 - 1),
    inst=st.integers(min_value=0, max_value=prf.MAX_INSTANCES - 1),
    rnd=st.integers(min_value=0, max_value=prf.MAX_ROUNDS - 1),
    step=st.integers(min_value=0, max_value=3),
    recv=st.integers(min_value=0, max_value=prf.MAX_N - 1),
    send=st.integers(min_value=0, max_value=prf.MAX_N - 1),
    purpose=st.integers(min_value=0, max_value=6),
    pack=st.sampled_from((1, 2)),
)
def test_prf_determinism_and_range(seed, inst, rnd, step, recv, send, purpose,
                                   pack):
    a = prf.prf_u32(seed, inst, rnd, step, recv, send, purpose, xp=np, pack=pack)
    b = prf.prf_u32(seed, inst, rnd, step, recv, send, purpose, xp=np, pack=pack)
    assert int(a) == int(b)
    assert 0 <= int(a) <= 0xFFFFFFFF
    bit = prf.prf_bit(seed, inst, rnd, step, recv, send, purpose, xp=np,
                      pack=pack)
    assert int(bit) == int(a) & 1
