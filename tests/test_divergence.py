"""Cross-model divergence (keys/urn/urn2): pinned discriminating power
(spec §4b/§4b-v2).

Round 3 found the keys↔urn per-instance outcomes identical at every committed
comparison point — all config-5-family points — so the cross-model statistical
tests were passing on samples that could not disagree. These tests pin (a)
configs where the models demonstrably diverge per-instance, pairwise across
all three samplers, while the statistical agreement still accepts them all,
(b) the config-5 family's exact per-instance delivery-robustness (all three
models identical), and (c) the structural mechanism behind it: binary-alphabet
steps under the adaptive class bias have value-homogeneous strata, so
delivered counts are closed-form deterministic — identical in every model by
construction (urn2's chains have K=0 there and consume no randomness). The
numpy backend is bit-deterministic, so every assertion here is on reproducible
exact values (tools/divergence.py holds the measured map;
artifacts/divergence_r5.json the committed numbers).
"""

import dataclasses

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu import SimConfig, Simulator
from byzantinerandomizedconsensus_tpu.tools.divergence import compare_row


@pytest.mark.parametrize("cfg,min_frac", [
    (SimConfig(protocol="benor", n=4, f=1, adversary="none", coin="local",
               seed=0, round_cap=64), 0.3),
    (SimConfig(protocol="benor", n=16, f=7, adversary="none", coin="local",
               seed=2, round_cap=64), 0.5),
    (SimConfig(protocol="bracha", n=10, f=3, adversary="byzantine",
               coin="local", seed=4, round_cap=64), 0.1),
], ids=lambda x: f"{x.protocol}-n{x.n}-{x.adversary}" if isinstance(x, SimConfig) else str(x))
def test_divergence_exists_and_statistics_accept(cfg, min_frac):
    """Per-instance outcomes differ measurably between the delivery models —
    the samples the statistical cross-model comparison runs on have
    discriminating power — while the distribution-level agreement that
    comparison asserts still holds."""
    row = compare_row(cfg, instances=300, backend="numpy")
    assert row["frac_rounds_differ"] > min_frac, row
    # urn2 is a third exact sampler: it must diverge per-instance from BOTH
    # other models in this regime (spec §4b-v2 inherits the §4b regimes)...
    assert row["frac_rounds_differ_keys_urn2"] > min_frac, row
    assert row["frac_rounds_differ_urn_urn2"] > min_frac, row
    # ... and the statistical acceptance the family-equality claim needs. The
    # mean-rounds bound is *relative* (5% + a small absolute floor): these
    # configs' rounds are geometric-tailed (local coin; the n=16 f=7 row's
    # mean is ~36 with σ ≈ mean), so an absolute bound has no headroom at a
    # few hundred samples — the committed divergence_r5.json measures a 1.06
    # absolute (2.9% relative) urn↔urn2 gap at n=16 f=7 with 400 instances,
    # and 5% + 0.3 keeps ~2× headroom over that while still rejecting a gap
    # twice the largest ever measured.
    for a, b in (("keys", "urn"), ("keys", "urn2"), ("urn", "urn2")):
        scale = max(row[f"mean_rounds_{a}"], row[f"mean_rounds_{b}"])
        assert abs(row[f"mean_rounds_{a}"] - row[f"mean_rounds_{b}"]) \
            < 0.05 * scale + 0.3, (a, b, row)
        assert abs(row[f"p1_{a}"] - row[f"p1_{b}"]) < 0.08, (a, b, row)


@pytest.mark.parametrize("adversary,protocol,n,f,coin,seed", [
    ("adaptive", "bracha", 16, 5, "local", 5),
    ("adaptive", "bracha", 16, 5, "local", 99),
    ("adaptive", "bracha", 16, 5, "shared", 11),
    # adaptive_min (spec §6.4b) is robust under BOTH protocols — including
    # benor, where the class rule diverges (its bias is a pure function of
    # the wire value, so strata stay value-homogeneous on binary steps).
    ("adaptive_min", "bracha", 16, 5, "local", 5),
    ("adaptive_min", "benor", 11, 2, "local", 3),
])
def test_config5_family_delivery_robust(adversary, protocol, n, f, coin, seed):
    """The adaptive family: per-instance outcomes are *identical* across the
    delivery models — the round-3 finding, pinned and extended to §6.4b.
    Spec §4b explains the two mechanisms (homogeneous strata on binary-alphabet
    steps; dead-margin ⊥-jitter on the remaining step)."""
    cfg = SimConfig(protocol=protocol, n=n, f=f, instances=200,
                    adversary=adversary, coin=coin, seed=seed, round_cap=64)
    keys = Simulator(cfg, "numpy").run()
    for delivery in ("urn", "urn2"):
        got = Simulator(dataclasses.replace(cfg, delivery=delivery), "numpy").run()
        np.testing.assert_array_equal(keys.rounds, got.rounds, err_msg=delivery)
        np.testing.assert_array_equal(keys.decision, got.decision, err_msg=delivery)


def test_binary_alphabet_adaptive_counts_model_invariant():
    """Structural half of the §4b robustness note, asserted exactly: when every
    wire value is in {0,1} and the bias is the adaptive class rule, both
    scheduling strata are value-homogeneous, so the delivered counts are a
    closed-form function of the strata sizes — keys, urn AND urn2 agree
    bit-for-bit, with zero scheduler freedom at count level (for §4b-v2 the
    homogeneous strata force COMP mode with comp=0, i.e. K=0 chains and a
    deterministic remainder — no LCG draw is even consumed)."""
    from byzantinerandomizedconsensus_tpu.ops import masks, tally, urn, urn2

    cfg = SimConfig(protocol="bracha", n=16, f=5, instances=1,
                    adversary="adaptive", coin="local", seed=5).validate()
    rng = np.random.default_rng(0)
    B, n = 6, cfg.n
    inst = np.arange(B, dtype=np.uint32)
    values = rng.integers(0, 2, size=(B, n)).astype(np.uint8)
    silent = np.zeros((B, n), dtype=bool)
    faulty = np.zeros((B, n), dtype=bool)
    pref = (np.arange(n) >= (n + 1) // 2).astype(np.uint8)  # spec §6.4 pref_v
    bias = (values[:, None, :] != pref[None, :, None]).astype(np.uint32)

    m = masks.delivery_mask(cfg, cfg.seed, inst, 3, 0, silent, bias, xp=np)
    k0, k1 = tally.tally01(m, values, xp=np)
    for mod in (urn, urn2):
        u0, u1 = mod.counts_fn(cfg, cfg.seed, inst, 3, 0, values, silent,
                               faulty, values, xp=np)
        np.testing.assert_array_equal(k0, u0, err_msg=mod.__name__)
        np.testing.assert_array_equal(k1, u1, err_msg=mod.__name__)

    # Closed form: own message + all unbiased others, minus D drops taken
    # biased-stratum-first (each stratum single-valued: unbiased ≡ pref_v,
    # biased ≡ 1−pref_v).
    quota = n - cfg.f - 1
    agree = (values[:, None, :] == pref[None, :, None])
    agree_others = agree.sum(-1) - np.take_along_axis(
        agree, np.arange(n)[None, :, None], -1)[..., 0].astype(np.int64)
    n_biased = (n - 1) - agree_others
    drops = n - 1 - quota  # all live ⇒ D = L − k
    drop_biased = np.minimum(drops, n_biased)
    drop_unbiased = drops - drop_biased
    c_pref = agree_others - drop_unbiased + (values == pref[None, :]).astype(int)
    c_anti = n_biased - drop_biased + (values != pref[None, :]).astype(int)
    expect0 = np.where(pref[None, :] == 0, c_pref, c_anti)
    expect1 = np.where(pref[None, :] == 1, c_pref, c_anti)
    np.testing.assert_array_equal(k0, expect0)
    np.testing.assert_array_equal(k1, expect1)


def test_committee_leg_row_shape_and_chernoff_bound():
    """The spec-§10 committee-vs-full-mesh leg (round 23): one live row —
    the measured f_C tail (real §10.1 sortition on the real §3.2 faulty
    sets) must sit under its Chernoff bound, and the committee's liveness
    shift vs the §4b-v2 reference is a bounded TV distance with nothing
    capped. The n=256 f=48 shape has a genuinely non-trivial tail
    (f_C = 20 < f), so the bound comparison has discriminating power."""
    from byzantinerandomizedconsensus_tpu.tools.divergence import (
        COMMITTEE_GRID, committee_row)

    cfg = COMMITTEE_GRID[-1]
    assert cfg.n == 256 and cfg.f == 48
    row = committee_row(cfg, instances=120, backend="numpy")
    assert row["committee_c"] < cfg.n            # sortition non-degenerate
    assert row["fc_tail_trivial"] is False
    assert row["committees_sampled"] >= 1000
    assert 0.0 < row["fc_tail_chernoff"] < 0.5
    assert row["fc_bound_holds"] is True
    assert 0.0 <= row["rounds_hist_tv_mesh_committee"] <= 1.0
    assert row["capped_committee"] == 0.0
    # the sortition law lands the committee at its designed size on average
    assert abs(row["mean_committee_size_measured"] - row["committee_c"]) < 2.0


def test_committee_leg_artifact_pinned():
    """The committed r23 committee-vs-full-mesh rows (ROADMAP #2 leg (c)):
    every COMMITTEE_GRID shape present, the Chernoff bound dominating the
    measured f_C tail on every row, at least two rows with a non-trivial
    tail, and no liveness loss (nothing capped)."""
    import json
    import pathlib

    from byzantinerandomizedconsensus_tpu.tools.divergence import (
        COMMITTEE_GRID)

    root = pathlib.Path(__file__).resolve().parents[1]
    doc = json.loads((root / "artifacts/divergence_r23.json").read_text())
    rows = doc["committee_rows"]
    assert len(rows) == len(COMMITTEE_GRID)
    for row in rows:
        assert row["fc_bound_holds"] is True
        assert row["capped_committee"] == 0.0
        assert 0.0 <= row["rounds_hist_tv_mesh_committee"] <= 1.0
    s = doc["summary"]
    assert s["committee_fc_bound_holds_all"] is True
    assert s["committee_nontrivial_tail_rows"] >= 2
    assert s["committee_max_capped"] == 0.0
    assert s["committee_max_fc_tail_measured"] <= \
        min(r["fc_tail_chernoff"] for r in rows
            if not r["fc_tail_trivial"])


def test_fault_liveness_row_shape():
    """The spec-§9 liveness leg: one config, fault-free baseline vs every
    fault kind — rows carry the TV distance and outcome stats per kind, and
    the summary reduces over them."""
    from byzantinerandomizedconsensus_tpu.tools.divergence import (
        FAULT_GRID, FAULT_KINDS_MEASURED, fault_row, fault_rows_summary)

    row = fault_row(FAULT_GRID[0], instances=60, backend="numpy")
    for kind in FAULT_KINDS_MEASURED:
        assert 0.0 <= row[f"rounds_hist_tv_{kind}"] <= 1.0
        assert row[f"mean_rounds_{kind}"] >= 1.0
        assert 0.0 <= row[f"capped_{kind}"] <= 1.0
    s = fault_rows_summary([row])
    for kind in FAULT_KINDS_MEASURED:
        assert s[f"fault_max_rounds_hist_tv_{kind}"] == \
            row[f"rounds_hist_tv_{kind}"]
