"""Serializable lane state (round 23): snapshot/restore at the segment
boundary.

The tentpole law under test: a lane parked mid-round at a segment boundary
and restored later continues *bit-identically* — because every PRF draw is
addressed by (key, instance, round, step) and lane placement never enters a
draw, the restored grid replays the exact trajectory the uninterrupted grid
would have taken. These tests pin that law

  * across the fault × adversary × delivery grid on BOTH backends (the jax
    grid compiles the same programs either way, so restore costs zero extra
    compilations),
  * across a crash-recovery *window* boundary (lanes captured while their
    crashed replicas are still silent, restored into the rejoin rounds),
  * through a JSON round-trip of the record (the exact bytes the fleet
    worker protocol ships), including the real worker subprocess leg, and
  * the version gate: a record from a different lanestate revision is
    refused by name (LaneStateVersionError), never spliced.
"""

import dataclasses
import json
import threading

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu import SimConfig
from byzantinerandomizedconsensus_tpu.backends import compaction
from byzantinerandomizedconsensus_tpu.backends.batch import FusedBucket
from byzantinerandomizedconsensus_tpu.backends.compaction import (
    CompactionPolicy)
from byzantinerandomizedconsensus_tpu.backends.lanestate import (
    LANESTATE_VERSION, LaneControl, LaneRecord, LaneStateVersionError)
from byzantinerandomizedconsensus_tpu.backends.base import get_backend

_POLICY = CompactionPolicy(width=8, segment=1)


def _fat_cfg(seed, **kw):
    """The slow shape (tools/hostile.py's preempt grid): bracha n=10 f=3
    under the adaptive adversary from split init runs ~35 rounds/lane
    fault-free — segments at every round, so a park request always finds
    live mid-round lanes to capture."""
    base = dict(protocol="bracha", n=10, f=3, instances=16,
                adversary="adaptive", coin="local", init="split",
                seed=seed, round_cap=48, delivery="urn2", faults="none")
    base.update(kw)
    return SimConfig(**base).validate()


def _park_restore(backend_name, cfg, *, via_json=False):
    """Run cfg uninterrupted, then again with a park queued before the
    first segment; restore the captured lanes in a fresh run_bucket call
    and return (baseline, restored, records)."""
    bk = get_backend(backend_name)
    bucket = FusedBucket.of(cfg)
    ids = [np.arange(cfg.instances, dtype=np.int64)]
    res0, _, _ = compaction.run_bucket(bk, bucket, [cfg], ids,
                                       policy=_POLICY)
    ctl = LaneControl()
    req = ctl.park()  # queued before start: serviced at the 1st boundary
    hold = {}
    t = threading.Thread(
        target=lambda: hold.update(
            out=compaction.run_bucket(bk, bucket, [cfg], ids,
                                      policy=_POLICY, control=ctl)))
    t.start()
    recs = req.wait(60)
    t.join(120)
    assert not t.is_alive()
    assert recs, "park captured no lanes at the segment boundary"
    if via_json:
        # the exact serialization the fleet worker protocol ships
        recs = [LaneRecord.from_doc(json.loads(
            json.dumps(rec.to_doc()))) for rec in recs]
    res1, _, _ = compaction.run_bucket(bk, bucket, [], [],
                                       policy=_POLICY, imports=recs)
    assert len(res1) == 1
    return res0[0], res1[0], recs


def _assert_identical(base, restored):
    order = np.argsort(np.asarray(base.inst_ids))
    r_order = np.argsort(np.asarray(restored.inst_ids))
    np.testing.assert_array_equal(
        np.asarray(base.inst_ids)[order],
        np.asarray(restored.inst_ids)[r_order])
    np.testing.assert_array_equal(np.asarray(base.rounds)[order],
                                  np.asarray(restored.rounds)[r_order])
    np.testing.assert_array_equal(np.asarray(base.decision)[order],
                                  np.asarray(restored.decision)[r_order])


@pytest.mark.parametrize("faults", ["none", "partition", "omission"])
@pytest.mark.parametrize("adversary,delivery", [
    ("adaptive", "urn2"), ("byzantine", "urn"), ("none", "keys"),
])
def test_restore_bit_identity_grid_numpy(faults, adversary, delivery):
    """Mid-round restore == uninterrupted run, exactly, across the
    fault × adversary × delivery grid (numpy backend: bit-deterministic,
    so this is an exact-value pin, not a statistical one)."""
    cfg = _fat_cfg(seed=31, faults=faults, adversary=adversary,
                   delivery=delivery)
    base, restored, recs = _park_restore("numpy", cfg)
    assert all(r.version == LANESTATE_VERSION for r in recs)
    _assert_identical(base, restored)


@pytest.mark.parametrize("faults", ["none", "partition"])
def test_restore_bit_identity_jax(faults):
    """The same law on the jax backend: snapshot arrays are pure data
    operands, so the restored grid re-enters the *same* compiled program
    and must produce the same bits."""
    cfg = _fat_cfg(seed=32, faults=faults)
    base, restored, _ = _park_restore("jax", cfg)
    _assert_identical(base, restored)


def test_restore_across_crash_window_boundary():
    """Lanes captured while crashed replicas are still silent (inside the
    §3.3 recovery window) restore into the rejoin rounds bit-identically —
    the window schedule is PRF-addressed by round, so it re-derives on the
    restored side rather than being (incorrectly) frozen at capture."""
    cfg = _fat_cfg(seed=33, faults="recover", crash_window=12)
    base, restored, recs = _park_restore("numpy", cfg)
    # the park lands at the first segment boundary — round ≈ 1, well
    # inside the 12-round window, so restored lanes cross it live
    for rec in recs:
        rounds_at_capture = np.asarray(rec.lanes["r"]).ravel()
        assert (rounds_at_capture < cfg.crash_window).any(), \
            "capture landed past the recovery window; tighten the park"
    _assert_identical(base, restored)


def test_record_json_roundtrip_exact():
    """to_doc → JSON bytes → from_doc is loss-free: every lane plane and
    bookkeeping field survives, and the runtime token never serializes."""
    cfg = _fat_cfg(seed=34, faults="partition")
    _, _, recs = _park_restore("numpy", cfg, via_json=True)
    rec = recs[0]
    doc = json.loads(json.dumps(rec.to_doc()))
    back = LaneRecord.from_doc(doc)
    assert back.version == rec.version == LANESTATE_VERSION
    assert back.token is None
    assert "token" not in doc
    np.testing.assert_array_equal(back.ids, rec.ids)
    np.testing.assert_array_equal(back.rounds, rec.rounds)
    np.testing.assert_array_equal(back.decision, rec.decision)
    assert back.remaining == rec.remaining
    assert back.pending == rec.pending
    for key in ("pos", "r"):
        np.testing.assert_array_equal(
            np.asarray(rec.lanes[key]), back.lanes[key], err_msg=key)
    assert set(back.lanes["st"]) == set(rec.lanes["st"])
    for key, plane in rec.lanes["st"].items():
        np.testing.assert_array_equal(np.asarray(plane),
                                      back.lanes["st"][key], err_msg=key)
    assert len(back.lanes["setup"]) == len(rec.lanes["setup"])


def test_version_mismatch_rejected_by_name():
    """A record stamped with a foreign lanestate revision is refused with
    LaneStateVersionError — pinned by name and message, because a silent
    cross-version splice would corrupt lane draws undetectably."""
    cfg = _fat_cfg(seed=35)
    _, _, recs = _park_restore("numpy", cfg)
    doc = recs[0].to_doc()
    doc["version"] = LANESTATE_VERSION + 1
    with pytest.raises(LaneStateVersionError, match="lanestate version"):
        LaneRecord.from_doc(doc)
    doc["version"] = 0
    with pytest.raises(LaneStateVersionError, match="refusing to restore"):
        LaneRecord.from_doc(doc)


@pytest.mark.slow
def test_worker_protocol_lane_roundtrip():
    """The real migration wire: a fleet worker subprocess serializes an
    in-flight request's lanes through the JSON-lines export op; importing
    the record back (as a thieving worker would) yields a reply
    bit-identical to an uninterrupted submit of the same config."""
    import subprocess
    import sys

    cfg = _fat_cfg(seed=36, faults="partition", instances=24)
    payload = dataclasses.asdict(cfg)
    proc = subprocess.Popen(
        [sys.executable, "-m", "byzantinerandomizedconsensus_tpu"
         ".serve.worker", "--index", "0", "--backend", "numpy",
         "--policy", "width=8,segment=1", "--round-cap-ceiling", "64",
         "--segment-latency-s", "0.05"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True)

    def emit(doc):
        proc.stdin.write(json.dumps(doc) + "\n")
        proc.stdin.flush()

    def read_until(want_ops, want_id=None):
        # a migrated request's dangling handle emits a stale fail frame
        # (error "migrated") — filter by id so it never satisfies a wait
        for line in proc.stdout:
            line = line.strip()
            if not line:
                continue
            msg = json.loads(line)
            if msg.get("op") not in want_ops:
                continue
            if want_id is not None and msg.get("id") != want_id:
                continue
            return msg
        raise AssertionError(f"worker EOF before {want_ops}")

    try:
        assert read_until({"ready"})["op"] == "ready"
        # baseline: uninterrupted run of the config
        emit({"op": "submit", "id": "base", "cfg": payload})
        base = read_until({"reply", "fail"}, "base")
        assert base["op"] == "reply", base
        # the migration leg: submit again, export mid-flight, import back
        lanes = []
        for attempt in range(4):
            fid = f"mig{attempt}"
            emit({"op": "submit", "id": fid, "cfg": payload})
            emit({"op": "export", "rpc": attempt, "ids": [fid]})
            msg = read_until({"export"})
            lanes = msg.get("lanes") or []
            if lanes:
                break
            # raced a fast retirement: drain the reply and try again
            read_until({"reply", "fail"}, fid)
        assert lanes, "export never caught the request in flight"
        for lane in lanes:
            assert lane["record"]["version"] == LANESTATE_VERSION
            emit({"op": "import", "id": "back-" + lane["id"],
                  "record": lane["record"]})
        restored = read_until({"reply", "fail"},
                              "back-" + lanes[0]["id"])
        assert restored["op"] == "reply", restored
        for key in ("inst_ids", "rounds", "decision"):
            assert restored["record"][key] == base["record"][key], key
    finally:
        emit({"op": "shutdown"})
        proc.stdin.close()
        proc.wait(timeout=60)
