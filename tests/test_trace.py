"""Host-side telemetry pipeline (obs/trace.py; round 12).

The acceptance bar has two halves. Inertness: tracing must be strictly a
side channel — results bit-identical traced vs untraced across the
fault x adversary x delivery grid, on both the vmapped-lane and the
compacted-lane paths (the measured wall-overhead bound lives in
artifacts/trace_r12.json / docs/PERF.md round 12). Fidelity: the JSONL is
well-formed (every line parses, spans properly nested per worker), the
digest is the exact nearest-rank percentile law, the Chrome export is
structurally valid trace-event JSON, and the follow mode reads a live
directory incrementally.
"""

import json
import threading

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu.backends import get_backend
from byzantinerandomizedconsensus_tpu.config import (
    DELIVERY_KINDS, FAULT_KINDS, SimConfig)
from byzantinerandomizedconsensus_tpu.obs import record, trace


@pytest.fixture(autouse=True)
def _no_leftover_tracer():
    """Every test starts and ends with tracing disabled — a leaked global
    tracer would silently instrument unrelated tests."""
    trace.disable()
    yield
    trace.disable()


def _cfg(adv, proto, delivery, fault, n=7, f=2, seed=13, **kw):
    base = dict(protocol=proto, n=n, f=f, instances=4, adversary=adv,
                coin="local", seed=seed, round_cap=32, delivery=delivery,
                faults=fault)
    base.update(kw)
    return SimConfig(**base).validate()


# ---------------------------------------------------------------------------
# the tracer itself


def test_disabled_fast_path_is_inert():
    assert not trace.enabled()
    trace.event("x", a=1)  # no tracer: must be a no-op, not an error
    cm = trace.span("y", b=2)
    assert cm is trace.span("z")  # the shared no-op context manager
    with cm as sp:
        sp["post"] = 3  # writes to the discard sink go nowhere
    assert trace.current() is None


def test_in_memory_tracer_records_spans_and_events():
    tr = trace.configure()  # no sink: bounded in-memory
    with trace.span("work", stage=1) as sp:
        sp["result"] = "ok"
    trace.event("tick", n=2)
    assert len(tr.events) == 2
    span_ev = next(e for e in tr.events if e["ph"] == "X")
    inst_ev = next(e for e in tr.events if e["ph"] == "i")
    assert span_ev["kind"] == "work" and span_ev["dur"] >= 0
    assert span_ev["attrs"] == {"stage": 1, "result": "ok"}
    assert inst_ev["kind"] == "tick" and inst_ev["attrs"] == {"n": 2}
    trace.disable()
    assert not trace.enabled()


def test_in_memory_tracer_bounds_memory():
    tr = trace.configure(max_events=5)
    for i in range(9):
        trace.event("e", i=i)
    assert len(tr.events) == 5 and tr.dropped == 4


def test_file_sink_is_threadsafe_jsonl(tmp_path):
    tr = trace.configure(tmp_path, role="threads")
    barrier = threading.Barrier(4)  # all 4 alive at once: no ident reuse

    def worker(w):
        barrier.wait()
        for i in range(20):
            with trace.span("w.span", worker=w, i=i):
                pass
        barrier.wait()

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(4)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    trace.disable()
    path = tmp_path / "trace-threads.jsonl"
    events = trace.read_events(path)
    assert len(events) == 80
    assert trace.validate_file(path) == []
    # 4 threads -> 4 distinct tids, each with its own properly-nested run.
    assert len({e["tid"] for e in events}) == 4


def test_merge_orders_worker_files_by_time(tmp_path):
    for role, ts0 in (("w1", 10.0), ("w2", 5.0)):
        with open(tmp_path / f"trace-{role}.jsonl", "w") as fh:
            for k in range(3):
                fh.write(json.dumps({"ph": "i", "kind": f"{role}.e",
                                     "ts": ts0 + k, "pid": 1, "tid": 0})
                         + "\n")
    merged = trace.merge(tmp_path)
    events = trace.read_events(merged)
    assert [e["ts"] for e in events] == sorted(e["ts"] for e in events)
    assert events[0]["kind"] == "w2.e" and events[-1]["kind"] == "w1.e"


def test_validate_catches_torn_lines_and_overlap(tmp_path):
    p = tmp_path / "trace-bad.jsonl"
    lines = [
        json.dumps({"ph": "X", "kind": "a", "ts": 1.0, "dur": 2.0,
                    "pid": 1, "tid": 0}),
        # partial overlap with "a" on the same thread: starts inside, ends
        # outside — improper nesting.
        json.dumps({"ph": "X", "kind": "b", "ts": 2.0, "dur": 3.0,
                    "pid": 1, "tid": 0}),
        "{torn json",
        json.dumps({"ph": "?", "kind": "c", "ts": 3.0}),
    ]
    p.write_text("\n".join(lines) + "\n")
    problems = trace.validate_file(p)
    assert any("unparseable" in s for s in problems)
    assert any("overlaps" in s for s in problems)
    assert any("missing kind/ph" in s for s in problems)
    # Properly nested + disjoint spans on one thread: clean.
    good = tmp_path / "trace-good.jsonl"
    good.write_text("\n".join(
        json.dumps(e) for e in [
            {"ph": "X", "kind": "parent", "ts": 1.0, "dur": 4.0,
             "pid": 1, "tid": 0},
            {"ph": "X", "kind": "child", "ts": 2.0, "dur": 1.0,
             "pid": 1, "tid": 0},
            {"ph": "X", "kind": "sibling", "ts": 6.0, "dur": 1.0,
             "pid": 1, "tid": 0},
        ]) + "\n")
    assert trace.validate_file(good) == []


def test_digest_is_exact_nearest_rank():
    events = ([{"ph": "X", "kind": "k", "ts": 0.0, "dur": d}
               for d in (0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0)]
              + [{"ph": "i", "kind": "tick", "ts": 0.0}])
    dg = trace.digest(events)
    k = dg["k"]
    assert k["count"] == 10 and k["total_s"] == 5.5
    # nearest-rank on 10 values: p50 = 5th smallest, p90 = 9th, p99 = 10th.
    assert (k["p50_s"], k["p90_s"], k["p99_s"]) == (0.5, 0.9, 1.0)
    assert dg["tick"] == {"count": 1, "total_s": 0.0}


def test_chrome_export_structure(tmp_path):
    tr = trace.configure(tmp_path, role="ch")
    with trace.span("s", a=1):
        trace.event("e", b=2)
    trace.disable()
    events = trace.read_events(tmp_path / "trace-ch.jsonl")
    doc = trace.to_chrome(events)
    assert isinstance(doc["traceEvents"], list) and len(doc["traceEvents"]) == 2
    for ev in doc["traceEvents"]:
        assert ev["ph"] in ("X", "i") and isinstance(ev["name"], str)
        assert isinstance(ev["ts"], (int, float))  # microseconds
        if ev["ph"] == "X":
            assert ev["dur"] >= 0
        else:
            assert ev["s"] == "t"
    # instants precede their enclosing span in file order (span written at
    # exit); chrome ts ordering is the reader's job, not the writer's.
    out = trace.write_chrome(events, tmp_path / "t.chrome.json")
    assert json.loads(out.read_text())["traceEvents"]


# ---------------------------------------------------------------------------
# inertness: the tentpole acceptance bar


def test_tracing_inert_across_fault_adversary_delivery_grid(tmp_path):
    """Bit-identity traced vs untraced over a covering (fault, delivery)
    sample with rotating adversaries — vmapped lanes AND the compacted lane
    grid (the two instrumented hot paths). The trace itself must come out
    non-trivial and well-formed."""
    from byzantinerandomizedconsensus_tpu.backends.compaction import (
        CompactionPolicy)

    _ADV_PROTO = (("none", "benor"), ("crash", "benor"),
                  ("byzantine", "bracha"), ("adaptive", "bracha"))
    cells = [(FAULT_KINDS[i], DELIVERY_KINDS[j])
             for i, j in ((0, 0), (1, 1), (2, 3), (3, 2))]
    cfgs = []
    for i, (fault, delivery) in enumerate(cells):
        adv, proto = _ADV_PROTO[i % len(_ADV_PROTO)]
        cfgs += [_cfg(adv, proto, delivery, fault),
                 _cfg(adv, proto, delivery, fault, f=1, seed=99,
                      instances=6)]
    jb = get_backend("jax")
    base, _ = jb.run_many(cfgs)
    base_c, _ = jb.run_many(cfgs, compaction=CompactionPolicy(width=4,
                                                              segment=1))

    trace.configure(tmp_path, role="grid")
    traced, _ = jb.run_many(cfgs)
    traced_c, _ = jb.run_many(cfgs, compaction=CompactionPolicy(width=4,
                                                                segment=1))
    trace.disable()

    for a, b in zip(base + base_c, traced + traced_c):
        np.testing.assert_array_equal(a.rounds, b.rounds)
        np.testing.assert_array_equal(a.decision, b.decision)

    path = tmp_path / "trace-grid.jsonl"
    assert trace.validate_file(path) == []
    kinds = {e["kind"] for e in trace.read_events(path)}
    assert {"batch.bucket", "batch.dispatch", "compaction.segment",
            "compaction.drain", "compaction.init"} <= kinds


def test_compaction_spans_carry_anatomy_attrs(tmp_path):
    """The round-11 per-trip anatomy as a queryable timeline: segment/drain
    spans carry queue depth, retired-lane counts and per-trip rounds; the
    refill span carries keep/take."""
    from byzantinerandomizedconsensus_tpu.backends.compaction import (
        CompactionPolicy)

    cfgs = [_cfg("crash", "benor", "urn2", "none", seed=s, instances=8)
            for s in (1, 2, 3)]
    jb = get_backend("jax")
    trace.configure(tmp_path, role="comp")
    jb.run_many(cfgs, compaction=CompactionPolicy(width=4, segment=1))
    trace.disable()
    events = trace.read_events(tmp_path / "trace-comp.jsonl")
    segs = [e for e in events
            if e["kind"] in ("compaction.segment", "compaction.drain")]
    assert segs, "no segment spans recorded"
    for e in segs:
        at = e["attrs"]
        assert {"width", "queued", "trip_max", "useful_trips", "retired",
                "live"} <= set(at)
    drains = [e for e in events if e["kind"] == "compaction.drain"]
    assert drains, "the straggler drain must be its own span kind"
    refills = [e for e in events if e["kind"] == "compaction.refill"]
    assert all({"keep", "take", "queued"} <= set(e["attrs"])
               for e in refills)


def test_compile_cache_wall_and_events(tmp_path):
    """The satellite: CompileCache stats carry compile_wall_s (the lazy-jit
    first-call proxy), and cache traffic lands in the trace as
    compile/hit events."""
    from byzantinerandomizedconsensus_tpu.backends.jax_backend import (
        JaxBackend)
    from byzantinerandomizedconsensus_tpu.backends import batch as batch_mod

    jb = JaxBackend()  # fresh instance: stats start at zero
    trace.configure(tmp_path, role="cc")
    a = _cfg("none", "benor", "urn2", "none", f=2, seed=1, instances=3)
    b = _cfg("none", "benor", "urn2", "none", f=1, seed=2, instances=3)
    jb.run_batch([a])
    jb.run_batch([b])  # same bucket: a cache hit
    trace.disable()
    s = batch_mod.compile_cache(jb).stats()
    assert s["compiles"] >= 1 and s["hits"] >= 1
    assert s["compile_wall_s"] > 0  # the first dispatch paid a real compile
    kinds = [e["kind"] for e in
             trace.read_events(tmp_path / "trace-cc.jsonl")]
    assert "compile_cache.compile" in kinds and "compile_cache.hit" in kinds
    # The compile event carries its wall (per-compile, not just the total).
    ev = next(e for e in trace.read_events(tmp_path / "trace-cc.jsonl")
              if e["kind"] == "compile_cache.compile")
    assert ev["attrs"]["wall_s"] > 0


# ---------------------------------------------------------------------------
# consumer surfaces (tools/trace.py)


def _write_sample_trace(tmp_path, role="sample"):
    trace.configure(tmp_path, role=role)
    trace.event("chaos.start", configs=4, seed=0, chaos=True, jobs=1)
    for k in range(4):
        with trace.span("chaos.config", index=k):
            pass
        trace.event("chaos.progress", done=k + 1, total=4, mismatches=0,
                    violations=0, skipped=0)
    trace.disable()
    return tmp_path / f"trace-{role}.jsonl"


def test_trace_cli_summary_and_export(tmp_path, capsys):
    from byzantinerandomizedconsensus_tpu.tools import trace as trace_tool

    path = _write_sample_trace(tmp_path)
    assert trace_tool.main(["summary", str(path),
                            "--json", str(tmp_path / "dg.json")]) == 0
    out = capsys.readouterr().out
    assert "chaos.config" in out and "p99" in out
    dg = json.loads((tmp_path / "dg.json").read_text())
    assert dg["problems"] == [] and dg["digest"]["chaos.config"]["count"] == 4

    assert trace_tool.main(["export", "--chrome", str(path)]) == 0
    out_path = path.with_suffix(".chrome.json")
    doc = json.loads(out_path.read_text())
    assert len(doc["traceEvents"]) == 9
    capsys.readouterr()


def test_trace_summary_top_ranks_by_total_wall(tmp_path, capsys):
    """Round-13 satellite: --top N sorts kinds by total span wall
    (descending, instants last) and truncates, naming what it dropped."""
    from byzantinerandomizedconsensus_tpu.tools import trace as trace_tool

    trace.configure(tmp_path, role="top")
    events = [("slow.kind", 0.5), ("slow.kind", 0.4),
              ("mid.kind", 0.3), ("fast.kind", 0.01)]
    path = tmp_path / "trace-top.jsonl"
    trace.disable()
    with open(path, "w") as fh:
        ts = 0.0
        for kind, dur in events:
            fh.write(json.dumps({"ph": "X", "kind": kind, "ts": ts,
                                 "dur": dur, "pid": 1, "tid": 0}) + "\n")
            ts += dur + 1.0
        fh.write(json.dumps({"ph": "i", "kind": "a.tick", "ts": ts,
                             "pid": 1, "tid": 0}) + "\n")
    assert trace_tool.main(["summary", str(path), "--top", "2"]) == 0
    out = capsys.readouterr().out
    body = [l for l in out.splitlines()[1:] if l.startswith("  ")]
    # Ranked: biggest total first, count-only instants below every span,
    # and the truncation is announced.
    assert body[0].startswith("  slow.kind") and "total 0.9 s" in body[0]
    assert body[1].startswith("  mid.kind")
    assert "fast.kind" not in out and "a.tick" not in out
    assert "2 more kind(s) below the top 2" in out
    # Default stays the full unranked (alphabetical) dump.
    assert trace_tool.main(["summary", str(path)]) == 0
    out = capsys.readouterr().out
    assert "fast.kind" in out and "a.tick" in out


def test_trace_follow_reads_live_directory_incrementally(tmp_path):
    from byzantinerandomizedconsensus_tpu.tools import trace as trace_tool

    _write_sample_trace(tmp_path, role="w1")
    lines = []
    state = trace_tool.follow(tmp_path, once=True, out=lines.append)
    assert state["events"] == 9
    assert state["progress"]["done"] == 4
    assert "configs 4/4" in lines[-1]
    # Incremental: append more events, a second pass picks up ONLY the tail.
    with open(tmp_path / "trace-w1.jsonl", "a") as fh:
        fh.write(json.dumps({"ph": "i", "kind": "chaos.progress",
                             "ts": 99.0, "pid": 1, "tid": 0,
                             "attrs": {"done": 5, "total": 5,
                                       "mismatches": 1, "violations": 0,
                                       "skipped": 0}}) + "\n")
    state2 = trace_tool.follow(tmp_path, once=True, out=lines.append)
    assert state2["events"] == 10  # fresh offsets: full re-read + tail
    assert state2["progress"]["mismatches"] == 1


def test_cli_routes_trace_verb(tmp_path, capsys):
    from byzantinerandomizedconsensus_tpu import cli

    path = _write_sample_trace(tmp_path)
    assert cli.main(["trace", "summary", str(path)]) == 0
    assert "trace summary" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# schema v1.3 record binding


def test_trace_block_and_validate_record(tmp_path):
    path = _write_sample_trace(tmp_path)
    blk = record.trace_block(path)
    assert blk["file"] == path.name and blk["events"] == 9
    assert blk["digest"]["chaos.config"]["count"] == 4
    doc = {**record.new_record("soak"), "trace": blk}
    assert record.validate_record(doc) == []
    assert doc["record_revision"] >= 3
    # Drift checks: a torn block and a digest without counts must fail.
    assert any("trace block missing" in p for p in record.validate_record(
        {**record.new_record("x"), "trace": {"file": "t.jsonl"}}))
    assert any("missing 'count'" in p for p in record.validate_record(
        {**record.new_record("x"),
         "trace": {"file": "t", "events": 1, "digest": {"k": {}}}}))
    # Unreadable path: None, never an exception (record assembly survives).
    assert record.trace_block(tmp_path / "absent.jsonl") is None
