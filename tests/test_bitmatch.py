"""The north-star acceptance test (BASELINE.json:5; SURVEY.md §4.2): identical
per-instance (rounds, decision) across the independent CPU oracle, the numpy
vectorized backend, and the jit'd JAX backend — exhaustively at small n, on sampled
instance subsets at benchmark scale."""

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu import SimConfig, Simulator, preset

SMALL = [
    SimConfig(protocol="benor", n=4, f=1, instances=60, adversary="none", coin="local",
              round_cap=64, seed=0),
    SimConfig(protocol="benor", n=9, f=4, instances=40, adversary="crash", coin="local",
              round_cap=96, seed=1),
    SimConfig(protocol="benor", n=16, f=3, instances=40, adversary="byzantine",
              coin="local", round_cap=64, seed=2),
    SimConfig(protocol="benor", n=11, f=2, instances=40, adversary="adaptive",
              coin="shared", round_cap=64, seed=3),
    SimConfig(protocol="bracha", n=10, f=3, instances=40, adversary="byzantine",
              coin="shared", round_cap=64, seed=4),
    SimConfig(protocol="bracha", n=16, f=5, instances=40, adversary="adaptive",
              coin="shared", round_cap=64, seed=5),
    SimConfig(protocol="bracha", n=13, f=4, instances=40, adversary="crash",
              coin="local", round_cap=64, seed=6),
    SimConfig(protocol="bracha", n=7, f=2, instances=40, adversary="none", coin="shared",
              round_cap=64, seed=7),
]


def _ids(cfg):
    return SMALL.index(cfg)


@pytest.mark.parametrize("cfg", SMALL, ids=lambda c: f"{c.protocol}-n{c.n}f{c.f}-{c.adversary}-{c.coin}")
def test_small_exhaustive(cfg):
    ref = Simulator(cfg, "cpu").run()
    for backend in ("numpy", "jax"):
        got = Simulator(cfg, backend).run()
        np.testing.assert_array_equal(ref.rounds, got.rounds, err_msg=f"rounds {backend}")
        np.testing.assert_array_equal(ref.decision, got.decision, err_msg=f"decision {backend}")


@pytest.mark.parametrize("name,n_sample", [("config2", 6), ("config3", 4), ("config4", 3)])
def test_benchmark_configs_sampled(name, n_sample):
    """Sampled bit-match at benchmark scale: instance i depends only on (cfg, seed, i),
    so the oracle simulates a pseudo-random subset and must match the batched run.

    Pinned to the keys validation model — the presets themselves pin urn, whose
    benchmark-scale sampled bit-match lives in tests/test_urn.py; this test keeps
    the keys O(n²)-mask path covered at benchmark n against the oracle."""
    import zlib

    cfg = preset(name, round_cap=64, delivery="keys")
    rng = np.random.default_rng(zlib.crc32(name.encode()))
    ids = np.unique(rng.integers(0, cfg.instances, size=n_sample))
    ref = Simulator(cfg, "cpu").run(ids)
    for backend in ("numpy", "jax"):
        got = Simulator(cfg, backend).run(ids)
        np.testing.assert_array_equal(ref.rounds, got.rounds, err_msg=f"rounds {backend}")
        np.testing.assert_array_equal(ref.decision, got.decision, err_msg=f"decision {backend}")


def test_subset_equals_full_run():
    """Batched full run restricted to a subset equals the subset run (instance
    independence — spec §1)."""
    cfg = SimConfig(protocol="bracha", n=16, f=5, instances=50, adversary="byzantine",
                    coin="shared", round_cap=64, seed=9)
    full = Simulator(cfg, "numpy").run()
    ids = np.array([0, 7, 13, 49])
    sub = Simulator(cfg, "numpy").run(ids)
    np.testing.assert_array_equal(full.rounds[ids], sub.rounds)
    np.testing.assert_array_equal(full.decision[ids], sub.decision)


def test_jax_chunking_invariance():
    """Chunk size must not affect results (padding correctness)."""
    from byzantinerandomizedconsensus_tpu.backends.jax_backend import JaxBackend

    cfg = SimConfig(protocol="bracha", n=10, f=3, instances=37, adversary="byzantine",
                    coin="shared", round_cap=64, seed=12)
    a = JaxBackend(max_chunk=8).run(cfg)
    b = JaxBackend(max_chunk=64).run(cfg)
    np.testing.assert_array_equal(a.rounds, b.rounds)
    np.testing.assert_array_equal(a.decision, b.decision)


@pytest.mark.parametrize("delivery", ["keys", "urn"])
@pytest.mark.parametrize("n,f", [(1, 0), (2, 0), (3, 1)])
def test_degenerate_sizes(n, f, delivery):
    """n=1..3 exercise empty-others urns, zero-drop quotas, and single-replica
    instant decision across all four backends."""
    cfg = SimConfig(protocol="benor", n=n, f=f, instances=20, adversary="none",
                    coin="local", round_cap=32, seed=3, delivery=delivery)
    ref = Simulator(cfg, "cpu").run()
    for b in ("numpy", "jax", "native"):
        got = Simulator(cfg, b).run()
        np.testing.assert_array_equal(ref.rounds, got.rounds, err_msg=f"{b}")
        np.testing.assert_array_equal(ref.decision, got.decision, err_msg=f"{b}")
