"""Mesh-sharded backend vs CPU oracle — bit-match on every mesh shape (SURVEY.md §4.3).

Runs on the 8 virtual CPU devices from conftest.py. The sharded backend must produce
bit-identical (rounds, decision) to the CPU oracle for every (data, model) mesh split,
for every protocol/adversary/coin pairing — this is the multi-chip analog of
tests/test_bitmatch.py and the [B:5] acceptance criterion.
"""

import numpy as np
import pytest

import jax

from byzantinerandomizedconsensus_tpu import SimConfig, Simulator
from byzantinerandomizedconsensus_tpu.parallel.mesh import make_mesh
from byzantinerandomizedconsensus_tpu.parallel.sharded import JaxShardedBackend

CONFIGS = [
    SimConfig(protocol="benor", n=8, f=2, instances=24, adversary="crash",
              coin="local", seed=11, round_cap=64),
    SimConfig(protocol="bracha", n=8, f=2, instances=24, adversary="byzantine",
              coin="shared", seed=12, round_cap=64),
    SimConfig(protocol="bracha", n=16, f=5, instances=12, adversary="adaptive",
              coin="shared", seed=13, round_cap=64),
    SimConfig(protocol="benor", n=16, f=3, instances=12, adversary="byzantine",
              coin="local", seed=14, round_cap=64),
]

MESHES = [(8, 1), (4, 2), (2, 4), (1, 8)]


def _cpu_devices(count):
    devs = jax.devices("cpu")
    if len(devs) < count:
        pytest.skip(f"needs {count} cpu devices")
    return devs[:count]


@pytest.fixture(scope="module")
def oracle_results():
    return {cfg: Simulator(cfg, "cpu").run() for cfg in CONFIGS}


@pytest.mark.parametrize("n_data,n_model", MESHES)
@pytest.mark.parametrize("cfg", CONFIGS, ids=lambda c: f"{c.protocol}-{c.adversary}-n{c.n}")
def test_sharded_bitmatch(cfg, n_data, n_model, oracle_results):
    mesh = make_mesh(n_data=n_data, n_model=n_model,
                     devices=_cpu_devices(n_data * n_model))
    backend = JaxShardedBackend(mesh=mesh)
    got = backend.run(cfg)
    ref = oracle_results[cfg]
    np.testing.assert_array_equal(got.rounds, ref.rounds)
    np.testing.assert_array_equal(got.decision, ref.decision)


def test_sharded_chunking_matches_unchunked():
    """Chunk boundaries (with padding) must not affect results."""
    cfg = SimConfig(protocol="bracha", n=8, f=2, instances=30, adversary="byzantine",
                    coin="shared", seed=7, round_cap=64)
    mesh = make_mesh(n_data=4, n_model=2, devices=_cpu_devices(8))
    big = JaxShardedBackend(mesh=mesh).run(cfg)
    small = JaxShardedBackend(mesh=mesh, max_chunk=8).run(cfg)
    np.testing.assert_array_equal(big.rounds, small.rounds)
    np.testing.assert_array_equal(big.decision, small.decision)


def test_registry_exposes_sharded():
    from byzantinerandomizedconsensus_tpu.backends import available_backends

    assert "jax_sharded" in available_backends()


@pytest.mark.parametrize("n_data,n_model", [(4, 2), (2, 4)])
def test_compiled_collective_inventory(n_data, n_model):
    """The ARCHITECTURE.md multi-chip cost model's measured half: the compiled
    benchmark-shape program contains exactly 3 all-gathers (one u8 wire-value
    gather per Bracha step) and 2 all-reduces (per-round termination psum +
    once-per-chunk decision psum) — nothing else crosses chips, on any mesh
    layout. A new collective appearing here invalidates the predicted scaling
    curve and must update that section."""
    import re

    import jax.numpy as jnp

    from byzantinerandomizedconsensus_tpu.config import preset
    from byzantinerandomizedconsensus_tpu.parallel import sharded

    mesh = make_mesh(n_data=n_data, n_model=n_model,
                     devices=_cpu_devices(n_data * n_model))
    cfg = preset("config4", instances=8, round_cap=64)
    fn = jax.jit(lambda ids, key: sharded._run_chunk_sharded(cfg, mesh, ids, key))
    hlo = fn.lower(jnp.arange(8, dtype=jnp.uint32),
                   jnp.zeros(2, dtype=jnp.uint32)).compile().as_text()
    counts = {op: len(re.findall(rf"\b{op}\b", hlo))
              for op in ("all-gather", "all-reduce", "collective-permute",
                         "all-to-all")}
    assert counts == {"all-gather": 3, "all-reduce": 2,
                      "collective-permute": 0, "all-to-all": 0}, counts
