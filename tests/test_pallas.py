"""Fused Pallas delivery+tally kernel (ops/pallas_tally.py): bit-match vs the
vectorized reference path, in interpret mode on the CPU test mesh (the same
kernel lowers to Mosaic on TPU; interpret mode checks the semantics)."""

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu.backends import get_backend
from byzantinerandomizedconsensus_tpu.config import SimConfig


def _sizes(proto, adv):
    if proto == "benor" and adv in ("byzantine", "adaptive"):
        return 11, 2
    if proto == "bracha":
        return 10, 3
    return 7, 3


# Per-config full-driver Pallas runs cost ~20 s of interpret-mode
# tracing/lowering each (execution is ~10 ms), so driver-level coverage keeps
# ONE representative program per kernel family; the breadth — every adversary,
# both protocols, tile-boundary shapes — lives in tests/test_pallas_step.py's
# eager step-level equality at ~1/10 the cost.
def test_bitmatch_full_driver():
    """One end-to-end driver-level Pallas bit-match (termination, chunking,
    overflow bucket composed with the kernel); kernel breadth is step-level."""
    cfg = SimConfig(protocol="bracha", n=10, f=3, instances=24,
                    adversary="byzantine", coin="shared", seed=13,
                    round_cap=48).validate()
    a = get_backend("jax_pallas").run(cfg)
    b = get_backend("numpy").run(cfg)
    np.testing.assert_array_equal(a.rounds, b.rounds)
    np.testing.assert_array_equal(a.decision, b.decision)


@pytest.mark.parametrize(
    "proto,adv",
    [(p, a) for p in ("benor", "bracha")
     for a in ("none", "crash", "byzantine", "adaptive")],
)
def test_bitmatch_xla_nosort_grid(proto, adv):
    """The sort-free pure-XLA selection (ops/masks.counts_nosort) bit-matches.
    Full protocol x adversary product: this is a cheap XLA compile, not an
    interpret-mode Pallas trace, so the GRID cost rationale does not apply."""
    n, f = _sizes(proto, adv)
    cfg = SimConfig(protocol=proto, n=n, f=f, instances=24, adversary=adv,
                    coin="shared", seed=13, round_cap=48).validate()
    a = get_backend("jax:xla_nosort").run(cfg)
    b = get_backend("numpy").run(cfg)
    np.testing.assert_array_equal(a.rounds, b.rounds)
    np.testing.assert_array_equal(a.decision, b.decision)


@pytest.mark.slow
def test_bitmatch_sharded_composition():
    """Fused kernel inside shard_map: receiver-shard offsets keep PRF addressing
    global, so the replica-sharded mesh bit-matches the reference path. (One
    mesh shape at driver level; shard-offset breadth is step-level. Slow: a
    second ~20 s interpret-mode driver trace — the composition it adds over
    test_bitmatch_full_driver + the step-level offset grid is mesh plumbing.)"""
    from byzantinerandomizedconsensus_tpu.parallel.mesh import make_mesh
    from byzantinerandomizedconsensus_tpu.parallel.sharded import JaxShardedBackend

    mesh = make_mesh(n_data=4, n_model=2)
    be = JaxShardedBackend(mesh=mesh, kernel="pallas")
    cfg = SimConfig(protocol="bracha", n=16, f=5, instances=16, adversary="adaptive",
                    coin="shared", seed=17, round_cap=48).validate()
    a = be.run(cfg)
    b = get_backend("numpy").run(cfg)
    np.testing.assert_array_equal(a.rounds, b.rounds)
    np.testing.assert_array_equal(a.decision, b.decision)


def test_kth_smallest_matches_sort():
    """The bitwise threshold search equals sorted[k-1] on distinct keys."""
    import jax.numpy as jnp

    from byzantinerandomizedconsensus_tpu.ops.pallas_tally import _kth_smallest

    rng = np.random.default_rng(0)
    for k in (1, 3, 17, 64):
        keys = rng.choice(2**32, size=(5, 64), replace=False).astype(np.uint32)
        got = np.asarray(_kth_smallest(jnp.asarray(keys), k))[:, 0]
        want = np.sort(keys, axis=-1)[:, k - 1]
        np.testing.assert_array_equal(got, want)


def test_smallest_k_mask_vs_sort_with_tie_classes(pallas_interpret):
    """_smallest_k_mask == argsort top-k on crafted keys with dense top-22
    collisions (the tie-resolution path that full-key thresholding never
    stresses at random: P[top22 collision] = 2^-20 per pair)."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from byzantinerandomizedconsensus_tpu.ops.pallas_tally import _smallest_k_mask

    def call(keys, k):
        # pltpu.roll evaluates only inside a pallas context
        def kern(x_ref, o_ref):
            o_ref[...] = _smallest_k_mask(x_ref[...], k).astype(jnp.int32)

        out = pl.pallas_call(
            kern, out_shape=jax.ShapeDtypeStruct(keys.shape, jnp.int32),
            interpret=pallas_interpret)(jnp.asarray(keys))
        return np.asarray(out).astype(bool)

    rng = np.random.default_rng(99)
    S = 96
    for trial in range(20):
        # few distinct top22 values -> large tie classes; low 10 bits = index
        top = rng.integers(0, 5, size=(4, S)).astype(np.uint32)
        keys = (top << np.uint32(10)) | np.arange(S, dtype=np.uint32)[None, :]
        k = int(rng.integers(1, S))
        got = call(keys, k)
        want = np.zeros_like(got)
        order = np.argsort(keys, axis=-1)
        np.put_along_axis(want, order[:, :k], True, axis=-1)
        np.testing.assert_array_equal(got, want, err_msg=f"trial {trial} k={k}")
