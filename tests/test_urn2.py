"""Urn inversion delivery (spec §4b-v2): chain-level exactness against the
closed-form hypergeometric pmf, bit-match across all four implementation
stacks, protocol properties, and statistical agreement with both the keys
model and the §4b urn sampler.

Like §4b, urn2 is a *different exact sampler of the same delivery distribution
family*: bit-matching is within delivery="urn2"; cross-model checks are
statistical.
"""

import dataclasses
import math

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu import SimConfig, Simulator, preset

URN2_SMALL = [
    SimConfig(protocol="benor", n=4, f=1, instances=60, adversary="none", coin="local",
              round_cap=64, seed=0, delivery="urn2"),
    SimConfig(protocol="benor", n=9, f=4, instances=40, adversary="crash", coin="local",
              round_cap=96, seed=1, delivery="urn2"),
    SimConfig(protocol="benor", n=16, f=3, instances=40, adversary="byzantine",
              coin="local", round_cap=64, seed=2, delivery="urn2"),
    SimConfig(protocol="benor", n=11, f=2, instances=40, adversary="adaptive",
              coin="shared", round_cap=64, seed=3, delivery="urn2"),
    SimConfig(protocol="bracha", n=10, f=3, instances=40, adversary="byzantine",
              coin="shared", round_cap=64, seed=4, delivery="urn2"),
    SimConfig(protocol="bracha", n=16, f=5, instances=40, adversary="adaptive",
              coin="shared", round_cap=64, seed=5, delivery="urn2"),
    SimConfig(protocol="bracha", n=13, f=4, instances=40, adversary="crash",
              coin="local", round_cap=64, seed=6, delivery="urn2"),
    SimConfig(protocol="bracha", n=7, f=2, instances=40, adversary="none",
              coin="shared", round_cap=64, seed=7, delivery="urn2"),
    SimConfig(protocol="bracha", n=13, f=4, instances=40, adversary="adaptive_min",
              coin="shared", round_cap=64, seed=8, delivery="urn2"),
]


def _hg_pmf(N: int, m: int, D: int, k: int) -> float:
    """Exact HG(N, m, D) pmf at k."""
    if k < max(0, D - (N - m)) or k > min(m, D):
        return 0.0
    return (math.comb(m, k) * math.comb(N - m, D - k)) / math.comb(N, D)


@pytest.mark.parametrize("N,m,D", [
    (20, 3, 9),    # ITEM mode  (m smallest)
    (20, 12, 5),   # DRAW mode  (D smallest)
    (20, 16, 10),  # COMP mode  (N-m smallest)
    (11, 5, 6),    # near-balanced
    (7, 7, 3),     # degenerate: all items marked -> d = D exactly
    (9, 0, 4),     # degenerate: no marked items -> d = 0 exactly
    (13, 6, 0),    # degenerate: no drops -> d = 0 exactly
])
def test_chain_exact_hypergeometric(N, m, D):
    """The §4b-v2 corner-minimal chain samples the exact HG(N, m, D) law (up
    to the spec's O(2^-22) range-reduction bias): empirical frequencies over
    many PRF streams match the closed-form pmf. This pins the sampler itself,
    independent of any protocol round."""
    from byzantinerandomizedconsensus_tpu.ops.urn2 import _chain

    B = 20_000
    inst = np.arange(B, dtype=np.uint32)
    recv = np.zeros(1, dtype=np.uint32)
    arr = lambda v: np.full((B, 1), v, dtype=np.int32)  # noqa: E731
    d = _chain(123, inst, 0, 0, recv, 2, arr(m), arr(N), arr(D), np)[:, 0]
    assert d.min() >= max(0, D - (N - m)) and d.max() <= min(m, D)
    for k in range(min(m, D) + 1):
        p = _hg_pmf(N, m, D, k)
        emp = float((d == k).mean())
        # 5-sigma binomial band around the exact pmf (plus 1e-4 slack for the
        # deterministic range-reduction bias).
        tol = 5 * math.sqrt(max(p * (1 - p), 1e-9) / B) + 1e-4
        assert abs(emp - p) < tol, f"k={k}: emp={emp:.5f} pmf={p:.5f}"


@pytest.mark.parametrize(
    "cfg", URN2_SMALL,
    ids=lambda c: f"{c.protocol}-n{c.n}f{c.f}-{c.adversary}-{c.coin}")
def test_urn2_bitmatch_small(cfg):
    ref = Simulator(cfg, "cpu").run()
    for backend in ("numpy", "jax", "native"):
        got = Simulator(cfg, backend).run()
        np.testing.assert_array_equal(ref.rounds, got.rounds, err_msg=f"rounds {backend}")
        np.testing.assert_array_equal(ref.decision, got.decision,
                                      err_msg=f"decision {backend}")


@pytest.mark.parametrize("name,n_sample", [("config2", 4), ("config3", 3), ("config4", 2)])
def test_urn2_bitmatch_benchmark_sampled(name, n_sample):
    import zlib

    cfg = preset(name, round_cap=64, delivery="urn2")
    rng = np.random.default_rng(zlib.crc32(name.encode()))
    ids = np.unique(rng.integers(0, cfg.instances, size=n_sample))
    ref = Simulator(cfg, "cpu").run(ids)
    for backend in ("numpy", "jax"):
        got = Simulator(cfg, backend).run(ids)
        np.testing.assert_array_equal(ref.rounds, got.rounds, err_msg=f"rounds {backend}")
        np.testing.assert_array_equal(ref.decision, got.decision,
                                      err_msg=f"decision {backend}")


@pytest.mark.parametrize("cfg", URN2_SMALL[:6],
                         ids=lambda c: f"{c.protocol}-{c.adversary}")
def test_urn2_agreement_and_validity(cfg):
    res = Simulator(cfg, "numpy").run()
    assert set(np.unique(res.decision)) <= {0, 1, 2}
    for init, expect in (("all0", 0), ("all1", 1)):
        c = dataclasses.replace(cfg, init=init, instances=30)
        r = Simulator(c, "numpy").run()
        decided = r.decision != 2
        assert np.all(r.decision[decided] == expect), f"validity broken for {init}"


@pytest.mark.parametrize("other", ["keys", "urn"])
def test_urn2_matches_other_models_statistically(other):
    """Same delivery distribution family ⇒ close round/decision statistics,
    against both the §4 keys model and the §4b sequential sampler."""
    base = SimConfig(protocol="bracha", n=16, f=5, instances=4000,
                     adversary="none", coin="shared", round_cap=64, seed=11)
    ref = Simulator(dataclasses.replace(base, delivery=other), "numpy").run()
    got = Simulator(dataclasses.replace(base, delivery="urn2"), "numpy").run()
    assert abs(float(ref.rounds.mean()) - float(got.rounds.mean())) < 0.1
    assert abs(float((ref.decision == 1).mean())
               - float((got.decision == 1).mean())) < 0.08


def test_urn2_adaptive_matches_urn_statistically():
    """The two-stratum (4-segment) path against §4b's draw loop — the
    stratum-priority decomposition must preserve the biased-first law."""
    base = SimConfig(protocol="bracha", n=16, f=5, instances=400,
                     adversary="adaptive", coin="local", round_cap=64, seed=11)
    ref = Simulator(dataclasses.replace(base, delivery="urn"), "native").run()
    got = Simulator(dataclasses.replace(base, delivery="urn2"), "native").run()
    assert abs(float(ref.rounds.mean()) - float(got.rounds.mean())) < 1.5
    assert abs(float((ref.decision == 1).mean())
               - float((got.decision == 1).mean())) < 0.08


@pytest.mark.parametrize("n_data,n_model", [(8, 1), (4, 2), (2, 4)])
def test_urn2_sharded_bitmatch(n_data, n_model):
    """Urn2 under shard_map (instance + replica sharding) bit-matches the
    single-device jax backend on every mesh shape."""
    from byzantinerandomizedconsensus_tpu.parallel.mesh import make_mesh
    from byzantinerandomizedconsensus_tpu.parallel.sharded import JaxShardedBackend

    cfg = SimConfig(protocol="bracha", n=16, f=5, instances=48,
                    adversary="adaptive", coin="shared", round_cap=64, seed=21,
                    delivery="urn2")
    ref = Simulator(cfg, "jax").run()
    got = JaxShardedBackend(mesh=make_mesh(n_data=n_data, n_model=n_model)).run(cfg)
    np.testing.assert_array_equal(ref.rounds, got.rounds)
    np.testing.assert_array_equal(ref.decision, got.decision)


def test_urn2_sharded_two_faced_byzantine():
    """Two-faced equivocation (spec §4b) under replica sharding with the
    §4b-v2 sampler: per-class value recomputation must line up with global
    receiver indices."""
    from byzantinerandomizedconsensus_tpu.parallel.mesh import make_mesh
    from byzantinerandomizedconsensus_tpu.parallel.sharded import JaxShardedBackend

    cfg = SimConfig(protocol="benor", n=16, f=3, instances=40,
                    adversary="byzantine", coin="local", round_cap=64, seed=31,
                    delivery="urn2")
    ref = Simulator(cfg, "cpu").run()
    got = JaxShardedBackend(mesh=make_mesh(n_data=2, n_model=4)).run(cfg)
    np.testing.assert_array_equal(ref.rounds, got.rounds)
    np.testing.assert_array_equal(ref.decision, got.decision)


def test_urn2_counts_conservation():
    """Spec §4b-v2: c0+c1+c2 = min(L, n-f-1)+1; with no faults and no bot
    values the delivered total is exactly n-f for every receiver."""
    from byzantinerandomizedconsensus_tpu.ops import urn2

    cfg = SimConfig(protocol="bracha", n=32, f=10, instances=8, adversary="none",
                    coin="shared", delivery="urn2")
    B, n = 5, cfg.n
    inst = np.arange(B, dtype=np.uint32)
    values = (np.arange(n, dtype=np.uint8) % 2)[None, :].repeat(B, 0)
    silent = np.zeros((B, n), dtype=bool)
    faulty = np.zeros((B, n), dtype=bool)
    c0, c1 = urn2.counts_fn(cfg, cfg.seed, inst, 0, 0, values, silent, faulty,
                            values, xp=np)
    np.testing.assert_array_equal(c0 + c1, np.full((B, n), n - cfg.f))
    assert (c0 <= (values == 0).sum(-1)[:, None] + 1).all()
    assert (c1 <= (values == 1).sum(-1)[:, None] + 1).all()
    assert (c0 >= 0).all() and (c1 >= 0).all()


def test_urn2_rejects_pallas_kernel():
    """The Pallas kernels implement §4b only; urn2 must fail loudly, not fall
    back silently (ADVICE r1 pattern)."""
    cfg = dataclasses.replace(URN2_SMALL[0], delivery="urn2")
    with pytest.raises(ValueError, match="urn2"):
        Simulator(cfg, "jax_pallas").run()


@pytest.mark.parametrize("adversary", ["none", "adaptive_min"])
def test_joint_counts_match_exact_stratified_law(adversary):
    """The FULL §4b-v2 decomposition against closed form: counts_fn's joint
    (c0, c1) distribution at a fixed wire must equal the deterministic stratum
    split composed with nested hypergeometrics — P(d0, d1) = HG(Lb, mb0, Db)
    · HG(Lb−mb0, mb1, Db−d0b) ⊗ (unbiased likewise) — not merely have correct
    single-segment marginals (test_chain_exact_hypergeometric). Sampled over
    many PRF instances at one receiver lane, 5σ bands per support point."""
    from byzantinerandomizedconsensus_tpu.ops import urn2

    cfg = SimConfig(protocol="bracha", n=16, f=5, instances=1,
                    adversary=adversary, coin="shared", delivery="urn2",
                    ).validate()
    n, f = cfg.n, cfg.f
    B = 40_000
    inst = np.arange(B, dtype=np.uint32)
    # Fixed wire: 5×0, 6×1, 5×⊥; faulty = last f senders (they "sent" what
    # values says — counts_fn only reads values/silent/faulty/honest).
    base = np.array([0] * 5 + [1] * 6 + [2] * 5, dtype=np.uint8)
    values = np.broadcast_to(base, (B, n)).copy()
    honest = values
    silent = np.zeros((B, n), dtype=bool)
    faulty = np.zeros((B, n), dtype=bool)
    faulty[:, n - f:] = True
    c0, c1 = urn2.counts_fn(cfg, cfg.seed, inst, 0, 0, values, silent, faulty,
                            honest, xp=np)
    v = 0  # receiver lane under test (own value 0, always delivered)
    m = [int(((np.arange(n) != v) & (base == w)).sum()) for w in (0, 1, 2)]
    L = sum(m)
    D = max(0, L - (n - f - 1))
    if adversary == "adaptive_min":
        # minority among live honest non-⊥ votes: 5×0 vs 6×1 among the 11
        # correct senders → minority = 0; biased(w) = (w == 2) | (w != 0),
        # i.e. the *majority* value and ⊥ are dropped first (spec §6.4b).
        st = [False, True, True]
    else:
        st = [False, False, False]
    mb = [m[w] if st[w] else 0 for w in range(3)]
    Lb, Db = sum(mb), min(D, sum(mb))

    def nested(mm0, mm1, LL, DD):
        """P(d0, d1) over one stratum: d0 ~ HG(LL, mm0, DD), d1 | d0."""
        out = {}
        for d0 in range(min(mm0, DD) + 1):
            p0 = _hg_pmf(LL, mm0, DD, d0)
            if p0 == 0.0:
                continue
            for d1 in range(min(mm1, DD - d0) + 1):
                p1 = _hg_pmf(LL - mm0, mm1, DD - d0, d1)
                if p1 > 0.0:
                    out[(d0, d1)] = out.get((d0, d1), 0.0) + p0 * p1
        return out

    pb = nested(mb[0], mb[1], Lb, Db)
    pu = nested(m[0] - mb[0], m[1] - mb[1], L - Lb, D - Db)
    joint = {}
    for (a0, a1), p in pb.items():
        for (b0, b1), q in pu.items():
            k = (a0 + b0, a1 + b1)
            joint[k] = joint.get(k, 0.0) + p * q

    own0 = 1  # receiver 0's own value is 0
    emp = {}
    for x, y in zip(c0[:, v], c1[:, v]):
        d0 = m[0] - (int(x) - own0)
        d1 = m[1] - int(y)
        emp[(d0, d1)] = emp.get((d0, d1), 0) + 1
    assert set(emp) <= set(joint), (sorted(emp), sorted(joint))
    for k, p in joint.items():
        e = emp.get(k, 0) / B
        tol = 5 * math.sqrt(max(p * (1 - p), 1e-9) / B) + 1e-4
        assert abs(e - p) < tol, f"{adversary} {k}: emp={e:.5f} pmf={p:.5f}"
