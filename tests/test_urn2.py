"""Urn inversion delivery (spec §4b-v2): chain-level exactness against the
closed-form hypergeometric pmf, bit-match across all four implementation
stacks, protocol properties, and statistical agreement with both the keys
model and the §4b urn sampler.

Like §4b, urn2 is a *different exact sampler of the same delivery distribution
family*: bit-matching is within delivery="urn2"; cross-model checks are
statistical.
"""

import dataclasses
import math

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu import SimConfig, Simulator, preset

URN2_SMALL = [
    SimConfig(protocol="benor", n=4, f=1, instances=60, adversary="none", coin="local",
              round_cap=64, seed=0, delivery="urn2"),
    SimConfig(protocol="benor", n=9, f=4, instances=40, adversary="crash", coin="local",
              round_cap=96, seed=1, delivery="urn2"),
    SimConfig(protocol="benor", n=16, f=3, instances=40, adversary="byzantine",
              coin="local", round_cap=64, seed=2, delivery="urn2"),
    SimConfig(protocol="benor", n=11, f=2, instances=40, adversary="adaptive",
              coin="shared", round_cap=64, seed=3, delivery="urn2"),
    SimConfig(protocol="bracha", n=10, f=3, instances=40, adversary="byzantine",
              coin="shared", round_cap=64, seed=4, delivery="urn2"),
    SimConfig(protocol="bracha", n=16, f=5, instances=40, adversary="adaptive",
              coin="shared", round_cap=64, seed=5, delivery="urn2"),
    SimConfig(protocol="bracha", n=13, f=4, instances=40, adversary="crash",
              coin="local", round_cap=64, seed=6, delivery="urn2"),
    SimConfig(protocol="bracha", n=7, f=2, instances=40, adversary="none",
              coin="shared", round_cap=64, seed=7, delivery="urn2"),
    SimConfig(protocol="bracha", n=13, f=4, instances=40, adversary="adaptive_min",
              coin="shared", round_cap=64, seed=8, delivery="urn2"),
]


def _hg_pmf(N: int, m: int, D: int, k: int) -> float:
    """Exact HG(N, m, D) pmf at k."""
    if k < max(0, D - (N - m)) or k > min(m, D):
        return 0.0
    return (math.comb(m, k) * math.comb(N - m, D - k)) / math.comb(N, D)


@pytest.mark.parametrize("N,m,D", [
    (20, 3, 9),    # ITEM mode  (m smallest)
    (20, 12, 5),   # DRAW mode  (D smallest)
    (20, 16, 10),  # COMP mode  (N-m smallest)
    (11, 5, 6),    # near-balanced
    (7, 7, 3),     # degenerate: all items marked -> d = D exactly
    (9, 0, 4),     # degenerate: no marked items -> d = 0 exactly
    (13, 6, 0),    # degenerate: no drops -> d = 0 exactly
])
def test_chain_exact_hypergeometric(N, m, D):
    """The §4b-v2 corner-minimal chain samples the exact HG(N, m, D) law (up
    to the spec's O(2^-22) range-reduction bias): empirical frequencies over
    many PRF streams match the closed-form pmf. This pins the sampler itself,
    independent of any protocol round."""
    from byzantinerandomizedconsensus_tpu.ops.urn2 import _chain

    B = 20_000
    inst = np.arange(B, dtype=np.uint32)
    recv = np.zeros(1, dtype=np.uint32)
    arr = lambda v: np.full((B, 1), v, dtype=np.int32)  # noqa: E731
    d = _chain(123, inst, 0, 0, recv, 2, arr(m), arr(N), arr(D), np)[:, 0]
    assert d.min() >= max(0, D - (N - m)) and d.max() <= min(m, D)
    for k in range(min(m, D) + 1):
        p = _hg_pmf(N, m, D, k)
        emp = float((d == k).mean())
        # 5-sigma binomial band around the exact pmf (plus 1e-4 slack for the
        # deterministic range-reduction bias).
        tol = 5 * math.sqrt(max(p * (1 - p), 1e-9) / B) + 1e-4
        assert abs(emp - p) < tol, f"k={k}: emp={emp:.5f} pmf={p:.5f}"


@pytest.mark.parametrize(
    "cfg", URN2_SMALL,
    ids=lambda c: f"{c.protocol}-n{c.n}f{c.f}-{c.adversary}-{c.coin}")
def test_urn2_bitmatch_small(cfg):
    ref = Simulator(cfg, "cpu").run()
    for backend in ("numpy", "jax", "native"):
        got = Simulator(cfg, backend).run()
        np.testing.assert_array_equal(ref.rounds, got.rounds, err_msg=f"rounds {backend}")
        np.testing.assert_array_equal(ref.decision, got.decision,
                                      err_msg=f"decision {backend}")


@pytest.mark.parametrize("name,n_sample", [("config2", 4), ("config3", 3), ("config4", 2)])
def test_urn2_bitmatch_benchmark_sampled(name, n_sample):
    import zlib

    cfg = preset(name, round_cap=64, delivery="urn2")
    rng = np.random.default_rng(zlib.crc32(name.encode()))
    ids = np.unique(rng.integers(0, cfg.instances, size=n_sample))
    ref = Simulator(cfg, "cpu").run(ids)
    for backend in ("numpy", "jax"):
        got = Simulator(cfg, backend).run(ids)
        np.testing.assert_array_equal(ref.rounds, got.rounds, err_msg=f"rounds {backend}")
        np.testing.assert_array_equal(ref.decision, got.decision,
                                      err_msg=f"decision {backend}")


@pytest.mark.parametrize("cfg", URN2_SMALL[:6],
                         ids=lambda c: f"{c.protocol}-{c.adversary}")
def test_urn2_agreement_and_validity(cfg):
    res = Simulator(cfg, "numpy").run()
    assert set(np.unique(res.decision)) <= {0, 1, 2}
    for init, expect in (("all0", 0), ("all1", 1)):
        c = dataclasses.replace(cfg, init=init, instances=30)
        r = Simulator(c, "numpy").run()
        decided = r.decision != 2
        assert np.all(r.decision[decided] == expect), f"validity broken for {init}"


@pytest.mark.parametrize("other", ["keys", "urn"])
def test_urn2_matches_other_models_statistically(other):
    """Same delivery distribution family ⇒ close round/decision statistics,
    against both the §4 keys model and the §4b sequential sampler."""
    base = SimConfig(protocol="bracha", n=16, f=5, instances=4000,
                     adversary="none", coin="shared", round_cap=64, seed=11)
    ref = Simulator(dataclasses.replace(base, delivery=other), "numpy").run()
    got = Simulator(dataclasses.replace(base, delivery="urn2"), "numpy").run()
    assert abs(float(ref.rounds.mean()) - float(got.rounds.mean())) < 0.1
    assert abs(float((ref.decision == 1).mean())
               - float((got.decision == 1).mean())) < 0.08


def test_urn2_adaptive_matches_urn_statistically():
    """The two-stratum (4-segment) path against §4b's draw loop — the
    stratum-priority decomposition must preserve the biased-first law."""
    base = SimConfig(protocol="bracha", n=16, f=5, instances=400,
                     adversary="adaptive", coin="local", round_cap=64, seed=11)
    ref = Simulator(dataclasses.replace(base, delivery="urn"), "native").run()
    got = Simulator(dataclasses.replace(base, delivery="urn2"), "native").run()
    assert abs(float(ref.rounds.mean()) - float(got.rounds.mean())) < 1.5
    assert abs(float((ref.decision == 1).mean())
               - float((got.decision == 1).mean())) < 0.08


@pytest.mark.parametrize("n_data,n_model", [(8, 1), (4, 2), (2, 4)])
def test_urn2_sharded_bitmatch(n_data, n_model):
    """Urn2 under shard_map (instance + replica sharding) bit-matches the
    single-device jax backend on every mesh shape."""
    from byzantinerandomizedconsensus_tpu.parallel.mesh import make_mesh
    from byzantinerandomizedconsensus_tpu.parallel.sharded import JaxShardedBackend

    cfg = SimConfig(protocol="bracha", n=16, f=5, instances=48,
                    adversary="adaptive", coin="shared", round_cap=64, seed=21,
                    delivery="urn2")
    ref = Simulator(cfg, "jax").run()
    got = JaxShardedBackend(mesh=make_mesh(n_data=n_data, n_model=n_model)).run(cfg)
    np.testing.assert_array_equal(ref.rounds, got.rounds)
    np.testing.assert_array_equal(ref.decision, got.decision)


def test_urn2_sharded_two_faced_byzantine():
    """Two-faced equivocation (spec §4b) under replica sharding with the
    §4b-v2 sampler: per-class value recomputation must line up with global
    receiver indices."""
    from byzantinerandomizedconsensus_tpu.parallel.mesh import make_mesh
    from byzantinerandomizedconsensus_tpu.parallel.sharded import JaxShardedBackend

    cfg = SimConfig(protocol="benor", n=16, f=3, instances=40,
                    adversary="byzantine", coin="local", round_cap=64, seed=31,
                    delivery="urn2")
    ref = Simulator(cfg, "cpu").run()
    got = JaxShardedBackend(mesh=make_mesh(n_data=2, n_model=4)).run(cfg)
    np.testing.assert_array_equal(ref.rounds, got.rounds)
    np.testing.assert_array_equal(ref.decision, got.decision)


def test_urn2_counts_conservation():
    """Spec §4b-v2: c0+c1+c2 = min(L, n-f-1)+1; with no faults and no bot
    values the delivered total is exactly n-f for every receiver."""
    from byzantinerandomizedconsensus_tpu.ops import urn2

    cfg = SimConfig(protocol="bracha", n=32, f=10, instances=8, adversary="none",
                    coin="shared", delivery="urn2")
    B, n = 5, cfg.n
    inst = np.arange(B, dtype=np.uint32)
    values = (np.arange(n, dtype=np.uint8) % 2)[None, :].repeat(B, 0)
    silent = np.zeros((B, n), dtype=bool)
    faulty = np.zeros((B, n), dtype=bool)
    c0, c1 = urn2.counts_fn(cfg, cfg.seed, inst, 0, 0, values, silent, faulty,
                            values, xp=np)
    np.testing.assert_array_equal(c0 + c1, np.full((B, n), n - cfg.f))
    assert (c0 <= (values == 0).sum(-1)[:, None] + 1).all()
    assert (c1 <= (values == 1).sum(-1)[:, None] + 1).all()
    assert (c0 >= 0).all() and (c1 >= 0).all()


def test_urn2_rejects_pallas_kernel():
    """The Pallas kernels implement §4b only; urn2 must fail loudly, not fall
    back silently (ADVICE r1 pattern)."""
    cfg = dataclasses.replace(URN2_SMALL[0], delivery="urn2")
    with pytest.raises(ValueError, match="urn2"):
        Simulator(cfg, "jax_pallas").run()
