"""At-scale acceptance (SURVEY.md §4.2; VERDICT r1 top item): the oracle-anchored
native C++ core arbitrates every accelerated backend on sampled instances at
benchmark scale, for both delivery models.

The anchoring chain: tests/test_native.py pins native to the Python object
oracle across the protocol grid; test_bitmatch.py pins numpy/jax to the oracle
on small configs and a few benchmark-n samples; here the (cheap) native core
widens the benchmark-n sampled coverage by an order of magnitude in CI and by
~10^3 in the artifact run (tools/acceptance.py, artifacts/acceptance_r3.json).
"""

import shutil

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu.backends import get_backend
from byzantinerandomizedconsensus_tpu.tools import acceptance

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no C++ toolchain")

# CI sample counts: big enough to dwarf the oracle-sampled checks (3-6 ids),
# small enough to keep the suite quick; the >=10^3 runs live in the artifact.
# keys at benchmark n costs ~0.5 s/instance on the 1-core box, so its CI
# count is the suite-budget compromise (VERDICT r2 #5).
CI_SAMPLES = {"urn": 192, "urn3": 192, "keys": 24}


@pytest.mark.parametrize("name,delivery", [
    *[(n, d) for d in ("urn", "keys")
      for n in ("config1", "config2", "config3", "config4")],
    # config5 = the adaptive adversary at benchmark n (sweep_point(512));
    # urn only in CI — the sweep pins urn, and the keys leg at n=512 costs
    # minutes on the numpy side (covered by the artifact run instead).
    ("config5", "urn"),
    # §4c legs (round 6): the cheap law at the headline shape and at the
    # adaptive benchmark point (where it must agree bit-for-bit with the
    # §4b family anyway — robust regime).
    ("config4", "urn3"),
    ("config5", "urn3"),
])
def test_at_scale_native_arbiter(name, delivery):
    entry = acceptance.check_at_scale(name, delivery,
                                      backends=("numpy", "jax"),
                                      samples=CI_SAMPLES[delivery])
    bad = {b: rec for b, rec in entry["backends"].items()
           if not rec.get("match")}
    assert not bad, f"{name}:{delivery} mismatches vs native: {bad}"


@pytest.mark.slow
def test_config2_shipped_round_cap():
    """Config 2 at its SHIPPED round cap (256) — the artifact runs lower the
    cap to 64 for cost (ACCEPT_ROUND_CAP, PRF-addressing argument), so this is
    the one leg that bit-matches the exact shipped config-2 surface
    (VERDICT r2 #7): ~100 sampled instances, native vs jax, 0 mismatches."""
    from byzantinerandomizedconsensus_tpu.config import preset

    cfg = preset("config2")
    assert cfg.round_cap == 256, "config2 shipped cap changed — update this test"
    ids = acceptance.sample_ids(cfg, 100, "config2:shipped-cap")
    ref = get_backend("native").run(cfg, ids)
    got = get_backend("jax").run(cfg, ids)
    np.testing.assert_array_equal(ref.rounds, got.rounds)
    np.testing.assert_array_equal(ref.decision, got.decision)
    # Local coin at f=(n-1)//3: most instances cap out, so the leg genuinely
    # exercises the 256-round overflow surface.
    assert (got.decision == 2).any()


@pytest.mark.slow
@pytest.mark.parametrize("n_model", [2, 4])
def test_benchmark_n_sharded_vs_native(n_model):
    """Config-4 shape on the virtual 8-device mesh with real replica-axis
    sharding ((4,2) and (2,4) meshes), bit-matched against native — the
    multi-chip correctness claim at the size that matters (VERDICT r1 #6)."""
    name, delivery, samples = "config4", "urn", 256
    cfg = acceptance._accept_config(name, delivery, samples)
    ids = acceptance.sample_ids(cfg, samples, f"sharded:{name}:{delivery}")
    ref = get_backend("native").run(cfg, ids)
    got = get_backend(f"jax_sharded:{n_model}").run(cfg, ids)
    np.testing.assert_array_equal(ref.rounds, got.rounds)
    np.testing.assert_array_equal(ref.decision, got.decision)


@pytest.mark.slow
def test_max_n_sharded_vs_native():
    """n=1024 — the v1 packing limit (prf.V1_MAX_N) and config-5's top sweep
    point — under replica-axis sharding ((2,4) mesh), bit-matched against
    native. (The overall ceiling is prf.MAX_N=4096 via the §2 v2 law;
    tests/test_packing.py covers the far side of the gate.)"""
    from byzantinerandomizedconsensus_tpu.config import sweep_point

    cfg = sweep_point(1024, instances=64)
    import dataclasses

    cfg = dataclasses.replace(cfg, round_cap=64).validate()
    ref = get_backend("native").run(cfg)
    got = get_backend("jax_sharded:4").run(cfg)
    np.testing.assert_array_equal(ref.rounds, got.rounds)
    np.testing.assert_array_equal(ref.decision, got.decision)
    assert (ref.decision != 2).all(), "shared coin should decide well before the cap"


@pytest.mark.slow
def test_max_n_adaptive_min_vs_native():
    """n=1024 under the §6.4b adversary: the minority observation, urn strata,
    and replica-sharded path at the packing limit, bit-matched against native."""
    import dataclasses

    from byzantinerandomizedconsensus_tpu.config import sweep_point

    cfg = dataclasses.replace(sweep_point(1024, instances=48),
                              adversary="adaptive_min", round_cap=64).validate()
    ref = get_backend("native").run(cfg)
    got = get_backend("jax_sharded:4").run(cfg)
    np.testing.assert_array_equal(ref.rounds, got.rounds)
    np.testing.assert_array_equal(ref.decision, got.decision)
    assert (ref.decision != 2).all()


def test_artifact_merge_roundtrip(tmp_path):
    """Separate tool invocations (TPU legs, virtual-mesh legs) must merge into
    one artifact without clobbering each other's backend entries."""
    entry = {"n": 4, "f": 1, "samples": 8, "delivery": "urn",
             "arbiter": {"backend": "native", "wall_s": 1.23},
             "backends": {"numpy": {"match": True, "mismatches": 0}}}
    path = tmp_path / "acc.json"
    acceptance.merge_artifact(path, None, {"config1:urn": dict(entry)}, "cpu")
    entry2 = dict(entry)
    # Per-run timing differs between hosts by construction; it must NOT
    # invalidate previously-merged legs.
    entry2["arbiter"] = {"backend": "native", "wall_s": 9.99}
    entry2["backends"] = {"jax": {"match": True, "mismatches": 0}}
    art = acceptance.merge_artifact(path, None, {"config1:urn": entry2}, "tpu")
    legs = art["at_scale"]["config1:urn"]["backends"]
    assert set(legs) == {"numpy@cpu", "jax@tpu"}
    assert art["all_match"]
    # A changed sample set invalidates previously-merged legs.
    entry3 = dict(entry2)
    entry3["samples"] = 16
    art = acceptance.merge_artifact(path, None, {"config1:urn": entry3}, "tpu")
    assert set(art["at_scale"]["config1:urn"]["backends"]) == {"jax@tpu"}
