"""Committee-sampled delivery (spec §10, delivery="committee"): the integer
committee laws (C, f_C, k_C) pinned and cross-checked python-int vs traced,
bit-match across the three stacks with a committee channel (cpu oracle,
numpy, jax), the counters schema rows, batched/fused lanes, and the honest
``CommitteeUnsupported`` gates on the stacks without a channel.

Unlike the full-mesh families, the committee family *changes which (n, f)
the thresholds see* — so the cross-stack bar is bit-identity within
delivery="committee", plus law-level pins for the sortition margin the
resilience gates enforce.
"""

import dataclasses

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu import SimConfig, Simulator
from byzantinerandomizedconsensus_tpu.backends import get_backend
from byzantinerandomizedconsensus_tpu.models.committee import (
    CommitteeUnsupported, check_committee_supported, quorum_params)
from byzantinerandomizedconsensus_tpu.ops import committee as cm


def _eq(a, b):
    return (np.array_equal(a.rounds, b.rounds)
            and np.array_equal(a.decision, b.decision))


# ---------------------------------------------------------------------------
# the §10.1/§10.3 integer laws


def test_committee_law_pins():
    """C(n) = min(n, max(16, 8·⌈log₂ n⌉)) and the f_C/k_C laws at the
    values the spec and the round-19 artifact quote."""
    # Degenerate zone: C == n through n = 48 (the full-mesh fold).
    assert cm.committee_size(4) == 4
    assert cm.committee_size(16) == 16
    assert cm.committee_size(40) == 40
    assert cm.committee_size(48) == 48
    # First genuine sortition at n = 49 (8·⌈log₂ 49⌉ = 48 < 49).
    assert cm.committee_size(49) == 48
    assert cm.committee_size(64) == 48
    assert cm.committee_size(2048) == 88
    assert cm.committee_size(100_000) == 136
    assert cm.committee_size(1 << 20) == 160
    # f_C: exactly f in the degenerate zone, ⌈C·f/n⌉ + ⌊√C⌋ past it.
    assert cm.committee_fault_budget(40, 7) == 7
    assert cm.committee_fault_budget(64, 4) == 3 + 6      # ⌈48·4/64⌉ + ⌊√48⌋
    assert cm.committee_fault_budget(100_000, 20_000) == 28 + 11
    assert cm.committee_quota(64, 4) == 48 - 9 - 1
    assert cm.committee_quota(40, 7) == 40 - 7 - 1        # §4b's n − f − 1


@pytest.mark.parametrize("n,f", [
    (16, 5), (49, 8), (64, 4), (2048, 200), (100_000, 20_000),
    (1 << 20, 100_000)])
def test_committee_laws_python_numpy_jax_agree(n, f):
    """The compare-sum forms are exact for python ints AND traced int32
    scalars — the batched-lane contract (ops/committee.py docstring)."""
    import jax
    import jax.numpy as jnp

    py = (cm.committee_size(n), cm.committee_fault_budget(n, f),
          cm.committee_quota(n, f))
    np_v = tuple(int(v) for v in (
        cm.committee_size(n, xp=np), cm.committee_fault_budget(n, f, xp=np),
        cm.committee_quota(n, f, xp=np)))

    @jax.jit
    def laws(a, b):
        return (cm.committee_size(a, xp=jnp),
                cm.committee_fault_budget(a, b, xp=jnp),
                cm.committee_quota(a, b, xp=jnp))

    traced = tuple(int(v) for v in laws(jnp.int32(n), jnp.int32(f)))
    assert py == np_v == traced


def test_membership_plane_matches_spec_law():
    """Sortition is a pure function of coordinates: replica u is a member
    iff prf(..., recv=u, send=0, COMMITTEE) % n < C (spec §10.1)."""
    from byzantinerandomizedconsensus_tpu.ops import prf

    cfg = SimConfig(protocol="bracha", n=64, f=10, instances=4,
                    adversary="byzantine", coin="shared", seed=11,
                    round_cap=48, delivery="committee").validate()
    inst = np.arange(3, dtype=np.uint32)
    plane = cm.membership_plane(cfg, cfg.seed, inst, 5, 1, xp=np)
    rep = np.arange(64, dtype=np.uint32)
    word = prf.prf_u32(cfg.seed, inst[:, None], 5, 1, rep[None, :], 0,
                       prf.COMMITTEE, xp=np, pack=cfg.pack_version)
    np.testing.assert_array_equal(plane, (word % np.uint32(64)) < 48)
    # Realized sizes concentrate around C = 48 (Bernoulli(C/n), σ < √C/2).
    sizes = plane.sum(axis=-1)
    assert np.all(sizes > 48 - 16) and np.all(sizes < 48 + 16)


def test_quorum_params_seam():
    """Non-committee deliveries get (n_eff, f) back as the identical
    objects (no compiled program moves); the committee family gets the
    static (C, f_C)."""
    full = SimConfig(protocol="bracha", n=64, f=10, instances=4,
                     adversary="byzantine", delivery="urn2").validate()
    n, f = quorum_params(full)
    assert n is full.n_eff and f is full.f
    comm = dataclasses.replace(full, delivery="committee").validate()
    assert quorum_params(comm) == (48, 14)
    # step_silence: the zero-cost fast path for every non-committee law.
    assert cm.step_silence(full, full.seed, np.arange(2, dtype=np.uint32),
                           0, 0, xp=np) is None


# ---------------------------------------------------------------------------
# resilience gates (spec §10.3) and the no-channel gates


def test_committee_resilience_gates():
    """The committee thresholds need the sortition margin: bracha 3·f_C < C,
    benor+lying 5·f_C < C, benor benign 2·f_C < C — each rejected with a
    message naming the violated bound (config.validate)."""
    def c(protocol, f, adversary):
        return SimConfig(protocol=protocol, n=64, f=f, instances=4,
                         adversary=adversary, delivery="committee")

    # f = 13 → f_C = 16, 3·16 = 48 ≮ 48 (the full-mesh bound 3·13 < 64 would
    # have passed — the committee gate is the binding one).
    with pytest.raises(ValueError, match="committee resilience: bracha requires"):
        c("bracha", 13, "byzantine").validate()
    c("bracha", 12, "byzantine").validate()     # f_C = 15, 45 < 48: boundary
    with pytest.raises(ValueError,
                       match=r"committee resilience: benor\+byzantine requires"):
        c("benor", 5, "byzantine").validate()   # f_C = 10, 50 ≥ 48
    c("benor", 4, "byzantine").validate()       # f_C = 9, 45 < 48
    with pytest.raises(ValueError, match="committee resilience: benor requires"):
        c("benor", 23, "crash").validate()      # f_C = 24, 48 ≮ 48
    c("benor", 22, "crash").validate()


def test_committee_gate_message_verbatim():
    cfg = SimConfig(protocol="benor", n=49, f=2, instances=4,
                    adversary="crash", delivery="committee").validate()
    with pytest.raises(CommitteeUnsupported) as ei:
        check_committee_supported(cfg, "the shard_map mesh")
    assert str(ei.value) == (
        "the shard_map mesh has no committee channel; "
        "delivery='committee' runs on the cpu|numpy|jax stacks")
    # Every other delivery passes through untouched.
    assert check_committee_supported(
        dataclasses.replace(cfg, delivery="urn3"), "anything") is None


def test_committee_unsupported_backends_degrade_cleanly():
    """The stacks without a committee channel refuse loudly before any
    compile — mirroring the FaultsUnsupported gates."""
    cfg = SimConfig(protocol="bracha", n=64, f=10, instances=4,
                    adversary="byzantine", delivery="committee").validate()
    with pytest.raises(CommitteeUnsupported, match="the native core"):
        get_backend("native").run(cfg)
    with pytest.raises(CommitteeUnsupported, match="kernel='pallas'"):
        get_backend("jax_pallas").run(cfg)
    with pytest.raises(CommitteeUnsupported, match="the shard_map mesh"):
        get_backend("jax_sharded").run(cfg)


# ---------------------------------------------------------------------------
# bit-match: oracle / numpy / jax

COMMITTEE_SMALL = [
    SimConfig(protocol="benor", n=16, f=2, instances=12, adversary="none",
              coin="local", round_cap=64, seed=0, delivery="committee"),
    SimConfig(protocol="benor", n=49, f=6, instances=4, adversary="crash",
              coin="local", round_cap=64, seed=1, delivery="committee"),
    SimConfig(protocol="benor", n=64, f=4, instances=3, adversary="byzantine",
              coin="local", round_cap=48, seed=2, delivery="committee"),
    SimConfig(protocol="benor", n=50, f=2, instances=6, adversary="adaptive",
              coin="shared", round_cap=48, seed=3, delivery="committee"),
    SimConfig(protocol="bracha", n=64, f=10, instances=6,
              adversary="byzantine", coin="shared", round_cap=48, seed=4,
              delivery="committee"),
    SimConfig(protocol="bracha", n=96, f=12, instances=4,
              adversary="adaptive", coin="shared", round_cap=48, seed=5,
              delivery="committee"),
    SimConfig(protocol="bracha", n=48, f=5, instances=6,
              adversary="adaptive_min", coin="shared", round_cap=48, seed=6,
              delivery="committee"),
]


@pytest.mark.parametrize(
    "cfg", COMMITTEE_SMALL,
    ids=lambda c: f"{c.protocol}-n{c.n}f{c.f}-{c.adversary}-{c.coin}")
def test_committee_bitmatch_small(cfg):
    """Oracle / numpy / jax derive identical committees, drops, and
    decisions — the acceptance bar every delivery family carries. The grid
    spans the degenerate fold (C = n at 16/48), the first genuine sortition
    shapes (49/50/64), and a v1-packed n = 96."""
    ref = Simulator(cfg, "cpu").run()
    for backend in ("numpy", "jax"):
        got = Simulator(cfg, backend).run()
        np.testing.assert_array_equal(ref.rounds, got.rounds,
                                      err_msg=f"rounds {backend}")
        np.testing.assert_array_equal(ref.decision, got.decision,
                                      err_msg=f"decision {backend}")


def test_committee_agreement_and_validity():
    cfg = COMMITTEE_SMALL[4]
    res = Simulator(cfg, "numpy").run()
    assert set(np.unique(res.decision)) <= {0, 1, 2}
    for init, expect in (("all0", 0), ("all1", 1)):
        c = dataclasses.replace(cfg, init=init, instances=16)
        r = Simulator(c, "numpy").run()
        decided = r.decision != 2
        assert np.all(r.decision[decided] == expect), f"validity broken for {init}"


# ---------------------------------------------------------------------------
# counters (schema v3 rows: committee_draws, committee_size@ph)


def test_committee_counters_cross_stack():
    """numpy and jax totals identical; the oracle's independent counts agree
    on the common subset; the sampler rows obey their closed-form laws."""
    from byzantinerandomizedconsensus_tpu.obs import counters as obs_counters

    cfg = SimConfig(protocol="bracha", n=64, f=10, instances=4,
                    adversary="byzantine", coin="shared", round_cap=48,
                    seed=4, delivery="committee").validate()
    nb, jb, cb = get_backend("numpy"), get_backend("jax"), get_backend("cpu")
    base = nb.run(cfg)
    res_n, doc_n = nb.run_with_counters(cfg)
    assert _eq(base, res_n), "counters moved the committee results"
    res_j, doc_j = jb.run_with_counters(cfg)
    assert doc_n["totals"] == doc_j["totals"]
    assert doc_n["schema"] == obs_counters.COUNTER_SCHEMA_VERSION

    t = doc_n["totals"]
    # §10 word law: 2·n COMMITTEE words per receiver-step (one membership
    # word per replica, one drop word per receiver), 3 steps per bracha round.
    assert t["committee_draws"] == 2 * cfg.n * 3 * t["rounds_active"]
    # Realized committee size per phase: mean over steps concentrates at C.
    size_keys = [k for k in t if k.startswith("committee_size@")]
    assert len(size_keys) == 3
    mean_c = sum(t[k] for k in size_keys) / (3 * t["rounds_active"])
    assert abs(mean_c - 48) < 6

    res_c, doc_c = cb.run_with_counters(cfg)
    assert _eq(res_n, res_c)
    common = {k: v for k, v in t.items() if k in doc_c["totals"]}
    assert common == doc_c["totals"]


# ---------------------------------------------------------------------------
# batched and fused lanes


def test_committee_batch_lanes_bitmatch():
    """Mixed-n committee lanes in one padded bucket vs the per-config jax
    path: the traced-n_eff committee laws must not shift a single draw."""
    jb = get_backend("jax")
    cfgs = [
        SimConfig(protocol="benor", n=64, f=4, instances=5,
                  adversary="byzantine", coin="local", round_cap=48, seed=1,
                  delivery="committee").validate(),
        SimConfig(protocol="benor", n=50, f=3, instances=4,
                  adversary="byzantine", coin="local", round_cap=48, seed=2,
                  delivery="committee").validate(),
        SimConfig(protocol="benor", n=49, f=2, instances=4,
                  adversary="byzantine", coin="local", round_cap=48, seed=3,
                  delivery="committee").validate(),
    ]
    for cfg, res in zip(cfgs, jb.run_batch(cfgs)):
        ref = jb.run(cfg)
        np.testing.assert_array_equal(ref.rounds, res.rounds)
        np.testing.assert_array_equal(ref.decision, res.decision)


def test_committee_fused_lanes_and_bucket_label():
    """The hunt-facing fused tier hosts committee lanes (the bucket
    universe's 10th cell) and the bucket key carries C(n_pad)."""
    from byzantinerandomizedconsensus_tpu.backends.batch import FusedBucket

    jb, nb = get_backend("jax"), get_backend("numpy")
    cfgs = [
        SimConfig(protocol="benor", n=7, f=1, instances=6, adversary="crash",
                  coin="local", round_cap=32, seed=1,
                  delivery="committee").validate(),
        SimConfig(protocol="benor", n=12, f=2, instances=5,
                  adversary="byzantine", coin="shared", round_cap=48, seed=2,
                  delivery="committee").validate(),
        SimConfig(protocol="benor", n=9, f=2, instances=6, adversary="none",
                  coin="local", round_cap=32, seed=3, init="split",
                  delivery="committee").validate(),
    ]
    results, report = jb.run_fused(cfgs)
    assert report["mode"] == "fused"
    for cfg, res in zip(cfgs, results):
        ref = nb.run(cfg)
        np.testing.assert_array_equal(ref.rounds, res.rounds)
        np.testing.assert_array_equal(ref.decision, res.decision)

    b = FusedBucket.of(cfgs[0])
    assert b.committee_c == cm.committee_size(b.n_pad)
    assert b.label().endswith(f"/C{b.committee_c}")
    plain = FusedBucket.of(dataclasses.replace(cfgs[0], delivery="urn3"))
    assert plain.committee_c == 0 and "/C" not in plain.label()
