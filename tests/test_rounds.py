"""Round-anchoring helper (utils/rounds.py): the vs_prev_round regression-guard
bookkeeping shared by bench.py and tools/product.py (VERDICT r2 #4, r3 #5;
ADVICE r3 on the unparseable-VERDICT fallback)."""

import json

from byzantinerandomizedconsensus_tpu.utils import rounds


def _value(doc):
    try:
        return float(doc.get("parsed", doc).get("value"))
    except (AttributeError, TypeError, ValueError):
        return None


def test_prev_round_skips_dead_capture(tmp_path):
    (tmp_path / "VERDICT.md").write_text("# VERDICT — round 3\n")
    (tmp_path / "BENCH_r02.json").write_text(json.dumps({"parsed": {"value": 111.0}}))
    (tmp_path / "BENCH_r03.json").write_text(json.dumps({"rc": 1}))  # dead capture
    got = rounds.prev_round_artifact("BENCH", root=tmp_path,
                                     usable=lambda d: _value(d) is not None)
    assert got[:2] == ("BENCH_r02.json", 2)


def test_prev_round_walks_past_multiple_dead_rounds(tmp_path):
    """The regression guard must keep walking: a dead driver capture AND an
    unparseable JSON in between still resolve to the newest usable round —
    bench.py keys its vs_prev_round comparison on exactly this walk."""
    (tmp_path / "VERDICT.md").write_text("# VERDICT — round 5\n")
    (tmp_path / "BENCH_r02.json").write_text(json.dumps({"parsed": {"value": 7.0}}))
    (tmp_path / "BENCH_r03.json").write_text("{truncated garbage")     # unparseable
    (tmp_path / "BENCH_r04.json").write_text(json.dumps({"rc": 1}))    # dead capture
    (tmp_path / "BENCH_r05.json").write_text(json.dumps({"parsed": {}}))  # no value
    got = rounds.prev_round_artifact("BENCH", root=tmp_path,
                                     usable=lambda d: _value(d) is not None)
    assert got[:2] == ("BENCH_r02.json", 2)
    assert _value(got[2]) == 7.0
    # ...and None when every candidate is dead — the guard is then omitted,
    # never fed a corpse.
    (tmp_path / "BENCH_r02.json").write_text(json.dumps({"rc": 1}))
    assert rounds.prev_round_artifact(
        "BENCH", root=tmp_path,
        usable=lambda d: _value(d) is not None) is None


def test_bench_prev_round_headline_uses_dead_capture_fallback(tmp_path, monkeypatch):
    """bench.py's _prev_round_headline end-to-end over the walk: skips the
    dead newest round and surfaces (artifact, value, device_busy_s) from the
    older usable one."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench", rounds.repo_root() / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    (tmp_path / "VERDICT.md").write_text("# VERDICT — round 4\n")
    (tmp_path / "BENCH_r03.json").write_text(json.dumps(
        {"parsed": {"value": 123.0, "detail": {"device_busy_s": 0.25}}}))
    (tmp_path / "BENCH_r04.json").write_text(json.dumps({"rc": 1}))
    monkeypatch.setattr(rounds, "repo_root", lambda: tmp_path)
    assert bench._prev_round_headline() == ("BENCH_r03.json", 123.0, 0.25)


def test_prev_round_never_exceeds_verdict_round(tmp_path):
    # BENCH_r04 is the CURRENT round's capture — must not self-compare.
    (tmp_path / "VERDICT.md").write_text("# VERDICT — round 3\n")
    (tmp_path / "BENCH_r03.json").write_text(json.dumps({"parsed": {"value": 5.0}}))
    (tmp_path / "BENCH_r04.json").write_text(json.dumps({"parsed": {"value": 9.0}}))
    got = rounds.prev_round_artifact("BENCH", root=tmp_path)
    assert got[:2] == ("BENCH_r03.json", 3)


def test_unparseable_verdict_omits_comparison(tmp_path, capsys):
    (tmp_path / "VERDICT.md").write_text("garbled heading\n")
    (tmp_path / "BENCH_r03.json").write_text(json.dumps({"parsed": {"value": 5.0}}))
    assert rounds.prev_round_artifact("BENCH", root=tmp_path) is None
    assert "unparseable" in capsys.readouterr().err
    assert rounds.this_round(tmp_path) is None


def test_round_numbering(tmp_path):
    assert rounds.this_round(tmp_path) == 1          # no VERDICT: round 1
    (tmp_path / "VERDICT.md").write_text("# VERDICT — round 3\n")
    assert rounds.verdict_round(tmp_path) == (True, 3)
    assert rounds.this_round(tmp_path) == 4


def test_default_artifact_matches_prev_round_lookup(tmp_path):
    """The shared --out default and prev_round_artifact's glob must agree —
    a tool writing this round's default name must be found as 'previous
    round' by the next round's guard."""
    (tmp_path / "VERDICT.md").write_text("# VERDICT — round 3\n")
    name = rounds.default_artifact("product", root=tmp_path)
    assert name == "artifacts/product_r4.json"
    art = tmp_path / name
    art.parent.mkdir()
    art.write_text(json.dumps({"x": 1}))
    (tmp_path / "VERDICT.md").write_text("# VERDICT — round 4\n")  # next round
    got = rounds.prev_round_artifact("product", root=tmp_path, subdir="artifacts")
    assert got[:2] == ("product_r4.json", 4)
    # unparseable VERDICT: unstamped fallback name
    (tmp_path / "VERDICT.md").write_text("garbled\n")
    assert rounds.default_artifact("product", root=tmp_path) == "artifacts/product.json"
