"""Decision-driven lane compaction (backends/compaction.py; round 11).

The acceptance bar is bit-identity: every instance that rides the compacted
lane grid — whatever lane, segment, or refill generation it lands in — must
equal the per-chunk path bit-for-bit, across the fault × adversary ×
delivery grid, with mixed-n padding lanes, with counters on (pad-exact
totals equality), and across refill boundaries that cut through crash
windows. Plus the policy law's pinned rejections, the §2 chunk-ceiling
clamp (satellite), the standard straggler metrics (utils/metrics.py), the
schema-v1.2 record block, and the bench_compaction tier-1 smoke.
"""

import json

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu.backends import get_backend
from byzantinerandomizedconsensus_tpu.backends.compaction import (
    CompactionPolicy)
from byzantinerandomizedconsensus_tpu.config import (
    DELIVERY_KINDS, FAULT_KINDS, SimConfig)
from byzantinerandomizedconsensus_tpu.utils import metrics

# One protocol pairing per adversary (mirrors tests/test_batch.py).
_ADV_PROTO = (("none", "benor"), ("crash", "benor"), ("byzantine", "bracha"),
              ("adaptive", "bracha"), ("adaptive_min", "bracha"))

#: Small grid + tiny width so every run exercises several refill
#: generations (width 4 over ~13 queued instances).
_POLICY = CompactionPolicy(width=4, segment=1, refill_threshold=0.25)


def _cfg(adv, proto, delivery, fault, n=7, f=2, seed=13, **kw):
    base = dict(protocol=proto, n=n, f=f, instances=4, adversary=adv,
                coin="local", seed=seed, round_cap=32, delivery=delivery,
                faults=fault)
    base.update(kw)
    return SimConfig(**base).validate()


def _lanes(adv, proto, delivery, fault):
    """Three configs of one bucket: varying f, seed and (mixed-n padding) n."""
    return [
        _cfg(adv, proto, delivery, fault),
        _cfg(adv, proto, delivery, fault, f=1, seed=99, instances=6),
        _cfg(adv, proto, delivery, fault, n=6, f=1, seed=7, instances=3),
    ]


def _assert_compacted_matches(cfgs, policy=_POLICY):
    jb = get_backend("jax")
    results, report = jb.run_many(cfgs, compaction=policy)
    for cfg, res in zip(cfgs, results):
        ref = get_backend("numpy").run(cfg)
        np.testing.assert_array_equal(ref.rounds, res.rounds)
        np.testing.assert_array_equal(ref.decision, res.decision)
    comp = report["compaction"]
    assert comp["occupancy"] is None or 0 < comp["occupancy"] <= 1
    assert comp["segments"] >= 1
    return report


# ---------------------------------------------------------------------------
# policy law


def test_policy_parse_and_validate():
    p = CompactionPolicy.parse("width=64,segment=3,threshold=0.5")
    assert (p.width, p.segment, p.refill_threshold) == (64, 3, 0.5)
    assert CompactionPolicy.parse("1") == CompactionPolicy()
    assert CompactionPolicy.parse("") == CompactionPolicy()
    assert CompactionPolicy.parse("w=8,s=2,t=1.0").width == 8
    with pytest.raises(ValueError, match="unknown compaction policy field"):
        CompactionPolicy.parse("wat=3")
    with pytest.raises(ValueError, match="segment=0 out of range"):
        CompactionPolicy(segment=0).validate()
    with pytest.raises(ValueError, match="refill_threshold"):
        CompactionPolicy(refill_threshold=0.0).validate()
    with pytest.raises(ValueError, match="width=0 out of range"):
        CompactionPolicy(width=0).validate()


# ---------------------------------------------------------------------------
# bit-identity: compacted lanes vs the per-chunk path


def test_compaction_bitmatch_tier1_sample():
    """Covering sample over (fault, delivery) with rotating adversaries —
    every fault kind and every delivery law once, 3 mixed-n configs each
    through one shared queue at width 4 (several refill generations). The
    full 16-cell grid runs as the slow-marked variant below."""
    cells = [(FAULT_KINDS[i], DELIVERY_KINDS[j])
             for i, j in ((0, 0), (1, 1), (2, 3), (3, 2))]
    for i, (fault, delivery) in enumerate(cells):
        adv, proto = _ADV_PROTO[i % len(_ADV_PROTO)]
        _assert_compacted_matches(_lanes(adv, proto, delivery, fault))


@pytest.mark.slow
@pytest.mark.parametrize("delivery", DELIVERY_KINDS)
@pytest.mark.parametrize("fault", FAULT_KINDS)
def test_compaction_bitmatch_grid_full(fault, delivery):
    i = FAULT_KINDS.index(fault) + DELIVERY_KINDS.index(delivery)
    adv, proto = _ADV_PROTO[i % len(_ADV_PROTO)]
    _assert_compacted_matches(_lanes(adv, proto, delivery, fault))


def test_compacted_backend_vs_per_chunk_jax():
    """The registered ``jax_compact`` backend against per-chunk *jax*
    directly (not just numpy): lane placement and segment boundaries must
    not shift a single PRF draw."""
    cfg = _cfg("byzantine", "bracha", "urn2", "none", instances=13, seed=2)
    ref = get_backend("jax").run(cfg)
    cb = get_backend("jax_compact:width=4,segment=2")
    res = cb.run(cfg)
    np.testing.assert_array_equal(ref.rounds, res.rounds)
    np.testing.assert_array_equal(ref.decision, res.decision)
    stats = cb.last_stats
    assert stats["refills"] >= 1          # 13 instances through 4 lanes
    assert stats["useful_lane_rounds"] == int(ref.rounds.sum())
    assert stats["device_lane_rounds"] >= stats["useful_lane_rounds"]
    assert stats["policy"]["segment"] == 2


def test_refill_mid_stream_crash_window():
    """Refill boundaries cutting through §3.3/§9 crash windows: instances
    enter lanes mid-run (their round counter restarts at 0 while neighbours
    sit at later rounds), and crash/recovery draws keyed on (instance,
    round) must replay bit-identically. crash_window=3 with segment=2 puts
    window edges inside and across segments."""
    for faults, adv in (("recover", "crash"), ("none", "crash")):
        cfg = _cfg(adv, "benor", "urn2", faults, instances=11, seed=31,
                   crash_window=3)
        ref = get_backend("numpy").run(cfg)
        res = get_backend("jax_compact:width=4,segment=2,threshold=0.25").run(cfg)
        np.testing.assert_array_equal(ref.rounds, res.rounds)
        np.testing.assert_array_equal(ref.decision, res.decision)


def test_fused_compaction_mixed_axes():
    """run_fused(compaction=...): one queue per fused bucket, with
    adversary/fault/coin/init/cap codes riding as per-lane operands — every
    config bit-identical to numpy."""
    jb = get_backend("jax")
    cfgs = [
        _cfg("byzantine", "bracha", "urn2", "partition", coin="shared",
             init="all1", round_cap=24, instances=5),
        _cfg("adaptive", "bracha", "urn2", "none", f=1, seed=5,
             coin="shared", init="split", instances=4),
        _cfg("none", "bracha", "urn2", "omission", n=6, f=1, seed=8,
             round_cap=48, instances=3),
    ]
    results, report = jb.run_fused(cfgs, compaction=_POLICY)
    for cfg, res in zip(cfgs, results):
        ref = get_backend("numpy").run(cfg)
        np.testing.assert_array_equal(ref.rounds, res.rounds)
        np.testing.assert_array_equal(ref.decision, res.decision)
    assert report["compaction"]["segments"] >= 1
    assert report["mode"] == "fused"


# ---------------------------------------------------------------------------
# counters: invariance + pad-exact totals on the compacted path


def test_compaction_counters_invariance_and_pad_exact_totals():
    """Counters-on compacted lanes: (rounds, decision) bit-identical to the
    counter-free path, per-instance accumulator rows harvested at retire
    time, and totals equal to the numpy counted run — including on a padded
    lane (n=6 inside the tier-8 program)."""
    jb = get_backend("jax")
    cfgs = [_cfg("adaptive", "bracha", "urn2", "partition", seed=3,
                 coin="shared", instances=5),
            _cfg("adaptive", "bracha", "urn2", "partition", n=6, f=1,
                 seed=21, coin="shared", instances=4)]
    results, docs, report = jb.run_many(cfgs, counters=True,
                                        compaction=_POLICY)
    for cfg, res, doc in zip(cfgs, results, docs):
        ref = get_backend("numpy").run(cfg)
        np.testing.assert_array_equal(ref.rounds, res.rounds)
        np.testing.assert_array_equal(ref.decision, res.decision)
        _, ndoc = get_backend("numpy").run_with_counters(cfg)
        assert doc["totals"] == ndoc["totals"]
        assert doc["supported"] and doc["schema"] == ndoc["schema"]
    assert report["compaction"]["segments"] >= 1


def test_fused_compaction_rejects_counters():
    from byzantinerandomizedconsensus_tpu.backends import compaction
    from byzantinerandomizedconsensus_tpu.backends.batch import FusedBucket
    from byzantinerandomizedconsensus_tpu.obs.counters import (
        CountersUnsupported)

    cfg = _cfg("none", "benor", "urn2", "none")
    jb = get_backend("jax")
    with pytest.raises(CountersUnsupported, match="fused compacted lanes"):
        compaction.run_bucket(jb, FusedBucket.of(cfg), [cfg],
                              [np.arange(4)], counters=True)


# ---------------------------------------------------------------------------
# straggler metrics (satellite): the PERF round-1 accounting as a metric


def test_wasted_lane_fraction_and_mean_max_rounds():
    rounds = np.array([1, 2, 1, 1, 3, 1, 1, 1], dtype=np.int32)
    # chunks of 4: maxes 2 and 3 -> device = (2 + 3) * 4 = 20, useful = 11.
    assert metrics.mean_max_rounds_per_chunk(rounds, 4) == 2.5
    assert metrics.wasted_lane_fraction(rounds, 4) == round(1 - 11 / 20, 6)
    # one instance per chunk: no straggler waste at all.
    assert metrics.wasted_lane_fraction(rounds, 1) == 0.0
    # tail chunk padded to the compiled width: 5 instances over chunk=4
    # pay (max(r[:4]) + max(r[4:])) * 4 device lane-rounds.
    r5 = np.array([1, 1, 1, 1, 4], dtype=np.int32)
    assert metrics.wasted_lane_fraction(r5, 4) == round(1 - 8 / 20, 6)
    assert metrics.wasted_lane_fraction(np.empty(0, dtype=np.int32), 4) is None
    with pytest.raises(ValueError, match="chunk=0"):
        metrics.wasted_lane_fraction(rounds, 0)


def test_summary_reports_straggler_metrics():
    from byzantinerandomizedconsensus_tpu.backends.base import SimResult

    cfg = _cfg("none", "benor", "urn2", "none", instances=6)
    res = SimResult(config=cfg, inst_ids=np.arange(6),
                    rounds=np.array([1, 2, 1, 1, 1, 1], dtype=np.int32),
                    decision=np.zeros(6, dtype=np.uint8))
    s = metrics.summary(res, chunk=3)
    assert s["chunk"] == 3
    assert s["mean_max_rounds_per_chunk"] == 1.5
    assert s["wasted_lane_fraction"] == round(1 - 7 / 9, 6)
    assert "wasted_lane_fraction" not in metrics.summary(res)


# ---------------------------------------------------------------------------
# §2 packing ceiling (satellite): chunk sizing clamped to the pack law


def test_chunk_size_respects_pack_law_ceiling():
    from byzantinerandomizedconsensus_tpu.backends.jax_backend import (
        JaxBackend)
    from byzantinerandomizedconsensus_tpu.ops import prf

    jb = JaxBackend(chunk_bytes=1 << 40, max_chunk=1 << 20)
    v1 = SimConfig(protocol="bracha", n=4, f=1, instances=8,
                   delivery="urn2").validate()
    assert jb._chunk_size(v1) <= prf.MAX_INSTANCES
    v2 = SimConfig(protocol="bracha", n=2048, f=682, instances=8,
                   delivery="urn2").validate()
    assert v2.pack_version == 2
    assert jb._chunk_size(v2) <= prf.V2_MAX_INSTANCES
    # keys model at tiny n would otherwise blow past the v1 ceiling too.
    k1 = SimConfig(protocol="benor", n=4, f=1, instances=8).validate()
    assert jb._chunk_size(k1) <= prf.MAX_INSTANCES


def test_validate_instances_overflow_names_pack_law():
    from byzantinerandomizedconsensus_tpu.ops import prf

    with pytest.raises(ValueError, match=r"spec\s+§2 v2 law packs instance"):
        SimConfig(protocol="bracha", n=2048, f=682,
                  instances=prf.V2_MAX_INSTANCES + 1).validate()


# ---------------------------------------------------------------------------
# schema v1.2: the compaction record block


def test_record_compaction_block_and_validation():
    from byzantinerandomizedconsensus_tpu.obs import record

    assert record.RECORD_REVISION >= 2
    assert record.compaction_block(None) is None
    stats = {"width": 8, "segments": 3, "refills": 2,
             "device_lane_rounds": 40, "useful_lane_rounds": 30,
             "occupancy": 0.75, "wasted_lane_fraction": 0.25,
             "policy": {"width": 8, "segment": 1, "refill_threshold": 0.25}}
    block = record.compaction_block(stats)
    doc = record.new_record("bench_compaction")
    doc["compaction"] = block
    assert record.validate_record(doc) == []
    bad = dict(doc)
    bad["compaction"] = {"occupancy": 0.5}
    problems = record.validate_record(bad)
    assert any("compaction block missing" in p for p in problems)


def test_run_record_from_backend_last_stats():
    from byzantinerandomizedconsensus_tpu.obs import record

    cfg = _cfg("none", "benor", "urn2", "none", instances=6)
    cb = get_backend("jax_compact:width=4,segment=1")
    cb.run(cfg)
    block = record.compaction_block(cb)
    assert block is not None and block["policy"]["width"] == 4
    doc = record.new_record("bench")
    doc["compaction"] = block
    assert record.validate_record(doc) == []


def test_bench_headline_records_compaction_block(tmp_path, monkeypatch,
                                                 capsys):
    """bench.py under BENCH_COMPACTION: the one-line artifact carries the
    schema-v1.2 compaction block next to the standard straggler metrics,
    keeps the CPU-only device_chain_note, and validates (satellite)."""
    import importlib.util

    from byzantinerandomizedconsensus_tpu.obs import record
    from byzantinerandomizedconsensus_tpu.utils.rounds import repo_root

    spec = importlib.util.spec_from_file_location(
        "bench", repo_root() / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    monkeypatch.setenv("BENCH_COMPACTION", "width=32,segment=1")
    monkeypatch.setattr("sys.argv", ["bench.py", "64"])
    assert bench.main() == 0
    line = [ln for ln in capsys.readouterr().out.splitlines()
            if ln.startswith("{")][-1]
    doc = json.loads(line)
    assert doc["record_revision"] >= 2
    assert record.validate_record(doc) == []
    assert doc["compaction"]["policy"]["width"] == 32
    assert doc["compaction"]["occupancy"] is not None
    assert doc["detail"]["wasted_lane_fraction"] is not None
    assert doc["detail"]["mean_max_rounds_per_chunk"] >= 1
    import jax

    if jax.default_backend() != "tpu":
        assert "device_chain_note" in doc


# ---------------------------------------------------------------------------
# bench_compaction smoke (the r11 A/B instrument, tier-1 sized)


def test_bench_compaction_smoke(tmp_path, capsys):
    from byzantinerandomizedconsensus_tpu.obs import record
    from byzantinerandomizedconsensus_tpu.tools import bench_compaction

    out = tmp_path / "compaction_smoke.json"
    rc = bench_compaction.main([
        "--smoke", "--instances", "64", "--deliveries", "urn2",
        "--policies", "width=16,segment=1,threshold=0.25",
        "--out", str(out)])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert doc["kind"] == "bench_compaction"
    assert record.validate_record(doc) == []
    leg = doc["legs"]["urn2"]
    assert leg["per_chunk"]["wasted_lane_fraction"] is not None
    assert leg["best"]["bit_identical"] is True
    assert leg["best"]["occupancy"] is not None
    assert doc["summary"]["bit_identical_all"] is True
    # The ledger reconstructs the occupancy columns from this artifact.
    from byzantinerandomizedconsensus_tpu.tools import ledger

    rows = ledger._compaction_rows_of("x.json", doc)
    assert rows and all(r["occupancy"] is not None for r in rows)
    capsys.readouterr()


# ---------------------------------------------------------------------------
# the live metrics plane (round 16)


def test_compaction_metrics_on_off_bit_identical():
    """Round 16: the consensus-health instrumentation at on_retire reads
    host-fetched state only — a compacted run with the metrics registry
    enabled equals the metrics-off run bit-for-bit, while the grid and
    consensus families fill from the same retirements."""
    from byzantinerandomizedconsensus_tpu.obs import metrics as _metrics

    cfgs = _lanes("none", "benor", "keys", "none")
    jb = get_backend("jax")
    off, _ = jb.run_many(cfgs, compaction=_POLICY)
    _metrics.configure()
    try:
        on, _ = jb.run_many(cfgs, compaction=_POLICY)
        snap = _metrics.snapshot()
    finally:
        _metrics.disable()
    for a, b in zip(off, on):
        np.testing.assert_array_equal(a.rounds, b.rounds)
        np.testing.assert_array_equal(a.decision, b.decision)

    assert snap["brc_compaction_segments_total"]["series"][0]["value"] >= 1
    rounds = snap["brc_consensus_rounds"]["series"][0]
    assert rounds["count"] == sum(cfg.instances for cfg in cfgs)
    s = _metrics.summary(snap)
    assert s["decided_fraction"] is not None and 0 <= s["decided_fraction"] <= 1
    decided = _metrics._sum_values(snap, "brc_consensus_decided_total") or 0
    undecided = _metrics._sum_values(snap, "brc_consensus_undecided_total") or 0
    assert decided + undecided == rounds["count"]
