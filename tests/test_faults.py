"""Fault-schedule axis (spec §9): composition grid, edge cases, gates.

The heart is the three-stack bit-match over the full composition grid —
all 4 fault kinds × {none, crash, byzantine} × all 4 delivery laws — plus
the §1 safety invariants over every cell, the recover-rejoin edge (outage
opening at round 0 and healing at/after round_cap), the crash_window
validation satellite, and the honest FaultsUnsupported gates.
"""

import dataclasses

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu.backends import get_backend
from byzantinerandomizedconsensus_tpu.config import (
    DELIVERY_KINDS, FAULT_KINDS, SimConfig)
from byzantinerandomizedconsensus_tpu.core.faults import FaultSchedule
from byzantinerandomizedconsensus_tpu.models import faults as mfaults
from byzantinerandomizedconsensus_tpu.models import invariants
from byzantinerandomizedconsensus_tpu.models.adversaries import AdversaryModel
from byzantinerandomizedconsensus_tpu.models.faults import FaultsUnsupported

# One protocol pairing per adversary: benign/crash run Ben-Or (protocol A),
# byzantine runs Bracha (the n > 3f benchmark pairing, spec §5.2).
_ADV_PROTO = (("none", "benor"), ("crash", "benor"), ("byzantine", "bracha"))


def _cfg(adv, proto, delivery, fault, **kw):
    base = dict(protocol=proto, n=7, f=2, instances=4, adversary=adv,
                coin="local", seed=13, round_cap=32, delivery=delivery,
                faults=fault)
    base.update(kw)
    return SimConfig(**base).validate()


@pytest.mark.parametrize("delivery", DELIVERY_KINDS)
@pytest.mark.parametrize("adv,proto", _ADV_PROTO)
@pytest.mark.parametrize("fault", FAULT_KINDS)
def test_fault_grid_oracle_numpy_bitmatch(fault, adv, proto, delivery):
    """The full 4 × 3 × 4 composition grid, oracle vs numpy, with the §1
    safety invariants over the full per-replica state for every cell."""
    cfg = _cfg(adv, proto, delivery, fault)
    a = get_backend("numpy").run(cfg)
    b = get_backend("cpu").run(cfg)
    np.testing.assert_array_equal(a.rounds, b.rounds)
    np.testing.assert_array_equal(a.decision, b.decision)
    assert invariants.check_config(cfg)["violations"] == []


@pytest.mark.slow
@pytest.mark.parametrize("delivery", DELIVERY_KINDS)
@pytest.mark.parametrize("adv,proto", _ADV_PROTO)
@pytest.mark.parametrize("fault", FAULT_KINDS)
def test_fault_grid_jax_bitmatch_full(fault, adv, proto, delivery):
    """The same full grid against the jit'd jax stack — 48 distinct compiled
    programs, so the exhaustive sweep is marked slow (still run by default;
    the tier-1 budget gets the covering sample below)."""
    cfg = _cfg(adv, proto, delivery, fault)
    a = get_backend("numpy").run(cfg)
    c = get_backend("jax").run(cfg)
    np.testing.assert_array_equal(a.rounds, c.rounds)
    np.testing.assert_array_equal(a.decision, c.decision)


def test_fault_grid_jax_bitmatch_tier1_sample():
    """Tier-1 jax leg: every (fault, delivery) pair once, rotating through
    the adversary pairings — 16 cells covering all three axes' values."""
    for i, fault in enumerate(FAULT_KINDS):
        for j, delivery in enumerate(DELIVERY_KINDS):
            adv, proto = _ADV_PROTO[(i + j) % len(_ADV_PROTO)]
            cfg = _cfg(adv, proto, delivery, fault)
            a = get_backend("numpy").run(cfg)
            c = get_backend("jax").run(cfg)
            np.testing.assert_array_equal(a.rounds, c.rounds)
            np.testing.assert_array_equal(a.decision, c.decision)


def test_faults_none_is_the_frozen_fast_path():
    """faults="none" must not even build fault state — the setup carries
    None, so compiled programs and draws are untouched by construction."""
    cfg = _cfg("crash", "benor", "urn2", "none")
    setup = AdversaryModel(cfg).setup(cfg.seed, np.arange(4), xp=np)
    assert setup["faults"] is None
    fsil, fside = mfaults.round_masks(cfg, cfg.seed, np.arange(4), 0,
                                      setup["faults"], xp=np)
    assert fsil is None and fside is None


def test_fault_prone_set_coincides_with_adversary_faulty():
    """With an active adversary the §9 fault-prone set IS the §3.2 faulty
    set (same PRF purpose), so composed misbehavior never exceeds f."""
    cfg = _cfg("crash", "benor", "urn2", "recover", instances=8)
    ids = np.arange(8)
    setup = AdversaryModel(cfg).setup(cfg.seed, ids, xp=np)
    np.testing.assert_array_equal(setup["faults"]["fprone"], setup["faulty"])


def test_partition_isolates_only_fault_prone_replicas():
    cfg = _cfg("none", "benor", "urn2", "partition", instances=16)
    ids = np.arange(16)
    fsetup = mfaults.setup_faults(cfg, cfg.seed, ids, xp=np)
    assert ((fsetup["side"] == 1) <= fsetup["fprone"]).all()
    # The per-round plane is zero outside the epoch and ⊆ side inside it.
    for r in range(cfg.round_cap):
        _, fside = mfaults.round_masks(cfg, cfg.seed, ids, r, fsetup, xp=np)
        active = ((r >= fsetup["part_start"])
                  & (r < fsetup["part_heal"]))[:, None]
        np.testing.assert_array_equal(
            fside, np.where(active, fsetup["side"], 0).astype(np.uint8))


def test_scalar_and_vectorized_masks_agree():
    """core/faults.py (oracle) and models/faults.py (vectorized) must emit
    bit-identical per-round masks for every kind."""
    for fault in ("recover", "partition", "omission"):
        cfg = _cfg("crash", "benor", "urn2", fault, instances=6,
                   crash_window=8)
        ids = np.arange(6)
        fsetup = mfaults.setup_faults(cfg, cfg.seed, ids, xp=np)
        for i in range(6):
            fs = FaultSchedule(cfg, cfg.seed, i)
            for r in range(cfg.round_cap):
                vsil, vside = mfaults.round_masks(cfg, cfg.seed, ids, r,
                                                  fsetup, xp=np)
                osil, oside = fs.round_masks(r)
                if vsil is None:
                    assert osil is None or not osil.any()
                else:
                    np.testing.assert_array_equal(vsil[i], osil)
                if vside is not None:
                    want = oside if oside is not None \
                        else np.zeros(cfg.n, dtype=np.uint8)
                    np.testing.assert_array_equal(vside[i], want)


def test_recover_rejoin_edge_crash_at_0_heal_at_round_cap():
    """The edge schedule: an outage opening at round 0 whose heal lands at or
    past round_cap — the replica is silent for the entire run and 'rejoins'
    exactly at the simulation edge. Found by a deterministic seed scan, then
    run through all three stacks + the safety checker."""
    cap, w = 8, 16
    hit = None
    for seed in range(500):
        cfg = SimConfig(protocol="benor", n=7, f=2, instances=1,
                        adversary="none", seed=seed, round_cap=cap,
                        crash_window=w, delivery="urn2",
                        faults="recover").validate()
        fs = FaultSchedule(cfg, seed, 0)
        m = fs.fprone & (fs.down_at == 0) & (fs.up_at >= cap)
        if m.any():
            hit = (cfg, fs, int(np.argmax(m)))
            break
    assert hit is not None, "no edge schedule within the scanned seed range"
    cfg, fs, j = hit
    fsetup = mfaults.setup_faults(cfg, cfg.seed, np.arange(1), xp=np)
    for r in range(cap):
        fsil, _ = mfaults.round_masks(cfg, cfg.seed, np.arange(1), r,
                                      fsetup, xp=np)
        assert fsil[0, j], f"edge replica spoke at round {r}"
        np.testing.assert_array_equal(fsil[0], fs.round_masks(r)[0])
    a = get_backend("numpy").run(cfg)
    b = get_backend("cpu").run(cfg)
    np.testing.assert_array_equal(a.rounds, b.rounds)
    np.testing.assert_array_equal(a.decision, b.decision)
    assert invariants.check_config(cfg)["violations"] == []


def test_crash_window_validation_message():
    """Satellite: crash_window < 1 used to reach ``% crash_window`` and yield
    silent numpy garbage; it must be a config error with a pinned message."""
    for bad in (0, -3):
        with pytest.raises(ValueError, match=rf"crash_window={bad} out of "
                                             r"range \(>= 1\)"):
            SimConfig(adversary="crash", crash_window=bad).validate()
    # Window 1 is the smallest valid schedule scale.
    SimConfig(adversary="crash", crash_window=1).validate()


def test_unknown_faults_rejected():
    with pytest.raises(ValueError, match="unknown faults"):
        SimConfig(faults="meteor").validate()


def test_faults_unsupported_gates():
    cfg = _cfg("none", "benor", "urn", "recover")
    with pytest.raises(FaultsUnsupported):
        get_backend("jax_pallas").run(cfg)
    import shutil
    if shutil.which("g++"):
        with pytest.raises(FaultsUnsupported):
            get_backend("native").run(cfg)


def test_virtual_mesh_supports_faults():
    """The host-side SPMD mesh shares the round bodies through the same
    recv_ids seams, so the fault axis rides along — pinned here so a future
    refactor cannot silently drop it."""
    cfg = SimConfig(protocol="bracha", n=8, f=2, instances=10,
                    adversary="crash", seed=4, round_cap=48,
                    delivery="urn2", faults="partition").validate()
    a = get_backend("numpy").run(cfg)
    v = get_backend("virtual:2x2").run(cfg)
    np.testing.assert_array_equal(a.rounds, v.rounds)
    np.testing.assert_array_equal(a.decision, v.decision)


def test_liveness_degrades_but_safety_holds():
    """The §9 schedules must cost rounds, not correctness: under recover the
    mean rounds-to-decision may only move, never the invariants."""
    base = SimConfig(protocol="benor", n=9, f=4, instances=64,
                     adversary="none", seed=2, round_cap=96,
                     delivery="urn2").validate()
    r0 = get_backend("numpy").run(base)
    for fault in ("recover", "partition", "omission"):
        cfg = dataclasses.replace(base, faults=fault)
        assert invariants.check_config(cfg)["violations"] == []
    assert (r0.decision != 2).any()
