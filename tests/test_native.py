"""Native C++ backend (native/simcore.cpp): bit-match vs the Python oracle and the
vectorized backends, thread-count invariance, and subset/overflow contracts.

The native core is an independent third implementation of spec/PROTOCOL.md (scalar
C++ vs the object oracle vs the vectorized arrays); these tests are what make it an
oracle-grade accelerator rather than just a fast approximation.
"""

import itertools
import shutil

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu.backends import get_backend
from byzantinerandomizedconsensus_tpu.config import SimConfig

pytestmark = pytest.mark.skipif(shutil.which("g++") is None, reason="no C++ toolchain")


def _sizes(proto, adv):
    if proto == "benor" and adv in ("byzantine", "adaptive"):
        return 11, 2  # n > 5f (Protocol B)
    if proto == "bracha":
        return 10, 3  # n > 3f
    return 7, 3       # n > 2f


@pytest.mark.parametrize("proto", ["benor", "bracha"])
@pytest.mark.parametrize("adv", ["none", "crash", "byzantine", "adaptive"])
@pytest.mark.parametrize("coin", ["local", "shared"])
def test_bitmatch_vs_oracle_grid(proto, adv, coin):
    n, f = _sizes(proto, adv)
    cfg = SimConfig(protocol=proto, n=n, f=f, instances=30, adversary=adv,
                    coin=coin, seed=11, round_cap=64).validate()
    a = get_backend("native").run(cfg)
    b = get_backend("cpu").run(cfg)
    np.testing.assert_array_equal(a.rounds, b.rounds)
    np.testing.assert_array_equal(a.decision, b.decision)


@pytest.mark.parametrize("init", ["random", "all0", "all1", "split"])
def test_bitmatch_init_modes(init):
    cfg = SimConfig(protocol="bracha", n=13, f=4, instances=25, adversary="byzantine",
                    coin="shared", init=init, seed=3, round_cap=64).validate()
    a = get_backend("native").run(cfg)
    b = get_backend("cpu").run(cfg)
    np.testing.assert_array_equal(a.rounds, b.rounds)
    np.testing.assert_array_equal(a.decision, b.decision)


def test_bitmatch_vs_numpy_larger():
    """At n=64 the object oracle is slow; the vectorized numpy backend (itself
    oracle-matched in test_bitmatch.py) is the cross-check."""
    cfg = SimConfig(protocol="bracha", n=64, f=21, instances=200, adversary="byzantine",
                    coin="shared", seed=5, round_cap=64).validate()
    a = get_backend("native").run(cfg)
    b = get_backend("numpy").run(cfg)
    np.testing.assert_array_equal(a.rounds, b.rounds)
    np.testing.assert_array_equal(a.decision, b.decision)


def test_thread_count_invariance():
    """Results are addressed by instance id, so the thread split cannot matter."""
    from byzantinerandomizedconsensus_tpu.backends.native_backend import NativeBackend

    cfg = SimConfig(protocol="bracha", n=16, f=5, instances=101, adversary="adaptive",
                    coin="shared", seed=9, round_cap=64).validate()
    one = NativeBackend(n_threads=1).run(cfg)
    four = NativeBackend(n_threads=4).run(cfg)
    np.testing.assert_array_equal(one.rounds, four.rounds)
    np.testing.assert_array_equal(one.decision, four.decision)


def test_subset_ids_and_overflow():
    cfg = SimConfig(protocol="benor", n=64, f=21, instances=1000, adversary="crash",
                    coin="local", seed=1, round_cap=2).validate()
    ids = np.array([3, 500, 999], dtype=np.int64)
    a = get_backend("native").run(cfg, ids)
    b = get_backend("numpy").run(cfg, ids)
    np.testing.assert_array_equal(a.rounds, b.rounds)
    np.testing.assert_array_equal(a.decision, b.decision)
    # round_cap=2 at f=Theta(n) with a local coin: overflow bucket, identically.
    assert (a.rounds == 2).all() and (a.decision == 2).all()
