"""Regression-chain ledger (tools/ledger.py): the committed-artifact audit.

The first test is the CI tripwire the round-8 issue asked for: it runs the
ledger over **every committed artifact in this checkout** and asserts zero
parse errors plus a correctly-reconstructed r1–r7 chain — so any future
artifact-format drift fails loudly instead of silently un-auditing a round.
"""

import json

from byzantinerandomizedconsensus_tpu.tools import ledger
from byzantinerandomizedconsensus_tpu.obs import record


def test_committed_artifacts_parse_and_chain_reconstructs():
    doc = ledger.build_ledger()
    assert doc["parse_errors"] == [], doc["parse_errors"]
    assert record.validate_record(doc) == []

    # The committed r1-r5 BENCH chain, values as captured by the driver.
    rounds = doc["bench_rounds"]
    for r in "12345":
        assert r in rounds, f"BENCH round {r} missing"
    assert rounds["5"]["value"] == 420110.7
    assert rounds["5"]["device_busy_s"] == 0.1602

    # Wall chain recomputed per utils/timing.regression_verdict and agreeing
    # with what the artifacts recorded at capture time.
    links = {(l["from_round"], l["to_round"]): l for l in doc["wall_chain"]}
    assert links[(4, 5)]["vs_prev_round"] == 1.538
    assert links[(4, 5)]["agrees_with_recorded"]
    assert all(l.get("agrees_with_recorded", True) for l in doc["wall_chain"])

    # The device chain: anchored at the newest round with a device leg.
    # As committed, that is r5 (0.1602 s) and rounds 6-7 are broken
    # (CPU-only sessions, docs/PERF.md rounds 6-7); a future TPU round that
    # moves the anchor past 7 legitimately closes them.
    dc = doc["device_chain"]
    assert dc["anchor_round"] is not None
    if dc["anchor_round"] == 5:
        assert dc["anchor_artifact"] == "BENCH_r05.json"
        assert dc["anchor_device_busy_s"] == 0.1602
        broken = {b["round"]: b for b in dc["broken_rounds"]}
        for r in (6, 7):
            assert r in broken, f"round {r} should be reported broken"
            assert broken[r]["cpu_only"], broken[r]
        # Forward-compatible on purpose: later CPU-only rounds may extend
        # the break (e.g. "rounds 6-8") — what must hold is that the status
        # reports a break and the closing action names the r5 anchor.
        assert dc["status"].startswith("broken at round")
        assert "0.1602" in dc["closes_with"]
    else:
        assert dc["anchor_round"] > 7  # chain re-anchored on a device round

    # Multichip rounds parsed with their ok flags.
    assert all(e["ok"] for e in doc["multichip_rounds"].values())


def test_ledger_report_renders(capsys):
    assert ledger.main([]) == 0
    out = capsys.readouterr().out
    assert "0 parse errors" in out
    assert "device-keyed chain" in out
    # Round 10: the committed batch A/B carries compile-cache stats, so the
    # report must print the schema-v1.1 columns.
    assert "compile-cache columns" in out
    assert "artifacts/batch_r10.json" in out


def test_census_includes_batch_artifact():
    """The round-10 batch A/B artifact: scanned, parsed, zero mismatches,
    the ≥3× chaos-grid wall reduction recorded, and the compile-cache
    columns reconstructed by the ledger."""
    import pathlib

    from byzantinerandomizedconsensus_tpu.utils.rounds import repo_root

    doc = ledger.build_ledger()
    assert doc["parse_errors"] == []
    rows = {r["artifact"]: r for r in doc["compile_cache_rows"]}
    assert "artifacts/batch_r10.json" in rows
    row = rows["artifacts/batch_r10.json"]
    assert isinstance(row["compiles"], int) and row["compiles"] >= 1
    assert isinstance(row["hits"], int)

    batch = json.loads(
        (pathlib.Path(repo_root()) / "artifacts/batch_r10.json").read_text())
    assert batch["kind"] == "bench_batch"
    assert record.validate_record(batch) == []
    assert batch["record_revision"] >= 1  # schema v1.1
    assert batch["legs"]["batched"]["mismatches"] == 0
    assert batch["legs"]["batched"]["violations"] == 0
    assert batch["legs"]["dense_bucket"]["bit_identical"] is True
    assert batch["summary"]["speedup_batched_vs_per_config"] >= 3.0


def test_ledger_synthetic_chain_and_parse_errors(tmp_path):
    """Anchor/broken-round logic and the parse census on a fabricated repo."""
    def bench(rnd, value, dev=None, platform="tpu", vs_prev=None):
        detail = {"walls_s": [1.0, 1.1], "platform": platform}
        if dev:
            detail["device_busy_s"] = dev
        parsed = {"value": value, "detail": detail}
        if vs_prev:
            parsed["vs_prev_round"] = vs_prev
        (tmp_path / f"BENCH_r0{rnd}.json").write_text(
            json.dumps({"n": rnd, "parsed": parsed}))

    bench(1, 100.0, dev=0.5)
    bench(2, 200.0, platform="cpu", vs_prev=2.0)
    art = tmp_path / "artifacts"
    art.mkdir()
    (art / "thing_r3.json").write_text(json.dumps(
        {"platform": "cpu", "legs": {"x": {"device_busy_error": "no pids"}}}))
    (art / "broken_r3.json").write_text("{not json")

    # A dead driver capture (parses, no value) must be *reported*, not die
    # mid-render — the ledger exists to name such rounds.
    (tmp_path / "BENCH_r04.json").write_text(json.dumps({"rc": 1}))

    doc = ledger.build_ledger(tmp_path)
    assert [e["artifact"] for e in doc["parse_errors"]] == \
        ["artifacts/broken_r3.json"]
    assert doc["bench_rounds"]["4"]["value"] is None
    assert "dead capture" in ledger.format_report(doc)
    dc = doc["device_chain"]
    assert dc["anchor_round"] == 1 and dc["anchor_device_busy_s"] == 0.5
    broken = {b["round"]: b for b in dc["broken_rounds"]}
    assert set(broken) == {2, 3, 4}
    assert broken[2]["cpu_only"] and broken[3]["cpu_only"]
    assert "no BENCH artifact" in broken[3]["reason"]
    assert "no device_busy_s" in broken[4]["reason"]
    link = doc["wall_chain"][0]
    assert link["vs_prev_round"] == 2.0 and link["agrees_with_recorded"]
    # Parse errors are the tool's failure signal.
    assert ledger.main(["--root", str(tmp_path)]) == 1


def test_ledger_json_out(tmp_path, capsys):
    out = tmp_path / "ledger.json"
    assert ledger.main(["--json", str(out)]) == 0
    doc = json.loads(out.read_text())
    assert doc["kind"] == "ledger" and doc["parse_errors"] == []
    capsys.readouterr()


def test_ledger_json_stdout_mode(capsys):
    """Round-13 satellite: bare --json prints the machine-readable record
    (sentinel verdict included) INSTEAD of the human table."""
    assert ledger.main(["--json"]) == 0
    out = capsys.readouterr().out
    doc = json.loads(out)  # stdout IS the record — no table around it
    assert doc["kind"] == "ledger"
    assert isinstance(doc["sentinel"]["ok"], bool)
    assert "flight-recorder ledger" not in out


def test_sentinel_passes_on_committed_artifact_set(capsys):
    """`brc-tpu ledger --check` — the regression sentinel — must be green
    on the repo as committed, with the r5->r11 wall link SKIPPED by the
    mechanical device-chain rule (a CPU wall is not comparable to the r5
    TPU anchor), not judged."""
    assert ledger.main(["--check"]) == 0
    capsys.readouterr()
    doc = ledger.build_ledger()
    sent = doc["sentinel"]
    assert sent["ok"] and sent["failures"] == []
    assert sent["threshold"] == 0.15  # timing.REGRESSION_THRESHOLD
    assert any("r5->r11" in s and "not comparable across platforms" in s
               for s in sent["links_skipped"])
    # The consecutive TPU links were actually checked, not skipped.
    checked = {c["link"] for c in sent["links_checked"]}
    assert {"r1->r2", "r2->r3", "r3->r4", "r4->r5"} <= checked


def _fake_repo(tmp_path, benches=(), artifacts=()):
    tmp_path.mkdir(parents=True, exist_ok=True)
    for rnd, parsed in benches:
        (tmp_path / f"BENCH_r0{rnd}.json").write_text(
            json.dumps({"parsed": parsed}))
    art = tmp_path / "artifacts"
    art.mkdir(exist_ok=True)
    for name, doc in artifacts:
        (art / name).write_text(json.dumps(doc))
    return tmp_path


def _bench_parsed(value, platform="tpu", vs_prev=None, walls=(1.0, 1.01)):
    parsed = {"value": value,
              "detail": {"walls_s": list(walls), "platform": platform}}
    if vs_prev is not None:
        parsed["vs_prev_round"] = vs_prev
    return parsed


def test_sentinel_flags_injected_wall_regression(tmp_path, capsys):
    """An injected same-platform wall regression past
    timing.REGRESSION_THRESHOLD exits nonzero under --check — and a
    cross-platform drop of any size is skipped, not flagged (the r5 rule)."""
    root = _fake_repo(tmp_path, benches=[
        (1, _bench_parsed(100.0)),
        (2, _bench_parsed(50.0, vs_prev=0.5)),       # real regression
        (3, _bench_parsed(1.0, platform="cpu")),      # cross-platform: skip
    ])
    assert ledger.main(["--root", str(root)]) == 0   # census still parses
    assert ledger.main(["--check", "--root", str(root)]) == 2
    out = capsys.readouterr().out
    assert "SENTINEL FAIL" in out
    doc = ledger.build_ledger(root)
    sent = doc["sentinel"]
    assert not sent["ok"]
    assert any("r1->r2" in f and "wall regression past "
               "timing.REGRESSION_THRESHOLD" in f for f in sent["failures"])
    assert any("r2->r3" in s and "not comparable" in s
               for s in sent["links_skipped"])
    # The 0.5 recomputed ratio AGREES with the recorded one, so only the
    # threshold failure fires, not a recorded-drift failure too.
    assert len(sent["failures"]) == 1

    # Recorded-vs-recomputed drift is its own failure: same chain, but the
    # artifact claims a ratio the walls don't support.
    root2 = _fake_repo(tmp_path / "drift", benches=[
        (1, _bench_parsed(100.0)),
        (2, _bench_parsed(95.0, vs_prev=1.9)),
    ])
    sent2 = ledger.build_ledger(root2)["sentinel"]
    assert any("disagrees with recorded" in f for f in sent2["failures"])
    capsys.readouterr()


def _programs_doc(key, hash_, platform="cpu"):
    return {"record_version": 1, "record_revision": 4, "kind": "x",
            "env": {"package": "0", "python": "3", "numpy": "1",
                    "platform": platform},
            "programs": {"count": 1, "programs": [
                {"key": key, "fingerprint": {"hash": hash_, "ops": {},
                                             "instructions": 1}}]}}


def test_sentinel_flags_injected_fingerprint_drift(tmp_path, capsys):
    """The same program key hashing differently on the same platform across
    committed artifacts exits nonzero under --check; the same key differing
    across PLATFORMS is expected (a TPU census is a fresh fingerprint
    family) and passes."""
    root = _fake_repo(tmp_path, artifacts=[
        ("a_r1.json", _programs_doc("fused/bracha/n40/urn2/p1", "aaaa")),
        ("b_r2.json", _programs_doc("fused/bracha/n40/urn2/p1", "bbbb")),
    ])
    assert ledger.main(["--root", str(root)]) == 0  # drift is not a parse error
    assert ledger.main(["--check", "--root", str(root)]) == 2
    assert "fingerprint drift" in capsys.readouterr().out
    sent = ledger.build_ledger(root)["sentinel"]
    assert any("fingerprint drift" in f and "aaaa" in f and "bbbb" in f
               for f in sent["failures"])

    # Same key, different platform: no drift.
    root2 = _fake_repo(tmp_path / "xplat", artifacts=[
        ("a_r1.json", _programs_doc("fused/bracha/n40/urn2/p1", "aaaa",
                                    platform="cpu")),
        ("b_r2.json", _programs_doc("fused/bracha/n40/urn2/p1", "cccc",
                                    platform="tpu")),
    ])
    assert ledger.main(["--check", "--root", str(root2)]) == 0
    capsys.readouterr()


def test_census_includes_programs_artifact():
    """The round-13 compiled-program census artifact: scanned, parsed with
    zero errors, bit-identity + overhead acceptance on the record, the
    schema-v1.4 program rows reconstructed by the ledger, and its
    fingerprints feeding the sentinel without drift."""
    import pathlib

    from byzantinerandomizedconsensus_tpu.utils.rounds import repo_root

    doc = ledger.build_ledger()
    assert doc["parse_errors"] == []
    rows = [r for r in doc["programs_rows"]
            if r["artifact"] == "artifacts/programs_r13.json"]
    assert rows, "programs_r13.json must yield census columns"
    for r in rows:
        assert r["key"] and r["hash"]
        assert isinstance(r["flops"], (int, float)) and r["flops"] > 0
        assert r["platform"] == "cpu"
    # The fused chaos-grid program family is present (the <= 8-program claim
    # is per (protocol, delivery, tier) — at least one fused key).
    assert any(r["key"].startswith("fused/") for r in rows)
    assert any(r["key"].startswith("compact-") for r in rows)
    assert doc["sentinel"]["ok"], doc["sentinel"]["failures"]

    pg = json.loads(
        (pathlib.Path(repo_root())
         / "artifacts/programs_r13.json").read_text())
    assert pg["kind"] == "programs_census"
    assert record.validate_record(pg) == []
    assert pg["record_revision"] >= 4  # schema v1.4
    assert pg["bit_identical"] is True
    assert pg["overhead_fraction"] is not None
    assert pg["overhead_fraction"] <= pg["overhead_bound"] == 0.02
    assert pg["programs"]["count"] >= 3
    assert pg["trace"]["file"] == "programs_r13.jsonl"
    assert "device_chain_note" in pg  # CPU-only capture, rule on record

    # The committed trace next to it is well-formed and program-attributed
    # (the roofline join surface).
    from byzantinerandomizedconsensus_tpu.obs import trace as trace_mod
    from byzantinerandomizedconsensus_tpu.tools import (
        programs as programs_tool)

    jsonl = pathlib.Path(repo_root()) / "artifacts/programs_r13.jsonl"
    assert trace_mod.validate_file(jsonl) == []
    entries = programs_tool._programs_of(
        pathlib.Path(repo_root()) / "artifacts/programs_r13.json")
    rows = programs_tool.roofline_rows(entries,
                                       trace_mod.read_events(jsonl))
    assert rows and any(r["in_census"] and r.get("gflops_per_s")
                        for r in rows)

    # And the report renders the v1.4 columns + the sentinel line.
    report = ledger.format_report(ledger.build_ledger())
    assert "compiled-program census columns" in report
    assert "sentinel: OK" in report


def test_census_includes_compaction_artifact():
    """The round-11 lane-compaction A/B artifact: scanned, parsed with zero
    errors, bit-identity recorded on every compacted leg, and the
    schema-v1.2 occupancy columns reconstructed by the ledger (artifact +
    path + occupancy/wasted/segments/refills)."""
    import pathlib

    from byzantinerandomizedconsensus_tpu.utils.rounds import repo_root

    doc = ledger.build_ledger()
    assert doc["parse_errors"] == []
    rows = [r for r in doc["compaction_rows"]
            if r["artifact"] == "artifacts/compaction_r11.json"]
    assert rows, "compaction_r11.json must yield occupancy columns"
    for r in rows:
        assert r["occupancy"] is not None and 0 < r["occupancy"] <= 1
        assert r["wasted_lane_fraction"] is not None
        assert isinstance(r["segments"], int) and r["segments"] >= 1
        assert isinstance(r["refills"], int)

    comp = json.loads(
        (pathlib.Path(repo_root())
         / "artifacts/compaction_r11.json").read_text())
    assert comp["kind"] == "bench_compaction"
    assert record.validate_record(comp) == []
    assert comp["record_revision"] >= 2  # schema v1.2
    assert comp["summary"]["bit_identical_all"] is True
    assert "device_chain_note" in comp  # CPU-only capture, rule on record
    # The headline urn2 leg carries the before/after straggler numbers.
    leg = comp["legs"]["urn2"]
    assert leg["per_chunk"]["wasted_lane_fraction"] is not None
    assert leg["best"]["occupancy"] is not None
    # §4b urn — the cost model the straggler accounting describes 1:1 —
    # must show the real win (the round-11 acceptance floor).
    assert comp["legs"]["urn"]["best"]["wall_speedup_vs_per_chunk"] >= 1.2

    # And the report renders the v1.2 columns.
    assert "compaction occupancy columns" in ledger.format_report(doc)


def test_census_includes_chaos_artifact():
    """The round-9 chaos artifact is part of the committed census: it must
    be scanned, parse cleanly, and carry zero mismatches/violations."""
    doc = ledger.build_ledger()
    assert doc["parse_errors"] == []
    ev = doc["artifact_round_evidence"]
    assert "9" in ev and "artifacts/chaos_r9.json" in ev["9"]["artifacts"]

    import pathlib

    from byzantinerandomizedconsensus_tpu.utils.rounds import repo_root

    chaos = json.loads(
        (pathlib.Path(repo_root()) / "artifacts/chaos_r9.json").read_text())
    assert chaos["kind"] == "soak" and chaos["chaos"] is True
    assert chaos["mismatches"] == []
    assert chaos["violations"] == []
    assert chaos["configs"] >= 200
    assert record.validate_record(chaos) == []


def test_census_includes_trace_artifact():
    """The round-12 telemetry artifact: scanned, parsed with zero errors,
    the inertness acceptance (bit-identical + overhead within the bound)
    on the record, and the schema-v1.3 trace-digest + compile-wall columns
    reconstructed by the ledger."""
    import pathlib

    from byzantinerandomizedconsensus_tpu.utils.rounds import repo_root

    doc = ledger.build_ledger()
    assert doc["parse_errors"] == []
    rows = {r["artifact"]: r for r in doc["trace_rows"]}
    assert "artifacts/trace_r12.json" in rows, \
        "trace_r12.json must yield trace-digest columns"
    row = rows["artifacts/trace_r12.json"]
    assert isinstance(row["events"], int) and row["events"] >= 1
    assert row["span_kinds"] >= 3  # dispatch + bucket + compaction kinds
    assert row["total_s"] > 0

    # The compile-cache columns now carry the v1.3 compile wall for it.
    cc_rows = {r["artifact"]: r for r in doc["compile_cache_rows"]}
    assert "artifacts/trace_r12.json" in cc_rows
    assert cc_rows["artifacts/trace_r12.json"]["compile_wall_s"] > 0

    tr = json.loads(
        (pathlib.Path(repo_root()) / "artifacts/trace_r12.json").read_text())
    assert tr["kind"] == "trace_bench"
    assert record.validate_record(tr) == []
    assert tr["record_revision"] >= 3  # schema v1.3
    assert tr["bit_identical"] is True
    assert tr["overhead_fraction"] is not None
    assert tr["overhead_fraction"] <= tr["overhead_bound"] == 0.02
    assert tr["trace"]["file"] == "trace_r12.jsonl"
    assert tr["trace"]["digest"]  # non-empty span digest on the record
    assert "device_chain_note" in tr  # CPU-only capture, rule on record

    # The committed trace file itself stays well-formed next to the record.
    from byzantinerandomizedconsensus_tpu.obs import trace as trace_mod

    jsonl = pathlib.Path(repo_root()) / "artifacts/trace_r12.jsonl"
    assert trace_mod.validate_file(jsonl) == []

    # And the report renders the v1.3 columns.
    report = ledger.format_report(doc)
    assert "trace-digest columns" in report
    assert "compile wall" in report


def test_census_includes_serve_artifact():
    """The round-14 serving artifact: scanned, parsed with zero errors, the
    zero-steady-state-recompile pin and the full differential on the
    record, and the schema-v1.5 serve latency/throughput columns
    reconstructed by the ledger."""
    import pathlib

    from byzantinerandomizedconsensus_tpu.utils.rounds import repo_root

    doc = ledger.build_ledger()
    assert doc["parse_errors"] == []
    rows = {r["artifact"]: r for r in doc["serve_rows"]}
    assert "artifacts/serve_r14.json" in rows, \
        "serve_r14.json must yield serve latency/throughput columns"
    row = rows["artifacts/serve_r14.json"]
    assert isinstance(row["requests"], int) and row["requests"] >= 200
    assert row["p50_ms"] is not None and row["p50_ms"] > 0
    assert row["p99_ms"] is not None and row["p99_ms"] >= row["p50_ms"]
    assert row["throughput_cps"] > 0
    assert row["steady_state_compiles"] == 0  # the round-14 claim

    sv = json.loads(
        (pathlib.Path(repo_root()) / "artifacts/serve_r14.json").read_text())
    assert sv["kind"] == "serve"
    assert record.validate_record(sv) == []
    assert sv["record_revision"] >= 5  # schema v1.5
    assert sv["differential"]["mismatches"] == 0
    assert sv["differential"]["configs"] == sv["requests"]
    assert sv["serve"]["steady_state_compiles"] == 0
    assert sv["serve"]["warmup_compiles"] > 0  # warm-up did compile
    assert sv["stream_digest"]  # the determinism pin rides the record

    # The committed trace file stays well-formed next to the record.
    from byzantinerandomizedconsensus_tpu.obs import trace as trace_mod

    jsonl = pathlib.Path(repo_root()) / "artifacts/serve_r14.jsonl"
    assert trace_mod.validate_file(jsonl) == []

    # And the report renders the v1.5 columns.
    report = ledger.format_report(doc)
    assert "serve latency/throughput columns" in report
    assert "steady-state compiles" in report


def test_census_includes_fleet_artifact():
    """The round-15 fleet artifact: parsed with zero errors, the per-worker
    zero-steady-state-recompile pin on every row, the steal counter, and
    the schema-v1.6 per-worker columns reconstructed by the ledger."""
    import pathlib

    from byzantinerandomizedconsensus_tpu.utils.rounds import repo_root

    doc = ledger.build_ledger()
    assert doc["parse_errors"] == []
    rows = [r for r in doc["fleet_rows"]
            if r["artifact"] == "artifacts/serve_fleet_r15.json"]
    assert rows, "serve_fleet_r15.json must yield per-worker fleet columns"
    for row in rows:
        assert isinstance(row["worker"], int)
        assert row["steady_state_compiles"] == 0  # the round-15 claim,
        # enforced per worker (a fleet-wide sum could hide one hot worker)
        assert row["replied"] is None or row["replied"] >= 0
    # the headline sweep leg carries the largest worker count
    assert max(r["workers"] for r in rows) >= 4
    assert any(r["fleet_steals"] and r["fleet_steals"] > 0 for r in rows), \
        "the committed fat-tail run must have stolen at least once"

    fv = json.loads((pathlib.Path(repo_root())
                     / "artifacts/serve_fleet_r15.json").read_text())
    assert fv["kind"] == "serve_fleet"
    assert record.validate_record(fv) == []
    assert fv["record_revision"] >= 6  # schema v1.6
    assert fv["differential"]["mismatches"] == 0
    assert fv["fleet"]["steady_state_compiles"] == 0
    assert fv["stream_digest"]
    assert "device_chain_note" in fv  # CPU-box honesty label

    report = ledger.format_report(doc)
    assert "fleet per-worker columns" in report


def test_census_includes_hunt_artifact():
    """The round-17 hunt artifact: parsed with zero errors, the
    zero-violation / zero-steady-state-recompile pins and the pipelined
    speedup on the record, and the schema-v1.8 hunt worst-case columns
    reconstructed by the ledger."""
    import pathlib

    from byzantinerandomizedconsensus_tpu.utils.rounds import repo_root

    doc = ledger.build_ledger()
    assert doc["parse_errors"] == []
    rows = {r["artifact"]: r for r in doc["hunt_rows"]}
    assert "artifacts/hunt_r17.json" in rows, \
        "hunt_r17.json must yield hunt worst-case columns"
    row = rows["artifacts/hunt_r17.json"]
    assert row["strategy"] == "evolution" and row["seed"] == 17
    assert isinstance(row["evaluations"], int) and row["evaluations"] >= 500
    assert row["best_fitness"] > 0
    assert row["archive_size"] >= 1
    assert row["violations"] == 0            # the round-17 safety claim
    assert row["steady_state_compiles"] == 0  # under adversarial search
    assert row["pipeline_speedup"] > 1        # ask-ahead beats the barrier

    hv = json.loads(
        (pathlib.Path(repo_root()) / "artifacts/hunt_r17.json").read_text())
    assert hv["kind"] == "hunt"
    assert record.validate_record(hv) == []
    assert hv["record_revision"] >= 8  # schema v1.8
    assert hv["hunt"]["rediscovery"]["above_baseline"] is True
    assert all(r["ok"] for r in hv["replay_check"])

    # the pinned regression archive rides the same schema head
    rg = json.loads((pathlib.Path(repo_root())
                     / "artifacts/hunt_regressions.json").read_text())
    assert rg["kind"] == "hunt_regressions"
    assert record.validate_record(rg) == []
    assert len(rg["entries"]) == rg["k"] == 8

    report = ledger.format_report(doc)
    assert "hunt worst-case columns" in report
    assert "steady-state compiles" in report


def test_census_includes_hostile_artifact():
    """The round-18 hostile-traffic artifact: parsed with zero errors, all
    five scenarios on the record with the zero-mismatch /
    zero-steady-state-recompile pins, backpressure demonstrated (overflow
    rejections > 0), the fairness verdict OK, and the schema-v1.9 hostile
    columns reconstructed by the ledger."""
    import pathlib

    from byzantinerandomizedconsensus_tpu.utils.rounds import repo_root

    doc = ledger.build_ledger()
    assert doc["parse_errors"] == []
    rows = {r["artifact"]: r for r in doc["hostile_rows"]}
    assert "artifacts/hostile_r18.json" in rows, \
        "hostile_r18.json must yield hostile-traffic columns"
    row = rows["artifacts/hostile_r18.json"]
    assert row["suite_seed"] == 18
    assert row["scenarios"] == 5             # the full hostile suite
    assert row["rejected_overflow"] >= 1     # backpressure really fired
    assert row["fairness_ok"] is True        # hog could not starve others
    assert row["deadline_hit_rate"] is None or row["deadline_hit_rate"] > 0
    assert row["mismatches"] == 0            # survivors bit-identical
    assert row["steady_state_compiles"] == 0  # under hostile load

    hv = json.loads((pathlib.Path(repo_root())
                     / "artifacts/hostile_r18.json").read_text())
    assert hv["kind"] == "hostile"
    assert record.validate_record(hv) == []
    assert hv["record_revision"] >= 9  # schema v1.9
    scen = {r["scenario"]: r for r in hv["hostile"]["scenarios"]}
    assert set(scen) == {"flash_crowd", "heavy_tail", "bucket_churn",
                         "tenant_hog", "cancel_storm"}
    assert all(r["slo_ok"] for r in scen.values())
    assert scen["cancel_storm"]["cancelled"] >= 1

    report = ledger.format_report(doc)
    assert "hostile-traffic columns" in report
    assert "overflow rejections" in report


def test_census_includes_committee_artifact():
    """The round-19 committee cost-curve artifact: parsed with zero errors,
    the flat-vs-linear headline on the record (committee per-replica ratio
    near 1 over a 64x n span while urn2 grows), the n=10^5 invariant-checker
    verdict green, the serve leg at 0 steady-state compiles with the offline
    differential bit-identical, and the schema-v1.10 committee columns
    reconstructed by the ledger."""
    import pathlib

    from byzantinerandomizedconsensus_tpu.utils.rounds import repo_root

    doc = ledger.build_ledger()
    assert doc["parse_errors"] == []
    rows = {r["artifact"]: r for r in doc["committee_rows"]}
    assert "artifacts/committee_r19.json" in rows, \
        "committee_r19.json must yield committee cost-curve columns"
    row = rows["artifacts/committee_r19.json"]
    assert row["n_max"] >= 100_000           # past the 4096 full-mesh ceiling
    assert row["n_span_committee"] >= 32     # a wide span, not two points
    assert row["flat_committee"] < 1.3       # per-replica cost flat-ish in n
    assert row["flat_urn2"] > 1.5            # the full-mesh family is linear
    assert row["checker_n"] >= 100_000 and row["checker_ok"] is True
    assert row["serve_steady_state_compiles"] == 0
    assert row["serve_offline_bitmatch"] is True

    cv = json.loads((pathlib.Path(repo_root())
                     / "artifacts/committee_r19.json").read_text())
    assert cv["kind"] == "committee_cost_curve"
    assert record.validate_record(cv) == []
    assert cv["record_revision"] >= 10  # schema v1.10
    cb = cv["committee"]
    # C(n) on the record matches the spec-§10.1 law at every measured n.
    from byzantinerandomizedconsensus_tpu.ops.committee import committee_size
    assert {int(k): v for k, v in cb["committee_sizes"].items()} == {
        n: committee_size(n) for n in cb["ns"]}

    report = ledger.format_report(doc)
    assert "committee cost-curve columns" in report
    assert "offline bitmatch True" in report


def test_census_includes_fused_artifact():
    """The round-20 fused-kernel artifact: parsed with zero errors, every
    ABI v6 A/B config bit-identical at zero steady-state compiles, the
    resident-state pack law on the record, and the schema-v1.11 fused
    columns reconstructed by the ledger — including the device-of-record
    debt row ("interpret/cpu" until the bit-match re-runs on a TPU)."""
    import json
    import pathlib

    from byzantinerandomizedconsensus_tpu.ops import prf
    from byzantinerandomizedconsensus_tpu.utils.rounds import repo_root

    doc = ledger.build_ledger()
    assert doc["parse_errors"] == []
    rows = {r["artifact"]: r for r in doc["fused_rows"]}
    assert "artifacts/fused_r20.json" in rows, \
        "fused_r20.json must yield fused-kernel columns"
    row = rows["artifacts/fused_r20.json"]
    assert row["configs"] == 5               # every closed gate + control
    assert row["mismatches"] == 0            # the round's bit-match claim
    assert row["ab_rows"] == 5
    assert row["steady_state_compiles"] == 0
    assert row["device_of_record"] == "interpret/cpu"
    assert row["device_debt"] is True        # the ledger names the debt

    fv = json.loads((pathlib.Path(repo_root())
                     / "artifacts/fused_r20.json").read_text())
    assert fv["kind"] == "fused_roofline"
    assert record.validate_record(fv) == []
    assert fv["record_revision"] >= 11  # schema v1.11
    fb = fv["fused"]
    # The committed pack law matches this build's (any relayout must bump
    # FUSED_STATE_PACK_VERSION and re-capture the artifact).
    assert fb["state_pack"] == {
        "version": prf.FUSED_STATE_PACK_VERSION,
        "bits": {k: list(v) for k, v in prf.FUSED_STATE_BITS.items()}}
    assert all(r["bit_identical"] for r in fb["rows"])
    # Every A/B row joins the r13-style census: a kfused key vs an xla
    # baseline key, both with bytes/dispatch from the cost analysis.
    for r in fb["rows"]:
        assert r["key"].endswith("/kfused")
        assert r["baseline_key"] and "kfused" not in r["baseline_key"]
        assert r["fused_bytes_per_dispatch"] > 0
        assert r["xla_bytes_per_dispatch"] > 0

    report = ledger.format_report(doc)
    assert "fused-kernel columns" in report
    assert "DEBT: bit-match not yet re-run on TPU" in report

def test_census_includes_session_artifact():
    """The round-21 replicated-log session artifact: the spec-§11 chain
    measured end to end — an L-slot session beating L independent requests
    past the 1.5x amortization floor at zero steady-state compiles, zero
    differential mismatches, and a bit-identical offline replay of every
    measured session — with the schema-v1.12 session columns reconstructed
    by the ledger."""
    import pathlib

    from byzantinerandomizedconsensus_tpu.utils.rounds import repo_root

    doc = ledger.build_ledger()
    assert doc["parse_errors"] == []
    rows = {r["artifact"]: r for r in doc["session_rows"]}
    assert "artifacts/session_r21.json" in rows, \
        "session_r21.json must yield session-amortization columns"
    row = rows["artifacts/session_r21.json"]
    assert row["sessions"] >= 4 and row["slots"] >= 8
    assert row["decisions"] == row["sessions"] * row["slots"] * 4  # inst=4
    assert row["amortization_ratio"] >= 1.5   # the acceptance floor
    assert row["session_cps"] > row["independent_cps"] > 0
    assert row["steady_state_compiles"] == 0  # one program, L slots
    assert row["mismatches"] == 0             # slot-for-slot cross-leg pin
    assert row["replay_ok"] is True           # numpy replay from base seed

    sv = json.loads((pathlib.Path(repo_root())
                     / "artifacts/session_r21.json").read_text())
    assert sv["kind"] == "session"
    assert record.validate_record(sv) == []
    assert sv["record_revision"] >= 12  # schema v1.12
    sb = sv["session"]
    assert sb["generator_version"] == 3
    assert sb["session_reseeds"] >= sb["sessions"] * (sb["slots"] - 2)
    assert sb["population"]["bucket"].startswith("fused/")

    report = ledger.format_report(doc)
    assert "session-amortization columns" in report
    assert "replay OK" in report


def test_census_includes_elastic_artifact():
    """The round-22 durability/autoscaling artifact: a SIGKILLed
    dispatcher recovered bit-identically from the write-ahead admission
    log, and the autoscale flash crowd meeting the p99 bound the pinned
    static fleet misses — with the schema-v1.13 elastic columns
    reconstructed by the ledger, and the census floor raised past it."""
    import pathlib

    from byzantinerandomizedconsensus_tpu.utils.rounds import repo_root

    doc = ledger.build_ledger()
    assert doc["parse_errors"] == []
    assert doc["files_scanned"] >= 14
    rows = {r["artifact"]: r for r in doc["elastic_rows"]}
    assert "artifacts/elastic_r22.json" in rows, \
        "elastic_r22.json must yield durability/autoscaling columns"
    row = rows["artifacts/elastic_r22.json"]
    assert row["recovered"] >= 1              # the kill drill owed work
    assert row["scale_up_events"] >= 1 and row["scale_down_events"] >= 1
    assert row["mismatches"] == 0             # recovery is bit-identical
    assert row["steady_state_compiles"] == 0  # warm across scale events
    assert row["slo_ok"] is True
    assert row["drills"] == {"dispatcher_kill": True,
                             "autoscale_crowd": True}
    assert row["elastic_p99_ms"] <= row["slo_ms"] < row["static_p99_ms"]

    ev = json.loads((pathlib.Path(repo_root())
                     / "artifacts/elastic_r22.json").read_text())
    assert ev["kind"] == "elastic"
    assert record.validate_record(ev) == []
    assert ev["record_revision"] >= 13  # schema v1.13
    eb = ev["elastic"]
    assert eb["suite_seed"] == 22
    assert {s["scenario"] for s in eb["scenarios"]} == \
        {"dispatcher_kill", "autoscale_crowd"}

    report = ledger.format_report(doc)
    assert "durability/autoscaling columns" in report
    assert "dispatcher_kill OK" in report and "autoscale_crowd OK" in report
    # evidence columns, not a new debt class: the elastic block adds no
    # standing debt (the full set is pinned exactly in the test below)
    assert {d["debt"] for d in ledger.debts_of(doc)} == \
        {"device-chain", "fused-bitmatch", "committee-curve"}


def test_census_includes_preempt_artifact():
    """The round-23 serialized-lane artifact: restore proven bit-identical
    across the fault×adversary×delivery grid, the preempt_storm drill
    beating the FIFO deadline baseline, and the lane-migration fleet sweep
    — with the schema-v1.14 lanestate/preempt columns reconstructed by the
    ledger and the census floor raised past it."""
    import pathlib

    from byzantinerandomizedconsensus_tpu.utils.rounds import repo_root

    doc = ledger.build_ledger()
    assert doc["parse_errors"] == []
    assert doc["files_scanned"] >= 15

    ls = {r["artifact"]: r for r in doc["lanestate_rows"]}
    assert "artifacts/preempt_r23.json" in ls, \
        "preempt_r23.json must yield serialized-lane columns"
    row = ls["artifacts/preempt_r23.json"]
    assert row["version"] >= 1
    assert row["grid_points"] >= 12           # full fault x adversary grid
    assert row["restore_mismatches"] == 0     # restore is bit-identical
    assert row["crash_window_ok"] is True     # mid-crash-window included
    assert row["roundtrip_ok"] is True
    assert row["lanes_round_tripped"] >= 1

    pr = {r["artifact"]: r for r in doc["preempt_rows"]}
    assert "artifacts/preempt_r23.json" in pr
    prow = pr["artifacts/preempt_r23.json"]
    assert prow["parks"] >= 1 and prow["resumes"] >= 1
    assert prow["lanes_exported"] >= 1 and prow["lanes_imported"] >= 1
    assert prow["deadline_hit_rate"] > prow["fifo_hit_rate"]
    assert prow["mismatches"] == 0
    assert prow["steady_state_compiles"] == 0

    # the lane-migration sweep artifact joins the fleet columns with the
    # round-23 migration counters
    fleet = [r for r in doc["fleet_rows"]
             if r["artifact"] == "artifacts/serve_fleet_migrate_r23.json"]
    assert fleet, "serve_fleet_migrate_r23.json must yield fleet columns"
    assert any((r.get("fleet_migrations") or 0) >= 1 for r in fleet)
    assert all(r["steady_state_compiles"] == 0 for r in fleet)

    pv = json.loads((pathlib.Path(repo_root())
                     / "artifacts/preempt_r23.json").read_text())
    assert pv["kind"] == "preempt"
    assert record.validate_record(pv) == []
    assert pv["record_revision"] >= 14  # schema v1.14

    report = ledger.format_report(doc)
    assert "serialized-lane columns" in report
    assert "preemption columns" in report


def test_debts_verb_prints_standing_rows(capsys):
    """``brc-tpu ledger --debts``: the one-glance "what still owes a TPU
    run" table. As committed, all three standing families appear — the r5
    device-chain anchor (every later round CPU-only), the r20 fused
    bit-match at device_of_record interpret/cpu, and the r19 committee
    flatness curve measured off-device — and the verb exits 0."""
    doc = ledger.build_ledger()
    debts = ledger.debts_of(doc)
    assert {d["debt"] for d in debts} == \
        {"device-chain", "fused-bitmatch", "committee-curve"}
    for d in debts:
        assert d["where"] and d["evidence"] and d["closes_with"]

    table = ledger.format_debts(doc)
    lines = table.splitlines()
    assert lines[0] == f"standing debts — {len(debts)} row(s)"
    assert lines[1].split() == ["DEBT", "WHERE", "EVIDENCE", "CLOSES", "WITH"]
    assert any(line.startswith("device-chain") for line in lines[2:])
    assert any(line.startswith("fused-bitmatch") for line in lines[2:])
    committee = [line for line in lines[2:]
                 if line.startswith("committee-curve")]
    assert committee and "x1.031" in committee[0]  # the r19 headline, named

    assert ledger.main(["--debts"]) == 0
    out = capsys.readouterr().out
    assert "device-chain" in out and "fused-bitmatch" in out \
        and "committee-curve" in out

    # a debt-free ledger renders the explicit all-clear, not an empty table
    clean = {"device_chain": {"broken_rounds": []}, "fused_rows": [],
             "committee_rows": []}
    assert ledger.format_debts(clean) == "standing debts: none"
    assert ledger.debts_of(clean) == []
