"""Test env: force JAX onto 8 virtual CPU devices (SURVEY.md §4.3) before jax imports.

Real-TPU runs (bench.py, CLI) are unaffected — this applies to the test process only.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
