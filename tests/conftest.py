"""Test env: force JAX onto 8 virtual CPU devices (SURVEY.md §4.3) before jax imports.

Two layers of defense, because the environment may carry an `axon` TPU-tunnel PJRT
plugin that a sitecustomize registers in every interpreter and that pins
``jax.config.jax_platforms = "axon,cpu"`` (overriding the JAX_PLATFORMS env var).
Initializing that backend dials a tunnel and can block for minutes when the tunnel
is down — tests must never touch it:

1. env vars (JAX_PLATFORMS / XLA_FLAGS) — effective in clean environments;
2. drop the ``axon`` backend factory from jax's registry and reset the
   ``jax_platforms`` config to ``cpu`` — effective when the plugin already
   registered itself at interpreter start. Safe no-op when no plugin exists.

Real-TPU runs (bench.py, CLI) are unaffected — this applies to the test process only.
"""

import os
import pathlib

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# Persistent XLA compilation cache: the suite is compile-bound (~100 distinct
# jit programs x 2-6 s of XLA CPU compile each — measured, VERDICT r2 #5), and
# the programs are identical run-to-run, so the second and every later suite
# run skips almost all of it (test_sharded.py alone: 39 s cold -> 16 s warm).
# Repo-local and gitignored; JAX_COMPILATION_CACHE_DIR overrides, empty
# disables. min_compile_time=0 + min_entry_size=-1: cache even the tiny eager
# op executables that interpret-mode Pallas tests churn through.
if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
    os.environ["JAX_COMPILATION_CACHE_DIR"] = str(
        pathlib.Path(__file__).resolve().parent.parent / ".jax_cache")
if os.environ["JAX_COMPILATION_CACHE_DIR"]:
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")


def _force_cpu_backend() -> None:
    # The one shared implementation of the drop-plugin private-API dance
    # (swallows private-API drift internally, leaving the env vars above as
    # the fallback layer rather than killing collection for the whole suite).
    from byzantinerandomizedconsensus_tpu.utils.devices import _drop_accelerator_plugins

    _drop_accelerator_plugins()


_force_cpu_backend()


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavyweight at-scale checks (still run by default; deselect "
        "with -m 'not slow' for a quick iteration loop)")
    config._brc_session_start = None


# ---------------------------------------------------------------------------
# Shared interpret-mode switch for every Pallas test (round 20). The suites
# previously each hard-coded `interpret=True`; the one shared fixture keeps
# them honest about WHY (no TPU in the test process — see the CPU pinning at
# the top of this file) and flips to compiled Mosaic automatically if a test
# box ever does run with a real TPU backend.

import pytest


@pytest.fixture(scope="session")
def pallas_interpret() -> bool:
    """True when Pallas kernels must run in interpret mode (non-TPU backend)."""
    import jax

    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# Tier-1 wall-clock budget guard (round 19). The CI driver runs the tier-1
# selection (-m 'not slow') under `timeout -k 10 870`; the suite must keep
# >= 15% headroom under that ceiling so one slow box or one new test does
# not start killing CI at the timeout. The guard reports the budget line on
# every run and fails the session only when BRC_TIER1_BUDGET_ENFORCE=1
# (wall time is machine-dependent; enforcement is for the box that owns the
# 870 s number, reporting is for everyone).

TIER1_BUDGET_S = 740.0   # 870 s ceiling minus 15% headroom


def _tier1_selected(config) -> bool:
    # Only the tier-1 selection carries the budget: a full run (slow marks
    # included) or a hand-picked subset has no 870 s contract.
    return "not slow" in (config.getoption("markexpr", "") or "")


def pytest_sessionstart(session):
    import time

    session.config._brc_session_start = time.monotonic()


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    import time

    start = getattr(config, "_brc_session_start", None)
    if start is None or not _tier1_selected(config):
        return
    wall = time.monotonic() - start
    headroom = 1.0 - wall / (TIER1_BUDGET_S / 0.85)
    terminalreporter.write_line(
        f"tier-1 budget: {wall:.0f} s of {TIER1_BUDGET_S:.0f} s "
        f"({headroom:.0%} headroom under the 870 s ceiling)")
    if wall > TIER1_BUDGET_S:
        terminalreporter.write_line(
            ("ERROR" if os.environ.get("BRC_TIER1_BUDGET_ENFORCE") == "1"
             else "WARNING")
            + f": tier-1 wall {wall:.0f} s exceeds the "
            f"{TIER1_BUDGET_S:.0f} s budget — demote the heaviest legs to "
            "@pytest.mark.slow (audit with --durations=25)")


def pytest_sessionfinish(session, exitstatus):
    import time

    start = getattr(session.config, "_brc_session_start", None)
    if (start is None or not _tier1_selected(session.config)
            or os.environ.get("BRC_TIER1_BUDGET_ENFORCE") != "1"):
        return
    if time.monotonic() - start > TIER1_BUDGET_S and exitstatus == 0:
        session.exitstatus = 1
