"""Test env: force JAX onto 8 virtual CPU devices (SURVEY.md §4.3) before jax imports.

Two layers of defense, because the environment may carry an `axon` TPU-tunnel PJRT
plugin that a sitecustomize registers in every interpreter and that pins
``jax.config.jax_platforms = "axon,cpu"`` (overriding the JAX_PLATFORMS env var).
Initializing that backend dials a tunnel and can block for minutes when the tunnel
is down — tests must never touch it:

1. env vars (JAX_PLATFORMS / XLA_FLAGS) — effective in clean environments;
2. drop the ``axon`` backend factory from jax's registry and reset the
   ``jax_platforms`` config to ``cpu`` — effective when the plugin already
   registered itself at interpreter start. Safe no-op when no plugin exists.

Real-TPU runs (bench.py, CLI) are unaffected — this applies to the test process only.
"""

import os
import pathlib

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# Persistent XLA compilation cache: the suite is compile-bound (~100 distinct
# jit programs x 2-6 s of XLA CPU compile each — measured, VERDICT r2 #5), and
# the programs are identical run-to-run, so the second and every later suite
# run skips almost all of it (test_sharded.py alone: 39 s cold -> 16 s warm).
# Repo-local and gitignored; JAX_COMPILATION_CACHE_DIR overrides, empty
# disables. min_compile_time=0 + min_entry_size=-1: cache even the tiny eager
# op executables that interpret-mode Pallas tests churn through.
if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
    os.environ["JAX_COMPILATION_CACHE_DIR"] = str(
        pathlib.Path(__file__).resolve().parent.parent / ".jax_cache")
if os.environ["JAX_COMPILATION_CACHE_DIR"]:
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")


def _force_cpu_backend() -> None:
    # The one shared implementation of the drop-plugin private-API dance
    # (swallows private-API drift internally, leaving the env vars above as
    # the fallback layer rather than killing collection for the whole suite).
    from byzantinerandomizedconsensus_tpu.utils.devices import _drop_accelerator_plugins

    _drop_accelerator_plugins()


_force_cpu_backend()


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavyweight at-scale checks (still run by default; deselect "
        "with -m 'not slow' for a quick iteration loop)")
