"""Tier-1 hostile-traffic smoke (round 18): the production traffic plane.

Pins the tentpole seams: bounded admission surfacing as **429 +
Retry-After** over live HTTP (honored by the client, eventually
accepted); per-tenant fairness (in-flight caps + deficit-weighted
rotations keep the non-hog p99 inside the bound); the request envelope
(``tenant`` / ``deadline_ms`` / ``priority``) validating at admission and
never perturbing results; cancellation of queued AND live requests with
every survivor bit-identical to the offline path; and the hostile-load
suite's smallest scenario end-to-end through the ``loadgen --scenario``
delegation in a subprocess (exit-code ladder enforced for real).
"""

import dataclasses
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from byzantinerandomizedconsensus_tpu.backends.base import get_backend
from byzantinerandomizedconsensus_tpu.backends.compaction import (
    CompactionPolicy, WorkFeed)
from byzantinerandomizedconsensus_tpu.config import SimConfig
from byzantinerandomizedconsensus_tpu.obs import record
from byzantinerandomizedconsensus_tpu.serve import admission
from byzantinerandomizedconsensus_tpu.serve.server import (
    ConsensusServer, serve_http)
from byzantinerandomizedconsensus_tpu.utils import metrics as umetrics

_POLICY = CompactionPolicy(width=8, segment=1)
_CEILING = 64


def _cfg(seed, *, protocol="benor", n=5, f=1, instances=8, round_cap=48,
         delivery="keys"):
    return SimConfig(protocol=protocol, n=n, f=f, instances=instances,
                     adversary="none", coin="local", init="random",
                     seed=seed, round_cap=round_cap,
                     delivery=delivery).validate()


def _assert_bit_identical(cfg, rec):
    ref = get_backend("numpy").run(cfg)
    assert rec["rounds"] == [int(r) for r in ref.rounds]
    assert rec["decision"] == [int(d) for d in ref.decision]


def test_envelope_validates_and_strips():
    """The scheduling envelope is popped before config validation; bad
    values are named ``bad_envelope`` rejections, and the config part the
    admission path sees carries no envelope keys."""
    payload = {"protocol": "benor", "n": 5, "f": 1, "instances": 4,
               "tenant": "alice", "deadline_ms": 250, "priority": 3,
               "check_invariants": True}
    cfg_part, env = admission.envelope(payload)
    assert set(cfg_part) & set(admission.ENVELOPE_FIELDS) == set()
    assert env["tenant"] == "alice"
    assert env["deadline_ms"] == 250.0
    assert env["priority"] == 3
    assert env["check_invariants"] is True
    for bad in ({"tenant": ""}, {"tenant": "x" * 65}, {"tenant": 7},
                {"deadline_ms": -1}, {"deadline_ms": "soon"},
                {"priority": 99}, {"priority": 1.5}):
        with pytest.raises(ValueError):
            admission.envelope({"n": 5, **bad})


def test_cancel_queued_and_live_survivors_bit_identical():
    """Cancellation mid-flight: a two-bucket burst, one victim cancelled
    while deep in the queue and one right after submission. Both resolve
    as cancelled, every request resolves, and every surviving reply is
    bit-identical to the per-config offline path."""
    cfgs = [(_cfg(60 + i) if i % 2 == 0 else
             _cfg(60 + i, protocol="bracha", n=7, f=2, delivery="urn"))
            for i in range(8)]
    with ConsensusServer(policy=_POLICY, round_cap_ceiling=_CEILING) as srv:
        handles = [srv.submit(c) for c in cfgs]
        early = srv.cancel(handles[0].id)   # just seeded: queued or live
        late = srv.cancel(handles[-1].id)   # other bucket: pending queue
        missing = srv.cancel("r-nope")
        for h in handles:
            assert h.done.wait(timeout=600.0)
        stats = srv.stats()

    assert missing["found"] is False and missing["cancelled"] is False
    assert early["found"] and late["found"]
    cancelled = [a for a in (early, late) if a["cancelled"]]
    assert cancelled, (early, late)
    for ack in cancelled:
        assert ack["where"] in ("queued", "live")
    assert stats["cancelled"] == len(cancelled)

    for i, h in enumerate(handles):
        if h.error == "cancelled":
            assert h.record is None
        else:
            assert record.validate_record(h.record) == [], h.record
            _assert_bit_identical(cfgs[i], h.record)
    survivors = sum(1 for h in handles if h.record is not None)
    assert survivors + len(cancelled) == len(handles)


def test_cancel_last_queued_item_keeps_session_owned_feed_open():
    """Round-21 WorkFeed.cancel edge case: a spec-§11 session's slot 0 is
    already flying (pulled into the grid) when the ONLY item still queued
    is cancelled. The queue empties, but the feed — owned by the live
    session, whose future slots materialize at the grid's retire seam, not
    here — must NOT report drained (``pull() -> None``) even once closed;
    that would close the feed out from under the dispatcher mid-session.
    Only ``session_done`` (the boundary-reap release path) ends the
    stream."""
    feed = WorkFeed(round_cap_ceiling=_CEILING)
    owner, bystander = object(), object()
    feed.push(_cfg(60), token=owner, session=3)
    items = feed.pull()
    assert [(it[2], it[3]) for it in items] == [(owner, 3)]  # grid owns it
    feed.push(_cfg(61), token=bystander)
    assert feed.cancel(bystander) is True  # the last queued item dies
    assert feed.pending() == 0
    feed.close()
    # empty + closed but session-owned: the stream stays open
    assert feed.pull() == []
    # cancelling the FLYING session releases nothing here either — the
    # grid owns it now, so the reap path must still run session_done
    assert feed.cancel(owner) is False
    assert feed.pull() == []
    feed.session_done(owner)
    assert feed.pull() is None  # last owner gone: drained at last


def test_tenant_hog_cannot_starve_interactive_tenant():
    """A flooding tenant behind a per-tenant in-flight cap: the
    interactive tenant's p99 stays inside the fairness bound
    (max(0.5 × hog p99, 2 s)) and the hog's work still completes."""
    hog_cfgs = [_cfg(70 + i, n=9, f=3, instances=8, round_cap=_CEILING)
                for i in range(6)]
    int_cfgs = [_cfg(90 + i, instances=2, round_cap=16) for i in range(3)]
    with ConsensusServer(policy=_POLICY, round_cap_ceiling=_CEILING,
                         tenant_inflight_cap=4) as srv:
        hog_handles, int_handles = [], []

        def hog():
            for c in hog_cfgs:
                payload = {**dataclasses.asdict(c), "tenant": "hog"}
                while True:
                    try:
                        hog_handles.append(srv.submit(payload))
                        break
                    except admission.Backpressure as e:
                        time.sleep(e.retry_after_s)

        def interactive():
            time.sleep(0.05)
            for c in int_cfgs:
                int_handles.append(srv.submit(
                    {**dataclasses.asdict(c), "tenant": "interactive",
                     "deadline_ms": 8000.0}))
                time.sleep(0.05)

        threads = [threading.Thread(target=hog),
                   threading.Thread(target=interactive)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for h in hog_handles + int_handles:
            h.wait(timeout=600.0)
        stats = srv.stats()

    assert len(hog_handles) == len(hog_cfgs)
    assert len(int_handles) == len(int_cfgs)
    # every ever-seen tenant reports (zeroed once drained)
    assert stats["tenants"].get("hog") == 0
    assert stats["tenants"].get("interactive") == 0
    (hog_p99,) = umetrics.percentiles(
        [h.latency_s * 1000.0 for h in hog_handles], (99,))
    (int_p99,) = umetrics.percentiles(
        [h.latency_s * 1000.0 for h in int_handles], (99,))
    assert int_p99 <= max(0.5 * hog_p99, 2000.0), (int_p99, hog_p99)
    for c, h in zip(int_cfgs, int_handles):
        _assert_bit_identical(c, h.record)


def test_http_429_retry_after_round_trip():
    """Backpressure over live HTTP: a bounded feed answers 429 with a
    parseable Retry-After header; a client honoring the hint eventually
    lands every request, and the replies stay bit-identical."""
    cfgs = [_cfg(40 + i, instances=16, round_cap=_CEILING)
            for i in range(4)]
    with ConsensusServer(policy=_POLICY, round_cap_ceiling=_CEILING,
                         feed_depth=1) as srv:
        httpd = serve_http(srv, host="127.0.0.1", port=0)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        base = "http://%s:%s" % httpd.server_address[:2]
        try:
            rejected = 0
            ids = []
            for i, c in enumerate(cfgs):
                if i == 1:
                    # the first request must hold lanes before the burst:
                    # submits against an inactive bucket queue for rotation
                    # (unbounded here) instead of hitting the bounded feed
                    t0 = time.monotonic()
                    while time.monotonic() - t0 < 600.0:
                        st = srv.stats()
                        if st["active_bucket"] and st["feed_depth"] == 0:
                            break
                        time.sleep(0.01)
                body = json.dumps(dataclasses.asdict(c)).encode()
                for _ in range(400):
                    req = urllib.request.Request(
                        base + "/submit", data=body, method="POST",
                        headers={"Content-Type": "application/json"})
                    try:
                        with urllib.request.urlopen(req, timeout=60) as r:
                            ids.append(json.loads(r.read().decode())["id"])
                            break
                    except urllib.error.HTTPError as e:
                        assert e.code == 429, e.code
                        doc = json.loads(e.read().decode())
                        assert doc["reason"] == "overflow"
                        hint = float(e.headers["Retry-After"])
                        assert 0.0 < hint < 1.0
                        rejected += 1
                        time.sleep(hint)
                else:
                    pytest.fail("submit never accepted")
            assert rejected >= 1
            recs = []
            for rid in ids:
                deadline = time.monotonic() + 600.0
                while time.monotonic() < deadline:
                    try:
                        with urllib.request.urlopen(
                                base + f"/result/{rid}", timeout=60) as r:
                            doc = json.loads(r.read().decode())
                    except urllib.error.HTTPError as e:
                        raise AssertionError(f"result: HTTP {e.code}")
                    if doc.get("done") is False:
                        time.sleep(0.05)
                        continue
                    recs.append(doc)
                    break
            # cancel of an unknown id stays a JSON 404 on the same route
            req = urllib.request.Request(base + "/cancel/r-nope",
                                         data=b"", method="POST")
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req, timeout=60)
            assert exc.value.code == 404
            assert "error" in json.loads(exc.value.read().decode())
        finally:
            httpd.shutdown()
            httpd.server_close()
    assert len(recs) == len(cfgs)
    for c, rec in zip(cfgs, recs):
        _assert_bit_identical(c, rec)


def test_hostile_suite_smallest_scenario_subprocess(tmp_path):
    """The smallest hostile scenario end-to-end, through the ``loadgen
    --scenario`` delegation, in a real subprocess: exit code 0, a valid
    schema-v1.9 record with the hostile block, zero mismatches and zero
    steady-state recompiles."""
    out = tmp_path / "hostile_smoke.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m",
         "byzantinerandomizedconsensus_tpu.tools.loadgen",
         "--scenario", "bucket_churn", "--smoke", "--out", str(out)],
        capture_output=True, text=True, timeout=560, env=env)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    doc = json.loads(out.read_text())
    assert record.validate_record(doc) == [], doc
    assert doc["record_revision"] == record.RECORD_REVISION
    hb = doc["hostile"]
    assert hb["mismatches"] == 0
    assert hb["steady_state_compiles"] == 0
    (row,) = hb["scenarios"]
    assert row["scenario"] == "bucket_churn"
    assert row["replied"] == row["requests"]
    assert row["slo_ok"] is True
