"""Urn delivery (spec §4b): bit-match across backends, protocol properties, and
statistical agreement with the keys model.

The urn model is a *different exact sampler of the same delivery distribution
family* (spec §4b): bit-matching is within delivery="urn", and the cross-model
check is statistical (same mean rounds / decision frequencies, not same bits).
"""

import dataclasses

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu import SimConfig, Simulator, preset

URN_SMALL = [
    SimConfig(protocol="benor", n=4, f=1, instances=60, adversary="none", coin="local",
              round_cap=64, seed=0, delivery="urn"),
    SimConfig(protocol="benor", n=9, f=4, instances=40, adversary="crash", coin="local",
              round_cap=96, seed=1, delivery="urn"),
    SimConfig(protocol="benor", n=16, f=3, instances=40, adversary="byzantine",
              coin="local", round_cap=64, seed=2, delivery="urn"),
    SimConfig(protocol="benor", n=11, f=2, instances=40, adversary="adaptive",
              coin="shared", round_cap=64, seed=3, delivery="urn"),
    SimConfig(protocol="bracha", n=10, f=3, instances=40, adversary="byzantine",
              coin="shared", round_cap=64, seed=4, delivery="urn"),
    SimConfig(protocol="bracha", n=16, f=5, instances=40, adversary="adaptive",
              coin="shared", round_cap=64, seed=5, delivery="urn"),
    SimConfig(protocol="bracha", n=13, f=4, instances=40, adversary="crash",
              coin="local", round_cap=64, seed=6, delivery="urn"),
    SimConfig(protocol="bracha", n=7, f=2, instances=40, adversary="none",
              coin="shared", round_cap=64, seed=7, delivery="urn"),
]


# The interpret-mode Pallas leg costs ~20 s of tracing per config; driver-level
# Pallas runs once, on the most intricate path (two-faced Ben-Or equivocation).
# The full grid's Pallas coverage lives in tests/test_pallas_step.py at
# step level, and the cheap backends keep driver breadth here.
_PALLAS_SEEDS = {2}


@pytest.mark.parametrize(
    "cfg", URN_SMALL,
    ids=lambda c: f"{c.protocol}-n{c.n}f{c.f}-{c.adversary}-{c.coin}")
def test_urn_bitmatch_small(cfg):
    ref = Simulator(cfg, "cpu").run()
    backends = ("numpy", "jax", "native")
    if cfg.seed in _PALLAS_SEEDS:
        backends += ("jax_pallas",)
    for backend in backends:
        got = Simulator(cfg, backend).run()
        np.testing.assert_array_equal(ref.rounds, got.rounds, err_msg=f"rounds {backend}")
        np.testing.assert_array_equal(ref.decision, got.decision,
                                      err_msg=f"decision {backend}")


@pytest.mark.parametrize("name,n_sample", [("config2", 4), ("config3", 3), ("config4", 2)])
def test_urn_bitmatch_benchmark_sampled(name, n_sample):
    import zlib

    cfg = preset(name, round_cap=64, delivery="urn")
    rng = np.random.default_rng(zlib.crc32(name.encode()))
    ids = np.unique(rng.integers(0, cfg.instances, size=n_sample))
    ref = Simulator(cfg, "cpu").run(ids)
    for backend in ("numpy", "jax"):
        got = Simulator(cfg, backend).run(ids)
        np.testing.assert_array_equal(ref.rounds, got.rounds, err_msg=f"rounds {backend}")
        np.testing.assert_array_equal(ref.decision, got.decision,
                                      err_msg=f"decision {backend}")


@pytest.mark.parametrize("cfg", URN_SMALL[:6],
                         ids=lambda c: f"{c.protocol}-{c.adversary}")
def test_urn_agreement_and_validity(cfg):
    """Agreement: every decided instance decides a single value; validity via
    unanimous starts (decision == the common initial value)."""
    res = Simulator(cfg, "numpy").run()
    assert set(np.unique(res.decision)) <= {0, 1, 2}
    for init, expect in (("all0", 0), ("all1", 1)):
        c = dataclasses.replace(cfg, init=init, instances=30)
        r = Simulator(c, "numpy").run()
        decided = r.decision != 2
        assert np.all(r.decision[decided] == expect), f"validity broken for {init}"


@pytest.mark.parametrize("adversary,coin,tol", [("none", "shared", 0.1),
                                                ("adaptive", "local", 1.5)])
def test_urn_matches_keys_statistically(adversary, coin, tol):
    """Same delivery distribution family ⇒ close round/decision statistics.

    The adaptive+local case is the sensitive one: the stratum-priority drops
    must match the keys model's bias-bit ordering, or mean rounds diverge
    wildly (observed: a priority inversion turns ~10 mean rounds into cap
    saturation)."""
    inst = 4000 if adversary == "none" else 400
    base = SimConfig(protocol="bracha", n=16, f=5, instances=inst,
                     adversary=adversary, coin=coin, round_cap=64, seed=11)
    keys = Simulator(base, "numpy").run()
    urn = Simulator(dataclasses.replace(base, delivery="urn"), "numpy").run()
    assert abs(float(keys.rounds.mean()) - float(urn.rounds.mean())) < tol
    assert abs(float((keys.decision == 1).mean())
               - float((urn.decision == 1).mean())) < 0.08


@pytest.mark.parametrize("n_data,n_model,kernel", [
    (8, 1, "xla"), (4, 2, "xla"), (2, 4, "xla"),
    # Pallas: one driver-level mesh shape (receiver-shard path); shard-offset
    # breadth incl. the class boundary is step-level in test_pallas_step.py.
    (4, 2, "pallas"),
])
def test_urn_sharded_bitmatch(n_data, n_model, kernel):
    """Urn delivery under shard_map (instance + replica sharding) bit-matches
    the single-device jax backend on every mesh shape, with both the XLA urn
    and the Pallas urn kernel (which exercises its receiver-shard path)."""
    from byzantinerandomizedconsensus_tpu.parallel.mesh import make_mesh
    from byzantinerandomizedconsensus_tpu.parallel.sharded import JaxShardedBackend

    cfg = SimConfig(protocol="bracha", n=16, f=5, instances=48,
                    adversary="adaptive", coin="shared", round_cap=64, seed=21,
                    delivery="urn")
    ref = Simulator(cfg, "jax").run()
    got = JaxShardedBackend(mesh=make_mesh(n_data=n_data, n_model=n_model),
                            kernel=kernel).run(cfg)
    np.testing.assert_array_equal(ref.rounds, got.rounds)
    np.testing.assert_array_equal(ref.decision, got.decision)


@pytest.mark.parametrize("kernel", ["xla"])
def test_urn_sharded_two_faced_byzantine(kernel):
    """Two-faced equivocation (spec §4b) under replica sharding: the per-class
    value recomputation must line up with global receiver indices. (The Pallas
    kernel's two-faced shard-offset path is covered at step level in
    test_pallas_step.py::test_urn_kernel_receiver_shard_offsets.)"""
    from byzantinerandomizedconsensus_tpu.parallel.mesh import make_mesh
    from byzantinerandomizedconsensus_tpu.parallel.sharded import JaxShardedBackend

    cfg = SimConfig(protocol="benor", n=16, f=3, instances=40,
                    adversary="byzantine", coin="local", round_cap=64, seed=31,
                    delivery="urn")
    ref = Simulator(cfg, "cpu").run()
    got = JaxShardedBackend(mesh=make_mesh(n_data=2, n_model=4),
                            kernel=kernel).run(cfg)
    np.testing.assert_array_equal(ref.rounds, got.rounds)
    np.testing.assert_array_equal(ref.decision, got.decision)


def test_urn_counts_conservation():
    """Spec §4b: c0+c1+c2 = min(L, n-f-1)+1; with no faults and no bot values
    the delivered total is exactly n-f for every receiver."""
    from byzantinerandomizedconsensus_tpu.ops import urn

    cfg = SimConfig(protocol="bracha", n=32, f=10, instances=8, adversary="none",
                    coin="shared", delivery="urn")
    B, n = 5, cfg.n
    inst = np.arange(B, dtype=np.uint32)
    values = (np.arange(n, dtype=np.uint8) % 2)[None, :].repeat(B, 0)
    silent = np.zeros((B, n), dtype=bool)
    faulty = np.zeros((B, n), dtype=bool)
    c0, c1 = urn.counts_fn(cfg, cfg.seed, inst, 0, 0, values, silent, faulty,
                           values, xp=np)
    np.testing.assert_array_equal(c0 + c1, np.full((B, n), n - cfg.f))
    # and the counts can't exceed what exists on the wire
    assert (c0 <= (values == 0).sum(-1)[:, None] + 1).all()
    assert (c1 <= (values == 1).sum(-1)[:, None] + 1).all()


def test_affine_lcg_tables_equal_iterated_lcg():
    """The algebra behind the Pallas affine urn kernel (ops/pallas_urn.py,
    spec §4b): s_{j+1} = A^{j+1}·s_0 + C_{j+1} mod 2^32 with the iteratively
    built scalar tables must equal j+1 applications of the LCG, for every
    draw index up to the benchmark f and arbitrary start states — pinned
    directly so the kernel's compile-time tables carry an independent proof,
    not just end-to-end bit-match evidence."""
    from byzantinerandomizedconsensus_tpu.ops import prf

    rng = np.random.default_rng(9)
    s0 = rng.integers(0, 1 << 32, size=64, dtype=np.uint64)
    M = 1 << 32
    s_iter = s0.copy()
    a_j, c_j = 1, 0
    for j in range(preset("config4").f):
        s_iter = (s_iter * prf.URN_LCG_A + prf.URN_LCG_C) % M
        a_j = (a_j * prf.URN_LCG_A) % M
        c_j = (c_j * prf.URN_LCG_A + prf.URN_LCG_C) % M
        np.testing.assert_array_equal((s0 * a_j + c_j) % M, s_iter,
                                      err_msg=f"draw {j}")


def test_multiseed_run_large():
    """run_large shards across derived seeds; each shard reproduces exactly the
    standalone run of its derived config (spec §2 multi-seed contract)."""
    from byzantinerandomizedconsensus_tpu.utils import multiseed

    cfg = SimConfig(protocol="bracha", n=10, f=3, instances=1, adversary="byzantine",
                    coin="shared", round_cap=64, seed=7, delivery="urn")
    merged, shards = multiseed.run_large(cfg, total_instances=70, backend="numpy",
                                         shard_instances=32)
    assert len(shards) == 3 and [s.instances for s in shards] == [32, 32, 6]
    assert len(merged.rounds) == 70
    assert len(set(s.seed for s in shards)) == 3
    # shard 1 standalone == its slice of the merged result
    solo = Simulator(shards[1], "numpy").run()
    np.testing.assert_array_equal(solo.rounds, merged.rounds[32:64])
    np.testing.assert_array_equal(solo.decision, merged.decision[32:64])
    # and the oracle bit-matches a sampled shard (the whole point of the design)
    oracle = Simulator(shards[2], "cpu").run()
    np.testing.assert_array_equal(oracle.rounds, merged.rounds[64:])
