"""Worker script for the multi-process multi-host tests (tests/test_multihost.py).

Run as: python tests/multihost_worker.py <coordinator_port> <process_id> \
            <num_processes> [mode]

Default mode (``hybrid``): each process owns 4 virtual CPU devices;
jax.distributed glues them into one global topology with per-process
indices — the smallest faithful model of a DCN-connected multi-host
deployment (SURVEY.md §5 distributed comm backend). Asserts the hybrid
mesh keeps the model axis host-local, runs a cross-host psum, and
bit-matches the sharded round driver against native.

``model-cross`` mode (round 15, VERDICT r5 next #5): each process owns 2
virtual devices; a deliberately *transposed* (num_processes, 2) mesh puts
the two model-axis devices of every row in DIFFERENT processes, so the
replica (model) axis crosses a process boundary — the DCN-crossing model
axis at n=512, bit-matched against native. If jax refuses the
cross-process model collective (the r7 shard_map precedent on 0.4.x),
the worker prints ``MULTIHOST_BLOCKED <reason>`` and exits 0 so the test
can record-as-blocked with a named skip instead of failing.

Prints "MULTIHOST_OK" on success; any assertion/exception exits non-zero.
"""

import os
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

_MODE = sys.argv[4] if len(sys.argv) > 4 else "hybrid"
_DEVS_PER_PROC = 2 if _MODE == "model-cross" else 4

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={_DEVS_PER_PROC}").strip()
os.environ.pop("PALLAS_AXON_POOL_IPS", None)


def main() -> int:
    port, pid, nproc = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])

    # Keep the axon TPU plugin from hijacking the platform list (its
    # registration pins jax.config.jax_platforms, overriding the env var) —
    # the one shared implementation of the private-API dance.
    from byzantinerandomizedconsensus_tpu.utils.devices import _drop_accelerator_plugins

    _drop_accelerator_plugins()

    import jax

    from byzantinerandomizedconsensus_tpu.parallel import mesh as pmesh

    pmesh.init_distributed(f"localhost:{port}", num_processes=nproc,
                           process_id=pid)
    import numpy as np

    import jax.numpy as jnp

    devs = jax.devices()
    assert len(devs) == _DEVS_PER_PROC * nproc, f"global devices: {len(devs)}"
    assert max(d.process_index for d in devs) == nproc - 1

    if _MODE == "model-cross":
        return _model_cross(pid, nproc, devs)

    # Hybrid mesh: data axis spans hosts (DCN leg), model axis stays host-local
    # (the ICI analog). per_host=4, n_model=2 -> global (data=4, model=2).
    mesh = pmesh.make_hybrid_mesh(n_model=2)
    grid = mesh.devices
    assert grid.shape == (2 * nproc, 2), grid.shape
    for row in grid:
        assert row[0].process_index == row[1].process_index, \
            "model axis must not cross hosts"
    data_procs = [grid[i, 0].process_index for i in range(grid.shape[0])]
    assert set(data_procs) == set(range(nproc)), \
        f"data axis must span all hosts, got {data_procs}"

    # Cross-host collective through the mesh: psum over both axes.
    from functools import partial

    from jax.sharding import NamedSharding, PartitionSpec as P

    @partial(jax.shard_map, mesh=mesh, in_specs=(), out_specs=P())
    def probe():
        return jax.lax.psum(jnp.ones((1,), jnp.int32), ("data", "model"))

    total = jax.jit(probe)()
    assert int(np.asarray(total)[0]) == 4 * nproc, total

    # The real product path: one sharded simulation chunk over the hybrid mesh,
    # bit-matched against the native arbiter on every host.
    from jax.experimental import multihost_utils

    from byzantinerandomizedconsensus_tpu.backends import get_backend
    from byzantinerandomizedconsensus_tpu.config import SimConfig
    from byzantinerandomizedconsensus_tpu.parallel.sharded import _run_chunk_sharded

    cfg = SimConfig(protocol="bracha", n=16, f=5, instances=16,
                    adversary="byzantine", coin="shared", round_cap=32,
                    seed=7, delivery="urn").validate()
    ids = np.arange(cfg.instances, dtype=np.uint32)
    sharding = NamedSharding(mesh, P("data"))
    gids = jax.make_array_from_callback(
        ids.shape, sharding, lambda idx: ids[idx])
    rounds, decision = jax.jit(
        partial(_run_chunk_sharded, cfg, mesh))(gids)
    rounds = multihost_utils.process_allgather(rounds, tiled=True)
    decision = multihost_utils.process_allgather(decision, tiled=True)

    ref = get_backend("native").run(cfg)
    np.testing.assert_array_equal(np.asarray(rounds), ref.rounds)
    np.testing.assert_array_equal(np.asarray(decision), ref.decision)

    print(f"MULTIHOST_OK pid={pid}", flush=True)
    return 0


def _model_cross(pid: int, nproc: int, devs) -> int:
    """The round-15 leg: a transposed (nproc, 2) mesh whose model axis
    spans two processes in every row, driven at n=512. A jax refusal is
    reported as MULTIHOST_BLOCKED (exit 0) for the named-skip path."""
    from functools import partial

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    by_proc: dict = {}
    for d in devs:
        by_proc.setdefault(d.process_index, []).append(d)
    for p in by_proc:
        by_proc[p].sort(key=lambda d: d.id)
    # Row i pairs process i's first device with process (i+1)'s second:
    # every model pair crosses a process boundary — the opposite of
    # hybrid_grid's host-local model placement, on purpose.
    rows = [[by_proc[i][0], by_proc[(i + 1) % nproc][1]]
            for i in range(nproc)]
    grid = np.asarray(rows, dtype=object)
    for row in grid:
        assert row[0].process_index != row[1].process_index, \
            "model axis must cross a process boundary in this mode"
    mesh = Mesh(grid, ("data", "model"))

    from byzantinerandomizedconsensus_tpu.backends import get_backend
    from byzantinerandomizedconsensus_tpu.config import SimConfig
    from byzantinerandomizedconsensus_tpu.parallel.sharded import (
        _run_chunk_sharded)

    cfg = SimConfig(protocol="bracha", n=512, f=5, instances=2 * nproc,
                    adversary="byzantine", coin="shared", round_cap=16,
                    seed=11, delivery="urn").validate()
    try:
        @partial(jax.shard_map, mesh=mesh, in_specs=(), out_specs=P())
        def probe():
            return jax.lax.psum(jnp.ones((1,), jnp.int32),
                                ("data", "model"))

        total = jax.jit(probe)()
        assert int(np.asarray(total)[0]) == 2 * nproc, total

        from jax.experimental import multihost_utils

        ids = np.arange(cfg.instances, dtype=np.uint32)
        sharding = NamedSharding(mesh, P("data"))
        gids = jax.make_array_from_callback(
            ids.shape, sharding, lambda idx: ids[idx])
        rounds, decision = jax.jit(
            partial(_run_chunk_sharded, cfg, mesh))(gids)
        rounds = multihost_utils.process_allgather(rounds, tiled=True)
        decision = multihost_utils.process_allgather(decision, tiled=True)
    except Exception as e:  # noqa: BLE001 — a refusal is evidence, not
        # a failure: the test records it as a named skip (r7 precedent)
        print(f"MULTIHOST_BLOCKED pid={pid} {type(e).__name__}: {e}",
              flush=True)
        return 0

    ref = get_backend("native").run(cfg)
    np.testing.assert_array_equal(np.asarray(rounds), ref.rounds)
    np.testing.assert_array_equal(np.asarray(decision), ref.decision)

    print(f"MULTIHOST_OK pid={pid}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
