"""Spec §2 v2/v3 coordinate packing: the n > 1024 gate (ISSUE 2 tentpole)
and the n > 4096 gate (ISSUE 15, round 19).

Four invariants:

1. **Frozen v1 law** — every draw of every n ≤ 1024 config is bit-identical to
   the pre-v2 code: pinned raw PRF words, plus a golden re-pin asserting the
   committed golden vectors (all n ≤ 1024, all four delivery models) still
   reproduce exactly under the v2-gated code path.
2. **The gate itself** — ``pack_version`` is a pure function of n; ``validate()``
   accepts n=2048/4096 and enforces the narrower v2 instance/round fields.
3. **Cross-stack agreement past the old cap** — numpy vs native (and a scalar
   oracle subsample on the slow leg) bit-match at n=2048 under the v2 law.
4. **The v3 gate** (round 19) — v1/v2 words never move under the widened law;
   ``validate()`` admits n = 10⁵/10⁶ for the committee family only, and
   rejects v3 field overflows and full-mesh deliveries past 4096 by name.
"""

import shutil

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu.backends import get_backend
from byzantinerandomizedconsensus_tpu.config import SimConfig
from byzantinerandomizedconsensus_tpu.ops import prf

needs_gxx = pytest.mark.skipif(shutil.which("g++") is None,
                               reason="no C++ toolchain")


# ---------------------------------------------------------------- v1 frozen law

# Raw prf_u32 words captured from the pre-v2 code (commit beb3814). If any of
# these move, every golden vector and checkpoint in the repo is invalidated.
V1_PINNED = [
    ((42, 3, 1, 0, 1, 1, prf.SCHED), 0x9A1E6B74),
    ((1234567890123, 99, 7, 2, 1023, 1023, prf.SCHED), 0xE07854E8),
    ((0, 0, 0, 0, 0, 0, prf.INIT_EST), 0x6B200159),
    ((7, 131071, 65535, 3, 512, 700, prf.URN2), 0x41BC3C2C),
    ((2**63 + 5, 1, 255, 1, 17, 0, prf.URN3), 0xA86FDA36),
]


@pytest.mark.parametrize("coords,expect", V1_PINNED)
def test_v1_words_pinned(coords, expect):
    assert int(prf.prf_u32(*coords, xp=np)) == expect           # default pack=1
    assert int(prf.prf_u32(*coords, xp=np, pack=1)) == expect


def test_golden_byte_identical_under_v2_gate():
    """Every committed golden vector (n ≤ 1024, all deliveries) reproduces
    byte-for-byte under the v2-gated code — the 'goldens must not move'
    acceptance gate, pinned independently of test_golden.py so a regen there
    cannot silently absorb a packing regression."""
    from spec.golden.regen import GOLDEN_CONFIGS, PATH

    assert PATH.exists(), "golden.npz missing"
    data = np.load(PATH)
    from byzantinerandomizedconsensus_tpu import Simulator

    for name, cfg in GOLDEN_CONFIGS.items():
        assert cfg.pack_version == 1, f"{name}: goldens must be v1 configs"
        res = Simulator(cfg, "cpu").run()
        np.testing.assert_array_equal(
            res.rounds, data[f"{name}__rounds"], err_msg=f"{name} rounds moved")
        np.testing.assert_array_equal(
            res.decision, data[f"{name}__decision"],
            err_msg=f"{name} decision moved")


# ------------------------------------------------------------------- the gate

def test_pack_version_is_pure_function_of_n():
    assert prf.pack_version(1) == 1
    assert prf.pack_version(1024) == 1
    assert prf.pack_version(1025) == 2
    assert prf.pack_version(2048) == 2
    assert prf.pack_version(4096) == 2
    assert prf.pack_version(4097) == 3
    assert prf.pack_version(100_000) == 3
    assert prf.pack_version(prf.V3_MAX_N) == 3
    with pytest.raises(ValueError):
        prf.pack_version(prf.V3_MAX_N + 1)


def test_v2_law_differs_from_v1():
    """The gates are non-vacuous: the three laws give pairwise different
    words on shared coordinates (same seed, same logical draw)."""
    coords = (42, 3, 1, 0, 1, 1, prf.SCHED)
    w1 = int(prf.prf_u32(*coords, xp=np, pack=1))
    w2 = int(prf.prf_u32(*coords, xp=np, pack=2))
    w3 = int(prf.prf_u32(*coords, xp=np, pack=3))
    assert len({w1, w2, w3}) == 3


def test_v2_numpy_matches_jax():
    jnp = pytest.importorskip("jax.numpy")
    inst = np.arange(50, dtype=np.uint32)[:, None]
    recv = np.arange(2048, dtype=np.uint32)[None, :]
    a = prf.prf_u32(99, inst, 5, 2, recv, 0, prf.URN3, xp=np, pack=2)
    b = prf.prf_u32(99, jnp.asarray(inst), 5, 2, jnp.asarray(recv), 0,
                    prf.URN3, xp=jnp, pack=2)
    np.testing.assert_array_equal(a, np.asarray(b))


def test_v2_recv_field_no_longer_collides():
    """The v1 failure mode that motivated v2: under v1 packing, recv=1024 at
    rnd=0 aliases recv=0 at rnd=1 (recv bits overflow into the round field).
    Under v2 the same pair of coordinates is distinct."""
    a1 = prf.prf_u32(7, 0, 0, 0, 1024, 0, prf.URN, xp=np, pack=1)
    b1 = prf.prf_u32(7, 0, 1, 0, 0, 0, prf.URN, xp=np, pack=1)
    assert int(a1) == int(b1)  # the v1 overflow, demonstrated
    a2 = prf.prf_u32(7, 0, 0, 0, 1024, 0, prf.URN, xp=np, pack=2)
    b2 = prf.prf_u32(7, 0, 1, 0, 0, 0, prf.URN, xp=np, pack=2)
    assert int(a2) != int(b2)


def test_validate_accepts_v2_sizes():
    c2048 = SimConfig(protocol="bracha", n=2048, f=682, instances=100,
                      adversary="adaptive", coin="shared",
                      delivery="urn2").validate()
    assert c2048.pack_version == 2
    c4096 = SimConfig(protocol="bracha", n=4096, f=1365, instances=10,
                      adversary="none", coin="shared",
                      delivery="urn3").validate()
    assert c4096.pack_version == 2
    with pytest.raises(ValueError):
        SimConfig(protocol="bracha", n=4097, f=1365, instances=1).validate()


def test_validate_rejects_v2_field_overflow():
    """v2 narrows the instance field to 16 bits and the round field to 12:
    counts legal under v1 must be rejected once n crosses the gate."""
    big_inst = prf.V2_MAX_INSTANCES + 1          # fine under v1 (2^17 cap)
    SimConfig(protocol="bracha", n=1024, f=341, instances=big_inst).validate()
    with pytest.raises(ValueError, match="packing v2"):
        SimConfig(protocol="bracha", n=2048, f=682,
                  instances=big_inst).validate()
    big_cap = prf.V2_MAX_ROUNDS + 1              # fine under v1 (2^16 cap)
    SimConfig(protocol="bracha", n=1024, f=341, instances=1,
              round_cap=big_cap).validate()
    with pytest.raises(ValueError, match="packing v2"):
        SimConfig(protocol="bracha", n=2048, f=682, instances=1,
                  round_cap=big_cap).validate()
    # At the exact v2 limits validate() still accepts.
    SimConfig(protocol="bracha", n=2048, f=682,
              instances=prf.V2_MAX_INSTANCES,
              round_cap=prf.V2_MAX_ROUNDS).validate()


# ----------------------------------------------------- the v3 gate (round 19)

def test_validate_accepts_v3_committee_sizes():
    """The §2 v3 law admits the committee family at n = 10⁵ and 10⁶ — the
    scales the §10 cost curve is measured at (artifacts/committee_r19.json)."""
    from byzantinerandomizedconsensus_tpu.config import committee_point

    c1e5 = committee_point(100_000, instances=4)
    assert c1e5.pack_version == 3
    c1e6 = committee_point(1_000_000, instances=2)
    assert c1e6.pack_version == 3
    assert prf.V3_MAX_N == 1 << 20


def test_validate_rejects_full_mesh_past_v2_ceiling():
    """Only the committee family crosses the 4096 edge: the full-mesh
    samplers stay behind the v2 ceiling, rejected by name."""
    for delivery in ("keys", "urn", "urn2", "urn3"):
        with pytest.raises(ValueError,
                           match="only delivery='committee'"):
            SimConfig(protocol="bracha", n=8192, f=1638, instances=1,
                      delivery=delivery).validate()


def test_validate_rejects_v3_field_overflow():
    """v3 narrows the instance field to 12 bits (the round field stays at
    v2's 12): an instance count legal under v2 must be rejected once n
    crosses the 4096 gate, and a round_cap past the 12-bit field too."""
    big_inst = prf.V3_MAX_INSTANCES + 1        # fine under v2 (2^16 cap)
    SimConfig(protocol="bracha", n=2048, f=409,
              instances=big_inst).validate()
    with pytest.raises(ValueError, match="under packing v3"):
        SimConfig(protocol="bracha", n=8192, f=1638, instances=big_inst,
                  delivery="committee").validate()
    with pytest.raises(ValueError, match="under packing v3"):
        SimConfig(protocol="bracha", n=8192, f=1638, instances=1,
                  round_cap=prf.V3_MAX_ROUNDS + 1,
                  delivery="committee").validate()
    # At the exact v3 limits validate() still accepts.
    SimConfig(protocol="bracha", n=8192, f=1638,
              instances=prf.V3_MAX_INSTANCES,
              round_cap=prf.V3_MAX_ROUNDS,
              delivery="committee").validate()


# ------------------------------------------- cross-stack agreement at n = 2048

def _cfg2048(delivery, instances=4, adversary="adaptive", round_cap=48):
    return SimConfig(protocol="bracha", n=2048, f=682, instances=instances,
                     adversary=adversary, coin="shared", seed=7,
                     round_cap=round_cap, delivery=delivery).validate()


@needs_gxx
@pytest.mark.parametrize("delivery", ["urn2", "urn3"])
def test_numpy_native_bitmatch_n2048(delivery):
    cfg = _cfg2048(delivery)
    a = get_backend("numpy").run(cfg)
    b = get_backend("native").run(cfg)
    np.testing.assert_array_equal(a.rounds, b.rounds)
    np.testing.assert_array_equal(a.decision, b.decision)


@needs_gxx
@pytest.mark.slow
def test_oracle_subsample_n2048():
    """One scalar-oracle instance at n=2048 (the oracle is O(n²) python per
    step, so one instance is the budget) — anchors the numpy and native legs
    to the third independent implementation under the v2 law."""
    cfg = _cfg2048("urn2", instances=1)
    a = get_backend("cpu").run(cfg)
    b = get_backend("numpy").run(cfg)
    c = get_backend("native").run(cfg)
    np.testing.assert_array_equal(a.rounds, b.rounds)
    np.testing.assert_array_equal(a.decision, b.decision)
    np.testing.assert_array_equal(a.rounds, c.rounds)
    np.testing.assert_array_equal(a.decision, c.decision)


def test_virtual_mesh_shard_equivalence_n2048():
    """Model-axis sharding semantics at n=2048 on a virtual (2,2) layout,
    host-side: the count-level delivery ops address randomness by *global*
    receiver coordinates, so computing each receiver shard independently
    (recv_ids slices — exactly what parallel/sharded.py's model axis does)
    must reassemble to the full-width result bit-for-bit under the v2 law."""
    from byzantinerandomizedconsensus_tpu.models import state as state_mod
    from byzantinerandomizedconsensus_tpu.models.adversaries import AdversaryModel
    from byzantinerandomizedconsensus_tpu.ops import delivery_counts_fn

    cfg = _cfg2048("urn2", instances=2)
    inst_ids = np.arange(2, dtype=np.int64)
    adv = AdversaryModel(cfg)
    setup = adv.setup(cfg.seed, inst_ids, xp=np)
    est = state_mod.init_est(cfg, cfg.seed, inst_ids, xp=np)
    values, silent, _bias = adv.inject(cfg.seed, inst_ids, 0, 0, est, setup,
                                       xp=np)
    counts = delivery_counts_fn(cfg.delivery)
    full = counts(cfg, cfg.seed, inst_ids, 0, 0, values, silent,
                  setup["faulty"], est, xp=np)
    n_model = 2
    n_local = cfg.n // n_model
    for part in range(2):  # both (c0, c1) planes
        shards = []
        for m in range(n_model):
            recv_ids = np.arange(m * n_local, (m + 1) * n_local,
                                 dtype=np.uint32)
            shards.append(counts(cfg, cfg.seed, inst_ids, 0, 0, values,
                                 silent, setup["faulty"], est,
                                 recv_ids=recv_ids, xp=np)[part])
        np.testing.assert_array_equal(np.concatenate(shards, axis=-1),
                                      full[part])


# -------------------------------------------- checkpoint packing-version token

def test_shard_name_packing_token():
    """v1 configs keep the legacy shard name (existing checkpoints stay
    resumable); v2 configs carry the _p2 token."""
    from byzantinerandomizedconsensus_tpu.utils import checkpoint

    v1 = SimConfig(protocol="bracha", n=1024, f=341, instances=10,
                   adversary="adaptive", coin="shared", delivery="urn2")
    assert "_p" not in checkpoint.shard_name(v1, 0, 10)
    v2 = _cfg2048("urn2")
    name = checkpoint.shard_name(v2, 0, 4)
    assert "_p2_s" in name and "_n2048_" in name


def test_stale_packing_token_warning(tmp_path):
    """A wide-n shard whose _pN token names a law other than what the current
    code derives for its n must be flagged, not silently ignored."""
    from byzantinerandomizedconsensus_tpu.utils.sweep import _warn_stale_shards

    # A forged pre-v2 shard name at n=2048 (no _p token => claims v1).
    (tmp_path / "bracha_n2048_f682_adaptive_shared_urn2_s0_i0-500.npz").touch()
    # A healthy v2 shard and a healthy v1 shard: neither may warn.
    (tmp_path / "bracha_n2048_f682_adaptive_shared_urn2_p2_s0_i500-1000.npz").touch()
    (tmp_path / "bracha_n512_f170_adaptive_shared_urn2_s0_i0-500.npz").touch()
    msgs = []
    _warn_stale_shards(tmp_path, "urn2", 256, msgs.append)
    assert len(msgs) == 1
    assert "packing-version token" in msgs[0]
    assert "i0-500" in msgs[0] and "n2048" in msgs[0]


@needs_gxx
@pytest.mark.parametrize("delivery", ["urn2", "urn3"])
def test_virtual_mesh_2x2_vs_native_n2048(delivery):
    """End-to-end sharded bit-match at n=2048 on a (2, 2) virtual mesh
    (parallel/virtual.py: the host-side SPMD emulation of the sharded
    layout — data×model threads, barrier all-gather through the same
    recv_ids/gather seams as parallel/sharded.py) against the native C++
    core: the §2 v2 global-coordinate addressing must make replica shards
    compute exactly the oracle's draws for their rows."""
    cfg = _cfg2048(delivery)
    a = get_backend("virtual:2x2").run(cfg)
    b = get_backend("native").run(cfg)
    np.testing.assert_array_equal(a.rounds, b.rounds)
    np.testing.assert_array_equal(a.decision, b.decision)


@needs_gxx
def test_virtual_mesh_small_grid_vs_native():
    """The virtual-mesh emulation itself, cross-checked at oracle-fast sizes
    over mesh shapes and both protocol/coin families (its n=2048 leg above
    then stands on a verified instrument)."""
    from byzantinerandomizedconsensus_tpu.config import SimConfig as C

    cases = [
        (C(protocol="bracha", n=16, f=5, instances=20, adversary="adaptive_min",
           coin="shared", seed=9, round_cap=64, delivery="keys"), "2x2"),
        (C(protocol="benor", n=8, f=1, instances=20, adversary="byzantine",
           coin="local", seed=4, round_cap=64, delivery="urn2"), "4x2"),
        (C(protocol="bracha", n=12, f=3, instances=16, adversary="crash",
           coin="shared", seed=5, round_cap=64, delivery="urn3"), "1x4"),
        (C(protocol="benor", n=10, f=4, instances=16, adversary="none",
           coin="local", seed=6, round_cap=128, delivery="urn"), "3x2"),
    ]
    for cfg, mesh in cases:
        cfg = cfg.validate()
        a = get_backend(f"virtual:{mesh}").run(cfg)
        b = get_backend("native").run(cfg)
        np.testing.assert_array_equal(a.rounds, b.rounds,
                                      err_msg=f"{mesh} {cfg}")
        np.testing.assert_array_equal(a.decision, b.decision,
                                      err_msg=f"{mesh} {cfg}")


@pytest.mark.parametrize("delivery", ["urn", "urn2", "urn3"])
def test_oracle_counts_match_numpy_at_v2_size(delivery):
    """Single-step delivered-count agreement, scalar python-int oracle vs the
    vectorized uint32 numpy sampler, at a v2 size (n=1536): pins the widened
    §2 v2 range reduction — under the v1 10/22 shifts the numpy product
    (u >> 10)·R wraps uint32 for urn sizes ≥ 2^10 while the oracle's python
    ints never wrap, so any reduction-law drift shows here immediately
    (without waiting for the slow full-instance subsample)."""
    from byzantinerandomizedconsensus_tpu.core.network import Network
    from byzantinerandomizedconsensus_tpu.models import state as state_mod
    from byzantinerandomizedconsensus_tpu.models.adversaries import AdversaryModel
    from byzantinerandomizedconsensus_tpu.ops import delivery_counts_fn

    cfg = SimConfig(protocol="bracha", n=1536, f=511, instances=2,
                    adversary="adaptive_min", coin="shared", seed=11,
                    delivery=delivery).validate()
    assert cfg.pack_version == 2
    inst_ids = np.arange(2, dtype=np.int64)
    adv = AdversaryModel(cfg)
    setup = adv.setup(cfg.seed, inst_ids, xp=np)
    est = state_mod.init_est(cfg, cfg.seed, inst_ids, xp=np)
    values, silent, _ = adv.inject(cfg.seed, inst_ids, 0, 0, est, setup, xp=np)
    c0, c1 = delivery_counts_fn(cfg.delivery)(
        cfg, cfg.seed, inst_ids, 0, 0, values, silent, setup["faulty"], est,
        xp=np)
    oracle_counts = {"urn": "urn_counts", "urn2": "urn2_counts",
                     "urn3": "urn3_counts"}[delivery]
    for k, inst in enumerate(inst_ids):
        net = Network(cfg, cfg.seed, int(inst))
        from byzantinerandomizedconsensus_tpu.core.adversary import make_adversary

        o_adv = make_adversary(cfg, cfg.seed, int(inst))
        oc0, oc1 = getattr(net, oracle_counts)(
            0, 0, [values[k], values[k]], silent[k], strata="minority",
            minority=int(o_adv.observed_minority(est[k])))
        np.testing.assert_array_equal(c0[k], oc0, err_msg=f"inst {inst} c0")
        np.testing.assert_array_equal(c1[k], oc1, err_msg=f"inst {inst} c1")


@needs_gxx
def test_numpy_native_bitmatch_n2048_single_stratum():
    """The non-adaptive (single-stratum) §4b draw path — including the
    packed-carry step_single specialisation and the v2 range reduction at
    full urn sizes R ≈ n−1 > 2^10 — at n=2048, numpy vs native."""
    cfg = _cfg2048("urn", instances=3, adversary="none", round_cap=32)
    a = get_backend("numpy").run(cfg)
    b = get_backend("native").run(cfg)
    np.testing.assert_array_equal(a.rounds, b.rounds)
    np.testing.assert_array_equal(a.decision, b.decision)
