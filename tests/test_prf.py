"""PRF tests: threefry correctness vs JAX's implementation, cross-namespace equality,
and packing injectivity (spec §2)."""

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu.ops import prf


def test_threefry_matches_jax_random():
    """Our 20-round threefry2x32 must equal jax._src.prng.threefry_2x32 word 0."""
    import jax
    import jax.numpy as jnp
    from jax._src import prng as jax_prng

    k0, k1 = np.uint32(0x12345678), np.uint32(0x9ABCDEF0)
    x0 = np.arange(64, dtype=np.uint32) * np.uint32(2654435761)
    x1 = np.arange(64, dtype=np.uint32) + np.uint32(7)

    ours = prf.threefry2x32(k0, k1, x0, x1, xp=np)
    ref = jax_prng.threefry_2x32(jnp.array([k0, k1]), jnp.stack([jnp.asarray(x0), jnp.asarray(x1)]))
    np.testing.assert_array_equal(ours, np.asarray(ref)[0])


def test_numpy_jnp_agree():
    import jax.numpy as jnp

    out_np = prf.prf_u32(1234567890123, np.arange(100)[:, None], 7, 2,
                         np.arange(8)[None, :], 3, prf.SCHED, xp=np)
    out_jnp = prf.prf_u32(1234567890123, jnp.arange(100)[:, None], 7, 2,
                          jnp.arange(8)[None, :], 3, prf.SCHED, xp=jnp)
    np.testing.assert_array_equal(out_np, np.asarray(out_jnp))
    assert out_np.dtype == np.uint32


def test_scalar_no_warning():
    with np.errstate(over="raise"):
        v = prf.prf_bit(0, 5, 3, prf.COIN_STEP, 0, 0, prf.SHARED_COIN, xp=np)
    assert int(v) in (0, 1)


def test_purpose_and_field_separation():
    """Different coordinates give different draws (whp); same coordinates identical."""
    seeds = []
    for purpose in (prf.INIT_EST, prf.LOCAL_COIN, prf.SHARED_COIN, prf.SCHED):
        for rnd in (0, 1):
            for recv in (0, 1):
                seeds.append(int(prf.prf_u32(42, 3, rnd, 0, recv, 1, purpose, xp=np)))
    assert len(set(seeds)) == len(seeds)
    a = prf.prf_u32(42, 3, 1, 0, 1, 1, prf.SCHED, xp=np)
    b = prf.prf_u32(42, 3, 1, 0, 1, 1, prf.SCHED, xp=np)
    assert int(a) == int(b)


def test_bit_balance():
    """Coin bits are roughly fair (binomial 4-sigma bound)."""
    bits = prf.prf_bit(9, np.arange(20000), 0, prf.COIN_STEP, 0, 0, prf.SHARED_COIN, xp=np)
    mean = float(bits.astype(np.float64).mean())
    assert abs(mean - 0.5) < 4 * 0.5 / np.sqrt(20000)
