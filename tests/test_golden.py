"""Golden-vector regression (spec §8): every backend must reproduce the frozen
per-instance outputs exactly. A mismatch means either a backend bug or an
intentional spec change (then regen via ``python -m spec.golden.regen``)."""

import pathlib

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu import Simulator

from spec.golden.regen import GOLDEN_CONFIGS, PATH


@pytest.mark.parametrize("name", sorted(GOLDEN_CONFIGS))
@pytest.mark.parametrize("backend", ["cpu", "numpy", "jax"])
def test_golden(name, backend):
    if not PATH.exists():
        pytest.fail("golden.npz missing — run `python -m spec.golden.regen`")
    data = np.load(PATH)
    cfg = GOLDEN_CONFIGS[name]
    res = Simulator(cfg, backend).run()
    np.testing.assert_array_equal(res.rounds, data[f"{name}__rounds"])
    np.testing.assert_array_equal(res.decision, data[f"{name}__decision"])
