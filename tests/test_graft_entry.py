"""Driver entry-point module: the forced-CPU helper must announce every
degradation on stderr (VERDICT r4 weak #4) — the dry-run's output is the
driver's multi-chip artifact of record and must never silently change meaning
when a private JAX API drifts."""

import pathlib
import sys

import pytest

_REPO_ROOT = str(pathlib.Path(__file__).resolve().parents[1])


@pytest.fixture()
def graft_entry(monkeypatch):
    if _REPO_ROOT not in sys.path:
        sys.path.insert(0, _REPO_ROOT)
    import __graft_entry__ as ge

    # conftest already forces JAX_PLATFORMS=cpu, so the helper's gate is open.
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    return ge


def test_force_cpu_warns_on_import_failure(graft_entry, monkeypatch, capsys):
    def boom():
        raise ImportError("private API moved")

    monkeypatch.setattr(graft_entry, "_import_xla_bridge", boom)
    graft_entry._force_cpu_if_requested()  # must not raise
    err = capsys.readouterr().err
    assert "WARNING forced-CPU setup degraded" in err
    assert "private API moved" in err


def test_force_cpu_warns_on_missing_factories(graft_entry, monkeypatch, capsys):
    class FakeBridge:
        # no _backend_factories dict, no backends_are_initialized
        pass

    monkeypatch.setattr(graft_entry, "_import_xla_bridge", lambda: FakeBridge())
    graft_entry._force_cpu_if_requested()  # must not raise
    err = capsys.readouterr().err
    assert "_backend_factories missing or not a dict" in err


def test_force_cpu_noop_without_cpu_request(graft_entry, monkeypatch, capsys):
    monkeypatch.setenv("JAX_PLATFORMS", "")
    monkeypatch.setenv("XLA_FLAGS", "")

    def boom():  # must never be reached when the env doesn't ask for CPU
        raise AssertionError("helper ran without a CPU request")

    monkeypatch.setattr(graft_entry, "_import_xla_bridge", boom)
    graft_entry._force_cpu_if_requested()
    assert capsys.readouterr().err == ""
