"""Round-17 adversary hunter: space admissibility, strategy determinism,
archive replay (numpy + jax, against the committed regressions artifact),
the bounded-WorkFeed backpressure seam, and a seeded in-process mini-hunt
smoke over the real serving stack."""

import json
import pathlib
import random

import pytest

from byzantinerandomizedconsensus_tpu.backends.batch import FusedBucket
from byzantinerandomizedconsensus_tpu.backends.compaction import (
    CompactionPolicy, WorkFeed, WorkFeedOverflow)
from byzantinerandomizedconsensus_tpu.config import SimConfig
from byzantinerandomizedconsensus_tpu.hunt import archive as hunt_archive
from byzantinerandomizedconsensus_tpu.hunt import space as hunt_space
from byzantinerandomizedconsensus_tpu.hunt.archive import Archive
from byzantinerandomizedconsensus_tpu.hunt.hunter import Hunter, fitness_of
from byzantinerandomizedconsensus_tpu.hunt.space import SearchSpace
from byzantinerandomizedconsensus_tpu.hunt.strategies import (
    STRATEGIES, make_strategy)
from byzantinerandomizedconsensus_tpu.tools import sampler

ROOT = pathlib.Path(__file__).resolve().parents[1]
_POLICY = CompactionPolicy(width=8, segment=1)


def _fake_fitness(cfg) -> float:
    """A deterministic stand-in evaluator: a pure function of the genome,
    so strategy determinism can be tested without a grid."""
    blob = json.dumps(hunt_space.encode(cfg), sort_keys=True)
    return float(sum(blob.encode()) % 997)


# ---- space ----------------------------------------------------------------


def test_space_shares_the_chaos_sampler_laws():
    """The hunt space draws THROUGH tools/sampler.py — same draw sequence,
    same (generator_version, seed) contract as `brc-tpu chaos`."""
    sp = SearchSpace()
    assert sp.generator_version == sampler.GENERATOR_VERSION
    assert sp.sample(random.Random(123)) == sampler.random_config(
        random.Random(123), chaos=True)


def test_space_candidates_are_admissible_everywhere():
    """Sampled, mutated, crossed and region-pinned candidates all pass
    validate() and stay inside the serving envelope (one fused tier,
    round_cap within the default feed ceiling)."""
    sp = SearchSpace()
    rng = random.Random(42)
    pool = [sp.sample(rng) for _ in range(30)]
    pool.extend(sp.mutate(cfg, rng) for cfg in list(pool))
    for a, b in zip(pool[:20], pool[20:40]):
        pool.append(sp.crossover(a, b, rng))
    for region in sp.regions():
        pool.append(sp.sample_region(region, rng))
    for cfg in pool:
        cfg.validate()  # raises on an inadmissible candidate
        assert cfg.n <= sp.max_n
        assert cfg.round_cap <= 128
        assert FusedBucket.of(cfg) in sp.buckets()


def test_space_committee_scale_wing():
    """SearchSpace(committee_scale=True) (round 23): §10 committee genomes
    ride the pow2 tiers past n ≤ 40, every candidate decodes admissibly
    (sortition f ceiling included), and the compiled-program universe stays
    closed at 10 + 2·len(COMMITTEE_N_TIERS)."""
    sp = SearchSpace(committee_scale=True)
    buckets = sp.buckets()
    tiers = hunt_space.COMMITTEE_N_TIERS
    assert len(buckets) == 10 + 2 * len(tiers)
    assert len(set(buckets)) == len(buckets)
    assert all(t & (t - 1) == 0 for t in tiers)  # pow2, tier-exact
    assert 1_000 <= tiers[0] and tiers[-1] <= 131_072

    big = 0
    for seed in range(120):
        rng = random.Random(seed)
        base = SimConfig(protocol="bracha", n=20, f=3, instances=8,
                         adversary="adaptive", delivery="committee",
                         seed=seed, round_cap=32).validate()
        m = sp.mutate(base, rng)
        m.validate()
        assert FusedBucket.of(m) in buckets
        if m.n > sp.max_n:
            big += 1
            assert m.delivery == "committee" and m.n in tiers
            # crossing with a full-mesh parent must clamp n back under
            # the fold — delivery gates the committee wing
            child = sp.crossover(m, sp.sample(rng), rng)
            child.validate()
            assert child.delivery == "committee" or child.n <= sp.max_n
            assert FusedBucket.of(child) in buckets
    assert big >= 1  # the wing is actually reachable

    # the default space is byte-for-byte the legacy universe
    assert len(SearchSpace().buckets()) == 10
    assert SearchSpace().doc()["committee_n_tiers"] == []
    """n ≤ 40 folds everything to one tier: the whole compiled-program
    universe is 2 protocols × 5 deliveries (committee joined in round 19) —
    what makes a complete warm-up (and hence the 0-steady-state-recompile
    pin) possible."""
    sp = SearchSpace()
    buckets = sp.buckets()
    assert len(buckets) == 10
    assert len(set(buckets)) == 10
    rng = random.Random(7)
    for _ in range(60):
        assert FusedBucket.of(sp.sample(rng)) in buckets


def test_space_region_pinning_survives_repair():
    """sample_region must return a candidate IN the region (the bandit
    attributes tells by the candidate's own axes) even where the forced
    adversary needs a larger shape."""
    sp = SearchSpace()
    rng = random.Random(5)
    for region in sp.regions():
        for _ in range(5):
            cfg = sp.sample_region(region, rng)
            assert (cfg.adversary, cfg.delivery) == region


def test_genome_roundtrip():
    sp = SearchSpace()
    cfg = sp.sample(random.Random(9))
    assert hunt_space.decode(hunt_space.encode(cfg)) == cfg


# ---- strategies -----------------------------------------------------------


@pytest.mark.parametrize("name", sorted(STRATEGIES))
def test_strategy_deterministic_from_name_and_seed(name):
    """Two strategies built from the same (strategy, seed) produce the
    identical candidate stream under the identical tell stream — the
    reproducibility contract the committed artifact rests on."""
    def run(seed):
        st = make_strategy(name, SearchSpace(), seed)
        out = []
        for _ in range(40):
            cfg = st.ask()
            st.tell(cfg, _fake_fitness(cfg))
            out.append(hunt_space.encode(cfg))
        return out, st.best_fitness

    a, best_a = run(11)
    b, best_b = run(11)
    assert a == b
    assert best_a == best_b
    c, _ = run(12)
    assert a != c  # a different seed moves the stream


@pytest.mark.parametrize("name", sorted(STRATEGIES))
def test_strategy_candidates_admissible(name):
    st = make_strategy(name, SearchSpace(), 3)
    for _ in range(30):
        cfg = st.ask()
        cfg.validate()
        st.tell(cfg, _fake_fitness(cfg))


def test_bandit_halves_regions():
    sp = SearchSpace()
    st = make_strategy("bandit", sp, 1)
    n0 = len(st._active)
    for _ in range(len(sp.regions()) * st.RUNG0):
        cfg = st.ask()
        st.tell(cfg, _fake_fitness(cfg))
    assert len(st._active) == max(1, n0 // 2)
    assert st._rung == 1


def test_cma_adapts_and_stays_deterministic():
    """The round-19 continuous strategy: generations close every λ tells,
    the latent mean/step-sizes move off their initial point, categorical
    tables stay normalized with the exploration floor, and the whole
    trajectory (including the internal state) is a pure function of
    (strategy, seed) + tell sequence."""
    def run(seed):
        st = make_strategy("cma", SearchSpace(), seed)
        for _ in range(3 * st.LAMBDA):
            cfg = st.ask()
            st.tell(cfg, _fake_fitness(cfg))
        return st

    a, b = run(5), run(5)
    assert a.generation == 3
    assert a.doc() == b.doc()
    assert a._mean == b._mean and a._sigma == b._sigma
    assert a._tables == b._tables
    # adaptation actually happened: some axis moved off the init point
    assert a._mean != [0.5] * len(a.AXES) or \
        a._sigma != [a.SIGMA0] * len(a.AXES)
    for axis, probs in a._tables.items():
        assert abs(sum(probs) - 1.0) < 1e-9
        assert min(probs) >= a.CAT_FLOOR - 1e-9
    # in-flight pipelined asks don't leak: pending drains as tells arrive
    assert not a._pending


def test_unknown_strategy_rejected():
    with pytest.raises(ValueError, match="unknown strategy"):
        make_strategy("gradient", SearchSpace(), 0)


# ---- archive --------------------------------------------------------------


def test_archive_keeps_topk_sorted_and_dedupes():
    sp = SearchSpace()
    rng = random.Random(2)
    a = Archive(4)
    cfgs = [sp.sample(rng) for _ in range(12)]
    for i, cfg in enumerate(cfgs):
        a.offer(cfg, float(i), [1, 2], [1, 1])
    assert len(a) == 4
    fits = [e["fitness"] for e in a.entries()]
    assert fits == sorted(fits, reverse=True)
    assert fits == [11.0, 10.0, 9.0, 8.0]
    # re-offering an archived genome is a no-op (distinct worst cases only)
    assert a.offer(cfgs[-1], 99.0, [1, 2], [1, 1]) is False
    assert len(a) == 4


def test_archive_replay_detects_drift():
    sp = SearchSpace()
    cfg = sp.sample(random.Random(31))
    from byzantinerandomizedconsensus_tpu.backends import get_backend
    res = get_backend("numpy").run(cfg)
    a = Archive(2)
    a.offer(cfg, 5.0, res.rounds, res.decision)
    entry = a.best()
    assert hunt_archive.replay(entry, backend="numpy")["ok"]
    tampered = dict(entry)
    tampered["rounds"] = [r + 1 for r in entry["rounds"]]
    verdict = hunt_archive.replay(tampered, backend="numpy")
    assert not verdict["ok"] and verdict["mismatches"] > 0


def _committed_regressions():
    p = ROOT / "artifacts" / "hunt_regressions.json"
    if not p.exists():
        pytest.skip("no committed hunt_regressions.json")
    return json.loads(p.read_text())


def test_committed_archive_replays_bit_identically_numpy():
    """Every archived worst case in the committed artifact replays
    bit-identically on the numpy reference — the regression-pin contract
    (the way adaptive_min became a preset)."""
    doc = _committed_regressions()
    assert doc["entries"], "committed archive is empty"
    for entry in doc["entries"]:
        verdict = hunt_archive.replay(entry, backend="numpy")
        assert verdict["ok"], (entry["genome"], verdict)


def test_committed_archive_replays_bit_identically_jax():
    """The top archived worst case replays bit-identically on the jax
    backend too — cross-backend, same arrays (the soak's differential
    claim, applied to the hunter's finds)."""
    doc = _committed_regressions()
    verdict = hunt_archive.replay(doc["entries"][0], backend="jax")
    assert verdict["ok"], verdict


# ---- fitness --------------------------------------------------------------


def test_fitness_weights_liveness_cliff():
    cfg = SimConfig(protocol="benor", n=7, f=1, instances=4,
                    adversary="crash", round_cap=64).validate()
    decided = fitness_of(cfg, [3, 5, 4, 4], [1, 0, 1, 1])
    capped = fitness_of(cfg, [64, 64, 64, 64], [2, 2, 2, 2])
    assert decided["undecided_fraction"] == 0.0
    assert capped["undecided_fraction"] == 1.0
    # an undecided-at-cap population dominates any decided one
    assert capped["fitness"] > decided["fitness"] + cfg.round_cap / 2


# ---- bounded WorkFeed (backpressure satellite) ----------------------------


def test_workfeed_default_stays_unbounded():
    feed = WorkFeed(round_cap_ceiling=64)
    assert feed.max_depth is None
    cfg = SimConfig(protocol="benor", n=4, f=0, instances=1,
                    round_cap=32).validate()
    for _ in range(300):  # far past any plausible implicit bound
        feed.push(cfg)
    assert feed.pending() == 300


def test_workfeed_bounded_rejects_overflow_by_name():
    feed = WorkFeed(round_cap_ceiling=64, max_depth=2)
    cfg = SimConfig(protocol="benor", n=4, f=0, instances=1,
                    round_cap=32).validate()
    feed.push(cfg)
    feed.push(cfg)
    with pytest.raises(WorkFeedOverflow, match="max_depth"):
        feed.push(cfg)
    # a drain (pull) frees depth again
    assert len(feed.pull()) == 2
    feed.push(cfg)
    with pytest.raises(ValueError, match="max_depth"):
        WorkFeed(max_depth=0)


# ---- the closed loop ------------------------------------------------------


@pytest.fixture()
def server():
    from byzantinerandomizedconsensus_tpu.serve.server import ConsensusServer
    srv = ConsensusServer(backend="jax", policy=_POLICY)
    srv.start()
    yield srv
    srv.shutdown(drain=True)


@pytest.mark.slow
def test_mini_hunt_smoke_pipelined(server):
    """A seeded in-process mini-hunt over the real serving stack: budget
    harvested exactly, all archive entries admissible, elite fitness
    monotone non-increasing down the archive, best == archive head, and
    the safety alarm quiet."""
    sp = SearchSpace()
    hunter = Hunter(server, make_strategy("evolution", sp, 5), space=sp,
                    archive=Archive(4), generation=6, pipelined=True,
                    check_invariants=True)
    stats = hunter.run(18)
    assert stats["evaluations"] == 18
    assert stats["generations"] == 3
    assert stats["violations"] == 0
    assert 1 <= stats["archive_size"] <= 4
    fits = [e["fitness"] for e in hunter.archive.entries()]
    assert fits == sorted(fits, reverse=True)
    assert stats["best_fitness"] == pytest.approx(fits[0])
    for entry in hunter.archive.entries():
        hunt_space.decode(entry["genome"])  # replayable genome
    # the stats dict is a valid schema-v1.8 hunt block
    from byzantinerandomizedconsensus_tpu.obs import record
    stats["steady_state_compiles"] = 0
    doc = record.new_record("hunt")
    doc["hunt"] = record.hunt_block(stats)
    assert record.validate_record(doc) == []


def test_mini_hunt_reply_invariants_flow_to_hunter(server):
    """check_invariants=True rides the round-17 serve satellite: the reply
    record itself carries the verdict block (no client second pass)."""
    sp = SearchSpace()
    cfg = sp.sample(random.Random(77))
    rec = server.submit(cfg, check_invariants=True).wait(timeout=600)
    inv = rec["invariants"]
    assert inv["checked_instances"] == cfg.instances
    assert inv["agreement_ok"] is True and inv["validity_ok"] is True
    assert inv["violations"] == 0
    # and stays opt-in: a plain submit carries no invariants block
    rec2 = server.submit(cfg).wait(timeout=600)
    assert "invariants" not in rec2
