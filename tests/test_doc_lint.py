"""Observability doc lint (round-13 satellite, tier-1).

docs/OBSERVABILITY.md is the contract for every telemetry surface the repo
emits: span/event kinds (obs/trace.py call sites) and versioned record-block
keys (obs/record.py). Schema growth has so far been caught by hand-written
per-round tests; this lint makes the catch mechanical — a new ``_trace.span(
"new.kind", ...)`` call or a new ``*_BLOCK_KEYS`` field that is not
documented fails tier-1, so the docs cannot silently fall behind the code.
"""

import pathlib
import re

import byzantinerandomizedconsensus_tpu as pkg
from byzantinerandomizedconsensus_tpu.utils.rounds import repo_root

_PKG_DIR = pathlib.Path(pkg.__file__).parent

#: Direct span/event emissions: ``_trace.span("kind", ...)`` /
#: ``_trace.event("kind", ...)`` — including the conditional-expression
#: form ``span("a.b" if cond else "c.d", ...)``.
_EMIT = re.compile(
    r"(?:_trace|trace)\.(?:span|event)\(\s*\n?\s*"
    r"\"([a-z0-9_.]+)\""
    r"(?:\s+if\s+\w+\s*\n?\s*else\s+\"([a-z0-9_.]+)\")?")


def _source_files():
    files = [p for p in _PKG_DIR.rglob("*.py")]
    files.append(pathlib.Path(repo_root()) / "bench.py")
    return files


def emitted_span_kinds() -> set:
    kinds = set()
    for p in _source_files():
        for m in _EMIT.finditer(p.read_text()):
            kinds.update(k for k in m.groups() if k)
    return kinds


def test_span_kind_census_is_nontrivial_and_complete():
    """The regex harvest must see the known seams — if a refactor moves the
    call sites out of its reach, this assert fails before the doc check
    can silently pass on an empty set."""
    kinds = emitted_span_kinds()
    for expected in ("batch.dispatch", "batch.bucket", "backend.run",
                     "compile_cache.compile", "compile_cache.hit",
                     "compile_cache.evict", "compaction.init",
                     "compaction.segment", "compaction.drain",
                     "compaction.refill", "compact.run", "program.compile",
                     "chaos.start", "chaos.progress", "chaos.skip",
                     "chaos.child.jax", "serve.request", "serve.admit",
                     "serve.dispatch", "serve.reply", "fleet.spawn",
                     "fleet.backoff", "fleet.route", "fleet.dispatch",
                     "fleet.steal", "fleet.worker_lost", "fleet.readmit",
                     "fleet.shutdown", "hunt.run", "hunt.generation",
                     "hunt.harvest", "hunt.best", "hunt.violation",
                     "hunt.done", "serve.backpressure", "serve.cancel",
                     "serve.rotate", "compaction.cancel",
                     "compaction.reseed", "serve.session_open",
                     "serve.session_slot", "serve.session_done",
                     "serve.recover", "serve.recovered", "fleet.retire",
                     "fleet.respawn", "autoscale.start", "autoscale.stop",
                     "autoscale.up", "autoscale.down",
                     "compaction.snapshot", "compaction.restore",
                     "compaction.import", "serve.preempt", "serve.park",
                     "serve.resume", "serve.export", "serve.import",
                     "fleet.migrate"):
        assert expected in kinds, (expected, sorted(kinds))
    assert len(kinds) >= 69


def test_every_emitted_span_kind_is_documented():
    doc = (pathlib.Path(repo_root()) / "docs/OBSERVABILITY.md").read_text()
    # The doc spells families compactly ("`compile_cache.compile` / `.hit`
    # / `.evict`", "`chaos.start` / `.spawn` / ..."): a kind counts as
    # documented when its full name appears, or its family head appears in
    # backticked dotted form AND its tail appears as a backticked `.suffix`
    # shorthand — anything looser lets an undocumented kind ride a word
    # that merely occurs in prose.
    missing = []
    for kind in sorted(emitted_span_kinds()):
        if kind in doc:
            continue
        head, _, tail = kind.rpartition(".")
        if head and f"`{head}." in doc and f"`.{tail}`" in doc:
            continue
        missing.append(kind)
    assert missing == [], (
        f"span/event kinds emitted by the code but absent from "
        f"docs/OBSERVABILITY.md: {missing} — document them in the "
        "instrumented-seams table (§3c/§3d)")


#: Registered metric names: ``_metrics.counter("brc_...", ...)`` /
#: ``.gauge(`` / ``.histogram(`` call sites (obs/metrics.py accessors) —
#: the name may land on the line after the call.
_METRIC = re.compile(
    r"(?:_metrics|metrics)\.(?:counter|gauge|histogram)\(\s*"
    r"\"(brc_[a-z0-9_]+)\"")


def registered_metric_names() -> set:
    names = set()
    for p in _source_files():
        names.update(_METRIC.findall(p.read_text()))
    return names


def test_metric_name_census_is_nontrivial_and_complete():
    """The regex harvest must see the known metric families — a refactor
    that moves registration out of its reach fails here before the doc
    check can pass vacuously on an empty set."""
    names = registered_metric_names()
    for expected in ("brc_serve_admitted_total", "brc_serve_rejected_total",
                     "brc_serve_replied_total", "brc_serve_failed_total",
                     "brc_serve_request_latency_seconds",
                     "brc_serve_queue_wait_seconds",
                     "brc_serve_service_seconds",
                     "brc_compile_cache_hits_total",
                     "brc_compile_cache_compiles_total",
                     "brc_compaction_segments_total",
                     "brc_compaction_occupancy",
                     "brc_consensus_rounds", "brc_consensus_decided_total",
                     "brc_consensus_fault_silenced_total",
                     "brc_fleet_workers_alive", "brc_fleet_worker_up",
                     "brc_fleet_steals_total", "brc_fleet_respawns_total",
                     "brc_hunt_generations_total",
                     "brc_hunt_evaluations_total",
                     "brc_hunt_violations_total", "brc_hunt_best_fitness",
                     "brc_hunt_archive_size",
                     "brc_serve_invariant_checks_total",
                     "brc_serve_invariant_violations_total",
                     "brc_serve_tenant_served_weight_total",
                     "brc_serve_tenant_inflight",
                     "brc_serve_cancel_requested_total",
                     "brc_serve_cancelled_total",
                     "brc_serve_cancel_too_late_total",
                     "brc_serve_deadline_met_total",
                     "brc_serve_deadline_missed_total",
                     "brc_session_reseeds_total", "brc_session_opened_total",
                     "brc_session_slots_replied_total",
                     "brc_session_completed_total",
                     "brc_wal_records_total", "brc_wal_recovered_total",
                     "brc_fleet_retired_total",
                     "brc_autoscale_target_workers",
                     "brc_autoscale_up_total", "brc_autoscale_down_total",
                     "brc_preempt_parked_total", "brc_preempt_resumed_total",
                     "brc_lane_migrated_total"):
        assert expected in names, (expected, sorted(names))
    assert len(names) >= 57


def test_every_registered_metric_is_documented():
    """Every metric name the code registers must appear in
    docs/OBSERVABILITY.md (§3g metric table) — the live metrics plane is a
    contract surface like the span kinds above it."""
    doc = (pathlib.Path(repo_root()) / "docs/OBSERVABILITY.md").read_text()
    missing = [n for n in sorted(registered_metric_names()) if n not in doc]
    assert missing == [], (
        f"metric names registered by the code but absent from "
        f"docs/OBSERVABILITY.md: {missing} — add them to the §3g metric "
        "table")


def test_every_record_block_key_is_documented():
    """Every versioned record block name and every required field of the
    *_BLOCK_KEYS registries (obs/record.py) must appear in
    docs/OBSERVABILITY.md — the mechanical form of the per-round schema
    sections."""
    from byzantinerandomizedconsensus_tpu.obs import record

    doc = (pathlib.Path(repo_root()) / "docs/OBSERVABILITY.md").read_text()
    blocks = {
        "compile_cache": ("compiles", "hits", "evictions"),
        "compaction": record.COMPACTION_BLOCK_KEYS,
        "trace": record.TRACE_BLOCK_KEYS,
        "programs": record.PROGRAMS_BLOCK_KEYS,
        "serve": record.SERVE_BLOCK_KEYS,
        "fleet": record.FLEET_BLOCK_KEYS,
        "metrics": record.METRICS_BLOCK_KEYS,
        "hunt": record.HUNT_BLOCK_KEYS,
        "hostile": record.HOSTILE_BLOCK_KEYS,
        "committee": record.COMMITTEE_BLOCK_KEYS,
        "fused": record.FUSED_BLOCK_KEYS,
        "session": record.SESSION_BLOCK_KEYS,
        "elastic": record.ELASTIC_BLOCK_KEYS,
        "lanestate": record.LANESTATE_BLOCK_KEYS,
        "preempt": record.PREEMPT_BLOCK_KEYS,
        "counters": ("supported", "totals"),
    }
    missing = []
    for block, keys in blocks.items():
        if f'"{block}"' not in doc and f"`{block}`" not in doc \
                and f"**{block}" not in doc and f"{block} block" not in doc:
            missing.append(block)
        for key in keys:
            if key not in doc:
                missing.append(f"{block}.{key}")
    assert missing == [], (
        f"record blocks/keys emitted by obs/record.py but absent from "
        f"docs/OBSERVABILITY.md: {missing}")
