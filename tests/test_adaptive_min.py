"""adaptive_min (spec §6.4b): the measured-strongest count-level scheduler as a
product adversary.

Round 4's scheduler-strength map (tools/schedstrength.py, spec §6.4) found
global-minority-first delivery weakly dominates the shipped class rule at every
measured point and is receiver-independent — i.e. expressible in the §4b urn
model. This file pins the shipped variant: 4-way bit-match across
implementation stacks on both delivery models, exact equivalence with the
experiment arm that motivated it, sharded-path equality, protocol properties,
and the stalling power that justifies shipping it.
"""

import dataclasses

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu import SimConfig, Simulator

CONFIGS = [
    SimConfig(protocol="bracha", n=16, f=5, instances=40, adversary="adaptive_min",
              coin="shared", round_cap=64, seed=9, delivery="urn"),
    SimConfig(protocol="bracha", n=16, f=5, instances=24, adversary="adaptive_min",
              coin="local", round_cap=32, seed=9, delivery="urn"),
    SimConfig(protocol="benor", n=11, f=2, instances=40, adversary="adaptive_min",
              coin="local", round_cap=64, seed=3, delivery="urn"),
    SimConfig(protocol="bracha", n=16, f=5, instances=24, adversary="adaptive_min",
              coin="local", round_cap=32, seed=9, delivery="keys"),
    SimConfig(protocol="benor", n=11, f=2, instances=24, adversary="adaptive_min",
              coin="shared", round_cap=64, seed=3, delivery="keys"),
]

# Pallas legs run on the shared-coin configs (few rounds — interpret-mode
# cost scales with executed steps), one per delivery model; the in-kernel
# §6.4b minority derivation runs every step either way.
_PALLAS_IDX = {0, 4}


@pytest.mark.parametrize(
    "idx,cfg", list(enumerate(CONFIGS)),
    ids=lambda x: f"{x.protocol}-{x.coin}-{x.delivery}" if isinstance(x, SimConfig) else None)
def test_bitmatch_across_stacks(idx, cfg):
    """cpu oracle == numpy == jax == native (and the Pallas kernels on the two
    configs that exercise their in-kernel minority derivation)."""
    ref = Simulator(cfg, "cpu").run()
    backends = ["numpy", "jax", "native"]
    if idx in _PALLAS_IDX:
        backends.append("jax_pallas")
    for backend in backends:
        got = Simulator(cfg, backend).run()
        np.testing.assert_array_equal(ref.rounds, got.rounds, err_msg=backend)
        np.testing.assert_array_equal(ref.decision, got.decision, err_msg=backend)


def test_equals_schedstrength_minority_arm():
    """The shipped adversary IS the experiment arm that motivated it: an
    adaptive_min keys run bit-equals ScheduledAdaptive(bias_mode='minority')
    run on the otherwise-identical adaptive config (the adversary kind enters
    no PRF stream, so the trajectories must be identical draw-for-draw)."""
    from byzantinerandomizedconsensus_tpu.backends.numpy_backend import NumpyBackend
    from byzantinerandomizedconsensus_tpu.tools.schedstrength import ScheduledAdaptive

    cfg_min = SimConfig(protocol="bracha", n=16, f=5, instances=60,
                        adversary="adaptive_min", coin="local", round_cap=32,
                        seed=0, delivery="keys").validate()
    cfg_cls = dataclasses.replace(cfg_min, adversary="adaptive")
    shipped = Simulator(cfg_min, "numpy").run()
    arm = NumpyBackend().run_with_adversary(
        cfg_cls, ScheduledAdaptive(cfg_cls, "minority"))
    np.testing.assert_array_equal(shipped.rounds, arm.rounds)
    np.testing.assert_array_equal(shipped.decision, arm.decision)


def test_sharded_bitmatch():
    from byzantinerandomizedconsensus_tpu.parallel.mesh import make_mesh
    from byzantinerandomizedconsensus_tpu.parallel.sharded import JaxShardedBackend

    cfg = CONFIGS[0]
    ref = Simulator(cfg, "cpu").run()
    got = JaxShardedBackend(mesh=make_mesh(n_data=4, n_model=2)).run(cfg)
    np.testing.assert_array_equal(ref.rounds, got.rounds)
    np.testing.assert_array_equal(ref.decision, got.decision)


def test_agreement_and_validity():
    """Agreement is asserted inside every cpu-oracle run (backends/cpu.py);
    validity via unanimous starts — the §6.4b liveness argument's base case."""
    for cfg in CONFIGS[:2]:
        for init, expect in (("all0", 0), ("all1", 1)):
            c = dataclasses.replace(cfg, init=init, instances=20)
            r = Simulator(c, "cpu").run()
            decided = r.decision != 2
            assert np.all(r.decision[decided] == expect), (cfg, init)


def test_stalling_power_anchor():
    """Why it ships: at the n=16 local-coin anchor adaptive_min stalls ≥90% of
    instances to the cap — the §6.4 measured map's 'weakly dominates every
    rule' row, pinned at product scale (numpy, deterministic)."""
    cfg = SimConfig(protocol="bracha", n=16, f=5, instances=80,
                    adversary="adaptive_min", coin="local", round_cap=32,
                    seed=0, delivery="urn").validate()
    res = Simulator(cfg, "numpy").run()
    assert float((res.decision == 2).mean()) >= 0.9
    # and the shared coin (the stub of BASELINE.json:10) still defeats it
    fast = Simulator(dataclasses.replace(cfg, coin="shared"), "numpy").run()
    assert float(fast.rounds.mean()) < 4


def test_validate_bounds():
    """adaptive_min is a lying adversary: benor needs n > 5f (Protocol B)."""
    with pytest.raises(ValueError):
        SimConfig(protocol="benor", n=10, f=2, adversary="adaptive_min").validate()
    SimConfig(protocol="benor", n=11, f=2, adversary="adaptive_min").validate()
    with pytest.raises(ValueError):
        SimConfig(protocol="bracha", n=9, f=3, adversary="adaptive_min").validate()
