"""Plot-helper smoke tests: the figures render and the histogram slicing is
robust when the cap bucket is the last nonzero bin (regression for the
off-by-one found while rendering the coin-contrast artifact)."""

import pytest

matplotlib = pytest.importorskip("matplotlib")

from byzantinerandomizedconsensus_tpu.utils import plot


def _summary(cap_saturated: bool):
    hist = [0] * 17
    if cap_saturated:
        hist[-1] = 40  # every instance in the overflow bucket at the cap
    else:
        hist[1], hist[2] = 25, 15
    return {"protocol": "bracha", "adversary": "adaptive", "coin": "shared",
            "f": 5, "round_histogram": hist}


def test_plot_sweep_cap_bucket_last(tmp_path):
    out = {16: _summary(cap_saturated=True), 32: _summary(cap_saturated=False)}
    plot.plot_sweep(out, tmp_path / "sweep.png")
    assert (tmp_path / "sweep.png").stat().st_size > 0


def test_plot_coin_contrast(tmp_path):
    shared = {16: _summary(False)}
    local = {16: _summary(True)}
    plot.plot_coin_contrast(shared, local, tmp_path / "c.png")
    assert (tmp_path / "c.png").stat().st_size > 0
