"""Round-15 fleet dispatcher tests (serve/fleet.py, serve/worker.py).

Tier-1 layer: thread-mode routing / affinity / work-stealing semantics and
the pure placement seam — in-process, no subprocess spawns. Slow layer: the
real subprocess fleet (spawn ladder, stdio protocol, per-worker traces) and
the worker-loss re-admission pin: kill a worker mid-stream and every
in-flight request must be re-admitted to survivors with bit-identical
replies under the same fleet ids.
"""

import dataclasses
import json
import time
from types import SimpleNamespace

import pytest

from byzantinerandomizedconsensus_tpu.backends.base import get_backend
from byzantinerandomizedconsensus_tpu.backends.compaction import CompactionPolicy
from byzantinerandomizedconsensus_tpu.config import SimConfig
from byzantinerandomizedconsensus_tpu.obs import record
from byzantinerandomizedconsensus_tpu.parallel import mesh as pmesh
from byzantinerandomizedconsensus_tpu.serve import admission
from byzantinerandomizedconsensus_tpu.serve.fleet import FleetServer, _policy_spec
from byzantinerandomizedconsensus_tpu.tools import trace as trace_tool

_POLICY = CompactionPolicy(width=8, segment=1)

#: Two genuinely distinct buckets (different protocols — benor and bracha
#: never fuse): the heavy one holds a worker long enough for the light
#: worker to go idle and steal.
_HEAVY = SimConfig(protocol="bracha", n=10, f=3, instances=24, seed=77,
                   round_cap=64, delivery="urn", adversary="byzantine")
_LIGHT = SimConfig(protocol="benor", n=4, f=1, instances=2, seed=3,
                   round_cap=16)
_THIRD = SimConfig(protocol="benor", n=9, f=3, instances=6, seed=21,
                   round_cap=64, adversary="crash", init="split")


def _offline(cfg):
    ref = get_backend("numpy").run(cfg)
    return [int(r) for r in ref.rounds], [int(d) for d in ref.decision]


def test_policy_spec_round_trips():
    p = CompactionPolicy(width=64, segment=2, refill_threshold=0.25)
    assert CompactionPolicy.parse(_policy_spec(p)) == p
    # width=None (unbounded lanes) must survive the argv spelling too
    q = CompactionPolicy(width=None, segment=1)
    assert CompactionPolicy.parse(_policy_spec(q)) == q


def test_fleet_rejects_bad_shape():
    with pytest.raises(ValueError, match="workers=0"):
        FleetServer(workers=0)
    with pytest.raises(ValueError, match="mode='coroutine'"):
        FleetServer(workers=2, mode="coroutine")
    with pytest.raises(ValueError, match="rotation_cap=0"):
        FleetServer(workers=2, rotation_cap=0)


def test_fleet_placement_layout():
    devs = [SimpleNamespace(platform="tpu", id=k, device_kind="v5e")
            for k in range(4)]
    rows = pmesh.fleet_placement(3, devices=devs)
    assert [r["device_id"] for r in rows] == [0, 1, 2]
    assert all(r["shared"] is False for r in rows)
    rows = pmesh.fleet_placement(4, devices=devs[:2])
    assert [r["device_id"] for r in rows] == [0, 1, 0, 1]
    assert all(r["shared"] is True for r in rows)
    with pytest.raises(ValueError, match="n_workers=0"):
        pmesh.fleet_placement(0, devices=devs)
    with pytest.raises(ValueError, match="at least one device"):
        pmesh.fleet_placement(2, devices=[])


def test_thread_fleet_routes_steals_and_bit_matches():
    """Thread-mode fleet: same-bucket affinity keeps a bucket on one
    worker; a worker going idle steals the longest cross-bucket pending
    rotation from the busiest peer; every reply bit-matches offline."""
    with FleetServer(workers=2, mode="thread", policy=_POLICY,
                     segment_latency_s=0.05) as fleet:
        # w0 runs the heavy bucket, w1 the light one (pin = warm-up seam).
        h_heavy = fleet.submit(_HEAVY, pin_worker=0)
        h_light = fleet.submit(_LIGHT, pin_worker=1)
        # Unpinned third bucket: both workers busy -> queued; whichever
        # worker drains first pumps it. The light worker finishes long
        # before the heavy one (segment latency scales with grid work),
        # so the pending rotation moves by steal or by idle-pump.
        h_third = fleet.submit(_THIRD)
        # Same-bucket request while the rotation is live: joins mid-flight
        # on the same worker (affinity), never opens a second grid.
        h_heavy2 = fleet.submit(dataclasses.replace(_HEAVY, seed=78), pin_worker=None)
        recs = [h.wait(timeout=600.0)
                for h in (h_heavy, h_light, h_third, h_heavy2)]
        stats = fleet.stats(live=True)

    assert stats["submitted"] == 4
    assert stats["replied"] == 4
    assert stats["failed"] == 0
    assert stats["lost_workers"] == 0
    assert len(stats["per_worker"]) == 2
    # both workers did real work (the steal/idle-pump moved the third
    # bucket off the pinned-busy worker)
    assert all(row["replied"] >= 1 for row in stats["per_worker"])

    for h, rec, cfg in zip((h_heavy, h_light, h_third, h_heavy2), recs,
                           (_HEAVY, _LIGHT, _THIRD, dataclasses.replace(_HEAVY, seed=78))):
        assert rec["request_id"] == h.id
        assert record.validate_record(rec) == [], rec
        rounds, decision = _offline(cfg)
        assert rec["rounds"] == rounds
        assert rec["decision"] == decision


def test_thread_fleet_steals_from_busiest_queue():
    """Deterministic steal: the light worker drains first and must pull the
    queued cross-bucket rotation off the still-busy heavy worker."""
    with FleetServer(workers=2, mode="thread", policy=_POLICY,
                     segment_latency_s=0.08) as fleet:
        h0 = fleet.submit(_HEAVY, pin_worker=0)
        # Queue the third bucket directly on the busy heavy worker: with
        # w1 idle the router's idle-pump (or w1's drain) must move it.
        h1 = fleet.submit(_LIGHT, pin_worker=1)
        h2 = fleet.submit(_THIRD)
        for h in (h0, h1, h2):
            h.wait(timeout=600.0)
        stats = fleet.stats(live=False)
    # Work moved across workers at least once: either counted as a steal
    # (pulled from a busy peer's queue) or both workers replied.
    moved = stats["steals"] >= 1 or all(
        row["replied"] >= 1 for row in stats["per_worker"])
    assert moved, stats


def test_rotation_cap_splits_hot_bucket_across_workers():
    """Work-sharing granularity: a single hot bucket is NOT an indivisible
    unit — with a rotation lane budget (here 6 lanes = exactly one
    6-instance request per rotation) its overflow queues stealable, an
    idle peer pulls a chunk immediately, and both workers end up serving
    it with bit-identical replies."""
    cfgs = [dataclasses.replace(_THIRD, seed=s) for s in range(30, 42)]
    with FleetServer(workers=2, mode="thread", policy=_POLICY,
                     segment_latency_s=0.05, rotation_cap=6) as fleet:
        handles = [fleet.submit(c) for c in cfgs]
        recs = [h.wait(timeout=600.0) for h in handles]
        stats = fleet.stats(live=False)
    assert stats["failed"] == 0 and stats["replied"] == len(cfgs)
    assert stats["rotation_cap"] == 6
    assert stats["steals"] >= 1  # w1 was idle: the first overflow chunk
    # is pulled the moment it queues (idle-pump), not on some reply path
    assert all(row["replied"] >= 1 for row in stats["per_worker"])
    for rec, cfg in zip(recs, cfgs):
        rounds, decision = _offline(cfg)
        assert rec["rounds"] == rounds
        assert rec["decision"] == decision


def test_fleet_shutdown_no_drain_fails_pending():
    fleet = FleetServer(workers=1, mode="thread", policy=_POLICY).start()
    h = fleet.submit(_LIGHT)
    fleet.shutdown(drain=True)
    assert h.error is None and h.record is not None
    with pytest.raises(RuntimeError, match="shutting down"):
        fleet.submit(_LIGHT)


def test_thread_fleet_kill_is_refused():
    fleet = FleetServer(workers=1, mode="thread", policy=_POLICY).start()
    try:
        with pytest.raises(RuntimeError, match="mode='process'"):
            fleet._workers[0].kill()
    finally:
        fleet.shutdown(drain=True)


def test_follow_heartbeat_renders_fleet_line(tmp_path):
    """Satellite: `trace follow` on a fleet trace dir shows the per-worker
    heartbeat — "fleet N/M replied (w0:a w1:b ...)" — attributing serve
    events to workers by sink file name alone."""
    def sink(name, events):
        (tmp_path / name).write_text(
            "".join(json.dumps(e) + "\n" for e in events))

    sink("trace-fleet-w0.jsonl", [
        {"kind": "serve.request", "attrs": {"id": "f000001"}},
        {"kind": "serve.request", "attrs": {"id": "f000002"}},
        {"kind": "serve.reply", "attrs": {"id": "f000001"}},
        {"kind": "serve.reply", "attrs": {"id": "f000002"}},
    ])
    sink("trace-fleet-w1.jsonl", [
        {"kind": "serve.request", "attrs": {"id": "f000003"}},
        {"kind": "serve.request", "attrs": {"id": "f000004"}},
        {"kind": "serve.reply", "attrs": {"id": "f000003"}},
    ])
    sink("trace-fleet-coord.jsonl", [
        {"kind": "fleet.route", "attrs": {"id": "f000001", "worker": 0}},
    ])
    lines = []
    state = trace_tool.follow(tmp_path, once=True, out=lines.append)
    assert state["fleet"] == {"w0": 2, "w1": 1}
    assert len(lines) == 1
    assert "fleet 3/4 replied (w0:2 w1:1)" in lines[0]


def test_follow_heartbeat_without_fleet_keeps_serve_line(tmp_path):
    (tmp_path / "trace-serve.jsonl").write_text(
        json.dumps({"kind": "serve.request", "attrs": {}}) + "\n"
        + json.dumps({"kind": "serve.reply", "attrs": {}}) + "\n")
    lines = []
    state = trace_tool.follow(tmp_path, once=True, out=lines.append)
    assert state["fleet"] == {}
    assert "serve 1/1 replied" in lines[0]
    assert "fleet" not in lines[0]


@pytest.mark.slow
def test_process_fleet_smoke_and_per_worker_traces(tmp_path):
    """The real subprocess fleet: spawn ladder, stdio protocol, per-worker
    compile counts over the stats RPC, merged per-worker trace sinks."""
    from byzantinerandomizedconsensus_tpu.obs import trace as _trace

    with FleetServer(workers=2, mode="process", policy=_POLICY,
                     trace_dir=str(tmp_path)) as fleet:
        handles = [fleet.submit(c) for c in (_HEAVY, _LIGHT, _THIRD)]
        recs = [h.wait(timeout=600.0) for h in handles]
        counts = fleet.compile_counts()
        stats = fleet.stats(live=True)

    assert stats["replied"] == 3 and stats["failed"] == 0
    assert len(counts) == 2 and all(c is not None for c in counts)
    for h, rec, cfg in zip(handles, recs, (_HEAVY, _LIGHT, _THIRD)):
        assert rec["request_id"] == h.id
        rounds, decision = _offline(cfg)
        assert rec["rounds"] == rounds
        assert rec["decision"] == decision
    # every worker wrote its own sink, and merge() folds them time-ordered
    sinks = sorted(p.name for p in tmp_path.glob("trace-fleet-w*.jsonl"))
    assert sinks == ["trace-fleet-w0.jsonl", "trace-fleet-w1.jsonl"]
    merged = _trace.merge(tmp_path)
    events = _trace.read_events(merged)
    assert any(e["kind"] == "serve.reply" for e in events)


@pytest.mark.slow
def test_process_fleet_worker_loss_readmits_bit_identical():
    """Satellite: kill one worker mid-stream. Its in-flight and queued
    requests are re-admitted to survivors under the same fleet ids and
    every reply stays bit-identical to the offline oracle."""
    victims = [_HEAVY, dataclasses.replace(_HEAVY, seed=101),
               dataclasses.replace(_HEAVY, seed=102)]
    with FleetServer(workers=2, mode="process", policy=_POLICY,
                     segment_latency_s=0.2) as fleet:
        doomed = [fleet.submit(c, pin_worker=0) for c in victims]
        safe = fleet.submit(_LIGHT, pin_worker=1)
        # wait until w0 actually has the rotation in flight, then kill it
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            with fleet._cv:
                if fleet._workers[0].inflight:
                    break
            time.sleep(0.05)
        fleet._workers[0].kill()
        recs = [h.wait(timeout=600.0) for h in doomed]
        safe_rec = safe.wait(timeout=600.0)
        stats = fleet.stats(live=False)

    assert stats["lost_workers"] == 1
    assert stats["readmitted"] >= 1
    assert stats["failed"] == 0
    assert stats["replied"] == 4
    for h, rec, cfg in zip(doomed, recs, victims):
        assert rec["request_id"] == h.id  # same id across re-admission
        rounds, decision = _offline(cfg)
        assert rec["rounds"] == rounds
        assert rec["decision"] == decision
    assert safe_rec["request_id"] == safe.id


def test_thread_fleet_scale_down_retires_not_dead():
    """Round-22 satellite, alongside the worker-kill pin above: a
    scaled-down worker is **retiring**, never dead — health stays ok (no
    503), ``lost_workers`` stays 0, the worker table names the state while
    the drain is in progress, and its in-flight work drains to completion
    with bit-identical replies under the same ids."""
    victims = [dataclasses.replace(_HEAVY, seed=201),
               dataclasses.replace(_HEAVY, seed=202)]
    with FleetServer(workers=2, mode="thread", policy=_POLICY,
                     segment_latency_s=0.2) as fleet:
        doomed = [fleet.submit(c, pin_worker=1) for c in victims]
        # wait until w1's rotation is genuinely in flight, then retire it
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            with fleet._cv:
                if fleet._workers[1].inflight:
                    break
            time.sleep(0.05)
        assert fleet.scale_down(1) == 1
        health = fleet.health()
        assert health["ok"] is True          # retiring is not dead: no 503
        assert health["retiring"] == [1]
        st = fleet.stats(live=False)
        assert st["routable"] == 1           # out of the routing fabric...
        assert st["workers"] == 2            # ...but still in the table
        recs = [h.wait(timeout=600.0) for h in doomed]
        for h, rec, cfg in zip(doomed, recs, victims):
            assert rec["request_id"] == h.id
            rounds, decision = _offline(cfg)
            assert rec["rounds"] == rounds and rec["decision"] == decision
        # the drain completes: retired, not lost — and health forgets it
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline and fleet.health().get("retiring"):
            time.sleep(0.05)
        assert fleet.health() == {"ok": True, "workers": 1, "alive": 1,
                                  "dead_workers": []}
        st = fleet.stats(live=False)
        assert st["lost_workers"] == 0
        assert st["retired_workers"] == 1


@pytest.mark.slow
def test_process_fleet_healthz_names_dead_worker():
    """Round-16 satellite: ``GET /healthz`` is per-worker liveness — 200
    while every worker is up; after a hard kill (with the default
    ``max_respawns=0`` budget the fleet never respawns past the initial
    backoff ladder) it degrades to 503 with a JSON body naming the dead
    worker, while survivors keep serving."""
    import threading
    import urllib.error
    import urllib.request

    from byzantinerandomizedconsensus_tpu.serve.server import serve_http

    with FleetServer(workers=2, mode="process", policy=_POLICY) as fleet:
        httpd = serve_http(fleet, host="127.0.0.1", port=0)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        host, port = httpd.server_address[:2]
        base = f"http://{host}:{port}"
        try:
            with urllib.request.urlopen(base + "/healthz", timeout=30) as r:
                assert r.status == 200
                doc = json.loads(r.read())
            assert doc == {"ok": True, "workers": 2, "alive": 2,
                           "dead_workers": []}
            # park work on the survivor, then hard-kill worker 0
            safe = fleet.submit(_LIGHT, pin_worker=1)
            fleet._workers[0].kill()
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline and fleet.health()["ok"]:
                time.sleep(0.05)
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(base + "/healthz", timeout=30)
            assert exc.value.code == 503
            doc = json.loads(exc.value.read())
            assert doc["ok"] is False
            assert doc["dead_workers"] == [0]
            assert doc["workers"] == 2 and doc["alive"] == 1
            # degraded, not down: the survivor still replies bit-identically
            rec = safe.wait(timeout=600.0)
            rounds, decision = _offline(_LIGHT)
            assert rec["rounds"] == rounds and rec["decision"] == decision
        finally:
            httpd.shutdown()
            httpd.server_close()


def test_thread_fleet_all_workers_share_one_front_door():
    """The admission seam is the fleet's only entry: a bad payload is
    rejected before any routing state mutates."""
    with FleetServer(workers=2, mode="thread", policy=_POLICY) as fleet:
        with pytest.raises(ValueError, match="unknown request field"):
            fleet.submit({"n": 5, "banana": 1})
        with pytest.raises(ValueError, match="exceeds the service ceiling"):
            fleet.submit(SimConfig(n=4, f=1, round_cap=256))
        assert fleet.stats(live=False)["submitted"] == 0
