"""Scheduling-bias strength harness (tools/schedstrength.py; spec §6.4).

Small-n checks that the experiment surface is sound: the "class" variant is
exactly the shipped adversary (same bits), variant runs are valid simulations,
and the measured strength ordering at the n=16 anchor (class/minority stall,
echo/anti collapse) is reproducible — the qualitative finding spec §6.4 cites.
"""

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu import SimConfig, Simulator
from byzantinerandomizedconsensus_tpu.backends.numpy_backend import NumpyBackend
from byzantinerandomizedconsensus_tpu.tools.schedstrength import (
    BIAS_MODES, ScheduledAdaptive, run_strength)

CFG = SimConfig(protocol="bracha", n=16, f=5, instances=80,
                adversary="adaptive", coin="local", seed=0, round_cap=32,
                delivery="keys")


def test_class_mode_is_the_shipped_adversary():
    """bias_mode='class' must reproduce the product adversary bit-for-bit —
    the experiment's baseline is anchored to spec §6.4, not a reimplementation."""
    ref = Simulator(CFG, "numpy").run()
    got = NumpyBackend().run_with_adversary(CFG, ScheduledAdaptive(CFG, "class"))
    np.testing.assert_array_equal(ref.rounds, got.rounds)
    np.testing.assert_array_equal(ref.decision, got.decision)


@pytest.mark.parametrize("mode", [m for m in BIAS_MODES if m != "class"])
def test_variant_runs_are_valid(mode):
    """Every bias variant yields a well-formed simulation (decisions in
    {0,1,2}, rounds within cap) — the bias bit cannot corrupt delivery."""
    res = NumpyBackend().run_with_adversary(CFG, ScheduledAdaptive(CFG, mode))
    assert res.rounds.max() <= CFG.round_cap
    assert set(np.unique(res.decision)) <= {0, 1, 2}


def test_strength_ordering_at_anchor():
    """The finding spec §6.4 cites, pinned at the n=16 s=1 anchor: the shipped
    class rule (and the balance-forcing minority rule) stall near-completely;
    the per-receiver echo/anti rules collapse termination instead of stalling
    it. Deterministic (numpy backend, fixed seed)."""
    out = run_strength((16,), instances=80, round_cap=32, progress=lambda _: None)
    capped = {m: out[m]["16"]["capped_fraction"] for m in BIAS_MODES}
    assert capped["class"] >= 0.9
    assert capped["minority"] >= 0.9
    assert capped["echo"] <= 0.3
    assert capped["anti"] <= 0.1
    assert capped["class"] >= capped["none"]


def test_rejects_non_adaptive_and_urn():
    with pytest.raises(ValueError):
        ScheduledAdaptive(SimConfig(adversary="none", delivery="keys"), "class")
    with pytest.raises(ValueError):
        ScheduledAdaptive(
            SimConfig(protocol="bracha", n=16, f=5, adversary="adaptive",
                      delivery="urn"), "class")
    with pytest.raises(ValueError):
        ScheduledAdaptive(CFG, "bogus")
