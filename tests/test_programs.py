"""Compiled-program census (obs/programs.py; round 13).

The acceptance bar mirrors the trace layer's: census-on runs must be
bit-identical to census-off across the fault x adversary x delivery grid on
the vmapped AND compacted paths (the measured wall-overhead bound lives in
artifacts/programs_r13.json), the HLO fingerprint must be stable against
the two known sources of spurious drift (SSA renumbering, source metadata),
and the consumer surfaces (schema-v1.4 programs block, `brc-tpu programs`
dump/diff/roofline, the ledger sentinel's fingerprint columns) must round-
trip what the census captured.
"""

import json

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu.backends import batch as batch_mod
from byzantinerandomizedconsensus_tpu.backends.jax_backend import JaxBackend
from byzantinerandomizedconsensus_tpu.config import (
    DELIVERY_KINDS, FAULT_KINDS, SimConfig)
from byzantinerandomizedconsensus_tpu.obs import programs, record, trace


@pytest.fixture(autouse=True)
def _no_leftover_census():
    """Every test starts and ends with the census (and tracer) disabled —
    a leaked global would silently AOT-compile unrelated tests' programs."""
    programs.disable()
    trace.disable()
    yield
    programs.disable()
    trace.disable()


def _cfg(adv, proto, delivery, fault, n=7, f=2, seed=13, **kw):
    base = dict(protocol=proto, n=n, f=f, instances=4, adversary=adv,
                coin="local", seed=seed, round_cap=32, delivery=delivery,
                faults=fault)
    base.update(kw)
    return SimConfig(**base).validate()


# ---------------------------------------------------------------------------
# the fingerprint


_HLO_A = """HloModule jit_f, entry_computation_layout={(f32[4]{0})->f32[4]{0}}

ENTRY %main.7 (Arg_0.1: f32[4]) -> f32[4] {
  %Arg_0.1 = f32[4]{0} parameter(0), metadata={op_name="x" source_file="/a/b.py" source_line=3}
  ROOT %sine.2 = f32[4]{0} sine(f32[4]{0} %Arg_0.1), metadata={op_name="jit(f)/sin"}
}
"""

# The same program after a different compile history: SSA suffixes moved,
# metadata points at another checkout path.
_HLO_A2 = """HloModule jit_f, entry_computation_layout={(f32[4]{0})->f32[4]{0}}

ENTRY %main.961 (Arg_0.44: f32[4]) -> f32[4] {
  %Arg_0.44 = f32[4]{0} parameter(0), metadata={op_name="x" source_file="/elsewhere/b.py" source_line=9}
  ROOT %sine.45 = f32[4]{0} sine(f32[4]{0} %Arg_0.44), metadata={op_name="jit(f)/sin"}
}
"""

_HLO_B = _HLO_A.replace("sine", "cosine")


def test_fingerprint_stable_against_renumbering_and_metadata():
    fa, fa2 = programs.hlo_fingerprint(_HLO_A), programs.hlo_fingerprint(
        _HLO_A2)
    assert fa["hash"] == fa2["hash"]
    assert fa["ops"] == {"parameter": 1, "sine": 1}
    assert fa["instructions"] == 2
    # A genuinely different program must hash differently.
    assert programs.hlo_fingerprint(_HLO_B)["hash"] != fa["hash"]


def test_normalize_strips_metadata_and_ssa_only():
    norm = programs.normalize_hlo(_HLO_A)
    assert "metadata" not in norm and "source_file" not in norm
    assert "%Arg_0 = f32[4]{0} parameter(0)" in norm
    # Constants and layouts survive normalization (they ARE the program).
    assert programs.normalize_hlo("  %c.1 = f32[] constant(0.5)\n") \
        == "%c = f32[] constant(0.5)"


def test_fingerprint_stable_across_real_compile_histories():
    import jax
    import jax.numpy as jnp

    def make():
        return jax.jit(lambda x: jnp.sin(x) @ x)

    args = (jnp.ones((4, 4)),)
    c1 = make().lower(*args).compile()
    for k in range(3):  # pollute the global SSA/name counters
        jax.jit(lambda x: x + k).lower(jnp.ones(3)).compile()
    c2 = make().lower(*args).compile()
    f1 = programs.hlo_fingerprint(c1.as_text())
    f2 = programs.hlo_fingerprint(c2.as_text())
    assert f1["hash"] == f2["hash"] and f1["instructions"] >= 2


# ---------------------------------------------------------------------------
# the capture seams


def test_disabled_census_is_inert():
    assert not programs.enabled()
    import jax

    fn = jax.jit(lambda x: x + 1)
    assert programs.instrument("k", fn) is fn  # no wrap when off
    assert record.programs_block() is None


def test_census_captures_bucket_programs_and_attaches_to_cache():
    jb = JaxBackend()  # fresh instance: its bucket cache starts empty
    census = programs.configure()
    tr = trace.configure()  # in-memory: catch the program.compile events
    a = _cfg("none", "benor", "urn2", "none", f=2, seed=1, instances=3)
    b = _cfg("none", "benor", "urn2", "none", f=1, seed=2, instances=3)
    res_a = jb.run_batch([a])
    jb.run_batch([b])  # same bucket: a cache hit, no second capture
    trace.disable()

    assert len(census.entries) == 1 and census.capture_errors == 0
    (key, entry), = census.entries.items()
    assert entry["fingerprint"]["hash"] and entry["fingerprint"]["ops"]
    assert entry["cost"]["flops"] > 0
    assert entry["cost"]["bytes_accessed"] > 0
    assert entry["memory"]["resident_bytes"] > 0
    assert entry["signature"]["num_args"] >= 5  # keys/fs/wins/neffs/ids
    assert entry["compile_wall_s"] > 0
    # Attached to the cache entry AND visible through the backend accessor.
    cache = batch_mod.compile_cache(jb)
    assert cache.programs[key] is entry
    assert jb.program_census()[key] is entry
    # The compile seam emitted the census trace event with the identity.
    ev = next(e for e in tr.events if e["kind"] == "program.compile")
    assert ev["attrs"]["hash"] == entry["fingerprint"]["hash"]
    assert ev["attrs"]["flops"] == entry["cost"]["flops"]
    # Results came from the AOT executable — compare against a census-off
    # backend for bit-identity.
    off = JaxBackend()
    programs.disable()
    ref = off.run_batch([a])
    np.testing.assert_array_equal(res_a[0].rounds, ref[0].rounds)
    np.testing.assert_array_equal(res_a[0].decision, ref[0].decision)


def test_census_covers_per_config_seam():
    jb = JaxBackend()
    census = programs.configure()
    cfg = _cfg("crash", "benor", "urn2", "none", instances=3)
    res = jb.run(cfg)
    keys = list(census.entries)
    assert any(k.startswith("config/benor/n7/") for k in keys), keys
    programs.disable()
    ref = JaxBackend().run(cfg)
    np.testing.assert_array_equal(res.rounds, ref.rounds)
    np.testing.assert_array_equal(res.decision, ref.decision)


def test_census_survives_shape_drift_on_per_config_path():
    """The AOT executable captured on the first call is shape-specialized,
    but the per-config cache is keyed by config alone — a later run of the
    SAME config with a smaller inst_ids subset dispatches a smaller chunk
    and must fall back to the lazy jit instead of crashing ('the census can
    never break a run')."""
    jb = JaxBackend()
    programs.configure()
    cfg = _cfg("none", "benor", "urn2", "none", instances=8)
    full = jb.run(cfg)                      # captures at chunk=8
    sub = jb.run(cfg, np.arange(2))         # chunk=2: shape drift
    programs.disable()
    ref = JaxBackend().run(cfg)
    np.testing.assert_array_equal(full.rounds, ref.rounds)
    np.testing.assert_array_equal(sub.rounds, ref.rounds[:2])
    np.testing.assert_array_equal(sub.decision, ref.decision[:2])


def test_census_inert_across_fault_adversary_delivery_grid():
    """The tentpole acceptance bar: census-on bit-identical to census-off
    over a covering (fault, delivery) sample with rotating adversaries, on
    the vmapped AND compacted paths — and the census must come out covering
    the dispatch + compaction program families."""
    from byzantinerandomizedconsensus_tpu.backends.compaction import (
        CompactionPolicy)

    _ADV_PROTO = (("none", "benor"), ("crash", "benor"),
                  ("byzantine", "bracha"), ("adaptive", "bracha"))
    cells = [(FAULT_KINDS[i], DELIVERY_KINDS[j])
             for i, j in ((0, 0), (1, 1), (2, 3), (3, 2))]
    cfgs = []
    for i, (fault, delivery) in enumerate(cells):
        adv, proto = _ADV_PROTO[i % len(_ADV_PROTO)]
        cfgs += [_cfg(adv, proto, delivery, fault),
                 _cfg(adv, proto, delivery, fault, f=1, seed=99,
                      instances=6)]
    off = JaxBackend()
    base, _ = off.run_many(cfgs)
    base_c, _ = off.run_many(cfgs, compaction=CompactionPolicy(width=4,
                                                               segment=1))

    on = JaxBackend()
    census = programs.configure()
    traced, _ = on.run_many(cfgs)
    traced_c, _ = on.run_many(cfgs, compaction=CompactionPolicy(width=4,
                                                                segment=1))

    for a, b in zip(base + base_c, traced + traced_c):
        np.testing.assert_array_equal(a.rounds, b.rounds)
        np.testing.assert_array_equal(a.decision, b.decision)

    assert census.capture_errors == 0
    keys = list(census.entries)
    assert any("compact-seg/" in k for k in keys)
    assert any("compact-init/" in k for k in keys)
    assert any(not k.startswith(("compact-", "config/")) for k in keys)
    # Every entry is identity-complete: fingerprint + cost on this backend.
    for entry in census.entries.values():
        assert entry["fingerprint"]["hash"]
        assert entry["cost"]["flops"] > 0


# ---------------------------------------------------------------------------
# schema v1.4


def test_programs_block_and_validate_record():
    census = programs.configure()
    census.record({"key": "k1", "compile_wall_s": 0.5,
                   "fingerprint": {"hash": "abc", "ops": {"add": 1},
                                   "instructions": 1},
                   "cost": {"flops": 10, "bytes_accessed": 4}})
    census.record({"key": "k2", "compile_wall_s": 0.25,
                   "fingerprint": {"hash": "def", "ops": {},
                                   "instructions": 0}})
    blk = record.programs_block()
    assert blk["count"] == 2
    assert blk["totals"]["flops"] == 10
    assert blk["totals"]["compile_wall_s"] == 0.75
    doc = {**record.new_record("programs_census"), "programs": blk}
    assert record.validate_record(doc) == []
    assert doc["record_revision"] == record.RECORD_REVISION >= 4

    # Drift checks: a torn block and an identity-free entry must fail.
    assert any("programs block missing" in p for p in record.validate_record(
        {**record.new_record("x"), "programs": {"count": 1}}))
    assert any("'key'/'fingerprint'" in p for p in record.validate_record(
        {**record.new_record("x"),
         "programs": {"count": 1, "programs": [{"cost": {}}]}}))


def test_programs_block_from_backend_and_empty_sources():
    assert record.programs_block() is None  # census off
    census = programs.configure()
    assert record.programs_block() is None  # on but empty
    census.record({"key": "k", "compile_wall_s": 0.0,
                   "fingerprint": {"hash": "h", "ops": {},
                                   "instructions": 0}})
    assert record.programs_block()["count"] == 1
    assert record.programs_block({"k": census.entries["k"]})["count"] == 1
    assert record.programs_block({}) is None


# ---------------------------------------------------------------------------
# consumer surfaces (tools/programs.py)


def _sample_artifact(tmp_path, name="census.json", key="prog/a",
                     hash_="aaaa", flops=1000, trace_file=None):
    blk = {"count": 1, "programs": [{
        "key": key, "compile_wall_s": 1.0,
        "fingerprint": {"hash": hash_, "ops": {"add": 2, "while": 1},
                        "instructions": 3},
        "cost": {"flops": flops, "bytes_accessed": 500,
                 "transcendentals": 7},
        "memory": {"resident_bytes": 2048}}],
        "totals": {"flops": flops}}
    doc = {**record.new_record("programs_census"), "programs": blk}
    if trace_file:
        doc["trace"] = {"file": trace_file, "events": 2, "digest": {}}
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return p


def test_programs_dump_and_diff(tmp_path, capsys):
    from byzantinerandomizedconsensus_tpu.tools import (
        programs as programs_tool)

    a = _sample_artifact(tmp_path, "a.json", hash_="aaaa")
    b = _sample_artifact(tmp_path, "b.json", hash_="bbbb", flops=2000)
    assert programs_tool.main(["dump", str(a), "--ops", "2"]) == 0
    out = capsys.readouterr().out
    assert "prog/a" in out and "aaaa" in out and "addx2" in out

    # Same key, different hash: drift — nonzero, with both hashes named.
    assert programs_tool.main(["diff", str(a), str(b)]) == 1
    out = capsys.readouterr().out
    assert "aaaa -> bbbb" in out and "1000 -> 2000" in out
    assert programs_tool.main(["diff", str(a), str(a)]) == 0
    capsys.readouterr()

    # No census block: dump says so and fails distinguishably.
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps(record.new_record("bench")))
    assert programs_tool.main(["dump", str(empty)]) == 1
    capsys.readouterr()


def test_programs_roofline_joins_trace_spans(tmp_path, capsys):
    from byzantinerandomizedconsensus_tpu.tools import (
        programs as programs_tool)

    art = _sample_artifact(tmp_path, "c.json", key="prog/a",
                           trace_file="c.jsonl")
    events = [
        {"ph": "X", "kind": "batch.dispatch", "ts": 1.0, "dur": 2.0,
         "pid": 1, "tid": 0, "attrs": {"program": "prog/a",
                                       "dispatches": 4}},
        {"ph": "X", "kind": "compaction.segment", "ts": 4.0, "dur": 1.0,
         "pid": 1, "tid": 0, "attrs": {"program": "prog/other"}},
        {"ph": "X", "kind": "batch.bucket", "ts": 0.0, "dur": 9.0,
         "pid": 1, "tid": 0, "attrs": {"program": "prog/a"}},  # not a dispatch kind
    ]
    (tmp_path / "c.jsonl").write_text(
        "\n".join(json.dumps(e) for e in events) + "\n")
    assert programs_tool.main(["roofline", "--census", str(art),
                               "--json"]) == 0
    rows = {r["key"]: r for r in
            json.loads(capsys.readouterr().out)["rows"]}
    row = rows["prog/a"]
    assert row["dispatches"] == 4 and row["wall_s"] == 2.0
    assert row["gflops_per_s"] == round(1000 * 4 / 2.0 / 1e9, 4)
    assert row["intensity_flops_per_byte"] == 2.0
    assert row["in_census"]
    # A dispatched program missing from the census is flagged, not dropped.
    assert rows["prog/other"]["in_census"] is False


def test_programs_census_smoke(tmp_path, capsys):
    """The tier-1 form of the round-13 A/B: a small seeded grid, one
    repeat, artifact written and self-validating, exit 0 (bit-identical,
    overhead bound trivially met at this scale is NOT asserted — only the
    record shape and the bit-identity/census-nonempty gates)."""
    from byzantinerandomizedconsensus_tpu.tools import (
        programs as programs_tool)

    out = tmp_path / "programs_smoke.json"
    rc = programs_tool.main([
        "census", "--configs", "4", "--repeats", "1",
        "--compacted-sample", "2", "--per-config-sample", "1",
        "--out", str(out)])
    capsys.readouterr()
    doc = json.loads(out.read_text())
    assert record.validate_record(doc) == []
    assert doc["kind"] == "programs_census"
    assert doc["bit_identical"] is True
    assert doc["programs"]["count"] >= 2
    assert doc["capture_errors"] == 0
    assert doc["trace"] is not None and doc["trace"]["events"] > 0
    # program.compile events landed in the bound trace.
    assert "program.compile" in doc["trace"]["digest"]
    # The A/B gates: rc 0 unless the tiny grid's walls were degenerate —
    # bit-identity and a non-empty census are the load-bearing assertions.
    assert rc in (0, 1)
    # The committed-artifact convention: the trace JSONL sits next to the
    # record under the record's own name.
    assert (tmp_path / "programs_smoke.jsonl").exists()
    # And the roofline verb joins the two as committed.
    assert programs_tool.main(["roofline", "--census", str(out),
                               "--json"]) == 0
    rows = json.loads(capsys.readouterr().out)["rows"]
    assert rows and any(r["in_census"] for r in rows)


def test_cli_routes_programs_verb(tmp_path, capsys):
    from byzantinerandomizedconsensus_tpu import cli

    art = _sample_artifact(tmp_path)
    assert cli.main(["programs", "dump", str(art)]) == 0
    assert "compiled-program census" in capsys.readouterr().out
