"""Statistical tests (SURVEY.md §4.4): chi-square on decision-bit frequencies under
symmetric inputs, coin fairness, and cross-seed stability of mean rounds-to-decision
for the small Ben-Or reference point."""

import dataclasses

import numpy as np

from byzantinerandomizedconsensus_tpu import SimConfig, Simulator

# chi-square critical value, 1 dof, p = 0.001 — loose enough for CI determinism.
CHI2_1DOF_P001 = 10.83


def _chi2_fair(counts0: int, counts1: int) -> float:
    tot = counts0 + counts1
    e = tot / 2.0
    return (counts0 - e) ** 2 / e + (counts1 - e) ** 2 / e


def test_decision_bit_symmetry_benor():
    """Random symmetric inputs + fair coin: decisions 0/1 occur equally often."""
    cfg = SimConfig(protocol="benor", n=4, f=1, instances=4000, adversary="none",
                    coin="local", round_cap=128, seed=41)
    res = Simulator(cfg, "numpy").run()
    d = res.decision
    assert (d != 2).all(), "n=4 f=1 local coin must terminate within the cap"
    assert _chi2_fair(int((d == 0).sum()), int((d == 1).sum())) < CHI2_1DOF_P001


def test_decision_bit_symmetry_bracha_shared():
    cfg = SimConfig(protocol="bracha", n=16, f=5, instances=3000, adversary="byzantine",
                    coin="shared", round_cap=64, seed=42)
    res = Simulator(cfg, "numpy").run()
    d = res.decision[res.decision != 2]
    assert len(d) >= 2900
    assert _chi2_fair(int((d == 0).sum()), int((d == 1).sum())) < CHI2_1DOF_P001


def test_shared_coin_fairness_and_commonality():
    """The shared-coin stub is fair across (instance, round) and identical across
    replicas (the threshold-signature property being stubbed — spec §5.3)."""
    from byzantinerandomizedconsensus_tpu.models import coins

    cfg = SimConfig(protocol="bracha", n=10, f=3, coin="shared").validate()
    ids = np.arange(3000, dtype=np.int64)
    allbits = []
    for rnd in range(4):
        bits = coins.coin_bits(cfg, cfg.seed, ids, rnd, xp=np)
        assert (bits == bits[:, :1]).all(), "shared coin differs across replicas"
        allbits.append(bits[:, 0])
    b = np.concatenate(allbits)
    assert _chi2_fair(int((b == 0).sum()), int((b == 1).sum())) < CHI2_1DOF_P001


def test_mean_rounds_matches_exact_markov_constant():
    """Mean rounds-to-decision for Ben-Or n=4 f=1 against the *exact* value from
    the spec/analytic.py Markov-chain enumeration (SURVEY.md §4.4; spec §8a):
    E[rounds] = 3.221122… for uniform initial estimates, identically for every
    delivery model. A consistently-wrong protocol cannot pass this; cross-seed
    stability alone could."""
    from spec.analytic import expected_rounds_benor_n4

    exact = expected_rounds_benor_n4()
    assert abs(exact - 3.221122) < 1e-5, "enumeration drifted from the pinned spec value"
    for delivery in ("urn", "urn2", "keys"):
        rs = []
        for seed in (1, 2, 3):
            cfg = SimConfig(protocol="benor", n=4, f=1, instances=2500,
                            adversary="none", coin="local", round_cap=256,
                            seed=seed, delivery=delivery)
            rs.append(Simulator(cfg, "numpy").run().rounds.astype(np.float64))
        r = np.concatenate(rs)
        sem = r.std(ddof=1) / np.sqrt(len(r))
        z = (r.mean() - exact) / sem
        assert abs(z) < 4.5, (f"{delivery}: mean {r.mean():.4f} vs exact "
                              f"{exact:.6f} (z={z:+.2f})")


def test_mean_rounds_matches_exact_bracha_chain():
    """Mean rounds-to-decision for Bracha n=4 f=1 under the *Byzantine*
    adversary against the exact spec/analytic_bracha.py enumeration (VERDICT
    r2 #8; spec §8b). This is the analytic pin for the §5.1b validation logic
    and the three-step round body: E[rounds] = 1.244628 (shared coin) /
    1.313035 (local coin), identically for every delivery model. The chain is
    re-derived here (≈6 s, cached) so a drift in either the enumeration or
    the pinned constants fails loudly."""
    from spec.analytic_bracha import expected_rounds_bracha_n4

    pinned = {"shared": 1.244628, "local": 1.313035}
    for coin, want in pinned.items():
        exact = expected_rounds_bracha_n4(coin)
        assert abs(exact - want) < 1e-5, \
            f"enumeration drifted from the pinned spec §8b value ({coin})"
    for coin in ("shared", "local"):
        for delivery in ("urn", "urn2", "keys"):
            cfg = SimConfig(protocol="bracha", n=4, f=1, instances=8000,
                            adversary="byzantine", coin=coin, round_cap=64,
                            seed=47, delivery=delivery)
            res = Simulator(cfg, "numpy").run()
            r = res.rounds.astype(np.float64)
            sem = r.std(ddof=1) / np.sqrt(len(r))
            z = (r.mean() - pinned[coin]) / sem
            assert abs(z) < 4.5, (
                f"{coin}/{delivery}: mean {r.mean():.4f} vs exact "
                f"{pinned[coin]:.6f} (z={z:+.2f})")
            # The decision-value law on the same runs: P[1] = 1/2 exactly
            # (spec §8b), for every coin x delivery leg.
            d = res.decision
            assert (d != 2).all()
            assert _chi2_fair(int((d == 0).sum()),
                              int((d == 1).sum())) < CHI2_1DOF_P001, \
                f"{coin}/{delivery}: decision split off 1/2"


def test_bracha_decision_split_matches_exact_chain():
    """The chain's decision-value law: P[decide 1] = 1/2 exactly at uniform
    init, both coins. Not an accident: at n=4 f=1 the delivered step-0/1
    count is always 3 (odd — the m/d ties→1 breaks never fire) and a step-2
    tie forces c ≤ 1, i.e. the coin branch, so w's tie-break is
    outcome-irrelevant — the chain is fully 0↔1 symmetric (spec §8b). The
    simulation legs live in test_mean_rounds_matches_exact_bracha_chain,
    which chi-squares the decision split of every coin x delivery run."""
    from spec.analytic_bracha import p_decide_one_bracha_n4

    assert abs(p_decide_one_bracha_n4("shared") - 0.5) < 1e-9
    assert abs(p_decide_one_bracha_n4("local") - 0.5) < 1e-9


def test_mean_rounds_matches_exact_adaptive_min_chain():
    """Third closed-form anchor (spec §8c, round 4): Bracha n=4 f=1 under
    adaptive_min. Deterministic minority injection + minority-first biased
    delivery collapse the chain to 8 undecided states with exact rational
    constants — E[rounds] = 1.75 (shared) / 4.0 (local), every delivery model
    (the local value, 3.05× the Byzantine anchor's 1.313, is the closed-form
    statement of §6.4's measured small-n dominance). P[decide 1] = 1/2 exactly
    (the §8b symmetry argument carries over)."""
    from spec.analytic_bracha import (
        expected_rounds_bracha_n4, p_decide_one_bracha_n4)

    pinned = {"shared": 1.75, "local": 4.0}
    for coin, want in pinned.items():
        assert abs(expected_rounds_bracha_n4(coin, "adaptive_min") - want) < 1e-9, \
            f"enumeration drifted from the pinned spec §8c value ({coin})"
        assert abs(p_decide_one_bracha_n4(coin, "adaptive_min") - 0.5) < 1e-9
    for coin, want in pinned.items():
        for delivery in ("urn", "urn2", "keys"):
            cfg = SimConfig(protocol="bracha", n=4, f=1, instances=8000,
                            adversary="adaptive_min", coin=coin, round_cap=64,
                            seed=47, delivery=delivery)
            res = Simulator(cfg, "numpy").run()
            r = res.rounds.astype(np.float64)
            sem = r.std(ddof=1) / np.sqrt(len(r))
            z = (r.mean() - want) / sem
            assert abs(z) < 4.5, (
                f"{coin}/{delivery}: mean {r.mean():.4f} vs exact "
                f"{want} (z={z:+.2f})")
            d = res.decision
            assert (d != 2).all()
            assert _chi2_fair(int((d == 0).sum()),
                              int((d == 1).sum())) < CHI2_1DOF_P001, \
                f"{coin}/{delivery}: decision split off 1/2"


def test_rabin_configuration_constant_rounds():
    """Rabin (FOCS 1983) = Ben-Or's rounds + a common lottery coin — the
    `protocol="benor", coin="shared"` configuration (spec §5.3). Its defining
    property vs plain Ben-Or: expected O(1) rounds even at f = Θ(n), where the
    local coin saturates the cap."""
    base = dict(protocol="benor", n=32, f=15, instances=200, adversary="crash",
                round_cap=64, seed=44)
    rabin = Simulator(SimConfig(coin="shared", **base), "numpy").run()
    benor = Simulator(SimConfig(coin="local", **base), "numpy").run()
    assert (rabin.decision != 2).all(), "shared coin must decide within the cap"
    assert float(rabin.rounds.mean()) < 6
    # The same sizes under the local coin mostly saturate — the contrast that
    # makes the common coin the point of Rabin's construction.
    assert (benor.decision == 2).mean() > 0.5


def test_shared_coin_expected_constant_rounds():
    """With the shared coin the adversary cannot stall: mean rounds is O(1) and
    nearly independent of n (spec §5.3) — the reason config 4 exists."""
    means = {}
    for n in (16, 64):
        cfg = SimConfig(protocol="bracha", n=n, f=(n - 1) // 3, instances=400,
                        adversary="byzantine", coin="shared", round_cap=64, seed=43)
        means[n] = float(Simulator(cfg, "numpy").run().rounds.mean())
    assert means[16] < 6 and means[64] < 6
    assert abs(means[64] - means[16]) < 2.0


def test_urn_counts_match_exact_hypergeometric():
    """The §4b urn sampler's delivered-ones count for a receiver must follow the
    exact multivariate-hypergeometric law: drop D=f of the L=n-1 live others
    uniformly, so c1_others ~ Hypergeom(L, m1, L-D). Chi-square against the
    closed-form pmf over lanes whose own value is 0 (their m1 is common)."""
    import math

    from byzantinerandomizedconsensus_tpu.ops import urn

    cfg = SimConfig(protocol="bracha", n=12, f=3, instances=1, adversary="none",
                    coin="shared", delivery="urn").validate()
    n, f = cfg.n, cfg.f
    B = 6000
    inst = np.arange(B, dtype=np.uint32)
    values = (np.arange(n, dtype=np.uint8) % 2)[None, :].repeat(B, 0)  # 6 ones
    silent = np.zeros((B, n), dtype=bool)
    faulty = np.zeros((B, n), dtype=bool)
    c0, c1 = urn.counts_fn(cfg, cfg.seed, inst, 0, 0, values, silent, faulty,
                           values, xp=np)
    own0 = values == 0                       # lanes whose own value is 0
    sample = c1[own0].ravel()                # c1 = delivered ones among others
    L, m1, k = n - 1, int(values[0].sum()), n - 1 - f
    lo_s, hi_s = max(0, k - (L - m1)), min(m1, k)
    pmf = np.array([math.comb(m1, j) * math.comb(L - m1, k - j) / math.comb(L, k)
                    for j in range(lo_s, hi_s + 1)])
    obs = np.array([(sample == j).sum() for j in range(lo_s, hi_s + 1)])
    assert obs.sum() == sample.size, "counts outside the hypergeometric support"
    exp = pmf * sample.size
    chi2 = float((((obs - exp) ** 2) / exp).sum())
    # dof = support-1 = 3; p=0.001 critical value 16.27
    assert chi2 < 16.27, f"chi2={chi2:.2f} vs exact hypergeometric pmf"
