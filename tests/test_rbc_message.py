"""Message-level Bracha RBC validation (spec §5.2; SURVEY.md §7 hard-part 5).

The count-level RBC abstraction is the one assumption every backend shares, so the
cross-implementation bit-match web cannot test it. These tests validate it from
below with spec/rbc_message.py's per-message echo/ready/accept implementation:

1. *Quotient*: under scripted split-brain equivocation, reactive rushing, and
   adversarial schedules, acceptance never splits (prefix-closed), is
   all-or-nothing at quiescence, and protocol-honest senders are always accepted
   with their sent value — i.e. the adversary's whole message-level freedom
   collapses to the count-level knob {silent, 0, 1} per (sender, step).
2. *Achievability*: every knob value has a message-level strategy realizing it,
   and the double-init strategy shows schedule choice alone spans the full knob
   set — the freedom is real, and no larger.
3. *Threshold boundary*: acceptance flips exactly at echo count 2c > n+f.
4. *Oracle match*: a full consensus instance run on message-level RBC (per-step
   RBC outcomes, receiver-local §5.1b validation, wait quotas realized per the
   delivery model — §4 mask rows, or §4b/§4b-v2 per-class count vectors via the
   count-realizing schedule, VERDICT r4 #3) reproduces backends/cpu.py's
   (rounds, decision) exactly, at n ∈ {4, 7, 10, 13, 16}, for all three
   delivery models and every non-crash adversary incl. adaptive_min.
5. *Schedule-free soundness*: under a free random schedule (wait quotas from raw
   message-arrival order, no §4 input), agreement and validity still hold.
"""

import random

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu.backends.cpu import CpuBackend
from byzantinerandomizedconsensus_tpu.config import SimConfig
from spec import rbc_message as rm

NF_FAST = [(4, 1), (7, 2)]
NF_SLOW = [(10, 3), (13, 4)]
NF_ALL = NF_FAST + [pytest.param(*p, marks=pytest.mark.slow) for p in NF_SLOW]


def _engine(n, f, seed, **kw):
    faulty = [j >= n - f for j in range(n)]  # sender n-1 and helpers are faulty
    return rm.Engine(n, f, faulty, rng=random.Random(seed), **kw)


@pytest.mark.parametrize("n,f", NF_ALL)
def test_knob_achievability(n, f):
    """Every count-level knob {silent, 0, 1, honest} has a realizing strategy."""
    s = n - 1
    for seed in range(3):
        # silent: say nothing — no acceptance even at quiescence
        eng = _engine(n, f, seed, check_every=1)
        eng.run()
        assert eng.outcomes() == [None] * n

        for value in (0, 1):
            for self_support in (False, True):
                eng = _engine(n, f, seed, check_every=1)
                rm.scripted_push(eng, s, value, self_support=self_support)
                eng.run()
                assert eng.outcomes()[s] == value

        # honest mode (the §6.3 b=3 outcome): full protocol participation
        eng = _engine(n, f, seed, check_every=1)
        eng.mark_protocol_honest(s, s)
        eng.start_broadcast(s, 1)
        for u in range(n - f):
            eng.start_broadcast(u, u & 1)
        eng.run()
        out = eng.outcomes()
        assert out[s] == 1 and all(out[u] == (u & 1) for u in range(n - f))


@pytest.mark.parametrize("n,f", NF_ALL)
def test_threshold_boundary(n, f):
    """Acceptance fires exactly when the echo count passes 2c > n+f: k correct
    inits + h helper echoes accept iff 2(k+h) > n+f, else stay silent."""
    s = n - 1
    helpers = list(range(n - f, n))  # all f faulty echo (s included)
    for h_cnt in (0, f):
        hs = helpers[:h_cnt]
        for k in range(n - f + 1):
            eng = _engine(n, f, seed=k, check_every=1)
            rm.scripted_tease(eng, s, 1, k, helpers=hs)
            eng.run()
            expect = 1 if 2 * (k + h_cnt) > n + f else None
            assert eng.outcomes()[s] == expect, (n, f, k, h_cnt)


@pytest.mark.parametrize("n,f", NF_ALL)
def test_silent_helper_boost_cannot_force_accept(n, f):
    """With no init at all, f scripted echo+ready boosters stay below both the
    echo quorum and the f+1 ready amplification — outcome must remain silent."""
    s = n - 1
    for seed in range(3):
        eng = _engine(n, f, seed, check_every=1)
        for h in range(n - f, n):
            eng.inject([rm.Msg(s, rm.ECHO, 1, h, d) for d in range(n)])
            eng.inject([rm.Msg(s, rm.READY, 1, h, d) for d in range(n)])
        eng.run()
        assert eng.outcomes()[s] is None


@pytest.mark.parametrize("n,f", NF_ALL)
def test_split_brain_never_splits(n, f):
    """Split-brain init/echo/ready equivocation under adversarial schedules:
    acceptance stays single-valued at every prefix and all-or-nothing at
    quiescence, whatever the partition, helper set, or delivery order."""
    s = n - 1
    correct = list(range(n - f))
    helpers = list(range(n - f, n - 1))
    half = len(correct) // 2
    partitions = [
        (correct[:half], correct[half:]),
        (correct[:1], correct[1:]),
        (correct, correct[-1:]),
    ]
    priorities = [None, rm.priority_value_first(0), rm.priority_value_first(1),
                  rm.priority_starve(correct[:half])]
    outcomes = set()
    for part0, part1 in partitions:
        for dual_ready in (False, True):
            for pi, pri in enumerate(priorities):
                eng = _engine(n, f, seed=pi, priority=pri, check_every=1)
                rm.scripted_split(eng, s, part0, part1, helpers=helpers,
                                  dual_ready=dual_ready)
                eng.run()
                outcomes.add(eng.outcomes()[s])
    assert outcomes <= {None, 0, 1}


@pytest.mark.parametrize("n,f", NF_ALL)
def test_double_init_schedule_spans_knob_set(n, f):
    """Sender inits BOTH values to everyone (first-init-wins makes each correct
    replica's echo schedule-dependent): delivery order alone then selects the
    outcome — value-0-first yields 0, value-1-first yields 1, random order stays
    within the knob set. The adversary's freedom is exactly {None, 0, 1}."""
    s = n - 1
    correct = list(range(n - f))
    got = set()
    for pri, expect in [(rm.priority_value_first(0), 0),
                        (rm.priority_value_first(1), 1)]:
        eng = _engine(n, f, seed=0, priority=pri, check_every=1)
        rm.scripted_split(eng, s, correct, correct)
        eng.run()
        assert eng.outcomes()[s] == expect
        got.add(expect)
    for seed in range(6):
        eng = _engine(n, f, seed=seed, check_every=1)
        rm.scripted_split(eng, s, correct, correct)
        eng.run()
        got.add(eng.outcomes()[s])
    assert got <= {None, 0, 1} and {0, 1} <= got


@pytest.mark.parametrize("n,f", NF_ALL)
def test_reactive_rushing_cannot_split(n, f):
    """A rushing adversary that watches every delivery and echoes the opposing
    value at replicas one echo short of quorum still cannot split acceptance."""
    s = n - 1
    correct = list(range(n - f))
    helpers = list(range(n - f, n))
    half = len(correct) // 2
    for seed in range(4):
        eng = _engine(n, f, seed, check_every=1)
        eng.add_reactive(rm.reactive_tipper(helpers))
        rm.scripted_split(eng, s, correct[:half], correct[half:], helpers=helpers)
        eng.run()
        assert eng.outcomes()[s] in (None, 0, 1)


# -- full-instance oracle match ------------------------------------------------

FAST_CFGS = [
    SimConfig(protocol="bracha", n=4, f=1, instances=10, adversary="none", coin="shared",
              round_cap=32, seed=7),
    SimConfig(protocol="bracha", n=4, f=1, instances=10, adversary="byzantine", coin="shared",
              round_cap=32, seed=11),
    SimConfig(protocol="bracha", n=7, f=2, instances=10, adversary="byzantine", coin="shared",
              round_cap=32, seed=13),
    SimConfig(protocol="bracha", n=7, f=2, instances=10, adversary="adaptive", coin="shared",
              round_cap=32, seed=17),
    # adaptive_min + the count-level deliveries (VERDICT r4 #3): the instrument
    # must validate the models the benchmark ships, not only the §4 mask.
    SimConfig(protocol="bracha", n=7, f=2, instances=10, adversary="adaptive_min",
              coin="shared", round_cap=32, seed=41),
    SimConfig(protocol="bracha", n=7, f=2, instances=10, adversary="adaptive_min",
              coin="shared", round_cap=32, seed=43, delivery="urn"),
    SimConfig(protocol="bracha", n=7, f=2, instances=10, adversary="none",
              coin="shared", round_cap=32, seed=47, delivery="urn"),
    SimConfig(protocol="bracha", n=7, f=2, instances=10, adversary="byzantine",
              coin="shared", round_cap=32, seed=53, delivery="urn2"),
    SimConfig(protocol="bracha", n=7, f=2, instances=10, adversary="adaptive",
              coin="shared", round_cap=32, seed=59, delivery="urn2"),
    # §4c leg: the count-realizing hold fed urn3 counts (this PR) — tier-1
    # coverage for the new dispatch at instrument-fast scale.
    SimConfig(protocol="bracha", n=7, f=2, instances=10, adversary="adaptive_min",
              coin="shared", round_cap=32, seed=113, delivery="urn3"),
]
SLOW_CFGS = [
    SimConfig(protocol="bracha", n=10, f=3, instances=4, adversary="byzantine", coin="shared",
              round_cap=32, seed=19),
    SimConfig(protocol="bracha", n=13, f=4, instances=4, adversary="adaptive", coin="shared",
              round_cap=32, seed=23),
    SimConfig(protocol="bracha", n=13, f=4, instances=4, adversary="byzantine", coin="local",
              round_cap=5, seed=29),  # exercises the round-cap/overflow path
    SimConfig(protocol="bracha", n=13, f=4, instances=4, adversary="adaptive_min",
              coin="shared", round_cap=32, seed=61),
    SimConfig(protocol="bracha", n=13, f=4, instances=4, adversary="adaptive_min",
              coin="shared", round_cap=32, seed=67, delivery="urn"),
    SimConfig(protocol="bracha", n=13, f=4, instances=4, adversary="crash",
              coin="local", round_cap=16, seed=71, delivery="urn"),
    SimConfig(protocol="bracha", n=10, f=3, instances=4, adversary="crash",
              coin="local", round_cap=16, seed=83),  # crash on the keys leg

    SimConfig(protocol="bracha", n=13, f=4, instances=4, adversary="adaptive",
              coin="shared", round_cap=32, seed=73, delivery="urn2"),
    # one n=16 config (VERDICT r4 weak #3): the largest instrument scale.
    SimConfig(protocol="bracha", n=16, f=5, instances=3, adversary="byzantine",
              coin="shared", round_cap=32, seed=79, delivery="urn2"),
]
# Large-n legs (VERDICT r5 next #7): n ∈ {25, 31}, byzantine + adaptive_min,
# urn2 + keys, plus the §4c legs the law-agnostic count-realizing hold now
# admits (urn3 counts are support-clamped, hence always hold-feasible).
LARGE_CFGS = [
    SimConfig(protocol="bracha", n=25, f=8, instances=2, adversary="byzantine",
              coin="shared", round_cap=32, seed=89, delivery="urn2"),
    SimConfig(protocol="bracha", n=25, f=8, instances=2, adversary="adaptive_min",
              coin="shared", round_cap=32, seed=97, delivery="keys"),
    SimConfig(protocol="bracha", n=25, f=8, instances=2, adversary="adaptive_min",
              coin="shared", round_cap=32, seed=107, delivery="urn3"),
    SimConfig(protocol="bracha", n=31, f=10, instances=1, adversary="adaptive_min",
              coin="shared", round_cap=32, seed=101, delivery="urn2"),
    SimConfig(protocol="bracha", n=31, f=10, instances=1, adversary="byzantine",
              coin="shared", round_cap=32, seed=103, delivery="keys"),
    SimConfig(protocol="bracha", n=31, f=10, instances=1, adversary="byzantine",
              coin="shared", round_cap=32, seed=109, delivery="urn3"),
]
ALL_CFGS = FAST_CFGS + [pytest.param(c, marks=pytest.mark.slow) for c in SLOW_CFGS]


def _cfg_id(c):
    c = getattr(c, "values", (c,))[0]
    return f"{c.delivery}-n{c.n}-{c.adversary}"


@pytest.mark.parametrize("cfg", ALL_CFGS, ids=_cfg_id)
def test_instance_matches_count_level_oracle(cfg):
    """A full consensus instance simulated on message-level RBC — every protocol
    message delivered individually, adversary knobs realized by randomized
    message strategies, §5.1b validation receiver-local, wait quotas from
    message arrival order under the delivery-realizing schedule (§4 mask hold,
    or the §4b/§4b-v2 count-realizing hold) — reproduces the count-level CPU
    oracle exactly. This is the abstraction-validity artifact VERDICT r3 #1
    asked for, extended to the shipped delivery models (VERDICT r4 #3): the
    per-step asserts inside run_message_instance are the theorem, the
    (rounds, decision) equality is the corollary. Fast configs run 10 instances
    under two independent scheduler/realization seed grids (VERDICT r4 weak #3)."""
    ids = np.arange(min(cfg.instances, 10))
    oracle = CpuBackend().run(cfg, ids)
    seed_grids = (100, 500) if cfg.n <= 7 else (100,)
    for base in seed_grids:
        for k, inst in enumerate(ids):
            got = rm.run_message_instance(cfg, int(inst),
                                          rng=random.Random(base + k))
            assert got == (int(oracle.rounds[k]), int(oracle.decision[k]))


@pytest.mark.parametrize("adversary,init,expect", [
    ("none", "all0", 0), ("byzantine", "all0", 0), ("byzantine", "all1", 1),
    ("adaptive", "all0", 0), ("adaptive_min", "all0", 0),
    ("adaptive_min", "all1", 1),
])
def test_free_schedule_validity_and_agreement(adversary, init, expect):
    """Schedule-free soundness: with wait quotas taken from raw message-arrival
    order under a random schedule (no §4 input anywhere), unanimous-init
    instances still decide the common value in one round — §5.2's liveness
    argument holds at message level, not just in the count model."""
    cfg = SimConfig(protocol="bracha", n=7, f=2, instances=4, adversary=adversary,
                    coin="shared", round_cap=16, init=init, seed=3)
    for inst in range(2):
        rounds, decision = rm.run_message_instance_free(
            cfg, inst, rng=random.Random(inst))
        assert (rounds, decision) == (1, expect)


@pytest.mark.slow
@pytest.mark.parametrize("cfg", LARGE_CFGS, ids=_cfg_id)
def test_instance_matches_count_level_oracle_large_n(cfg):
    """The message-level instrument at n ∈ {25, 31} (VERDICT r5 next #7):
    every count-level assertion of run_message_instance — wire equality,
    receiver-local §5.1b validation, and the delivery-realizing hold (mask
    row for keys, per-class count targets for urn2/urn3, the latter via the
    §4c-aware feed of the law-agnostic hold) — at double the previous largest
    instrument scale, plus the (rounds, decision) oracle corollary."""
    ids = np.arange(cfg.instances)
    oracle = CpuBackend().run(cfg, ids)
    for k, inst in enumerate(ids):
        got = rm.run_message_instance(cfg, int(inst),
                                      rng=random.Random(300 + k))
        assert got == (int(oracle.rounds[k]), int(oracle.decision[k]))


@pytest.mark.slow
def test_free_schedule_agreement_random_init():
    """Random inits, free schedule: decisions may legitimately differ from the
    count-level oracle (different delivered sets), but agreement/termination must
    hold — asserted inside run_message_instance_free."""
    cfg = SimConfig(protocol="bracha", n=10, f=3, instances=4, adversary="byzantine",
                    coin="shared", round_cap=32, seed=31)
    for inst in range(4):
        rounds, decision = rm.run_message_instance_free(
            cfg, inst, rng=random.Random(40 + inst))
        assert decision in (0, 1) and rounds <= cfg.round_cap
