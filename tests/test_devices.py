"""Tunnel-resilient device discovery (utils/devices.py): the probe/fallback
decision logic with the probe and plugin-drop injected, so no real tunnel (or
hang) is involved."""

import pytest

from byzantinerandomizedconsensus_tpu.utils import devices


@pytest.fixture
def no_cpu_env(monkeypatch):
    # conftest forces JAX_PLATFORMS=cpu for the suite; these tests exercise the
    # non-forced (headless bench/CLI) entry conditions.
    monkeypatch.setenv("JAX_PLATFORMS", "axon,cpu")


def test_cpu_env_skips_probe_but_still_drops_plugins(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    calls = []
    out = devices.ensure_live_backend(probe=lambda t: calls.append(t),
                                      force_cpu=lambda: calls.append("force"))
    # No subprocess probe, but the plugin drop must run: the tunnel plugin's
    # registration overrides the env var, so cpu-env alone does not protect.
    assert out == "cpu-env" and calls == ["force"]


def test_live_probe_leaves_platform_alone(no_cpu_env):
    forced = []
    out = devices.ensure_live_backend(probe=lambda t: True,
                                      force_cpu=lambda: forced.append(1))
    assert out == "ok" and not forced


def test_dead_probe_forces_cpu_and_warns(no_cpu_env):
    forced, warnings = [], []
    out = devices.ensure_live_backend(timeout_s=7.0,
                                      probe=lambda t: False,
                                      force_cpu=lambda: forced.append(1),
                                      warn=warnings.append)
    assert out == "cpu-fallback"
    assert forced == [1]
    assert warnings and "7s" in warnings[0]


def test_default_probe_detects_broken_interpreter(monkeypatch, no_cpu_env):
    """The real subprocess probe, pointed at a python that exits non-zero."""
    import subprocess

    real_run = subprocess.run

    def fake_run(cmd, **kw):
        raise subprocess.TimeoutExpired(cmd, kw.get("timeout", 0))

    monkeypatch.setattr(subprocess, "run", fake_run)
    assert devices._default_probe(0.1) is False
    monkeypatch.setattr(subprocess, "run", real_run)
