"""Tunnel-resilient device discovery (utils/devices.py): the hazard-gate and
probe/fallback decision logic with the probe and plugin-drop injected, so no
real tunnel (or hang) is involved."""

import pytest

from byzantinerandomizedconsensus_tpu.utils import devices


@pytest.fixture
def hazard_env(monkeypatch):
    # Simulate an axon-tunnel machine: plugin marker present, platform list
    # not CPU-forced (the headless bench/CLI entry conditions).
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "192.0.2.1")
    monkeypatch.setenv("JAX_PLATFORMS", "axon,cpu")


def test_no_hazard_skips_probe_and_force(monkeypatch):
    monkeypatch.setattr(devices, "_tunnel_hazard_present", lambda: False)
    calls = []
    out = devices.ensure_live_backend(probe=lambda t: calls.append("probe"),
                                      force_cpu=lambda: calls.append("force"))
    assert out == "no-hazard" and calls == []


def test_cpu_env_skips_probe_but_still_drops_plugins(monkeypatch):
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "192.0.2.1")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    calls = []
    out = devices.ensure_live_backend(probe=lambda t: calls.append(t),
                                      force_cpu=lambda: calls.append("force"))
    # No subprocess probe, but the plugin drop must run: the tunnel plugin's
    # registration overrides the env var, so cpu-env alone does not protect.
    assert out == "cpu-env" and calls == ["force"]


def test_live_probe_leaves_platform_alone(hazard_env):
    forced = []
    out = devices.ensure_live_backend(probe=lambda t: True,
                                      force_cpu=lambda: forced.append(1))
    assert out == "ok" and not forced


def test_dead_probe_forces_cpu_and_warns(hazard_env):
    forced, warnings = [], []
    out = devices.ensure_live_backend(timeout_s=7.0,
                                      probe=lambda t: False,
                                      force_cpu=lambda: forced.append(1),
                                      warn=warnings.append)
    assert out == "cpu-fallback"
    assert forced == [1]
    assert warnings and "7s" in warnings[0]


def test_hazard_detection_env_markers(monkeypatch):
    monkeypatch.delenv("PALLAS_AXON_POOL_IPS", raising=False)
    monkeypatch.setenv("JAX_PLATFORMS", "axon,cpu")
    assert devices._tunnel_hazard_present()
    monkeypatch.setenv("JAX_PLATFORMS", "")
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "192.0.2.1")
    assert devices._tunnel_hazard_present()


def test_default_probe_detects_broken_interpreter(monkeypatch, hazard_env):
    """The real subprocess probe, pointed at a python that times out."""
    import subprocess

    def fake_run(cmd, **kw):
        raise subprocess.TimeoutExpired(cmd, kw.get("timeout", 0))

    monkeypatch.setattr(subprocess, "run", fake_run)
    assert devices._default_probe(0.1) is False
