"""Round-22 durability/elasticity tests (serve/wal.py, serve/autoscale.py).

Tier-1 layer: the write-ahead admission log's property surface (torn-line
tolerance, tail repair, replay idempotence, bit-identical recovery —
session envelopes included), the autoscaler control law driven
deterministically through an injected clock and a fake fleet, the
recovering-503 admission gate, and the real thread-fleet scale-up /
scale-down path with bit-identical replies. Slow layer: the budgeted
respawn ladder on a real subprocess fleet, and the ``loadgen --scenario
dispatcher_kill --smoke`` drill end-to-end in a subprocess (SIGKILL,
restart, ``--recover``, schema-v1.13 artifact).
"""

import dataclasses
import json
import os
import subprocess
import sys
import time

import pytest

from byzantinerandomizedconsensus_tpu.backends.base import get_backend
from byzantinerandomizedconsensus_tpu.backends.compaction import CompactionPolicy
from byzantinerandomizedconsensus_tpu.config import SimConfig
from byzantinerandomizedconsensus_tpu.models import session as _session
from byzantinerandomizedconsensus_tpu.obs import metrics as _metrics
from byzantinerandomizedconsensus_tpu.obs import record
from byzantinerandomizedconsensus_tpu.serve import admission
from byzantinerandomizedconsensus_tpu.serve.autoscale import Autoscaler
from byzantinerandomizedconsensus_tpu.serve.fleet import FleetServer
from byzantinerandomizedconsensus_tpu.serve.server import (ConsensusServer,
                                                           serve_http)
from byzantinerandomizedconsensus_tpu.serve.wal import (WAL_NAME,
                                                        WriteAheadLog)

_POLICY = CompactionPolicy(width=8, segment=1)


def _cfg(seed: int, **kw) -> SimConfig:
    base = dict(protocol="benor", n=5, f=1, instances=4, adversary="none",
                coin="local", init="random", seed=seed, round_cap=32,
                delivery="keys")
    base.update(kw)
    return SimConfig(**base).validate()


def _offline(cfg):
    ref = get_backend("numpy").run(cfg)
    return [int(r) for r in ref.rounds], [int(d) for d in ref.decision]


# ------------------------------------------------------------------ WAL --


def test_wal_round_trip_plan_and_counter(tmp_path):
    wal = WriteAheadLog(str(tmp_path))
    cfg_doc = dataclasses.asdict(_cfg(1))
    wal.append_admit("r000001", cfg_doc, {})
    wal.append_done("r000001")
    wal.append_admit("r000002", cfg_doc, {"session_slots": 2})
    wal.append_admit("r000007", cfg_doc, {})
    wal.append_done("r000007", failed=True)
    wal.close()

    entries = WriteAheadLog.read_entries(str(tmp_path))
    assert [e["op"] for e in entries] == ["admit", "done", "admit",
                                         "admit", "fail"]
    plan, counter = WriteAheadLog.plan_recovery(str(tmp_path))
    assert [e["id"] for e in plan] == ["r000002"]  # done AND fail both close
    assert plan[0]["env"] == {"session_slots": 2}
    assert counter == 7  # resume past the highest id, not the open one


def test_wal_tolerates_torn_final_line_only(tmp_path):
    wal = WriteAheadLog(str(tmp_path))
    cfg_doc = dataclasses.asdict(_cfg(2))
    wal.append_admit("r000001", cfg_doc, {})
    wal.append_admit("r000002", cfg_doc, {})
    wal.close()
    path = tmp_path / WAL_NAME

    # a crash mid-append tears the FINAL line: reads drop it silently
    whole = path.read_text()
    path.write_text(whole + '{"op": "admit", "id": "r0000')
    plan, counter = WriteAheadLog.plan_recovery(str(tmp_path))
    assert [e["id"] for e in plan] == ["r000001", "r000002"]
    assert counter == 2

    # the same tear ANYWHERE else is corruption, not a crash: loud failure
    lines = whole.splitlines()
    path.write_text("\n".join([lines[0][: len(lines[0]) // 2]]
                              + lines[1:]) + "\n")
    with pytest.raises(ValueError, match="only the final line may be torn"):
        WriteAheadLog.read_entries(str(tmp_path))


def test_wal_repairs_torn_tail_before_appending(tmp_path):
    """Opening for append truncates a torn final line first — otherwise
    recovery's own completion records would land after the tear and turn
    a tolerated crash signature into mid-file corruption."""
    wal = WriteAheadLog(str(tmp_path))
    cfg_doc = dataclasses.asdict(_cfg(3))
    wal.append_admit("r000001", cfg_doc, {})
    wal.close()
    path = tmp_path / WAL_NAME
    path.write_text(path.read_text() + '{"op": "admit", "id')

    wal2 = WriteAheadLog(str(tmp_path))  # the repair seam
    wal2.append_done("r000001")
    wal2.close()
    entries = WriteAheadLog.read_entries(str(tmp_path))
    assert [e["op"] for e in entries] == ["admit", "done"]

    # a newline-terminated but unparseable tail is repaired the same way
    path.write_text(path.read_text() + "}}}not json{{{\n")
    wal3 = WriteAheadLog(str(tmp_path))
    wal3.append_admit("r000002", cfg_doc, {})
    wal3.close()
    assert [e["op"] for e in WriteAheadLog.read_entries(str(tmp_path))] \
        == ["admit", "done", "admit"]


def test_recovery_replays_bit_identical_and_idempotent(tmp_path):
    """The tentpole's replay law at the library seam: journaled admits
    with no completion replay under their ORIGINAL ids with replies
    bit-identical to the offline oracle, the id counter resumes past the
    journal, completed work never replays, and recovering twice is a
    no-op (replaying appends fresh completion records)."""
    cfgs = [_cfg(10), _cfg(11), _cfg(12)]
    wal = WriteAheadLog(str(tmp_path))
    for i, c in enumerate(cfgs):
        wal.append_admit(f"r{i + 1:06d}", dataclasses.asdict(c), {})
    wal.append_done("r000002")  # this one replied before the crash
    wal.close()

    srv = ConsensusServer(backend="numpy", policy=_POLICY,
                          wal_dir=str(tmp_path)).start()
    try:
        out = srv.recover(timeout=600.0)
        assert out["ids"] == ["r000001", "r000003"]
        assert out["replayed"] == 2 and out["recovered"] == 2
        assert srv.recovering is False
        for rid, h, c in zip(out["ids"], out["handles"],
                             [cfgs[0], cfgs[2]]):
            rec = h.wait(timeout=600.0)
            assert rec["request_id"] == rid
            rounds, decision = _offline(c)
            assert rec["rounds"] == rounds
            assert rec["decision"] == decision
        # counter resumed: the next fresh admission continues the sequence
        h = srv.submit(_cfg(13))
        assert h.id == "r000004"
        h.wait(timeout=600.0)
        # idempotence: the journal now pairs every admit — nothing replays
        out2 = srv.recover(timeout=600.0)
        assert out2["replayed"] == 0 and out2["ids"] == []
    finally:
        srv.shutdown()


def test_recovery_reproduces_session_envelopes(tmp_path):
    """A journaled session envelope recovers as a full spec-§11 session:
    the replayed reply carries the per-slot log and is bit-identical to
    the offline ``run_session`` chain from the base seed."""
    cfg = _cfg(21)
    wal = WriteAheadLog(str(tmp_path))
    wal.append_admit("r000001", dataclasses.asdict(cfg),
                     {"session_slots": 3})
    wal.close()

    srv = ConsensusServer(backend="numpy", policy=_POLICY,
                          wal_dir=str(tmp_path)).start()
    try:
        out = srv.recover(timeout=600.0)
        assert out["recovered"] == 1
        rec = out["handles"][0].wait(timeout=600.0)
        blk = rec["session"]
        assert blk["slots"] == 3 and len(blk["rounds"]) == 3
        be = get_backend("numpy")
        served = list(zip(blk["rounds"], blk["decisions"]))
        assert _session.replay_matches(be, cfg, served)
        ref = _session.run_session(be, cfg, 3)
        assert blk["decisions"][-1] == [int(d) for d in ref[-1].decision]
    finally:
        srv.shutdown()


# ----------------------------------------------------------- autoscaler --


class _FakeFleet:
    """stats()/scale_up()/scale_down() surface for deterministic tick
    tests — outstanding work and worker count are plain knobs."""

    def __init__(self, routable: int = 1, outstanding: int = 0):
        self.routable = routable
        self.outstanding = outstanding
        self.ups = 0
        self.downs = 0

    def stats(self, live=False):
        return {"workers": self.routable, "routable": self.routable,
                "submitted": self.outstanding, "replied": 0, "failed": 0,
                "cancelled": 0}

    def scale_up(self):
        self.routable += 1
        self.ups += 1
        return self.routable - 1

    def scale_down(self, idx=None):
        self.routable -= 1
        self.downs += 1
        return self.routable


def test_autoscaler_rejects_bad_shape():
    fl = _FakeFleet()
    with pytest.raises(ValueError, match="min_workers"):
        Autoscaler(fl, min_workers=0)
    with pytest.raises(ValueError, match="min_workers"):
        Autoscaler(fl, min_workers=3, max_workers=2)
    with pytest.raises(ValueError, match="deadband"):
        Autoscaler(fl, up_per_worker=1.0, down_per_worker=1.0)


def test_autoscaler_control_law_hysteresis_cooldown_and_bounds():
    """The control law on an injected clock: scale-up needs ``up_ticks``
    of sustained pressure, the post-action cooldown blocks flapping,
    bounds hold at both ends, and scale-down needs the (longer)
    ``down_ticks`` streak."""
    t = [0.0]
    fl = _FakeFleet(routable=1, outstanding=10)
    sc = Autoscaler(fl, min_workers=1, max_workers=3, up_per_worker=4.0,
                    down_per_worker=0.5, up_ticks=2, down_ticks=3,
                    cooldown_s=10.0, clock=lambda: t[0])
    assert sc.tick() == "hold"            # hot streak 1 < up_ticks
    assert sc.tick() == "up"              # streak 2: scale to 2 workers
    assert fl.routable == 2
    # pressure 5 >= 4 is still hot, but the cooldown pins the fleet
    assert sc.tick() == "hold" and sc.tick() == "hold"
    t[0] = 11.0                           # cooldown expired
    assert sc.tick() == "up"              # sustained streak carries over
    assert fl.routable == 3
    t[0] = 22.0
    for _ in range(5):                    # at max_workers: hot but capped
        assert sc.tick() == "hold"
    assert fl.ups == 2

    fl.outstanding = 0                    # the crowd is gone
    assert sc.tick() == "hold" and sc.tick() == "hold"  # cold streak 1, 2
    assert sc.tick() == "down"            # streak 3 == down_ticks
    assert fl.routable == 2
    t[0] = 40.0
    for _ in range(2):
        assert sc.tick() == "hold"
    assert sc.tick() == "down"
    assert fl.routable == 1
    t[0] = 60.0
    for _ in range(5):                    # at min_workers: never below
        assert sc.tick() == "hold"
    assert fl.downs == 2
    assert sc.stop() == {"ups": 2, "downs": 2}


def test_autoscaler_deadband_holds():
    """Pressure inside (down_per_worker, up_per_worker) never moves the
    fleet, no matter how long it persists."""
    fl = _FakeFleet(routable=2, outstanding=4)   # 2.0 per worker
    sc = Autoscaler(fl, min_workers=1, max_workers=4, up_per_worker=4.0,
                    down_per_worker=0.5, up_ticks=1, down_ticks=2,
                    cooldown_s=0.0, clock=lambda: 0.0)
    for _ in range(10):
        assert sc.tick() == "hold"
    assert fl.ups == 0 and fl.downs == 0


def test_thread_fleet_autoscale_round_trip_bit_identical():
    """The real seam under the law: a backlogged one-worker thread fleet
    scales up on sustained pressure, the newcomer absorbs stealable work,
    the idle fleet scales back down gracefully (retired, not lost), and
    every reply is bit-identical to the offline oracle."""
    cfgs = [_cfg(50 + i, protocol=p, n=n, delivery=d)
            for i, (p, n, d) in enumerate(
                [("benor", 5, "keys"), ("bracha", 7, "keys"),
                 ("benor", 5, "urn2")] * 2)]
    with FleetServer(workers=1, mode="thread", backend="numpy",
                     policy=_POLICY, segment_latency_s=0.05) as fl:
        sc = Autoscaler(fl, min_workers=1, max_workers=2,
                        up_per_worker=3.0, down_per_worker=0.5,
                        up_ticks=1, down_ticks=2, cooldown_s=0.0)
        handles = [fl.submit(c) for c in cfgs]
        deadline = time.monotonic() + 60.0
        while sc.tick() != "up":          # backlog of 6 on 1 worker: hot
            assert time.monotonic() < deadline
            time.sleep(0.01)
        assert fl.stats(live=False)["routable"] == 2
        for h, c in zip(handles, cfgs):
            rec = h.wait(timeout=600.0)
            rounds, decision = _offline(c)
            assert rec["rounds"] == rounds and rec["decision"] == decision
        deadline = time.monotonic() + 60.0
        while sc.tick() != "down":        # drained: sustained cold
            assert time.monotonic() < deadline
            time.sleep(0.01)
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline and fl.health().get("retiring"):
            time.sleep(0.05)
        st = fl.stats(live=False)
        assert st["routable"] == 1
        assert st["lost_workers"] == 0 and st["retired_workers"] == 1
        assert fl.health()["ok"] is True


# ------------------------------------------------------ recovering gate --


def test_submit_during_recovery_rejects_503_with_retry_after(tmp_path):
    """While a recovery replay is in progress, fresh submits answer 503
    with the named ``recovering`` reason, a Retry-After hint, and the
    ``brc_serve_rejected_total{reason="recovering"}`` count — replayed
    work never races fresh admissions."""
    import threading
    import urllib.error
    import urllib.request

    _metrics.configure()
    try:
        with ConsensusServer(backend="numpy", policy=_POLICY) as srv:
            httpd = serve_http(srv, host="127.0.0.1", port=0)
            threading.Thread(target=httpd.serve_forever,
                             daemon=True).start()
            host, port = httpd.server_address[:2]
            base = f"http://{host}:{port}"
            try:
                srv._recovering = True    # hold the replay window open
                with pytest.raises(admission.Backpressure) as exc:
                    srv.submit(_cfg(60))
                assert exc.value.reason == "recovering"

                body = json.dumps(dataclasses.asdict(_cfg(61))).encode()
                req = urllib.request.Request(
                    base + "/submit", data=body,
                    headers={"Content-Type": "application/json"})
                with pytest.raises(urllib.error.HTTPError) as exc:
                    urllib.request.urlopen(req, timeout=30)
                assert exc.value.code == 503
                assert float(exc.value.headers["Retry-After"]) > 0
                doc = json.loads(exc.value.read())
                assert doc["reason"] == "recovering"

                snap = _metrics.snapshot()
                series = snap["brc_serve_rejected_total"]["series"]
                assert any(s["labels"].get("reason") == "recovering"
                           and s["value"] >= 2 for s in series)

                srv._recovering = False   # replay done: the door reopens
                with urllib.request.urlopen(req, timeout=30) as r:
                    assert r.status == 200
            finally:
                srv._recovering = False
                httpd.shutdown()
                httpd.server_close()
    finally:
        _metrics.disable()


# -------------------------------------------------------- respawn budget --


@pytest.mark.slow
def test_process_fleet_respawn_budget_and_terminal_state():
    """Satellite: ``max_respawns`` replaces a crashed worker through the
    backoff ladder (health returns to ok — the loss is absorbed, not just
    reported) until the budget is spent, at which point the fleet lands
    in the NAMED terminal state instead of silently shrinking."""
    with FleetServer(workers=2, mode="process", policy=_POLICY,
                     max_respawns=1) as fleet:
        fleet._workers[0].kill()
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            st = fleet.stats(live=False)
            if st["respawns"]["used"] == 1 and fleet.health()["ok"]:
                break
            time.sleep(0.1)
        health = fleet.health()
        assert health["ok"] is True, health  # replaced: green again
        assert st["lost_workers"] == 1
        # the replacement still serves bit-identically
        h = fleet.submit(_cfg(70))
        rec = h.wait(timeout=600.0)
        rounds, decision = _offline(_cfg(70))
        assert rec["rounds"] == rounds and rec["decision"] == decision

        # spend past the budget: the next loss is terminal, and named
        with fleet._cv:
            victim = next(w for w in fleet._workers
                          if w.alive and not w.retiring)
        victim.kill()
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            health = fleet.health()
            if health.get("terminal"):
                break
            time.sleep(0.1)
        assert health["terminal"] == "respawn_budget_exhausted"
        assert health["ok"] is False
        st = fleet.stats(live=False)
        assert st["respawns"] == {"budget": 1, "used": 1,
                                  "terminal": "respawn_budget_exhausted"}


# ------------------------------------------------------ subprocess drill --


def test_dispatcher_kill_drill_smoke_subprocess(tmp_path):
    """The kill-the-dispatcher recovery drill end-to-end through the
    ``loadgen --scenario`` delegation in a real subprocess: SIGKILL
    mid-stream, restart with ``--recover``, exit 0, and a valid
    schema-v1.13 record whose elastic block proves recovered work with
    zero mismatches and zero steady-state recompiles."""
    out = tmp_path / "elastic_smoke.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m",
         "byzantinerandomizedconsensus_tpu.tools.loadgen",
         "--scenario", "dispatcher_kill", "--smoke", "--backend", "numpy",
         "--out", str(out)],
        capture_output=True, text=True, timeout=560, env=env)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    doc = json.loads(out.read_text())
    assert record.validate_record(doc) == [], doc
    assert doc["record_revision"] == record.RECORD_REVISION
    eb = doc["elastic"]
    assert eb["mismatches"] == 0
    assert eb["steady_state_compiles"] == 0
    assert eb["recovered"] >= 1
    assert eb["slo_ok"] is True
    (row,) = eb["scenarios"]
    assert row["scenario"] == "dispatcher_kill"
    # pre-kill replies plus recovered replays cover every admitted
    # request; the sum can exceed requests when the SIGKILL lands after a
    # reply but before its WAL completion record is flushed — that
    # request replays too, which is exactly what idempotence is for
    assert row["replied"] + row["recovered"] >= row["requests"]
    assert row["recovered"] == row["owed"] >= 1
    assert row["slo_ok"] is True
