"""Tier-1 serve smoke (round 14): the in-process consensus service.

Pins the tentpole seams: admission → fused bucket → continuously-batched
compacted lane grid → streamed schema-v1.5 reply records; graceful
shutdown draining in-flight lanes (no lost requests); the thread-safe
``CompileCache`` under concurrent access; and the serve trace kinds the
follow heartbeat consumes.
"""

import threading

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu.backends.base import get_backend
from byzantinerandomizedconsensus_tpu.backends.batch import CompileCache
from byzantinerandomizedconsensus_tpu.backends.compaction import (
    CompactionPolicy, WorkFeed)
from byzantinerandomizedconsensus_tpu.config import SimConfig
from byzantinerandomizedconsensus_tpu.obs import record
from byzantinerandomizedconsensus_tpu.obs import trace
from byzantinerandomizedconsensus_tpu.serve import admission
from byzantinerandomizedconsensus_tpu.serve.server import ConsensusServer

#: Small lane grid: fast compiles, still exercises refill (instances > W).
_POLICY = CompactionPolicy(width=8, segment=1)

#: Mixed-shape batch: two fused buckets (protocol × delivery), heterogeneous
#: n/f/instances/adversary/round_cap within them.
_CFGS = [
    SimConfig(protocol="benor", n=5, f=1, instances=6, seed=3,
              round_cap=32),
    SimConfig(protocol="benor", n=9, f=3, instances=12, seed=21,
              round_cap=64, adversary="crash", init="split"),
    SimConfig(protocol="bracha", n=7, f=2, instances=4, seed=9,
              round_cap=32, delivery="urn"),
    SimConfig(protocol="bracha", n=10, f=3, instances=9, seed=77,
              round_cap=64, delivery="urn", adversary="byzantine"),
]


def test_serve_smoke_mixed_shapes_bit_identical():
    """The round-trip: mixed-shape requests through the service, every
    reply a valid schema-v1.5 record, bit-identical to the per-config
    offline path, clean shutdown with nothing lost."""
    with ConsensusServer(policy=_POLICY) as srv:
        handles = [srv.submit(c) for c in _CFGS]
        recs = [h.wait(timeout=600.0) for h in handles]
        stats = srv.stats()
    assert stats["submitted"] == len(_CFGS)
    assert stats["replied"] == len(_CFGS)
    assert stats["failed"] == 0

    offline = get_backend("numpy")
    for cfg, h, rec in zip(_CFGS, handles, recs):
        assert record.validate_record(rec) == [], rec
        assert rec["record_revision"] == record.RECORD_REVISION
        assert rec["kind"] == "serve_reply"
        assert rec["request_id"] == h.id
        assert rec["config"]["n"] == cfg.n
        assert rec["latency_s"] > 0
        ref = offline.run(cfg)
        assert rec["rounds"] == [int(r) for r in ref.rounds]
        assert rec["decision"] == [int(d) for d in ref.decision]


def test_serve_shutdown_drains_in_flight():
    """A shutdown racing fresh submissions must drain every queued bucket:
    all requests reply, none are lost or failed."""
    srv = ConsensusServer(policy=_POLICY).start()
    handles = [srv.submit(c) for c in _CFGS]
    srv.shutdown(drain=True)  # immediately: lanes still in flight
    for h in handles:
        rec = h.wait(timeout=600.0)  # already done post-drain
        assert rec is not None and h.error is None
    stats = srv.stats()
    assert stats["replied"] == len(_CFGS)
    assert stats["failed"] == 0
    with pytest.raises(RuntimeError, match="shutting down"):
        srv.submit(_CFGS[0])


def test_serve_no_drain_shutdown_fails_pending_by_name():
    srv = ConsensusServer(policy=_POLICY).start()
    srv.shutdown(drain=True)  # empty server: both paths must be clean
    srv2 = ConsensusServer(policy=_POLICY)  # never started: queue only
    req = srv2.submit(_CFGS[0])
    srv2.shutdown(drain=False)
    assert req.done.is_set() and req.error is not None
    with pytest.raises(RuntimeError, match="shutdown before dispatch"):
        req.wait(timeout=1.0)


def test_admission_rejects_bad_requests():
    with pytest.raises(ValueError, match="unknown request field"):
        admission.admit({"n": 5, "banana": 1})
    with pytest.raises(TypeError, match="not a SimConfig or dict"):
        admission.admit(42)
    with pytest.raises(ValueError, match="exceeds the service ceiling"):
        admission.admit(SimConfig(n=4, f=1, round_cap=256),
                        round_cap_ceiling=128)
    with pytest.raises(ValueError):
        admission.admit({"n": 4, "f": 3})  # resilience bound
    cfg = admission.admit({"protocol": "bracha", "n": 7, "f": 2,
                           "instances": 3, "round_cap": 64})
    assert isinstance(cfg, SimConfig) and cfg.protocol == "bracha"
    assert admission.bucket_of(cfg).protocol == "bracha"


def test_serve_reply_carries_optin_invariant_summary():
    """Round-17 satellite: ``submit(cfg, check_invariants=True)`` (or the
    ``check_invariants`` key in a dict payload, the HTTP spelling) makes
    the reply record carry the Agreement/Validity verdicts from the numpy
    reference checker — and stays strictly opt-in."""
    with ConsensusServer(policy=_POLICY) as srv:
        flagged = srv.submit(_CFGS[0], check_invariants=True)
        via_dict = srv.submit({"protocol": "bracha", "n": 7, "f": 2,
                               "instances": 3, "round_cap": 32,
                               "check_invariants": True})
        plain = srv.submit(_CFGS[1])
        rec = flagged.wait(timeout=600.0)
        rec_d = via_dict.wait(timeout=600.0)
        rec_plain = plain.wait(timeout=600.0)
    for doc, n_inst in ((rec, _CFGS[0].instances), (rec_d, 3)):
        inv = doc["invariants"]
        assert inv["checked_instances"] == n_inst
        assert inv["violations"] == 0 and inv["detail"] == []
        assert inv["agreement_ok"] is True
        assert inv["validity_ok"] is True
        assert inv["by_kind"] == {}  # per-kind counts of observed offenders
    assert "invariants" not in rec_plain


def test_serve_span_kinds_emitted():
    """The §3e serve kinds ride every request: request + admit at intake,
    one dispatch span per grid, one reply per retirement."""
    tr = trace.configure()  # in-memory
    try:
        with ConsensusServer(policy=_POLICY) as srv:
            srv.submit(_CFGS[0]).wait(timeout=600.0)
        kinds = {e["kind"] for e in tr.events}
    finally:
        trace.disable()
    for kind in ("serve.request", "serve.admit", "serve.dispatch",
                 "serve.reply"):
        assert kind in kinds, (kind, sorted(kinds))


def test_trace_follow_treats_serve_request_as_heartbeat():
    from byzantinerandomizedconsensus_tpu.tools import trace as trace_tool

    state = {"events": 0, "compiles": 0, "skips": 0, "progress": None,
             "queue": None, "live": None, "total": None,
             "serve_requests": 0, "serve_replies": 0}
    trace_tool._follow_consume(state, {"kind": "serve.request", "attrs": {}})
    trace_tool._follow_consume(state, {"kind": "serve.request", "attrs": {}})
    trace_tool._follow_consume(state, {"kind": "serve.reply", "attrs": {}})
    assert state["serve_requests"] == 2 and state["serve_replies"] == 1
    line = trace_tool._follow_render(state)
    assert "serve 1/2 replied" in line


def test_workfeed_contract():
    feed = WorkFeed(round_cap_ceiling=64)
    cfg = SimConfig(n=4, f=1, round_cap=32)
    feed.push(cfg, token="a")
    with pytest.raises(ValueError, match="exceeds the feed ceiling"):
        feed.push(SimConfig(n=4, f=1, round_cap=128))
    assert feed.pull() == [(cfg, None, "a", None)]
    assert feed.pull() == []  # open + empty
    feed.push(cfg, token="b")
    feed.close()
    with pytest.raises(RuntimeError, match="closed WorkFeed"):
        feed.push(cfg)
    # items pushed before close are still drained, THEN the None sentinel
    assert feed.pull() == [(cfg, None, "b", None)]
    assert feed.pull() is None
    assert feed.pull(block=True) is None


def test_compile_cache_concurrent_access():
    """The round-14 thread-safety satellite: hammer one cache from many
    threads — exactly one build per resident key, consistent counters, LRU
    bound respected."""
    cache = CompileCache(max_entries=8)
    built = []
    build_lock = threading.Lock()

    def make_build(key):
        def build():
            with build_lock:
                built.append(key)
            return lambda x, _k=key: (x, _k)
        return build

    keys = [("bucket", i) for i in range(8)]
    errors = []

    def worker(seed):
        rng = np.random.default_rng(seed)
        try:
            for _ in range(300):
                k = keys[int(rng.integers(len(keys)))]
                fn = cache.get(k, make_build(k))
                out = fn(1)
                assert out == (1, k)
        except Exception as e:  # pragma: no cover - failure reporting
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(s,)) for s in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == []
    stats = cache.stats()
    # 8 keys, capacity 8: every key built exactly once, everything else hit
    assert stats["compiles"] == 8 == len(built)
    assert stats["evictions"] == 0
    assert stats["hits"] == 8 * 300 - 8
    assert len(cache) == 8


def test_compile_cache_concurrent_eviction_consistency():
    """Under capacity pressure the counters must stay coherent (compiles =
    evictions + residents) even with racing threads."""
    cache = CompileCache(max_entries=4)
    keys = [("k", i) for i in range(12)]

    def worker(seed):
        rng = np.random.default_rng(seed)
        for _ in range(200):
            k = keys[int(rng.integers(len(keys)))]
            fn = cache.get(k, lambda _k=k: (lambda: _k))
            assert fn() == k

    threads = [threading.Thread(target=worker, args=(s,)) for s in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stats = cache.stats()
    assert len(cache) == 4
    assert stats["compiles"] - stats["evictions"] == len(cache)
    assert stats["compiles"] + stats["hits"] == 6 * 200


# ---------------------------------------------------------------------------
# the live metrics plane (round 16)


def test_serve_metrics_plane_bit_identical_and_exposed():
    """Round 16: with the metrics registry enabled, replies stay
    bit-identical to the offline path; GET /metrics serves the serve
    families as valid exposition text; /stats carries the one-shape
    per_worker row; health() reports liveness; rejected admissions land
    in the labeled rejection counter."""
    import json as _json
    import urllib.error
    import urllib.request

    from byzantinerandomizedconsensus_tpu.obs import metrics as _metrics
    from byzantinerandomizedconsensus_tpu.serve.server import serve_http

    _metrics.configure()
    try:
        with ConsensusServer(policy=_POLICY) as srv:
            httpd = serve_http(srv, port=0)
            t = threading.Thread(target=httpd.serve_forever, daemon=True)
            t.start()
            handles = [srv.submit(c) for c in _CFGS]
            recs = [h.wait(timeout=600.0) for h in handles]
            with pytest.raises(ValueError, match="service ceiling"):
                srv.submit({"n": 4, "f": 1, "round_cap": 256})
            port = httpd.server_address[1]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
                assert r.status == 200
                assert r.headers["Content-Type"] == _metrics.CONTENT_TYPE
                body = r.read().decode()
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
                health = _json.loads(r.read())
            stats = srv.stats()
            httpd.shutdown()
            httpd.server_close()
    finally:
        _metrics.disable()

    # bit-identity with the plane enabled
    offline = get_backend("numpy")
    for cfg, rec in zip(_CFGS, recs):
        ref = offline.run(cfg)
        assert rec["rounds"] == [int(r) for r in ref.rounds]
        assert rec["decision"] == [int(d) for d in ref.decision]

    # one-shape /stats: the single server reports the fleet row shape
    assert stats["workers"] == 1 and stats["alive"] == 1
    row = stats["per_worker"][0]
    assert {"worker", "pid", "alive", "replied", "steals", "inflight",
            "pending", "load"} <= set(row)
    assert row["replied"] == len(_CFGS) and row["steals"] == 0

    # live-endpoint health: single server, nothing dead
    assert health["ok"] is True and health["dead_workers"] == []

    # the scraped exposition parses back into the serve families
    snap = _metrics.parse_text(body)
    assert (snap["brc_serve_replied_total"]["series"][0]["value"]
            == len(_CFGS))
    lat = snap["brc_serve_request_latency_seconds"]["series"][0]
    assert lat["count"] == len(_CFGS) and lat["sum"] > 0
    qw = snap["brc_serve_queue_wait_seconds"]["series"][0]
    sv = snap["brc_serve_service_seconds"]["series"][0]
    assert qw["count"] == len(_CFGS) and sv["count"] == len(_CFGS)
    rejected = {s["labels"].get("reason"): s["value"]
                for s in snap["brc_serve_rejected_total"]["series"]}
    assert rejected.get("cap_ceiling") == 1
    # compile-cache activity: a cold process compiles, a warm one (earlier
    # tests primed the shared cache) hits — either way the cache families
    # must show the traffic
    cache_traffic = (
        (_metrics._sum_values(snap, "brc_compile_cache_compiles_total") or 0)
        + (_metrics._sum_values(snap, "brc_compile_cache_hits_total") or 0))
    assert cache_traffic > 0
    s = _metrics.summary(snap)
    assert s["replied"] == len(_CFGS) and s["error_rate"] == 0.0
    assert s["p99_latency_ms"] is not None


def test_serve_healthz_degrades_to_503_when_stopped():
    """The /healthz contract: health() is duck-typed off the wrapped
    server; a shut-down single server reports ok=False and worker 0 dead,
    and the endpoint turns that into a 503 with the JSON naming it."""
    import json as _json
    import urllib.error
    import urllib.request

    from byzantinerandomizedconsensus_tpu.serve.server import serve_http

    srv = ConsensusServer(policy=_POLICY).start()
    httpd = serve_http(srv, port=0)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    port = httpd.server_address[1]
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
            assert r.status == 200
        srv.shutdown(drain=True)
        assert srv.health()["ok"] is False
        assert srv.health()["dead_workers"] == [0]
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10)
        assert ei.value.code == 503
        doc = _json.loads(ei.value.read())
        assert doc["ok"] is False and doc["dead_workers"] == [0]
    finally:
        httpd.shutdown()
        httpd.server_close()
