"""Replicated-log session pins (round 21, tier-1).

The spec-§11 law under test: a session is a pure function of
(seed, config, L). Slot k+1's seed derives from slot k's decision vector
through the SESSION_SEND PRF purpose, so the offline replay
(models/session.run_session) must be bit-identical across backends AND
bit-identical to what the serving stack streamed back — the in-grid lane
re-seeding (backends/compaction.py retire seam) is an optimization, never
an observable.
"""

import dataclasses

import pytest

from byzantinerandomizedconsensus_tpu.backends.base import get_backend
from byzantinerandomizedconsensus_tpu.backends.compaction import (
    CompactionPolicy)
from byzantinerandomizedconsensus_tpu.config import SimConfig
from byzantinerandomizedconsensus_tpu.models import session
from byzantinerandomizedconsensus_tpu.ops import prf
from byzantinerandomizedconsensus_tpu.serve import admission
from byzantinerandomizedconsensus_tpu.serve.server import ConsensusServer


def _cfg(seed=91, **kw):
    base = dict(protocol="benor", n=4, f=1, instances=3, adversary="none",
                coin="local", init="random", seed=seed, round_cap=24,
                delivery="keys")
    base.update(kw)
    return SimConfig(**base).validate()


# -- the chain law itself -------------------------------------------------

def test_session_digest_folds_every_decision_bit():
    """The §11 digest is the sequential LCG fold over the decision vector
    (closed affine form == the loop), seeded by the slot index; every
    entry — including an undecided-at-cap 2 — moves it."""
    dec = [1, 0, 1, 2, 0]
    d = (0 + 1) & 0xFFFFFFFF
    for x in dec:
        d = (prf.URN_LCG_A * d + x + 1) & 0xFFFFFFFF
    assert prf.session_digest(0, dec) == d
    # slot index is part of the digest; so is every decision position
    assert prf.session_digest(1, dec) != prf.session_digest(0, dec)
    for i in range(len(dec)):
        flipped = list(dec)
        flipped[i] = 1 - flipped[i] if flipped[i] in (0, 1) else 0
        assert prf.session_digest(0, flipped) != prf.session_digest(0, dec)
    assert prf.session_digest(3, []) == 4  # empty vector: d0 = slot + 1


def test_next_slot_config_is_pure_seed_derivation():
    """Chained init is seed derivation only: everything except the seed is
    the base config, the derived seed is deterministic in
    (seed, slot, decision), and it matches the prf law directly."""
    cfg = _cfg()
    dec = [1, 1, 0]
    nxt = session.next_slot_config(cfg, 0, dec)
    assert nxt.seed == prf.session_chain_seed(cfg.seed, 0, dec,
                                             pack=cfg.pack_version)
    assert dataclasses.replace(nxt, seed=cfg.seed) == cfg
    assert session.next_slot_config(cfg, 0, dec) == nxt
    assert session.next_slot_config(cfg, 1, dec).seed != nxt.seed
    assert session.next_slot_config(cfg, 0, [1, 0, 0]).seed != nxt.seed


def test_run_session_bit_identical_numpy_vs_jax():
    """The offline replay law across backends: same (seed, config, L) →
    the same per-slot seeds, rounds and decisions bit-for-bit on numpy
    and jax (coordinate-addressed draws, never draw order)."""
    cfg = _cfg(seed=77)
    n_np = session.run_session(get_backend("numpy"), cfg, 4)
    n_jx = session.run_session(get_backend("jax"), cfg, 4)
    assert len(n_np) == len(n_jx) == 4
    for a, b in zip(n_np, n_jx):
        assert a.config.seed == b.config.seed
        assert [int(x) for x in a.rounds] == [int(x) for x in b.rounds]
        assert [int(x) for x in a.decision] == [int(x) for x in b.decision]
    # the chain moved: at least one derived seed differs from the base
    assert any(r.config.seed != cfg.seed for r in n_np[1:])
    # and session_slot_configs re-derives exactly the configs that ran
    redone = session.session_slot_configs(
        cfg, [[int(x) for x in r.decision] for r in n_np])
    assert [c.seed for c in redone] == [r.config.seed for r in n_np]


def test_replay_matches_rejects_tampered_slots():
    be = get_backend("numpy")
    cfg = _cfg(seed=13)
    ref = session.run_session(be, cfg, 3)
    served = [([int(x) for x in r.rounds], [int(x) for x in r.decision])
              for r in ref]
    assert session.replay_matches(be, cfg, served)
    rounds, decision = served[1]
    assert not session.replay_matches(
        be, cfg, [served[0], (rounds, [1 - decision[0]] + decision[1:]),
                  served[2]])
    assert not session.replay_matches(
        be, cfg, [served[0], ([r + 1 for r in rounds], decision), served[2]])


def test_session_envelope_admission_bounds():
    """session_slots is an envelope key, never a SimConfig field: it is
    popped before admit(), bounded by MAX_SESSION_SLOTS, and rejected by
    name when malformed."""
    payload = dataclasses.asdict(_cfg())
    rest, env = admission.envelope({**payload, "session_slots": 5})
    assert env["session_slots"] == 5
    assert "session_slots" not in rest
    assert not hasattr(admission.admit(rest), "session_slots")
    for bad in (0, -1, session.MAX_SESSION_SLOTS + 1, True, 2.0, "4"):
        with pytest.raises(ValueError):
            admission.envelope({**payload, "session_slots": bad})
    # None means "not a session", the pre-round-21 default
    assert admission.envelope(
        {**payload, "session_slots": None})[1]["session_slots"] == 1


# -- the serving path against the offline law -----------------------------

@pytest.mark.slow
def test_served_session_bit_identical_to_offline_replay():
    """A session served in-grid (lane re-seeding at the retire seam,
    slot-by-slot streaming) replays bit-identically offline on numpy AND
    jax from the base seed alone — the whole log is (seed, config, L)."""
    cfg = _cfg(seed=35, instances=2)
    slots = 4
    policy = CompactionPolicy(width=8, segment=2)
    with ConsensusServer(policy=policy) as srv:
        h = srv.submit({**dataclasses.asdict(cfg), "session_slots": slots})
        rec = h.wait(timeout=600.0)
    blk = rec["session"]
    assert blk["slots"] == slots and len(blk["rounds"]) == slots
    # the reply's top level is slot 0 (existing differentials hold)
    assert rec["rounds"] == blk["rounds"][0]
    assert rec["decision"] == blk["decisions"][0]
    served = list(zip(blk["rounds"], blk["decisions"]))
    for backend in ("numpy", "jax"):
        assert session.replay_matches(get_backend(backend), cfg, served), \
            f"served session diverged from the {backend} offline replay"
    # the streamed seeds are the chain the replay derives
    ref = session.run_session(get_backend("numpy"), cfg, slots)
    assert blk["seeds"] == [int(r.config.seed) for r in ref]
