"""Run-record schema v1 (obs/record.py): the head every artifact merges,
the env fingerprint, and the canonical timing-block mapping."""

import numpy as np

from byzantinerandomizedconsensus_tpu.config import preset
from byzantinerandomizedconsensus_tpu.obs import record
from byzantinerandomizedconsensus_tpu.utils import metrics
from byzantinerandomizedconsensus_tpu.backends.base import SimResult


def test_env_fingerprint_fields():
    env = record.env_fingerprint()
    for key in ("package", "python", "numpy", "jax", "native_abi",
                "pack_versions"):
        assert key in env, key
    assert env["pack_versions"] == [1, 2, 3]
    assert env["native_abi"] == 5  # native/simcore.cpp sim_abi_version


def test_env_fingerprint_packing_law_fields():
    """Schema v1.11: the fingerprint records every packing identity this
    build speaks — the per-step Pallas laws (stop at v2) AND the fused
    round kernel's resident-state word (ABI v6, spec §A6). Any relayout
    must bump FUSED_STATE_PACK_VERSION, so artifacts stay joinable by law."""
    env = record.env_fingerprint()
    assert env["pallas_pack_versions"] == [1, 2]
    fsp = env["fused_state_pack"]
    assert fsp["version"] == 1
    assert fsp["bits"] == {"est": [0, 2], "decided": [2, 1],
                           "decided_val": [3, 2], "phase": [8, 24]}


def test_new_record_validates():
    doc = record.new_record("bench", description="x", config=preset("config1"))
    assert record.validate_record(doc) == []
    assert doc["kind"] == "bench" and doc["record_version"] == 1
    assert doc["config"]["n"] == 4 and doc["config"]["pack_version"] == 1


def test_validate_record_catches_drift():
    assert record.validate_record([]) != []
    assert any("kind" in p for p in
               record.validate_record({"record_version": 1, "env": {}}))
    assert any("record_version" in p for p in
               record.validate_record({"kind": "x", "env": {}}))
    bad_counters = {**record.new_record("x"),
                    "counters": {"supported": True}}
    assert any("totals" in p for p in record.validate_record(bad_counters))


def test_validate_record_rejects_unknown_revision():
    """Schema v1.4: a record_revision this build does not know (from the
    future, or garbage) must fail BY NAME — the schema-drift census then
    catches a half-understood artifact instead of part-validating it."""
    future = {**record.new_record("x"),
              "record_revision": record.RECORD_REVISION + 1}
    problems = record.validate_record(future)
    assert any(p.startswith("unknown record_revision") for p in problems), \
        problems
    assert any(f"0..{record.RECORD_REVISION}" in p for p in problems)
    for bad in ("4", 4.5, True, -1):
        assert any("unknown record_revision" in p for p in
                   record.validate_record({**record.new_record("x"),
                                           "record_revision": bad})), bad
    # Every revision this build knows — including the legacy implied-v1
    # absence — stays valid.
    for ok in (None, 0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11,
               record.RECORD_REVISION):
        doc = record.new_record("x")
        if ok is None:
            doc.pop("record_revision")
        else:
            doc["record_revision"] = ok
        assert record.validate_record(doc) == [], ok


def test_validate_record_checks_serve_block():
    """Schema v1.5: a serve block missing its required keys (or its latency
    percentiles) must fail by name; the loadgen's own block validates."""
    bad = {**record.new_record("serve"), "serve": {"requests": 3}}
    problems = record.validate_record(bad)
    assert any("serve block missing 'arrival_seed'" in p for p in problems)
    assert any("steady_state_compiles" in p for p in problems)
    good_stats = {
        "arrival_seed": 14, "admission_policy": {"mode": "fused-compaction"},
        "requests": 3, "latency_ms": {"p50": 1.0, "p99": 2.0},
        "throughput_cps": 10.0, "time_to_first_result_ms": 5.0,
        "steady_state_compiles": 0}
    good = {**record.new_record("serve"),
            "serve": record.serve_block(good_stats)}
    assert record.validate_record(good) == []
    # half-built percentiles fail by name too
    lame = {**good, "serve": {**record.serve_block(good_stats),
                              "latency_ms": {"p50": 1.0}}}
    assert any("serve latency_ms missing 'p99'" in p
               for p in record.validate_record(lame))
    assert record.serve_block(None) is None


def test_validate_record_checks_fleet_block():
    """Schema v1.6: a fleet block missing its required keys, latency
    percentiles, or per-worker compile split must fail by name; the
    loadgen's own fleet block validates."""
    bad = {**record.new_record("serve_fleet"), "fleet": {"workers": 2}}
    problems = record.validate_record(bad)
    assert any("fleet block missing 'arrival_seed'" in p for p in problems)
    assert any("steady_state_compiles" in p for p in problems)
    assert any("'per_worker'" in p for p in problems)
    good_stats = {
        "workers": 2, "arrival_seed": 15,
        "admission_policy": {"mode": "fused-compaction"},
        "requests": 8, "latency_ms": {"p50": 1.0, "p99": 2.0},
        "throughput_cps": 10.0, "steady_state_compiles": 0,
        "steals": 1, "readmitted": 0, "lost_workers": 0,
        "per_worker": [{"worker": 0, "steady_state_compiles": 0},
                       {"worker": 1, "steady_state_compiles": 0}],
        "fabric_latency_ms": 12.0}
    good = {**record.new_record("serve_fleet"),
            "fleet": record.fleet_block(good_stats)}
    assert record.validate_record(good) == []
    assert good["fleet"]["fabric_latency_ms"] == 12.0  # passthrough extras
    lame = {**good, "fleet": {**record.fleet_block(good_stats),
                              "latency_ms": {"p50": 1.0}}}
    assert any("fleet latency_ms missing 'p99'" in p
               for p in record.validate_record(lame))
    torn = {**good, "fleet": {**record.fleet_block(good_stats),
                              "per_worker": [{"worker": 0}]}}
    assert any("per_worker row 0" in p
               for p in record.validate_record(torn))
    assert record.fleet_block(None) is None


def test_validate_record_checks_metrics_block():
    """Schema v1.7: a metrics block missing its required keys fails by
    name; a real snapshot digest (with and without an SLO verdict)
    validates; a torn slo (no 'ok') fails by name."""
    bad = {**record.new_record("metrics_bench"), "metrics": {"names": []}}
    problems = record.validate_record(bad)
    assert any("metrics block missing" in p for p in problems), problems

    snap = {
        "brc_serve_replied_total": {
            "type": "counter", "help": "x",
            "series": [{"labels": {}, "value": 3.0}]},
        "brc_serve_request_latency_seconds": {
            "type": "histogram", "help": "x",
            "series": [{"labels": {}, "buckets": [0.1, 1.0, 10.0],
                        "counts": [1, 2, 0, 0], "sum": 1.4, "count": 3}]},
    }
    blk = record.metrics_block(snap)
    assert blk is not None
    assert blk["names"] == sorted(snap)
    assert blk["series"] == 2
    assert blk["p99_latency_ms"] is not None
    good = {**record.new_record("metrics_bench"), "metrics": blk}
    assert record.validate_record(good) == []

    gated = {**record.new_record("metrics_bench"),
             "metrics": record.metrics_block(
                 snap, slo={"ok": True, "checks": {}})}
    assert record.validate_record(gated) == []
    assert gated["metrics"]["slo"]["ok"] is True
    torn = {**good, "metrics": {**blk, "slo": {"checks": {}}}}
    assert any("slo" in p and "ok" in p
               for p in record.validate_record(torn)), \
        record.validate_record(torn)

    assert record.metrics_block(None) is None
    assert record.metrics_block({}) is None


def test_validate_record_checks_hunt_block():
    """Schema v1.8: a hunt block missing its required keys fails by name;
    a real hunter stats dict validates (including the optional best
    genome); a best entry without a genome fails by name."""
    bad = {**record.new_record("hunt"), "hunt": {"strategy": "evolution"}}
    problems = record.validate_record(bad)
    assert any("hunt block missing" in p for p in problems), problems
    assert any(p.startswith("hunt block is not a dict") for p in
               record.validate_record(
                   {**record.new_record("hunt"), "hunt": []}))

    stats = {"strategy": "evolution", "seed": 17, "budget": 32,
             "evaluations": 32, "generations": 2, "best_fitness": 256.0,
             "archive_size": 8, "violations": 0,
             "steady_state_compiles": 0,
             "best": {"fitness": 256.0, "genome": {"protocol": "benor"}},
             "pipeline_speedup": 2.2}
    good = {**record.new_record("hunt"), "hunt": record.hunt_block(stats)}
    assert record.validate_record(good) == []
    assert good["hunt"]["pipeline_speedup"] == 2.2  # extras pass through

    torn = {**good, "hunt": {**good["hunt"], "best": {"fitness": 1.0}}}
    assert any("genome" in p for p in record.validate_record(torn)), \
        record.validate_record(torn)

    assert record.hunt_block(None) is None


def test_validate_record_checks_fused_block():
    """Schema v1.11: a fused block missing its required keys (or with rows
    that lack the census-label / bytes-per-dispatch join fields) fails by
    name; the ``programs fused`` verb's own block validates."""
    bad = {**record.new_record("fused_roofline"), "fused": {"configs": 5}}
    problems = record.validate_record(bad)
    assert any("fused block missing 'mismatches'" in p for p in problems)
    assert any("'device_of_record'" in p for p in problems)
    assert any(p.startswith("fused block is not a dict") for p in
               record.validate_record(
                   {**record.new_record("fused_roofline"), "fused": []}))

    stats = {
        "configs": 5, "mismatches": 0, "device_of_record": "interpret/cpu",
        "steady_state_compiles": 0,
        "state_pack": {"version": 1},
        "rows": [{"key": "benor/n8/...", "xla_bytes_per_dispatch": 100.0,
                  "fused_bytes_per_dispatch": 40.0, "bytes_ratio": 0.4}],
        "bytes_total": 140.0, "duration_s": 1.0}
    good = {**record.new_record("fused_roofline"),
            "fused": record.fused_block(stats)}
    assert record.validate_record(good) == []
    assert good["fused"]["state_pack"] == {"version": 1}  # optionals ride

    torn = {**good, "fused": {**record.fused_block(stats),
                              "rows": [{"key": "x"}]}}
    assert any("fused row 0" in p for p in record.validate_record(torn)), \
        record.validate_record(torn)

    assert record.fused_block(None) is None


def test_validate_record_checks_session_block():
    """Schema v1.12: a session block missing its required keys fails by
    name (torn blocks caught at validate time, not in a future ledger);
    the session bench's own block validates, with the optional columns
    riding along."""
    bad = {**record.new_record("session"), "session": {"sessions": 8}}
    problems = record.validate_record(bad)
    assert any("session block missing 'amortization_ratio'" in p
               for p in problems)
    assert any("'replay_ok'" in p for p in problems)
    assert any(p.startswith("session block is not a dict") for p in
               record.validate_record(
                   {**record.new_record("session"), "session": []}))

    stats = {
        "sessions": 8, "slots": 12, "decisions": 384,
        "amortization_ratio": 1.7, "session_cps": 1800.0,
        "independent_cps": 1050.0, "steady_state_compiles": 0,
        "mismatches": 0, "replay_ok": True,
        "generator_version": 3, "session_reseeds": 88, "duration_s": 2.0}
    good = {**record.new_record("session"),
            "session": record.session_block(stats)}
    assert record.validate_record(good) == []
    assert good["session"]["session_reseeds"] == 88  # optionals ride

    torn = {**good, "session": {**record.session_block(stats),
                                "replay_ok": "yes"}}
    assert any("'replay_ok' is not a bool" in p for p in
               record.validate_record(torn))
    torn2 = {**good, "session": {**record.session_block(stats),
                                 "amortization_ratio": "1.7"}}
    assert any("'amortization_ratio' is not a number" in p for p in
               record.validate_record(torn2))

    assert record.session_block(None) is None


def test_timing_block_maps_suspect_to_error():
    """Absence-of-signal device 0.0s must land as errors (VERDICT r5 weak #1),
    real measurements as device_busy_s — the one mapping every tool shares."""
    walls = [0.21, 0.2, 0.24]
    out = record.timing_block(walls, {"device_busy_suspect": "no TPU pids"})
    assert out["device_busy_error"] == "no TPU pids"
    assert out["wall_s"] == 0.2 and out["walls_s"] == [0.21, 0.2, 0.24]
    assert out["walls_spread"] == round((0.24 - 0.2) / 0.2, 3)
    assert record.timing_block(walls, {"device_busy_s": 0.16}
                               )["device_busy_s"] == 0.16
    assert "failed" in record.timing_block(
        walls, {"error": "failed"})["device_busy_error"]


def test_summary_triage_fields_and_timing_legs():
    """metrics.summary answers the first triage questions in one dict —
    decided fraction always, walls spread + device-busy when legs passed."""
    cfg = preset("config1", instances=4).validate()
    res = SimResult(config=cfg, inst_ids=np.arange(4),
                    rounds=np.array([1, 2, 2, cfg.round_cap], dtype=np.int32),
                    decision=np.array([0, 1, 1, 2], dtype=np.uint8))
    s = metrics.summary(res)
    assert s["decided_fraction"] == 0.75
    assert s["mean_rounds_decided"] == (1 + 2 + 2) / 3
    assert "walls_spread" not in s  # no timing legs passed

    s = metrics.summary(res, walls=[0.5, 0.4],
                        device={"device_busy_s": 0.1602})
    assert s["walls_spread"] == 0.25 and s["wall_s"] == 0.4
    assert s["device_busy_s"] == 0.1602
    assert s["instances_per_sec"] == 10.0
    import json

    json.dumps(s)  # every field JSON-serializable


def test_percentiles_exact_nearest_rank():
    """The one quantile implementation (round-12 satellite): exact
    nearest-rank, no interpolation — int inputs yield int elements of the
    input, never invented midpoints."""
    vals = metrics.percentiles([4, 1, 3, 2], (50, 90, 99, 100))
    assert vals == [2, 4, 4, 4]
    assert all(isinstance(v, int) for v in vals)
    # numpy int arrays come back as exact python ints too.
    arr = np.array([7, 7, 9, 11, 30], dtype=np.int32)
    assert metrics.percentiles(arr, (50, 99)) == [9, 30]
    # p50 of an even count is the lower middle (nearest-rank, not the mean).
    assert metrics.percentiles([1, 2], (50,)) == [1]
    assert metrics.percentiles([], (50, 99)) == [None, None]
    import pytest

    with pytest.raises(ValueError, match="out of range"):
        metrics.percentiles([1], (0,))


def test_summary_reports_rounds_percentiles():
    cfg = preset("config1", instances=5).validate()
    res = SimResult(config=cfg, inst_ids=np.arange(5),
                    rounds=np.array([1, 1, 2, 3, 9], dtype=np.int32),
                    decision=np.array([0, 1, 1, 0, 2], dtype=np.uint8))
    s = metrics.summary(res)
    assert (s["rounds_p50"], s["rounds_p90"], s["rounds_p99"]) == (2, 9, 9)
    import json

    json.dumps(s)


def test_schema_census_every_committed_artifact_validates():
    """Schema-drift tripwire (round-12 satellite): validate_record over
    EVERY committed artifacts/*.json and BENCH_r*.json that carries a
    record_version head — a schema change that orphans an old artifact
    fails here, not in some future ledger run. (The ledger's parse census
    only checks that the JSON loads.)"""
    import json
    import pathlib

    from byzantinerandomizedconsensus_tpu.utils.rounds import repo_root

    root = pathlib.Path(repo_root())
    files = sorted((root / "artifacts").glob("*.json")) + \
        sorted(root.glob("BENCH_r*.json"))
    assert files, "no committed artifacts found"
    checked = []
    for p in files:
        doc = json.loads(p.read_text())
        payload = doc.get("parsed", doc) if isinstance(doc, dict) else {}
        if not (isinstance(payload, dict) and "record_version" in payload):
            continue  # legacy r1-r7 shapes predate the schema head
        problems = record.validate_record(payload)
        assert problems == [], (p.name, problems)
        checked.append(p.name)
    # The v1+ era census as committed (r8-r17: ledger_r8, chaos_r9,
    # batch_r10, compaction_r11, BENCH_r11, trace_r12, programs_r13,
    # serve_r14, serve_fleet_r15, metrics_r16, hunt_r17 +
    # hunt_regressions): an accidentally narrowed glob must not silently
    # pass on near-zero coverage — and the v1.4/v1.5/v1.6/v1.7/v1.8
    # artifacts must be in the checked set, so the unknown-revision,
    # serve-block, fleet-block, metrics-block, and hunt-block checks
    # above provably ran against real revision-4..8 heads.
    assert len(checked) >= 13, checked
    assert "programs_r13.json" in checked, checked
    assert "serve_r14.json" in checked, checked
    assert "serve_fleet_r15.json" in checked, checked
    assert "metrics_r16.json" in checked, checked
    assert "hunt_r17.json" in checked, checked
    assert "hunt_regressions.json" in checked, checked
    assert "fused_r20.json" in checked, checked  # the v1.11 fused block
    assert "session_r21.json" in checked, checked  # the v1.12 session block
