"""Delivery-mask unit tests (spec §4): exact n-f delivery, own-message rule, silent
exclusion, numpy/jnp agreement, and the oracle Network's independent implementation."""

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu.config import SimConfig
from byzantinerandomizedconsensus_tpu.core.network import Network
from byzantinerandomizedconsensus_tpu.ops import masks


@pytest.fixture
def cfg():
    return SimConfig(protocol="bracha", n=16, f=5, instances=4, adversary="byzantine",
                     coin="shared", seed=11).validate()


def _mk(cfg, silent, bias=None, xp=np, rnd=2, t=1):
    ids = np.arange(4, dtype=np.int64)
    if bias is None:
        bias = xp.zeros((4, 1, cfg.n), dtype=xp.uint32)
    return masks.delivery_mask(cfg, cfg.seed, xp.asarray(ids), rnd, t,
                               xp.asarray(silent), bias, xp=xp)


def test_exact_quota_and_own_delivery(cfg):
    silent = np.zeros((4, cfg.n), dtype=bool)
    silent[:, 3] = True  # one silent sender
    m = _mk(cfg, silent)
    assert m.shape == (4, cfg.n, cfg.n)
    # exactly n-f delivered per receiver
    np.testing.assert_array_equal(m.sum(-1), np.full((4, cfg.n), cfg.n - cfg.f))
    # silent sender never delivered to anyone else (only to itself)
    others = np.ones(cfg.n, dtype=bool)
    others[3] = False
    assert not m[:, others, 3].any()
    # own message always delivered, silence notwithstanding (spec §4)
    diag = np.einsum("bii->bi", m.astype(np.int32))
    np.testing.assert_array_equal(diag, np.ones((4, cfg.n), dtype=np.int32))


def test_numpy_jnp_and_oracle_agree(cfg):
    import jax.numpy as jnp

    silent = np.zeros((4, cfg.n), dtype=bool)
    silent[:, 0] = True
    silent[:, 7] = True
    m_np = _mk(cfg, silent, xp=np)
    m_jnp = _mk(cfg, silent, xp=jnp)
    np.testing.assert_array_equal(m_np, np.asarray(m_jnp))

    # oracle Network (independent row-wise implementation)
    for b, inst in enumerate(range(4)):
        net = Network(cfg, cfg.seed, inst)
        m_net = net.delivery_mask(2, 1, silent[b], np.zeros((1, cfg.n), dtype=np.uint32))
        np.testing.assert_array_equal(m_np[b], m_net)


def test_bias_prefers_unbiased_senders(cfg):
    """Biased senders are delivered only when unbiased ones can't fill the quota."""
    silent = np.zeros((4, cfg.n), dtype=bool)
    bias = np.zeros((4, 1, cfg.n), dtype=np.uint32)
    bias[:, :, : cfg.n // 2] = 1  # first half biased away
    m = _mk(cfg, silent, bias=bias)
    # quota is n-f = 11; unbiased senders are 8 -> all 8 delivered, 3 biased fill up
    unbiased = m[:, :, cfg.n // 2 :].sum(-1)
    np.testing.assert_array_equal(unbiased, np.full((4, cfg.n), cfg.n // 2))
    assert (m.sum(-1) == cfg.n - cfg.f).all()


def test_mask_changes_with_round_step(cfg):
    silent = np.zeros((4, cfg.n), dtype=bool)
    a = _mk(cfg, silent, rnd=1, t=0)
    b = _mk(cfg, silent, rnd=1, t=1)
    c = _mk(cfg, silent, rnd=2, t=0)
    assert not np.array_equal(a, b)
    assert not np.array_equal(a, c)
