"""Installability surface (ROADMAP / VERDICT r5 next #2): the pyproject's
dynamic version and console-script target must stay wired to real objects.
The full fresh-venv `pip install -e .` + wheel smoke test is a manual/release
check (README Install section documents the air-gapped variant); this is the
reduced CI leg that catches the common breakages — a renamed entry point, a
moved `__version__`, a package dir dropped from the find-include list —
without invoking pip."""

import pathlib
import re

import byzantinerandomizedconsensus_tpu as pkg

ROOT = pathlib.Path(__file__).resolve().parent.parent
PYPROJECT = ROOT / "pyproject.toml"


def test_pyproject_exists_and_version_is_dynamic():
    text = PYPROJECT.read_text()
    assert 'dynamic = ["version"]' in text
    assert 'version = { attr = "byzantinerandomizedconsensus_tpu.__version__" }' in text
    # The attr it names must resolve and look like a version.
    assert re.fullmatch(r"\d+\.\d+\.\d+", pkg.__version__)


def test_console_script_target_is_callable():
    text = PYPROJECT.read_text()
    m = re.search(r'brc-tpu = "([\w.]+):(\w+)"', text)
    assert m, "brc-tpu console script missing from pyproject"
    module, func = m.groups()
    import importlib

    target = getattr(importlib.import_module(module), func)
    assert callable(target)
    # argparse exits 0 on --help: the standard console-script smoke.
    import pytest

    with pytest.raises(SystemExit) as e:
        target(["--help"])
    assert e.value.code == 0


def test_only_namespaced_package_ships():
    """The wheel must never claim generic top-level module names: only the
    byzantinerandomizedconsensus_tpu namespace is packaged — the repo-side
    `spec/` layer (which would install as top-level `spec`) stays a checkout
    resource."""
    text = PYPROJECT.read_text()
    m = re.search(r"include = \[([^\]]*)\]", text)
    assert m, "packages.find include list missing"
    assert m.group(1).strip() == '"byzantinerandomizedconsensus_tpu*"'
    # The goldens the repo tests pin still live in the checkout.
    assert (ROOT / "spec" / "golden" / "golden.npz").exists()
