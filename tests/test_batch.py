"""Config-batched execution (backends/batch.py; docs/PERF.md round 10).

The acceptance bar is bit-match: every lane of a batched dispatch must equal
the per-config path bit-for-bit — across the fault × adversary × delivery
grid, with mixed-n padding lanes in one bucket, and with the counter side
output enabled. Plus the bucket law, the pinned validate_batch rejections,
the bounded compile-cache LRU (the round-10 fix for the unbounded
``_compiled_counters`` dict), and the bench_batch tier-1 smoke.
"""

import json

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu.backends import get_backend
from byzantinerandomizedconsensus_tpu.backends.batch import (
    CompileCache, ShapeBucket, lane_tier, n_tier)
from byzantinerandomizedconsensus_tpu.config import (
    DELIVERY_KINDS, FAULT_KINDS, SimConfig, validate_batch)

# One protocol pairing per adversary (mirrors tests/test_faults.py).
_ADV_PROTO = (("none", "benor"), ("crash", "benor"), ("byzantine", "bracha"),
              ("adaptive", "bracha"), ("adaptive_min", "bracha"))


def _cfg(adv, proto, delivery, fault, n=7, f=2, seed=13, **kw):
    base = dict(protocol=proto, n=n, f=f, instances=4, adversary=adv,
                coin="local", seed=seed, round_cap=32, delivery=delivery,
                faults=fault)
    base.update(kw)
    return SimConfig(**base).validate()


def _lanes(adv, proto, delivery, fault):
    """Three lanes of one bucket: varying f, seed and (mixed-n padding) n."""
    return [
        _cfg(adv, proto, delivery, fault),
        _cfg(adv, proto, delivery, fault, f=1, seed=99, instances=6),
        _cfg(adv, proto, delivery, fault, n=6, f=1, seed=7, instances=3),
    ]


def _assert_lanes_match_numpy(cfgs, results):
    for cfg, res in zip(cfgs, results):
        ref = get_backend("numpy").run(cfg)
        np.testing.assert_array_equal(ref.rounds, res.rounds)
        np.testing.assert_array_equal(ref.decision, res.decision)


# ---------------------------------------------------------------------------
# bucket law + validate_batch (no jax involved)


def test_bucket_and_lane_tiers():
    assert n_tier(4) == 4 and n_tier(5) == 8 and n_tier(8) == 8
    assert n_tier(40) == 64 and n_tier(1024) == 1024 and n_tier(1025) == 2048
    assert lane_tier(1) == 1 and lane_tier(3) == 4 and lane_tier(8) == 8
    a = ShapeBucket.of(_cfg("none", "benor", "urn2", "none", n=5, seed=1))
    b = ShapeBucket.of(_cfg("none", "benor", "urn2", "none", n=7, f=1,
                            seed=2))
    assert a == b and a.n_pad == 8  # mixed n, one tier -> one bucket
    # packing version follows the tier members, and tiers never straddle the
    # n=1024 packing edge by construction of N_TIERS.
    assert ShapeBucket.of(SimConfig(protocol="bracha", n=2048, f=3,
                                    delivery="urn2").validate()
                          ).pack_version == 2


def test_validate_batch_rejects_mixed_delivery():
    cfgs = [_cfg("none", "benor", "urn2", "none"),
            _cfg("none", "benor", "urn3", "none")]
    with pytest.raises(ValueError,
                       match="one lane bucket runs one delivery law"):
        validate_batch(cfgs)


def test_validate_batch_rejects_mixed_pack_versions():
    cfgs = [SimConfig(protocol="bracha", n=512, f=2, delivery="urn2").validate(),
            SimConfig(protocol="bracha", n=2048, f=2, delivery="urn2").validate()]
    with pytest.raises(ValueError, match=r"packing versions v1 and v2"):
        validate_batch(cfgs)


def test_run_batch_rejects_multiple_buckets():
    jb = get_backend("jax")
    cfgs = [_cfg("none", "benor", "urn2", "none"),
            _cfg("crash", "benor", "urn2", "none")]
    with pytest.raises(ValueError, match="use run_many"):
        jb.run_batch(cfgs)


def test_compile_cache_lru_bounded_eviction():
    cache = CompileCache(max_entries=2)
    built = []
    for key in ("a", "b", "a", "c", "c"):
        cache.get(key, lambda k=key: built.append(k) or k)
    # a, b compiled; a hit; c compiled evicting b (LRU); c hit.
    assert built == ["a", "b", "c"]
    s = cache.stats()
    assert s["compiles"] == 3 and s["hits"] == 2 and s["evictions"] == 1
    assert s["entries"] == 2 and s["max_entries"] == 2
    assert s["compile_wall_s"] >= 0  # schema v1.3: build wall accounted


def test_compile_cache_times_lazy_first_call():
    """compile_wall_s must capture the *lazy* jit cost (round-12 satellite):
    build() returning a callable defers the real compile to the first
    invocation, so the cache times that first call, folds it into the
    total, and unwraps — steady-state calls pay no timing."""
    import time as _time

    cache = CompileCache(max_entries=4)

    def build():
        def fn(x):  # "compile" on first call
            _time.sleep(0.01)
            return x + 1

        return fn

    got = cache.get("k", build)
    assert cache.compile_wall_s < 0.005  # build itself was cheap
    assert got(1) == 2
    assert cache.compile_wall_s >= 0.01  # first call captured
    wall_after_first = cache.compile_wall_s
    # A held wrapper reference (the multi-chunk dispatch loop fetches the
    # program ONCE and calls it per chunk) must not re-time later calls.
    assert got(5) == 6
    assert cache.compile_wall_s == wall_after_first
    unwrapped = cache.get("k", build)
    assert unwrapped is not got  # the timed wrapper was replaced...
    assert unwrapped(2) == 3
    assert cache.compile_wall_s == wall_after_first  # ...and timing stopped
    assert cache.stats()["compiles"] == 1 and cache.stats()["hits"] == 1


# ---------------------------------------------------------------------------
# bit-match: batched lanes vs the per-config path


def test_batch_bitmatch_tier1_sample():
    """Covering sample over (fault, delivery) with rotating adversaries —
    every fault kind and every delivery law once, 3 lanes each (one a
    mixed-n padding lane), vs numpy (which existing tier-1 legs pin
    bit-identical to per-config jax). The full 16-cell grid runs as the
    slow-marked variant below."""
    jb = get_backend("jax")
    cells = [(FAULT_KINDS[i], DELIVERY_KINDS[j])
             for i, j in ((0, 0), (1, 1), (2, 3), (3, 2))]
    for i, (fault, delivery) in enumerate(cells):
        adv, proto = _ADV_PROTO[i % len(_ADV_PROTO)]
        cfgs = _lanes(adv, proto, delivery, fault)
        _assert_lanes_match_numpy(cfgs, jb.run_batch(cfgs))


@pytest.mark.slow
@pytest.mark.parametrize("delivery", DELIVERY_KINDS)
@pytest.mark.parametrize("fault", FAULT_KINDS)
def test_batch_bitmatch_grid_full(fault, delivery):
    """The full fault × delivery grid with rotating adversaries (16 buckets
    × 3 lanes) — still run by default, excluded from the tier-1 budget."""
    jb = get_backend("jax")
    i = FAULT_KINDS.index(fault) + DELIVERY_KINDS.index(delivery)
    adv, proto = _ADV_PROTO[i % len(_ADV_PROTO)]
    cfgs = _lanes(adv, proto, delivery, fault)
    _assert_lanes_match_numpy(cfgs, jb.run_batch(cfgs))


def test_batch_padding_lanes_vs_per_config_jax():
    """Mixed n in one tier-8 bucket, checked against the *jax* per-config
    path directly (not just numpy): the padding seam must not shift a single
    PRF draw."""
    jb = get_backend("jax")
    cfgs = [_cfg("byzantine", "bracha", "urn2", "none", n=7, f=2),
            _cfg("byzantine", "bracha", "urn2", "none", n=5, f=1, seed=3,
                 instances=5),
            _cfg("byzantine", "bracha", "urn2", "none", n=8, f=2, seed=4)]
    batched = jb.run_batch(cfgs)
    for cfg, res in zip(cfgs, batched):
        ref = jb.run(cfg)
        np.testing.assert_array_equal(ref.rounds, res.rounds)
        np.testing.assert_array_equal(ref.decision, res.decision)


def test_run_many_groups_preserve_input_order():
    jb = get_backend("jax")
    cfgs = [_cfg("none", "benor", "urn3", "none", n=5, f=1, seed=t,
                 instances=3 + t) for t in range(3)]
    cfgs.insert(1, _cfg("none", "benor", "urn3", "none", n=16, f=4, seed=5,
                        instances=3))
    results, report = jb.run_many(cfgs)
    assert [len(r.inst_ids) for r in results] == [3, 3, 4, 5]
    _assert_lanes_match_numpy(cfgs, results)
    assert report["buckets"] == 2 and report["configs"] == 4
    occ = {o["bucket"]: o["configs"] for o in report["occupancy"]}
    assert sorted(occ.values()) == [1, 3]
    assert report["compile_cache"]["compiles"] >= 1


# ---------------------------------------------------------------------------
# counters: invariance, pad-exact totals, bucket-keyed LRU (satellite)


def test_batch_counters_invariance_and_pad_exact_totals():
    """Counters-on batched lanes: (rounds, decision) bit-identical to the
    counter-free per-config path, and totals equal to the numpy counted run
    — including on a padded lane (n=7 inside the tier-8 program)."""
    jb = get_backend("jax")
    cfgs = [_cfg("adaptive", "bracha", "urn2", "partition", seed=3,
                 coin="shared", instances=5),
            _cfg("adaptive", "bracha", "urn2", "partition", f=1, seed=21,
                 coin="shared", instances=4)]
    results, docs = jb.run_batch(cfgs, counters=True)
    for cfg, res, doc in zip(cfgs, results, docs):
        ref = get_backend("numpy").run(cfg)
        np.testing.assert_array_equal(ref.rounds, res.rounds)
        np.testing.assert_array_equal(ref.decision, res.decision)
        _, ndoc = get_backend("numpy").run_with_counters(cfg)
        assert doc["totals"] == ndoc["totals"]
        assert doc["supported"] and doc["schema"] == ndoc["schema"]


def test_run_with_counters_is_bucket_keyed_and_bounded():
    """The satellite fix: counted configs sharing a bucket share one
    compiled program (cache hit, no growth), and the cache is the bounded
    LRU whose stats the run-record carries."""
    from byzantinerandomizedconsensus_tpu.backends import batch as batch_mod
    from byzantinerandomizedconsensus_tpu.backends.jax_backend import (
        JaxBackend)

    jb = JaxBackend()  # fresh instance: cache counters start at zero
    assert not hasattr(jb, "_compiled_counters")  # the old dict is gone
    cache = batch_mod.compile_cache(jb)
    a = _cfg("none", "benor", "urn2", "none", f=2, seed=1, instances=3)
    b = _cfg("none", "benor", "urn2", "none", f=1, seed=2, instances=3)
    jb.run_with_counters(a)
    compiles_after_first = cache.stats()["compiles"]
    jb.run_with_counters(b)  # same bucket, different lane data
    s = cache.stats()
    assert s["compiles"] == compiles_after_first  # no second compile
    assert s["hits"] >= 1
    assert s["entries"] <= s["max_entries"]
    assert jb.compile_cache_stats() == s


# ---------------------------------------------------------------------------
# fused superset lanes (the sparse-grid lever)


def test_fused_lanes_bitmatch_mixed_axes():
    """One fused bucket per (protocol, delivery, tier): adversary kind,
    fault kind, coin, init and round_cap all ride as lane codes — every
    lane bit-identical to the per-config numpy path. Two buckets compile
    here (bracha/urn2 with four mixed lanes incl. a padding lane, and
    benor/keys with the Byzantine equivocation-matrix case)."""
    jb = get_backend("jax")
    groups = [
        [  # bracha + urn2 tier: mixed adversary/faults/coin/init/cap/n
            _cfg("byzantine", "bracha", "urn2", "partition", coin="shared",
                 init="all1", round_cap=24),
            _cfg("adaptive", "bracha", "urn2", "none", f=1, seed=5,
                 coin="shared", round_cap=48),
            _cfg("none", "bracha", "urn2", "omission", n=5, f=1, seed=9,
                 init="split", crash_window=2),
            _cfg("adaptive_min", "bracha", "urn2", "recover", f=1, seed=3,
                 coin="shared", crash_window=8, instances=6),
        ],
        [  # benor + keys tier: the (B, R, n) equivocation superset case
            _cfg("byzantine", "benor", "keys", "none", n=6, f=1, seed=2),
            _cfg("crash", "benor", "keys", "recover", seed=4,
                 crash_window=2),
            _cfg("adaptive", "benor", "keys", "none", n=11, f=2, seed=7,
                 round_cap=48),
            _cfg("none", "benor", "keys", "partition", f=1, seed=8,
                 init="all0"),
        ],
    ]
    for cfgs in groups:
        results, report = jb.run_fused(cfgs)
        _assert_lanes_match_numpy(cfgs, results)
    assert report["mode"] == "fused"


def test_fused_buckets_collapse_axes():
    from byzantinerandomizedconsensus_tpu.backends.batch import (
        FUSED_SMALL_TIER, FusedBucket)

    a = FusedBucket.of(_cfg("none", "benor", "urn3", "none", n=4, f=1))
    b = FusedBucket.of(_cfg("adaptive_min", "benor", "urn3", "omission",
                            n=39, f=2, seed=9, coin="shared", init="split",
                            round_cap=128))
    assert a == b and a.n_pad == FUSED_SMALL_TIER


# ---------------------------------------------------------------------------
# tier-1 smoke: a 4-config bucket end-to-end through bench_batch


def test_bench_batch_smoke_runs_a_bucket_end_to_end(tmp_path, capsys):
    from byzantinerandomizedconsensus_tpu.obs import record
    from byzantinerandomizedconsensus_tpu.tools import bench_batch

    out = tmp_path / "batch_smoke.json"
    rc = bench_batch.main(["--smoke", "--configs", "3",
                           "--out", str(out)])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert record.validate_record(doc) == []
    assert doc["kind"] == "bench_batch"
    assert doc["record_revision"] >= 1  # schema v1.1
    dense = doc["legs"]["dense_bucket"]
    assert dense["lanes"] == 4 and dense["bit_identical"]
    assert doc["legs"]["batched"]["mismatches"] == 0
    assert doc["legs"]["batched"]["violations"] == 0
    assert "compile_cache" in doc and "compiles" in doc["compile_cache"]
