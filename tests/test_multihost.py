"""Multi-host (DCN) leg of the distributed comm backend (SURVEY.md §5, §7
step 8; VERDICT r1 missing #1).

Two layers of evidence, neither needing real multi-host hardware:

1. Unit tests of the hybrid-mesh layout logic (`hybrid_grid`) with stand-in
   device objects — the DCN boundary grouping (process-per-host; slice on
   multi-slice pods) and its error paths.
2. A real two-process ``jax.distributed`` run on localhost (4 virtual CPU
   devices per process = the smallest faithful two-host topology): global
   device discovery, hybrid-mesh layout, a cross-host psum, and the sharded
   round driver bit-matching the native arbiter across processes
   (tests/multihost_worker.py).
"""

import pathlib
import socket
import subprocess
import sys
from types import SimpleNamespace

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu.parallel import mesh as pmesh


def _fake_devs(n_hosts, per_host, n_slices=1):
    devs = []
    for h in range(n_hosts):
        for k in range(per_host):
            devs.append(SimpleNamespace(
                id=h * per_host + k,
                process_index=h,
                slice_index=h % n_slices if n_slices > 1 else 0))
    return devs


def test_hybrid_grid_two_hosts_layout():
    grid = pmesh.hybrid_grid(_fake_devs(2, 4), n_model=2)
    assert grid.shape == (4, 2)
    for row in grid:
        assert row[0].process_index == row[1].process_index
    assert [grid[i, 0].process_index for i in range(4)] == [0, 0, 1, 1]


def test_hybrid_grid_four_hosts_model4():
    grid = pmesh.hybrid_grid(_fake_devs(4, 8), n_model=4)
    assert grid.shape == (8, 4)
    for row in grid:
        assert len({d.process_index for d in row}) == 1
    assert sorted({grid[i, 0].process_index for i in range(8)}) == [0, 1, 2, 3]


def test_hybrid_grid_rejects_bad_model_split():
    with pytest.raises(ValueError, match="n_model=3"):
        pmesh.hybrid_grid(_fake_devs(2, 4), n_model=3)


def test_hybrid_single_host_fallback():
    """With one process, make_hybrid_mesh must equal the plain mesh."""
    a = pmesh.make_hybrid_mesh(n_model=2)
    b = pmesh.make_mesh(n_model=2)
    assert a.shape == b.shape
    assert (a.devices == b.devices).all()


@pytest.mark.slow
def test_two_process_distributed_end_to_end():
    """Spawn 2 jax.distributed processes on localhost; each asserts the hybrid
    mesh layout, runs a cross-host collective, and bit-matches the sharded
    round driver against native (see multihost_worker.py)."""
    worker = pathlib.Path(__file__).parent / "multihost_worker.py"
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(port), str(k), "2"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for k in range(2)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost worker timed out")
        outs.append((p.returncode, out))
    for k, (rc, out) in enumerate(outs):
        assert rc == 0, f"worker {k} failed:\n{out[-3000:]}"
        assert f"MULTIHOST_OK pid={k}" in out


@pytest.mark.slow
def test_four_process_model_axis_crosses_hosts():
    """4 jax.distributed processes (2 virtual devices each) with a transposed
    hybrid mesh: the model axis of every mesh row spans two processes, so the
    replica collective rides the DCN leg, bit-matched at n=512 (VERDICT r5
    next #5). If jax 0.4.x refuses the cross-process model collective (the r7
    shard_map precedent), the run is recorded as blocked via a named skip."""
    worker = pathlib.Path(__file__).parent / "multihost_worker.py"
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(port), str(k), "4", "model-cross"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for k in range(4)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("model-cross multihost worker timed out")
        outs.append((p.returncode, out))
    blocked = [line for _, out in outs for line in out.splitlines()
               if line.startswith("MULTIHOST_BLOCKED")]
    if blocked:
        pytest.skip("cross-process model axis refused by this jax build: "
                    + blocked[0])
    for k, (rc, out) in enumerate(outs):
        assert rc == 0, f"worker {k} failed:\n{out[-3000:]}"
        assert f"MULTIHOST_OK pid={k}" in out
