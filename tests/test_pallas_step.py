"""Step-level Pallas-kernel equality — the cheap, broad layer of Pallas
coverage.

Interpret-mode Pallas inside the jitted round *driver* costs ~20 s of
tracing/lowering per config (measured; execution is ~10 ms), so the full grid
of driver-level Pallas bit-matches made the suite compile-bound. The kernels,
however, are pure per-step functions: running the real ``round_body`` *eagerly*
(no jit, interpret-mode pallas_call) exercises them in their exact calling
context — adversary injection, validation silences, wire values — at ~1 s per
config. Full-driver Pallas runs remain, but only one per kernel family
(tests/test_pallas.py, tests/test_urn.py); this module carries the breadth.
"""

import functools

import numpy as np
import pytest

import jax.numpy as jnp

from byzantinerandomizedconsensus_tpu.config import SimConfig
from byzantinerandomizedconsensus_tpu.models import benor, bracha, state as state_mod
from byzantinerandomizedconsensus_tpu.models.adversaries import AdversaryModel


def _run_rounds(cfg, counts_fn, n_rounds):
    """Eager round_body applications; returns the per-round state snapshots."""
    ids = jnp.arange(cfg.instances, dtype=jnp.uint32)
    round_body = benor.round_body if cfg.protocol == "benor" else bracha.round_body
    adv = AdversaryModel(cfg)
    setup = adv.setup(cfg.seed, ids, xp=jnp)
    st = state_mod.init_state(cfg, cfg.seed, ids, xp=jnp)
    out = []
    for r in range(n_rounds):
        st = round_body(cfg, cfg.seed, ids, r, st, adv, setup, xp=jnp,
                        counts_fn=counts_fn)
        out.append({k: np.asarray(v) for k, v in st.items()})
    return out


def _assert_rounds_equal(cfg, ref_counts_fn, got_counts_fn, n_rounds=2):
    ref = _run_rounds(cfg, ref_counts_fn, n_rounds)
    got = _run_rounds(cfg, got_counts_fn, n_rounds)
    for r, (a, b) in enumerate(zip(ref, got)):
        assert sorted(a) == sorted(b)
        for k in a:
            np.testing.assert_array_equal(a[k], b[k],
                                          err_msg=f"round {r} field {k}")


# Interpret-mode pallas_call cost is dominated by per-call tracing (~flat in
# the batch size, linear in rounds x steps — measured), so the default subset
# is a *covering* one (VERDICT r2 #5): one config per adversary class plus
# the tile-boundary shapes (which themselves carry the byzantine/adaptive
# classes for the keys kernel), small batches, mostly one round. The kernels
# under test are stateless per step (counts_fn sees only this step's values/
# silences/faulty planes), so round-2 runs buy different *inputs* — decided
# replicas, validation-silenced senders — not different kernel code paths;
# the slow-marked entries carry that second-round input coverage for the
# fault-injecting adversary classes; the tile shapes stay one-round.

URN_STEP = [
    # (cfg, n_rounds, slow)
    (SimConfig(protocol="benor", n=4, f=1, instances=8, adversary="none",
               coin="local", round_cap=8, seed=0, delivery="urn"), 2, False),
    (SimConfig(protocol="benor", n=9, f=4, instances=8, adversary="crash",
               coin="local", round_cap=8, seed=1, delivery="urn"), 2, True),
    (SimConfig(protocol="benor", n=16, f=3, instances=8, adversary="byzantine",
               coin="local", round_cap=8, seed=2, delivery="urn"), 1, False),  # two-faced
    (SimConfig(protocol="benor", n=16, f=3, instances=8, adversary="byzantine",
               coin="local", round_cap=8, seed=7, delivery="urn"), 2, True),   # two-faced, r2 inputs
    (SimConfig(protocol="benor", n=11, f=2, instances=8, adversary="adaptive",
               coin="shared", round_cap=8, seed=3, delivery="urn"), 2, True),
    (SimConfig(protocol="bracha", n=10, f=3, instances=8, adversary="byzantine",
               coin="shared", round_cap=8, seed=4, delivery="urn"), 1, False),
    (SimConfig(protocol="bracha", n=16, f=5, instances=8, adversary="adaptive",
               coin="shared", round_cap=8, seed=5, delivery="urn"), 2, False),
    (SimConfig(protocol="bracha", n=13, f=4, instances=8, adversary="crash",
               coin="local", round_cap=8, seed=6, delivery="urn"), 2, True),
]


@pytest.mark.parametrize(
    "cfg,n_rounds", [pytest.param(c, r, marks=[pytest.mark.slow] if s else [],
                                  id=f"{c.protocol}-n{c.n}f{c.f}-{c.adversary}")
                     for c, r, s in URN_STEP])
def test_urn_kernel_steps(cfg, n_rounds, pallas_interpret):
    """Pallas urn kernel == XLA urn path through the real round body."""
    from byzantinerandomizedconsensus_tpu.ops import pallas_urn

    _assert_rounds_equal(
        cfg, None,
        functools.partial(pallas_urn.counts_fn, interpret=pallas_interpret),
        n_rounds=n_rounds)


KEYS_STEP = [
    (SimConfig(protocol="benor", n=7, f=3, instances=6, adversary="none",
               coin="shared", round_cap=8, seed=13), 1, False),
    (SimConfig(protocol="benor", n=11, f=2, instances=6, adversary="byzantine",
               coin="shared", round_cap=8, seed=13), 2, True),
    (SimConfig(protocol="benor", n=7, f=3, instances=6, adversary="crash",
               coin="local", round_cap=8, seed=5), 1, False),
    (SimConfig(protocol="bracha", n=10, f=3, instances=6, adversary="crash",
               coin="shared", round_cap=8, seed=13), 2, True),
    (SimConfig(protocol="bracha", n=10, f=3, instances=6, adversary="byzantine",
               coin="shared", round_cap=8, seed=13), 2, True),
    (SimConfig(protocol="bracha", n=16, f=5, instances=6, adversary="adaptive",
               coin="shared", round_cap=8, seed=13), 2, True),
    # Tile boundaries: n == lane width, and n straddling two receiver tiles.
    (SimConfig(protocol="bracha", n=128, f=42, instances=4, adversary="byzantine",
               coin="shared", round_cap=4, seed=2), 1, False),
    (SimConfig(protocol="bracha", n=200, f=66, instances=4, adversary="adaptive",
               coin="shared", round_cap=4, seed=2), 1, False),
]


@pytest.mark.parametrize(
    "cfg,n_rounds", [pytest.param(c, r, marks=[pytest.mark.slow] if s else [],
                                  id=f"{c.protocol}-n{c.n}f{c.f}-{c.adversary}")
                     for c, r, s in KEYS_STEP])
def test_keys_kernel_steps(cfg, n_rounds, pallas_interpret):
    """Fused Pallas selection+tally kernel == XLA masks+tally path through the
    real round body (incl. the tile-boundary shapes)."""
    from byzantinerandomizedconsensus_tpu.ops import pallas_tally

    _assert_rounds_equal(
        cfg, None,
        functools.partial(pallas_tally.counts_fn, interpret=pallas_interpret),
        n_rounds=n_rounds)


@pytest.mark.parametrize("lo,hi", [(0, 5), (5, 11), (11, 16)])
def test_urn_kernel_receiver_shard_offsets(lo, hi, pallas_interpret):
    """Direct counts_fn comparison on receiver sub-ranges: the Pallas urn
    kernel's recv_offset path (incl. the two-faced class boundary at
    (n+1)//2 = 8) must match ops/urn.py for every shard."""
    from byzantinerandomizedconsensus_tpu.ops import pallas_urn, prf, urn

    cfg = SimConfig(protocol="benor", n=16, f=3, instances=12,
                    adversary="byzantine", coin="local", round_cap=8, seed=31,
                    delivery="urn").validate()
    B, n = cfg.instances, cfg.n
    inst = np.arange(B, dtype=np.uint32)
    send = np.arange(n, dtype=np.uint32)
    honest = prf.prf_bit(cfg.seed, inst[:, None], 0, 0, 0, send[None, :],
                         prf.INIT_EST, xp=np).astype(np.uint8)
    faulty = (send[None, :] % 5 == 0) & np.ones((B, 1), bool)
    silent = np.zeros((B, n), dtype=bool)
    recv = np.arange(lo, hi, dtype=np.uint32)
    a0, a1 = urn.counts_fn(cfg, cfg.seed, inst, 1, 0, honest, silent, faulty,
                           honest, recv_ids=recv, xp=np)
    b0, b1 = pallas_urn.counts_fn(
        cfg, cfg.seed, jnp.asarray(inst), 1, 0, jnp.asarray(honest),
        jnp.asarray(silent), jnp.asarray(faulty), jnp.asarray(honest),
        recv_ids=jnp.asarray(recv), interpret=pallas_interpret)
    np.testing.assert_array_equal(a0, np.asarray(b0))
    np.testing.assert_array_equal(a1, np.asarray(b1))
