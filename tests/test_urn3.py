"""Cheap delivery law (spec §4c, delivery="urn3"): law-level exactness against
the enumerated closed-form pmf, bit-match across all four implementation
stacks, protocol properties, the §8d Markov anchor, and the divergence map.

Unlike §4b/§4b-v2, urn3 is a *different delivery distribution* — cross-model
checks assert bounded deviation (and exact identity in the delivery-robust
regime), not family equality. Bit-matching is within delivery="urn3".
"""

import dataclasses
import math

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu import SimConfig, Simulator, preset

URN3_SMALL = [
    SimConfig(protocol="benor", n=4, f=1, instances=60, adversary="none", coin="local",
              round_cap=64, seed=0, delivery="urn3"),
    SimConfig(protocol="benor", n=9, f=4, instances=40, adversary="crash", coin="local",
              round_cap=96, seed=1, delivery="urn3"),
    SimConfig(protocol="benor", n=16, f=3, instances=40, adversary="byzantine",
              coin="local", round_cap=64, seed=2, delivery="urn3"),
    SimConfig(protocol="benor", n=11, f=2, instances=40, adversary="adaptive",
              coin="shared", round_cap=64, seed=3, delivery="urn3"),
    SimConfig(protocol="bracha", n=10, f=3, instances=40, adversary="byzantine",
              coin="shared", round_cap=64, seed=4, delivery="urn3"),
    SimConfig(protocol="bracha", n=16, f=5, instances=40, adversary="adaptive",
              coin="shared", round_cap=64, seed=5, delivery="urn3"),
    SimConfig(protocol="bracha", n=13, f=4, instances=40, adversary="crash",
              coin="local", round_cap=64, seed=6, delivery="urn3"),
    SimConfig(protocol="bracha", n=7, f=2, instances=40, adversary="none",
              coin="shared", round_cap=64, seed=7, delivery="urn3"),
    SimConfig(protocol="bracha", n=13, f=4, instances=40, adversary="adaptive_min",
              coin="shared", round_cap=64, seed=8, delivery="urn3"),
]


@pytest.mark.parametrize("m,Lr,Dr", [
    (5, 11, 6),      # mixed, interior support
    (170, 341, 170), # the config-4 near-balanced shape
    (3, 3, 1),       # homogeneous stratum -> deterministic d = Dr
    (0, 9, 4),       # empty class -> d = 0
    (7, 7, 3),       # all items in class -> d = Dr
    (2, 9, 0),       # no drops -> d = 0
    (4, 5, 4),       # tight support (lo = 3)
])
def test_cheap_exact_pmf(m, Lr, Dr):
    """The §4c segment law against its closed form: the correction nibble has
    16 equally likely values, so the pmf is exactly enumerable
    (spec/analytic.py::urn3_segment_pmf) and the sampler's empirical
    frequencies must match it (5σ) — the law-level anchor, independent of any
    protocol round."""
    from spec.analytic import urn3_segment_pmf

    from byzantinerandomizedconsensus_tpu.ops import prf
    from byzantinerandomizedconsensus_tpu.ops.urn3 import _cheap

    B = 20_000
    inst = np.arange(B, dtype=np.uint32)
    recv = np.zeros(1, dtype=np.uint32)
    u = prf.prf_u32(123, inst[:, None], 0, 0, recv[None, :], 0, prf.URN3, xp=np)
    arr = lambda v: np.full((B, 1), v, dtype=np.int32)  # noqa: E731
    d = _cheap(u, 2, arr(m), arr(Lr), arr(Dr), np)[:, 0]
    pmf = urn3_segment_pmf(m, Lr, Dr)
    assert d.min() >= max(0, Dr - (Lr - m)) and d.max() <= min(m, Dr)
    assert set(np.unique(d)) <= set(pmf)
    for k, p in pmf.items():
        emp = float((d == k).mean())
        tol = 5 * math.sqrt(max(p * (1 - p), 1e-9) / B) + 1e-4
        assert abs(emp - p) < tol, f"d={k}: emp={emp:.5f} pmf={p:.5f}"


@pytest.mark.parametrize(
    "cfg", URN3_SMALL,
    ids=lambda c: f"{c.protocol}-n{c.n}f{c.f}-{c.adversary}-{c.coin}")
def test_urn3_bitmatch_small(cfg):
    ref = Simulator(cfg, "cpu").run()
    for backend in ("numpy", "jax", "native"):
        got = Simulator(cfg, backend).run()
        np.testing.assert_array_equal(ref.rounds, got.rounds, err_msg=f"rounds {backend}")
        np.testing.assert_array_equal(ref.decision, got.decision,
                                      err_msg=f"decision {backend}")


@pytest.mark.parametrize("name,n_sample", [("config2", 4), ("config3", 3), ("config4", 2)])
def test_urn3_bitmatch_benchmark_sampled(name, n_sample):
    import zlib

    cfg = preset(name, round_cap=64, delivery="urn3")
    rng = np.random.default_rng(zlib.crc32(name.encode()))
    ids = np.unique(rng.integers(0, cfg.instances, size=n_sample))
    ref = Simulator(cfg, "cpu").run(ids)
    for backend in ("numpy", "jax"):
        got = Simulator(cfg, backend).run(ids)
        np.testing.assert_array_equal(ref.rounds, got.rounds, err_msg=f"rounds {backend}")
        np.testing.assert_array_equal(ref.decision, got.decision,
                                      err_msg=f"decision {backend}")


@pytest.mark.parametrize("cfg", URN3_SMALL[:6],
                         ids=lambda c: f"{c.protocol}-{c.adversary}")
def test_urn3_agreement_and_validity(cfg):
    res = Simulator(cfg, "numpy").run()
    assert set(np.unique(res.decision)) <= {0, 1, 2}
    for init, expect in (("all0", 0), ("all1", 1)):
        c = dataclasses.replace(cfg, init=init, instances=30)
        r = Simulator(c, "numpy").run()
        decided = r.decision != 2
        assert np.all(r.decision[decided] == expect), f"validity broken for {init}"


def test_urn3_counts_conservation():
    """Spec §4c preserves the §4b count guarantees by support-clamping:
    c0+c1+c2 = min(L, n-f-1)+1; with no faults and no bot values the
    delivered total is exactly n-f for every receiver."""
    from byzantinerandomizedconsensus_tpu.ops import urn3

    cfg = SimConfig(protocol="bracha", n=32, f=10, instances=8, adversary="none",
                    coin="shared", delivery="urn3")
    B, n = 5, cfg.n
    inst = np.arange(B, dtype=np.uint32)
    values = (np.arange(n, dtype=np.uint8) % 2)[None, :].repeat(B, 0)
    silent = np.zeros((B, n), dtype=bool)
    faulty = np.zeros((B, n), dtype=bool)
    c0, c1 = urn3.counts_fn(cfg, cfg.seed, inst, 0, 0, values, silent, faulty,
                            values, xp=np)
    np.testing.assert_array_equal(c0 + c1, np.full((B, n), n - cfg.f))
    assert (c0 <= (values == 0).sum(-1)[:, None] + 1).all()
    assert (c1 <= (values == 1).sum(-1)[:, None] + 1).all()
    assert (c0 >= 0).all() and (c1 >= 0).all()


@pytest.mark.parametrize("adversary", ["none", "adaptive", "adaptive_min"])
def test_urn3_support_bounds_property(adversary):
    """Property sweep over random wires (⊥ and silents included): every §4c
    count obeys the exact-law support — c_w ≥ m_w − D, c_w ≤ m_w + [own],
    and the delivered total is exactly min(L, n−f−1) + 1 (the n−f quorum
    feasibility the §5 wait rule needs)."""
    from byzantinerandomizedconsensus_tpu.ops import urn3

    cfg = SimConfig(protocol="bracha", n=24, f=7, instances=1,
                    adversary=adversary, coin="shared", delivery="urn3"
                    ).validate()
    n, f = cfg.n, cfg.f
    rng = np.random.default_rng(42)
    B = 40
    inst = np.arange(B, dtype=np.uint32)
    values = rng.integers(0, 3, size=(B, n)).astype(np.uint8)
    silent = rng.random((B, n)) < 0.15
    silent &= silent.cumsum(-1) <= f  # at most f silent senders (spec §4)
    faulty = np.zeros((B, n), dtype=bool)
    faulty[:, n - f:] = True
    c0, c1 = urn3.counts_fn(cfg, cfg.seed, inst, 2, 1, values, silent, faulty,
                            values, xp=np)
    live = ~silent
    own = values  # common wire (no two-faced pairing here)
    # Per-lane class counts over senders u != v, and the urn totals.
    L = live.sum(-1, keepdims=True) - live.astype(int)
    D = np.maximum(L - (n - f - 1), 0)
    for w, cw in ((0, c0), (1, c1)):
        m_w = ((live & (values == w)).sum(-1, keepdims=True)
               - (live & (own == w)).astype(int))
        own_term = (own == w).astype(int)
        # d_w ≤ min(m_w, D): c_w sits inside the exact-law support.
        assert (cw <= m_w + own_term).all()
        assert (cw >= m_w - np.minimum(m_w, D) + own_term).all()
    # Quorum feasibility: delivered total (⊥ and own included) is exactly
    # min(L, n−f−1) + 1; c2 = total − c0 − c1 must fit its class.
    total = np.minimum(L, n - f - 1) + 1
    c2_max = ((live & (values == 2)).sum(-1, keepdims=True)
              - (live & (own == 2)).astype(int) + (own == 2).astype(int))
    assert (c0 + c1 >= total - c2_max).all()
    assert (c0 + c1 <= total).all()


def test_urn3_mean_rounds_matches_exact_chain():
    """The §8d closed-form anchor: E[rounds] for Ben-Or n=4, f=1 under the
    §4c law, uniform init, exact Markov solve vs simulation at 4.5σ. Pins the
    cheap law end-to-end through the Protocol-A round body (and distinguishes
    it from the exact family: the §8a constant 3.221122 sits ~4σ away at this
    sample size — the anchor has discriminating power)."""
    from spec.analytic import expected_rounds_benor_n4_urn3

    cfg = SimConfig(protocol="benor", n=4, f=1, instances=40_000,
                    adversary="none", coin="local", round_cap=256, seed=123,
                    delivery="urn3")
    res = Simulator(cfg, "native").run()
    mean = float(res.rounds.mean())
    se = float(res.rounds.std()) / math.sqrt(cfg.instances)
    exact = expected_rounds_benor_n4_urn3()
    assert abs(mean - exact) < 4.5 * se, (mean, exact, se)
    # Validity face of the anchor: unanimity decides in exactly one round.
    for init in ("all0", "all1"):
        r = Simulator(dataclasses.replace(cfg, init=init, instances=50),
                      "native").run()
        assert (r.rounds == 1).all()


@pytest.mark.parametrize("adversary,protocol,n,f,coin,seed", [
    ("adaptive", "bracha", 16, 5, "local", 5),
    ("adaptive", "bracha", 16, 5, "shared", 11),
    ("adaptive_min", "bracha", 16, 5, "local", 5),
    ("adaptive_min", "benor", 11, 2, "local", 3),
])
def test_urn3_robust_regime_identical(adversary, protocol, n, f, coin, seed):
    """The delivery-robust regime is law-independent: on binary-alphabet
    steps the adaptive family's bias strata are value-homogeneous, so §4c's
    support clamp gives lo = hi and the cheap law produces the *identical*
    counts as the exact family — per-instance outcomes match keys and urn2
    bit-for-bit (the §4b mechanism, carried over; measured in
    artifacts/divergence_r6.json)."""
    cfg = SimConfig(protocol=protocol, n=n, f=f, instances=200,
                    adversary=adversary, coin=coin, seed=seed, round_cap=64)
    ref = Simulator(dataclasses.replace(cfg, delivery="urn3"), "numpy").run()
    for other in ("keys", "urn2"):
        got = Simulator(dataclasses.replace(cfg, delivery=other), "numpy").run()
        np.testing.assert_array_equal(ref.rounds, got.rounds, err_msg=other)
        np.testing.assert_array_equal(ref.decision, got.decision, err_msg=other)


def test_urn3_divergence_smoke():
    """Divergent regime: §4c differs per-instance from §4b-v2 (it is a
    different law) with a bounded distribution shift — nonzero disagreement,
    rounds-histogram TV distance recorded and small, decision split intact."""
    from byzantinerandomizedconsensus_tpu.tools.divergence import compare_row

    cfg = SimConfig(protocol="bracha", n=16, f=5, adversary="none",
                    coin="shared", seed=11, round_cap=64)
    row = compare_row(cfg, instances=400, backend="numpy")
    assert row["frac_rounds_differ_urn2_urn3"] > 0.02, row
    assert 0.0 < row["rounds_hist_tv_urn2_urn3"] < 0.25, row
    assert abs(row["p1_urn2"] - row["p1_urn3"]) < 0.1, row


def test_urn3_rejects_pallas_kernel():
    """The Pallas kernels implement §4b only; urn3 must fail loudly, not fall
    back silently (ADVICE r1 pattern)."""
    cfg = URN3_SMALL[0]
    with pytest.raises(ValueError, match="urn3"):
        Simulator(cfg, "jax_pallas").run()
