"""Live metrics plane (obs/metrics.py, round 16): registry units —
counter monotonicity (incl. under a thread hammer), histogram bucket-edge
law and exact sum/count, type-conflict rejection, the disabled fast path's
strict inertness, Prometheus text-exposition validity, the parse_text
round-trip (the ONE scrape parser), histogram_quantile, the fleet absorb
federation rule, and the env self-enable discipline."""

import re
import threading

import pytest

from byzantinerandomizedconsensus_tpu.obs import metrics as _metrics


@pytest.fixture(autouse=True)
def _inert_registry():
    """Every test starts and ends on the disabled fast path — the metrics
    plane is process-global, and leaking an enabled registry into another
    test file would break ITS inertness assumptions."""
    _metrics.disable()
    yield
    _metrics.disable()


# ---------------------------------------------------------------------------
# disabled fast path


def test_disabled_fast_path_is_strictly_inert():
    assert not _metrics.enabled()
    assert _metrics.current() is None
    assert _metrics.snapshot() is None
    # every accessor hands out the one shared no-op...
    c = _metrics.counter("brc_x_total", "x")
    g = _metrics.gauge("brc_x", "x")
    h = _metrics.histogram("brc_x_seconds", "x", buckets=(1.0, 2.0))
    assert c is g is h is _metrics.counter("brc_y_total")
    # ...which swallows every mutation (even invalid ones: no registry,
    # no bookkeeping, no validation work on the disabled path)
    c.inc()
    c.inc(-5)
    g.set(3)
    g.dec()
    h.observe(0.5)
    h.observe_many([1, 2, 3])
    assert _metrics.snapshot() is None
    assert _metrics.render().startswith("# brc metrics disabled")
    _metrics.absorb({"brc_x": {"type": "gauge", "series": []}}, worker="0")
    assert _metrics.snapshot() is None


def test_env_self_enable_discipline(monkeypatch):
    monkeypatch.delenv(_metrics.METRICS_ENV, raising=False)
    assert _metrics.maybe_enable_from_env() is None
    assert not _metrics.enabled()
    monkeypatch.setenv(_metrics.METRICS_ENV, "0")
    assert _metrics.maybe_enable_from_env() is None
    monkeypatch.setenv(_metrics.METRICS_ENV, "1")
    assert _metrics.maybe_enable_from_env() is not None
    assert _metrics.enabled()
    # already-configured: no-op (does not replace the live registry)
    r = _metrics.current()
    assert _metrics.maybe_enable_from_env() is None
    assert _metrics.current() is r


# ---------------------------------------------------------------------------
# counters / gauges


def test_counter_monotonic_negative_increment_raises():
    _metrics.configure()
    c = _metrics.counter("brc_t_total", "t")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError, match="monotonic"):
        c.inc(-1)
    assert c.value == 3.5


def test_counter_thread_hammer_loses_nothing():
    _metrics.configure()

    def hammer():
        # re-resolve the child through the registry each time: the
        # accessor path (dict get + lock) is the production call shape
        for _ in range(500):
            _metrics.counter("brc_hammer_total", "t").inc()

    threads = [threading.Thread(target=hammer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert _metrics.counter("brc_hammer_total").value == 8 * 500


def test_gauge_set_inc_dec():
    _metrics.configure()
    g = _metrics.gauge("brc_g", "g")
    g.set(10)
    g.inc(5)
    g.dec(2)
    assert g.value == 13.0


def test_type_conflict_rejected():
    _metrics.configure()
    _metrics.counter("brc_dual", "x")
    with pytest.raises(ValueError, match="already registered"):
        _metrics.gauge("brc_dual", "x")


def test_labeled_series_are_distinct_children():
    _metrics.configure()
    _metrics.counter("brc_r_total", "r", reason="bad_type").inc()
    _metrics.counter("brc_r_total", "r", reason="cap_ceiling").inc(2)
    snap = _metrics.snapshot()
    rows = {tuple(sorted(s["labels"].items())): s["value"]
            for s in snap["brc_r_total"]["series"]}
    assert rows == {(("reason", "bad_type"),): 1.0,
                    (("reason", "cap_ceiling"),): 2.0}


# ---------------------------------------------------------------------------
# histograms


def test_histogram_bucket_edges_le_semantics():
    """Prometheus ``le`` law: a value equal to an edge lands in that
    edge's bucket; above every finite edge lands in +Inf."""
    _metrics.configure()
    h = _metrics.histogram("brc_h_seconds", "h", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.1, 0.5, 1.0, 9.99, 10.0, 11.0):
        h.observe(v)
    #            <=0.1      <=1        <=10        +Inf
    assert h.counts == [2, 2, 2, 1]
    assert h.count == 7
    assert h.sum == pytest.approx(0.05 + 0.1 + 0.5 + 1.0 + 9.99 + 10.0 + 11.0)


def test_histogram_observe_many_matches_observe():
    _metrics.configure()
    a = _metrics.histogram("brc_a_seconds", "a", buckets=(1.0, 2.0))
    b = _metrics.histogram("brc_b_seconds", "b", buckets=(1.0, 2.0))
    vals = [0.5, 1.0, 1.5, 2.5, 3.0]
    a.observe_many(vals)
    for v in vals:
        b.observe(v)
    assert a.counts == b.counts and a.sum == b.sum and a.count == b.count
    a.observe_many([])   # empty batch is a no-op, not an error
    assert a.count == 5


def test_histogram_bad_buckets_rejected():
    _metrics.configure()
    for bad in ((), (2.0, 1.0), (1.0, 1.0)):
        with pytest.raises(ValueError, match="ascending"):
            _metrics.histogram(f"brc_bad_{len(bad)}", "x", buckets=bad)


def test_histogram_quantile_interpolation_and_edges():
    series = {"labels": {}, "buckets": [0.1, 1.0, 10.0],
              "counts": [2, 2, 0, 0], "sum": 1.2, "count": 4}
    # rank 2 of 4 sits at the top of the first bucket
    assert _metrics.histogram_quantile(series, 0.5) == pytest.approx(0.1)
    assert _metrics.histogram_quantile(series, 0.75) == pytest.approx(0.55)
    # +Inf cell answers the top finite edge, never infinity
    inf_heavy = {"labels": {}, "buckets": [1.0], "counts": [0, 5],
                 "sum": 50.0, "count": 5}
    assert _metrics.histogram_quantile(inf_heavy, 0.99) == 1.0
    empty = {"labels": {}, "buckets": [1.0], "counts": [0, 0],
             "sum": 0.0, "count": 0}
    assert _metrics.histogram_quantile(empty, 0.5) is None
    assert _metrics.histogram_quantile([], 0.5) is None
    # multi-series (the fleet's per-worker histograms) fold into one
    two = [series, dict(series, counts=[0, 0, 4, 0])]
    assert _metrics.histogram_quantile(two, 0.99) <= 10.0


# ---------------------------------------------------------------------------
# exposition text + the one scrape parser

#: One exposition sample line: metric name, optional {labels}, a value.
_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\")*\})?"
    r" (NaN|[+-]Inf|[-+]?[0-9.eE+-]+)$")


def _populated_registry():
    _metrics.configure()
    _metrics.counter("brc_serve_replied_total", "Replies").inc(7)
    _metrics.counter("brc_serve_rejected_total", "Rejections",
                     reason="bad_type").inc(2)
    _metrics.gauge("brc_fleet_workers_alive", "Alive").set(2)
    h = _metrics.histogram("brc_serve_request_latency_seconds", "Latency",
                           buckets=(0.1, 1.0, 10.0))
    h.observe_many([0.05, 0.5, 2.0, 20.0])
    return _metrics.snapshot()


def test_render_is_valid_prometheus_exposition():
    snap = _populated_registry()
    body = _metrics.render()
    assert body.endswith("\n")
    seen_types = {}
    for line in body.splitlines():
        if not line:
            continue
        if line.startswith("# TYPE"):
            _, _, name, kind = line.split(None, 3)
            seen_types[name] = kind
            continue
        if line.startswith("#"):
            assert line.startswith("# HELP"), line
            continue
        assert _SAMPLE.match(line), f"invalid exposition line: {line!r}"
    assert seen_types["brc_serve_replied_total"] == "counter"
    assert seen_types["brc_serve_request_latency_seconds"] == "histogram"
    # cumulative bucket law: counts 1,2,3 at the finite edges, 4 at +Inf
    assert "brc_serve_request_latency_seconds_bucket{le=\"0.1\"} 1" in body
    assert "brc_serve_request_latency_seconds_bucket{le=\"+Inf\"} 4" in body
    assert "brc_serve_request_latency_seconds_count 4" in body
    assert snap is not None


def test_parse_text_roundtrips_snapshot():
    snap = _populated_registry()
    parsed = _metrics.parse_text(_metrics.render())
    assert set(parsed) == set(snap)
    assert parsed["brc_serve_replied_total"]["series"][0]["value"] == 7.0
    rej = parsed["brc_serve_rejected_total"]["series"][0]
    assert rej["labels"] == {"reason": "bad_type"}
    hist = parsed["brc_serve_request_latency_seconds"]["series"][0]
    ref = snap["brc_serve_request_latency_seconds"]["series"][0]
    assert hist["buckets"] == ref["buckets"]
    assert hist["counts"] == ref["counts"]
    assert hist["count"] == ref["count"]
    assert hist["sum"] == pytest.approx(ref["sum"])
    # quantiles computed off the scrape match the local snapshot
    assert (_metrics.histogram_quantile(hist, 0.5)
            == _metrics.histogram_quantile(ref, 0.5))


def test_label_escaping_roundtrips():
    _metrics.configure()
    ugly = 'quote " backslash \\ end'
    _metrics.counter("brc_esc_total", "esc", what=ugly).inc()
    parsed = _metrics.parse_text(_metrics.render())
    assert parsed["brc_esc_total"]["series"][0]["labels"]["what"] == ugly


def test_summary_reads_the_headline_gauges():
    _populated_registry()
    _metrics.counter("brc_serve_failed_total", "f").inc(1)
    _metrics.counter("brc_consensus_decided_total", "d").inc(9)
    _metrics.counter("brc_consensus_undecided_total", "u").inc(1)
    s = _metrics.summary(_metrics.snapshot())
    assert s["replied"] == 7 and s["failed"] == 1
    assert s["error_rate"] == pytest.approx(1 / 8)
    assert s["decided_fraction"] == pytest.approx(0.9)
    assert s["p99_latency_ms"] is not None
    # absent families answer None, not garbage
    none = _metrics.summary({})
    assert none["p99_latency_ms"] is None
    assert none["decided_fraction"] is None
    assert _metrics.summary(None)["replied"] is None


# ---------------------------------------------------------------------------
# fleet federation


def test_absorb_is_latest_wins_per_labeled_series():
    _metrics.configure()
    worker_snap = {
        "brc_serve_replied_total": {
            "type": "counter", "help": "x",
            "series": [{"labels": {}, "value": 5.0}]},
        "brc_serve_request_latency_seconds": {
            "type": "histogram", "help": "x",
            "series": [{"labels": {}, "buckets": [1.0],
                        "counts": [2, 1], "sum": 4.0, "count": 3}]},
    }
    _metrics.absorb(worker_snap, worker="3")
    _metrics.absorb(worker_snap, worker="3")  # absolute, not summed
    snap = _metrics.snapshot()
    rows = snap["brc_serve_replied_total"]["series"]
    assert rows == [{"labels": {"worker": "3"}, "value": 5.0}]
    hrow = snap["brc_serve_request_latency_seconds"]["series"][0]
    assert hrow["labels"] == {"worker": "3"}
    assert hrow["counts"] == [2, 1] and hrow["count"] == 3
    # a second worker's series lands beside it, never over it
    _metrics.absorb(worker_snap, worker="4")
    assert len(_metrics.snapshot()["brc_serve_replied_total"]["series"]) == 2
    _metrics.absorb(None, worker="5")   # dead worker: no-op
