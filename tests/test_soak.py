"""Reduced CI leg of the randomized differential soak (tools/soak.py).

The committed artifact (artifacts/soak_r7.json) is the full run; this keeps
the instrument itself honest on every suite run: the generator only emits
valid configs covering all four delivery models, and a small soak finds zero
numpy-vs-native mismatches with the oracle subsample on.
"""

import random
import shutil

import pytest

from byzantinerandomizedconsensus_tpu.config import DELIVERY_KINDS
from byzantinerandomizedconsensus_tpu.tools import soak

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no C++ toolchain")


def test_generator_emits_valid_configs_all_deliveries():
    rng = random.Random(7)
    seen = set()
    for _ in range(80):
        cfg = soak.random_config(rng)          # .validate() runs inside
        assert cfg.n <= soak.MAX_SOAK_N
        assert cfg.pack_version == 1           # soak stays on the v1 side
        seen.add(cfg.delivery)
    assert seen == set(DELIVERY_KINDS)


def test_small_soak_zero_mismatches():
    doc = soak.run_soak(8, seed=123, oracle_every=4, oracle_instances=2,
                        progress=lambda *a: None)
    assert doc["configs"] == 8
    assert doc["oracle_subsampled_configs"] == 2
    assert doc["mismatches"] == []


def test_soak_reports_mismatch_instead_of_raising(monkeypatch):
    """A soak that stops at the first divergence (or asserts) is useless as an
    instrument — it must record and keep going."""
    import numpy as np

    from byzantinerandomizedconsensus_tpu.backends import get_backend
    real = get_backend("native").run

    class Liar:
        name = "native"

        def run(self, cfg, inst_ids=None):
            res = real(cfg, inst_ids)
            res.rounds[0] += 1  # corrupt one leg
            return res

    monkeypatch.setattr(soak, "get_backend",
                        lambda name: Liar() if name == "native"
                        else get_backend(name))
    doc = soak.run_soak(3, seed=5, oracle_every=100,
                        progress=lambda *a: None)
    assert len(doc["mismatches"]) == 3
    assert all(m["leg"] == "numpy_vs_native" for m in doc["mismatches"])
