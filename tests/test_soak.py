"""Reduced CI leg of the randomized differential soak (tools/soak.py).

The committed artifacts (artifacts/soak_r7.json, artifacts/chaos_r9.json)
are the full runs; this keeps the instrument itself honest on every suite
run: the generator only emits valid configs covering all four delivery
models, a small soak finds zero numpy-vs-native mismatches with the oracle
subsample on, a seeded chaos smoke (subprocess leg included) finds zero
mismatches/violations, and the injected crash/hang drills prove the
timeout → backoff → retry → skip-with-record path plus checkpoint resume.
"""

import random
import shutil

import pytest

from byzantinerandomizedconsensus_tpu.config import DELIVERY_KINDS
from byzantinerandomizedconsensus_tpu.tools import soak

# Chaos mode has no native leg (FaultsUnsupported by design); only the
# classic numpy-vs-native legs need the toolchain.
needs_gxx = pytest.mark.skipif(shutil.which("g++") is None,
                               reason="no C++ toolchain")


def test_generator_emits_valid_configs_all_deliveries():
    rng = random.Random(7)
    seen = set()
    for _ in range(80):
        cfg = soak.random_config(rng)          # .validate() runs inside
        assert cfg.n <= soak.MAX_SOAK_N
        assert cfg.pack_version == 1           # soak stays on the v1 side
        assert cfg.faults == "none"            # legacy population unchanged
        seen.add(cfg.delivery)
    assert seen == set(DELIVERY_KINDS)


def test_chaos_generator_covers_fault_axis():
    from byzantinerandomizedconsensus_tpu.config import FAULT_KINDS

    rng = random.Random(7)
    seen = set()
    for _ in range(80):
        cfg = soak.random_config(rng, chaos=True)
        assert cfg.crash_window >= 1
        seen.add(cfg.faults)
    assert seen == set(FAULT_KINDS)


@needs_gxx
def test_small_soak_zero_mismatches():
    doc = soak.run_soak(8, seed=123, oracle_every=4, oracle_instances=2,
                        progress=lambda *a: None)
    assert doc["configs"] == 8
    assert doc["oracle_subsampled_configs"] == 2
    assert doc["mismatches"] == []


@needs_gxx
def test_soak_reports_mismatch_instead_of_raising(monkeypatch):
    """A soak that stops at the first divergence (or asserts) is useless as an
    instrument — it must record and keep going. The records must reproduce
    standalone: first divergent instance index + per-leg (rounds, decision)
    summaries, not just the config dict."""
    import numpy as np

    from byzantinerandomizedconsensus_tpu.backends import get_backend
    real = get_backend("native").run

    class Liar:
        name = "native"

        def run(self, cfg, inst_ids=None):
            res = real(cfg, inst_ids)
            res.rounds[0] += 1  # corrupt one leg
            return res

    monkeypatch.setattr(soak, "get_backend",
                        lambda name: Liar() if name == "native"
                        else get_backend(name))
    doc = soak.run_soak(3, seed=5, oracle_every=100,
                        progress=lambda *a: None)
    assert len(doc["mismatches"]) == 3
    assert all(m["leg"] == "numpy_vs_native" for m in doc["mismatches"])
    for m in doc["mismatches"]:
        assert m["first_divergent_instance"] == 0
        assert m["n_differing"] >= 1
        at = m["at_first_divergence"]
        assert at["native"]["rounds"] == at["numpy"]["rounds"] + 1
        for leg in ("numpy", "native"):
            assert len(m[leg]["rounds"]) == m["config"]["instances"]
            assert len(m[leg]["decision"]) == m["config"]["instances"]


@pytest.mark.slow
def test_chaos_smoke_subprocess_leg(tmp_path):
    """The deterministic tier-1 chaos smoke: 8 seeded configs, each run in a
    real subprocess (numpy-vs-jax + oracle subsample + safety invariants) —
    zero mismatches, zero violations, zero skips. Runs under ``--jobs 2``
    (round 10): the population is pre-drawn, so the worker pool must report
    the exact same census the sequential path would.

    Round 12: the smoke also runs **traced** (``--trace`` / ``trace_dir``)
    and asserts the whole telemetry pipeline on the result — per-worker
    JSONL files written by the real subprocesses, coordinator lifecycle +
    heartbeat events, the merged trace well-formed (every line parses,
    spans properly nested per worker), the schema-v1.3 trace block bound
    into the artifact, and ``brc-tpu trace export --chrome`` emitting
    structurally valid trace-event JSON."""
    import json
    import pathlib

    from byzantinerandomizedconsensus_tpu.obs import record, trace
    from byzantinerandomizedconsensus_tpu.tools import trace as trace_tool

    trace_dir = tmp_path / "tr"
    doc = soak.run_soak(8, seed=123, oracle_every=4, oracle_instances=2,
                        chaos=True, timeout_s=600, jobs=2,
                        checkpoint=str(tmp_path / "ck.json"),
                        trace_dir=str(trace_dir),
                        progress=lambda *a: None)
    assert doc["configs"] == 8
    assert doc["chaos"] is True
    assert doc["mismatches"] == []
    assert doc["violations"] == []
    assert doc["skipped"] == []
    assert doc["oracle_subsampled_configs"] == 2
    assert doc["safety_checked_instances"] > 0
    assert sum(doc["by_faults"].values()) == 8
    assert sum(1 for k, v in doc["by_faults"].items()
               if k != "none" and v) >= 2  # fault kinds actually exercised

    # --- the traced-run telemetry assertions (round-12 CI satellite) ---
    assert not trace.enabled()  # run_soak cleaned up the global tracer
    merged = pathlib.Path(trace_dir) / "trace.jsonl"
    assert merged.exists()
    assert trace.validate_file(merged) == []  # parses + nested per worker
    events = trace.read_events(merged)
    kinds = {e["kind"] for e in events}
    # Coordinator lifecycle + heartbeat, and real subprocess-worker spans
    # (each child wrote its own trace-w<pid>.jsonl via BRC_TRACE).
    assert {"chaos.start", "chaos.spawn", "chaos.config", "chaos.progress",
            "chaos.done", "chaos.child.numpy", "chaos.child.jax"} <= kinds
    assert len({e["pid"] for e in events}) >= 2  # coordinator + workers
    heartbeats = [e for e in events if e["kind"] == "chaos.progress"]
    assert heartbeats[-1]["attrs"]["done"] == 8

    # The artifact binds the trace (schema v1.3) and still validates.
    assert doc["trace"] is not None
    assert doc["trace"]["file"] == "trace.jsonl"
    assert doc["trace"]["events"] == len(events)
    assert doc["trace"]["digest"]["chaos.config"]["count"] == 8
    assert record.validate_record(doc) == []

    # Chrome export over the merged trace: structurally valid trace-event
    # JSON (the Perfetto-loadable form).
    out = tmp_path / "trace.chrome.json"
    assert trace_tool.main(["export", "--chrome", str(merged),
                            "--out", str(out)]) == 0
    chrome = json.loads(out.read_text())
    assert isinstance(chrome["traceEvents"], list)
    assert len(chrome["traceEvents"]) == len(events)
    assert all(ev["ph"] in ("X", "i") and "ts" in ev and "name" in ev
               for ev in chrome["traceEvents"])

    # And the live follow surface reads the same directory.
    state = trace_tool.follow(trace_dir, once=True, out=lambda *a: None)
    assert state["progress"]["done"] == 8
    assert state["progress"]["mismatches"] == 0

    # A --jobs run's checkpoint resumes (no subprocesses this time): the
    # parallel merge wrote every record under the same binding keys.
    doc2 = soak.run_soak(8, seed=123, oracle_every=4, oracle_instances=2,
                         chaos=True, timeout_s=600, jobs=3,
                         checkpoint=str(tmp_path / "ck.json"),
                         progress=lambda *a: None)
    assert doc2["resumed_configs"] == 8
    assert doc2["mismatches"] == [] and doc2["skipped"] == []
    assert doc2["oracle_subsampled_configs"] == 2


@pytest.mark.slow
def test_chaos_survives_crash_and_hang_and_resumes(tmp_path):
    """The acceptance drill: an injected subprocess crash AND an injected
    hang each go timeout → backoff → retry → skip-with-record (the run
    completes); a later run resumes from the checkpoint, retrying exactly
    the skipped configs, and a third run loads everything from checkpoint."""
    ck = str(tmp_path / "ck.json")
    doc = soak.run_soak(2, seed=7, oracle_every=100, chaos=True,
                        timeout_s=8, backoff_s=0.05, checkpoint=ck,
                        inject={0: "crash", 1: "hang"},
                        progress=lambda *a: None)
    assert len(doc["skipped"]) == 2
    assert all(s["attempts"] == 2 for s in doc["skipped"])
    errs = " ".join(s["error"] for s in doc["skipped"])
    assert "exit 139" in errs and "timeout" in errs
    assert doc["mismatches"] == [] and doc["violations"] == []

    # Resume: the two skipped configs are retried (now uninjected) and pass.
    doc2 = soak.run_soak(2, seed=7, oracle_every=100, chaos=True,
                         timeout_s=600, backoff_s=0.05, checkpoint=ck,
                         progress=lambda *a: None)
    assert doc2["resumed_configs"] == 0
    assert doc2["skipped"] == [] and doc2["mismatches"] == []

    # And a third run restores every record straight from the checkpoint.
    doc3 = soak.run_soak(2, seed=7, oracle_every=100, chaos=True,
                         timeout_s=600, checkpoint=ck,
                         progress=lambda *a: None)
    assert doc3["resumed_configs"] == 2
    assert doc3["skipped"] == [] and doc3["mismatches"] == []


def test_chaos_checkpoint_rejects_other_population(tmp_path):
    """A checkpoint binds to (generator_version, seed, chaos): resuming a
    different seed must start fresh, not splice foreign records in."""
    import pathlib

    ck = pathlib.Path(tmp_path / "ck.json")
    soak._save_checkpoint(ck, seed=1, records={"0": {"status": "ok"}})
    assert soak._load_checkpoint(ck, seed=1) == {"0": {"status": "ok"}}
    assert soak._load_checkpoint(ck, seed=2) == {}
    ck.write_text("{ torn")
    assert soak._load_checkpoint(ck, seed=1) == {}
