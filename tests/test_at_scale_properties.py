"""Direct protocol-property checks at benchmark n (VERDICT r2 #2; SURVEY.md §4.1).

At n>=256 the suite's correctness evidence was previously *cross-implementation
equality only* — which a spec misreading encoded identically in all four
implementations would survive. These tests close that loop: they run the real
vectorized product path (NumpyBackend.run_with_state — the same models/ round
bodies the JAX backend jits) at config-3/config-4 scale and assert the [ALG]
invariants over the FULL (B, n) per-replica state, not the collapsed
per-instance decision:

- **Agreement**: no two correct replicas of one instance decide differently.
- **Validity**: unanimous correct inputs v force decision v, under Byzantine
  and adaptive adversaries.
- **Termination**: with the shared coin, every instance decides well under the
  round cap (expected O(1) rounds [ALG: Rabin '83 / CKS '00]).
- **Decision consistency**: SimResult.decision — which reads only the
  lowest-indexed correct replica (models/state.py:extract_decision) — equals
  EVERY correct replica's decided value (the weak-#6 closure: the bit-match
  surface cannot hide a higher-indexed disagreement if this holds).

Urn legs run at full width (hundreds of instances — cheap: O(n·f) count-level
work); the O(n²) keys legs are slow-marked at reduced-but-real sample sizes.
"""

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu.backends.numpy_backend import NumpyBackend
from byzantinerandomizedconsensus_tpu.config import SimConfig


def _run(n, f, adversary, delivery, instances, init="random", seed=31):
    cfg = SimConfig(protocol="bracha", n=n, f=f, instances=instances,
                    adversary=adversary, coin="shared", seed=seed,
                    delivery=delivery, init=init).validate()
    res, state, faulty = NumpyBackend().run_with_state(cfg)
    return cfg, res, state, faulty


def _assert_invariants(cfg, res, state, faulty, expect_value=None):
    correct = ~faulty
    decided = state["decided"]
    vals = state["decided_val"]

    # Termination (shared coin): every correct replica of every instance
    # decided, comfortably under the cap.
    assert bool((decided | faulty).all()), "undecided correct replica"
    assert int(res.rounds.max()) < cfg.round_cap
    assert int((res.decision == 2).sum()) == 0

    # Decided values are bits.
    assert bool(np.isin(vals[correct & decided], (0, 1)).all())

    # Agreement over the full state: per instance, the correct deciders'
    # values span max-min == 0.
    cd = correct & decided
    v_masked = np.where(cd, vals, 0)
    per_inst_max = v_masked.max(axis=1)
    v_masked_hi = np.where(cd, vals, 1)
    per_inst_min = v_masked_hi.min(axis=1)
    assert bool((per_inst_max == per_inst_min).all()), \
        "Agreement violation among correct replicas"

    # Decision consistency (weak #6): the reported per-instance decision must
    # equal EVERY correct replica's decided value, not just replica correct[0].
    assert bool((vals[cd] == np.broadcast_to(
        res.decision[:, None], vals.shape)[cd]).all())

    # Validity: unanimous correct inputs force that value.
    if expect_value is not None:
        assert bool((res.decision == expect_value).all()), \
            f"Validity violation: expected unanimous decision {expect_value}"


@pytest.mark.parametrize("n,f", [(256, 85), (512, 170)])
@pytest.mark.parametrize("adversary", ["byzantine", "adaptive"])
def test_invariants_urn_at_benchmark_n(n, f, adversary):
    cfg, res, state, faulty = _run(n, f, adversary, "urn", instances=200)
    _assert_invariants(cfg, res, state, faulty)


def test_invariants_urn_adaptive_min_at_scale():
    """adaptive_min (spec §6.4b) holds the direct invariants at scale too;
    n=256 keeps the fast-suite cost of the extra adversary modest (the n=512
    shape is covered for the grid above)."""
    cfg, res, state, faulty = _run(256, 85, "adaptive_min", "urn", instances=200)
    _assert_invariants(cfg, res, state, faulty)


@pytest.mark.parametrize("n,f,adversary,instances", [
    (256, 85, "byzantine", 64),
    (256, 85, "adaptive", 64),
    (512, 170, "byzantine", 32),
])
@pytest.mark.slow
def test_invariants_keys_at_benchmark_n(n, f, adversary, instances):
    cfg, res, state, faulty = _run(n, f, adversary, "keys", instances=instances)
    _assert_invariants(cfg, res, state, faulty)


@pytest.mark.parametrize("n,f", [(256, 85), (512, 170)])
@pytest.mark.parametrize("adversary", ["byzantine", "adaptive"])
@pytest.mark.parametrize("init,expect", [("all0", 0), ("all1", 1)])
def test_validity_unanimous_urn_at_benchmark_n(n, f, adversary, init, expect):
    cfg, res, state, faulty = _run(n, f, adversary, "urn", instances=100,
                                   init=init)
    _assert_invariants(cfg, res, state, faulty, expect_value=expect)


@pytest.mark.parametrize("init,expect", [("all0", 0), ("all1", 1)])
@pytest.mark.slow
def test_validity_unanimous_keys_at_benchmark_n(init, expect):
    cfg, res, state, faulty = _run(256, 85, "byzantine", "keys", instances=48,
                                   init=init)
    _assert_invariants(cfg, res, state, faulty, expect_value=expect)


def test_oracle_agreement_assert_is_armed():
    """The oracle's always-on Agreement check (backends/cpu.py) fires on a
    fabricated disagreement — so its silence on real runs is evidence."""
    from byzantinerandomizedconsensus_tpu.backends.cpu import CpuBackend
    from byzantinerandomizedconsensus_tpu.core import replica as replica_mod

    cfg = SimConfig(protocol="benor", n=4, f=0, instances=1,
                    adversary="none", coin="shared", seed=3).validate()
    orig = replica_mod.Replica.end_round

    def sabotage(self, coin):
        orig(self, coin)
        if self.decided and self.index == 0:
            self.decided_val = 1 - self.decided_val

    replica_mod.Replica.end_round = sabotage
    try:
        with pytest.raises(AssertionError, match="Agreement violation"):
            CpuBackend().run(cfg)
    finally:
        replica_mod.Replica.end_round = orig
