"""Adversary unit tests (SURVEY.md §4.5): crash silences exactly the chosen replicas,
Byzantine equivocation produces per-receiver differences, the adaptive hook is a pure
function of round-t state, and faulty-set selection is exact."""

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu.config import SimConfig
from byzantinerandomizedconsensus_tpu.core.adversary import make_adversary
from byzantinerandomizedconsensus_tpu.models.adversaries import AdversaryModel, faulty_mask


def test_faulty_set_size_and_determinism():
    cfg = SimConfig(protocol="bracha", n=64, f=21, instances=50, adversary="byzantine",
                    coin="shared", seed=5).validate()
    ids = np.arange(50, dtype=np.int64)
    fm = faulty_mask(cfg, cfg.seed, ids, xp=np)
    np.testing.assert_array_equal(fm.sum(-1), np.full(50, cfg.f))
    fm2 = faulty_mask(cfg, cfg.seed, ids, xp=np)
    np.testing.assert_array_equal(fm, fm2)
    # oracle-side selection matches the vectorized one
    for i in (0, 17, 49):
        adv = make_adversary(cfg, cfg.seed, i)
        np.testing.assert_array_equal(adv.faulty, fm[i])
    # different instances get different sets (whp)
    assert not np.array_equal(fm[0], fm[1])


def test_none_adversary_has_no_faults():
    cfg = SimConfig(protocol="bracha", n=512, f=170, instances=3, adversary="none",
                    coin="shared", seed=0).validate()
    fm = faulty_mask(cfg, cfg.seed, np.arange(3), xp=np)
    assert not fm.any()


def test_crash_silences_only_faulty_and_sticks():
    cfg = SimConfig(protocol="benor", n=16, f=7, instances=20, adversary="crash",
                    coin="local", seed=6, crash_window=4).validate()
    ids = np.arange(20, dtype=np.int64)
    adv = AdversaryModel(cfg)
    setup = adv.setup(cfg.seed, ids, xp=np)
    honest = np.zeros((20, 16), dtype=np.uint8)
    prev_silent = np.zeros((20, 16), dtype=bool)
    for r in range(6):
        _, silent, _ = adv.inject(cfg.seed, ids, r, 0, honest, setup, xp=np)
        assert not (silent & ~setup["faulty"]).any(), "crash silenced a correct replica"
        assert (prev_silent <= silent).all(), "a crashed replica came back"
        prev_silent = silent
    # by round >= crash_window all faulty replicas have crashed
    assert (prev_silent == setup["faulty"]).all()


def test_byzantine_equivocation_differs_per_receiver():
    cfg = SimConfig(protocol="benor", n=16, f=3, instances=10, adversary="byzantine",
                    coin="local", seed=7).validate()
    ids = np.arange(10, dtype=np.int64)
    adv = AdversaryModel(cfg)
    setup = adv.setup(cfg.seed, ids, xp=np)
    honest = np.ones((10, 16), dtype=np.uint8)
    values, silent, _ = adv.inject(cfg.seed, ids, 0, 0, honest, setup, xp=np)
    assert values.ndim == 3, "plain-byzantine pairing must use the equivocation matrix"
    fidx = np.argmax(setup["faulty"][0])
    col = values[0, :, fidx]
    assert len(np.unique(col)) > 1, "faulty sender never equivocated"
    # honest columns are constant
    hidx = np.argmax(~setup["faulty"][0])
    assert len(np.unique(values[0, :, hidx])) == 1


def test_byzantine_rbc_common_outcome():
    cfg = SimConfig(protocol="bracha", n=16, f=5, instances=10, adversary="byzantine",
                    coin="shared", seed=8).validate()
    ids = np.arange(10, dtype=np.int64)
    adv = AdversaryModel(cfg)
    setup = adv.setup(cfg.seed, ids, xp=np)
    honest = np.ones((10, 16), dtype=np.uint8)
    values, silent, _ = adv.inject(cfg.seed, ids, 0, 0, honest, setup, xp=np)
    assert values.ndim == 2, "bracha pairing must deliver a common per-sender outcome"
    # over many (instance, sender, step) draws, all four outcomes occur
    outs = set()
    for r in range(4):
        for t in range(3):
            v, s, _ = adv.inject(cfg.seed, ids, r, t, honest, setup, xp=np)
            f = setup["faulty"]
            outs |= set(np.asarray(v[f & ~s]).tolist())
            if (f & s).any():
                outs.add("silent")
    assert outs >= {0, 1, "silent"}


def test_adaptive_pushes_minority_and_is_pure():
    cfg = SimConfig(protocol="bracha", n=16, f=5, instances=8, adversary="adaptive",
                    coin="shared", seed=9).validate()
    ids = np.arange(8, dtype=np.int64)
    adv = AdversaryModel(cfg)
    setup = adv.setup(cfg.seed, ids, xp=np)
    # construct a 10-vs-1 honest split; minority is 0 where honest ones dominate
    honest = np.ones((8, 16), dtype=np.uint8)
    hidx = np.where(~setup["faulty"][0])[0]
    honest[0, hidx[0]] = 0
    values, silent, bias = adv.inject(cfg.seed, ids, 3, 1, honest, setup, xp=np)
    assert (values[0, setup["faulty"][0]] == 0).all(), "adaptive must push the minority value"
    # purity: same inputs -> same outputs (no hidden state, no future information)
    values2, silent2, bias2 = adv.inject(cfg.seed, ids, 3, 1, honest, setup, xp=np)
    np.testing.assert_array_equal(values, values2)
    np.testing.assert_array_equal(bias, bias2)
    # bias splits receivers into two camps with opposite preferences
    assert bias.shape == (8, 16, 16)
    lo, hi = bias[0, 0], bias[0, 15]
    assert not np.array_equal(lo, hi), "receiver halves must be biased oppositely"
