"""Protocol counters (obs/counters.py): the flight recorder's on-device leg.

Two load-bearing properties, per the round-8 acceptance bar:

1. **Invariance** — enabling counters leaves the bit-match surface
   (rounds/decision) bit-identical on the jax and numpy backends, for preset
   configs (the side channel never feeds back into the round math);
2. **Cross-check** — the vectorized totals equal the scalar oracle's
   independent message-level counts at small n, across every delivery law
   and adversary family.
"""

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu.backends import get_backend
from byzantinerandomizedconsensus_tpu.config import (
    SimConfig, preset, sweep_point)
from byzantinerandomizedconsensus_tpu.obs import counters as obs_counters


def _eq(a, b):
    return (np.array_equal(a.rounds, b.rounds)
            and np.array_equal(a.decision, b.decision))


# Three benchmark presets (instance counts trimmed to CI scale — the config
# *shapes*, which drive the kernels, are as shipped) plus the config-5 sweep
# shape: benor/none, benor/crash, bracha/byzantine, bracha/adaptive.
PRESET_CASES = [
    ("config1", preset("config1")),
    ("config2", preset("config2", instances=32)),
    ("config3", preset("config3", instances=8)),
    ("config5", sweep_point(64, instances=8)),
]


@pytest.mark.parametrize("name,cfg", PRESET_CASES,
                         ids=[c[0] for c in PRESET_CASES])
def test_counters_invariant_and_backend_agree(name, cfg):
    """Counters on == counters off, bit-for-bit, on numpy AND jax — and the
    two stacks' totals are identical."""
    nb, jb = get_backend("numpy"), get_backend("jax")
    base = nb.run(cfg)
    res_n, doc_n = nb.run_with_counters(cfg)
    assert _eq(base, res_n), f"{name}: numpy counters moved the results"

    jbase = jb.run(cfg)
    assert _eq(base, jbase), f"{name}: jax/numpy bit-mismatch (pre-existing)"
    res_j, doc_j = jb.run_with_counters(cfg)
    assert _eq(jbase, res_j), f"{name}: jax counters moved the results"

    assert doc_n["totals"] == doc_j["totals"]
    assert doc_n["schema"] == obs_counters.COUNTER_SCHEMA_VERSION
    # Built-in self-check: rounds_active ≡ the result surface's rounds sum.
    assert doc_n["totals"]["rounds_active"] == int(base.rounds.sum())


ORACLE_GRID = [
    ("bracha", "adaptive", 10, 3),
    ("bracha", "byzantine", 10, 3),
    ("bracha", "adaptive_min", 8, 2),
    ("benor", "byzantine", 7, 1),   # two-faced §4b equivocation under benor
    ("benor", "crash", 9, 4),
    ("benor", "none", 7, 2),
]


@pytest.mark.parametrize("delivery", ["keys", "urn", "urn2", "urn3"])
def test_counters_cross_check_oracle(delivery):
    """Vectorized totals == the oracle's independent message-level counts
    (its common subset: delivered/dropped per phase, coin flips, rounds)."""
    nb, cb = get_backend("numpy"), get_backend("cpu")
    for proto, adv, n, f in ORACLE_GRID:
        cfg = SimConfig(protocol=proto, n=n, f=f, instances=6, adversary=adv,
                        coin="shared", delivery=delivery,
                        round_cap=32).validate()
        res_n, doc_n = nb.run_with_counters(cfg)
        res_c, doc_c = cb.run_with_counters(cfg)
        assert _eq(res_n, res_c), (proto, adv, delivery)
        common = {k: v for k, v in doc_n["totals"].items()
                  if k in doc_c["totals"]}
        assert common == doc_c["totals"], (proto, adv, delivery)
        # The oracle subset covers everything but the sampler cost counters.
        assert set(doc_n["totals"]) - set(doc_c["totals"]) <= {
            "urn_draws", "chain_trips", "chain_trips_max", "urn3_words"}


def test_sampler_cost_counter_laws():
    """The sampler-owned counters obey their closed-form laws: §4b draws =
    the drop total; §4c words = one per receiver-step; §4b-v2 chain trips
    reach K = D on balanced wires and collapse on adaptive strata."""
    nb = get_backend("numpy")

    def totals(adversary, delivery):
        cfg = SimConfig(protocol="bracha", n=16, f=5, instances=16,
                        adversary=adversary, coin="shared", delivery=delivery,
                        round_cap=64).validate()
        _, doc = nb.run_with_counters(cfg)
        return doc["totals"]

    t = totals("none", "urn")
    dropped = sum(v for k, v in t.items() if k.startswith("dropped@"))
    assert t["urn_draws"] == dropped

    t = totals("none", "urn3")
    assert t["urn3_words"] == 3 * 16 * t["rounds_active"]  # steps · n · rounds

    balanced = totals("none", "urn2")       # mixed random ests: wires balance
    adaptive = totals("adaptive", "urn2")   # value-homogeneous bias strata
    assert 0 < balanced["chain_trips_max"] <= 5          # K ≤ D ≤ f
    assert balanced["chain_trips"] > 10 * adaptive["chain_trips"], \
        "the adaptive shape should sit in the chains' deterministic corner"


def test_counters_unsupported_backends_degrade_cleanly():
    cfg = preset("config1")
    for backend in ("native", "jax_pallas", "virtual"):
        with pytest.raises(obs_counters.CountersUnsupported):
            be = get_backend(backend)
            # jax_pallas rejects at the kernel gate, native/virtual at the
            # base seam — neither needs a device or a compiler to refuse.
            be.run_with_counters(preset("config1", delivery="urn")
                                 if backend == "jax_pallas" else cfg)
    from byzantinerandomizedconsensus_tpu.obs import record

    doc = record.collect_counters(get_backend("native"), cfg)
    assert doc == {"schema": obs_counters.COUNTER_SCHEMA_VERSION,
                   "supported": False, "reason": doc["reason"]}
    assert "native" in doc["reason"]


def test_accumulator_uint32_carry_and_max_merge():
    """The (lo, hi) pair arithmetic: per-round uint32 increments carry into
    the hi word exactly; max counters max-merge instead of summing."""
    cfg = preset("config1")  # delivery=urn2 → has a max counter
    names = obs_counters.counter_names(cfg)
    big = np.uint32(0xFFFFFFFF)
    acc = obs_counters.zeros(cfg, 2, np)
    inc = np.full((2, len(names)), big, dtype=np.uint32)
    active = np.array([True, True])
    for _ in range(2):
        acc = obs_counters.accumulate(acc, inc, active, cfg, np)
    totals = obs_counters.finalize(cfg, acc)
    for name in names:
        if name == "chain_trips_max":
            assert totals[name] == 0xFFFFFFFF
        else:  # 2 instances × 2 rounds × (2^32 − 1)
            assert totals[name] == 2 * 2 * 0xFFFFFFFF


def test_accumulator_respects_activity_mask():
    cfg = preset("config1")
    names = obs_counters.counter_names(cfg)
    acc = obs_counters.zeros(cfg, 2, np)
    inc = np.full((2, len(names)), 7, dtype=np.uint32)
    acc = obs_counters.accumulate(acc, inc, np.array([True, False]), cfg, np)
    totals = obs_counters.finalize(cfg, acc)
    assert totals["rounds_active"] == 7  # only the active instance counted


FAULT_KINDS_ACTIVE = ("recover", "partition", "omission")


@pytest.mark.parametrize("fault", FAULT_KINDS_ACTIVE)
def test_fault_counters_invariant_and_cross_stack(fault):
    """Schema v2 (spec §9): fault-attributed counters are a pure side output
    (results bit-identical with counters on), numpy == jax totals, and the
    message-level subset — including the fault counters and the
    partition-aware dropped law — equals the oracle's independent count."""
    nb, jb, cb = get_backend("numpy"), get_backend("jax"), get_backend("cpu")
    for delivery in ("keys", "urn2"):
        cfg = SimConfig(protocol="bracha", n=8, f=2, instances=6,
                        adversary="crash", coin="local", seed=9,
                        round_cap=48, delivery=delivery,
                        faults=fault).validate()
        base = nb.run(cfg)
        res_n, doc_n = nb.run_with_counters(cfg)
        assert _eq(base, res_n), "counters moved the results under faults"
        res_j, doc_j = jb.run_with_counters(cfg)
        assert _eq(base, res_j)
        assert doc_n["totals"] == doc_j["totals"]
        res_c, doc_c = cb.run_with_counters(cfg)
        assert _eq(base, res_c)
        common = {k: v for k, v in doc_n["totals"].items()
                  if k in doc_c["totals"]}
        assert common == doc_c["totals"], (fault, delivery)
        # The v2 fault columns exist for every phase...
        phases = obs_counters.phase_names(cfg)
        for ph in phases:
            assert f"fault_silenced@{ph}" in doc_n["totals"]
            assert f"fault_cut_pairs@{ph}" in doc_n["totals"]
        # ...and attribute the right mechanism: silences for recover and
        # omission, cut pairs only for partition.
        sil = sum(doc_n["totals"][f"fault_silenced@{ph}"] for ph in phases)
        cut = sum(doc_n["totals"][f"fault_cut_pairs@{ph}"] for ph in phases)
        if fault == "partition":
            assert sil == 0
        else:
            assert cut == 0


def test_fault_counters_absent_without_fault_schedule():
    """faults="none" keeps the exact v1 column set — schema v2 adds columns
    only when a schedule is configured."""
    cfg = SimConfig(protocol="benor", n=7, f=2, instances=4,
                    adversary="crash", round_cap=32,
                    delivery="urn2").validate()
    assert not any(n.startswith("fault_")
                   for n in obs_counters.counter_names(cfg))
