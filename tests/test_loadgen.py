"""Loadgen determinism pin (round-14 satellite, tier-1).

The request stream must be a pure function of (generator_version, seed,
requests, rate): two runs at the same seed reproduce the identical stream
byte-for-byte (arrival times, configs AND session slot counts — generator
v3 mixes spec-§11 sessions into the population), and serving the stream
returns results bit-identical to the offline batched path over the same
configs.
"""

import dataclasses

import pytest

from byzantinerandomizedconsensus_tpu.backends.base import get_backend
from byzantinerandomizedconsensus_tpu.backends.compaction import (
    CompactionPolicy)
from byzantinerandomizedconsensus_tpu.serve import admission
from byzantinerandomizedconsensus_tpu.serve.server import ConsensusServer
from byzantinerandomizedconsensus_tpu.tools import loadgen

#: Pinned so the stream below stays 3 fused buckets (compile-light in CI);
#: a generator change that moves it shows up as a digest change here.
_SEED = 35


def test_stream_reproduces_byte_for_byte():
    a = loadgen.request_stream(40, seed=_SEED, rate=4.0)
    b = loadgen.request_stream(40, seed=_SEED, rate=4.0)
    assert loadgen.stream_digest(a) == loadgen.stream_digest(b)
    assert [(t, dataclasses.asdict(c), s) for t, c, s in a] == \
        [(t, dataclasses.asdict(c), s) for t, c, s in b]
    # arrival times strictly increase (open-loop Poisson gaps)
    times = [t for t, _, _ in a]
    assert all(t1 > t0 for t0, t1 in zip(times, times[1:]))
    # a different seed is a different stream
    c = loadgen.request_stream(40, seed=_SEED + 1, rate=4.0)
    assert loadgen.stream_digest(c) != loadgen.stream_digest(a)


def test_stream_digest_invariant_under_worker_count():
    """Round-15 fleet pin: the request stream is generated once, before
    dispatch — the sha256 digest is byte-identical for --workers 1/2/4
    (worker count routes, it never reshapes the arrival process)."""
    digests = {
        k: loadgen.stream_digest(
            loadgen.fleet_request_stream(40, seed=_SEED, rate=4.0,
                                         workers=k))
        for k in (1, 2, 4)}
    assert digests[1] == digests[2] == digests[4]
    assert digests[1] == loadgen.stream_digest(
        loadgen.request_stream(40, seed=_SEED, rate=4.0))


def test_stream_population_is_admissible():
    """Every draw respects the service's admission bounds by construction:
    validated configs, round_cap at or under the ceiling, the three
    population modes all present at this size."""
    stream = loadgen.request_stream(120, seed=7, rate=4.0)
    fat, keys, sessions = 0, 0, 0
    for _, cfg, slots in stream:
        cfg.validate()
        assert cfg.round_cap <= loadgen.ROUND_CAP_CEILING
        assert 1 <= slots <= 8
        if cfg.instances > 32:
            fat += 1
        if cfg.delivery == "keys" and cfg.adversary == "none":
            keys += 1
        if slots > 1:
            sessions += 1
    assert fat > 0, "fat-tail shapes absent from the population"
    assert keys > 0, "keys-model validation traffic absent"
    assert sessions > 0, "session traffic absent (generator v3 mix)"


def test_generator_v3_session_mix_is_pinned():
    """Generator v3 (round 21) draws a session slot count per request;
    the draw is part of the stream, so the digest covers it — a slot-count
    change at a fixed seed is a digest change, and the mix shows up at
    modest stream sizes."""
    assert loadgen.GENERATOR_VERSION == 3
    stream = loadgen.request_stream(40, seed=_SEED, rate=4.0)
    n_sessions = sum(1 for _, _, s in stream if s > 1)
    assert n_sessions == 7  # seed pin: v3 mix at _SEED/40
    mutated = [(t, c, (s + 1 if i == 0 else s))
               for i, (t, c, s) in enumerate(stream)]
    assert loadgen.stream_digest(mutated) != loadgen.stream_digest(stream)


@pytest.mark.slow
def test_served_results_bit_identical_to_offline_batched_path():
    """The same configs, served (streamed, continuously batched) vs the
    offline batched path (grid barrier, run_many over the shared compile
    cache): per-instance rounds/decisions equal bit-for-bit."""
    stream = loadgen.request_stream(6, seed=_SEED, rate=50.0)
    cfgs = [c for _, c, _ in stream]
    assert len({admission.bucket_of(c) for c in cfgs}) == 3  # seed pin
    policy = CompactionPolicy(width=8, segment=1)
    with ConsensusServer(policy=policy) as srv:
        handles = [srv.submit(c) for c in cfgs]
        recs = [h.wait(timeout=600.0) for h in handles]
    offline, _report = get_backend("jax").run_many(cfgs, compaction=policy)
    for rec, ref in zip(recs, offline):
        assert rec["rounds"] == [int(r) for r in ref.rounds]
        assert rec["decision"] == [int(d) for d in ref.decision]
