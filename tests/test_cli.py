"""CLI surface tests (SURVEY.md C9): every subcommand end-to-end in-process,
plus the documented error paths. Uses the numpy backend so no device is needed."""

import json

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu import cli


def _run_cli(capsys, argv):
    rc = cli.main(argv)
    out = capsys.readouterr().out.strip().splitlines()
    return rc, json.loads(out[-1]) if out else None


def test_run_preset(capsys):
    rc, out = _run_cli(capsys, ["run", "--preset", "config1", "--backend", "numpy"])
    assert rc == 0
    assert out["n"] == 4 and out["instances"] == 1
    assert out["decided"] + out["undecided_at_cap"] == 1


def test_run_custom_urn_hist(capsys):
    rc, out = _run_cli(capsys, [
        "run", "--protocol", "bracha", "-n", "10", "-f", "3", "--instances", "50",
        "--adversary", "byzantine", "--coin", "shared", "--backend", "numpy",
        "--delivery", "urn", "--hist"])
    assert rc == 0
    hist = out["round_histogram"]
    assert sum(hist) == 50
    assert sum(out["decision_histogram"]) == 50


def test_run_round_cap_overflow(capsys):
    rc, out = _run_cli(capsys, [
        "run", "--preset", "config1", "--backend", "numpy", "--round-cap", "1"])
    assert rc == 0
    assert out["decision_histogram"][2] == out["undecided_at_cap"]


def test_run_total_instances_multiseed(capsys):
    rc, out = _run_cli(capsys, [
        "run", "--protocol", "bracha", "-n", "7", "-f", "2", "--instances", "1",
        "--coin", "shared", "--backend", "numpy", "--delivery", "urn",
        "--total-instances", "40"])
    assert rc == 0
    assert out["instances"] == 40 and len(out["seeds"]) >= 1


def test_bitmatch_pass_and_guard(capsys):
    rc, out = _run_cli(capsys, [
        "bitmatch", "--protocol", "bracha", "-n", "10", "-f", "3",
        "--instances", "30", "--adversary", "crash", "--backend", "numpy",
        "--samples", "4"])
    assert rc == 0 and out["bitmatch"] is True
    # cpu-vs-cpu is rejected with a usage error
    assert cli.main(["bitmatch", "--preset", "config1", "--backend", "cpu"]) == 2
    capsys.readouterr()


def test_sweep_resumable(tmp_path, capsys):
    argv = ["sweep", "--out", str(tmp_path), "--backend", "numpy",
            "--ns", "16", "--instances", "40", "--shard-instances", "20",
            "--delivery", "urn"]
    rc, out = _run_cli(capsys, argv)
    assert rc == 0
    # The sweep artifact is a v1 run record (obs/record.py): points under
    # "points", next to the record head.
    assert out["record_version"] == 1 and out["kind"] == "sweep"
    assert sum(out["points"]["16"]["round_histogram"]) == 40
    assert len(list(tmp_path.glob("*.npz"))) == 2
    # resume: identical points, no new shards (the env fingerprint may
    # legitimately differ between invocations — e.g. backend init state)
    rc2, out2 = _run_cli(capsys, argv)
    assert rc2 == 0 and out2["points"] == out["points"]


def test_invalid_config_errors():
    with pytest.raises(ValueError, match="n > 3f"):
        cli.main(["run", "--protocol", "bracha", "-n", "9", "-f", "3",
                  "--backend", "numpy"])
    with pytest.raises(SystemExit):  # argparse rejects unknown choices
        cli.main(["run", "--delivery", "bogus"])
    with pytest.raises(KeyError, match="unknown backend"):
        cli.main(["run", "--preset", "config1", "--backend", "nope"])


def test_accept_subcommand_passthrough(capsys, tmp_path):
    """`cli accept` forwards argv to tools/acceptance.py."""
    import shutil

    if shutil.which("g++") is None:
        pytest.skip("no C++ toolchain")
    rc, out = _run_cli(capsys, [
        "accept", "--out", str(tmp_path / "acc.json"), "--samples", "8",
        "--presets", "config1", "--deliveries", "urn", "--backends", "numpy"])
    assert rc == 0
    assert out["all_match"] is True


def test_product_subcommand_passthrough(capsys, tmp_path):
    """`cli product` runs shipped configs end-to-end and merges an artifact."""
    # Partial-run merge: a pre-existing entry from another invocation (e.g.
    # the TPU legs) must survive a later single-config run.
    (tmp_path / "p.json").write_text(json.dumps(
        {"config4": {"wall_s": 0.37, "instances_per_sec": 272479.6}}))
    rc, out = _run_cli(capsys, [
        "product", "--out", str(tmp_path / "p.json"), "--backend", "numpy",
        "--configs", "config1"])
    assert rc == 0 and out["configs"] == ["config1", "config4"]
    art = json.loads((tmp_path / "p.json").read_text())
    assert art["config1"]["round_cap"] == 256  # as shipped, never lowered
    assert sum(art["config1"]["round_histogram"]) == 1
    assert art["config4"]["instances_per_sec"] == 272479.6


def test_slack_subcommand_passthrough(capsys, tmp_path):
    rc, out = _run_cli(capsys, [
        "slack", "--out", str(tmp_path / "s.json"),
        "--shards", str(tmp_path / "shards"), "--fig", "",
        "--ns", "13", "--instances", "8", "--round-cap", "8",
        "--backend", "numpy"])
    assert rc == 0
    assert (tmp_path / "s.json").exists()


def test_bitmatch_native_arbiter(capsys):
    import shutil

    if shutil.which("g++") is None:
        pytest.skip("no C++ toolchain")
    rc, out = _run_cli(capsys, [
        "bitmatch", "--protocol", "bracha", "-n", "16", "-f", "5",
        "--instances", "200", "--adversary", "adaptive", "--coin", "shared",
        "--delivery", "urn", "--backend", "numpy",
        "--arbiter", "native", "--samples", "100"])
    assert rc == 0
    assert out["bitmatch"] is True and out["arbiter"] == "native"
    assert out["n_samples"] == 100 and "samples" not in out


def test_bitmatch_reports_effective_instances(capsys):
    """Widening a small preset's id range is recorded in the output JSON
    (ADVICE r2): instances must reflect the config actually compared."""
    rc, out = _run_cli(capsys, [
        "bitmatch", "--preset", "config1", "--backend", "numpy",
        "--samples", "8"])
    assert rc == 0
    assert out["instances"] == 8  # config1 ships instances=1, widened to samples
    rc2, out2 = _run_cli(capsys, [
        "bitmatch", "--protocol", "benor", "-n", "4", "-f", "1",
        "--instances", "30", "--backend", "numpy", "--samples", "4"])
    assert rc2 == 0 and out2["instances"] == 30  # no widening: kept verbatim


def test_sweep_warns_on_round_cap_mismatch(tmp_path, capsys):
    """Shards computed under a different round cap must not silently fail to
    resume (ADVICE r2): the driver names them stale and says why."""
    base = ["sweep", "--out", str(tmp_path), "--backend", "numpy",
            "--ns", "16", "--instances", "20", "--shard-instances", "20",
            "--delivery", "urn"]
    assert cli.main(base + ["--round-cap", "64"]) == 0
    capsys.readouterr()
    assert cli.main(base + ["--round-cap", "128"]) == 0
    err = capsys.readouterr().err
    assert "round cap" in err and "round_cap=128" in err
