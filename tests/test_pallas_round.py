"""Gate-closure pins for the fused round kernel (ops/pallas_round.py, ABI v6).

Round 20: every fault × committee config below used to raise
``FaultsUnsupported`` / ``CommitteeUnsupported`` on the Pallas path (the
per-step kernels have no fault-schedule or committee channel). The fused
kernel carries both in-kernel, so the same configs now run on
``kernel='fused'`` and must bit-match the XLA oracle — on CPU the kernel
runs in Pallas interpret mode (see the ``pallas_interpret`` fixture), which
is exactly how the bit-match is provable in CI. The per-step kernels keep
their gates: closing one door must not silently unlock the others.
"""

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu.backends.base import get_backend
from byzantinerandomizedconsensus_tpu.config import SimConfig
from byzantinerandomizedconsensus_tpu.models.committee import CommitteeUnsupported
from byzantinerandomizedconsensus_tpu.models.faults import FaultsUnsupported
from byzantinerandomizedconsensus_tpu.ops.pallas_round import FusedUnsupported


# Previously-gated surface, one config per closed gate: every §9 fault kind
# (recover / omission / partition) and the §10 committee family, plus a
# fault-free control. Kept small — whole-round interpret mode pays per-op
# eager dispatch, so instance counts stay in one 8-block where possible.
GATED_GRID = [
    SimConfig(protocol="bracha", n=6, f=1, instances=8,
              adversary="adaptive", coin="shared", init="split", seed=7,
              round_cap=64, delivery="urn2", faults="recover",
              crash_window=4),
    SimConfig(protocol="benor", n=8, f=1, instances=12,
              adversary="crash", coin="shared", init="random", seed=11,
              round_cap=32, delivery="urn"),
    SimConfig(protocol="bracha", n=8, f=1, instances=10,
              adversary="none", coin="local", init="all1", seed=5,
              round_cap=32, delivery="urn3", faults="omission"),
    SimConfig(protocol="benor", n=12, f=2, instances=8,
              adversary="adaptive_min", coin="shared", init="random",
              seed=9, round_cap=48, delivery="urn", faults="partition"),
    SimConfig(protocol="benor", n=64, f=2, instances=6,
              adversary="byzantine", coin="shared", init="random",
              seed=3, round_cap=48, delivery="committee"),
]


@pytest.mark.parametrize(
    "cfg", GATED_GRID,
    ids=[f"{c.protocol}-n{c.n}-{c.delivery}-{c.adversary}-f{c.faults}"
         for c in GATED_GRID])
def test_fused_closes_fault_and_committee_gates(cfg, pallas_interpret):
    """Configs the per-step Pallas path rejects run on kernel='fused' and
    bit-match the XLA oracle (rounds AND decision, every instance)."""
    assert pallas_interpret, "suite is pinned to CPU interpret mode"
    cfg = cfg.validate()
    a = get_backend("jax").run(cfg)
    b = get_backend("jax_fused").run(cfg)
    np.testing.assert_array_equal(a.rounds, b.rounds)
    np.testing.assert_array_equal(a.decision, b.decision)


@pytest.mark.parametrize("backend_cfg,exc", [
    (SimConfig(protocol="benor", n=6, f=1, instances=4, adversary="none",
               coin="local", round_cap=8, seed=0, delivery="urn",
               faults="recover", crash_window=4), FaultsUnsupported),
    (SimConfig(protocol="benor", n=16, f=1, instances=4, adversary="none",
               coin="shared", round_cap=8, seed=0,
               delivery="committee"), CommitteeUnsupported),
], ids=["faults", "committee"])
def test_per_step_pallas_gates_still_raise(backend_cfg, exc):
    """The per-step kernel path keeps its named gates — the fused kernel
    closing them must not silently change kernel='pallas' behavior."""
    with pytest.raises(exc, match="kernel='pallas'"):
        get_backend("jax_pallas").run(backend_cfg.validate())


def test_fused_unsupported_names_the_surface():
    """Outside the ABI v6 surface the fused kernel raises one named error
    that lists the whole supported surface (never a silent fallback)."""
    cfg = SimConfig(protocol="benor", n=7, f=3, instances=4,
                    adversary="none", coin="shared", round_cap=8,
                    seed=0).validate()  # delivery='keys' (superset lanes)
    with pytest.raises(FusedUnsupported) as ei:
        get_backend("jax_fused").run(cfg)
    msg = str(ei.value)
    assert "delivery='keys'" in msg
    for named in ("urn", "urn2", "urn3", "committee",   # deliveries
                  "adaptive_min", "recover", "partition", "omission"):
        assert named in msg, f"surface must name {named!r}"


def test_packed_state_word_roundtrip_and_layout():
    """The resident u32 state word round-trips and its bit layout matches
    the published prf.FUSED_STATE_BITS record (spec §A6; any relayout must
    bump FUSED_STATE_PACK_VERSION)."""
    import jax.numpy as jnp

    from byzantinerandomizedconsensus_tpu.ops import prf
    from byzantinerandomizedconsensus_tpu.ops.pallas_round import (
        _pack_state, _unpack_state)

    assert prf.FUSED_STATE_PACK_VERSION == 1
    assert prf.FUSED_STATE_BITS == {"est": (0, 2), "decided": (2, 1),
                                    "decided_val": (3, 2), "phase": (8, 24)}

    rng = np.random.default_rng(20)
    st = {
        "est": jnp.asarray(rng.integers(0, 2, 64, dtype=np.uint8)),
        "decided": jnp.asarray(rng.integers(0, 2, 64).astype(bool)),
        "decided_val": jnp.asarray(rng.integers(0, 2, 64, dtype=np.uint8)),
        "phase": jnp.asarray(rng.integers(0, 1 << 20, 64, dtype=np.int32)),
    }
    word = _pack_state(st)
    assert word.dtype == jnp.uint32
    back = _unpack_state(word)
    for k in st:
        np.testing.assert_array_equal(np.asarray(back[k]), np.asarray(st[k]),
                                      err_msg=k)
    # Layout pin: each field lands at its published (shift, width) slot.
    w = np.asarray(word).astype(np.uint64)
    for field, (shift, width) in prf.FUSED_STATE_BITS.items():
        got = (w >> np.uint64(shift)) & np.uint64((1 << width) - 1)
        want = np.asarray(st[field]).astype(np.uint64)
        np.testing.assert_array_equal(got, want, err_msg=field)


def test_fused_compile_cache_is_seed_and_request_size_independent():
    """The serve pin: the key rides as an operand plane and chunks clamp to
    power-of-two bins, so new seeds / new instance counts inside a warmed
    bin compile nothing (zero steady-state recompiles)."""
    from byzantinerandomizedconsensus_tpu.backends.jax_backend import JaxBackend

    be = JaxBackend(kernel="fused")
    base = SimConfig(protocol="benor", n=6, f=1, instances=5,
                     adversary="crash", coin="shared", round_cap=16,
                     seed=1, delivery="urn").validate()
    warm = be.run(base)
    warmed = be.compile_probe()
    assert warmed >= 1  # the warm-up did compile something

    import dataclasses
    for seed, instances in ((2, 5), (3, 7), (40, 3), (2, 8)):
        cfg = dataclasses.replace(base, seed=seed,
                                  instances=instances).validate()
        out = be.run(cfg)
        assert len(out.decision) == instances
    assert be.compile_probe() == warmed, "steady-state recompile on the fused path"
    # and the warm-up result itself stays the oracle's
    oracle = get_backend("jax").run(base)
    np.testing.assert_array_equal(warm.rounds, oracle.rounds)
    np.testing.assert_array_equal(warm.decision, oracle.decision)
