"""Protocol property tests (SURVEY.md §4.1): Agreement, Validity, Termination — the
[ALG] invariants, checked as backend-independent oracles over the vectorized state
(fast, many instances) and spot-checked on the CPU oracle."""

import numpy as np
import pytest

from byzantinerandomizedconsensus_tpu import SimConfig, Simulator
from byzantinerandomizedconsensus_tpu.models import benor, bracha, state as state_mod
from byzantinerandomizedconsensus_tpu.models.adversaries import AdversaryModel


def run_to_state(cfg, rounds=None):
    """Run all instances with the numpy models path; return (state, faulty)."""
    cfg = cfg.validate()
    ids = np.arange(cfg.instances, dtype=np.int64)
    adv = AdversaryModel(cfg)
    setup = adv.setup(cfg.seed, ids, xp=np)
    st = state_mod.init_state(cfg, cfg.seed, ids, xp=np)
    body = benor.round_body if cfg.protocol == "benor" else bracha.round_body
    for r in range(rounds or cfg.round_cap):
        st = body(cfg, cfg.seed, ids, r, st, adv, setup, xp=np)
        if state_mod.all_correct_decided(st, setup["faulty"], xp=np).all():
            break
    return st, setup["faulty"]


CONFIGS = [
    SimConfig(protocol="benor", n=4, f=1, instances=300, adversary="none", coin="local",
              round_cap=128, seed=21),
    SimConfig(protocol="benor", n=16, f=7, instances=200, adversary="crash", coin="local",
              round_cap=256, seed=22),
    SimConfig(protocol="benor", n=16, f=3, instances=200, adversary="byzantine",
              coin="local", round_cap=256, seed=23),
    SimConfig(protocol="benor", n=16, f=3, instances=200, adversary="adaptive",
              coin="shared", round_cap=256, seed=24),
    SimConfig(protocol="bracha", n=16, f=5, instances=200, adversary="byzantine",
              coin="shared", round_cap=128, seed=25),
    SimConfig(protocol="bracha", n=16, f=5, instances=200, adversary="adaptive",
              coin="shared", round_cap=128, seed=26),
    SimConfig(protocol="bracha", n=10, f=3, instances=200, adversary="crash",
              coin="shared", round_cap=128, seed=27),
]

_id = lambda c: f"{c.protocol}-n{c.n}f{c.f}-{c.adversary}-{c.coin}"


@pytest.mark.parametrize("cfg", CONFIGS, ids=_id)
def test_agreement(cfg):
    """No two correct replicas of one instance ever decide different values."""
    st, faulty = run_to_state(cfg)
    correct_decided = st["decided"] & ~faulty
    vals = st["decided_val"]
    # max and min over decided correct replicas must coincide per instance
    vmax = np.where(correct_decided, vals, 0).max(axis=1)
    vmin = np.where(correct_decided, vals, 1).min(axis=1)
    has2 = correct_decided.sum(axis=1) >= 2
    assert (vmax[has2] == vmin[has2]).all(), "agreement violated"


@pytest.mark.parametrize("cfg", CONFIGS, ids=_id)
@pytest.mark.parametrize("v", [0, 1])
def test_validity(cfg, v):
    """If every correct replica starts with v, every correct decision is v — and with
    unanimous starts the instance must decide (round 1 under any schedule, spec §5)."""
    import dataclasses

    cfg2 = dataclasses.replace(cfg, init=f"all{v}", instances=50)
    st, faulty = run_to_state(cfg2)
    correct = ~faulty
    assert (st["decided"] | ~correct).all(), "unanimous instance failed to terminate"
    assert (np.where(correct, st["decided_val"], v) == v).all(), "validity violated"


@pytest.mark.parametrize(
    "cfg",
    [c for c in CONFIGS if c.coin == "shared" or c.n <= 4 or c.adversary == "none"],
    ids=_id,
)
def test_termination_quantile(cfg):
    """Probabilistic termination, asserted on quantiles (SURVEY.md §4.1): shared-coin
    and tiny-n local-coin regimes decide well before the cap for ≥ 95% of instances."""
    res = Simulator(cfg, "numpy").run()
    frac = float((res.decision != 2).mean())
    assert frac >= 0.95, f"only {frac:.2%} of instances terminated"


def test_decided_state_frozen():
    """Once decided, est/decided_val never change (decided-mask freezing)."""
    cfg = SimConfig(protocol="bracha", n=10, f=3, instances=100, adversary="byzantine",
                    coin="shared", round_cap=32, seed=31)
    ids = np.arange(cfg.instances, dtype=np.int64)
    adv = AdversaryModel(cfg)
    setup = adv.setup(cfg.seed, ids, xp=np)
    st = state_mod.init_state(cfg, cfg.seed, ids, xp=np)
    frozen = {}
    for r in range(cfg.round_cap):
        prev = st
        st = bracha.round_body(cfg, cfg.seed, ids, r, st, adv, setup, xp=np)
        was = prev["decided"]
        assert (st["decided"] | ~was).all(), "decided bit un-set"
        assert (st["decided_val"][was] == prev["decided_val"][was]).all()
        assert (st["est"][was] == prev["est"][was]).all()
        assert (st["phase"][was] == prev["phase"][was]).all()
