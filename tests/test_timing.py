"""utils/timing.py: the shared measurement discipline (VERDICT r4 #2).

The wall-based legs are exercised end-to-end by bench/product runs on the live
device; these tests pin the host-testable halves — the regression-verdict
rule, trace parsing/freshness, and the host-backend degradation path — so the
driver-facing artifact semantics can't drift silently.
"""

import gzip
import json
import pathlib

from byzantinerandomizedconsensus_tpu.utils import timing


# -- regression_verdict: the machine-readable explain-or-noise rule -----------

def test_verdict_quiet_walls_keys_on_wall_ratio():
    out = timing.regression_verdict([1.0, 1.05, 1.1], prev_wall_rate=100.0,
                                    rate=110.0, device_busy_s=0.5,
                                    prev_device_busy_s=0.6)
    assert out["regression_signal"] == "vs_prev_round"
    assert out["vs_prev_round"] == 1.1
    assert out["vs_prev_round_device"] == 1.2  # still recorded alongside


def test_verdict_noisy_walls_keys_on_device():
    out = timing.regression_verdict([1.0, 1.5], prev_wall_rate=100.0,
                                    rate=70.0, device_busy_s=0.5,
                                    prev_device_busy_s=0.5)
    assert out["walls_spread"] == 0.5
    assert out["regression_signal"] == "vs_prev_round_device"
    assert out["vs_prev_round_device"] == 1.0  # the wall "regression" is noise


def test_verdict_noisy_walls_without_device_says_so():
    out = timing.regression_verdict([1.0, 1.5], prev_wall_rate=100.0, rate=70.0)
    assert out["regression_signal"].startswith("none: walls too noisy")


def test_verdict_zero_device_forms_no_ratio():
    """A sub-50µs device leg legitimately rounds to 0.0 — recorded upstream,
    but no ratio can be formed from it."""
    out = timing.regression_verdict([1.0, 1.01], prev_wall_rate=100.0,
                                    rate=100.0, device_busy_s=0.0,
                                    prev_device_busy_s=0.5)
    assert "vs_prev_round_device" not in out
    assert out["regression_signal"] == "vs_prev_round"


def test_verdict_without_prev_round():
    out = timing.regression_verdict([1.0, 1.02])
    assert "vs_prev_round" not in out and "regression_signal" not in out


# -- trace parsing + freshness ------------------------------------------------

def _write_trace(path: pathlib.Path, busy_us: int) -> None:
    doc = {"traceEvents": [
        {"ph": "M", "name": "process_name", "pid": 7,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "X", "pid": 7, "name": "jit_step", "dur": busy_us},
        {"ph": "X", "pid": 7, "name": "fusion.1", "dur": busy_us // 2},
        # host-pid events must not count toward device busy
        {"ph": "X", "pid": 1, "name": "jit_step", "dur": 10 ** 9},
    ]}
    path.parent.mkdir(parents=True, exist_ok=True)
    with gzip.open(path, "wt") as fh:
        json.dump(doc, fh)


def test_parse_trace_sums_top_level_jit_device_time(tmp_path):
    _write_trace(tmp_path / "a" / "x.trace.json.gz", busy_us=250_000)
    out = timing.parse_trace(tmp_path, before={})
    assert out["device_busy_s"] == 0.25  # jit_step only; host pid excluded
    assert "jit_step" in out["top_device_ops_s"]


def test_parse_trace_rejects_stale_and_accepts_same_mtime_overwrite(tmp_path):
    """Freshness is (mtime_ns, size), not bare mtime (ADVICE r4): an overwrite
    landing in the same mtime quantum still counts as fresh when its size
    changes; an untouched dir is an error, never a silent reparse."""
    import os

    p = tmp_path / "t" / "x.trace.json.gz"
    _write_trace(p, busy_us=100_000)
    before = timing.trace_snapshot(tmp_path)
    assert timing.parse_trace(tmp_path, before=before) == {
        "error": "no new trace.json.gz produced by this run"}
    # overwrite with different content but force the snapshot's mtime back
    mtime = before[p][0]
    _write_trace(p, busy_us=900_000)
    os.utime(p, ns=(mtime, mtime))
    out = timing.parse_trace(tmp_path, before=before)
    assert out.get("device_busy_s") == 0.9, out


def test_device_busy_host_backend_degrades_to_error():
    from byzantinerandomizedconsensus_tpu.backends import get_backend
    from byzantinerandomizedconsensus_tpu.config import preset

    out = timing.device_busy(get_backend("numpy"), preset("config1"))
    assert "error" in out and "host" in out["error"]


def test_parse_trace_flags_jit_naming_drift(tmp_path):
    """Device pids with X events but zero 'jit_'-prefixed names must be
    flagged, not silently reported as 0.0 (VERDICT r5 weak #1): a PJRT/plugin
    op-naming drift would otherwise disable the device-busy regression signal
    — the exact failure the machinery exists to prevent."""
    doc = {"traceEvents": [
        {"ph": "M", "name": "process_name", "pid": 7,
         "args": {"name": "/device:TPU:0"}},
        # renamed programs: the jit_ convention drifted
        {"ph": "X", "pid": 7, "name": "pjrt_exec_step", "dur": 250_000},
        {"ph": "X", "pid": 7, "name": "fusion.1", "dur": 100_000},
    ]}
    p = tmp_path / "d" / "x.trace.json.gz"
    p.parent.mkdir(parents=True)
    with gzip.open(p, "wt") as fh:
        json.dump(doc, fh)
    out = timing.parse_trace(tmp_path, before={})
    assert out["device_busy_s"] == 0.0
    assert "device_busy_suspect" in out
    assert "0 'jit_'-prefixed" in out["device_busy_suspect"]
    # regression_verdict's >0 guard then refuses the device ratio.
    verdict = timing.regression_verdict(
        [1.0, 1.5], prev_wall_rate=100.0, rate=70.0,
        device_busy_s=out["device_busy_s"], prev_device_busy_s=0.5)
    assert "vs_prev_round_device" not in verdict


def test_parse_trace_no_flag_when_jit_names_match(tmp_path):
    _write_trace(tmp_path / "a" / "x.trace.json.gz", busy_us=250_000)
    out = timing.parse_trace(tmp_path, before={})
    assert "device_busy_suspect" not in out


def test_device_busy_drops_dangling_source(monkeypatch):
    """device_busy with no caller trace_dir must not leak a 'source' path
    into an already-deleted TemporaryDirectory (ADVICE r5 #3)."""
    import numpy as np

    from byzantinerandomizedconsensus_tpu.backends import get_backend
    from byzantinerandomizedconsensus_tpu.config import preset

    cfg = preset("config1", instances=2)
    be = get_backend("jax")
    be.run(cfg, np.arange(1, dtype=np.int64))  # compile outside the capture
    out = timing.device_busy(be, cfg)
    assert "source" not in out, out


def test_device_busy_keeps_source_for_persistent_trace_dir(tmp_path):
    import numpy as np

    from byzantinerandomizedconsensus_tpu.backends import get_backend
    from byzantinerandomizedconsensus_tpu.config import preset

    cfg = preset("config1", instances=2)
    be = get_backend("jax")
    be.run(cfg, np.arange(1, dtype=np.int64))
    out = timing.device_busy(be, cfg, trace_dir=tmp_path)
    if "error" not in out:  # capture support varies by platform
        assert "source" in out and str(tmp_path) in out["source"]
