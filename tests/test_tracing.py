"""Retrace/compile-hygiene guards (SURVEY.md §5 — the TPU analog of race/sanitizer
CI): the round kernel must compile exactly once per (config, chunk-shape), and the
profiling hook must wrap device work without disturbing results."""

import numpy as np

from byzantinerandomizedconsensus_tpu import SimConfig
from byzantinerandomizedconsensus_tpu.backends.jax_backend import JaxBackend
from byzantinerandomizedconsensus_tpu.utils import profiling


def test_single_trace_per_config_shape():
    """Exactly one compiled program per (config, chunk-shape) — the compile-
    hygiene invariant (jax_backend.py module docstring). Asserted exactly, so
    a per-call retrace that happens to stabilize cannot slip through."""
    be = JaxBackend()
    cfg = SimConfig(protocol="benor", n=8, f=3, instances=64, adversary="crash",
                    coin="local", round_cap=32, seed=1).validate()
    be.run(cfg, np.arange(16, dtype=np.int64))
    fn = be._fn(cfg)
    assert fn._cache_size() == 1, "first run should compile exactly one program"
    # Same chunk shape, different ids → must NOT retrace.
    be.run(cfg, np.arange(16, 32, dtype=np.int64))
    assert fn._cache_size() == 1, "same-shape rerun retraced"
    # Smaller id set → one new chunk shape, exactly one new program...
    be.run(cfg, np.arange(5, dtype=np.int64))
    assert fn._cache_size() == 2, f"expected 2 traces, got {fn._cache_size()}"
    # ...and repeating it must hit that cache.
    be.run(cfg, np.arange(7, 12, dtype=np.int64))
    assert fn._cache_size() == 2, "second-shape rerun retraced"


def test_one_program_across_seeds():
    """Runs differing only in seed share one compiled program (the PRF key is
    a runtime argument) — and the seed still changes the results."""
    import dataclasses

    be = JaxBackend()
    cfg1 = SimConfig(protocol="bracha", n=10, f=3, instances=32,
                     adversary="byzantine", coin="shared", round_cap=32,
                     seed=1, delivery="urn").validate()
    cfg2 = dataclasses.replace(cfg1, seed=2)
    a = be.run(cfg1)
    fn = be._fn(cfg1)
    assert fn._cache_size() == 1
    b = be.run(cfg2)
    assert be._fn(cfg2) is fn, "seed must not key the compiled-fn cache"
    assert fn._cache_size() == 1, "different seed retraced the program"
    assert not (np.array_equal(a.rounds, b.rounds)
                and np.array_equal(a.decision, b.decision)), \
        "different seeds produced identical trajectories"


def test_profiling_noop_and_annotate():
    with profiling.trace(None):
        x = np.arange(4).sum()
    assert x == 6
    with profiling.annotate("brc/test-span"):
        assert np.arange(3).sum() == 3


def test_trace_falls_back_without_jax(monkeypatch, tmp_path, capsys):
    """trace(out_dir) must honor the module's no-op contract like annotate
    does (round-12 satellite): jax unavailable -> stderr warning, still
    yields, writes nothing — previously it imported jax unconditionally
    whenever a directory was given and broke the promise."""
    import builtins

    real_import = builtins.__import__

    def no_jax(name, *args, **kwargs):
        if name == "jax" or name.startswith("jax."):
            raise ImportError("jax unavailable (simulated)")
        return real_import(name, *args, **kwargs)

    monkeypatch.setattr(builtins, "__import__", no_jax)
    ran = False
    with profiling.trace(tmp_path / "tr"):
        ran = True
    assert ran
    assert "jax unavailable" in capsys.readouterr().err
    assert not (tmp_path / "tr").exists()  # degraded to a no-op, no artifacts


def test_annotate_falls_back_without_jax(monkeypatch):
    """The module docstring promises a no-op fallback when profiling is
    unavailable — annotate must honor it like trace does, instead of dying
    on its jax import (round-8 satellite)."""
    import builtins
    import contextlib

    real_import = builtins.__import__

    def no_jax(name, *args, **kwargs):
        if name == "jax" or name.startswith("jax."):
            raise ImportError("jax unavailable (simulated)")
        return real_import(name, *args, **kwargs)

    monkeypatch.setattr(builtins, "__import__", no_jax)
    cm = profiling.annotate("brc/fallback")
    assert isinstance(cm, contextlib.nullcontext)
    with cm:
        assert 1 + 1 == 2


def test_annotate_labels_traced_ops():
    """Inside jit tracing, annotate's named_scope must reach the HLO — the
    phase labels a --profile capture shows on the device rows."""
    import jax
    import jax.numpy as jnp

    def fn(x):
        with profiling.annotate("brc/phase-label"):
            return x * 2

    # Scope names ride the op_name metadata, visible in the compiled module
    # (the same metadata the profiler uses to label Perfetto rows).
    text = jax.jit(fn).lower(jnp.arange(4)).compile().as_text()
    assert "brc/phase-label" in text


def test_profiling_trace_writes(tmp_path):
    import jax.numpy as jnp

    with profiling.trace(tmp_path / "tr"):
        jnp.arange(8).sum().block_until_ready()
    assert any((tmp_path / "tr").rglob("*")), "no trace artifacts written"
