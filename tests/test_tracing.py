"""Retrace/compile-hygiene guards (SURVEY.md §5 — the TPU analog of race/sanitizer
CI): the round kernel must compile exactly once per (config, chunk-shape), and the
profiling hook must wrap device work without disturbing results."""

import numpy as np

from byzantinerandomizedconsensus_tpu import SimConfig
from byzantinerandomizedconsensus_tpu.backends.jax_backend import JaxBackend
from byzantinerandomizedconsensus_tpu.utils import profiling


def test_single_trace_per_config_shape():
    be = JaxBackend()
    cfg = SimConfig(protocol="benor", n=8, f=3, instances=64, adversary="crash",
                    coin="local", round_cap=32, seed=1).validate()
    be.run(cfg, np.arange(16, dtype=np.int64))
    fn = be._fn(cfg)
    n0 = fn._cache_size()
    assert n0 == 1, "first run should compile exactly one program"
    # same shape, different ids -> no retrace; chunk padding keeps the tail shape
    be.run(cfg, np.arange(16, 32, dtype=np.int64))
    be.run(cfg, np.arange(5, dtype=np.int64))  # padded to cached chunk? (new shape ok)
    assert fn._cache_size() <= 2, f"retracing per call: {fn._cache_size()} traces"


def test_profiling_noop_and_annotate():
    with profiling.trace(None):
        x = np.arange(4).sum()
    assert x == 6


def test_profiling_trace_writes(tmp_path):
    import jax.numpy as jnp

    with profiling.trace(tmp_path / "tr"):
        jnp.arange(8).sum().block_until_ready()
    assert any((tmp_path / "tr").rglob("*")), "no trace artifacts written"
