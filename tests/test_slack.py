"""Slack-vs-rounds tool (tools/slack.py): slack bookkeeping, resumability via
sweep shards, and the figure render — at toy sizes."""

import json

from byzantinerandomizedconsensus_tpu.tools import slack


def test_run_slack_fields_and_plot(tmp_path):
    ns = (13, 14, 15)  # slacks 1, 2, 3 (f = 4, 4, 4)
    out = slack.run_slack(tmp_path / "shards", ns=ns, instances=24,
                          backend="numpy", round_cap=12, progress=lambda m: None)
    for coin in ("local", "shared"):
        assert sorted(out[coin]) == sorted(ns)
        for n in ns:
            s = out[coin][n]
            assert s["slack"] == n - 3 * s["f"] and s["slack"] in (1, 2, 3)
            assert 0.0 <= s["capped_fraction"] <= 1.0
            assert sum(s["round_histogram"]) == 24
    # Shared coin cannot be stalled by the adaptive adversary: nothing capped.
    assert all(out["shared"][n]["capped_fraction"] == 0.0 for n in ns)
    fig = tmp_path / "slack.png"
    slack.plot_slack(out, fig)
    assert fig.stat().st_size > 0


def test_slack_cli_roundtrip(tmp_path, capsys):
    rc = slack.main(["--out", str(tmp_path / "s.json"),
                     "--shards", str(tmp_path / "shards"),
                     "--fig", str(tmp_path / "s.png"),
                     "--ns", "13", "14", "--instances", "12",
                     "--round-cap", "8", "--backend", "numpy"])
    assert rc == 0
    data = json.loads((tmp_path / "s.json").read_text())
    assert set(data) == {"local", "shared"}
    assert (tmp_path / "s.png").exists()
