"""TPU-native randomized Byzantine consensus simulation framework.

Built from scratch to the capability surface of ``sithu/ByzantineRandomizedConsensus``
(see SURVEY.md — the reference mount was empty, so the blueprint derives from
BASELINE.json's north star and the published algorithms: Ben-Or 1983, Bracha 1987,
Cachin-Kursawe-Shoup 2005).

Layering (SURVEY.md §1):

- ``core``     — front-end object model: Replica, Network, Adversary, Simulator
- ``models``   — protocol round logic: Ben-Or, Bracha (RBC count-level), coins
- ``ops``      — kernels: the counter-based PRF, scheduling masks, quorum tallies
- ``backends`` — the SimulatorBackend seam: ``cpu`` oracle loop, ``jax`` vectorized
- ``utils``    — metrics/histograms, sweep checkpointing
"""

from byzantinerandomizedconsensus_tpu.config import SimConfig, PRESETS, preset
from byzantinerandomizedconsensus_tpu.backends.base import get_backend, register_backend
from byzantinerandomizedconsensus_tpu.core.simulator import Simulator

__version__ = "0.1.0"

__all__ = [
    "SimConfig",
    "PRESETS",
    "preset",
    "Simulator",
    "get_backend",
    "register_backend",
    "__version__",
]
