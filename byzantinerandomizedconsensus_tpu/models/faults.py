"""Vectorized fault schedules as data (spec/PROTOCOL.md §9) — the axis
orthogonal to the §6 adversaries.

A fault schedule is (a) a static per-instance setup — the §3.2 fault-prone
set plus PRF-drawn window/epoch parameters — and (b) a pure per-round mask
function mapping the round index to

- ``fsil``: (B, n) bool extra *sender* silences this round (crash-recovery
  windows, omission bursts), OR'd into the adversary's silent set before
  §5.1b validation and §4 delivery; and
- ``fside``: (B, n) uint8 partition side plane (1 = isolated) with cross-side
  messages suppressed at the delivery law in both directions — 0 everywhere
  when the instance is not inside its partition epoch.

Everything is a pure function of (seed, instance, round, replica) —
jit-compatible (``rnd`` may be a traced scalar), and every schedule draws
only from the §3.2 fault-prone set (the same size-f selection the adversary
uses), so the composed run never has more than f misbehaving replicas and the
§5 safety arguments apply verbatim; see spec §9 for the reduction. The scalar
oracle implements the same laws independently in core/faults.py.
"""

from __future__ import annotations

import numpy as np

from byzantinerandomizedconsensus_tpu.ops import prf


class FaultsUnsupported(RuntimeError):
    """Raised by stacks that have no fault-schedule channel (the native ABI,
    the Pallas kernels, the shard_map mesh). Callers degrade honestly —
    mirroring obs/counters.CountersUnsupported — instead of silently running
    the fault-free law."""


def check_faults_supported(cfg, stack: str) -> None:
    """Shared gate: reject ``cfg.faults != "none"`` on a stack without a
    fault channel with one uniform message."""
    if cfg.faults != "none":
        raise FaultsUnsupported(
            f"{stack} has no fault-schedule channel; "
            f"faults={cfg.faults!r} runs on the cpu|numpy|jax stacks")


def fault_prone_mask(cfg, seed, inst_ids, xp=np):
    """(B, n) bool — the §3.2 fault-prone set: the f replicas with smallest
    combined FAULTY_RANK keys. The same selection law as
    models/adversaries.faulty_mask, but *not* gated on ``cfg.adversary``:
    with any active adversary the two sets coincide (same PRF purpose), so
    fault schedules never widen the misbehaving set beyond f."""
    B = inst_ids.shape[0]
    f = cfg.f
    f_static = isinstance(f, (int, np.integer))
    if f_static and f == 0:
        return xp.zeros((B, cfg.n), dtype=bool)
    replica = xp.arange(cfg.n, dtype=xp.uint32)[None, :]
    rank = prf.prf_u32(seed, xp.asarray(inst_ids, dtype=xp.uint32)[:, None],
                       0, 0, replica, 0, prf.FAULTY_RANK, xp=xp,
                       pack=cfg.pack_version)
    key = (rank & xp.uint32(prf.KEY_MASK[cfg.pack_version])) | replica
    n_eff = cfg.n_eff
    padded = not (isinstance(n_eff, (int, np.integer)) and n_eff == cfg.n)
    if padded:
        # Batched lane with n < the padded tier: padding replicas must never
        # displace a real one from the f-smallest selection. Forcing their
        # keys to the uint32 max pushes them past every real key in the sort
        # (there are n > f real keys, so the f-th smallest stays real), and
        # the explicit replica < n_eff guard below removes them from the mask
        # even on an all-ones-key tie.
        key = xp.where(replica < xp.asarray(n_eff, dtype=xp.uint32),
                       key, xp.uint32(0xFFFFFFFF))
    if f_static:
        if xp is np:
            kth = np.partition(key, f - 1, axis=-1)[..., f - 1]
        else:
            kth = xp.sort(key, axis=-1)[..., f - 1]
        mask = key <= kth[..., None]
    else:
        # Traced lane f (backends/batch.py): dynamic index into the sorted
        # keys, clamped so f = 0 stays in range, then masked out entirely.
        idx = xp.maximum(xp.asarray(f, dtype=xp.int32), 1) - 1
        kth = xp.take_along_axis(
            xp.sort(key, axis=-1),
            xp.broadcast_to(idx.astype(xp.int32), (B,))[:, None], axis=-1)
        mask = (key <= kth) & (xp.asarray(f, dtype=xp.int32) > 0)
    if padded:
        mask = mask & (replica < xp.asarray(n_eff, dtype=xp.uint32))
    return mask


def setup_faults(cfg, seed, inst_ids, xp=np):
    """Static per-instance fault-schedule state (spec §9), or None for
    ``faults="none"`` — the fast path that keeps every existing config's
    compiled program and draws untouched.

    ``cfg.faults == "superset"`` is the fused-lane law (backends/batch.py
    run_fused): the recover AND partition setups are both drawn (distinct
    PRF purposes — unused draws never feed the selected masks) and
    :func:`round_masks` selects per lane by the traced ``faults_code``.
    """
    if cfg.faults == "none":
        return None
    inst = xp.asarray(inst_ids, dtype=xp.uint32)[:, None]
    replica = xp.arange(cfg.n, dtype=xp.uint32)[None, :]
    # asarray, not the dtype constructor: crash_window may be a traced lane
    # scalar under the batched runner (backends/batch.py).
    w = xp.asarray(cfg.crash_window, dtype=xp.uint32)
    out = {"fprone": fault_prone_mask(cfg, seed, inst_ids, xp=xp)}
    if cfg.faults in ("recover", "superset"):
        down = prf.prf_u32(seed, inst, 0, 0, replica, 0, prf.FAULT_CRASH,
                           xp=xp, pack=cfg.pack_version) % w
        length = prf.prf_u32(seed, inst, 0, 0, replica, 0, prf.FAULT_HEAL,
                             xp=xp, pack=cfg.pack_version) % (w + w)
        out["down_at"] = down.astype(xp.int32)
        out["up_at"] = (down + length).astype(xp.int32) + xp.int32(1)
    if cfg.faults in ("partition", "superset"):
        side = prf.prf_u32(seed, inst, 0, 0, replica, 0, prf.FAULT_SIDE,
                           xp=xp, pack=cfg.pack_version) & xp.uint32(1)
        # The cut isolates a PRF-drawn *subset of the fault-prone set*: from
        # any main-side receiver the epoch is indistinguishable from crash
        # silence of ≤ f replicas, and the isolated side (≤ f replicas) can
        # never assemble a §5 quorum — the safety reduction of spec §9.
        out["side"] = (side.astype(xp.uint8)
                       * out["fprone"].astype(xp.uint8))
        inst1 = xp.asarray(inst_ids, dtype=xp.uint32)
        start = prf.prf_u32(seed, inst1, 0, 0, 0, 0, prf.FAULT_EPOCH,
                            xp=xp, pack=cfg.pack_version) % w
        length = prf.prf_u32(seed, inst1, 0, 0, 1, 0, prf.FAULT_EPOCH,
                             xp=xp, pack=cfg.pack_version) % (w + w)
        out["part_start"] = start.astype(xp.int32)
        out["part_heal"] = (start + length).astype(xp.int32) + xp.int32(1)
    return out


def round_masks(cfg, seed, inst_ids, rnd, fsetup, xp=np):
    """Per-round fault masks ``(fsil, fside)`` (module docstring shapes);
    ``(None, None)`` for ``faults="none"``. ``rnd`` may be traced.

    ``cfg.faults == "superset"`` (fused lanes, backends/batch.py): all three
    laws' masks are evaluated and the traced ``faults_code`` selects — a
    lane with code 0 gets an all-False ``fsil`` / all-zero ``fside``, which
    composes as a no-op at every consumer (silence OR, side-split class
    counts, cross-cut plane), so it is bit-identical to the ``None`` fast
    path."""
    if fsetup is None:
        return None, None
    fprone = fsetup["fprone"]
    r = xp.asarray(rnd, dtype=xp.int32)
    if cfg.faults == "recover":
        fsil = fprone & (r >= fsetup["down_at"]) & (r < fsetup["up_at"])
        return fsil, None
    if cfg.faults == "partition":
        active = (r >= fsetup["part_start"]) & (r < fsetup["part_heal"])
        fside = xp.where(active[:, None], fsetup["side"], xp.uint8(0))
        return None, fside.astype(xp.uint8)
    # omission: a per-(instance, round) burst gate (rate 1/4) picks rounds;
    # inside a burst each fault-prone replica is silenced by its own PRF bit.
    inst = xp.asarray(inst_ids, dtype=xp.uint32)
    gate = prf.prf_u32(seed, inst, r, 0, 0, 1, prf.FAULT_OMIT, xp=xp,
                       pack=cfg.pack_version)
    burst = (gate & xp.uint32(3)) == 0
    replica = xp.arange(cfg.n, dtype=xp.uint32)[None, :]
    bit = prf.prf_u32(seed, inst[:, None], r, 0, replica, 0, prf.FAULT_OMIT,
                      xp=xp, pack=cfg.pack_version) & xp.uint32(1)
    fsil_om = fprone & burst[:, None] & (bit == 1)
    if cfg.faults == "omission":
        return fsil_om, None
    if cfg.faults != "superset":
        raise ValueError(f"unknown faults {cfg.faults!r}")
    code = xp.asarray(cfg.faults_code)
    fsil_rec = fprone & (r >= fsetup["down_at"]) & (r < fsetup["up_at"])
    active = (r >= fsetup["part_start"]) & (r < fsetup["part_heal"])
    fside_part = xp.where(active[:, None], fsetup["side"], xp.uint8(0))
    false = xp.zeros_like(fprone)
    fsil = xp.where(code == 1, fsil_rec,
                    xp.where(code == 3, fsil_om, false))
    fside = xp.where(code == 2, fside_part.astype(xp.uint8), xp.uint8(0))
    return fsil, fside


def cross_silent(fside, recv_ids=None, xp=np):
    """(B, R, n) bool — the partition cut as a per-(recv, send) silence
    plane for the spec-§4 mask model: suppressed iff the two sides differ.
    ``fside`` is the (B, n) per-round side plane; ``recv_ids`` restricts the
    receiver axis (the replica-sharded path)."""
    n = fside.shape[-1]
    if recv_ids is None:
        recv_ids = xp.arange(n, dtype=xp.uint32)
    if xp is np:
        fside_recv = fside[:, np.asarray(recv_ids, dtype=np.int64)]
    else:
        fside_recv = fside[:, xp.asarray(recv_ids).astype(xp.int32)]
    return fside_recv[:, :, None] != fside[:, None, :]
