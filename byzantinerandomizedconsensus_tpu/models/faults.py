"""Vectorized fault schedules as data (spec/PROTOCOL.md §9) — the axis
orthogonal to the §6 adversaries.

A fault schedule is (a) a static per-instance setup — the §3.2 fault-prone
set plus PRF-drawn window/epoch parameters — and (b) a pure per-round mask
function mapping the round index to

- ``fsil``: (B, n) bool extra *sender* silences this round (crash-recovery
  windows, omission bursts), OR'd into the adversary's silent set before
  §5.1b validation and §4 delivery; and
- ``fside``: (B, n) uint8 partition side plane (1 = isolated) with cross-side
  messages suppressed at the delivery law in both directions — 0 everywhere
  when the instance is not inside its partition epoch.

Everything is a pure function of (seed, instance, round, replica) —
jit-compatible (``rnd`` may be a traced scalar), and every schedule draws
only from the §3.2 fault-prone set (the same size-f selection the adversary
uses), so the composed run never has more than f misbehaving replicas and the
§5 safety arguments apply verbatim; see spec §9 for the reduction. The scalar
oracle implements the same laws independently in core/faults.py.
"""

from __future__ import annotations

import numpy as np

from byzantinerandomizedconsensus_tpu.ops import prf


class FaultsUnsupported(RuntimeError):
    """Raised by stacks that have no fault-schedule channel (the native ABI,
    the Pallas kernels, the shard_map mesh). Callers degrade honestly —
    mirroring obs/counters.CountersUnsupported — instead of silently running
    the fault-free law."""


def check_faults_supported(cfg, stack: str) -> None:
    """Shared gate: reject ``cfg.faults != "none"`` on a stack without a
    fault channel with one uniform message."""
    if cfg.faults != "none":
        raise FaultsUnsupported(
            f"{stack} has no fault-schedule channel; "
            f"faults={cfg.faults!r} runs on the cpu|numpy|jax stacks")


def fault_prone_mask(cfg, seed, inst_ids, xp=np):
    """(B, n) bool — the §3.2 fault-prone set: the f replicas with smallest
    combined FAULTY_RANK keys. The same selection law as
    models/adversaries.faulty_mask, but *not* gated on ``cfg.adversary``:
    with any active adversary the two sets coincide (same PRF purpose), so
    fault schedules never widen the misbehaving set beyond f."""
    B = inst_ids.shape[0]
    if cfg.f == 0:
        return xp.zeros((B, cfg.n), dtype=bool)
    replica = xp.arange(cfg.n, dtype=xp.uint32)[None, :]
    rank = prf.prf_u32(seed, xp.asarray(inst_ids, dtype=xp.uint32)[:, None],
                       0, 0, replica, 0, prf.FAULTY_RANK, xp=xp,
                       pack=cfg.pack_version)
    key = (rank & xp.uint32(prf.KEY_MASK[cfg.pack_version])) | replica
    if xp is np:
        kth = np.partition(key, cfg.f - 1, axis=-1)[..., cfg.f - 1]
    else:
        kth = xp.sort(key, axis=-1)[..., cfg.f - 1]
    return key <= kth[..., None]


def setup_faults(cfg, seed, inst_ids, xp=np):
    """Static per-instance fault-schedule state (spec §9), or None for
    ``faults="none"`` — the fast path that keeps every existing config's
    compiled program and draws untouched."""
    if cfg.faults == "none":
        return None
    inst = xp.asarray(inst_ids, dtype=xp.uint32)[:, None]
    replica = xp.arange(cfg.n, dtype=xp.uint32)[None, :]
    w = xp.uint32(cfg.crash_window)
    out = {"fprone": fault_prone_mask(cfg, seed, inst_ids, xp=xp)}
    if cfg.faults == "recover":
        down = prf.prf_u32(seed, inst, 0, 0, replica, 0, prf.FAULT_CRASH,
                           xp=xp, pack=cfg.pack_version) % w
        length = prf.prf_u32(seed, inst, 0, 0, replica, 0, prf.FAULT_HEAL,
                             xp=xp, pack=cfg.pack_version) % (w + w)
        out["down_at"] = down.astype(xp.int32)
        out["up_at"] = (down + length).astype(xp.int32) + xp.int32(1)
    elif cfg.faults == "partition":
        side = prf.prf_u32(seed, inst, 0, 0, replica, 0, prf.FAULT_SIDE,
                           xp=xp, pack=cfg.pack_version) & xp.uint32(1)
        # The cut isolates a PRF-drawn *subset of the fault-prone set*: from
        # any main-side receiver the epoch is indistinguishable from crash
        # silence of ≤ f replicas, and the isolated side (≤ f replicas) can
        # never assemble a §5 quorum — the safety reduction of spec §9.
        out["side"] = (side.astype(xp.uint8)
                       * out["fprone"].astype(xp.uint8))
        inst1 = xp.asarray(inst_ids, dtype=xp.uint32)
        start = prf.prf_u32(seed, inst1, 0, 0, 0, 0, prf.FAULT_EPOCH,
                            xp=xp, pack=cfg.pack_version) % w
        length = prf.prf_u32(seed, inst1, 0, 0, 1, 0, prf.FAULT_EPOCH,
                             xp=xp, pack=cfg.pack_version) % (w + w)
        out["part_start"] = start.astype(xp.int32)
        out["part_heal"] = (start + length).astype(xp.int32) + xp.int32(1)
    return out


def round_masks(cfg, seed, inst_ids, rnd, fsetup, xp=np):
    """Per-round fault masks ``(fsil, fside)`` (module docstring shapes);
    ``(None, None)`` for ``faults="none"``. ``rnd`` may be traced."""
    if fsetup is None:
        return None, None
    fprone = fsetup["fprone"]
    r = xp.asarray(rnd, dtype=xp.int32)
    if cfg.faults == "recover":
        fsil = fprone & (r >= fsetup["down_at"]) & (r < fsetup["up_at"])
        return fsil, None
    if cfg.faults == "partition":
        active = (r >= fsetup["part_start"]) & (r < fsetup["part_heal"])
        fside = xp.where(active[:, None], fsetup["side"], xp.uint8(0))
        return None, fside.astype(xp.uint8)
    # omission: a per-(instance, round) burst gate (rate 1/4) picks rounds;
    # inside a burst each fault-prone replica is silenced by its own PRF bit.
    inst = xp.asarray(inst_ids, dtype=xp.uint32)
    gate = prf.prf_u32(seed, inst, r, 0, 0, 1, prf.FAULT_OMIT, xp=xp,
                       pack=cfg.pack_version)
    burst = (gate & xp.uint32(3)) == 0
    replica = xp.arange(cfg.n, dtype=xp.uint32)[None, :]
    bit = prf.prf_u32(seed, inst[:, None], r, 0, replica, 0, prf.FAULT_OMIT,
                      xp=xp, pack=cfg.pack_version) & xp.uint32(1)
    fsil = fprone & burst[:, None] & (bit == 1)
    return fsil, None


def cross_silent(fside, recv_ids=None, xp=np):
    """(B, R, n) bool — the partition cut as a per-(recv, send) silence
    plane for the spec-§4 mask model: suppressed iff the two sides differ.
    ``fside`` is the (B, n) per-round side plane; ``recv_ids`` restricts the
    receiver axis (the replica-sharded path)."""
    n = fside.shape[-1]
    if recv_ids is None:
        recv_ids = xp.arange(n, dtype=xp.uint32)
    if xp is np:
        fside_recv = fside[:, np.asarray(recv_ids, dtype=np.int64)]
    else:
        fside_recv = fside[:, xp.asarray(recv_ids).astype(xp.int32)]
    return fside_recv[:, :, None] != fside[:, None, :]
