"""Bracha message validation at count level (spec/PROTOCOL.md §5.1b) — vectorized.

Invalid messages are merged into the silent set *before* the delivery mask is drawn,
so they never consume a wait-quota slot. This is what defeats garbage-flooding
liveness attacks by the adaptive scheduler while keeping Bracha's agreement intact.
All inputs/outputs are integer arrays with leading batch axis B.
"""

from __future__ import annotations

import numpy as np


def live_counts(values, silent, xp=np):
    """Global per-instance counts G_b of live messages with value b. (B,) int32 each."""
    live = ~silent
    g0 = (live & (values == 0)).sum(axis=-1, dtype=xp.int32)
    g1 = (live & (values == 1)).sum(axis=-1, dtype=xp.int32)
    return g0, g1


def validate_step1(cfg, values, g0_0, g0_1, xp=np, nf=None):
    """(B, n) bool — invalid step-1 (x) messages, from step-0 global counts.

    ``nf``, when given, overrides the (n, f) pair the quorum q = n − f is
    derived from — the committee round body passes its (C, f_C) so the
    validity interval matches the committee-scoped G counts (spec §10.3)."""
    n, f = nf if nf is not None else (cfg.n_eff, cfg.f)  # value-of-n law
    q = n - f                         # traced under batching
    ok1 = g0_1 >= (q + 1) // 2        # x=1: can be a ties->1 majority of a q-subset
    ok0 = g0_0 >= q // 2 + 1          # x=0: must be a strict majority
    return ~xp.where(values == 1, ok1[:, None],
                     xp.where(values == 0, ok0[:, None], True))


def validate_step2(cfg, values, g1_0, g1_1, xp=np, nf=None):
    """(B, n) bool — invalid step-2 (z) messages, from valid step-1 global
    counts. ``nf`` overrides (n, f) as in :func:`validate_step1`."""
    n, f = nf if nf is not None else (cfg.n_eff, cfg.f)  # value-of-n law
    q = n - f
    okv1 = g1_1 >= n // 2 + 1
    okv0 = g1_0 >= n // 2 + 1
    # z = bot: some q-subset of valid step-1 messages has no > n/2 majority.
    lo = xp.maximum(xp.maximum(0, q - g1_0), q - n // 2)
    hi = xp.minimum(xp.minimum(g1_1, q), n // 2)
    okbot = lo <= hi
    return ~xp.where(values == 1, okv1[:, None],
                     xp.where(values == 0, okv0[:, None], okbot[:, None]))
