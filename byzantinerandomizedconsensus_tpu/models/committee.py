"""Committee quorum seam for the round bodies (spec/PROTOCOL.md §10).

The protocol layer reads every value-of-n law through ``cfg.n_eff`` and
``cfg.f``. The committee family (ops/committee.py) changes *which* (n, f)
the thresholds see — the static committee size C and fault budget f_C —
without touching the threshold arithmetic itself. :func:`quorum_params` is
that one seam: for every non-committee delivery it returns
``(cfg.n_eff, cfg.f)`` unchanged (the identical objects, so no compiled
program moves), and for the committee family it returns ``(C, f_C)``
(python ints for plain configs, traced int32 scalars under the batched
lane runner).

:class:`CommitteeUnsupported` mirrors models/faults.FaultsUnsupported for
the stacks without a committee channel (the native ABI, the Pallas kernels,
the shard_map mesh): they degrade honestly instead of silently running the
full-mesh law.
"""

from __future__ import annotations

import numpy as np

from byzantinerandomizedconsensus_tpu.ops import committee as _committee
from byzantinerandomizedconsensus_tpu.ops.committee import step_silence  # noqa: F401  (re-export for the round bodies)


class CommitteeUnsupported(RuntimeError):
    """Raised by stacks that have no committee channel (the native ABI, the
    Pallas kernels, the shard_map mesh). Callers degrade honestly —
    mirroring models/faults.FaultsUnsupported — instead of silently running
    the full-mesh delivery law."""


def check_committee_supported(cfg, stack: str) -> None:
    """Shared gate: reject ``cfg.delivery == "committee"`` on a stack
    without a committee channel with one uniform message."""
    if cfg.delivery == "committee":
        raise CommitteeUnsupported(
            f"{stack} has no committee channel; "
            "delivery='committee' runs on the cpu|numpy|jax stacks")


def quorum_params(cfg, xp=np):
    """The (n, f) pair the protocol thresholds evaluate over (spec §10.3).

    Non-committee deliveries return ``(cfg.n_eff, cfg.f)`` — the identical
    objects, so every existing config's round body is untouched. The
    committee family returns the static ``(C, f_C)``; both laws are exact
    compare-sum integer forms (ops/committee.py), so the python-int and
    traced paths agree bit-for-bit.
    """
    n, f = cfg.n_eff, cfg.f
    if cfg.delivery != "committee":
        return n, f
    if isinstance(n, (int, np.integer)) and isinstance(f, (int, np.integer)):
        return (_committee.committee_size(int(n)),
                _committee.committee_fault_budget(int(n), int(f)))
    return (_committee.committee_size(n, xp=xp),
            _committee.committee_fault_budget(n, f, xp=xp))
