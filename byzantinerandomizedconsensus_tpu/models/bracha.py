"""Bracha-style randomized consensus over reliable broadcast — vectorized round body
(spec/PROTOCOL.md §5.2) [Bracha, Information & Computation 75, 1987].

One round = 3 broadcast steps, each conceptually wrapped in Bracha reliable broadcast
(echo > (n+f)/2, ready amplification at f+1, accept at 2f+1). RBC is simulated at the
count level via its delivered guarantees under n > 3f (no equivocation within a step,
all-or-nothing faulty outcomes) — see spec §5.2 for the adversary-completeness
argument (SURVEY.md §7 hard-part 5), validated mechanically against the per-message
echo/ready/accept oracle in spec/rbc_message.py (tests/test_rbc_message.py). Thresholds: > n/2 absolute for decide-proposals,
2f+1 to decide, f+1 to adopt.
"""

from __future__ import annotations

import numpy as np

from byzantinerandomizedconsensus_tpu.models import (coins, committee, faults,
                                                     validation)
from byzantinerandomizedconsensus_tpu.models.delivery import make_counts
from byzantinerandomizedconsensus_tpu.utils import profiling


def round_body(cfg, seed, inst_ids, rnd, state, adv, setup, xp=np,
               recv_ids=None, gather=None, counts_fn=None, obs=None):
    """Execute one Bracha round; returns the new state dict.

    ``recv_ids``/``gather`` support the replica-sharded path (parallel/sharded.py):
    state arrays carry only the local receiver shard; ``gather`` all-gathers a
    (B, R) per-sender value array to full (B, n) width before broadcast. Validation
    and live counts operate on full sender width and need no changes.

    ``counts_fn`` swaps the delivery+tally implementation (the fused Pallas
    kernel, ops/pallas_tally.py) for the default masks+tally path.

    ``obs``, when a dict, collects the opt-in counter side outputs per step
    (models/delivery.py; obs/counters.py) — a pure side channel the round
    math never reads, so the bit-match surface is identical either way. The
    recorded per-step ``silent`` includes the spec §5.1b validation
    silences, matching what the delivery law actually saw.
    """
    # n enters the round body only as a protocol *value* (quorum thresholds),
    # never as a shape — read n_eff so the batched lane runner can trace it.
    # Committee configs (spec §10.3) evaluate the same thresholds over
    # (C, f_C); every other delivery gets (n_eff, f) back unchanged.
    n, f = committee.quorum_params(cfg, xp)
    if gather is None:
        gather = lambda v: v
    est, decided = state["est"], state["decided"]
    # Fault-schedule masks for this round (spec §9). Composition order: fault
    # silences join the silent set *before* the §5.1b validation counts (a
    # fault-silent sender's message does not exist, so it cannot vouch for
    # validity); the partition cut applies only at the delivery law.
    fsil, fside = faults.round_masks(cfg, seed, inst_ids, rnd,
                                     setup.get("faults"), xp=xp)
    counts = make_counts(cfg, seed, inst_ids, rnd, setup, xp,
                         recv_ids=recv_ids, counts_fn=counts_fn, obs=obs,
                         fsil=fsil, fside=fside)

    # Step 0 — broadcast est; majority of delivered (ties -> 1).
    with profiling.annotate("brc/bracha/initial"):
        h0 = gather(est)
        v0, s0, b0 = adv.inject(seed, inst_ids, rnd, 0, h0, setup, xp=xp,
                                recv_ids=recv_ids)
        if fsil is not None:
            s0 = s0 | fsil
        msil0 = committee.step_silence(cfg, seed, inst_ids, rnd, 0, xp=xp)
        if msil0 is not None:
            s0 = s0 | msil0
        g0_0, g0_1 = validation.live_counts(v0, s0, xp=xp)
        c0_0, c0_1 = counts(0, h0, v0, s0, b0)
        m = (c0_1 >= c0_0).astype(xp.uint8)

    # Step 1 — broadcast m; invalid messages silenced pre-delivery (spec §5.1b);
    # decide-proposal needs an absolute > n/2 quorum.
    with profiling.annotate("brc/bracha/echo"):
        h1 = gather(m)
        v1, s1, b1 = adv.inject(seed, inst_ids, rnd, 1, h1, setup, xp=xp,
                                recv_ids=recv_ids)
        if fsil is not None:
            s1 = s1 | fsil
        msil1 = committee.step_silence(cfg, seed, inst_ids, rnd, 1, xp=xp)
        if msil1 is not None:
            s1 = s1 | msil1
        s1 = s1 | validation.validate_step1(cfg, v1, g0_0, g0_1, xp=xp,
                                            nf=(n, f))
        g1_0, g1_1 = validation.live_counts(v1, s1, xp=xp)
        c1_0, c1_1 = counts(1, h1, v1, s1, b1)
        d = xp.where(2 * c1_1 > n, xp.uint8(1),
                     xp.where(2 * c1_0 > n, xp.uint8(0), xp.uint8(2)))

    # Step 2 — broadcast d (bot = 2 excluded from counts); validated against G1.
    with profiling.annotate("brc/bracha/ready"):
        h2 = gather(d)
        v2, s2, b2 = adv.inject(seed, inst_ids, rnd, 2, h2, setup, xp=xp,
                                recv_ids=recv_ids)
        if fsil is not None:
            s2 = s2 | fsil
        msil2 = committee.step_silence(cfg, seed, inst_ids, rnd, 2, xp=xp)
        if msil2 is not None:
            s2 = s2 | msil2
        s2 = s2 | validation.validate_step2(cfg, v2, g1_0, g1_1, xp=xp,
                                            nf=(n, f))
        c2_0, c2_1 = counts(2, h2, v2, s2, b2)
        w = (c2_1 >= c2_0).astype(xp.uint8)
        c = xp.where(w == 1, c2_1, c2_0)

    with profiling.annotate("brc/coin"):
        coin = coins.coin_bits(cfg, seed, inst_ids, rnd, xp=xp, recv_ids=recv_ids)
    decide_now = c >= 2 * f + 1
    adopt = c >= f + 1
    new_est = xp.where(adopt, w, coin).astype(xp.uint8)

    upd = ~decided
    state = dict(state)
    state["est"] = xp.where(upd, new_est, est)
    state["decided_val"] = xp.where(upd & decide_now, w, state["decided_val"])
    state["decided"] = decided | (upd & decide_now)
    state["phase"] = state["phase"] + upd.astype(xp.int32)
    return state
