"""Shared delivery-count dispatch of the two round bodies (benor/bracha).

One closure decides, per step, which delivery+tally implementation runs: a
caller-supplied custom kernel (the fused Pallas paths), the registered
count-level sampler (spec §4b / §4b-v2 / §4c), or the spec-§4 masks+tally
path — and, when the opt-in counter side channel is enabled, records each
step's count outputs into ``obs`` for obs/counters.py. Factored here so the
two protocols cannot drift in either the dispatch rule or the side-channel
shape.
"""

from __future__ import annotations

from byzantinerandomizedconsensus_tpu.ops import delivery_counts_fn, masks, tally
from byzantinerandomizedconsensus_tpu.utils import profiling


def make_counts(cfg, seed, inst_ids, rnd, setup, xp, recv_ids=None,
                counts_fn=None, obs=None):
    """Build the ``counts(t, honest, values, silent, bias) -> (c0, c1)``
    closure a round body calls once per broadcast step.

    ``obs``, when a dict, receives per-step entries
    ``obs[t] = {"c0", "c1", "silent", "stats"}`` — a pure side channel that
    the step math never reads, so enabling it cannot move the bit-match
    surface. ``stats`` carries the sampler-owned cost counters (chain trips
    etc.; see the ``stats`` parameter of the ops/urn*.py samplers). Custom
    kernels (``counts_fn`` given) have no side channel — backends gate
    counter collection to the default paths (obs/counters.CountersUnsupported).
    """

    def counts(t, honest, values, silent, bias):
        if counts_fn is not None:
            return counts_fn(cfg, seed, inst_ids, rnd, t, values, silent,
                             setup["faulty"], honest, recv_ids=recv_ids)
        if cfg.count_level:
            fn = delivery_counts_fn(cfg.delivery)
            with profiling.annotate(f"brc/{cfg.delivery}"):
                if obs is None:
                    return fn(cfg, seed, inst_ids, rnd, t, values, silent,
                              setup["faulty"], honest, recv_ids=recv_ids,
                              xp=xp)
                stats = {}
                c0, c1 = fn(cfg, seed, inst_ids, rnd, t, values, silent,
                            setup["faulty"], honest, recv_ids=recv_ids, xp=xp,
                            stats=stats)
                obs[t] = {"c0": c0, "c1": c1, "silent": silent, "stats": stats}
                return c0, c1
        with profiling.annotate("brc/mask"):
            m = masks.delivery_mask(cfg, seed, inst_ids, rnd, t, silent, bias,
                                    xp=xp, recv_ids=recv_ids)
        with profiling.annotate("brc/tally"):
            c0, c1 = tally.tally01(m, values, xp=xp)
        if obs is not None:
            obs[t] = {"c0": c0, "c1": c1, "silent": silent, "stats": {}}
        return c0, c1

    return counts
