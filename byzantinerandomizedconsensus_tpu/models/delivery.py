"""Shared delivery-count dispatch of the two round bodies (benor/bracha).

One closure decides, per step, which delivery+tally implementation runs: a
caller-supplied custom kernel (the fused Pallas paths), the registered
count-level sampler (spec §4b / §4b-v2 / §4c), or the spec-§4 masks+tally
path — and, when the opt-in counter side channel is enabled, records each
step's count outputs into ``obs`` for obs/counters.py. Factored here so the
two protocols cannot drift in either the dispatch rule or the side-channel
shape. The spec-§9 fault masks thread through here too: ``fside`` reaches
the delivery law (count-level samplers via their ``fside`` argument, the §4
mask model via the cross-cut silence plane), and ``fsil`` rides into the
side channel for the schema-v2 fault-attributed counters.
"""

from __future__ import annotations

from byzantinerandomizedconsensus_tpu.ops import delivery_counts_fn, masks, tally
from byzantinerandomizedconsensus_tpu.utils import profiling


def make_counts(cfg, seed, inst_ids, rnd, setup, xp, recv_ids=None,
                counts_fn=None, obs=None, fsil=None, fside=None):
    """Build the ``counts(t, honest, values, silent, bias) -> (c0, c1)``
    closure a round body calls once per broadcast step.

    ``obs``, when a dict, receives per-step entries
    ``obs[t] = {"c0", "c1", "silent", "stats", "fsil", "fside"}`` — a pure
    side channel that the step math never reads, so enabling it cannot move
    the bit-match surface. ``stats`` carries the sampler-owned cost counters
    (chain trips etc.; see the ``stats`` parameter of the ops/urn*.py
    samplers); ``fsil``/``fside`` are the round's spec-§9 fault masks (None
    on the faults="none" path) for the schema-v2 fault-attributed counters.
    Custom kernels (``counts_fn`` given) have no side channel — backends gate
    counter collection to the default paths (obs/counters.CountersUnsupported)
    and fault schedules to the default kernels (models/faults.FaultsUnsupported).
    """
    if counts_fn is not None and (fsil is not None or fside is not None):
        from byzantinerandomizedconsensus_tpu.models.faults import (
            FaultsUnsupported)

        raise FaultsUnsupported(
            "custom delivery kernels (Pallas / xla_nosort) have no "
            "fault-schedule channel; faults run on the default kernels")
    # The partition cut for the §4 mask model: one (B, R, n) cross-side
    # silence plane per round, shared by all steps.
    xsil = None
    if fside is not None and not cfg.count_level:
        from byzantinerandomizedconsensus_tpu.models.faults import cross_silent

        xsil = cross_silent(fside, recv_ids=recv_ids, xp=xp)

    def counts(t, honest, values, silent, bias):
        if counts_fn is not None:
            return counts_fn(cfg, seed, inst_ids, rnd, t, values, silent,
                             setup["faulty"], honest, recv_ids=recv_ids)
        if cfg.count_level:
            fn = delivery_counts_fn(cfg.delivery)
            with profiling.annotate(f"brc/{cfg.delivery}"):
                if obs is None:
                    return fn(cfg, seed, inst_ids, rnd, t, values, silent,
                              setup["faulty"], honest, recv_ids=recv_ids,
                              xp=xp, fside=fside)
                stats = {}
                c0, c1 = fn(cfg, seed, inst_ids, rnd, t, values, silent,
                            setup["faulty"], honest, recv_ids=recv_ids, xp=xp,
                            stats=stats, fside=fside)
                obs[t] = {"c0": c0, "c1": c1, "silent": silent, "stats": stats,
                          "fsil": fsil, "fside": fside}
                return c0, c1
        with profiling.annotate("brc/mask"):
            m = masks.delivery_mask(cfg, seed, inst_ids, rnd, t, silent, bias,
                                    xp=xp, recv_ids=recv_ids, xsilent=xsil)
        with profiling.annotate("brc/tally"):
            c0, c1 = tally.tally01(m, values, xp=xp)
        if obs is not None:
            obs[t] = {"c0": c0, "c1": c1, "silent": silent, "stats": {},
                      "fsil": fsil, "fside": fside}
        return c0, c1

    return counts
