"""Vectorized protocol round logic — array-level, generic over numpy / jax.numpy.

These functions implement spec/PROTOCOL.md §5-§6 over struct-of-arrays state with a
leading instance-batch axis. They are consumed by the ``numpy`` and ``jax`` backends;
the ``cpu`` oracle backend is an independent per-replica implementation of the same
spec (``core/replica.py``) used to cross-check this one.
"""
