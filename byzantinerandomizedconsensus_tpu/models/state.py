"""State initialisation and result extraction shared by the vectorized backends."""

from __future__ import annotations

import numpy as np

from byzantinerandomizedconsensus_tpu.ops import prf


def init_est(cfg, seed, inst_ids, xp=np, recv_ids=None):
    """(B, R) uint8 initial estimates (spec §3.1); R = len(recv_ids) or n.

    ``cfg.init == "superset"`` is the fused-lane law (backends/batch.py
    run_fused): all four init laws are evaluated and the lane's
    ``init_code`` (traced; 0 = random, 1 = all0, 2 = all1, 3 = split)
    selects — bit-identical per lane to the static law.
    """
    B = inst_ids.shape[0]
    if recv_ids is None:
        recv_ids = xp.arange(cfg.n, dtype=xp.uint32)
    replica = xp.asarray(recv_ids, dtype=xp.uint32)[None, :]
    R = replica.shape[1]
    if cfg.init == "all0":
        return xp.zeros((B, R), dtype=xp.uint8)
    if cfg.init == "all1":
        return xp.ones((B, R), dtype=xp.uint8)
    if cfg.init == "split":
        return xp.broadcast_to((replica & xp.uint32(1)).astype(xp.uint8), (B, R))
    inst = xp.asarray(inst_ids, dtype=xp.uint32)[:, None]
    rand = prf.prf_bit(seed, inst, 0, 0, replica, 0, prf.INIT_EST, xp=xp,
                       pack=cfg.pack_version).astype(xp.uint8)
    if cfg.init == "random":
        return rand
    if cfg.init != "superset":
        raise ValueError(f"unknown init {cfg.init!r}")
    code = xp.asarray(cfg.init_code)
    split = xp.broadcast_to((replica & xp.uint32(1)).astype(xp.uint8), (B, R))
    return xp.where(code == 0, rand,
                    xp.where(code == 1, xp.uint8(0),
                             xp.where(code == 2, xp.uint8(1), split)))


def init_state(cfg, seed, inst_ids, xp=np, recv_ids=None):
    B = inst_ids.shape[0]
    est = init_est(cfg, seed, inst_ids, xp=xp, recv_ids=recv_ids)
    R = est.shape[1]
    return {
        "est": est,
        "decided": xp.zeros((B, R), dtype=bool),
        "decided_val": xp.zeros((B, R), dtype=xp.uint8),
        "phase": xp.zeros((B, R), dtype=xp.int32),
    }


def all_correct_decided(state, faulty, xp=np):
    """(B,) bool — instance termination predicate (spec §1)."""
    return xp.all(state["decided"] | faulty, axis=-1)


def extract_decision(state, faulty, done, xp=np):
    """(B,) uint8 — decided value of the lowest-indexed correct replica, 2 if undone."""
    first_correct = xp.argmax(~faulty, axis=-1)
    val = xp.take_along_axis(state["decided_val"], first_correct[:, None], axis=-1)[:, 0]
    return xp.where(done, val, xp.uint8(2)).astype(xp.uint8)
