"""Vectorized adversaries-as-data (spec/PROTOCOL.md §6; SURVEY.md C3, §7 step 5).

An adversary is (a) a static per-instance setup — faulty set, crash rounds — and (b) a
pure per-step injection function mapping honest outgoing values to
``(values, silent, bias)``:

- ``values``: (B, n) common per-sender wire values, or (B, n, n) per-(recv, send) for
  the plain-Ben-Or Byzantine equivocation path (spec §6.3);
- ``silent``: (B, n) bool sender silence flags;
- ``bias``:   (B, 1, n) or (B, n, n) scheduling-bias bits (spec §4 bit 30).

Everything is a pure function of (seed, instance, round, step, current honest votes) —
jit-compatible, and the adaptive adversary provably sees only round-t state, never
future coins (SURVEY.md §4.5).
"""

from __future__ import annotations

import numpy as np

from byzantinerandomizedconsensus_tpu.ops import prf


def faulty_mask(cfg, seed, inst_ids, xp=np):
    """(B, n) bool — the f replicas with smallest combined FAULTY_RANK keys
    (spec §3.2). One shared selection law with the §9 fault-prone set
    (models/faults.fault_prone_mask) — the safety reduction *requires* the
    two sets to coincide under an active adversary, so there is exactly one
    implementation, gated here on the benign adversary. Under the fused-lane
    "superset" adversary the gate is the lane's traced ``adv_code`` (0 =
    none) instead of a Python branch."""
    from byzantinerandomizedconsensus_tpu.models.faults import fault_prone_mask

    if cfg.adversary == "none":
        return xp.zeros((inst_ids.shape[0], cfg.n), dtype=bool)
    mask = fault_prone_mask(cfg, seed, inst_ids, xp=xp)
    if cfg.adversary == "superset":
        mask = mask & (xp.asarray(cfg.adv_code) != 0)
    return mask


def observed_minority(honest_values, faulty, xp=np):
    """(B,) uint8 — the spec §6.4 observation: minority value among live honest
    non-⊥ votes this step (ties → 1). Shared by the adaptive/adaptive_min value
    attack, the §6.4b bias rule, and the urn/Pallas stratum derivations."""
    honest_live = ~faulty
    nonbot = honest_values != 2
    h1 = (honest_live & nonbot & (honest_values == 1)).sum(-1, dtype=xp.int32)
    h0 = (honest_live & nonbot & (honest_values == 0)).sum(-1, dtype=xp.int32)
    return xp.where(h1 <= h0, xp.uint8(1), xp.uint8(0))


def crash_rounds(cfg, seed, inst_ids, xp=np):
    """(B, n) int32 crash round per replica (only meaningful where faulty; spec §3.3)."""
    replica = xp.arange(cfg.n, dtype=xp.uint32)[None, :]
    c = prf.prf_u32(seed, xp.asarray(inst_ids, dtype=xp.uint32)[:, None],
                    0, 0, replica, 0, prf.CRASH_ROUND, xp=xp,
                    pack=cfg.pack_version)
    # asarray (not the dtype constructor): crash_window may be a traced lane
    # scalar under the batched runner; values are identical either way.
    return (c % xp.asarray(cfg.crash_window, dtype=xp.uint32)).astype(xp.int32)


class AdversaryModel:
    """Static dispatch on cfg.adversary; instances hold only static config."""

    def __init__(self, cfg):
        self.cfg = cfg

    def setup(self, seed, inst_ids, xp=np):
        cfg = self.cfg
        fm = faulty_mask(cfg, seed, inst_ids, xp=xp)
        if cfg.adversary in ("crash", "superset"):
            cr = crash_rounds(cfg, seed, inst_ids, xp=xp)
        else:
            cr = xp.zeros(fm.shape, dtype=xp.int32)
        # The orthogonal fault-schedule axis (spec §9) rides the same setup
        # dict so every vectorized backend plumbs it for free; None when
        # cfg.faults == "none" (models/faults.py — the frozen fast path).
        from byzantinerandomizedconsensus_tpu.models import faults as _faults

        return {"faulty": fm, "crash_round": cr,
                "faults": _faults.setup_faults(cfg, seed, inst_ids, xp=xp)}

    def inject(self, seed, inst_ids, rnd, t, honest_values, setup, xp=np, recv_ids=None):
        """Apply the adversary to one step's honest outgoing values (spec §6).

        ``honest_values``: (B, n) uint8 in {0,1,2} — what each replica's honest state
        machine sends this step (faulty replicas run the honest machine too, §6.3).
        Returns (values, silent, bias) as described in the module docstring; the
        receiver axis of per-receiver outputs (equivocation values, adaptive bias) is
        restricted to ``recv_ids`` (global indices) when given — the replica-shard
        path of parallel/sharded.py. Sender-axis outputs are always full width.
        """
        cfg = self.cfg
        B, n = honest_values.shape
        if recv_ids is None:
            recv_ids = xp.arange(n, dtype=xp.uint32)
        recv_ids = xp.asarray(recv_ids, dtype=xp.uint32)
        faulty = setup["faulty"]
        no_bias = xp.zeros((B, 1, n), dtype=xp.uint32)
        zero_silent = xp.zeros((B, n), dtype=bool)

        if cfg.adversary == "none":
            return honest_values, zero_silent, no_bias

        if cfg.adversary == "crash":
            silent = faulty & (xp.asarray(rnd, dtype=xp.int32) >= setup["crash_round"])
            return honest_values, silent, no_bias

        inst = xp.asarray(inst_ids, dtype=xp.uint32)[:, None]
        send = xp.arange(n, dtype=xp.uint32)[None, :]

        if cfg.adversary == "byzantine":
            if cfg.protocol == "bracha":
                # RBC count-level outcome, common to all receivers (spec §6.3).
                # Sender-addressed draw: prf_sender swaps the wide field
                # under the §2 v3 packing law (bit-identical at pack ≤ 2).
                b = prf.prf_sender(seed, inst, rnd, t, 0, send, prf.BYZ_VALUE,
                                   xp=xp, pack=cfg.pack_version) & xp.uint32(3)
                silent = faulty & (b == 0)
                v = xp.where(b == 1, xp.uint8(0),
                             xp.where(b == 2, xp.uint8(1), honest_values.astype(xp.uint8)))
                values = xp.where(faulty, v, honest_values).astype(xp.uint8)
                return values, silent, no_bias
            if cfg.count_level:
                # §4b: urn counts recompute the two-faced class values from
                # (honest, faulty) themselves — never build the O(B,n,n) matrix.
                return honest_values, zero_silent, no_bias
            # Plain Ben-Or pairing: full per-receiver equivocation matrix (spec §6.3).
            R = recv_ids.shape[0]
            recv3 = recv_ids[None, :, None]
            send3 = xp.arange(n, dtype=xp.uint32)[None, None, :]
            inst3 = xp.asarray(inst_ids, dtype=xp.uint32)[:, None, None]
            e = prf.prf_u32(seed, inst3, rnd, t, recv3, send3, prf.BYZ_VALUE,
                            xp=xp, pack=cfg.pack_version)
            vmat = (e % xp.uint32(3)).astype(xp.uint8)  # {0,1,2=silent-to-this-recv}
            values = xp.where(faulty[:, None, :], vmat,
                              xp.broadcast_to(honest_values[:, None, :], (B, R, n)).astype(xp.uint8))
            return values, zero_silent, no_bias

        if cfg.adversary == "superset":
            # Fused lanes (backends/batch.py run_fused): every adversary's
            # outputs are computed on the shared setup and the traced lane
            # ``adv_code`` selects (0 none, 1 crash, 2 byzantine, 3 adaptive,
            # 4 adaptive_min). ``faulty`` is already code-gated (all-False on
            # none-lanes), so each variant's output is bit-identical to its
            # static-law value wherever it is selected.
            code = xp.asarray(cfg.adv_code)
            r32 = xp.asarray(rnd, dtype=xp.int32)
            crash_sil = faulty & (r32 >= setup["crash_round"])
            minority = observed_minority(honest_values, faulty, xp=xp)
            adapt_values = xp.where(faulty, minority[:, None],
                                    honest_values).astype(xp.uint8)
            if cfg.protocol == "bracha" or cfg.count_level:
                # Values stay (B, n). Byzantine: the RBC count-level outcome
                # for bracha; for count-level Ben-Or the urns recompute the
                # two-faced class values themselves (lane_setup selects).
                if cfg.protocol == "bracha":
                    b = prf.prf_sender(seed, inst, rnd, t, 0, send,
                                       prf.BYZ_VALUE, xp=xp,
                                       pack=cfg.pack_version) & xp.uint32(3)
                    byz_sil = faulty & (b == 0)
                    v = xp.where(b == 1, xp.uint8(0),
                                 xp.where(b == 2, xp.uint8(1),
                                          honest_values.astype(xp.uint8)))
                    byz_values = xp.where(faulty, v,
                                          honest_values).astype(xp.uint8)
                else:
                    byz_sil = zero_silent
                    byz_values = honest_values
                values = xp.where(code == 2, byz_values,
                                  xp.where(code >= 3, adapt_values,
                                           honest_values)).astype(xp.uint8)
                silent = xp.where(code == 1, crash_sil,
                                  xp.where(code == 2, byz_sil, zero_silent))
                if cfg.count_level:
                    return values, silent, no_bias
                # bracha + keys: only the adaptive family biases scheduling.
                vv = values[:, None, :]
                pref = (recv_ids.astype(xp.int32)
                        >= (cfg.n_eff + 1) // 2)[None, :, None].astype(xp.uint8)
                bias_ad = ((vv == 2) | (vv != pref)).astype(xp.uint32)
                bias_min = ((vv == 2)
                            | (vv != minority[:, None, None])).astype(xp.uint32)
                bias = xp.where(code == 3,
                                bias_ad,
                                xp.where(code == 4, bias_min,
                                         xp.zeros((B, 1, n),
                                                  dtype=xp.uint32)))
                return values, silent, bias
            # Ben-Or + keys: the Byzantine lane needs the per-receiver
            # equivocation matrix, so values are (B, R, n) for every lane
            # (non-Byzantine lanes broadcast — same per-sender value at every
            # receiver, hence identical tallies).
            R = recv_ids.shape[0]
            recv3 = recv_ids[None, :, None]
            send3 = xp.arange(n, dtype=xp.uint32)[None, None, :]
            inst3 = xp.asarray(inst_ids, dtype=xp.uint32)[:, None, None]
            e = prf.prf_u32(seed, inst3, rnd, t, recv3, send3, prf.BYZ_VALUE,
                            xp=xp, pack=cfg.pack_version)
            vmat = (e % xp.uint32(3)).astype(xp.uint8)
            byz3 = xp.where(faulty[:, None, :], vmat,
                            xp.broadcast_to(honest_values[:, None, :],
                                            (B, R, n)).astype(xp.uint8))
            flat = xp.where(code >= 3, adapt_values,
                            honest_values).astype(xp.uint8)
            values = xp.where(code == 2, byz3,
                              xp.broadcast_to(flat[:, None, :],
                                              (B, R, n)).astype(xp.uint8))
            silent = xp.where(code == 1, crash_sil, zero_silent)
            vv = values
            pref = (recv_ids.astype(xp.int32)
                    >= (cfg.n_eff + 1) // 2)[None, :, None].astype(xp.uint8)
            bias_ad = ((vv == 2) | (vv != pref)).astype(xp.uint32)
            bias_min = ((vv == 2)
                        | (vv != minority[:, None, None])).astype(xp.uint32)
            bias = xp.where(code == 3, bias_ad,
                            xp.where(code == 4, bias_min,
                                     xp.zeros((B, 1, n), dtype=xp.uint32)))
            return values, silent, bias

        if cfg.adversary in ("adaptive", "adaptive_min"):
            # spec §6.4/§6.4b — observe honest votes, push the minority value,
            # bias delivery (by receiver class, or globally minority-first).
            minority = observed_minority(honest_values, faulty, xp=xp)
            values = xp.where(faulty, minority[:, None], honest_values).astype(xp.uint8)
            if cfg.count_level:
                # §4b: scheduling strata are derived inside the urn from the
                # wire values — the (B, R, n) bias matrix is never needed.
                return values, zero_silent, no_bias
            vv = values[:, None, :]
            if cfg.adversary == "adaptive_min":
                # §6.4b: receiver-independent — minority-value senders first.
                bias = ((vv == 2) | (vv != minority[:, None, None])).astype(xp.uint32)
                return values, zero_silent, bias
            # §6.4: receiver v prefers value 0 iff v < n/2; senders whose wire value
            # matches the receiver's preference get bias 0 (delivered first).
            # n_eff, not the (possibly padded) array width: the receiver-class
            # split is a protocol value of n (traced under batching).
            pref = (recv_ids.astype(xp.int32) >= (cfg.n_eff + 1) // 2)[None, :, None].astype(xp.uint8)
            bias = ((vv == 2) | (vv != pref)).astype(xp.uint32)
            return values, zero_silent, bias

        raise ValueError(f"unknown adversary {cfg.adversary}")
