"""Ben-Or randomized binary consensus — vectorized round body (spec/PROTOCOL.md §5.1).

One round = 2 broadcast steps (report, propose) + coin. State is struct-of-arrays with
leading batch axis B: ``est`` (B,n) u8, ``decided`` (B,n) bool, ``decided_val`` (B,n)
u8, ``phase`` (B,n) i32. All thresholds are absolute in n and f (strict ``2*c > n``),
all arithmetic integer [Ben-Or, PODC 1983].
"""

from __future__ import annotations

import numpy as np

from byzantinerandomizedconsensus_tpu.models import coins, committee, faults
from byzantinerandomizedconsensus_tpu.models.delivery import make_counts
from byzantinerandomizedconsensus_tpu.utils import profiling


def round_body(cfg, seed, inst_ids, rnd, state, adv, setup, xp=np,
               recv_ids=None, gather=None, counts_fn=None, obs=None):
    """Execute one Ben-Or round; returns the new state dict.

    ``recv_ids``/``gather`` support the replica-sharded path (parallel/sharded.py):
    state arrays carry only the local receiver shard; ``gather`` all-gathers a
    (B, R) per-sender value array to full (B, n) width before broadcast.

    ``counts_fn`` swaps the delivery+tally implementation (the fused Pallas
    kernel, ops/pallas_tally.py) for the default masks+tally path; it receives
    the pre-inject honest vector so equivocation matrices can be recomputed
    in-kernel (the unused inject output is dead-code-eliminated under jit).

    ``obs``, when a dict, collects the opt-in counter side outputs per step
    (models/delivery.py; obs/counters.py) — a pure side channel the round
    math never reads, so the bit-match surface is identical either way.
    """
    # n enters the round body only as a protocol *value* (quorum thresholds),
    # never as a shape — read n_eff so the batched lane runner can trace it.
    # Committee configs (spec §10.3) evaluate the same thresholds over
    # (C, f_C); every other delivery gets (n_eff, f) back unchanged.
    n, f = committee.quorum_params(cfg, xp)
    if gather is None:
        gather = lambda v: v
    est, decided = state["est"], state["decided"]
    # Fault-schedule masks for this round (spec §9): extra sender silences
    # OR'd in after each inject, and the partition side plane threaded to the
    # delivery law. Both None on the faults="none" fast path.
    fsil, fside = faults.round_masks(cfg, seed, inst_ids, rnd,
                                     setup.get("faults"), xp=xp)
    counts = make_counts(cfg, seed, inst_ids, rnd, setup, xp,
                         recv_ids=recv_ids, counts_fn=counts_fn, obs=obs,
                         fsil=fsil, fside=fside)

    # Protocol A (benign) vs Protocol B (lying) thresholds — spec §5.1.
    # ``lying_adversary`` is a traced per-lane bool under the fused batched
    # runner (adversary kind as lane data): the arithmetic forms n + f·lying
    # / 1 + f·lying equal the Python branches exactly for both values.
    lying = cfg.lying_adversary
    lying_static = isinstance(lying, (bool, np.bool_))
    if lying_static:
        quorum_rhs = n + f if lying else n
        adopt_min = f + 1 if lying else 1
    else:
        lyi = xp.asarray(lying, dtype=xp.int32)
        quorum_rhs = n + f * lyi
        adopt_min = 1 + f * lyi

    # Step 0 — report: broadcast est.
    with profiling.annotate("brc/benor/report"):
        h0 = gather(est)
        v0, silent0, bias0 = adv.inject(seed, inst_ids, rnd, 0, h0, setup,
                                        xp=xp, recv_ids=recv_ids)
        if fsil is not None:
            silent0 = silent0 | fsil
        msil0 = committee.step_silence(cfg, seed, inst_ids, rnd, 0, xp=xp)
        if msil0 is not None:
            silent0 = silent0 | msil0
        r0, r1 = counts(0, h0, v0, silent0, bias0)
        prop = xp.where(2 * r1 > quorum_rhs, xp.uint8(1),
                        xp.where(2 * r0 > quorum_rhs, xp.uint8(0), xp.uint8(2)))

    # Step 1 — propose: broadcast prop (bot = 2 excluded from counts).
    with profiling.annotate("brc/benor/propose"):
        h1 = gather(prop)
        v1, silent1, bias1 = adv.inject(seed, inst_ids, rnd, 1, h1, setup,
                                        xp=xp, recv_ids=recv_ids)
        if fsil is not None:
            silent1 = silent1 | fsil
        msil1 = committee.step_silence(cfg, seed, inst_ids, rnd, 1, xp=xp)
        if msil1 is not None:
            silent1 = silent1 | msil1
        p0, p1 = counts(1, h1, v1, silent1, bias1)
        w = (p1 >= p0).astype(xp.uint8)
        c = xp.where(w == 1, p1, p0)

    with profiling.annotate("brc/coin"):
        coin = coins.coin_bits(cfg, seed, inst_ids, rnd, xp=xp, recv_ids=recv_ids)
    new_est = xp.where(c >= adopt_min, w, coin).astype(xp.uint8)
    if lying_static:
        decide_now = (2 * c > n + f) if lying else (c >= f + 1)
    else:
        decide_now = xp.where(lying, 2 * c > n + f, c >= f + 1)

    # Updates apply to every not-yet-decided replica (spec §6.3 eligibility rule).
    upd = ~decided
    state = dict(state)
    state["est"] = xp.where(upd, new_est, est)
    state["decided_val"] = xp.where(upd & decide_now, w, state["decided_val"])
    state["decided"] = decided | (upd & decide_now)
    state["phase"] = state["phase"] + upd.astype(xp.int32)
    return state
