"""Common-coin implementations (spec/PROTOCOL.md §5.3; SURVEY.md C6).

``local``  — independent fair bit per (instance, round, replica)  [Ben-Or 1983].
``shared`` — one common bit per (instance, round): the threshold-signature *stub* of
BASELINE.json:10 (Cachin-Kursawe-Shoup shared coin with the share combination replaced
by a keyed PRF — the north star explicitly stubs the cryptography).
"""

from __future__ import annotations

import numpy as np

from byzantinerandomizedconsensus_tpu.ops import prf


def coin_bits(cfg, seed, inst_ids, rnd, xp=np, recv_ids=None):
    """Coin bits, shape (B, R) uint8 — R = len(recv_ids) (a replica shard) or n."""
    inst = xp.asarray(inst_ids, dtype=xp.uint32)[:, None]
    if recv_ids is None:
        recv_ids = xp.arange(cfg.n, dtype=xp.uint32)
    replica = xp.asarray(recv_ids, dtype=xp.uint32)[None, :]
    if cfg.coin == "shared":
        bit = prf.prf_bit(seed, inst, rnd, prf.COIN_STEP, 0, 0, prf.SHARED_COIN,
                          xp=xp, pack=cfg.pack_version)
        return xp.broadcast_to(bit.astype(xp.uint8), (inst.shape[0], replica.shape[1]))
    bit = prf.prf_bit(seed, inst, rnd, prf.COIN_STEP, replica, 0, prf.LOCAL_COIN,
                      xp=xp, pack=cfg.pack_version)
    return bit.astype(xp.uint8)
