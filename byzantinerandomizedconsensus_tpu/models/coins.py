"""Common-coin implementations (spec/PROTOCOL.md §5.3; SURVEY.md C6).

``local``  — independent fair bit per (instance, round, replica)  [Ben-Or 1983].
``shared`` — one common bit per (instance, round): the threshold-signature *stub* of
BASELINE.json:10 (Cachin-Kursawe-Shoup shared coin with the share combination replaced
by a keyed PRF — the north star explicitly stubs the cryptography).
"""

from __future__ import annotations

import numpy as np

from byzantinerandomizedconsensus_tpu.ops import prf


def coin_bits(cfg, seed, inst_ids, rnd, xp=np, recv_ids=None):
    """Coin bits, shape (B, R) uint8 — R = len(recv_ids) (a replica shard) or n.

    ``cfg.coin == "superset"`` is the fused-lane law (backends/batch.py
    run_fused): both coin laws are drawn and the lane's ``coin_code`` (a
    traced scalar; 0 = local, 1 = shared) selects — the selected plane is
    bit-identical to the corresponding static law by PRF coordinates.
    """
    inst = xp.asarray(inst_ids, dtype=xp.uint32)[:, None]
    if recv_ids is None:
        recv_ids = xp.arange(cfg.n, dtype=xp.uint32)
    replica = xp.asarray(recv_ids, dtype=xp.uint32)[None, :]
    if cfg.coin == "local":
        bit = prf.prf_bit(seed, inst, rnd, prf.COIN_STEP, replica, 0,
                          prf.LOCAL_COIN, xp=xp, pack=cfg.pack_version)
        return bit.astype(xp.uint8)
    shared = xp.broadcast_to(
        prf.prf_bit(seed, inst, rnd, prf.COIN_STEP, 0, 0, prf.SHARED_COIN,
                    xp=xp, pack=cfg.pack_version).astype(xp.uint8),
        (inst.shape[0], replica.shape[1]))
    if cfg.coin == "shared":
        return shared
    if cfg.coin != "superset":
        raise ValueError(f"unknown coin {cfg.coin!r}")
    local = prf.prf_bit(seed, inst, rnd, prf.COIN_STEP, replica, 0,
                        prf.LOCAL_COIN, xp=xp,
                        pack=cfg.pack_version).astype(xp.uint8)
    return xp.where(xp.asarray(cfg.coin_code) == 1, shared, local)
