"""Replicated-log sessions: the chained-slot seed law (spec §11).

A session is one stream of ``L`` chained decision slots over a single base
config: slot 0 runs the config as written, slot ``k+1`` runs the *same*
config with the seed derived from slot ``k``'s seed and decision vector
(:func:`~byzantinerandomizedconsensus_tpu.ops.prf.session_chain_seed`).
Every slot is an ordinary run — the chained-init law is seed derivation,
not a new init mode — so the whole log is a pure function of
``(seed, config, L)`` and bit-identical replay from the base seed is the
correctness criterion. This module is the offline form of that law; the
serving stack (backends/compaction.py lane re-seeding, serve/server.py
session envelopes) must reproduce it bit-for-bit, which
tests/test_session.py pins on the numpy AND jax backends.
"""

from __future__ import annotations

import dataclasses

from byzantinerandomizedconsensus_tpu.config import SimConfig
from byzantinerandomizedconsensus_tpu.ops import prf

#: Admitted slot-count ceiling (serve/admission.py validates against it):
#: bounds a single session's lane-round weight so the r18 deficit-weighted
#: fairness always sees a finite, known claim per envelope.
MAX_SESSION_SLOTS = 256


def next_slot_config(cfg: SimConfig, slot: int, decision) -> SimConfig:
    """Slot ``slot + 1``'s config: the base config with the spec-§11
    derived seed. ``decision`` is slot ``slot``'s per-instance decision
    vector in instance order (values 0/1/2)."""
    seed = prf.session_chain_seed(cfg.seed, slot, decision,
                                  pack=cfg.pack_version)
    return dataclasses.replace(cfg, seed=seed).validate()


def session_slot_configs(cfg: SimConfig, results) -> list:
    """The slot configs a finished session actually ran, re-derived from
    the base config and the per-slot decision vectors (``results`` is the
    slot-ordered list of decision vectors). Slot 0 is ``cfg`` itself."""
    out = [cfg]
    for k, dec in enumerate(results[:-1] if results else []):
        out.append(next_slot_config(out[-1], k, dec))
    return out


def run_session(backend, cfg: SimConfig, slots: int) -> list:
    """Run an ``slots``-slot session offline: the reference implementation
    of the spec-§11 chain (slot k+1's seed from slot k's decision), one
    ``backend.run`` per slot. Returns the slot-ordered SimResult list.

    This is the replay law: any serving-path session must be bit-identical
    to this function at the same (backend-independent) base seed.
    """
    if slots < 1:
        raise ValueError(f"slots={slots} out of range (>= 1)")
    out = []
    slot_cfg = cfg
    for k in range(slots):
        res = backend.run(slot_cfg)
        out.append(res)
        if k + 1 < slots:
            slot_cfg = next_slot_config(slot_cfg, k, res.decision)
    return out


def replay_matches(backend, cfg: SimConfig, served_slots) -> bool:
    """Bit-identity check of a served session against the offline replay:
    ``served_slots`` is the slot-ordered list of ``(rounds, decision)``
    int-list pairs a server streamed back. True iff every slot matches the
    :func:`run_session` replay from the base seed exactly."""
    ref = run_session(backend, cfg, len(served_slots))
    for (rounds, decision), r in zip(served_slots, ref):
        if rounds != [int(x) for x in r.rounds]:
            return False
        if decision != [int(x) for x in r.decision]:
            return False
    return True
