"""Safety invariants over the full per-replica state (spec §1; §9 checker).

The result surface (``SimResult.decision``) collapses an instance to the
lowest-indexed correct replica's value, which *assumes* Agreement — the
always-on oracle assertion (backends/cpu.py) covers the oracle leg only. The
fault-schedule axis (spec §9) makes whole-state checking a first-class
instrument: every chaos-soak config runs through here, and a violation is a
hard artifact-recorded failure, never a silent statistic.

Checked per instance, over the state the product path actually computed
(``NumpyBackend.run_with_state``):

- **Agreement** — all correct decided replicas share one decided value;
- **Validity** — unanimity forces the decision, over the basis the fault
  model actually guarantees: under a **lying** adversary (byzantine /
  adaptive / adaptive_min) the basis is the *correct* replicas (faulty
  inputs are adversarial and carry no weight); under the benign/crash
  models the basis is **all** replicas — crash-faulty replicas run the
  honest machine on honest inputs, and Ben-Or Protocol A's validity is
  exactly the all-processes-unanimous statement [Ben-Or 1983] (a
  correct-only basis is provably too strong there: with n=5, f=2, three
  correct replicas at v and two honest-until-crash replicas at w, the
  delivery quota can hide every v-report behind the two w-reports, no
  round-1 proposal forms, and the shared coin legally walks everyone to w
  — found live by the round-9 chaos soak, at faults="none");
- **Decision consistency** — the collapsed ``SimResult.decision`` equals the
  first correct replica's decided value (2 when the instance capped out).
"""

from __future__ import annotations

import numpy as np

from byzantinerandomizedconsensus_tpu.models import state as state_mod


def state_violations(cfg, state, faulty, res=None, inst_ids=None) -> list:
    """List of violation records over a (B, n) state dict; empty = safe.

    ``faulty`` is the adversary's (B, n) faulty mask (spec §3.2) — replicas
    silenced by a §9 fault schedule but not adversary-faulty are *correct*
    and fully bound by Agreement/Validity. ``res``, when given, adds the
    decision-consistency check against its (B,) arrays.
    """
    decided = np.asarray(state["decided"])
    dval = np.asarray(state["decided_val"])
    correct = ~np.asarray(faulty)
    B = decided.shape[0]
    if inst_ids is None:
        inst_ids = np.arange(B)
    est0 = state_mod.init_est(cfg, cfg.seed, np.asarray(inst_ids), xp=np)

    out = []
    for i in range(B):
        inst = int(inst_ids[i])
        cd = correct[i] & decided[i]
        vals = sorted(set(dval[i][cd].tolist()))
        if len(vals) > 1:
            out.append({"instance": inst, "kind": "agreement",
                        "decided_values": vals})
        # Validity basis per fault model (module docstring): correct
        # replicas under a lying adversary, all replicas otherwise.
        ce = est0[i][correct[i]] if cfg.lying_adversary else est0[i]
        if len(ce) and (ce == ce[0]).all():
            v = int(ce[0])
            if any(int(x) != v for x in dval[i][cd]):
                out.append({"instance": inst, "kind": "validity",
                            "unanimous_init": v,
                            "decided_values": vals})
        if res is not None:
            done = bool(cd.sum() == correct[i].sum() and correct[i].any())
            want = int(dval[i][np.argmax(correct[i])]) if done \
                and int(res.rounds[i]) < cfg.round_cap else None
            got = int(res.decision[i])
            if want is not None and got != want:
                out.append({"instance": inst, "kind": "decision_consistency",
                            "expected": want, "got": got})
    return out


def check_config(cfg, backend="numpy", inst_ids=None) -> dict:
    """Run ``cfg`` on the numpy backend with full state and check the safety
    invariants; returns ``{"checked_instances", "violations"}``. The backend
    argument is pinned to one with ``run_with_state`` (numpy)."""
    from byzantinerandomizedconsensus_tpu.backends import get_backend

    be = get_backend(backend)
    res, state, faulty = be.run_with_state(cfg, inst_ids)
    return {
        "checked_instances": int(len(res.inst_ids)),
        "violations": state_violations(cfg, state, faulty, res=res,
                                       inst_ids=res.inst_ids),
    }
