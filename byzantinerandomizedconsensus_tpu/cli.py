"""CLI / sweep driver (SURVEY.md C9): select a benchmark config or custom parameters,
run on a chosen backend, emit JSON summaries and histograms.

Usage examples:
    python -m byzantinerandomizedconsensus_tpu.cli run --preset config4 --backend jax
    python -m byzantinerandomizedconsensus_tpu.cli run --protocol bracha -n 64 -f 21 \
        --instances 1000 --adversary byzantine --coin shared --backend numpy
    python -m byzantinerandomizedconsensus_tpu.cli sweep --out sweep_out --backend jax
    python -m byzantinerandomizedconsensus_tpu.cli bitmatch --preset config2 --samples 8
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

from byzantinerandomizedconsensus_tpu import PRESETS, SimConfig, Simulator, preset
from byzantinerandomizedconsensus_tpu.config import DELIVERY_KINDS, FAULT_KINDS
from byzantinerandomizedconsensus_tpu.utils import metrics, sweep


def _add_config_args(p: argparse.ArgumentParser, default_backend: str = "cpu") -> None:
    p.add_argument("--preset", choices=sorted(PRESETS), default=None)
    p.add_argument("--protocol", choices=["benor", "bracha"], default=None)
    p.add_argument("-n", type=int, default=None)
    p.add_argument("-f", type=int, default=None)
    p.add_argument("--instances", type=int, default=None)
    p.add_argument("--adversary",
                   choices=["none", "crash", "byzantine", "adaptive", "adaptive_min"],
                   default=None)
    p.add_argument("--coin", choices=["local", "shared"], default=None)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--round-cap", type=int, default=None)
    p.add_argument("--init", choices=["random", "all0", "all1", "split"], default=None)
    p.add_argument("--delivery", choices=list(DELIVERY_KINDS), default=None,
                   help="scheduling model: urn (spec §4b, sequential count-level "
                        "draws) | urn2 (spec §4b-v2, direct count inversion) | "
                        "urn3 (spec §4c, mode-anchored cheap law — a different "
                        "distribution, not a §4b-family sampler) — the "
                        "count-level trio; presets pin the A/B-measured "
                        "product one | keys (spec §4, O(n²) mask — the "
                        "validation model)")
    p.add_argument("--faults", choices=list(FAULT_KINDS), default=None,
                   help="fault schedule (spec §9), orthogonal to --adversary: "
                        "recover (crash-recovery windows) | partition "
                        "(PRF-drawn epoch isolating a fault-prone sub-block) "
                        "| omission (transient per-round bursts) — all "
                        "confined to the §3.2 fault-prone set; supported on "
                        "the cpu|numpy|jax stacks")
    p.add_argument("--backend", default=default_backend,
                   help="cpu (oracle) | numpy | native[:threads] | jax | jax_cpu "
                        "| jax_pallas | jax_sharded[:n_model] | virtual[:DxM] "
                        "(host-side SPMD emulation of the sharded layout)")


def _positive_int(text: str) -> int:
    value = int(text)
    if value <= 0:
        raise argparse.ArgumentTypeError(f"must be positive, got {value}")
    return value


def _announce_default_delivery() -> str:
    """One-line stderr notice when --delivery is defaulted (ADVICE r4): the
    product model can change between rounds, silently changing the results of
    previously-issued command lines. Returns the product model."""
    from byzantinerandomizedconsensus_tpu.config import PRODUCT_DELIVERY

    print(f"[cli] --delivery not given: using the product scheduling model "
          f"'{PRODUCT_DELIVERY}' (pass --delivery {'|'.join(DELIVERY_KINDS)} "
          "to pin)", file=sys.stderr)
    return PRODUCT_DELIVERY


def _config_from(args) -> SimConfig:
    # Every explicitly-passed flag applies — also on top of a preset.
    overrides = {k: v for k, v in [
        ("protocol", args.protocol), ("n", args.n), ("f", args.f),
        ("instances", args.instances), ("adversary", args.adversary),
        ("coin", args.coin), ("seed", args.seed), ("round_cap", args.round_cap),
        ("init", args.init), ("delivery", args.delivery),
        ("faults", getattr(args, "faults", None)),
    ] if v is not None}
    if args.preset:
        return preset(args.preset, **overrides)
    # Ad-hoc runs get the product scheduling model, same as every preset — the
    # CLI never silently selects the §4 validation model; pass --delivery keys
    # to get it. (SimConfig's *dataclass* default stays "keys" for code-level
    # spec-§4 work — see its docstring.)
    delivery = args.delivery if args.delivery is not None \
        else _announce_default_delivery()
    defaults = dict(protocol="benor", n=4, f=1, instances=1, adversary="none",
                    coin="local", seed=0, round_cap=256, init="random",
                    delivery=delivery)
    defaults.update(overrides)
    return SimConfig(**defaults).validate()


def cmd_run(args) -> int:
    from byzantinerandomizedconsensus_tpu.utils import profiling

    cfg = _config_from(args)
    counters_doc = None
    with profiling.trace(args.profile):
        if args.total_instances:
            from byzantinerandomizedconsensus_tpu.utils import multiseed

            if args.counters:
                print("--counters is not supported with --total-instances "
                      "(multi-seed shards have no counter channel yet)",
                      file=sys.stderr)
                return 2
            res, shards = multiseed.run_large(
                cfg, args.total_instances, backend=args.backend,
                progress=lambda msg: print(msg, file=sys.stderr))
        elif args.counters:
            # The protocol-counter side output (obs/counters.py): same run,
            # bit-identical results, plus the flight-recorder totals. Backends
            # without a counter channel degrade to an honest JSON block.
            from byzantinerandomizedconsensus_tpu.backends import get_backend
            from byzantinerandomizedconsensus_tpu.obs import counters as _c

            import time

            try:
                t0 = time.perf_counter()
                res, counters_doc = get_backend(
                    args.backend).run_with_counters(cfg)
                res.wall_s = time.perf_counter() - t0  # same leg timed_run sets
            except _c.CountersUnsupported as e:
                print(f"[cli] {e}", file=sys.stderr)
                counters_doc = _c.unsupported_doc(e)
                res = Simulator(cfg, args.backend).run()
        else:
            res = Simulator(cfg, args.backend).run()
    out = metrics.summary(res)
    if counters_doc is not None:
        out["counters"] = counters_doc
    if args.total_instances:
        # summary already reports the base seed and the grand total (the merged
        # result carries the user's config); the derived per-shard seeds are
        # what's needed to reproduce any shard standalone.
        out["seeds"] = [s.seed for s in shards]
    out["backend"] = args.backend
    if args.hist:
        out["round_histogram"] = metrics.round_histogram(res).tolist()
    print(json.dumps(out))
    return 0


def cmd_bitmatch(args) -> int:
    """Sampled arbiter vs accelerated-backend bit-match check.

    The default arbiter is the Python object oracle (slow, definitionally
    correct); ``--arbiter native`` uses the oracle-anchored C++ core instead,
    which makes thousand-sample benchmark-scale checks interactive
    (tools/acceptance.py is the artifact-producing form of the same idea)."""
    from byzantinerandomizedconsensus_tpu.tools.acceptance import (
        compare_results, sample_ids)

    # Base-name comparison: "native:4" resolves to the same implementation as
    # "native", and arbiter-vs-itself would be vacuous evidence.
    if args.backend.partition(":")[0] == args.arbiter:
        print("bitmatch compares the arbiter against a *different* backend; "
              "pick a --backend not implemented by the arbiter "
              "(numpy|jax|jax_cpu|jax_pallas|jax_sharded, or native vs "
              "--arbiter cpu)", file=sys.stderr)
        return 2
    cfg = _config_from(args)
    if cfg.instances < args.samples:
        # A small preset (config1 ships instances=1) must not silently shrink
        # a requested thousand-sample check to a near-vacuous one: widen the
        # id range instead (instance i depends only on (cfg, seed, i) —
        # spec §1; tools/acceptance.py does the same).
        import dataclasses

        cfg = dataclasses.replace(cfg, instances=args.samples).validate()
    ids = sample_ids(cfg, args.samples, seed=cfg.seed)
    ref = Simulator(cfg, args.arbiter).run(ids)
    got = Simulator(cfg, args.backend).run(ids)
    cmp = compare_results(ref, got)
    out = {
        "bitmatch": cmp["match"],
        "arbiter": args.arbiter,
        "backend": args.backend,
        "n_samples": int(len(ids)),
        # The *effective* id range: may exceed a small preset's shipped
        # instances (widened above) — record it so the artifact is honest
        # about which config was actually compared.
        "instances": int(cfg.instances),
        "mismatches": cmp["mismatches"],
    }
    if len(ids) <= 32:  # keep the JSON line readable for the common case
        out["samples"] = ids.tolist()
        out["arbiter_rounds"] = ref.rounds.tolist()
        out["backend_rounds"] = got.rounds.tolist()
    print(json.dumps(out))
    return 0 if cmp["match"] else 1


def cmd_sweep(args) -> int:
    if args.plot:
        # Fail before the (potentially hours-long) sweep, not after it.
        try:
            import matplotlib  # noqa: F401
        except ImportError:
            print("--plot requires matplotlib, which is not installed",
                  file=sys.stderr)
            return 2
    delivery = args.delivery if args.delivery is not None \
        else _announce_default_delivery()
    from byzantinerandomizedconsensus_tpu.config import SWEEP_NS_EXTENDED

    default_ns = SWEEP_NS_EXTENDED if args.extended else sweep.SWEEP_NS
    points = sweep.run_sweep(
        pathlib.Path(args.out), backend=args.backend,
        ns=tuple(int(x) for x in args.ns) if args.ns else default_ns,
        instances=args.instances, seed=args.seed,
        shard_instances=args.shard_instances, coin=args.coin,
        delivery=delivery, round_cap=args.round_cap,
        batched=args.batched,
        progress=lambda msg: print(msg, file=sys.stderr),
    )
    # One artifact format across all tools (obs/record.py): the per-n
    # summaries ride under "points", next to the record head.
    print(json.dumps(sweep.sweep_record(points, args.backend, delivery)))
    if args.plot:
        from byzantinerandomizedconsensus_tpu.utils import plot

        plot.plot_sweep(points, args.plot)
        print(f"wrote {args.plot}", file=sys.stderr)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="byzantinerandomizedconsensus_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p_run = sub.add_parser("run", help="run one config to termination")
    _add_config_args(p_run)
    p_run.add_argument("--hist", action="store_true", help="include the round histogram")
    p_run.add_argument("--total-instances", type=int, default=None,
                       help="run this many instances via multi-seed sharding "
                            "(beyond the 2^17 per-seed limit — spec §2)")
    p_run.add_argument("--profile", default=None, metavar="DIR",
                       help="write a jax.profiler trace (TensorBoard/Perfetto) "
                            "to DIR — phase spans (brc/mask, brc/urn2, "
                            "brc/coin, ...) label the timeline")
    p_run.add_argument("--counters", action="store_true",
                       help="collect the protocol-counter side output "
                            "(obs/counters.py): delivered/dropped per phase, "
                            "coin flips, sampler cost counters — results stay "
                            "bit-identical")
    p_run.set_defaults(fn=cmd_run)

    p_bm = sub.add_parser("bitmatch", help="sampled oracle-vs-backend bit-match")
    _add_config_args(p_bm, default_backend="jax")
    p_bm.add_argument("--samples", type=_positive_int, default=4)
    p_bm.add_argument("--arbiter", choices=["cpu", "native"], default="cpu",
                      help="reference implementation: cpu (object oracle) | "
                           "native (oracle-anchored C++ core — fast enough "
                           "for thousand-sample benchmark-scale checks)")
    p_bm.set_defaults(fn=cmd_bitmatch)

    p_sw = sub.add_parser("sweep", help="config-5 adaptive sweep (resumable)")
    p_sw.add_argument("--out", default="sweep_out")
    p_sw.add_argument("--backend", default="jax")
    p_sw.add_argument("--ns", nargs="*", type=int, default=None)
    p_sw.add_argument("--extended", action="store_true",
                      help="include the opt-in n=2048 point past the v1 "
                           "packing edge (spec §2 v2; config.SWEEP_NS_EXTENDED)")
    p_sw.add_argument("--instances", type=int, default=sweep.SWEEP_INSTANCES)
    p_sw.add_argument("--shard-instances", type=int, default=500)
    p_sw.add_argument("--seed", type=int, default=0)
    p_sw.add_argument("--round-cap", type=int, default=None)
    p_sw.add_argument("--coin", choices=["local", "shared"], default="shared")
    p_sw.add_argument("--delivery", choices=list(DELIVERY_KINDS), default=None)
    p_sw.add_argument("--batched", action="store_true",
                      help="config-batched shards (backends/batch.py): sweep "
                           "points sharing a shape tier ride one compiled "
                           "program and one dispatch per shard — "
                           "bit-identical results, fewer compiles")
    p_sw.add_argument("--plot", default=None, metavar="FILE",
                      help="render the round-distribution figure (png/svg)")
    p_sw.set_defaults(fn=cmd_sweep)

    # Artifact tools, surfaced for discoverability in --help; dispatched
    # before argparse (argparse.REMAINDER cannot capture leading options).
    sub.add_parser("accept",
                   help="at-scale acceptance artifact (tools/acceptance.py; "
                        "all further options pass through)")
    sub.add_parser("slack",
                   help="slack-vs-rounds boundary artifact (tools/slack.py; "
                        "all further options pass through)")
    sub.add_parser("product",
                   help="five-preset as-shipped product-run artifact "
                        "(tools/product.py; all further options pass through)")
    sub.add_parser("ledger",
                   help="regression-chain ledger over every committed "
                        "BENCH/MULTICHIP/artifact JSON (tools/ledger.py; "
                        "`ledger --check` is the regression sentinel — "
                        "nonzero on wall regression or program-fingerprint "
                        "drift; `ledger --debts` prints only the standing "
                        "device-of-record DEBT rows as a table; `--json` "
                        "for the machine-readable verdict; all further "
                        "options pass through)")
    sub.add_parser("chaos",
                   help="chaos soak: randomized spec-§9 fault schedules, "
                        "subprocess-isolated with timeout/retry/checkpoint "
                        "(tools/soak.py --chaos; all further options pass "
                        "through)")
    sub.add_parser("compaction",
                   help="decision-driven lane-compaction A/B at the "
                        "headline shape (tools/bench_compaction.py; all "
                        "further options pass through)")
    sub.add_parser("trace",
                   help="host-side telemetry consumers (tools/trace.py): "
                        "`trace export --chrome` (Perfetto), `trace "
                        "summary [--top N]` (p50/p90/p99 span digest, "
                        "ranked by total wall with --top), `trace "
                        "follow DIR` (live fleet progress), `trace "
                        "overhead` (the traced-vs-untraced A/B)")
    sub.add_parser("programs",
                   help="compiled-program census consumers "
                        "(tools/programs.py): `programs dump ART` (XLA "
                        "cost/memory + HLO fingerprints), `programs diff "
                        "A B` (fingerprint drift), `programs roofline "
                        "--census ART [--vs BASE]` (per-dispatch wall vs "
                        "per-program flops/bytes, bytes/dispatch delta vs "
                        "a baseline census), `programs census` (the "
                        "census-on-vs-off A/B artifact), `programs fused` "
                        "(the ABI v6 xla-vs-fused A/B artifact)")
    sub.add_parser("serve",
                   help="always-on consensus service (serve/server.py): "
                        "stdlib-HTTP front end over continuous-batching "
                        "fused lane grids, streamed schema-v1.5 replies, "
                        "zero steady-state recompiles; --workers N shards "
                        "the service across subprocess workers with "
                        "bucket-affine routing + work stealing "
                        "(serve/fleet.py); --wal DIR journals every "
                        "admission write-ahead and --recover DIR replays "
                        "a crashed dispatcher's in-flight work "
                        "bit-identically (serve/wal.py); --max-workers N "
                        "turns on the metrics-driven autoscaler "
                        "(serve/autoscale.py) (all further options pass "
                        "through)")
    sub.add_parser("loadgen",
                   help="seeded open-loop load generator for the service "
                        "(tools/loadgen.py): Poisson arrivals over a "
                        "heterogeneous population, emits the serving "
                        "artifact with p50/p99 latency + sustained "
                        "configs/sec + the zero-recompile pin; --workers "
                        "1,2,4 sweeps the fleet and pins the scaling "
                        "curve (schema-v1.6 fleet block); --slo-p99-ms / "
                        "--slo-error-rate gate the run against a live "
                        "/metrics scrape (exit 5 on breach); --scenario "
                        "flash_crowd|heavy_tail|bucket_churn|tenant_hog|"
                        "cancel_storm|session_hog|all runs the hostile-"
                        "load suite (tools/hostile.py, schema-v1.9 "
                        "hostile block); --scenario dispatcher_kill|"
                        "autoscale_crowd|elastic runs the round-22 "
                        "durability/autoscaling drills (schema-v1.13 "
                        "elastic block); --session-bench measures the "
                        "spec-§11 session amortization ratio (schema-"
                        "v1.12 session block)")
    sub.add_parser("dash",
                   help="live terminal dashboard over a serving endpoint's "
                        "GET /metrics (tools/dash.py): request p50/p99 + "
                        "rate, admission/rejection counters, grid "
                        "occupancy, compile-cache deltas, consensus "
                        "decided fraction + rounds sparkline, per-worker "
                        "fleet table; read-only and survives a dead "
                        "endpoint")
    sub.add_parser("hunt",
                   help="closed-loop worst-case search driving the serving "
                        "stack (hunt/): seeded ask/tell strategies "
                        "(random|evolution|bandit) over the adversary × "
                        "fault × delivery × shape space, ask-ahead "
                        "pipelined generations vs a barriered control, "
                        "per-reply safety verdicts, elite archive exported "
                        "as replayable regression configs; emits the "
                        "schema-v1.8 hunt artifact (exit 1 safety "
                        "violation, 2 steady-state compiles, 3 invalid "
                        "record, 4 replay drift)")

    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] in ("accept", "slack", "product", "ledger", "chaos",
                            "compaction", "trace", "programs", "serve",
                            "loadgen", "dash", "hunt"):
        from byzantinerandomizedconsensus_tpu.hunt import hunter as hunt_tool
        from byzantinerandomizedconsensus_tpu.serve import server as serve_tool
        from byzantinerandomizedconsensus_tpu.tools import (
            acceptance, bench_compaction, dash, ledger, loadgen, product,
            slack, soak)
        from byzantinerandomizedconsensus_tpu.tools import (
            programs as programs_tool)
        from byzantinerandomizedconsensus_tpu.tools import trace as trace_tool

        if argv[0] == "chaos":
            return soak.main(["--chaos", *argv[1:]])
        tool = {"accept": acceptance, "slack": slack,
                "product": product, "ledger": ledger,
                "compaction": bench_compaction, "trace": trace_tool,
                "programs": programs_tool, "serve": serve_tool,
                "loadgen": loadgen, "dash": dash,
                "hunt": hunt_tool}[argv[0]]
        return tool.main(argv[1:])
    args = ap.parse_args(argv)
    if getattr(args, "backend", "").startswith("jax"):
        # Headless resilience (docs/NEXT.md item 6): never hang on a dead TPU
        # tunnel — probe device init out-of-process and fall back to CPU.
        from byzantinerandomizedconsensus_tpu.utils.devices import ensure_live_backend

        ensure_live_backend()
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
