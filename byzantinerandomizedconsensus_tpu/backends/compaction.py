"""Decision-driven lane compaction — continuous batching at the instance axis
(docs/PERF.md round 11).

The jit'd ``lax.while_loop`` chunk runner (backends/jax_backend.py::_run_chunk)
makes every instance in a chunk pay the chunk's **max** rounds-to-decision:
docs/PERF.md measures mean max-rounds/chunk at 2.08 against 1.42 mean rounds
at the headline operating point — a ~1.5x straggler tax that is also why the
chunk size is capped at 2048. Inference servers solved the same problem with
*continuous batching*: retire finished sequences, refill their slots, keep
the device at fixed occupancy. This module applies that idiom at the instance
axis:

- the round loop runs in short **segments** (``CompactionPolicy.segment``
  rounds per dispatch) over a fixed-width lane grid, one instance per lane,
  each lane carrying its own round counter ``r`` — lanes at different global
  rounds coexist in one dispatch;
- after each segment the host fetches only the tiny per-lane
  ``(finished, rounds, decision)`` surface; when the retired fraction of the
  grid crosses ``refill_threshold`` (and a queue of pending instances
  exists), survivors are **compacted** (gathered by lane permutation) and the
  freed lanes **refilled** from the queue — all on device, inside the same
  compiled step program;
- once the queue is dry the **drain** variant of the program (segment length
  = the round cap) runs the stragglers to completion in one dispatch: the
  per-lane loop conditions stop it the moment the last lane decides, so the
  tail costs exactly one straggler tail for the whole run instead of one per
  chunk.

Bit-identity to ``_run_chunk`` is the law. It holds by construction: the PRF
addresses every draw by *coordinates* ``(key, instance, round, step, ...)``
(spec §2), and a lane's round counter is the instance's own round index — so
which lane, segment, or refill generation an instance lands in never enters
any draw or any threshold. The per-lane state update, decision predicate and
extraction are the same models/ functions ``_run_chunk`` calls, vmapped over
lanes instead of batched over a chunk axis (tests/test_compaction.py asserts
bit-identity across the fault x adversary x delivery grid, with mixed-n
padding lanes and with counters on).

The lane grid speaks the round-10 bucket language (backends/batch.py): lane
operands are ``(key, f, crash_window, n_eff)`` — so one compiled step program
serves every config of a :class:`~.batch.ShapeBucket`, and ``run_many`` /
``run_fused`` feed a whole bucket's configs through ONE shared queue
(``compaction=`` policy): lanes freed by one config's instances are refilled
with the next config's, keeping occupancy high across config boundaries.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional, Sequence

import numpy as np

from byzantinerandomizedconsensus_tpu.backends.batch import (
    ADV_CODES, COIN_CODES, FAULT_CODES, INIT_CODES, FusedBucket,
    FusedLaneConfig, LaneConfig, ShapeBucket, _chunk_instances, _key_label,
    _PadAdversary, compile_cache, lane_tier)
from byzantinerandomizedconsensus_tpu.backends import lanestate as _lanestate
from byzantinerandomizedconsensus_tpu.obs import metrics as _metrics
from byzantinerandomizedconsensus_tpu.obs import trace as _trace
from byzantinerandomizedconsensus_tpu.ops import prf


@dataclasses.dataclass(frozen=True)
class CompactionPolicy:
    """The decision-driven refill law.

    ``width``: lanes resident on device (None = the backend's chunk-sizing
    law for the bucket, the like-for-like A/B width; compaction removes the
    straggler pressure that capped chunks at 2048, so larger widths are now
    profitable — tools/bench_compaction.py sweeps this). Rounded to the next
    power of two so nearby runs share programs.

    ``segment``: rounds per device dispatch between refill opportunities.
    Small segments react faster (retired lanes idle at most ``segment - 1``
    rounds before a refill can reclaim them) but pay more host round-trips.

    ``refill_threshold``: compact + refill when at least this fraction of
    lanes is retired (and pending instances exist). The host always refills
    when the grid is fully drained, whatever the threshold.
    """

    width: Optional[int] = None
    segment: int = 2
    refill_threshold: float = 0.25

    def validate(self) -> "CompactionPolicy":
        if self.width is not None and self.width < 1:
            raise ValueError(f"compaction width={self.width} out of range")
        if self.segment < 1:
            raise ValueError(
                f"compaction segment={self.segment} out of range (>= 1)")
        if not (0.0 < self.refill_threshold <= 1.0):
            raise ValueError(
                f"refill_threshold={self.refill_threshold} out of range "
                "(0 < t <= 1)")
        return self

    @classmethod
    def parse(cls, spec: str) -> "CompactionPolicy":
        """``"width=4096,segment=2,threshold=0.25"`` (any subset; bare "1"
        or "" = defaults) — the CLI/env spelling of the policy."""
        kw: dict = {}
        if spec and spec not in ("1", "default"):
            for part in spec.split(","):
                k, _, v = part.partition("=")
                k = k.strip()
                if k in ("width", "w"):
                    kw["width"] = int(v)
                elif k in ("segment", "seg", "s"):
                    kw["segment"] = int(v)
                elif k in ("threshold", "thr", "t", "refill_threshold"):
                    kw["refill_threshold"] = float(v)
                else:
                    raise ValueError(
                        f"unknown compaction policy field {k!r}; use "
                        "width=/segment=/threshold=")
        return cls(**kw).validate()

    def doc(self) -> dict:
        """The run-record ``policy`` sub-block (obs/record.py schema v1.2)."""
        return {"width": self.width, "segment": self.segment,
                "refill_threshold": self.refill_threshold}


class WorkFeedOverflow(RuntimeError):
    """Raised by :meth:`WorkFeed.push` when a bounded feed is full.

    The named rejection is the backpressure seam (ROADMAP #4 seed, round 17):
    a producer that can outdraw the grid — the adversary hunter's ask-ahead
    loop is the first — gets a typed signal to throttle on instead of growing
    the host queue without bound. Default feeds stay unbounded, so no
    existing caller can see this without opting in via ``max_depth``.
    """


class WorkFeed:
    """Externally-fed work queue for :func:`run_bucket` — the serving seam
    (round 14, closing round 11's open leg (b)).

    The offline path hands ``run_bucket`` a closed list of configs; a server
    cannot. A ``WorkFeed`` lets requests arrive *while the lane grid is
    flying*: ``push(cfg)`` from any thread enqueues a config (with an opaque
    ``token`` the retirement callback hands back), ``run_bucket`` splices
    newly arrived items into its host work stream at every segment boundary,
    and freed lanes refill from them exactly like queued offline work —
    placement never enters a draw (spec §2 coordinates), so served results
    stay bit-identical to the offline path.

    Two program-stability rules keep the steady state recompile-free:
    ``run_bucket`` pins the grid width to the policy's lane tier (never
    shrinking to the momentary queue length), and the drain program is
    compiled once at ``round_cap_ceiling`` — ``push`` rejects configs whose
    cap exceeds it, so no late request can mint a new program key.
    """

    def __init__(self, round_cap_ceiling: int = 128,
                 max_depth: int | None = None):
        if round_cap_ceiling < 1:
            raise ValueError(
                f"round_cap_ceiling={round_cap_ceiling} out of range (>= 1)")
        if max_depth is not None and max_depth < 1:
            raise ValueError(
                f"max_depth={max_depth} out of range (>= 1, or None for "
                "unbounded)")
        self.round_cap_ceiling = int(round_cap_ceiling)
        self.max_depth = None if max_depth is None else int(max_depth)
        self._items: list = []
        self._cancelled: list = []
        self._cv = threading.Condition()
        self._closed = False
        self._poked = False
        # Tokens of live sessions (spec §11) that own this feed: a session's
        # future slots materialize at the grid's retire seam, not here, so
        # "queue empty + closed" is NOT "drained" while an owner lives —
        # pull() keeps the stream open until every owner finishes
        # (session_done) or is cancelled.
        self._owner_tokens: list = []

    def _release_owner(self, token) -> None:
        """Drop ``token`` from the live-session owners (identity match,
        idempotent — a cancel and a reap may both report the same death)."""
        self._owner_tokens = [t for t in self._owner_tokens
                              if t is not token]

    def push(self, cfg, ids=None, token=None, force: bool = False,
             session=None) -> None:
        """Enqueue one config (its instances become queued lane work).
        ``ids`` defaults to the config's full instance range; ``token`` is
        returned verbatim to ``on_retire`` when the config completes.
        ``force=True`` bypasses the ``max_depth`` bound — the server's
        rotation seed uses it, because seeded requests were admitted
        before this feed existed (round 18). ``session=L`` marks the config
        as slot 0 of an L-slot spec-§11 session: the grid re-seeds slots
        1..L-1 in place at its retire seam, ``on_retire`` fires once per
        slot with the same token, and the token owns the feed (it cannot
        report drained) until the last slot retires or the session is
        cancelled."""
        if cfg.round_cap > self.round_cap_ceiling:
            raise ValueError(
                f"round_cap={cfg.round_cap} exceeds the feed ceiling "
                f"{self.round_cap_ceiling}: the drain program is compiled "
                "once per bucket at the ceiling, so admission must reject "
                "or re-route larger caps")
        session = None if session is None or int(session) <= 1 \
            else int(session)
        with self._cv:
            if self._closed:
                raise RuntimeError("push on a closed WorkFeed")
            if not force and self.max_depth is not None and \
                    len(self._items) >= self.max_depth:
                raise WorkFeedOverflow(
                    f"WorkFeed depth {len(self._items)} at max_depth="
                    f"{self.max_depth}: producer must back off until the "
                    "grid drains")
            self._items.append((cfg, ids, token, session))
            if session is not None:
                self._owner_tokens.append(token)
            self._cv.notify_all()

    def close(self) -> None:
        """No more pushes; run_bucket drains what remains and returns."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def closed(self) -> bool:
        return self._closed

    def pending(self) -> int:
        """Configs pushed but not yet pulled into the grid — the queue-depth
        probe the serving stats and the fleet dispatcher's steal heuristic
        read (serve/server.py stats, serve/fleet.py)."""
        with self._cv:
            return len(self._items)

    def cancel(self, token) -> bool:
        """Mark ``token``'s work dead (round 18, the cancellation seam).

        Items still queued in the feed are removed here, synchronously —
        they never reach a lane. Items already pulled into a flying grid
        are reclaimed by :func:`run_bucket` at its next segment boundary:
        the lane is dropped from the host bookkeeping (no result is ever
        recorded, ``on_retire`` never fires) and freed at the next
        compaction refill. Returns True when the token was still queued
        here (the cheap case); False means the grid owns it now — or never
        saw it — and the boundary reap is the reclaim path. Survivors are
        bit-identical either way: lane placement never enters a draw.

        Session ownership is released **only** for a session still queued
        here (it died before its first slot reached a lane); a session
        already flying keeps owning the feed until :func:`run_bucket`'s
        boundary reap reports its death via :meth:`session_done`. The
        round-21 edge case this ordering fixes: cancelling the last queued
        config of a session-owned feed empties the queue, but must NOT make
        a closed feed report drained (``pull() -> None``) while a different
        session's future slots are still due from the grid — that would
        close the feed out from under the dispatcher mid-session.
        """
        with self._cv:
            n = len(self._items)
            kept = []
            for it in self._items:
                if it[2] is token:
                    if it[3] is not None:
                        self._release_owner(token)
                else:
                    kept.append(it)
            self._items = kept
            self._cancelled.append(token)
            self._cv.notify_all()
            return len(self._items) < n

    def pop_cancelled(self) -> list:
        """Drain the cancel marks since the last call — run_bucket's
        segment-boundary reap reads them (tokens, verbatim)."""
        with self._cv:
            out = self._cancelled
            self._cancelled = []
            return out

    def sessions(self) -> int:
        """Live sessions owning this feed (queued or flying) — the serving
        stats probe."""
        with self._cv:
            return len(self._owner_tokens)

    def session_done(self, token) -> None:
        """Release ``token``'s session ownership — :func:`run_bucket` calls
        this when the session's last slot retires (or its lanes are reaped
        after a cancel), letting a closed feed finally report drained."""
        with self._cv:
            self._release_owner(token)
            self._cv.notify_all()

    def poke(self) -> None:
        """Wake a grid parked in a blocking :meth:`pull` without enqueuing
        work (round 23): the next blocking pull returns ``[]`` once so
        ``run_bucket`` reaches its segment boundary and services any
        pending :class:`~byzantinerandomizedconsensus_tpu.backends.\
lanestate.LaneControl` request (park/extract)."""
        with self._cv:
            self._poked = True
            self._cv.notify_all()

    def pull(self, block: bool = False):
        """Everything pushed since the last pull: a list of
        ``(cfg, ids, token, session)`` items, ``[]`` when nothing is
        pending, or ``None`` once the feed is closed *and* drained.
        ``block=True`` waits for items, close, or a :meth:`poke` — the idle
        server parks here. A feed owned by a live session is never drained:
        its future slots materialize at the grid's retire seam, so pull
        keeps the stream open (returns ``[]`` / keeps waiting) until every
        owner retires its last slot or is cancelled."""
        with self._cv:
            while block and not self._items and not self._poked and not (
                    self._closed and not self._owner_tokens):
                self._cv.wait()
            self._poked = False
            if not self._items:
                return (None if self._closed and not self._owner_tokens
                        else [])
            out = self._items
            self._items = []
            return out


def _lane_cfg(bucket, op):
    """The per-lane config view: strict buckets trace (f, crash_window,
    n_eff); fused buckets additionally trace the folded-axis codes + cap."""
    if isinstance(bucket, FusedBucket):
        return FusedLaneConfig(
            bucket, f=op["f"], crash_window=op["win"], n_eff=op["neff"],
            round_cap=op["cap"], adv_code=op["adv"], faults_code=op["flt"],
            coin_code=op["coin"], init_code=op["init"])
    return LaneConfig(bucket, f=op["f"], crash_window=op["win"],
                      n_eff=op["neff"])


def _lane_cap(bucket, op):
    """Round cap per lane: static for strict buckets (part of the bucket),
    traced lane data for fused ones."""
    if isinstance(bucket, FusedBucket):
        return op["cap"]
    return bucket.round_cap


def _host_op_row(bucket, cfg) -> dict:
    """The host-side lane-operand row for one config (numpy scalars)."""
    row = {
        "key": np.asarray(prf.seed_key(cfg.seed), dtype=np.uint32),
        "f": np.int32(cfg.f),
        "win": np.uint32(cfg.crash_window),
        "neff": np.int32(cfg.n),
    }
    if isinstance(bucket, FusedBucket):
        row.update({
            "cap": np.int32(cfg.round_cap),
            "adv": np.int32(ADV_CODES[cfg.adversary]),
            "flt": np.int32(FAULT_CODES[cfg.faults]),
            "coin": np.int32(COIN_CODES[cfg.coin]),
            "init": np.int32(INIT_CODES[cfg.init]),
        })
    return row


def _lane_fns(bucket, counters: bool):
    """The per-lane building blocks the three compiled programs share.

    ``fresh_one(op, iid)`` does the one-time per-instance work — initial
    state (spec §3.1) plus the adversary/fault setup draws (spec §3.2/§3.3/
    §9) — exactly what ``_run_chunk`` computes once per chunk invocation;
    carrying the products in the lane carry keeps the hot segment program
    free of it (a straggler-tax fix must not re-tax every segment).

    ``lane_segment(...)`` runs up to ``seg`` rounds of ONE lane from its own
    round counter ``r0``. Under vmap, jax batches the ``while_loop`` to "run
    while any lane's condition holds, freeze finished lanes' carries" — the
    chunk runner's frozen-decided-instance semantics, per lane.
    """
    import jax
    import jax.numpy as jnp

    from byzantinerandomizedconsensus_tpu.models import (
        benor, bracha, state as state_mod)
    from byzantinerandomizedconsensus_tpu.obs import counters as _c

    round_body = (benor.round_body if bucket.protocol == "benor"
                  else bracha.round_body)

    def lane_adv(op, cfg):
        pad = jnp.arange(bucket.n_pad, dtype=jnp.int32) >= cfg.n_eff
        return _PadAdversary(cfg, pad)

    def fresh_one(op, iid):
        cfg = _lane_cfg(bucket, op)
        adv = lane_adv(op, cfg)
        st = state_mod.init_state(cfg, op["key"], iid[None], xp=jnp)
        setup = adv.setup(op["key"], iid[None], xp=jnp)
        return ({k: v[0] for k, v in st.items()},
                jax.tree_util.tree_map(lambda v: v[0], setup))

    def lane_segment(seg, op, iid, r0, st_row, setup_row, done0, acc0=None):
        cfg = _lane_cfg(bucket, op)
        cap = _lane_cap(bucket, op)
        adv = lane_adv(op, cfg)
        key = op["key"]
        ids = iid[None]
        setup = jax.tree_util.tree_map(lambda v: v[None], setup_row)
        faulty = setup["faulty"]
        st = {k: v[None] for k, v in st_row.items()}
        init = (jnp.int32(0), st, done0) + (
            ((acc0[None],) if counters else ()))

        def cond(carry):
            k, _, done = carry[:3]
            return (k < seg) & (done < 0) & (r0 + k < cap)

        def body(carry):
            k, st, done = carry[:3]
            rr = r0 + k
            obs = {} if counters else None
            st2 = round_body(cfg, key, ids, rr, st, adv, setup, xp=jnp,
                             counts_fn=None, obs=obs)
            out = (k + 1, st2)
            if counters:
                acc = _c.accumulate(carry[3],
                                    _c.round_increments(cfg, obs, jnp),
                                    (done < 0)[None], cfg, jnp)
            done_now = state_mod.all_correct_decided(st2, faulty, xp=jnp)[0]
            done = jnp.where((done < 0) & done_now, rr + 1, done)
            return out + (done,) + ((acc,) if counters else ())

        final = jax.lax.while_loop(cond, body, init)
        k, st, done = final[:3]
        r1 = r0 + k
        done_b = done >= 0
        finished = done_b | (r1 >= cap)
        rounds = jnp.where(done_b, done, cap).astype(jnp.int32)
        decision = state_mod.extract_decision(st, faulty, done_b[None],
                                              xp=jnp)[0]
        st_out = {kk: v[0] for kk, v in st.items()}
        out = (r1, st_out, done, rounds, decision, finished)
        if counters:
            out += (final[3][0],)
        return out

    return fresh_one, lane_segment


# Carry layout: (ops, iids, r, st, setup, done[, acc]).
def _n_carry(counters: bool) -> int:
    return 7 if counters else 6


def _make_init(bucket, counters: bool):
    """The grid-fill program: build the whole carry fresh from a W-row
    operand block. ``init(ops, iids, n_fill) -> carry``; slots at index
    ``>= n_fill`` start already-retired (``done = 0``) so the segment loop
    never runs them (queue shorter than the grid)."""
    import jax
    import jax.numpy as jnp

    from byzantinerandomizedconsensus_tpu.obs import counters as _c

    fresh_one, _ = _lane_fns(bucket, counters)

    def init(ops, iids, n_fill):
        W = iids.shape[0]
        st, setup = jax.vmap(fresh_one)(ops, iids)
        done = jnp.where(jnp.arange(W, dtype=jnp.int32) < n_fill,
                         jnp.int32(-1), jnp.int32(0))
        carry = (ops, iids, jnp.zeros(W, dtype=jnp.int32), st, setup, done)
        if counters:
            n_c = len(_c.counter_names(_StaticCfgView(bucket)))
            carry += (jnp.zeros((W, n_c, 2), dtype=jnp.uint32),)
        return carry

    return init


def _make_refill(bucket, F: int, counters: bool):
    """The compaction program: gather survivors, splice a fresh block in.

    ``refill(perm, n_keep, n_fill, ops_block, iids_block, *carry) ->
    carry'``. Slot ``i < n_keep`` takes old carry row ``perm[i]`` (survivors
    packed first); slot ``n_keep + j`` takes fresh block row ``j`` (live for
    ``j < n_fill``, inert-retired otherwise). The fresh block is ``F`` rows —
    a power-of-two quantum so the expensive one-time work (init draws, §3.2
    setup) is paid for the refill size, not the grid width.
    """
    import jax
    import jax.numpy as jnp

    fresh_one, _ = _lane_fns(bucket, counters)

    def refill(perm, n_keep, n_fill, ops_block, iids_block, *carry):
        W = perm.shape[0]
        idx = jnp.arange(W, dtype=jnp.int32)
        keep = idx < n_keep
        src_new = jnp.clip(idx - n_keep, 0, F - 1)
        st_f, setup_f = jax.vmap(fresh_one)(ops_block, iids_block)
        ops, iids, r, st, setup, done = carry[:6]

        def merge(old, fresh_block):
            return jax.tree_util.tree_map(
                lambda a, b: jnp.where(
                    keep.reshape((W,) + (1,) * (a.ndim - 1)),
                    a[perm], b[src_new]),
                old, fresh_block)

        out = (merge(ops, ops_block), merge(iids, iids_block),
               jnp.where(keep, r[perm], jnp.int32(0)),
               merge(st, st_f), merge(setup, setup_f),
               jnp.where(keep, done[perm],
                         jnp.where(idx - n_keep < n_fill, jnp.int32(-1),
                                   jnp.int32(0))))
        if counters:
            acc = carry[6]
            out += (jnp.where(keep[:, None, None], acc[perm],
                              jnp.zeros_like(acc)),)
        return out

    return refill


def _make_segment(bucket, seg: int, counters: bool):
    """The hot program: up to ``seg`` rounds per lane, nothing else.
    ``segment(*carry) -> carry' + (rounds, decision, finished)``."""
    import jax
    from functools import partial as _partial

    _, lane_segment = _lane_fns(bucket, counters)

    def segment(*carry):
        ops, iids, r, st, setup, done = carry[:6]
        args = (ops, iids, r, st, setup, done) + (
            (carry[6],) if counters else ())
        out = jax.vmap(_partial(lane_segment, seg))(*args)
        r1, st1, done1, rounds, decision, finished = out[:6]
        new = (ops, iids, r1, st1, setup, done1)
        if counters:
            new += (out[6],)
        return new + (rounds, decision, finished)

    return segment


class _StaticCfgView:
    """Minimal cfg duck for counter-schema resolution from a bucket (the
    schema is a static function of protocol/delivery/faults, all bucket
    statics)."""

    def __init__(self, bucket):
        self.protocol = bucket.protocol
        self.delivery = bucket.delivery
        self.faults = bucket.faults


def run_bucket(backend, bucket, cfgs, ids_list, policy=None,
               counters: bool = False, progress=None, feed=None,
               on_retire=None, control=None, imports=None):
    """Run every instance of every config of ONE bucket through the
    compacted lane grid. Returns ``(results, docs_or_None, stats)`` with
    ``results`` per-config SimResults bit-identical to the per-chunk path and
    ``stats`` the run-record ``compaction`` block payload (occupancy,
    wasted-lane-rounds, refills).

    ``feed`` (a :class:`WorkFeed`) opens the queue to the outside: configs
    pushed from other threads join the work stream at segment boundaries and
    refill freed lanes mid-flight — the serving loop's admission path. The
    grid width is then pinned to the policy tier and the drain length to the
    feed's cap ceiling so steady state compiles nothing new. ``on_retire``
    is called as ``on_retire(token, SimResult)`` the moment a config's last
    instance retires — replies stream out per request, not at grid end
    (tokens for the initial ``cfgs`` are their list indices).

    Feed items pushed with ``session=L`` (spec §11) stay resident across
    slots: when slot ``k``'s last instance retires, its ``on_retire`` fires
    with that slot's SimResult and the retire seam immediately splices slot
    ``k+1`` — the same config under the chained seed
    (models/session.py::next_slot_config) — into the work stream, so the
    next refill re-seeds the freed lanes in place. No admission round-trip,
    no new program key (the seed is a dynamic operand), and each slot is
    bit-identical to the offline ``run_session`` replay.

    ``control`` (a :class:`~byzantinerandomizedconsensus_tpu.backends.\
lanestate.LaneControl`) opens the round-23 snapshot seam: at every segment
    boundary the grid services queued **park** (export every extractable
    config as :class:`~byzantinerandomizedconsensus_tpu.backends.lanestate.\
LaneRecord` and return — the preemption path) and **extract** (export just
    the named tokens, keep flying — the migration path) requests. Spec-§11
    sessions are never extractable. ``imports`` is the other half: a list of
    LaneRecords whose pending instances re-enter the work stream and whose
    mid-round lanes are spliced back into the device carry on host after the
    ordinary init/refill placement — restored lanes continue bit-identically
    (PRF draws are coordinate-addressed; placement never enters one), and
    snapshot arrays are pure data operands, so no program key changes.
    """
    import jax
    import jax.numpy as jnp

    from byzantinerandomizedconsensus_tpu.backends.base import SimResult
    from byzantinerandomizedconsensus_tpu.models import session as _session_mod
    from byzantinerandomizedconsensus_tpu.obs import counters as _c

    policy = (policy or CompactionPolicy()).validate()
    if counters and isinstance(bucket, FusedBucket):
        raise _c.CountersUnsupported(
            "fused compacted lanes have no counter leg: the counter schema "
            "is a static function of the fault kind, which is lane data "
            "here (same rule as run_fused)")
    if counters and feed is not None:
        raise _c.CountersUnsupported(
            "the externally-fed lane grid has no counter leg: serving "
            "replies carry (rounds, decision) only")

    cfgs = list(cfgs)
    ids_list = list(ids_list)
    tokens = list(range(len(cfgs)))
    remaining = [len(ids) for ids in ids_list]
    rounds_out = [np.zeros(len(ids), dtype=np.int32) for ids in ids_list]
    dec_out = [np.zeros(len(ids), dtype=np.uint8) for ids in ids_list]
    total = sum(remaining)
    # Spec-§11 session bookkeeping, parallel to cfgs: slots still owed
    # (including the current one), the current slot index, and whether the
    # entry owns its feed (must session_done on final retire or reap).
    sess_left = [1] * len(cfgs)
    sess_slot = [0] * len(cfgs)
    sess_owner = [False] * len(cfgs)

    # The shared work stream: configs in input order, flattened to parallel
    # (config index, row position, instance id) arrays with a head pointer.
    # Queue order never enters any draw (spec §2 coordinates).
    if cfgs:
        work_cfg = np.concatenate([np.full(len(ids), ci, dtype=np.int32)
                                   for ci, ids in enumerate(ids_list)])
        work_pos = np.concatenate([np.arange(len(ids), dtype=np.int64)
                                   for ids in ids_list])
        work_iid = np.concatenate([np.asarray(ids, dtype=np.uint32)
                                   for ids in ids_list])
        cfg_rows = [_host_op_row(bucket, c) for c in cfgs]
        op_mat = {k: np.stack([row[k] for row in cfg_rows])
                  for k in cfg_rows[0]}  # (n_cfgs, ...) per operand
    else:
        work_cfg = np.empty(0, dtype=np.int32)
        work_pos = np.empty(0, dtype=np.int64)
        work_iid = np.empty(0, dtype=np.uint32)
        op_mat = {}

    # Round-23 restore: imported LaneRecords join the books like configs;
    # their pending instances enter the work stream as ordinary (pos, iid)
    # entries (fresh init is a pure function of (key, iid) — bit-identical
    # to never having been exported) and their mid-round lanes enter it too,
    # flagged in ``restore_map`` so the host splices the saved carry rows in
    # right after init/refill places them.
    restore_map: dict = {}  # (ci, pos) -> (record, lane row j)
    restored_lanes = 0
    import_entries: list = []  # (ci, record) — counters acc preload below
    for rec in (imports or []):
        if rec.version != _lanestate.LANESTATE_VERSION:
            raise _lanestate.LaneStateVersionError(
                f"lanestate version {rec.version!r} (this build speaks "
                f"{_lanestate.LANESTATE_VERSION})")
        cfg = rec.cfg.validate()
        ids = np.asarray(rec.ids, dtype=np.uint32)
        ci = len(cfgs)
        cfgs.append(cfg)
        ids_list.append(ids)
        tokens.append(rec.token if rec.token is not None else ci)
        rounds_out.append(np.array(rec.rounds, dtype=np.int32))
        dec_out.append(np.array(rec.decision, dtype=np.uint8))
        lane_pos = np.asarray(rec.lanes["pos"], dtype=np.int64)
        pend = list(rec.pending)
        remaining.append(len(pend) + len(lane_pos))
        sess_left.append(1)
        sess_slot.append(0)
        sess_owner.append(False)
        row = _host_op_row(bucket, cfg)
        for k in row:
            v = np.asarray(row[k])[None]
            op_mat[k] = (np.concatenate([op_mat[k], v])
                         if k in op_mat else v)
        pos_new = np.concatenate(
            [lane_pos, np.asarray([p for p, _ in pend], dtype=np.int64)])
        iid_new = np.concatenate(
            [ids[lane_pos].astype(np.uint32),
             np.asarray([i for _, i in pend], dtype=np.uint32)])
        work_cfg = np.concatenate(
            [work_cfg, np.full(len(pos_new), ci, dtype=np.int32)])
        work_pos = np.concatenate([work_pos, pos_new])
        work_iid = np.concatenate([work_iid, iid_new])
        total += len(pos_new)
        for j, p in enumerate(lane_pos):
            restore_map[(ci, int(p))] = (rec, j)
        import_entries.append((ci, rec))
        _trace.event("compaction.import", cfg_index=ci,
                     **rec.doc_summary())

    def _ingest(block=False):
        """Splice newly arrived feed items into the host work stream.
        Returns False once the feed is closed and drained."""
        nonlocal work_cfg, work_pos, work_iid, total
        items = feed.pull(block=block)
        if items is None:
            return False
        for cfg, ids, token, session in items:
            cfg = cfg.validate()
            ids = (np.asarray(backend._resolve_inst_ids(cfg, None))
                   if ids is None else np.asarray(ids))
            ci = len(cfgs)
            cfgs.append(cfg)
            ids_list.append(ids)
            tokens.append(token if token is not None else ci)
            remaining.append(len(ids))
            rounds_out.append(np.zeros(len(ids), dtype=np.int32))
            dec_out.append(np.zeros(len(ids), dtype=np.uint8))
            sess_left.append(int(session) if session else 1)
            sess_slot.append(0)
            sess_owner.append(session is not None)
            row = _host_op_row(bucket, cfg)
            for k in row:
                v = np.asarray(row[k])[None]
                op_mat[k] = (np.concatenate([op_mat[k], v])
                             if k in op_mat else v)
            work_cfg = np.concatenate(
                [work_cfg, np.full(len(ids), ci, dtype=np.int32)])
            work_pos = np.concatenate(
                [work_pos, np.arange(len(ids), dtype=np.int64)])
            work_iid = np.concatenate(
                [work_iid, np.asarray(ids, dtype=np.uint32)])
            total += len(ids)
            if len(ids) == 0:
                # Degenerate: nothing to run, so nothing to chain either —
                # reply once and release any session ownership.
                if on_retire is not None:
                    on_retire(tokens[ci], SimResult(
                        config=cfg, inst_ids=ids, rounds=rounds_out[ci],
                        decision=dec_out[ci]))
                if sess_owner[ci]:
                    feed.session_done(tokens[ci])
        return True

    if feed is not None:
        # Block for the first work item so the grid never spins empty; a
        # feed closed before any push degenerates to the offline empty run.
        _ingest(block=total == 0)

    head = 0
    if total == 0:
        results = [SimResult(config=c, inst_ids=i,
                             rounds=np.empty(0, dtype=np.int32),
                             decision=np.empty(0, dtype=np.uint8))
                   for c, i in zip(cfgs, ids_list)]
        docs = None
        if counters:
            docs = [_c.counters_doc(c, _c.finalize(c, _c.zeros(c, 0, np)),
                                    backend=backend.name) for c in cfgs]
        if control is not None:
            control.detach()
        return results, docs, {"width": 0, "segments": 0, "refills": 0,
                               "parks": 0, "parked_exit": False,
                               "exported_cfgs": 0, "exported_lanes": 0,
                               "restored_lanes": 0,
                               "device_lane_rounds": 0,
                               "useful_lane_rounds": 0, "occupancy": None,
                               "wasted_lane_fraction": None,
                               "policy": policy.doc()}

    n_counters = len(_c.counter_names(cfgs[0])) if counters else 0
    acc_out = ([np.zeros((len(ids), n_counters, 2), dtype=np.uint32)
                for ids in ids_list] if counters else None)
    if counters:
        # Imported records restore their already-retired instances' partial
        # counter totals; live lanes' accumulators splice with the carry.
        for ci, rec in import_entries:
            if rec.acc_done is not None:
                acc_out[ci][:] = np.asarray(rec.acc_done, dtype=np.uint32)

    base = policy.width or _chunk_instances(
        bucket, 1, total, backend.chunk_bytes, backend.max_chunk)
    # Feed mode pins W to the policy tier: shrinking to the momentary queue
    # length would mint per-arrival program keys and recompile at steady
    # state; offline keeps the round-11 total-shrink (small grids, small
    # programs).
    W = (lane_tier(base) if feed is not None
         else min(lane_tier(base), lane_tier(total)))

    cache = compile_cache(backend)
    seg = policy.segment
    drain_seg = (max(seg, feed.round_cap_ceiling) if feed is not None
                 else max(seg, max(int(c.round_cap) for c in cfgs)))

    def init_program():
        return cache.get(("compact-init", bucket, W, counters),
                         lambda: jax.jit(_make_init(bucket, counters)))

    def refill_program(F):
        return cache.get(("compact-refill", bucket, W, F, counters),
                         lambda: jax.jit(_make_refill(bucket, F, counters)))

    def segment_program(seg_len):
        return cache.get(("compact-seg", bucket, W, seg_len, counters),
                         lambda: jax.jit(_make_segment(bucket, seg_len,
                                                       counters)))

    # The census/cache labels of the three (four with the drain variant)
    # compiled programs, precomputed ONCE so attaching them to segment spans
    # costs nothing per trip — tools/programs.py joins these against the
    # per-program flops/bytes census for its roofline table. None when
    # tracing is off: the untraced fast path computes no label strings
    # (same discipline as backends/base.py).
    if _trace.enabled():
        lab_init = _key_label(("compact-init", bucket, W, counters))
        lab_refill = _key_label(("compact-refill", bucket, W, W, counters))
        lab_seg = _key_label(("compact-seg", bucket, W, seg, counters))
        lab_drain = _key_label(("compact-seg", bucket, W, drain_seg,
                                counters))
    else:
        lab_init = lab_refill = lab_seg = lab_drain = None

    def block(take, F):
        """(ops, iids) operand block of F rows: the next ``take`` stream
        items, padded with row-0 repeats (inert — ``n_fill`` gates them)."""
        src = np.zeros(F, dtype=np.int32)
        src[:take] = work_cfg[head:head + take]
        iids = np.zeros(F, dtype=np.uint32)
        iids[:take] = work_iid[head:head + take]
        return ({k: jnp.asarray(v[src]) for k, v in op_mat.items()},
                jnp.asarray(iids))

    owner_cfg = np.full(W, -1, dtype=np.int32)   # -1 = lane not live
    owner_pos = np.zeros(W, dtype=np.int64)
    prev_r = np.zeros(W, dtype=np.int64)
    segments = refills = 0
    device_rounds = useful_rounds = 0
    n_carry = _n_carry(counters)

    # Cancellation (round 18): config indices whose token was cancelled.
    # Their queued stream entries are dropped, their live lanes reclaimed at
    # the segment boundary (freed at the next refill), and no result is ever
    # recorded for them — survivors stay bit-identical because placement
    # never enters a draw.
    dead: set = set()
    cancelled_lanes = 0
    session_reseeds = 0

    def _reap() -> bool:
        """Process feed.cancel() marks at the segment boundary. Returns
        True when any lane or queued entry was reclaimed."""
        nonlocal work_cfg, work_pos, work_iid, total, cancelled_lanes
        changed = False
        for token in feed.pop_cancelled():
            for ci, t in enumerate(tokens):
                if t is not token or ci in dead:
                    continue
                dead.add(ci)
                tail = work_cfg[head:]
                keep = tail != ci
                dropped = int((~keep).sum())
                if dropped:
                    work_cfg = np.concatenate([work_cfg[:head], tail[keep]])
                    work_pos = np.concatenate(
                        [work_pos[:head], work_pos[head:][keep]])
                    work_iid = np.concatenate(
                        [work_iid[:head], work_iid[head:][keep]])
                    total -= dropped
                lanes = int((owner_cfg == ci).sum())
                cancelled_lanes += lanes
                owner_cfg[owner_cfg == ci] = -1
                changed = True
                if sess_owner[ci]:
                    # A cancelled session chains no further slots; release
                    # its feed ownership so a closed feed can drain.
                    feed.session_done(tokens[ci])
                _trace.event("compaction.cancel", cfg_index=ci,
                             lanes=lanes, queued_dropped=dropped)
        return changed

    def _chain_slot(ci: int) -> None:
        """The spec-§11 retire/refill seam: slot ``ci`` just retired with
        slots still owed, so splice the next slot — same config, chained
        seed — into the work stream in place. The freed lanes re-seed from
        it at the next refill without touching admission, and the seed is a
        dynamic operand so no program key changes."""
        nonlocal work_cfg, work_pos, work_iid, total, session_reseeds
        nxt = _session_mod.next_slot_config(cfgs[ci], sess_slot[ci],
                                            dec_out[ci])
        ids = ids_list[ci]
        cj = len(cfgs)
        cfgs.append(nxt)
        ids_list.append(ids)
        tokens.append(tokens[ci])
        remaining.append(len(ids))
        rounds_out.append(np.zeros(len(ids), dtype=np.int32))
        dec_out.append(np.zeros(len(ids), dtype=np.uint8))
        sess_left.append(sess_left[ci] - 1)
        sess_slot.append(sess_slot[ci] + 1)
        sess_owner.append(sess_owner[ci])
        row = _host_op_row(bucket, nxt)
        for k in row:
            op_mat[k] = np.concatenate([op_mat[k], np.asarray(row[k])[None]])
        work_cfg = np.concatenate(
            [work_cfg, np.full(len(ids), cj, dtype=np.int32)])
        work_pos = np.concatenate(
            [work_pos, np.arange(len(ids), dtype=np.int64)])
        work_iid = np.concatenate(
            [work_iid, np.asarray(ids, dtype=np.uint32)])
        total += len(ids)
        session_reseeds += 1
        _trace.event("compaction.reseed", cfg_index=cj,
                     slot=sess_slot[cj], slots_left=sess_left[cj],
                     lanes=len(ids))
        if _metrics.enabled():
            _metrics.counter(
                "brc_session_reseeds_total",
                "In-grid session slot re-seeds at the retire seam "
                "(spec §11)").inc()

    # Round-23 snapshot seam state: configs exported out of this grid behave
    # like cancelled ones from here on (no retire, no record) — their state
    # now lives in LaneRecords owned by the control's caller.
    parked_cis: set = set()
    exported_lanes = 0
    parks = 0

    def _restore_rows(carry, placed):
        """Splice saved carry rows over freshly placed lanes (host-side pure
        data movement — the restore half of the round-23 seam)."""
        nonlocal restored_lanes
        rows = []
        for w in placed:
            key = (int(owner_cfg[w]), int(owner_pos[w]))
            if key in restore_map:
                rows.append((w,) + restore_map.pop(key))
        if not rows:
            return carry
        with _trace.span("compaction.restore", lanes=len(rows)):
            host = jax.tree_util.tree_map(
                lambda a: np.array(a), jax.device_get(carry))
            r_h, st_h, setup_h, done_h = host[2], host[3], host[4], host[5]
            leaves, _treedef = jax.tree_util.tree_flatten(setup_h)
            for w, rec, j in rows:
                r_h[w] = rec.lanes["r"][j]
                for k in st_h:
                    st_h[k][w] = rec.lanes["st"][k][j]
                for li, leaf in enumerate(leaves):
                    leaf[w] = rec.lanes["setup"][li][j]
                done_h[w] = -1
                if counters and rec.lanes.get("acc") is not None:
                    host[6][w] = rec.lanes["acc"][j]
                prev_r[w] = int(r_h[w])
            carry = jax.tree_util.tree_map(jnp.asarray, host)
        restored_lanes += len(rows)
        return carry

    def _export(tokens_req=None) -> list:
        """Export every extractable config (or just ``tokens_req``'s, by
        identity) as LaneRecords: slice live lanes off a host copy of the
        carry, pull queued stream entries, and drop the config from the
        grid's books. Sessions and dead configs are never exported."""
        nonlocal work_cfg, work_pos, work_iid, total, exported_lanes
        cis = []
        for ci in range(len(cfgs)):
            if ci in dead or ci in parked_cis or remaining[ci] <= 0:
                continue
            if sess_owner[ci] or sess_left[ci] > 1 or sess_slot[ci] > 0:
                continue  # spec-§11 sessions ride one grid whole
            if tokens_req is not None and not any(
                    tokens[ci] is t for t in tokens_req):
                continue
            cis.append(ci)
        if not cis:
            return []
        records = []
        with _trace.span("compaction.snapshot", configs=len(cis)) as sp:
            host = None
            if any((owner_cfg == ci).any() for ci in cis):
                host = jax.tree_util.tree_map(
                    lambda a: np.array(a), jax.device_get(carry))
            for ci in cis:
                sel = owner_cfg == ci
                n_l = int(sel.sum())
                if n_l:
                    leaves, _ = jax.tree_util.tree_flatten(host[4])
                    lanes = {
                        "pos": owner_pos[sel].copy(),
                        "r": host[2][sel],
                        "st": {k: host[3][k][sel] for k in host[3]},
                        "setup": [leaf[sel] for leaf in leaves],
                    }
                    if counters:
                        lanes["acc"] = host[6][sel]
                else:
                    lanes = {"pos": np.empty(0, dtype=np.int64),
                             "r": np.empty(0, dtype=np.int32),
                             "st": {}, "setup": []}
                tail = work_cfg[head:]
                mask = tail == ci
                pend = list(zip(work_pos[head:][mask].tolist(),
                                work_iid[head:][mask].tolist()))
                if mask.any():
                    keep = ~mask
                    work_cfg = np.concatenate([work_cfg[:head], tail[keep]])
                    work_pos = np.concatenate(
                        [work_pos[:head], work_pos[head:][keep]])
                    work_iid = np.concatenate(
                        [work_iid[:head], work_iid[head:][keep]])
                    total -= int(mask.sum())
                records.append(_lanestate.LaneRecord(
                    version=_lanestate.LANESTATE_VERSION,
                    cfg=cfgs[ci],
                    ids=np.asarray(ids_list[ci], dtype=np.uint32),
                    rounds=rounds_out[ci].copy(),
                    decision=dec_out[ci].copy(),
                    remaining=len(pend) + n_l,
                    pending=pend,
                    lanes=lanes,
                    token=tokens[ci],
                    acc_done=(np.array(acc_out[ci]) if counters else None)))
                parked_cis.add(ci)
                owner_cfg[sel] = -1
                exported_lanes += n_l
            sp["lanes"] = sum(r.lane_count() for r in records)
            sp["pending"] = sum(len(r.pending) for r in records)
        return records

    def _service_control() -> bool:
        """Drain the control mailbox at this boundary. True = a park
        emptied the grid, so run_bucket should return now."""
        nonlocal parks
        stop = False
        while True:
            req = control._pop_request()
            if req is None:
                return stop
            recs = _export(req.tokens)
            if req.kind == "park":
                parks += 1
                control._deliver_park(req, recs)
                if not (owner_cfg >= 0).any():
                    stop = True
            else:
                req.deliver(recs)

    # Fill the whole grid, then alternate segment dispatches with
    # compaction+refill dispatches whenever the retired fraction crosses the
    # policy threshold (always when the grid fully drains).
    take = min(W, total)
    with _trace.span("compaction.init", width=W, fill=take,
                     queued=total - take, program=lab_init):
        ops_b, iids_b = block(take, W)
        carry = init_program()(ops_b, iids_b, jnp.int32(take))
    owner_cfg[:take] = work_cfg[:take]
    owner_pos[:take] = work_pos[:take]
    head = take
    carry = _restore_rows(carry, range(take))

    parked_exit = False
    while True:
        # The per-trip wall the round-11 anatomy reconstructed by hand is
        # now this span's duration; drain trips get their own kind so the
        # straggler tail is directly queryable in the digest. An open feed
        # suppresses drain mode: short segments keep the grid responsive to
        # arrivals; the long drain dispatch waits for close().
        drain = head >= total and (feed is None or feed.closed())
        with _trace.span("compaction.drain" if drain
                         else "compaction.segment",
                         width=W, queued=total - head,
                         program=lab_drain if drain else lab_seg) as sp:
            fn = segment_program(drain_seg if drain else seg)
            out = fn(*carry)
            carry = out[:n_carry]
            fetch = jax.device_get(
                (carry[2],) + out[n_carry:n_carry + 3]
                + ((carry[6],) if counters else ()))
            r_h, rounds_h, dec_h, fin_h = fetch[:4]
            segments += 1
            trips = np.asarray(r_h, dtype=np.int64) - prev_r
            device_rounds += int(trips.max()) * W
            useful_rounds += int(trips.sum())
            prev_r = np.asarray(r_h, dtype=np.int64)
            retire = np.asarray(fin_h, dtype=bool) & (owner_cfg >= 0)
            for ci in np.unique(owner_cfg[retire]):
                ci = int(ci)
                if ci in dead:
                    continue  # cancelled: reclaim silently, never record
                sel = retire & (owner_cfg == ci)
                rows = owner_pos[sel]
                rounds_out[ci][rows] = rounds_h[sel]
                dec_out[ci][rows] = dec_h[sel]
                if counters:
                    acc_out[ci][rows] = fetch[4][sel]
                remaining[ci] -= int(sel.sum())
                if remaining[ci] == 0:
                    if on_retire is not None:
                        # Stream the finished slot out NOW — the serving
                        # loop's reply path; the grid keeps flying. Sessions
                        # reply once per slot (same token every time).
                        on_retire(tokens[ci], SimResult(
                            config=cfgs[ci], inst_ids=ids_list[ci],
                            rounds=rounds_out[ci], decision=dec_out[ci]))
                    if sess_left[ci] > 1:
                        # Spec §11: the retiring slot's decision seeds the
                        # next slot in place — no admission round-trip.
                        _chain_slot(ci)
                    elif sess_owner[ci] and feed is not None:
                        feed.session_done(tokens[ci])
            owner_cfg[retire] = -1
            live = owner_cfg >= 0
            free = W - int(live.sum())
            sp["trip_max"] = int(trips.max())
            sp["useful_trips"] = int(trips.sum())
            sp["retired"] = int(retire.sum())
            sp["live"] = W - free
        if _metrics.enabled():
            # Live consensus health off the host-fetched arrays (nothing
            # feeds back into the grid math — bit-identity is structural):
            # the rounds-to-decision histogram is the protocol's headline
            # distribution as a stream; decision==2 marks undecided-at-cap.
            _metrics.counter("brc_compaction_segments_total",
                             "Segment dispatches across all grids").inc()
            _metrics.gauge("brc_compaction_live_lanes",
                           "Lanes holding live instances after the last "
                           "segment").set(W - free)
            if device_rounds:
                _metrics.gauge("brc_compaction_occupancy",
                               "Cumulative useful/device lane-round "
                               "ratio").set(
                                   round(useful_rounds / device_rounds, 6))
            n_ret = int(retire.sum())
            if n_ret:
                _metrics.histogram(
                    "brc_consensus_rounds",
                    "Ben-Or rounds to decision per retired instance",
                    buckets=_metrics.ROUNDS_BUCKETS).observe_many(
                        np.asarray(rounds_h)[retire].tolist())
                decided = int((np.asarray(dec_h)[retire] != 2).sum())
                if decided:
                    _metrics.counter("brc_consensus_decided_total",
                                     "Instances retired with a "
                                     "decision").inc(decided)
                if n_ret - decided:
                    _metrics.counter("brc_consensus_undecided_total",
                                     "Instances retired undecided at "
                                     "round_cap").inc(n_ret - decided)
        if progress is not None:
            progress(f"compaction segment {segments}: {W - free}/{W} live, "
                     f"{total - head} queued")
        if feed is not None:
            _ingest()  # arrivals during the dispatch join the queue
            if _reap():  # cancels land at the same boundary
                live = owner_cfg >= 0
                free = W - int(live.sum())
        if control is not None:
            if _service_control():
                parked_exit = True
                break
            live = owner_cfg >= 0
            free = W - int(live.sum())
        if head >= total and not live.any():
            # Grid idle. Offline that is the end; a live feed parks here
            # (blocking pull) until new work arrives or the feed closes.
            if feed is None or not _ingest(block=True):
                break
        if head >= total:
            continue  # queue dry: drain the stragglers, no more refills
        if free >= W * policy.refill_threshold or not live.any():
            with _trace.span("compaction.refill", width=W,
                             program=lab_refill) as sp:
                perm = np.concatenate(
                    [np.flatnonzero(live),
                     np.flatnonzero(~live)]).astype(np.int32)
                n_keep = W - free
                take = min(free, total - head)
                # The fresh block is always W rows (n_fill gates the live
                # ones): ONE refill program per bucket, so the warm-up
                # compiles exactly the timed program set (utils/timing.py
                # discipline).
                ops_b, iids_b = block(take, W)
                carry = refill_program(W)(
                    jnp.asarray(perm), jnp.int32(n_keep), jnp.int32(take),
                    ops_b, iids_b, *carry)
                owner_cfg = np.concatenate(
                    [owner_cfg[perm[:n_keep]],
                     np.full(free, -1, dtype=np.int32)])
                owner_pos = np.concatenate(
                    [owner_pos[perm[:n_keep]],
                     np.zeros(free, dtype=np.int64)])
                prev_r = np.concatenate(
                    [prev_r[perm[:n_keep]], np.zeros(free, dtype=np.int64)])
                sl = slice(n_keep, n_keep + take)
                owner_cfg[sl] = work_cfg[head:head + take]
                owner_pos[sl] = work_pos[head:head + take]
                head += take
                refills += 1
                sp["keep"] = n_keep
                sp["take"] = take
                sp["queued"] = total - head
            carry = _restore_rows(carry, range(n_keep, n_keep + take))
            if _metrics.enabled():
                _metrics.counter("brc_compaction_refills_total",
                                 "Compaction+refill dispatches").inc()
                _metrics.gauge("brc_compaction_refill_depth",
                               "Work-stream items still queued after the "
                               "last refill").set(total - head)

    if control is not None:
        # Deliver [] to any still-queued control request: the grid is gone
        # (drained or parked), so nothing more is extractable from it.
        control.detach()

    results = [SimResult(config=c, inst_ids=i, rounds=r, decision=d)
               for c, i, r, d in zip(cfgs, ids_list, rounds_out, dec_out)]
    docs = None
    if counters:
        docs = [_c.counters_doc(c, _c.finalize(c, rows),
                                backend=backend.name)
                for c, rows in zip(cfgs, acc_out)]
        if _metrics.enabled():
            # fault-attribution lives only in the schema-v2 counter totals
            # (the feed/fused paths have no counter leg — CountersUnsupported
            # above), so the silenced stream updates per counters-enabled run
            silenced = sum(int(v) for d in docs
                           for k, v in d["totals"].items()
                           if k.startswith("fault_silenced@"))
            if silenced:
                _metrics.counter("brc_consensus_fault_silenced_total",
                                 "Messages silenced by faulty senders "
                                 "(schema-v2 counter totals)").inc(silenced)
    stats = {
        "width": W,
        "segments": segments,
        "refills": refills,
        "cancelled_cfgs": len(dead),
        "cancelled_lanes": cancelled_lanes,
        "session_reseeds": session_reseeds,
        "parks": parks,
        "parked_exit": parked_exit,
        "exported_cfgs": len(parked_cis),
        "exported_lanes": exported_lanes,
        "restored_lanes": restored_lanes,
        "device_lane_rounds": device_rounds,
        "useful_lane_rounds": useful_rounds,
        "occupancy": (round(useful_rounds / device_rounds, 4)
                      if device_rounds else None),
        "wasted_lane_fraction": (round(1.0 - useful_rounds / device_rounds, 4)
                                 if device_rounds else None),
        "policy": policy.doc(),
    }
    return results, docs, stats


def merge_stats(per_bucket: Sequence[dict]) -> dict:
    """Fold per-bucket compaction stats into the one run-record block
    (obs/record.py schema v1.2 ``compaction``)."""
    dev = sum(s["device_lane_rounds"] for s in per_bucket)
    use = sum(s["useful_lane_rounds"] for s in per_bucket)
    return {
        "buckets": len(per_bucket),
        "segments": sum(s["segments"] for s in per_bucket),
        "refills": sum(s["refills"] for s in per_bucket),
        "device_lane_rounds": dev,
        "useful_lane_rounds": use,
        "occupancy": round(use / dev, 4) if dev else None,
        "wasted_lane_fraction": round(1.0 - use / dev, 4) if dev else None,
        "policy": per_bucket[0]["policy"] if per_bucket else None,
    }
