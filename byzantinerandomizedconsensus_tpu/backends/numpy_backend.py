"""Numpy vectorized backend — the models/ round logic run eagerly on host.

Shares the array-level round bodies with the JAX backend (xp=numpy vs xp=jax.numpy),
which triangulates the bit-match: ``cpu`` (independent per-replica oracle) vs
``numpy`` checks the vectorized *logic*; ``numpy`` vs ``jax`` checks the *compiler*
path (jit, XLA sort, dtype semantics).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from byzantinerandomizedconsensus_tpu.backends.base import SimResult, SimulatorBackend
from byzantinerandomizedconsensus_tpu.config import SimConfig
from byzantinerandomizedconsensus_tpu.models import benor, bracha, state as state_mod
from byzantinerandomizedconsensus_tpu.models.adversaries import AdversaryModel


class NumpyBackend(SimulatorBackend):
    name = "numpy"

    def __init__(self, chunk_bytes: int = 1 << 28):
        self.chunk_bytes = chunk_bytes

    def _chunk_size(self, cfg: SimConfig) -> int:
        if cfg.count_level:
            # O(B·n) state only (spec §4b/§4b-v2): ~16 live int32 per-lane planes
            # (class counts, picks, carry) — keep honoring the memory cap.
            return max(1, min(1 << 14, self.chunk_bytes // (cfg.n * 64)))
        per_inst = cfg.n * cfg.n * 4 * 4  # ~4 live (B,n,n) u32-sized transients
        return max(1, min(1 << 14, self.chunk_bytes // per_inst))

    def run(self, cfg: SimConfig, inst_ids: Optional[np.ndarray] = None) -> SimResult:
        res, _, _, _ = self._run_impl(cfg, inst_ids, collect_state=False)
        return res

    def run_with_counters(self, cfg: SimConfig,
                          inst_ids: Optional[np.ndarray] = None):
        """``run`` plus the protocol-counter totals (obs/counters.py).

        The counter leg is a pure side output of the shared round bodies
        (``obs=`` hook) folded under the same ``done_at < 0`` activity mask
        that gates state updates, so the (rounds, decision) arrays are
        bit-identical to ``run``'s — asserted by tests/test_obs_counters.py.
        """
        from byzantinerandomizedconsensus_tpu.obs import counters as _counters

        res, _, _, rows = self._run_impl(cfg, inst_ids, collect_state=False,
                                         counters=True)
        totals = _counters.finalize(res.config, rows)
        return res, _counters.counters_doc(res.config, totals, backend=self.name)

    def run_with_adversary(self, cfg: SimConfig, adv: AdversaryModel,
                           inst_ids: Optional[np.ndarray] = None) -> SimResult:
        """``run`` with a caller-supplied adversary model.

        Experiment surface (tools/schedstrength.py): lets measurement harnesses
        swap in AdversaryModel subclasses (e.g. alternative scheduling-bias
        rules) without forking the round loop. Product configs never need this
        — ``run`` always uses the spec §6 model."""
        res, _, _, _ = self._run_impl(cfg, inst_ids, collect_state=False, adv=adv)
        return res

    def run_with_state(self, cfg: SimConfig,
                       inst_ids: Optional[np.ndarray] = None):
        """``run`` plus the FULL final per-replica state and the faulty mask.

        Returns ``(SimResult, state, faulty)`` where ``state`` is the
        models/state.py dict with every array concatenated to ``(B, n)`` and
        ``faulty`` is the (B, n) bool mask. This is the direct
        protocol-property surface (VERDICT r2 #2): ``SimResult.decision``
        deliberately collapses an instance to the lowest-indexed correct
        replica's value (models/state.py:extract_decision), which *assumes*
        Agreement — at-scale tests must instead assert Agreement/Validity
        over every replica of the state the product path actually computed.
        """
        return self._run_impl(cfg, inst_ids, collect_state=True)[:3]

    def _run_impl(self, cfg: SimConfig, inst_ids, collect_state: bool, adv=None,
                  counters: bool = False):
        if counters:
            from byzantinerandomizedconsensus_tpu.obs import counters as _c
        cfg = cfg.validate()
        ids = self._resolve_inst_ids(cfg, inst_ids)
        round_body = benor.round_body if cfg.protocol == "benor" else bracha.round_body
        if adv is None:
            adv = AdversaryModel(cfg)
        chunk = self._chunk_size(cfg)

        rounds_out = np.full(len(ids), cfg.round_cap, dtype=np.int32)
        decision_out = np.full(len(ids), 2, dtype=np.uint8)
        states, faulties, counter_rows = [], [], []

        for lo in range(0, len(ids), chunk):
            sl = slice(lo, min(lo + chunk, len(ids)))
            cids = ids[sl]
            setup = adv.setup(cfg.seed, cids, xp=np)
            st = state_mod.init_state(cfg, cfg.seed, cids, xp=np)
            faulty = setup["faulty"]
            done_at = np.full(len(cids), -1, dtype=np.int32)
            acc = _c.zeros(cfg, len(cids), np) if counters else None
            for r in range(cfg.round_cap):
                if np.all(done_at >= 0):
                    break
                obs = {} if counters else None
                st = round_body(cfg, cfg.seed, cids, r, st, adv, setup, xp=np,
                                obs=obs)
                if counters:
                    acc = _c.accumulate(acc, _c.round_increments(cfg, obs, np),
                                        done_at < 0, cfg, np)
                done_now = state_mod.all_correct_decided(st, faulty, xp=np)
                done_at = np.where((done_at < 0) & done_now, r + 1, done_at)
            done = done_at >= 0
            rounds_out[sl] = np.where(done, done_at, cfg.round_cap)
            decision_out[sl] = state_mod.extract_decision(st, faulty, done, xp=np)
            if collect_state:
                states.append(st)
                faulties.append(faulty)
            if counters:
                counter_rows.append(acc)

        res = SimResult(config=cfg, inst_ids=ids, rounds=rounds_out, decision=decision_out)
        rows = None
        if counters:
            rows = (np.concatenate(counter_rows) if counter_rows
                    else _c.zeros(cfg, 0, np))
        if not collect_state:
            return res, None, None, rows
        if not states:  # empty inst_ids: mirror run()'s empty-result support
            empty = state_mod.init_state(cfg, cfg.seed, ids, xp=np)
            return res, empty, np.zeros((0, cfg.n), dtype=bool), rows
        state = {k: np.concatenate([s[k] for s in states]) for k in states[0]}
        return res, state, np.concatenate(faulties), rows
