"""The SimulatorBackend seam (BASELINE.json:5; SURVEY.md §1).

The front-end (Replica/Adversary/Network object model, CLI, metrics) talks to a
backend through one call: ``run(cfg, inst_ids) -> SimResult``. The CPU oracle loop is
the default backend; the JAX/TPU backend plugs in behind the same boundary. Because
instance ``i``'s trajectory depends only on ``(cfg, seed, i)`` (spec §1), ``inst_ids``
may be any subset — the sampled bit-match harness relies on this.
"""

from __future__ import annotations

import abc
import dataclasses
import time
from typing import Callable, Optional

import numpy as np

from byzantinerandomizedconsensus_tpu.config import SimConfig


def check_pallas_delivery(cfg: SimConfig) -> None:
    """Reject kernel='pallas' for deliveries the Pallas kernels don't
    implement — fail loudly rather than fall back silently (ADVICE r1).
    Shared by JaxBackend and JaxShardedBackend so the guard can't drift."""
    if cfg.delivery in ("urn2", "urn3"):
        raise ValueError(
            "kernel='pallas' implements the §4b sampler only; "
            f"delivery={cfg.delivery!r} supports kernel='xla'")


@dataclasses.dataclass
class SimResult:
    """Per-instance outputs (spec §1): the bit-match surface."""

    config: SimConfig
    inst_ids: np.ndarray   # (I,) int64 — which instances these rows are
    rounds: np.ndarray     # (I,) int32 — rounds to termination (== round_cap if capped)
    decision: np.ndarray   # (I,) uint8 — 0/1 decided value, 2 = undecided (overflow)
    wall_s: float = 0.0

    @property
    def instances_per_sec(self) -> float:
        return len(self.inst_ids) / self.wall_s if self.wall_s > 0 else float("inf")


class SimulatorBackend(abc.ABC):
    name: str = "?"

    #: True when the first run at a shape pays a compile (jit backends); the
    #: timing helper (utils/timing.py) uses this to decide whether a warm-up
    #: run is needed before the timed window (ADVICE r3: the numpy backend has
    #: a ``_chunk_size`` but nothing to compile and must not pay a warm-up).
    needs_warmup: bool = False

    @abc.abstractmethod
    def run(self, cfg: SimConfig, inst_ids: Optional[np.ndarray] = None) -> SimResult:
        """Simulate the given instances (default: all of them) to termination."""

    def run_with_counters(self, cfg: SimConfig,
                          inst_ids: Optional[np.ndarray] = None):
        """``run`` plus the protocol-counter side output (obs/counters.py):
        returns ``(SimResult, counters_doc)``. The counter leg is a pure side
        output — the result arrays are bit-identical to ``run``'s.

        Default: unsupported (the native core's ABI has no counter channel;
        meshes and custom kernels don't thread the side channel). Raises
        :class:`~byzantinerandomizedconsensus_tpu.obs.counters.CountersUnsupported`
        so record builders can degrade to an honest ``supported: false``
        block (obs/record.collect_counters) instead of dying.
        """
        from byzantinerandomizedconsensus_tpu.obs.counters import (
            CountersUnsupported)

        raise CountersUnsupported(
            f"backend {self.name!r} has no protocol-counter channel")

    @staticmethod
    def _run_chunked(fn, ids: np.ndarray, chunk: int, extra_args=()):
        """Run ``fn(chunk_ids) -> (rounds, decision)`` over fixed-size chunks.

        The tail chunk is padded (with a repeated last id) to the compiled shape so
        exactly one program per config is compiled; padded rows are discarded.
        All chunks are dispatched before any result is fetched — JAX's async
        dispatch then queues them back-to-back on the device instead of
        round-tripping through the host after every chunk. The results are then
        pulled with ONE batched ``jax.device_get`` over all chunks: with a
        tunnelled TPU each host round-trip costs ~0.1-0.2 s, so per-chunk
        fetches would dominate once the kernels themselves are fast. (A
        device-side concatenate would also work but costs a multi-second XLA
        compile of the throwaway concat program on first use.)
        """
        rounds_out, decision_out = SimulatorBackend._run_chunked_multi(
            fn, ids, chunk, extra_args)[:2]
        return rounds_out, decision_out

    @staticmethod
    def _run_chunked_multi(fn, ids: np.ndarray, chunk: int,
                           extra_args=(), n_extra: int = 0) -> tuple:
        """:meth:`_run_chunked` generalized to variable output arity: the
        chunk fn returns ``(rounds, decision, *extras)`` with ``n_extra``
        extra leading-batch-axis outputs (e.g. the counter accumulator).
        One copy of the dispatch / batched-fetch / tail-padding-discard
        invariant serves the product and counter paths alike."""
        import jax

        pending = SimulatorBackend._dispatch_chunks(fn, ids, chunk, extra_args)
        fetched = jax.device_get(pending)
        if not fetched:  # empty inst_ids: keep run()'s empty-result support
            return (np.empty(0, dtype=np.int32),
                    np.empty(0, dtype=np.uint8)) + (None,) * n_extra
        outs = []
        for pos in range(len(fetched[0])):
            parts = []
            for i, ch in enumerate(fetched):
                lo = i * chunk
                hi = min(lo + chunk, len(ids))
                parts.append(np.asarray(ch[pos])[: hi - lo])
            outs.append(np.concatenate(parts))
        outs[0] = outs[0].astype(np.int32, copy=False)
        outs[1] = outs[1].astype(np.uint8, copy=False)
        return tuple(outs)

    @staticmethod
    def _dispatch_chunks(fn, ids: np.ndarray, chunk: int, extra_args=()) -> list:
        """Async-dispatch ``fn`` over fixed-size chunks; no results fetched.

        The tail chunk is padded (repeated last id) to the compiled shape so
        exactly one program per config is compiled; callers discard padded
        rows. This is *the* dispatch loop of the product path — profiling
        tools (tools/roofline.py) call it too, so what they measure is what
        ships."""
        import jax.numpy as jnp

        pending = []
        for lo in range(0, len(ids), chunk):
            hi = min(lo + chunk, len(ids))
            cids = ids[lo:hi]
            if len(cids) < chunk:
                cids = np.concatenate([cids, np.full(chunk - len(cids), cids[-1])])
            pending.append(fn(jnp.asarray(cids, dtype=jnp.uint32), *extra_args))
        return pending

    @staticmethod
    def _resolve_inst_ids(cfg: SimConfig, inst_ids) -> np.ndarray:
        if inst_ids is None:
            return np.arange(cfg.instances, dtype=np.int64)
        ids = np.asarray(inst_ids, dtype=np.int64)
        if ids.size and (ids.min() < 0 or ids.max() >= cfg.instances):
            raise ValueError("inst_ids out of range for config")
        return ids

    def timed_run(self, cfg: SimConfig, inst_ids=None) -> SimResult:
        t0 = time.perf_counter()
        res = self.run(cfg, inst_ids)
        res.wall_s = time.perf_counter() - t0
        return res


class JitChunkedBackend(SimulatorBackend):
    """Shared scaffolding for jit-compiled chunked backends (jax, jax_sharded):
    per-config compiled-function cache, chunk sizing/clamping, chunked execution,
    and SimResult assembly. Subclasses provide ``_make_fn`` / ``_chunk_size`` and
    may override ``_check_config`` / ``_clamp_chunk`` / ``_device_ctx``."""

    #: The per-step Pallas kernel ("pallas") bakes concrete PRF key words
    #: in-kernel; everything else — including the fused round kernel, whose
    #: ABI v6 key plane is an operand — takes the key dynamically so one
    #: program serves every seed.
    kernel: str = "xla"

    needs_warmup = True  # first run at a shape compiles an XLA program

    def __init__(self, chunk_bytes: int, max_chunk: int):
        self.chunk_bytes = chunk_bytes
        self.max_chunk = max_chunk
        self._compiled: dict = {}

    def _cache_key(self, cfg: SimConfig) -> SimConfig:
        if self.kernel == "pallas":
            return cfg
        if self.kernel == "fused":
            # The fused program is additionally request-size-independent:
            # cfg.instances only bounds id resolution (nothing under
            # models/ or ops/ reads it) and the dispatch shape is the
            # power-of-two chunk clamp, so one program serves every
            # request size in a bin — the serve path's steady state.
            return dataclasses.replace(cfg, seed=0, instances=1)
        return dataclasses.replace(cfg, seed=0)

    def _extra_args(self, cfg: SimConfig) -> tuple:
        if self.kernel == "pallas":
            return ()
        import jax.numpy as jnp

        from byzantinerandomizedconsensus_tpu.ops import prf

        return (jnp.asarray(prf.seed_key(cfg.seed), dtype=jnp.uint32),)

    def _make_fn(self, cfg: SimConfig):
        raise NotImplementedError

    def _chunk_size(self, cfg: SimConfig) -> int:
        raise NotImplementedError

    def _check_config(self, cfg: SimConfig) -> None:
        pass

    def _clamp_chunk(self, cfg: SimConfig, chunk: int) -> int:
        return chunk

    def _device_ctx(self):
        import contextlib

        return contextlib.nullcontext()

    def _census_label(self, cfg: SimConfig) -> str:
        """The per-config census key. Non-default kernels append ``/k<name>``
        so an A/B census (xla vs fused over the same config) keeps distinct
        entries — additive: every existing kernel="xla" label is unchanged,
        so the committed r13 census keys still match."""
        from byzantinerandomizedconsensus_tpu.obs import programs as _programs

        label = _programs.config_label(self._cache_key(cfg))
        if self.kernel != "xla":
            label += f"/k{self.kernel}"
        return label

    def _fn(self, cfg: SimConfig):
        key = self._cache_key(cfg)
        if key not in self._compiled:
            fn = self._make_fn(key)
            # The per-config half of the compiled-program census
            # (obs/programs.py, opt-in): the first call AOT-compiles and
            # records the program's cost/memory/fingerprint anatomy — the
            # headline bench path is a per-config program, so BENCH_PROGRAMS
            # coverage needs this seam as well as the bucket CompileCache.
            # Strictly inert when the census is off (fn returned unchanged).
            from byzantinerandomizedconsensus_tpu.obs import (
                programs as _programs)

            if _programs.enabled():
                fn = _programs.instrument(self._census_label(cfg), fn)
            self._compiled[key] = fn
        return self._compiled[key]

    def compile_probe(self) -> int:
        """Programs compiled through the per-config dispatch path: jit-cache
        entries summed over the compiled-fn cache, so a shape recompile
        counts too. The serve loadgen's zero-steady-state-recompile pin
        reads this probe's delta for non-xla kernels, whose requests go
        through direct dispatch and never touch the bucket CompileCache."""
        total = 0
        for fn in self._compiled.values():
            size = getattr(fn, "_cache_size", None)
            if callable(size):
                total += int(size())
        return total

    def run(self, cfg: SimConfig, inst_ids: Optional[np.ndarray] = None) -> SimResult:
        from byzantinerandomizedconsensus_tpu.obs import trace as _trace

        cfg = cfg.validate()
        self._check_config(cfg)
        ids = self._resolve_inst_ids(cfg, inst_ids)
        chunk = self._clamp_chunk(cfg, min(self._chunk_size(cfg), max(1, len(ids))))
        fn = self._fn(cfg)
        # The host-telemetry seam for the per-config path (obs/trace.py):
        # one span per run covering dispatch + the batched fetch, so a
        # BENCH_TRACE capture shows the product path's chunk anatomy too.
        with self._device_ctx(), \
                _trace.span("backend.run", backend=self.name, n=cfg.n,
                            instances=int(len(ids)), chunk=int(chunk),
                            dispatches=-(-len(ids) // chunk)
                            if len(ids) else 0) as sp:
            if _trace.enabled():
                # The per-config census key (obs/programs.py), attached
                # post-hoc so the untraced fast path never computes it —
                # the roofline join (tools/programs.py) matches it against
                # the census like the bucket paths' dispatch spans.
                sp["program"] = self._census_label(cfg)
            rounds_out, decision_out = self._run_chunked(
                fn, ids, chunk, self._extra_args(cfg))
        return SimResult(config=cfg, inst_ids=ids, rounds=rounds_out, decision=decision_out)


_REGISTRY: dict[str, Callable[..., SimulatorBackend]] = {}
_INSTANCES: dict[str, SimulatorBackend] = {}


def register_backend(name: str, factory: Callable[..., SimulatorBackend]) -> None:
    """``factory`` takes no arguments, or one string argument if the backend
    accepts a ``name:param`` suffix (see :func:`get_backend`)."""
    _REGISTRY[name] = factory


def get_backend(name: str) -> SimulatorBackend:
    """Look up a backend; ``name`` may carry a parameter suffix, e.g.
    ``jax_sharded:4`` → the ``jax_sharded`` factory called with ``"4"``."""
    if name not in _INSTANCES:
        base, _, param = name.partition(":")
        if base not in _REGISTRY:
            raise KeyError(f"unknown backend {name!r}; known: {sorted(_REGISTRY)}")
        factory = _REGISTRY[base]
        if param:
            try:
                _INSTANCES[name] = factory(param)
            except TypeError as e:
                raise ValueError(
                    f"backend {base!r} does not take a {name.partition(':')[2]!r} "
                    f"parameter ({e})"
                ) from None
        else:
            _INSTANCES[name] = factory()
    return _INSTANCES[name]


def available_backends() -> list[str]:
    return sorted(_REGISTRY)
