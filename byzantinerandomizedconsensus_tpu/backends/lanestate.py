"""Serializable per-lane state — the snapshot/restore seam (round 23).

The compacted lane grid (backends/compaction.py) carries, per lane, a tiny
pure function of coordinates: the PRF key, the lane's own round counter, the
packed replica state word(s), the adversary/fault setup products, and — when
counters are on — the per-lane counter accumulator. Because the PRF addresses
every draw by ``(key, instance, round, step, ...)`` (spec §2) and never by
placement, that carry row *is* the instance's entire future: freeze it at a
segment boundary, thaw it in any other grid of the same bucket, and the
instance continues bit-identically (tests/test_lanestate.py proves this
across the fault × adversary × delivery grid, mid-crash-window and
mid-partition included).

This module gives that fact a wire format:

- :class:`LaneRecord` — ONE config's extractable state: the config itself,
  its instance ids, the partial results already retired, the queued
  ``(pos, iid)`` entries not yet dispatched, and the mid-round live-lane
  arrays sliced from the device carry. Versioned like the r20 fused state
  word (``LANESTATE_VERSION``; :func:`LaneRecord.from_doc` rejects a
  mismatch by name — :class:`LaneStateVersionError`).
- :meth:`LaneRecord.to_doc` / :meth:`LaneRecord.from_doc` — a JSON-safe
  array codec so serialized lanes ride the fleet worker's JSON-lines
  protocol (serve/worker.py ``export``/``import`` ops) unchanged.
- :class:`LaneControl` — the thread-safe mailbox through which a scheduler
  asks a flying ``run_bucket`` to **park** (export everything and return —
  serve/server.py preemption) or **extract** specific tokens (keep flying —
  serve/fleet.py lane-level migration). Requests are serviced only at
  segment boundaries, so the records are always boundary-consistent.

Snapshot records are arrival-free *data* operands: restore re-enters lanes
through the ordinary init/refill programs and splices the saved rows in on
host, so no program key ever changes — the zero-steady-state-recompile pin
survives preemption and migration untouched.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional

import numpy as np

from byzantinerandomizedconsensus_tpu.config import SimConfig

#: The lane-state schema version. Bump whenever the carry row layout changes
#: (st keys, setup leaf order, acc shape) — a restore across versions would
#: silently corrupt draws, so :func:`LaneRecord.from_doc` rejects by name.
LANESTATE_VERSION = 1


class LaneStateVersionError(ValueError):
    """A serialized lane record speaks a different schema version."""


def _nd_doc(a) -> dict:
    a = np.asarray(a)
    return {"dtype": str(a.dtype), "shape": list(a.shape),
            "data": a.reshape(-1).tolist()}


def _nd_undoc(d) -> np.ndarray:
    return np.asarray(d["data"], dtype=np.dtype(d["dtype"])).reshape(
        tuple(d["shape"]))


def _tree_doc(obj):
    """JSON-encode a pytree of numpy arrays (dict / list / ndarray)."""
    if isinstance(obj, dict):
        return {"kind": "dict",
                "items": {k: _tree_doc(v) for k, v in obj.items()}}
    if isinstance(obj, (list, tuple)):
        return {"kind": "list", "items": [_tree_doc(v) for v in obj]}
    return {"kind": "nd", **_nd_doc(obj)}


def _tree_undoc(doc):
    kind = doc.get("kind")
    if kind == "dict":
        return {k: _tree_undoc(v) for k, v in doc["items"].items()}
    if kind == "list":
        return [_tree_undoc(v) for v in doc["items"]]
    return _nd_undoc(doc)


@dataclasses.dataclass
class LaneRecord:
    """One config's serialized lane state, captured at a segment boundary.

    ``lanes`` is the mid-round surface: parallel arrays over the config's
    live lanes at capture time — ``pos`` (row position in the config's
    instance list), ``r`` (per-lane round counter), ``st`` (dict of packed
    replica-state rows, models/state.py layout), ``setup`` (the adversary
    setup pytree's leaves, flattened in ``jax.tree_util`` order — the
    structure is a pure function of the bucket, so leaves alone round-trip),
    and optionally ``acc`` (the counter accumulator rows).

    ``pending`` is the not-yet-dispatched surface: ``(pos, iid)`` pairs that
    were still queued in the host work stream. A restore re-derives their
    lanes from scratch — initial state is a pure function of ``(key, iid)``,
    so fresh init is bit-identical to having never been exported.

    ``rounds`` / ``decision`` hold the partial results of instances that
    already retired before capture; ``remaining`` counts what the record
    still owes (``len(pending) + len(lanes["pos"])``).

    ``token`` is the in-process retire token (e.g. the ServeRequest). It is
    deliberately NOT serialized — across a process boundary the importer
    supplies its own token.
    """

    version: int
    cfg: SimConfig
    ids: np.ndarray
    rounds: np.ndarray
    decision: np.ndarray
    remaining: int
    pending: list  # [(pos, iid), ...]
    lanes: dict    # {"pos", "r", "st": {...}, "setup": [leaves], "acc"?}
    token: object = None
    #: Counters-mode only: the partial per-instance counter accumulator
    #: ``(len(ids), n_counters, 2)`` for instances retired before capture
    #: (live lanes' accumulators ride ``lanes["acc"]`` instead).
    acc_done: Optional[np.ndarray] = None

    def lane_count(self) -> int:
        return int(np.asarray(self.lanes["pos"]).shape[0])

    def doc_summary(self) -> dict:
        """The trace/metrics-facing shape of this record (no arrays)."""
        return {"version": self.version, "instances": len(self.ids),
                "remaining": self.remaining, "pending": len(self.pending),
                "mid_round_lanes": self.lane_count()}

    def to_doc(self) -> dict:
        """JSON-safe document (fleet worker protocol). ``token`` is NOT
        serialized — the importer owns request identity."""
        lanes = {
            "pos": _nd_doc(self.lanes["pos"]),
            "r": _nd_doc(self.lanes["r"]),
            "st": {k: _nd_doc(v) for k, v in self.lanes["st"].items()},
            "setup": [_tree_doc(leaf) for leaf in self.lanes["setup"]],
        }
        if self.lanes.get("acc") is not None:
            lanes["acc"] = _nd_doc(self.lanes["acc"])
        doc = {
            "version": int(self.version),
            "cfg": dataclasses.asdict(self.cfg),
            "ids": _nd_doc(self.ids),
            "rounds": _nd_doc(self.rounds),
            "decision": _nd_doc(self.decision),
            "remaining": int(self.remaining),
            "pending": [[int(p), int(i)] for p, i in self.pending],
            "lanes": lanes,
        }
        if self.acc_done is not None:
            doc["acc_done"] = _nd_doc(self.acc_done)
        return doc

    @classmethod
    def from_doc(cls, doc: dict, token=None) -> "LaneRecord":
        ver = doc.get("version")
        if ver != LANESTATE_VERSION:
            raise LaneStateVersionError(
                f"lanestate version {ver!r} (this build speaks "
                f"{LANESTATE_VERSION}): refusing to restore — a cross-"
                "version splice would silently corrupt lane draws")
        ld = doc["lanes"]
        lanes = {
            "pos": _nd_undoc(ld["pos"]),
            "r": _nd_undoc(ld["r"]),
            "st": {k: _nd_undoc(v) for k, v in ld["st"].items()},
            "setup": [_tree_undoc(leaf) for leaf in ld["setup"]],
        }
        if "acc" in ld:
            lanes["acc"] = _nd_undoc(ld["acc"])
        return cls(
            version=int(ver),
            cfg=SimConfig(**doc["cfg"]).validate(),
            ids=_nd_undoc(doc["ids"]),
            rounds=_nd_undoc(doc["rounds"]),
            decision=_nd_undoc(doc["decision"]),
            remaining=int(doc["remaining"]),
            pending=[(int(p), int(i)) for p, i in doc["pending"]],
            lanes=lanes,
            token=token,
            acc_done=(_nd_undoc(doc["acc_done"])
                      if "acc_done" in doc else None),
        )


class _ControlRequest:
    """One park/extract ask, delivered at the next segment boundary."""

    def __init__(self, kind: str, tokens=None):
        self.kind = kind          # "park" | "extract"
        self.tokens = tokens      # extract: identity-matched token list
        self.records: list = []
        self._done = threading.Event()

    def deliver(self, records: list) -> None:
        self.records = records
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> list:
        self._done.wait(timeout)
        return self.records


class LaneControl:
    """The scheduler → grid mailbox for boundary snapshot requests.

    A scheduler thread calls :meth:`park` (export every extractable config
    and return from ``run_bucket``) or :meth:`extract` (export just the
    named tokens, keep flying). ``run_bucket`` services requests at its next
    segment boundary and delivers :class:`LaneRecord` lists; when the grid
    exits (drained, or parked) it **detaches**, delivering ``[]`` to any
    still-queued request so callers never hang on a dead rotation.

    Spec-§11 sessions are never extractable: a session's future slots chain
    at the grid's retire seam under bucket-resident state, so the session
    rides one grid whole (the same rule serve/fleet.py applies to
    whole-rotation stealing).
    """

    def __init__(self):
        self._cv = threading.Condition()
        self._requests: list = []
        self._detached = False
        #: Records delivered by a serviced park — the dispatcher reads these
        #: after ``run_bucket`` returns.
        self.parked: list = []

    def park(self, feed=None) -> _ControlRequest:
        """Ask the grid to export everything extractable and return. The
        grid's exit delivers the request; records also land in ``parked``.
        ``feed.poke()`` wakes a grid idling in its blocking pull."""
        req = _ControlRequest("park")
        with self._cv:
            if self._detached:
                req.deliver([])
                return req
            self._requests.append(req)
        if feed is not None:
            feed.poke()
        return req

    def extract(self, tokens, feed=None,
                timeout: Optional[float] = None) -> list:
        """Export the configs owning ``tokens`` (identity match) at the next
        boundary; the grid keeps flying. Blocks until delivered (or the
        grid detaches → ``[]``)."""
        req = _ControlRequest("extract", tokens=list(tokens))
        with self._cv:
            if self._detached:
                return []
            self._requests.append(req)
        if feed is not None:
            feed.poke()
        return req.wait(timeout)

    # ---- grid side -------------------------------------------------------

    def _pop_request(self):
        with self._cv:
            return self._requests.pop(0) if self._requests else None

    def _deliver_park(self, req: _ControlRequest, records: list) -> None:
        self.parked.extend(records)
        req.deliver(records)

    def detach(self) -> None:
        """Grid exit: fail any queued request with an empty delivery and
        refuse new ones — callers must not hang on a finished rotation."""
        with self._cv:
            self._detached = True
            reqs, self._requests = self._requests, []
        for req in reqs:
            req.deliver([])
