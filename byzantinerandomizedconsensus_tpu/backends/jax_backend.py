"""JAX/TPU backend (SURVEY.md §7 step 4) — the performance core.

One instance-chunk is simulated by a single jit'd ``lax.while_loop`` whose body is the
vectorized round (models/benor.py / models/bracha.py with ``xp = jax.numpy``): mask
generation from the PRF, tallies, coin, decided-mask-frozen state update. Control flow
is compiler-friendly: static shapes, no data-dependent Python branching; the loop
predicate is ``any instance still undecided and round < cap`` (SURVEY.md §7
hard-part 2 — cost per chunk is the max rounds in the chunk, with the cap and the
overflow bucket keeping CPU/TPU agreement on capped instances).

Chunking bounds the O(B·n²) mask transient (hard-part 3); the last chunk is padded to
the chunk size so XLA compiles exactly one program per config.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from byzantinerandomizedconsensus_tpu.backends.base import (
    JitChunkedBackend, SimResult, check_pallas_delivery)
from byzantinerandomizedconsensus_tpu.config import SimConfig
from byzantinerandomizedconsensus_tpu.models import benor, bracha, state as state_mod
from byzantinerandomizedconsensus_tpu.models.adversaries import AdversaryModel


def _run_chunk(cfg, inst_ids: jnp.ndarray, key=None, counts_fn=None,
               counters: bool = False, adv=None):
    """Simulate one padded chunk; returns (rounds (B,), decision (B,)) — plus
    the (B, C, 2) uint32 per-instance counter accumulator when ``counters``.

    ``counts_fn`` selects the delivery+tally implementation: None = the XLA
    masks+tally path; ops/pallas_tally.counts_fn = the fused Pallas kernel.
    ``key`` is the (2,) uint32 PRF key as a *dynamic* argument (None = bake
    cfg.seed statically — required by the Pallas kernels, whose in-kernel
    threefry needs concrete key words): with a dynamic key, runs that differ
    only in seed (multi-seed sharding, seed sweeps) reuse one program.

    ``counters`` (static) adds the opt-in side-output leg (obs/counters.py)
    to the while-loop carry: the round body records per-step count outputs,
    which fold under the same ``done_at < 0`` activity mask that gates state
    updates. Nothing flows from the accumulator back into the round math, so
    the (rounds, decision) surface is bit-identical either way.

    ``adv`` overrides the adversary model (default: ``AdversaryModel(cfg)``)
    — the batched lane runner (backends/batch.py) passes its padding-aware
    wrapper here, and ``cfg`` may then be a ``LaneConfig`` view carrying
    traced lane scalars (f, crash_window, n_eff) over static bucket shapes.
    """
    from byzantinerandomizedconsensus_tpu.obs import counters as _c

    seed = cfg.seed if key is None else key
    round_body = benor.round_body if cfg.protocol == "benor" else bracha.round_body
    if adv is None:
        adv = AdversaryModel(cfg)
    setup = adv.setup(seed, inst_ids, xp=jnp)
    faulty = setup["faulty"]
    st = state_mod.init_state(cfg, seed, inst_ids, xp=jnp)
    done_at = jnp.full(inst_ids.shape[0], -1, dtype=jnp.int32)
    # The accumulator joins the carry only when collecting, so the
    # counters-off program is structurally identical to the pre-obs kernel.
    init = (jnp.int32(0), st, done_at) + (
        (_c.zeros(cfg, inst_ids.shape[0], jnp),) if counters else ())

    def cond(carry):
        r, _, done_at = carry[:3]
        return (r < cfg.round_cap) & ~jnp.all(done_at >= 0)

    def body(carry):
        r, st, done_at = carry[:3]
        obs = {} if counters else None
        st = round_body(cfg, seed, inst_ids, r, st, adv, setup, xp=jnp,
                        counts_fn=counts_fn, obs=obs)
        out = (r + 1, st)
        if counters:
            acc = _c.accumulate(carry[3], _c.round_increments(cfg, obs, jnp),
                                done_at < 0, cfg, jnp)
        done_now = state_mod.all_correct_decided(st, faulty, xp=jnp)
        done_at = jnp.where((done_at < 0) & done_now, r + 1, done_at)
        return out + (done_at,) + ((acc,) if counters else ())

    final = jax.lax.while_loop(cond, body, init)
    _, st, done_at = final[:3]
    done = done_at >= 0
    rounds = jnp.where(done, done_at, cfg.round_cap).astype(jnp.int32)
    decision = state_mod.extract_decision(st, faulty, done, xp=jnp)
    if counters:
        return rounds, decision, final[3]
    return rounds, decision


class JaxBackend(JitChunkedBackend):
    """``device='tpu'|'cpu'|None`` pins the computation; None = JAX default device.
    ``kernel='xla'`` (masks+tally), ``'pallas'`` (fused step kernel) or
    ``'fused'`` (the whole round loop in one pallas_call, ops/pallas_round.py
    — faults + committees in-kernel, ABI v6); the Pallas kernels select
    interpret mode automatically on non-TPU platforms so CI can bit-match."""

    name = "jax"

    def __init__(self, chunk_bytes: int = 1 << 30, max_chunk: int = 1 << 14,
                 device=None, kernel: str = "xla"):
        super().__init__(chunk_bytes, max_chunk)
        self.device = device
        if kernel not in ("xla", "xla_nosort", "pallas", "fused"):
            raise ValueError(
                f"unknown kernel {kernel!r}; use 'xla', 'xla_nosort', "
                "'pallas' or 'fused'")
        self.kernel = kernel

    def _chunk_size(self, cfg: SimConfig) -> int:
        # The chunk may never exceed the spec §2 instance-field ceiling of
        # the config's packing law (v2 narrows instances to 2^16): the cap
        # used to be independent of the pack law, which left a future
        # max_chunk bump free to outrun it. validate() rejects configs whose
        # *total* instances overflow; this clamp keeps the per-dispatch shape
        # inside the same law by construction.
        from byzantinerandomizedconsensus_tpu.ops import prf

        pack_cap = {1: prf.MAX_INSTANCES, 2: prf.V2_MAX_INSTANCES,
                    3: prf.V3_MAX_INSTANCES}[cfg.pack_version]
        max_chunk = min(self.max_chunk, pack_cap)
        if self.kernel == "fused":
            # The whole round loop runs per 8-instance block inside one
            # pallas_call (ops/pallas_round.py); state is O(B·n) and a block
            # exits as soon as its instances decide, so stragglers cost at
            # block granularity, not chunk granularity. Same O(B·n) budget
            # as the count-level path, capped at the Pallas dispatch sweet
            # spot.
            return max(1, min(max_chunk, 4096, (1 << 20) // max(1, cfg.n)))
        if cfg.count_level:
            # No O(B·n²) transient at all — state is O(B·n). Measured optimum
            # at n=512 on v5e is ~2k instances/chunk: beyond that the
            # while-loop straggler cost (whole chunk pays max rounds) outweighs
            # dispatch amortisation.
            return max(1, min(max_chunk, (1 << 20) // max(1, cfg.n)))
        if self.kernel == "pallas":
            # The fused kernel keeps the (B,n,n) key tensor VMEM-resident per
            # block — HBM holds only O(B·n) state, so the chunk is sized for
            # dispatch amortisation vs while-loop straggler cost (measured
            # optimum ~4k instances at n=512 on v5e; degrades past 16k).
            return max(1, min(max_chunk, 4096))
        per_inst = cfg.n * cfg.n * 4 * 4  # ~4 live (B,n,n) u32-sized transients
        return max(1, min(max_chunk, self.chunk_bytes // per_inst))

    def _clamp_chunk(self, cfg: SimConfig, chunk: int) -> int:
        if self.kernel != "fused":
            return chunk
        # Shape-stabilize the fused dispatch: round the chunk up to a power
        # of two (tail rows pad with a repeated last id, the established
        # tail law), so the per-config jit cache holds a log-bounded program
        # set instead of one program per distinct request size — the serve
        # path's zero-steady-state-recompile pin needs shape reuse, not
        # just config reuse.
        return 1 << max(3, (chunk - 1).bit_length())

    def _make_fn(self, cfg: SimConfig):
        if self.kernel == "fused":
            # ABI v6 (ops/pallas_round.py): faults and committees run
            # in-kernel, so the per-step kernels' gates don't apply; the
            # fused kernel has its own named surface check instead.
            from byzantinerandomizedconsensus_tpu.ops import pallas_round

            pallas_round.check_fused_supported(cfg)
            interpret = jax.default_backend() != "tpu"
            return jax.jit(partial(pallas_round.run_chunk, cfg,
                                   interpret=interpret))
        if self.kernel in ("xla_nosort", "pallas"):
            # The per-step custom-kernel paths compute delivery in-kernel and
            # have no fault-schedule or committee channel — fail loudly,
            # never fall back silently.
            from byzantinerandomizedconsensus_tpu.models.committee import (
                check_committee_supported)
            from byzantinerandomizedconsensus_tpu.models.faults import (
                check_faults_supported)

            check_faults_supported(cfg, f"kernel={self.kernel!r}")
            check_committee_supported(cfg, f"kernel={self.kernel!r}")
        counts_fn = None
        if cfg.count_level:
            # counts_fn=None routes the round bodies to ops/urn.py or
            # ops/urn2.py (XLA); kernel='pallas' swaps in the VMEM-resident
            # urn kernel (§4b only). Other kernels are keys-only — fail loudly
            # so an A/B invocation can't silently measure the default path
            # (ADVICE r1).
            if self.kernel == "xla_nosort":
                raise ValueError(
                    "kernel='xla_nosort' applies to delivery='keys' only; "
                    "count-level deliveries support kernel='xla' or 'pallas'")
            if self.kernel == "pallas":
                from byzantinerandomizedconsensus_tpu.ops import pallas_urn

                check_pallas_delivery(cfg)
                interpret = jax.default_backend() != "tpu"
                counts_fn = partial(pallas_urn.counts_fn, interpret=interpret)
            return jax.jit(partial(_run_chunk, cfg, counts_fn=counts_fn))
        if self.kernel == "pallas":
            from byzantinerandomizedconsensus_tpu.ops import pallas_tally

            interpret = jax.default_backend() != "tpu"
            counts_fn = partial(pallas_tally.counts_fn, interpret=interpret)
        elif self.kernel == "xla_nosort":
            from byzantinerandomizedconsensus_tpu.ops import masks

            counts_fn = masks.counts_nosort
        return jax.jit(partial(_run_chunk, cfg, counts_fn=counts_fn))

    def _device_ctx(self):
        if self.device is None:
            return super()._device_ctx()
        return jax.default_device(jax.devices(self.device)[0])

    def run_batch(self, cfgs, inst_ids=None, counters: bool = False):
        """Run many configs of one shape bucket in vmapped lanes — one
        compiled program per bucket instead of one per config, bit-identical
        per lane to :meth:`run` (backends/batch.py; docs/PERF.md round 10)."""
        from byzantinerandomizedconsensus_tpu.backends import batch

        return batch.run_batch(self, cfgs, inst_ids=inst_ids,
                               counters=counters)

    def run_many(self, cfgs, inst_ids=None, counters: bool = False,
                 progress=None, compaction=None):
        """Auto-group arbitrary configs by shape bucket and run each group
        batched; returns ``(results, report)`` (+ counters docs when asked).
        The fleet-path entry point (soak, divergence, acceptance grids).
        ``compaction`` (a CompactionPolicy) swaps each bucket's config lanes
        for the compacted instance-lane grid with one shared queue per
        bucket (backends/compaction.py; docs/PERF.md round 11)."""
        from byzantinerandomizedconsensus_tpu.backends import batch

        return batch.run_many(self, cfgs, inst_ids=inst_ids,
                              counters=counters, progress=progress,
                              compaction=compaction)

    def run_fused(self, cfgs, inst_ids=None, progress=None, compaction=None):
        """Fused superset lanes for sparse grids (backends/batch.py): only
        (protocol, delivery, tier, pack version) stay baked; adversary kind,
        fault kind, coin, init and round_cap ride as traced lane codes.
        Bit-identical per lane; the chaos-grid amortization lever.
        ``compaction`` recycles lanes across configs AND instances of each
        fused bucket (backends/compaction.py)."""
        from byzantinerandomizedconsensus_tpu.backends import batch

        return batch.run_fused(self, cfgs, inst_ids=inst_ids,
                               progress=progress, compaction=compaction)

    def compile_cache_stats(self) -> dict:
        """The bucket-program LRU counters for run records (obs/record.py
        schema v1.1) — compiles / hits / evictions / occupancy."""
        from byzantinerandomizedconsensus_tpu.backends import batch

        return batch.compile_cache(self).stats()

    def program_census(self) -> dict:
        """The compiled-program census entries attached to this backend's
        caches (obs/programs.py, opt-in; schema v1.4): the bucket
        CompileCache's captures plus any per-config programs captured
        through :meth:`_fn` — label → entry. Empty when the census was off
        (``record.programs_block`` then returns None)."""
        from byzantinerandomizedconsensus_tpu.backends import batch

        out = dict(batch.compile_cache(self).programs)
        for fn in self._compiled.values():
            key = getattr(fn, "census_key", None)
            if key is not None:
                from byzantinerandomizedconsensus_tpu.obs import (
                    programs as _programs)

                census = _programs.current()
                if census is not None and key in census.entries:
                    out[key] = census.entries[key]
        return out

    def run_compacted(self, cfg: SimConfig, inst_ids=None,
                      counters: bool = False, policy=None):
        """Decision-driven lane compaction (backends/compaction.py; docs/
        PERF.md round 11): the round loop runs in short segments over a
        fixed-width lane grid, retired lanes are compacted away and refilled
        from the pending-instance queue — the continuous-batching idiom at
        the instance axis. Bit-identical per instance to :meth:`run`
        (tests/test_compaction.py). Returns ``(SimResult, stats)``, or
        ``(SimResult, counters_doc, stats)`` with ``counters``; ``stats`` is
        the run-record ``compaction`` block payload (occupancy,
        wasted-lane-rounds, refills — obs/record.py schema v1.2)."""
        from byzantinerandomizedconsensus_tpu.backends import batch, compaction
        from byzantinerandomizedconsensus_tpu.obs import counters as _counters

        if self.kernel != "xla":
            raise ValueError(
                f"compacted lanes require the default 'xla' kernel; "
                f"kernel={self.kernel!r} compiles per-config programs")
        cfg = cfg.validate()
        self._check_config(cfg)
        ids = self._resolve_inst_ids(cfg, inst_ids)
        bucket = batch.ShapeBucket.of(cfg, counters=counters)
        with self._device_ctx():
            results, docs, stats = compaction.run_bucket(
                self, bucket, [cfg], [ids], policy=policy, counters=counters)
        if counters:
            return results[0], docs[0], stats
        return results[0], stats

    def run_with_counters(self, cfg: SimConfig,
                          inst_ids: Optional[np.ndarray] = None):
        """``run`` plus the protocol-counter totals (obs/counters.py).

        Counter collection is implemented for the default XLA kernels only:
        the Pallas paths compute delivery+tally in-kernel and expose no side
        channel, and ``xla_nosort`` is a keys-only A/B kernel — both raise
        :class:`CountersUnsupported` rather than silently measuring a
        different code path.

        The counted program is the single-lane batched bucket program: it is
        keyed by shape bucket (not by config) in the bounded
        :class:`~byzantinerandomizedconsensus_tpu.backends.batch.CompileCache`
        LRU, so a grid of counted configs sharing a bucket compiles once —
        the round-10 fix for the previously unbounded per-config
        ``_compiled_counters`` dict. Counter collection stays a pure side
        output: results are bit-identical to :meth:`run`'s
        (tests/test_obs_counters.py, tests/test_batch.py).
        """
        from byzantinerandomizedconsensus_tpu.obs import counters as _counters

        if self.kernel != "xla":
            raise _counters.CountersUnsupported(
                f"kernel={self.kernel!r} has no counter side channel; "
                "protocol counters require the default 'xla' kernels")
        cfg = cfg.validate()
        self._check_config(cfg)
        ids = self._resolve_inst_ids(cfg, inst_ids)
        results, docs = self.run_batch(
            [cfg], inst_ids=[ids], counters=True)
        return results[0], docs[0]


def _floor_pow2(x: int) -> int:
    t = 1
    while t * 2 <= x:
        t <<= 1
    return t


class CompactedJaxBackend(JaxBackend):
    """``jax_compact[:<policy>]`` — the JaxBackend with the decision-driven
    lane-compaction runner (backends/compaction.py) as its ``run`` path:
    bit-identical results, straggler-free device schedule. The optional
    parameter is the :class:`~.compaction.CompactionPolicy` spelling, e.g.
    ``jax_compact:width=4096,segment=1,threshold=0.25``.

    The timing discipline (utils/timing.timed_best_of) warms up with a
    ``_chunk_size``-sized id subset, so ``_chunk_size`` here returns the
    resolved lane-grid width — the warm-up then compiles exactly the step +
    drain programs the timed run uses. ``last_stats`` holds the compaction
    block of the most recent run for record builders (bench.py schema v1.2).
    """

    name = "jax_compact"

    def __init__(self, policy=None, **kw):
        from byzantinerandomizedconsensus_tpu.backends.compaction import (
            CompactionPolicy)

        super().__init__(**kw)
        self.policy = (policy or CompactionPolicy()).validate()
        self.last_stats: Optional[dict] = None

    def _resolved_width(self, cfg: SimConfig) -> int:
        from byzantinerandomizedconsensus_tpu.backends.batch import lane_tier

        if self.policy.width is not None:
            return lane_tier(self.policy.width)
        return _floor_pow2(super()._chunk_size(cfg))

    def _chunk_size(self, cfg: SimConfig) -> int:
        # 2x the grid width: timed_best_of warms up with a subset this
        # size, which exercises the FULL compiled program set (init, the
        # hot segment, one compaction+refill, the drain) at the timed
        # width — a W-sized warm-up would drain immediately and leave the
        # segment + refill compiles inside the timed window.
        from byzantinerandomizedconsensus_tpu.ops import prf

        pack_cap = {1: prf.MAX_INSTANCES, 2: prf.V2_MAX_INSTANCES,
                    3: prf.V3_MAX_INSTANCES}[cfg.pack_version]
        return min(2 * self._resolved_width(cfg), pack_cap)

    def run(self, cfg: SimConfig, inst_ids=None) -> "SimResult":
        import dataclasses as _dc

        from byzantinerandomizedconsensus_tpu.obs import trace as _trace

        policy = _dc.replace(self.policy, width=self._resolved_width(cfg))
        res, stats = self.run_compacted(cfg, inst_ids=inst_ids,
                                        policy=policy)
        self.last_stats = stats
        # One summary event per compacted run (obs/trace.py): a BENCH_TRACE
        # capture then carries the occupancy verdict next to the per-trip
        # segment/refill/drain spans run_bucket emitted.
        _trace.event("compact.run", width=stats["width"],
                     segments=stats["segments"], refills=stats["refills"],
                     occupancy=stats["occupancy"])
        return res

    def run_with_counters(self, cfg: SimConfig,
                          inst_ids: Optional[np.ndarray] = None):
        import dataclasses as _dc

        policy = _dc.replace(self.policy, width=self._resolved_width(cfg))
        res, doc, stats = self.run_compacted(
            cfg, inst_ids=inst_ids, counters=True, policy=policy)
        self.last_stats = stats
        return res, doc
