"""Config-batched execution — shape-bucketed compile cache + vmapped lanes
(docs/PERF.md round 10).

The single-config hot path compiles one XLA program per ``SimConfig`` because
the config is baked into the jit closure. That is the right trade for the
benchmark presets (hours of instances amortize one compile), and exactly the
wrong one for the *fleet* paths — soak, chaos, divergence, acceptance,
cost-curve grids — where hundreds of small-n configs each pay a full
retrace + recompile that dwarfs their simulation time. This module splits a
config the way a serving stack splits a request:

- the **shape bucket** (:class:`ShapeBucket`): everything that determines the
  compiled program's *structure* — n padded to the next supported tier,
  round_cap, delivery law, adversary kind, coin, init, protocol, fault kind,
  counters on/off, spec §2 packing version. One compiled program per bucket.
- the **lane parameters**: everything that only enters the *arithmetic* — f,
  the PRF key, crash_window, and the lane's real n (``n_eff``) — passed as
  device operands and ``vmap``-ed over a lane axis, so many configs of one
  bucket ride one dispatch.

Bit-match is the acceptance bar: a lane's (rounds, decision) arrays are
bit-identical to the per-config path (tests/test_batch.py asserts it across
the fault × adversary × delivery grid). Two mechanisms make that hold:

- the PRF addresses randomness by *coordinates* (spec §2), so a lane's draws
  do not depend on which program evaluates them — the lane key is data;
- lanes whose n is below the bucket tier mark their padding replicas silent
  (``_PadAdversary``) and faulty-for-termination, force their §3.2 rank keys
  past every real key, and read every value-of-n law through ``cfg.n_eff``
  (quorums, drop totals, receiver classes) — so padding replicas neither
  send, count, nor gate termination, exactly as if they did not exist.

The compiled programs live in a **bounded LRU** (:class:`CompileCache`) keyed
by (bucket, lane-tier, chunk), with compile/hit/eviction counters surfaced in
run records (obs/record.py schema v1.1) and reconstructed by ``brc-tpu
ledger``.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from collections import OrderedDict
from functools import partial
from typing import Optional, Sequence

import numpy as np

from byzantinerandomizedconsensus_tpu.config import SimConfig, validate_batch
from byzantinerandomizedconsensus_tpu.models.adversaries import AdversaryModel
from byzantinerandomizedconsensus_tpu.obs import metrics as _metrics
from byzantinerandomizedconsensus_tpu.obs import programs as _programs
from byzantinerandomizedconsensus_tpu.obs import trace as _trace
from byzantinerandomizedconsensus_tpu.ops import prf

# Supported n tiers: a lane's n is padded up to the next tier so that nearby
# sizes share one compiled program. Powers of two from the smallest legal
# quorum shape to the spec §2 v3 ceiling; tiers above 4096 are reachable only
# by the §10 committee family (config.validate gates every full-mesh delivery
# at the v2 ceiling), so the full-mesh program set is exactly what it was.
N_TIERS = (4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096,
           8192, 16384, 32768, 65536, 131072, 262144, 524288, 1048576)

# Environment knob for the opt-in persistent XLA compilation cache (see
# :func:`enable_persistent_compilation_cache`): retries, resumes and chaos
# workers then start warm across *processes*, not just within one.
COMPILE_CACHE_ENV = "BRC_COMPILATION_CACHE"


def n_tier(n: int) -> int:
    """The bucket shape tier for a config of size n (next tier ≥ n)."""
    for t in N_TIERS:
        if n <= t:
            return t
    raise ValueError(f"n={n} exceeds the largest supported tier {N_TIERS[-1]}")


def _bucket_committee_c(delivery: str, n_pad: int) -> int:
    """C(n_pad) for committee buckets, 0 otherwise (see ShapeBucket docs)."""
    if delivery != "committee":
        return 0
    from byzantinerandomizedconsensus_tpu.ops.committee import committee_size

    return committee_size(n_pad)


def lane_tier(lanes: int) -> int:
    """Lane-axis padding: next power of two ≥ lanes, so repeated batch calls
    with nearby lane counts reuse one compiled program."""
    if lanes < 1:
        raise ValueError("lane_tier needs >= 1 lanes")
    t = 1
    while t < lanes:
        t <<= 1
    return t


@dataclasses.dataclass(frozen=True)
class ShapeBucket:
    """The static half of a SimConfig: what the compiled program bakes in.

    ``protocol``, ``coin`` and ``init`` are structural too (step count, coin
    law and init law select different code paths), so they ride the bucket
    even though the ISSUE's minimal law doesn't name them — a bucket must
    never compile a program that branches on a lane value it cannot trace.

    ``committee_c`` is the §10.1 committee-size ceiling C(n_pad) for
    committee-delivery buckets (0 otherwise). A lane's realized C derives
    from its *traced* n_eff inside the program (ops/committee.py), so this
    field is a pure function of (delivery, n_pad) — it adds committee params
    to the bucket identity/label without ever splitting programs, which is
    what keeps committee serve admission at 0 steady-state recompiles.
    """

    protocol: str
    n_pad: int
    round_cap: int
    delivery: str
    adversary: str
    coin: str
    init: str
    faults: str
    counters: bool
    pack_version: int
    committee_c: int = 0

    @classmethod
    def of(cls, cfg: SimConfig, counters: bool = False) -> "ShapeBucket":
        return cls(protocol=cfg.protocol, n_pad=n_tier(cfg.n),
                   round_cap=cfg.round_cap, delivery=cfg.delivery,
                   adversary=cfg.adversary, coin=cfg.coin, init=cfg.init,
                   faults=cfg.faults, counters=counters,
                   pack_version=cfg.pack_version,
                   committee_c=_bucket_committee_c(cfg.delivery,
                                                   n_tier(cfg.n)))

    def label(self) -> str:
        """Compact human key for reports/ledger columns."""
        tag = f"{self.protocol}/n{self.n_pad}/c{self.round_cap}/" \
              f"{self.delivery}/{self.adversary}/{self.coin}/{self.init}/" \
              f"f{self.faults}/p{self.pack_version}"
        if self.committee_c:
            tag += f"/C{self.committee_c}"
        return tag + ("/counters" if self.counters else "")


class LaneConfig:
    """A SimConfig view over (bucket statics, traced lane scalars).

    Quacks like :class:`SimConfig` for the model layer: ``n`` is the padded
    tier (static — every array shape), while ``f``, ``crash_window`` and
    ``n_eff`` are traced device scalars. ``seed`` is None by construction —
    the PRF key is always passed dynamically on the batched path.
    """

    __slots__ = ("_b", "f", "crash_window", "n_eff")

    def __init__(self, bucket: ShapeBucket, f, crash_window, n_eff):
        self._b = bucket
        self.f = f
        self.crash_window = crash_window
        self.n_eff = n_eff

    # -- static structure (from the bucket) ---------------------------------
    @property
    def protocol(self):
        return self._b.protocol

    @property
    def n(self):
        return self._b.n_pad

    @property
    def round_cap(self):
        return self._b.round_cap

    @property
    def delivery(self):
        return self._b.delivery

    @property
    def adversary(self):
        return self._b.adversary

    @property
    def coin(self):
        return self._b.coin

    @property
    def init(self):
        return self._b.init

    @property
    def faults(self):
        return self._b.faults

    @property
    def pack_version(self):
        return self._b.pack_version

    @property
    def seed(self):
        return None

    # -- derived predicates (mirroring SimConfig) ---------------------------
    @property
    def steps_per_round(self):
        return 2 if self.protocol == "benor" else 3

    @property
    def count_level(self):
        from byzantinerandomizedconsensus_tpu.config import (
            COUNT_LEVEL_DELIVERIES)

        return self.delivery in COUNT_LEVEL_DELIVERIES

    @property
    def lying_adversary(self):
        return self.adversary in ("byzantine", "adaptive", "adaptive_min")


class _PadAdversary(AdversaryModel):
    """Adversary wrapper that makes padding replicas non-existent: they are
    silent on every step (never counted by any delivery law or validation
    rule) and faulty for termination/extraction (never gate a decision).
    ``pad`` is the (n_pad,) bool padding mask (replica index ≥ lane n)."""

    def __init__(self, cfg, pad):
        super().__init__(cfg)
        self._pad = pad

    def setup(self, seed, inst_ids, xp=np):
        s = super().setup(seed, inst_ids, xp=xp)
        if self._pad is not None:
            s = dict(s)
            s["faulty"] = s["faulty"] | self._pad[None, :]
        return s

    def inject(self, seed, inst_ids, rnd, t, honest_values, setup, xp=np,
               recv_ids=None):
        v, sil, b = super().inject(seed, inst_ids, rnd, t, honest_values,
                                   setup, xp=xp, recv_ids=recv_ids)
        if self._pad is not None:
            sil = sil | self._pad[None, :]
        return v, sil, b


def _key_label(key) -> str:
    """Compact human spelling of a cache key for trace events (buckets know
    their own label; everything else falls back to str)."""
    if isinstance(key, tuple):
        return "/".join(_key_label(k) for k in key)
    lab = getattr(key, "label", None)
    if callable(lab):
        try:
            return lab()
        except Exception:
            pass
    return str(key)


class CompileCache:
    """Bounded LRU of compiled bucket programs, with the observability
    counters the run record carries (compiles / hits / evictions, plus the
    schema-v1.3 ``compile_wall_s`` total). One instance per backend serves
    both the batched path and the counter leg — the fix for the previously
    unbounded ``_compiled_counters`` dict.

    Compile wall accounting: ``build()`` usually returns a *lazy* ``jax.jit``
    wrapper, so the XLA compile is actually paid on the first invocation —
    callable entries are therefore wrapped to time that first call (trace +
    compile; the one execution riding along is the standard first-call
    proxy), fold it into ``compile_wall_s``, emit the
    ``compile_cache.compile`` trace event (obs/trace.py), and then unwrap
    so steady-state calls pay nothing.

    With the compiled-program census enabled (obs/programs.py — opt-in,
    round 13), that same first call instead goes through the AOT
    ``lower()``/``compile()`` stages: the census records the program's cost/
    memory analyses, HLO fingerprint and signature, the entry is attached
    here in ``programs`` (keyed by the cache key's label), and the cached
    callable becomes the compiled executable itself — the same XLA program
    the lazy jit would have built, so results are bit-identical either way
    (tests/test_programs.py).

    Thread safety (round 14): the serving loop calls ``get`` from its
    dispatcher thread while request/monitor threads read ``stats()`` — all
    LRU-dict mutation, counter updates and the census ``programs`` attach
    happen under one reentrant lock. Lookup *and* ``build()`` stay under the
    lock on purpose: ``build`` returns a lazy ``jax.jit`` wrapper in
    microseconds, so serializing it costs nothing and guarantees one entry
    per key; the expensive XLA compile runs in ``_timed_first_call`` under a
    per-entry lock instead, so a compile never blocks unrelated hits."""

    def __init__(self, max_entries: int = 32):
        if max_entries < 1:
            raise ValueError("CompileCache needs max_entries >= 1")
        self.max_entries = max_entries
        self._entries: OrderedDict = OrderedDict()
        self._lock = threading.RLock()
        self.compiles = 0
        self.hits = 0
        self.evictions = 0
        self.compile_wall_s = 0.0
        #: census entries attached to their cache entry (label -> entry);
        #: populated only while obs/programs is enabled. Entries survive an
        #: LRU eviction on purpose — the census is an audit of what this
        #: cache built, not of what it currently holds.
        self.programs: OrderedDict = OrderedDict()

    def get(self, key, build):
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                self.hits += 1
                _metrics.counter("brc_compile_cache_hits_total",
                                 "CompileCache lookups served warm").inc()
                _trace.event("compile_cache.hit", key=_key_label(key))
                return self._entries[key]
            t0 = time.perf_counter()
            fn = build()
            wall = time.perf_counter() - t0
            self.compiles += 1
            self.compile_wall_s += wall
            # the steady-state-compile counter: loadgen/SLO runs assert its
            # delta is zero once every bucket program is warm
            _metrics.counter("brc_compile_cache_compiles_total",
                             "Program builds (cold CompileCache keys)").inc()
            if callable(fn):
                fn = self._timed_first_call(key, fn, wall)
            else:
                _trace.event("compile_cache.compile", key=_key_label(key),
                             wall_s=round(wall, 6))
            self._entries[key] = fn
            while len(self._entries) > self.max_entries:
                old_key, _ = self._entries.popitem(last=False)
                self.evictions += 1
                _metrics.counter("brc_compile_cache_evictions_total",
                                 "LRU evictions from the CompileCache").inc()
                _trace.event("compile_cache.evict", key=_key_label(old_key))
            return fn

    def _timed_first_call(self, key, fn, build_wall: float):
        timed = False
        first = threading.Lock()  # one real XLA compile, however many callers

        def wrapper(*args, **kw):
            # Only the FIRST invocation is the compile; callers that hold
            # the wrapper (the multi-chunk dispatch loop fetches it once)
            # keep calling it, and those later calls are plain execution —
            # timing them would inflate compile_wall_s and spam the trace.
            # Concurrent first callers serialize on the per-entry lock (the
            # loser executes plain once the winner's compile lands); the
            # cache-wide lock is NOT held across the compile, so a slow
            # compile in one bucket never stalls hits in another.
            nonlocal timed, fn
            if timed:
                return fn(*args, **kw)
            with first:
                if timed:
                    return fn(*args, **kw)
                label = _key_label(key)
                if _programs.enabled() and hasattr(fn, "lower"):
                    # Census path (opt-in): the one compile seam routes
                    # through AOT lower()/compile() so the program's anatomy
                    # is capturable; the compiled executable replaces the
                    # lazy jit wrapper (same XLA program — bit-identical
                    # results).
                    t0 = time.perf_counter()
                    out, compiled, entry = _programs.capture_call(
                        label, fn, args, kw)
                    wall = time.perf_counter() - t0
                    if compiled is not None:
                        fn = compiled
                    timed = True
                    with self._lock:
                        self.compile_wall_s += wall
                        if entry is not None:
                            self.programs[label] = entry
                        if self._entries.get(key) is wrapper:  # unwrap
                            self._entries[key] = fn
                    _trace.event("compile_cache.compile", key=label,
                                 wall_s=round(build_wall + wall, 6))
                    return out
                t0 = time.perf_counter()
                out = fn(*args, **kw)
                wall = time.perf_counter() - t0
                timed = True
                with self._lock:
                    self.compile_wall_s += wall
                    if self._entries.get(key) is wrapper:  # unwrap
                        self._entries[key] = fn
                _trace.event("compile_cache.compile", key=label,
                             wall_s=round(build_wall + wall, 6))
                return out

        return wrapper

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        """The run-record ``compile_cache`` block (obs/record.py v1.1;
        ``compile_wall_s`` since schema v1.3). Safe from any thread — the
        serving loop reads it per request to prove zero steady-state
        recompiles."""
        with self._lock:
            return {
                "compiles": self.compiles,
                "hits": self.hits,
                "evictions": self.evictions,
                "entries": len(self._entries),
                "max_entries": self.max_entries,
                "compile_wall_s": round(self.compile_wall_s, 6),
            }


def _run_lanes(bucket: ShapeBucket, keys, fs, wins, neffs, inst_ids):
    """The traced bucket program: vmap of the per-config chunk kernel over
    the lane axis. ``keys`` (L, 2) u32, ``fs``/``neffs`` (L,) i32, ``wins``
    (L,) u32, ``inst_ids`` (L, B) u32. Returns (rounds (L, B), decision
    (L, B)[, counter accumulator (L, B, C, 2)])."""
    import jax
    import jax.numpy as jnp

    from byzantinerandomizedconsensus_tpu.backends import jax_backend

    def one(key, f, w, ne, ids):
        cfg = LaneConfig(bucket, f=f, crash_window=w, n_eff=ne)
        pad = jnp.arange(bucket.n_pad, dtype=jnp.int32) >= ne
        return jax_backend._run_chunk(cfg, ids, key=key,
                                      counters=bucket.counters,
                                      adv=_PadAdversary(cfg, pad))

    return jax.vmap(one)(keys, fs, wins, neffs, inst_ids)


def _chunk_instances(bucket: ShapeBucket, lanes: int, max_i: int,
                     chunk_bytes: int, max_chunk: int) -> int:
    """Instances per lane per dispatch: the single-config sizing law divided
    across the lane axis (the O(lanes · B · n²) mask transient must fit the
    same budget), rounded to a power of two so nearby grids share programs."""
    from byzantinerandomizedconsensus_tpu.config import COUNT_LEVEL_DELIVERIES

    n = bucket.n_pad
    if bucket.delivery in COUNT_LEVEL_DELIVERIES:
        per_lane = max(1, (1 << 20) // max(1, n))
    else:
        per_inst = n * n * 4 * 4
        per_lane = max(1, chunk_bytes // per_inst)
    b = max(1, min(per_lane // lanes, max_chunk))
    # Floor the budget to a power of two (never exceed it), but allow one
    # whole-grid dispatch when the grid itself is small: ceil-pow2(max_i)
    # overshoots the budget by < 2x at worst, and only at trivial sizes.
    floor_b = 1
    while floor_b * 2 <= b:
        floor_b <<= 1
    ceil_i = 1
    while ceil_i < max_i:
        ceil_i <<= 1
    return min(floor_b, ceil_i)


def run_batch(backend, cfgs: Sequence[SimConfig], inst_ids=None,
              counters: bool = False):
    """Run many configs of ONE shape bucket in vmapped lanes on ``backend``
    (a JaxBackend). Returns a list of per-config SimResults, bit-identical
    to ``backend.run`` per lane; with ``counters``, returns
    ``(results, counters_docs)``.

    ``inst_ids`` is an optional per-config list of instance-id arrays.
    Raises ``ValueError`` on mixed delivery laws / packing versions
    (config.validate_batch — pinned messages) or on configs that fall into
    different buckets. The counter leg is pad-exact: per-receiver counter
    sums mask padding receivers (ops/urn*.py stats, obs/counters.py), so a
    padded lane's totals equal the per-config run's.
    """
    import jax
    import jax.numpy as jnp

    if backend.kernel != "xla":
        raise ValueError(
            f"batched lanes require the default 'xla' kernel; "
            f"kernel={backend.kernel!r} compiles per-config programs")
    cfgs = validate_batch(cfgs)
    buckets = {ShapeBucket.of(c, counters=counters) for c in cfgs}
    if len(buckets) != 1:
        labels = sorted(b.label() for b in buckets)
        raise ValueError(
            f"batch spans {len(buckets)} shape buckets ({', '.join(labels)}); "
            "run_batch serves one bucket — use run_many to auto-group")
    bucket = next(iter(buckets))

    lanes = len(cfgs)
    l_pad = lane_tier(lanes)
    ids_list = [
        backend._resolve_inst_ids(c, None if inst_ids is None else inst_ids[i])
        for i, c in enumerate(cfgs)]
    max_i = max((len(i) for i in ids_list), default=0)
    if max_i == 0:
        empty = [_empty_result(c, i) for c, i in zip(cfgs, ids_list)]
        if counters:
            from byzantinerandomizedconsensus_tpu.obs import counters as _c

            return empty, [_c.counters_doc(c, _c.finalize(c, _c.zeros(c, 0)),
                                           backend=backend.name)
                           for c in cfgs]
        return empty

    chunk = _chunk_instances(bucket, l_pad, max_i, backend.chunk_bytes,
                             backend.max_chunk)
    cache = compile_cache(backend)
    cache_key = (bucket, l_pad, chunk)
    fn = cache.get(cache_key,
                   lambda: jax.jit(partial(_run_lanes, bucket)))

    # Lane operands: padding lanes replicate the last config (discarded).
    def lane_cfg(i):
        return cfgs[min(i, lanes - 1)]

    keys = np.stack([np.asarray(prf.seed_key(lane_cfg(i).seed),
                                dtype=np.uint32) for i in range(l_pad)])
    fs = np.asarray([lane_cfg(i).f for i in range(l_pad)], dtype=np.int32)
    wins = np.asarray([lane_cfg(i).crash_window for i in range(l_pad)],
                      dtype=np.uint32)
    neffs = np.asarray([lane_cfg(i).n for i in range(l_pad)], dtype=np.int32)
    lane_ops = (jnp.asarray(keys), jnp.asarray(fs), jnp.asarray(wins),
                jnp.asarray(neffs))

    return _dispatch_and_collect(backend, fn, lane_ops, cfgs, ids_list,
                                 l_pad, chunk, max_i, counters,
                                 program=(_key_label(cache_key)
                                          if _trace.enabled() else None))


def _dispatch_and_collect(backend, fn, lane_ops, cfgs, ids_list, l_pad,
                          chunk, max_i, counters, program=None):
    """Shared lane-grid executor: async-dispatch every (l_pad, chunk) id
    grid, one batched device_get, per-lane unpad/trim — the run_batch /
    run_fused common tail. ``program`` is the compiled program's census/
    cache label, carried on the dispatch span so a roofline join
    (tools/programs.py) can match per-dispatch wall to per-program
    flops/bytes."""
    import jax
    import jax.numpy as jnp

    lanes = len(cfgs)

    def lane_ids(i):
        ids = ids_list[min(i, lanes - 1)]
        return ids if len(ids) else np.zeros(1, dtype=np.int64)

    pending = []
    with backend._device_ctx(), \
            _trace.span("batch.dispatch", lanes=l_pad, chunk=chunk,
                        configs=lanes, program=program,
                        occupancy=round(lanes / l_pad, 4)) as sp:
        for lo in range(0, max_i, chunk):
            grid = np.empty((l_pad, chunk), dtype=np.uint32)
            for l in range(l_pad):
                ids = lane_ids(l)
                seg = ids[lo:lo + chunk]
                if len(seg) == 0:
                    seg = ids[-1:]
                if len(seg) < chunk:
                    seg = np.concatenate(
                        [seg, np.full(chunk - len(seg), seg[-1])])
                grid[l] = seg.astype(np.uint32)
            pending.append(fn(*lane_ops, jnp.asarray(grid)))
        sp["dispatches"] = len(pending)
        fetched = jax.device_get(pending)

    results = []
    docs = []
    for l, (cfg, ids) in enumerate(zip(cfgs, ids_list)):
        parts_r, parts_d, parts_c = [], [], []
        for c, ch in enumerate(fetched):
            lo = c * chunk
            take = max(0, min(len(ids) - lo, chunk))
            if take == 0:
                continue
            parts_r.append(np.asarray(ch[0][l])[:take])
            parts_d.append(np.asarray(ch[1][l])[:take])
            if counters:
                parts_c.append(np.asarray(ch[2][l])[:take])
        if parts_r:
            rounds = np.concatenate(parts_r).astype(np.int32, copy=False)
            decision = np.concatenate(parts_d).astype(np.uint8, copy=False)
        else:
            rounds = np.empty(0, dtype=np.int32)
            decision = np.empty(0, dtype=np.uint8)
        from byzantinerandomizedconsensus_tpu.backends.base import SimResult

        results.append(SimResult(config=cfg, inst_ids=ids, rounds=rounds,
                                 decision=decision))
        if counters:
            from byzantinerandomizedconsensus_tpu.obs import counters as _c

            rows = (np.concatenate(parts_c) if parts_c
                    else _c.zeros(cfg, 0, np))
            docs.append(_c.counters_doc(cfg, _c.finalize(cfg, rows),
                                        backend=backend.name))
    if counters:
        return results, docs
    return results


def _empty_result(cfg, ids):
    from byzantinerandomizedconsensus_tpu.backends.base import SimResult

    return SimResult(config=cfg, inst_ids=ids,
                     rounds=np.empty(0, dtype=np.int32),
                     decision=np.empty(0, dtype=np.uint8))


def run_many(backend, cfgs: Sequence[SimConfig], inst_ids=None,
             counters: bool = False, progress=None, compaction=None):
    """Group arbitrary configs by shape bucket and run each group batched.

    Returns ``(results, report)`` with ``results`` in input order and
    ``report`` the observability block: per-bucket occupancy plus the
    backend's compile-cache stats (the run-record ``batch`` payload).
    ``inst_ids`` is an optional per-config list of instance-id arrays.
    With ``counters``, returns ``(results, docs, report)``.

    ``compaction``: a :class:`~.compaction.CompactionPolicy` routes each
    bucket group through the decision-driven compacted lane grid instead of
    the vmapped config lanes — every (config, instance) pair of a bucket
    feeds ONE shared queue, so lanes freed by one config's fast instances
    are refilled with the next config's (queue-fed lane recycling across
    configs; docs/PERF.md round 11). Bit-identical either way; the report
    gains the run-record ``compaction`` block (obs/record.py schema v1.2).
    """
    cfgs = [c.validate() for c in cfgs]
    groups: OrderedDict = OrderedDict()
    for i, c in enumerate(cfgs):
        groups.setdefault(ShapeBucket.of(c, counters=counters),
                          []).append(i)
    results = [None] * len(cfgs)
    docs = [None] * len(cfgs)
    occupancy = []
    compaction_stats = []
    for bucket, idxs in groups.items():
        if progress is not None:
            progress(f"batch bucket {bucket.label()}: {len(idxs)} config(s)")
        group_ids = (None if inst_ids is None
                     else [inst_ids[i] for i in idxs])
        with _trace.span("batch.bucket", bucket=bucket.label(),
                         configs=len(idxs),
                         mode=("compacted" if compaction is not None
                               else "bucketed")) as sp:
            if compaction is not None:
                from byzantinerandomizedconsensus_tpu.backends import (
                    compaction as _compaction)

                group = [cfgs[i] for i in idxs]
                ids_list = [
                    backend._resolve_inst_ids(
                        c, None if group_ids is None else group_ids[j])
                    for j, c in enumerate(group)]
                group_res, group_docs, stats = _compaction.run_bucket(
                    backend, bucket, group, ids_list, policy=compaction,
                    counters=counters, progress=progress)
                compaction_stats.append(stats)
                sp["lane_tier"] = stats["width"]
                sp["occupancy"] = stats["occupancy"]
                occupancy.append({"bucket": bucket.label(),
                                  "configs": len(idxs),
                                  "lane_tier": stats["width"],
                                  "compaction": stats})
            else:
                out = run_batch(backend, [cfgs[i] for i in idxs],
                                inst_ids=group_ids, counters=counters)
                group_res, group_docs = out if counters else (out, None)
                sp["lane_tier"] = lane_tier(len(idxs))
                occupancy.append({"bucket": bucket.label(),
                                  "configs": len(idxs),
                                  "lane_tier": lane_tier(len(idxs))})
        for j, i in enumerate(idxs):
            results[i] = group_res[j]
            if counters:
                docs[i] = group_docs[j]
    report = {
        "buckets": len(groups),
        "configs": len(cfgs),
        "occupancy": occupancy,
        "compile_cache": compile_cache(backend).stats(),
    }
    if compaction_stats:
        from byzantinerandomizedconsensus_tpu.backends import (
            compaction as _compaction)

        report["compaction"] = _compaction.merge_stats(compaction_stats)
    if counters:
        return results, docs, report
    return results, report


def run_grid(backend, cfgs: Sequence[SimConfig], inst_ids=None,
             progress=None):
    """Fleet-path convenience: batched ``run_many`` when ``backend`` (an
    object or a registered name) supports it, an honest per-config loop
    otherwise. Returns ``(results, report_or_None)`` — tools wire their
    grids through this one seam so ``--batched`` never changes results,
    only how many programs get compiled."""
    if isinstance(backend, str):
        from byzantinerandomizedconsensus_tpu.backends.base import get_backend

        backend = get_backend(backend)
    if hasattr(backend, "run_many"):
        return run_many(backend, cfgs, inst_ids=inst_ids, progress=progress)
    results = [backend.run(c, None if inst_ids is None else inst_ids[i])
               for i, c in enumerate(cfgs)]
    return results, None


def compile_cache(backend) -> CompileCache:
    """The backend's bucket-keyed compiled-program LRU (created on first
    use). Shared by run_batch and the counter leg."""
    cache = getattr(backend, "_bucket_cache", None)
    if cache is None:
        cache = CompileCache()
        backend._bucket_cache = cache
    return cache


# ---------------------------------------------------------------------------
# fused lanes — the sparse-grid specialization (docs/PERF.md round 10)
#
# The strict bucket law above amortizes compiles only when a grid *shares*
# buckets (seed sweeps, f sweeps, tier-sharing sweep points). A randomized
# grid like the chaos population spans protocol × adversary × delivery ×
# faults × cap × coin × init × tier and buckets at occupancy ≈ 1 — nothing
# amortizes (measured: 275 buckets for 280 configs). The fused mode folds
# every foldable axis into lane data: adversary kind, fault kind, coin, init
# and round_cap become traced lane codes selecting between jointly-computed
# variants, and small n pads to one coarse tier — leaving ONE superset
# program per (protocol, delivery, tier, §2 pack version). Bit-match still
# holds per lane: each variant's math is the static law's (the samplers'
# documented st ≡ False collapse covers the adaptive structure; unused PRF
# draws are coordinate-addressed and never feed selected values).

#: Lane-code registries (the traced half of the fused split).
ADV_CODES = {"none": 0, "crash": 1, "byzantine": 2, "adaptive": 3,
             "adaptive_min": 4}
FAULT_CODES = {"none": 0, "recover": 1, "partition": 2, "omission": 3}
COIN_CODES = {"local": 0, "shared": 1}
INIT_CODES = {"random": 0, "all0": 1, "all1": 2, "split": 3}

#: The coarse small-n tier for fused grids: every n below it pads up, so a
#: whole small-n fleet shares one program per (protocol, delivery). 40 =
#: the soak/chaos generator's n ceiling (tools/soak.MAX_SOAK_N) — the
#: dominant fused workload pads with zero waste at its own edge; shapes
#: need no power-of-two alignment on the XLA side.
FUSED_SMALL_TIER = 40


def fused_tier(n: int) -> int:
    return FUSED_SMALL_TIER if n <= FUSED_SMALL_TIER else n_tier(n)


@dataclasses.dataclass(frozen=True)
class FusedBucket:
    """The static residue of a config under fused lanes: only what selects
    genuinely different *code* (step count, sampler family, key packing) or
    array shapes stays baked."""

    protocol: str
    n_pad: int
    delivery: str
    pack_version: int
    committee_c: int = 0

    @classmethod
    def of(cls, cfg: SimConfig) -> "FusedBucket":
        return cls(protocol=cfg.protocol, n_pad=fused_tier(cfg.n),
                   delivery=cfg.delivery, pack_version=cfg.pack_version,
                   committee_c=_bucket_committee_c(cfg.delivery,
                                                   fused_tier(cfg.n)))

    def label(self) -> str:
        tag = (f"fused/{self.protocol}/n{self.n_pad}/{self.delivery}/"
               f"p{self.pack_version}")
        return tag + (f"/C{self.committee_c}" if self.committee_c else "")

    #: duck-typing for _chunk_instances
    counters = False


class FusedLaneConfig(LaneConfig):
    """LaneConfig whose adversary / faults / coin / init are the "superset"
    sentinel laws (models compute every variant and select by the traced
    lane codes) and whose round_cap is traced lane data too."""

    __slots__ = ("round_cap_t", "adv_code", "faults_code", "coin_code",
                 "init_code")

    def __init__(self, bucket, f, crash_window, n_eff, round_cap,
                 adv_code, faults_code, coin_code, init_code):
        super().__init__(bucket, f=f, crash_window=crash_window, n_eff=n_eff)
        self.round_cap_t = round_cap
        self.adv_code = adv_code
        self.faults_code = faults_code
        self.coin_code = coin_code
        self.init_code = init_code

    @property
    def round_cap(self):
        return self.round_cap_t

    @property
    def adversary(self):
        return "superset"

    @property
    def faults(self):
        return "superset"

    @property
    def coin(self):
        return "superset"

    @property
    def init(self):
        return "superset"

    @property
    def lying_adversary(self):
        # byzantine(2) / adaptive(3) / adaptive_min(4) — a traced bool;
        # models/benor.py takes the arithmetic threshold form for it.
        return self.adv_code >= 2


def _run_fused_lanes(bucket: FusedBucket, keys, fs, wins, neffs, caps,
                     advs, faults_, coins_, inits, inst_ids):
    """The fused bucket program: vmap over lanes with every foldable config
    axis as lane data."""
    import jax
    import jax.numpy as jnp

    from byzantinerandomizedconsensus_tpu.backends import jax_backend

    def one(key, f, w, ne, cap, adv, flt, coin, init, ids):
        cfg = FusedLaneConfig(bucket, f=f, crash_window=w, n_eff=ne,
                              round_cap=cap, adv_code=adv, faults_code=flt,
                              coin_code=coin, init_code=init)
        pad = jnp.arange(bucket.n_pad, dtype=jnp.int32) >= ne
        return jax_backend._run_chunk(cfg, ids, key=key, counters=False,
                                      adv=_PadAdversary(cfg, pad))

    return jax.vmap(one)(keys, fs, wins, neffs, caps, advs, faults_,
                         coins_, inits, inst_ids)


def run_fused(backend, cfgs: Sequence[SimConfig], inst_ids=None,
              progress=None, compaction=None):
    """Run arbitrary configs through fused superset lanes — grouped only by
    (protocol, delivery, tier, pack version). Bit-identical per lane to the
    per-config path; no counter leg (the counter schema is a static function
    of the fault kind, which is lane data here).

    Returns ``(results, report)`` like :func:`run_many`. ``compaction``
    routes each fused bucket through the compacted lane grid (one queue per
    bucket, instance-granular lanes carrying the folded-axis codes as lane
    operands — docs/PERF.md round 11): a sparse heterogeneous grid then
    recycles lanes across *configs* as well as instances.
    """
    if backend.kernel != "xla":
        raise ValueError(
            f"fused lanes require the default 'xla' kernel; "
            f"kernel={backend.kernel!r} compiles per-config programs")
    import jax
    import jax.numpy as jnp

    cfgs = [c.validate() for c in cfgs]
    groups: OrderedDict = OrderedDict()
    for i, c in enumerate(cfgs):
        groups.setdefault(FusedBucket.of(c), []).append(i)
    results = [None] * len(cfgs)
    occupancy = []
    compaction_stats = []
    cache = compile_cache(backend)
    for bucket, idxs in groups.items():
        if progress is not None:
            progress(f"fused bucket {bucket.label()}: {len(idxs)} config(s)")
        group = [cfgs[i] for i in idxs]
        ids_list = [
            backend._resolve_inst_ids(
                c, None if inst_ids is None else inst_ids[idxs[j]])
            for j, c in enumerate(group)]
        max_i = max((len(i) for i in ids_list), default=0)
        if max_i == 0:
            for j, i in enumerate(idxs):
                results[i] = _empty_result(group[j], ids_list[j])
            continue
        if compaction is not None:
            from byzantinerandomizedconsensus_tpu.backends import (
                compaction as _compaction)

            with _trace.span("batch.bucket", bucket=bucket.label(),
                             configs=len(idxs), mode="compacted") as sp:
                group_res, _docs, stats = _compaction.run_bucket(
                    backend, bucket, group, ids_list, policy=compaction,
                    counters=False, progress=progress)
                sp["lane_tier"] = stats["width"]
                sp["occupancy"] = stats["occupancy"]
            for j, i in enumerate(idxs):
                results[i] = group_res[j]
            compaction_stats.append(stats)
            occupancy.append({"bucket": bucket.label(),
                              "configs": len(idxs),
                              "lane_tier": stats["width"],
                              "compaction": stats})
            continue
        lanes = len(group)
        l_pad = lane_tier(lanes)
        chunk = _chunk_instances(bucket, l_pad, max_i, backend.chunk_bytes,
                                 backend.max_chunk)
        cache_key = ("fused", bucket, l_pad, chunk)
        fn = cache.get(cache_key,
                       lambda: jax.jit(partial(_run_fused_lanes, bucket)))

        def lc(i):
            return group[min(i, lanes - 1)]

        lane_ops = (
            jnp.asarray(np.stack([np.asarray(prf.seed_key(lc(i).seed),
                                             dtype=np.uint32)
                                  for i in range(l_pad)])),
            jnp.asarray(np.asarray([lc(i).f for i in range(l_pad)],
                                   dtype=np.int32)),
            jnp.asarray(np.asarray([lc(i).crash_window for i in range(l_pad)],
                                   dtype=np.uint32)),
            jnp.asarray(np.asarray([lc(i).n for i in range(l_pad)],
                                   dtype=np.int32)),
            jnp.asarray(np.asarray([lc(i).round_cap for i in range(l_pad)],
                                   dtype=np.int32)),
            jnp.asarray(np.asarray([ADV_CODES[lc(i).adversary]
                                    for i in range(l_pad)], dtype=np.int32)),
            jnp.asarray(np.asarray([FAULT_CODES[lc(i).faults]
                                    for i in range(l_pad)], dtype=np.int32)),
            jnp.asarray(np.asarray([COIN_CODES[lc(i).coin]
                                    for i in range(l_pad)], dtype=np.int32)),
            jnp.asarray(np.asarray([INIT_CODES[lc(i).init]
                                    for i in range(l_pad)], dtype=np.int32)),
        )
        with _trace.span("batch.bucket", bucket=bucket.label(),
                         configs=len(idxs), mode="fused", lane_tier=l_pad):
            group_res = _dispatch_and_collect(
                backend, fn, lane_ops, group, ids_list, l_pad, chunk, max_i,
                counters=False, program=(_key_label(cache_key)
                                         if _trace.enabled() else None))
        for j, i in enumerate(idxs):
            results[i] = group_res[j]
        occupancy.append({"bucket": bucket.label(), "configs": len(idxs),
                          "lane_tier": l_pad})
    report = {
        "mode": "fused",
        "buckets": len(groups),
        "configs": len(cfgs),
        "occupancy": occupancy,
        "compile_cache": cache.stats(),
    }
    if compaction_stats:
        from byzantinerandomizedconsensus_tpu.backends import (
            compaction as _compaction)

        report["compaction"] = _compaction.merge_stats(compaction_stats)
    return results, report


# ---------------------------------------------------------------------------
# persistent (cross-process) XLA compilation cache — opt-in


def enable_persistent_compilation_cache(path: str) -> bool:
    """Point jax's persistent compilation cache at ``path`` (opt-in): chaos
    workers, retries and checkpoint resumes then reuse compiled programs
    across *processes*. Returns False (with no side effect) when this jax
    build lacks the knob — never a hard failure, the cache is an
    accelerant, not a correctness seam."""
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", str(path))
        # Cache every program, however fast the compile: the fleet paths this
        # serves are dominated by many small programs.
        try:
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        except Exception:
            pass
        return True
    except Exception:
        return False


def maybe_enable_cache_from_env() -> Optional[str]:
    """Honor ``BRC_COMPILATION_CACHE=<dir>`` when set (the soak/chaos parent
    exports it to its workers). Returns the directory when enabled."""
    path = os.environ.get(COMPILE_CACHE_ENV)
    if path and enable_persistent_compilation_cache(path):
        return path
    return None
