"""CPU oracle backend (SURVEY.md §7 step 2) — the default, bit-exact reference.

Per-instance, per-replica object loop over the front-end model (Replica, Network,
Adversary). Correctness-first and independent of the vectorized models/ code: this is
the arbiter implementation the JAX/TPU backend must bit-match (BASELINE.json:5).
"""

from __future__ import annotations

import math

from typing import Optional

import numpy as np

from byzantinerandomizedconsensus_tpu.backends.base import SimResult, SimulatorBackend
from byzantinerandomizedconsensus_tpu.config import SimConfig
from byzantinerandomizedconsensus_tpu.core.adversary import make_adversary
from byzantinerandomizedconsensus_tpu.core.network import Network
from byzantinerandomizedconsensus_tpu.core.replica import Replica
from byzantinerandomizedconsensus_tpu.ops import prf


def _committee_nf(n: int, f: int):
    """Oracle-side (C, f_C) — spec §10.1/§10.3. Independent of
    ops/committee.py: bit_length()/math.isqrt vs the static compare-sums."""
    cn = min(n, max(16, 8 * (n - 1).bit_length()))
    fc = f if cn == n else (cn * f + n - 1) // n + math.isqrt(cn)
    return cn, fc


class CpuBackend(SimulatorBackend):
    name = "cpu"

    def run(self, cfg: SimConfig, inst_ids: Optional[np.ndarray] = None) -> SimResult:
        cfg = cfg.validate()
        ids = self._resolve_inst_ids(cfg, inst_ids)
        rounds = np.empty(len(ids), dtype=np.int32)
        decision = np.empty(len(ids), dtype=np.uint8)
        for k, i in enumerate(ids):
            rounds[k], decision[k] = self._run_instance(cfg, int(i))
        return SimResult(config=cfg, inst_ids=ids, rounds=rounds, decision=decision)

    def run_with_counters(self, cfg: SimConfig,
                          inst_ids: Optional[np.ndarray] = None):
        """``run`` plus the message-level protocol-counter subset
        (obs/counters.py): delivered/dropped per phase, coin flips, rounds.

        Counted with independent scalar arithmetic straight off the oracle's
        own per-receiver counts — this is the anchor the vectorized stacks'
        totals are cross-checked against at small n. The sampler-owned cost
        counters (chain trips etc.) are kernel internals of the vectorized
        implementations and are deliberately absent here.
        """
        from byzantinerandomizedconsensus_tpu.obs import counters as _counters

        cfg = cfg.validate()
        ids = self._resolve_inst_ids(cfg, inst_ids)
        rounds = np.empty(len(ids), dtype=np.int32)
        decision = np.empty(len(ids), dtype=np.uint8)
        totals: dict = {}
        for k, i in enumerate(ids):
            rounds[k], decision[k] = self._run_instance(cfg, int(i),
                                                        collect=totals)
        names = [n for n in _counters.counter_names(cfg)
                 if n.split("@")[0] in ("delivered0", "delivered1", "dropped",
                                        "fault_silenced", "fault_cut_pairs")
                 or n in ("coin_flips", "rounds_active")]
        totals = {n: totals.get(n, 0) for n in names}
        res = SimResult(config=cfg, inst_ids=ids, rounds=rounds,
                        decision=decision)
        return res, _counters.counters_doc(cfg, totals, backend=self.name)

    @staticmethod
    def _invalid(cfg: SimConfig, t: int, values: np.ndarray, g_prev,
                 nf=None) -> np.ndarray:
        """Per-sender invalidity per spec §5.1b, from the previous step's global
        live-valid counts (g0, g1). Independent scalar re-implementation of
        models/validation.py for the oracle cross-check. ``nf`` overrides the
        (n, f) pair the intervals derive from — the committee path passes
        (C, f_C) so validity matches the committee-scoped G counts (§10.3)."""
        n, f = nf if nf is not None else (cfg.n, cfg.f)
        q = n - f
        g0, g1 = g_prev
        if t == 1:
            ok = {1: g1 >= (q + 1) // 2, 0: g0 >= q // 2 + 1, 2: True}
        else:
            lo = max(0, q - g0, q - n // 2)
            hi = min(g1, q, n // 2)
            ok = {1: g1 >= n // 2 + 1, 0: g0 >= n // 2 + 1, 2: lo <= hi}
        return np.array([not ok[int(v)] for v in values], dtype=bool)

    @staticmethod
    def _initial_estimates(cfg: SimConfig, instance: int) -> np.ndarray:
        replica = np.arange(cfg.n, dtype=np.uint32)
        if cfg.init == "all0":
            return np.zeros(cfg.n, dtype=np.uint8)
        if cfg.init == "all1":
            return np.ones(cfg.n, dtype=np.uint8)
        if cfg.init == "split":
            return (replica & 1).astype(np.uint8)
        return prf.prf_bit(cfg.seed, instance, 0, 0, replica, 0, prf.INIT_EST,
                           xp=np, pack=cfg.pack_version).astype(np.uint8)

    def _run_instance(self, cfg: SimConfig, instance: int, collect=None):
        est0 = self._initial_estimates(cfg, instance)
        replicas = [Replica(cfg, j, est0[j]) for j in range(cfg.n)]
        net = Network(cfg, cfg.seed, instance)
        adv = make_adversary(cfg, cfg.seed, instance)
        correct = [j for j in range(cfg.n) if not adv.faulty[j]]
        fs = None
        if cfg.faults != "none":
            from byzantinerandomizedconsensus_tpu.core.faults import (
                FaultSchedule)

            fs = FaultSchedule(cfg, cfg.seed, instance)

        two_faced = cfg.count_level and cfg.adversary == "byzantine" \
            and cfg.protocol != "bracha"
        committee = cfg.delivery == "committee"
        cm_nf = _committee_nf(cfg.n, cfg.f) if committee else None

        if collect is not None:
            from byzantinerandomizedconsensus_tpu.obs.counters import (
                phase_names)

            phases = phase_names(cfg)
            # Every delivery law waits for a quota of k live messages per
            # receiver: n−f−1 for the full mesh (spec §4), the committee
            # k_C = C − f_C − 1 under §10.2.
            k_quota = (cm_nf[0] - cm_nf[1] - 1) if committee \
                else cfg.n - cfg.f - 1

            def note(name: str, inc: int) -> None:
                collect[name] = collect.get(name, 0) + int(inc)

        for r in range(cfg.round_cap):
            g_prev = None  # global live-valid counts of the previous step (bracha)
            # Fault-schedule masks for this round (spec §9): silences join the
            # silent set before §5.1b validation; the partition side plane
            # applies only at the delivery law — same composition order as
            # the vectorized round bodies.
            fsil, fside = fs.round_masks(r) if fs is not None else (None, None)
            for t in range(cfg.steps_per_round):
                honest = np.array([rep.send_value(t) for rep in replicas], dtype=np.uint8)
                values, silent, bias = adv.inject(r, t, honest)
                if fsil is not None:
                    silent = silent | fsil
                if committee:
                    # spec §10.4 composition order: membership silence joins
                    # after the §9 fault silences, before §5.1b validation —
                    # non-members of this step's committee do not broadcast.
                    rep_ids = np.arange(cfg.n, dtype=np.uint32)
                    mw = prf.prf_u32(cfg.seed, instance, r, t, rep_ids, 0,
                                     prf.COMMITTEE, xp=np,
                                     pack=cfg.pack_version)
                    silent = silent | ((mw % np.uint32(cfg.n))
                                       >= np.uint32(cm_nf[0]))
                if cfg.protocol == "bracha":
                    # spec §5.1b: invalid messages are silenced before delivery.
                    if t > 0:
                        silent = silent | self._invalid(cfg, t, values, g_prev,
                                                        nf=cm_nf)
                    live = ~silent
                    g_prev = (int(np.count_nonzero(live & (values == 0))),
                              int(np.count_nonzero(live & (values == 1))))
                if cfg.count_level:
                    if two_faced:
                        # §4b two-faced equivocation, independent of ops/urn.py.
                        send = np.arange(cfg.n, dtype=np.uint32)
                        vbc = []
                        for h in (0, 1):
                            # Sender-addressed: prf_sender puts the sender
                            # index in the wide field under §2 v3 (bit-
                            # identical at pack ≤ 2).
                            e = prf.prf_sender(cfg.seed, instance, r, t, h,
                                               send, prf.BYZ_VALUE, xp=np,
                                               pack=cfg.pack_version)
                            vh = (e % np.uint32(3)).astype(np.uint8)
                            vbc.append(np.where(adv.faulty, vh, honest).astype(np.uint8))
                    else:
                        vbc = [values, values]
                    if cfg.adversary == "adaptive":
                        strata, minority = "class", 0
                    elif cfg.adversary == "adaptive_min":
                        strata = "minority"
                        minority = adv.observed_minority(honest)
                    else:
                        strata, minority = "none", 0
                    counts = {"urn": net.urn_counts, "urn2": net.urn2_counts,
                              "urn3": net.urn3_counts,
                              "committee": net.committee_counts}[cfg.delivery]
                    c0, c1 = counts(r, t, vbc, silent,
                                    strata=strata, minority=minority,
                                    fside=fside)
                    if collect is not None:
                        note(f"delivered0@{phases[t]}", c0.sum())
                        note(f"delivered1@{phases[t]}", c1.sum())
                    for rep in replicas:
                        rep.on_counts(t, int(c0[rep.index]), int(c1[rep.index]))
                else:
                    vmat, mask = net.deliver(r, t, values, silent, bias,
                                             fside=fside)
                    if collect is not None:
                        note(f"delivered0@{phases[t]}", (mask & (vmat == 0)).sum())
                        note(f"delivered1@{phases[t]}", (mask & (vmat == 1)).sum())
                    for rep in replicas:
                        rep.on_deliver(t, vmat[rep.index], mask[rep.index])
                if collect is not None:
                    # Every delivery law drops exactly max(0, L_v − (n−f−1))
                    # live messages per receiver (spec §4) — same scalar
                    # formula obs/counters.round_increments vectorizes. Under
                    # a §9 partition, L_v counts only same-side live senders.
                    live = ~silent
                    if fside is None:
                        live_tot = np.full(cfg.n, np.count_nonzero(live))
                    else:
                        live_tot = np.array(
                            [np.count_nonzero(live & (fside == fside[v]))
                             for v in range(cfg.n)])
                    note(f"dropped@{phases[t]}",
                         sum(max(0, int(live_tot[v])
                                 - (0 if silent[v] else 1) - k_quota)
                             for v in range(cfg.n)))
                    if cfg.faults != "none":
                        # Schema-v2 fault attribution (obs/counters.py):
                        # schedule-silenced senders, and live cross-cut pairs.
                        note(f"fault_silenced@{phases[t]}",
                             0 if fsil is None else int(fsil.sum()))
                        cut = 0
                        if fside is not None:
                            for v in range(cfg.n):
                                cut += int(np.count_nonzero(
                                    live & (fside != fside[v])))
                        note(f"fault_cut_pairs@{phases[t]}", cut)
            if cfg.coin == "shared":
                shared = int(prf.prf_bit(cfg.seed, instance, r, prf.COIN_STEP, 0, 0,
                                         prf.SHARED_COIN, xp=np,
                                         pack=cfg.pack_version))
                coin = [shared] * cfg.n
            else:
                replica = np.arange(cfg.n, dtype=np.uint32)
                coin = prf.prf_bit(cfg.seed, instance, r, prf.COIN_STEP, replica, 0,
                                   prf.LOCAL_COIN, xp=np, pack=cfg.pack_version)
            for rep in replicas:
                rep.end_round(int(coin[rep.index]))
            if collect is not None:
                note("coin_flips", cfg.n if cfg.coin == "local" else 1)
                note("rounds_active", 1)
            if all(replicas[j].decided for j in correct):
                # Always-on Agreement invariant (VERDICT r2 #2): the result
                # surface reports correct[0]'s value, which would mask a
                # disagreement among higher-indexed correct replicas — so the
                # oracle checks ALL of them before returning. Every
                # oracle-anchored run (tools/acceptance.py run_anchor,
                # bitmatch --arbiter cpu) is thereby an agreement check.
                vals = {replicas[j].decided_val for j in correct}
                if len(vals) != 1:
                    raise AssertionError(
                        f"Agreement violation: correct replicas decided {sorted(vals)} "
                        f"(instance={instance}, cfg={cfg})")
                return r + 1, replicas[correct[0]].decided_val
        # Agreement binds any two correct deciders even when the instance
        # caps out with a partial decided set.
        vals = {replicas[j].decided_val for j in correct if replicas[j].decided}
        if len(vals) > 1:
            raise AssertionError(
                f"Agreement violation at round cap: correct replicas decided "
                f"{sorted(vals)} (instance={instance}, cfg={cfg})")
        return cfg.round_cap, 2
