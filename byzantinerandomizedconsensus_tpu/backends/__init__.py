"""Backend registry: ``cpu`` (oracle, default), ``numpy`` (vectorized host),
``native`` (multithreaded C++ core), ``jax`` (jit/TPU), ``jax_cpu`` (jit pinned to
host devices, for CI bit-matching), ``jax_sharded`` (mesh-parallel)."""

from byzantinerandomizedconsensus_tpu.backends.base import (
    SimResult,
    SimulatorBackend,
    available_backends,
    get_backend,
    register_backend,
)


def _cpu():
    from byzantinerandomizedconsensus_tpu.backends.cpu import CpuBackend

    return CpuBackend()


def _numpy():
    from byzantinerandomizedconsensus_tpu.backends.numpy_backend import NumpyBackend

    return NumpyBackend()


def _jax(kernel: str = "xla"):
    """``jax`` or ``jax:<kernel>`` with kernel in xla | xla_nosort | pallas
    | fused."""
    from byzantinerandomizedconsensus_tpu.backends.jax_backend import JaxBackend

    return JaxBackend(kernel=kernel or "xla")


def _jax_cpu():
    from byzantinerandomizedconsensus_tpu.backends.jax_backend import JaxBackend

    return JaxBackend(device="cpu")


def _jax_pallas():
    """JAX backend with the Pallas kernels: the fused delivery+tally kernel is
    the TPU fast path for delivery='keys' (ops/pallas_tally.py); under
    delivery='urn' this selects the cross-check kernel (ops/pallas_urn.py),
    which is ~21x slower than the default XLA urn path (measured
    op-throughput-bound, docs/PERF.md round 3) — use plain ``jax`` for urn
    performance."""
    from byzantinerandomizedconsensus_tpu.backends.jax_backend import JaxBackend

    return JaxBackend(kernel="pallas")


def _jax_fused():
    """``jax_fused`` — the whole round loop resident in one Pallas kernel
    (ops/pallas_round.py, ABI v6): delivery draw → tally → coin → decide
    with the spec §9 fault parameters and the §10 committee draw in-kernel,
    for the count-level deliveries. Interpret mode off-TPU; bit-identical to
    ``jax`` (tests/test_pallas_round.py)."""
    from byzantinerandomizedconsensus_tpu.backends.jax_backend import JaxBackend

    return JaxBackend(kernel="fused")


def _jax_compact(policy: str = ""):
    """``jax_compact[:<policy>]`` — the decision-driven lane-compaction
    runner (backends/compaction.py; docs/PERF.md round 11): bit-identical to
    ``jax``, straggler-free device schedule. Policy spelling:
    ``width=4096,segment=2,threshold=0.25`` (any subset)."""
    from byzantinerandomizedconsensus_tpu.backends.compaction import (
        CompactionPolicy)
    from byzantinerandomizedconsensus_tpu.backends.jax_backend import (
        CompactedJaxBackend)

    return CompactedJaxBackend(policy=CompactionPolicy.parse(policy))


def _native(n_threads: str = "0"):
    """``native`` or ``native:<threads>`` — the C++ core (native/simcore.cpp)."""
    from byzantinerandomizedconsensus_tpu.backends.native_backend import NativeBackend

    return NativeBackend(n_threads=int(n_threads))


def _virtual(param: str = "2x2"):
    """``virtual[:<data>x<model>]`` — host-side SPMD emulation of the sharded
    layout (parallel/virtual.py): numpy round bodies on threads with a
    barrier all-gather. A validation instrument (sharding-semantics bit-match
    without an accelerator), not a performance path."""
    from byzantinerandomizedconsensus_tpu.parallel.virtual import VirtualMeshBackend

    d, _, m = param.partition("x")
    return VirtualMeshBackend(n_data=int(d or "2"), n_model=int(m or "1"))


def _jax_sharded(param: str = "1"):
    """``jax_sharded[:<n_model>[,pallas]]`` — replica-shard count over the mesh's
    model axis (must divide the device count and cfg.n), optionally with the
    fused Pallas kernel."""
    from byzantinerandomizedconsensus_tpu.parallel.sharded import JaxShardedBackend

    n_model, _, kernel = param.partition(",")
    return JaxShardedBackend(n_model=int(n_model or "1"), kernel=kernel or "xla")


register_backend("cpu", _cpu)
register_backend("numpy", _numpy)
register_backend("jax", _jax)
register_backend("jax_cpu", _jax_cpu)
register_backend("jax_sharded", _jax_sharded)
register_backend("jax_pallas", _jax_pallas)
register_backend("jax_fused", _jax_fused)
register_backend("jax_compact", _jax_compact)
register_backend("native", _native)
register_backend("virtual", _virtual)

__all__ = [
    "SimResult",
    "SimulatorBackend",
    "available_backends",
    "get_backend",
    "register_backend",
]
