"""Native C++ backend — the multithreaded host runtime (native/simcore.cpp).

Compiles the C++ core with g++ on first use (cached in ``native/build/`` keyed by
a source hash + ABI version) and drives it through ctypes — no pybind11 needed.
Bit-matches the CPU oracle (tests/test_native.py); its role is fast host-side
validation and baselines at sizes where the Python object loop is impractical
(SURVEY.md §2 component inventory: native runtime leg).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import pathlib
import subprocess
from typing import Optional

import numpy as np

from byzantinerandomizedconsensus_tpu.backends.base import SimResult, SimulatorBackend
from byzantinerandomizedconsensus_tpu.config import SimConfig

_PROTO = {"benor": 0, "bracha": 1}
_ADV = {"none": 0, "crash": 1, "byzantine": 2, "adaptive": 3, "adaptive_min": 4}
_COIN = {"local": 0, "shared": 1}
_INIT = {"random": 0, "all0": 1, "all1": 2, "split": 3}
_DELIVERY = {"keys": 0, "urn": 1, "urn2": 2, "urn3": 3}

# v5: sim_run carries the spec §2 packing version in the call contract.
_ABI_VERSION = 5

_lib = None


def _source_path() -> pathlib.Path:
    return pathlib.Path(__file__).resolve().parents[2] / "native" / "simcore.cpp"


def build_library(force: bool = False) -> pathlib.Path:
    """Compile native/simcore.cpp to a cached shared library; returns its path."""
    src = _source_path()
    if not src.exists():
        raise FileNotFoundError(f"native source not found: {src}")
    digest = hashlib.sha256(src.read_bytes()).hexdigest()[:16]
    build_dir = src.parent / "build"
    build_dir.mkdir(exist_ok=True)
    so = build_dir / f"simcore-v{_ABI_VERSION}-{digest}.so"
    if so.exists() and not force:
        return so
    base = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
            str(src), "-o", str(so)]
    # -march=native when the toolchain supports it; plain -O3 otherwise.
    for cmd in ([*base[:2], "-march=native", *base[2:]], base):
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
            return so
        except FileNotFoundError:
            raise RuntimeError("g++ not found; the native backend needs a C++ toolchain")
        except subprocess.CalledProcessError as e:
            err = e.stderr
    raise RuntimeError(f"native build failed:\n{err}")


def _load():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(str(build_library()))
        lib.sim_abi_version.restype = ctypes.c_int
        if lib.sim_abi_version() != _ABI_VERSION:
            raise RuntimeError("native library ABI mismatch; rebuild")
        lib.sim_run.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_uint64, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            ctypes.c_int64, ctypes.c_int,
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
        ]
        lib.sim_run.restype = None
        _lib = lib
    return _lib


class NativeBackend(SimulatorBackend):
    """``n_threads=0`` (default) uses all CPUs."""

    name = "native"

    def __init__(self, n_threads: int = 0):
        self.n_threads = n_threads or (os.cpu_count() or 1)

    def run(self, cfg: SimConfig, inst_ids: Optional[np.ndarray] = None) -> SimResult:
        cfg = cfg.validate()
        from byzantinerandomizedconsensus_tpu.models.committee import (
            check_committee_supported)
        from byzantinerandomizedconsensus_tpu.models.faults import (
            check_faults_supported)

        check_faults_supported(cfg, "the native core (ABI v5)")
        check_committee_supported(cfg, "the native core (ABI v5)")
        lib = _load()
        ids = np.ascontiguousarray(self._resolve_inst_ids(cfg, inst_ids))
        rounds = np.empty(len(ids), dtype=np.int32)
        decision = np.empty(len(ids), dtype=np.uint8)
        if len(ids):
            lib.sim_run(
                _PROTO[cfg.protocol], cfg.n, cfg.f, _ADV[cfg.adversary],
                _COIN[cfg.coin], _INIT[cfg.init],
                ctypes.c_uint64(cfg.seed & 0xFFFFFFFFFFFFFFFF),
                cfg.round_cap, cfg.crash_window, _DELIVERY[cfg.delivery],
                cfg.pack_version,
                ids, len(ids), self.n_threads, rounds, decision,
            )
        return SimResult(config=cfg, inst_ids=ids, rounds=rounds, decision=decision)
