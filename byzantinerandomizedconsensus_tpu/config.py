"""SimConfig and the five benchmark presets (spec/PROTOCOL.md §7, BASELINE.json:6-12)."""

from __future__ import annotations

import dataclasses
from typing import Literal

from byzantinerandomizedconsensus_tpu.ops import prf

Protocol = Literal["benor", "bracha"]
AdversaryKind = Literal["none", "crash", "byzantine", "adaptive", "adaptive_min"]
CoinKind = Literal["local", "shared"]
InitKind = Literal["random", "all0", "all1", "split"]
DeliveryKind = Literal["keys", "urn", "urn2", "urn3", "committee"]
FaultKind = Literal["none", "recover", "partition", "omission"]

# The delivery registry: every scheduling model a SimConfig may name, in spec
# order. COUNT_LEVEL_DELIVERIES are the §4b-family samplers (no O(n²) mask
# object; class-granular adversary structure); "keys" is the spec-§4 mask
# model. validate(), the CLI choices, the native-backend enum and the round
# bodies' counts dispatch all derive from these two tuples, so adding a
# delivery model is a one-line registration here plus its sampler
# implementations (ops/, core/network.py, native/simcore.cpp).
# "committee" (spec §10) is the sampled-quorum family: per-round, per-phase
# PRF-drawn committees with thresholds over committee counts — the only
# family admitted past the full-mesh n ≤ 4096 ceiling (spec §2 v3).
COUNT_LEVEL_DELIVERIES = ("urn", "urn2", "urn3", "committee")
DELIVERY_KINDS = ("keys",) + COUNT_LEVEL_DELIVERIES

# The fault-schedule registry (spec §9): an axis orthogonal to the §6
# adversary axis, "faults-as-data" in the same style. Every schedule draws
# only from the §3.2 fault-prone set (size f), so composition with any
# adversary keeps total misbehaving replicas ≤ f and the §5 safety arguments
# apply verbatim. "recover" = crash-recovery windows (silent, then rejoin);
# "partition" = a PRF-drawn epoch isolating a fault-prone sub-block (messages
# across the cut suppressed both ways); "omission" = transient per-round
# send-omission bursts. Implemented in models/faults.py (vectorized) and
# core/faults.py (scalar oracle); the native core and the per-step Pallas
# kernels raise FaultsUnsupported. The fused round kernel (ABI v6,
# ops/pallas_round.py) closed that gate for the count-level deliveries: its
# operand block carries the §9 schedules in-kernel, on the single-host and
# sharded paths alike.
FAULT_KINDS = ("none", "recover", "partition", "omission")

# Single source for the default round cap. checkpoint.shard_name encodes only
# NON-default caps (legacy shard names imply this value), so every site that
# interprets a shard name must agree with SimConfig's field default.
DEFAULT_ROUND_CAP = 256


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """One simulation configuration (spec/PROTOCOL.md §7).

    ⚠ ``delivery`` defaults to ``"keys"`` — the spec-§4 O(n²)-mask
    *validation* model. **Every user-facing surface defaults to the
    product model instead**: the presets, ``sweep_point(...)``, bench.py,
    and the CLI (including ad-hoc ``cli run`` without ``--preset``) all
    pin or default ``delivery=PRODUCT_DELIVERY`` (a count-level model,
    §4b/§4b-v2) — the "keys" default is reachable only by constructing
    ``SimConfig`` in code. That bare-constructor default is kept at
    "keys" deliberately: in-repo constructor call sites are
    overwhelmingly spec-§4 cross-model work (tests, golden vectors, fuzz
    harnesses), and flipping it would silently change the sampled
    delivery schedule (and thus the bit-match surface) of ~100 such
    sites with no signature change to flag it. If you want the benchmark
    semantics in code, go through ``preset(...)``/``sweep_point(...)``
    or pass ``delivery=config.PRODUCT_DELIVERY`` explicitly.
    """

    protocol: Protocol = "benor"
    n: int = 4
    f: int = 1
    instances: int = 1
    adversary: AdversaryKind = "none"
    coin: CoinKind = "local"
    seed: int = 0
    round_cap: int = DEFAULT_ROUND_CAP
    crash_window: int = 4
    init: InitKind = "random"
    # Scheduling model. The count-level samplers "urn" (spec §4b, sequential
    # draws), "urn2" (spec §4b-v2, direct count inversion) and "urn3"
    # (spec §4c, mode-anchored cheap law — a *different distribution*, not a
    # third exact sampler of the §4b family) are the TPU-native models; the
    # benchmark presets pin whichever the measured A/B made the product path
    # (docs/PERF.md rounds 5-6). "keys" (spec §4, the O(n²) permutation-key
    # mask) is the validation model: an independent exact sampler of the same
    # delivery-distribution family as §4b/§4b-v2, kept as the SimConfig
    # default for ad-hoc spec-§4 work and cross-model checks.
    delivery: DeliveryKind = "keys"
    # Fault schedule (spec §9) — orthogonal to ``adversary``. "none" is the
    # frozen default: every existing config draws and decides bit-identically.
    # The schedules silence (or cut off) only §3.2 fault-prone replicas, and
    # reuse ``crash_window`` as their PRF time scale.
    faults: FaultKind = "none"

    @property
    def steps_per_round(self) -> int:
        return 2 if self.protocol == "benor" else 3

    @property
    def n_eff(self) -> int:
        """The value of n in protocol *arithmetic* (quorum thresholds, drop
        totals, receiver classes, coin budgets). For a plain SimConfig this is
        just ``n``; the batched lane runner (backends/batch.py) substitutes a
        config view whose ``n`` is the padded shape tier and whose ``n_eff``
        is the lane's real n (a traced scalar) — the model layer reads ``n``
        wherever a static array *shape* is needed and ``n_eff`` wherever the
        protocol's value of n enters the math, so one compiled program serves
        every n in a tier bit-exactly."""
        return self.n

    @property
    def count_level(self) -> bool:
        """True for the count-domain delivery models (§4b "urn", §4b-v2
        "urn2", §4c "urn3"): no O(n²) mask object exists, adversary structure
        is class-granular, and memory is O(B·n)."""
        return self.delivery in COUNT_LEVEL_DELIVERIES

    @property
    def lying_adversary(self) -> bool:
        """Selects Ben-Or Protocol B thresholds (spec §5.1)."""
        return self.adversary in ("byzantine", "adaptive", "adaptive_min")

    @property
    def pack_version(self) -> int:
        """The spec §2 packing law this config draws under: 1 (the frozen
        original) for n ≤ 1024, 2 (spec §2 v2, wider recv/send fields) for
        1024 < n ≤ 4096, 3 (spec §2 v3, 20-bit replica field) above. Every
        consumer of PRF coordinates — the vectorized ops, the oracle, the
        Pallas kernels, the native core — must thread this through as the
        ``pack`` argument; it is a pure function of n so the five stacks
        cannot disagree."""
        return prf.pack_version(self.n)

    def validate(self) -> "SimConfig":
        if self.delivery not in DELIVERY_KINDS:
            raise ValueError(
                f"unknown delivery {self.delivery!r}; "
                f"use one of {'|'.join(DELIVERY_KINDS)}")
        if self.faults not in FAULT_KINDS:
            raise ValueError(
                f"unknown faults {self.faults!r}; "
                f"use one of {'|'.join(FAULT_KINDS)}")
        if self.crash_window < 1:
            # §3.3 / §9 draw crash rounds as ``prf % crash_window``: a zero
            # window is a modulo-by-zero that numpy turns into silent garbage
            # (0 with a RuntimeWarning) instead of an error — reject it here.
            raise ValueError(
                f"crash_window={self.crash_window} out of range (>= 1); "
                "the §3.3/§9 schedules draw rounds mod crash_window")
        if not (0 < self.n <= prf.MAX_N):
            raise ValueError(f"n={self.n} out of range (1..{prf.MAX_N})")
        if self.n > prf.V2_MAX_N and self.delivery != "committee":
            # The full-mesh samplers are O(n·f) per replica; only the §10
            # committee family is admitted past the v2 packing edge.
            raise ValueError(
                f"n={self.n} exceeds the full-mesh ceiling ({prf.V2_MAX_N}); "
                f"only delivery='committee' (spec §10) runs under the §2 v3 "
                f"packing law (got delivery={self.delivery!r})")
        if not (0 <= self.f < self.n):
            raise ValueError(f"f={self.f} out of range for n={self.n}")
        # Field limits depend on the packing law (spec §2 / §2 v2 / §2 v3):
        # v2/v3 buy replica-field width by narrowing instance and round.
        max_inst = {1: prf.MAX_INSTANCES, 2: prf.V2_MAX_INSTANCES,
                    3: prf.V3_MAX_INSTANCES}[self.pack_version]
        max_rounds = {1: prf.MAX_ROUNDS, 2: prf.V2_MAX_ROUNDS,
                      3: prf.V3_MAX_ROUNDS}[self.pack_version]
        if not (0 < self.instances <= max_inst):
            raise ValueError(
                f"instances={self.instances} out of range (1..{max_inst}) "
                f"under packing v{self.pack_version} (n={self.n}): the spec "
                f"§2 v{self.pack_version} law packs instance ids in "
                f"{ {1: 17, 2: 16, 3: 12}[self.pack_version] } bits — chunk "
                "sizing (backends/jax_backend.py::_chunk_size) is clamped to "
                "the same ceiling")
        if not (0 < self.round_cap <= max_rounds):
            raise ValueError(
                f"round_cap={self.round_cap} out of range (1..{max_rounds}) "
                f"under packing v{self.pack_version} (n={self.n})")
        # Resilience bounds (spec §5.1/§5.2): benor Protocol A needs n > 2f, benor
        # Protocol B (lying adversaries) needs n > 5f, bracha needs n > 3f (the
        # n > 3f Byzantine benchmark pairing is Bracha — config 3).
        if self.protocol == "bracha":
            if 3 * self.f >= self.n:
                raise ValueError(f"bracha requires n > 3f (got n={self.n}, f={self.f})")
        elif self.lying_adversary:
            if 5 * self.f >= self.n:
                raise ValueError(
                    f"benor+{self.adversary} requires n > 5f (got n={self.n}, f={self.f}); "
                    "use protocol='bracha' for n > 3f resilience"
                )
        elif 2 * self.f >= self.n:
            raise ValueError(f"benor requires n > 2f (got n={self.n}, f={self.f})")
        if self.delivery == "committee":
            # Committee resilience (spec §10.3): thresholds are evaluated
            # over committee counts, so the bound that must hold is the
            # protocol's — in (C, f_C), the static committee size and fault
            # budget. The full-mesh n > kf bounds above are necessary but
            # not sufficient (f_C carries a +sqrt(C) sampling margin).
            from byzantinerandomizedconsensus_tpu.ops import committee as _cm

            c = _cm.committee_size(self.n)
            fc = _cm.committee_fault_budget(self.n, self.f)
            if self.protocol == "bracha":
                if 3 * fc >= c:
                    raise ValueError(
                        f"committee resilience: bracha requires 3·f_C < C, "
                        f"got C={c}, f_C={fc} (n={self.n}, f={self.f}; spec "
                        f"§10.3 — lower f to restore the sortition margin)")
            elif self.lying_adversary:
                if 5 * fc >= c:
                    raise ValueError(
                        f"committee resilience: benor+{self.adversary} "
                        f"requires 5·f_C < C, got C={c}, f_C={fc} "
                        f"(n={self.n}, f={self.f}; spec §10.3)")
            elif 2 * fc >= c:
                raise ValueError(
                    f"committee resilience: benor requires 2·f_C < C, got "
                    f"C={c}, f_C={fc} (n={self.n}, f={self.f}; spec §10.3)")
        return self


def validate_batch(cfgs) -> list["SimConfig"]:
    """Validate a batched lane request (backends/batch.py::run_batch).

    Every config must validate individually, and the batch must be servable
    by ONE compiled bucket program: a bucket bakes exactly one delivery law
    and one spec §2 packing law into its XLA program, so a request mixing
    either is a caller error — rejected here with a pinned message rather
    than silently split (``run_many`` is the auto-grouping entry point).
    Returns the validated configs.
    """
    cfgs = [c.validate() for c in cfgs]
    if not cfgs:
        raise ValueError("empty batch: at least one config is required")
    d0 = cfgs[0].delivery
    for c in cfgs[1:]:
        if c.delivery != d0:
            raise ValueError(
                f"batch mixes delivery laws {d0!r} and {c.delivery!r}: one "
                "lane bucket runs one delivery law (split the batch per "
                "delivery, or use run_many to auto-group)")
    p0 = cfgs[0].pack_version
    for c in cfgs[1:]:
        if c.pack_version != p0:
            raise ValueError(
                f"batch mixes spec §2 packing versions v{p0} and "
                f"v{c.pack_version}: one lane bucket draws under one packing "
                "law (split the batch at the n = 1024 packing edge, or use "
                "run_many to auto-group)")
    return cfgs


def _f_opt(n: int) -> int:
    return (n - 1) // 3


# The product scheduling model: what every preset, sweep_point, bench.py and
# ad-hoc CLI run defaults to. Decided by the measured device-busy A/B between
# the count-level samplers (docs/PERF.md round 5: urn2 0.1602 s device /
# urn 0.2759 s at config 4, 1.72x; the committed artifacts/ab_delivery_r5.json
# records walls of 387.0k vs 259.4k inst/s in its — noisier — capture window,
# the 430k wall headline is PERF.md's best session); flipping it re-goldens
# every preset-level artifact, so it changes only with an A/B writeup.
# Round 6 A/B'd §4c "urn3" against it (artifacts/ab_delivery_r6.json;
# docs/PERF.md round 6) — see the ship-or-bury verdict there.
PRODUCT_DELIVERY = "urn2"

# Benchmark presets (BASELINE.json:6-12; pinned in spec/PROTOCOL.md §7).
# All presets pin the product scheduling model; pass delivery="keys"
# explicitly to run the spec-§4 validation model instead.
PRESETS: dict[str, SimConfig] = {
    "config1": SimConfig(protocol="benor", n=4, f=1, instances=1, adversary="none", coin="local", delivery=PRODUCT_DELIVERY),
    "config2": SimConfig(protocol="benor", n=64, f=21, instances=10_000, adversary="crash", coin="local", delivery=PRODUCT_DELIVERY),
    # config3's instance count is the one preset field BASELINE.json leaves
    # unspecified ("—"); 1000 is our choice (big enough for stable histograms,
    # small enough for the oracle-anchored checks), not a [B] requirement.
    "config3": SimConfig(protocol="bracha", n=256, f=85, instances=1_000, adversary="byzantine", coin="shared", delivery=PRODUCT_DELIVERY),
    "config4": SimConfig(protocol="bracha", n=512, f=170, instances=100_000, adversary="none", coin="shared", delivery=PRODUCT_DELIVERY),
}

# Config 5 is a sweep (spec §7): bracha, adaptive adversary, shared coin.
SWEEP_NS = (128, 256, 384, 512, 640, 768, 896, 1024)
# Opt-in extension past the v1 packing edge (spec §2 v2): the first
# count-level cost-curve point beyond the old n=1024 ceiling. Not part of the
# default sweep — the CLI exposes it via `sweep --extended`, and checkpoints
# written for it carry the packing-version token (utils/checkpoint.shard_name).
SWEEP_NS_EXTENDED = SWEEP_NS + (2048,)
SWEEP_INSTANCES = 2_000
# The single sweep point that stands in for config 5 wherever one config is
# needed (tools/product.py, tools/acceptance.py): benchmark n, the headline
# scale. Both tools import this so the two "config5" surfaces cannot diverge.
SWEEP_POINT_N = 512


def sweep_point(n: int, seed: int = 0, instances: int = SWEEP_INSTANCES) -> SimConfig:
    return SimConfig(
        protocol="bracha", n=n, f=_f_opt(n), instances=instances,
        adversary="adaptive", coin="shared", seed=seed,
        delivery=PRODUCT_DELIVERY,
    ).validate()


# The committee benchmark fault fraction (spec §10.3): f = n/5 rather than
# the full-mesh optimum (n-1)/3, because the committee fault budget carries
# a +sqrt(C) sampling margin — at f = n/3 the margin consumes the whole
# bracha 3·f_C < C headroom. n/5 is the largest simple fraction that keeps
# every committee tier (C from 16 to 160) resilient for bracha.
COMMITTEE_FAULT_DIV = 5


def committee_point(n: int, seed: int = 0,
                    instances: int = SWEEP_INSTANCES) -> SimConfig:
    """The config-5-shaped committee benchmark point (spec §10): the same
    bracha/adaptive/shared shape as :func:`sweep_point` so cost curves
    compare like against like, with the §10.3 fault fraction."""
    return SimConfig(
        protocol="bracha", n=n, f=n // COMMITTEE_FAULT_DIV,
        instances=instances, adversary="adaptive", coin="shared", seed=seed,
        delivery="committee",
    ).validate()


def preset(name: str, **overrides) -> SimConfig:
    cfg = PRESETS[name]
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    return cfg.validate()
