"""Tunnel-resilient device discovery (docs/NEXT.md item 6; VERDICT r1 #8).

The environment may carry an ``axon`` TPU-tunnel PJRT plugin registered from
``sitecustomize`` in every interpreter. When the relay tunnel is dead, the
*first backend initialization* (``jax.devices()`` or any traced op) dials it
and blocks indefinitely — including for ``JAX_PLATFORMS=cpu`` requests,
because the plugin's registration pins ``jax.config.jax_platforms``.
tests/conftest.py solves this for the test process; this module is the same
defense for headless ``bench.py`` / CLI runs.

Strategy: probe device initialization in a *subprocess* with a timeout (a
thread cannot be used — a hung in-process probe would wedge xla_bridge's init
lock for the whole process), and on hang/failure drop the tunnel plugin and
force the CPU platform before this process touches any device.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Callable, Optional

_PROBE_CODE = "import jax; jax.devices()"


def _drop_accelerator_plugins() -> None:
    """Force the CPU platform in this process (same dance as tests/conftest.py)."""
    try:
        from jax._src import xla_bridge as xb

        # Drop only tunnel-style plugins. The builtin "tpu" factory must stay
        # registered even when unusable: Pallas registers MLIR lowering rules
        # for the "tpu" platform at import, which requires it to be *known* —
        # popping it turns every interpret-mode Pallas test into
        # NotImplementedError ("unknown platform tpu").
        for name in list(xb._backend_factories):
            if name not in ("cpu", "tpu"):
                xb._backend_factories.pop(name, None)
        import jax

        if xb.backends_are_initialized():
            from jax.extend.backend import clear_backends

            clear_backends()
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        # Private-API drift: leave the env-var layer (set by our caller) to do
        # what it can rather than failing the run outright.
        pass
    os.environ["JAX_PLATFORMS"] = "cpu"


def _tunnel_hazard_present() -> bool:
    """True iff a tunnel-style PJRT plugin that can hang init is registered.

    On plugin-free machines the probe (a full child-interpreter jax import +
    device init) would be pure startup latency, so callers skip it.

    The env-var markers are checked first and unconditionally: a tunnel
    plugin is free to register under the standard "tpu" factory name, in
    which case the factory-name scan below would miss it (ADVICE r2).
    Whenever the tunnel's own configuration variables are present, probe.
    The marker set is scoped to the tunnel's actual variable family
    (PALLAS_AXON_* / AXON_LOOPBACK_RELAY) — a bare "AXON_" prefix would
    drag unrelated variables into a 45 s probe on plugin-free machines.
    """
    if any(k.startswith("PALLAS_AXON") for k in os.environ) or \
            "AXON_LOOPBACK_RELAY" in os.environ or \
            "axon" in os.environ.get("JAX_PLATFORMS", ""):
        return True
    try:
        from jax._src import xla_bridge as xb

        return any(name not in ("cpu", "tpu") for name in xb._backend_factories)
    except Exception:
        return True  # can't tell — probe to be safe


def _default_probe(timeout_s: float) -> bool:
    """True iff a fresh interpreter can initialize jax devices in time."""
    try:
        subprocess.run([sys.executable, "-c", _PROBE_CODE], check=True,
                       capture_output=True, timeout=timeout_s)
        return True
    except (subprocess.TimeoutExpired, subprocess.CalledProcessError, OSError):
        return False


def ensure_live_backend(timeout_s: float = 45.0,
                        probe: Optional[Callable[[float], bool]] = None,
                        force_cpu: Optional[Callable[[], None]] = None,
                        warn=None) -> str:
    """Make sure this process's first jax device init cannot hang.

    Returns ``"no-hazard"`` (no tunnel plugin registered — nothing can hang),
    ``"cpu-env"`` (platform forced to CPU; plugin dropped, no probe needed),
    ``"ok"`` (probe initialized devices; this process can safely do the same),
    or ``"cpu-fallback"`` (probe hung/failed; accelerator plugins dropped and
    CPU forced in this process). ``probe``/``force_cpu`` are injectable for
    unit tests (tests/test_devices.py).
    """
    probe = probe or _default_probe
    force_cpu = force_cpu or _drop_accelerator_plugins
    if not _tunnel_hazard_present():
        return "no-hazard"
    if os.environ.get("JAX_PLATFORMS", "").split(",")[0] == "cpu":
        # CPU explicitly requested: no probe needed, but the tunnel plugin must
        # still be dropped — its registration pins jax.config.jax_platforms
        # OVER the env var, so a poisoned interpreter would hang regardless.
        force_cpu()
        return "cpu-env"
    if probe(timeout_s):
        return "ok"
    if warn is None:
        warn = lambda m: print(m, file=sys.stderr)  # noqa: E731
    warn(f"warning: device initialization did not come up within {timeout_s:.0f}s "
         "(accelerator tunnel down?); falling back to the CPU platform")
    force_cpu()
    return "cpu-fallback"
