"""Round/decision histograms and headline metrics (SURVEY.md C8; BASELINE.json:2).

Histograms are derived from the per-instance (rounds, decision) arrays — the bit-match
surface — and include the overflow bucket for capped instances (SURVEY.md §7
hard-part 2): ``decision == 2`` marks undecided-at-cap, and such instances sit in the
``rounds == round_cap`` bin.
"""

from __future__ import annotations

import json
import math

import numpy as np

from byzantinerandomizedconsensus_tpu.backends.base import SimResult


def round_histogram(res: SimResult) -> np.ndarray:
    """(round_cap + 1,) int64 — counts of rounds-to-decision; index r = "terminated in
    r rounds" (index 0 unused), with capped instances in the final bin."""
    return np.bincount(res.rounds, minlength=res.config.round_cap + 1).astype(np.int64)


def decision_histogram(res: SimResult) -> np.ndarray:
    """(3,) int64 — counts of decisions 0, 1, and 2 (= undecided at cap)."""
    return np.bincount(res.decision, minlength=3).astype(np.int64)


def percentiles(values, qs=(50, 90, 99)) -> list:
    """Exact nearest-rank percentiles, one per ``q`` in ``qs`` (percent,
    0 < q <= 100): the q-th percentile is the ceil(q·N/100)-th smallest
    element — no interpolation, so the returned value is always an element
    of ``values`` (int rounds stay exact ints). Empty input maps every q to
    None. The ONE quantile implementation the trace digests (obs/trace.py),
    ``summary``'s rounds percentiles, and the serving loop's future p50/p99
    request-latency targets (ROADMAP #1) share."""
    vals = sorted(np.asarray(values).ravel().tolist())
    n = len(vals)
    out = []
    for q in qs:
        if not (0 < q <= 100):
            raise ValueError(f"percentile {q} out of range (0, 100]")
        if n == 0:
            out.append(None)
            continue
        out.append(vals[max(1, math.ceil(q * n / 100.0)) - 1])
    return out


def mean_max_rounds_per_chunk(rounds: np.ndarray, chunk: int) -> float | None:
    """Mean over chunks of the chunk's max rounds-to-termination — the
    while-loop straggler statistic docs/PERF.md round 1 derived by hand
    (every instance of a jit'd chunk pays the chunk's max rounds). Chunks
    are consecutive ``chunk``-sized windows of the rounds array, the exact
    partition the dispatch loop uses (backends/base.py::_dispatch_chunks);
    the padded tail repeats real instances, so its max equals the tail max.
    """
    rounds = np.asarray(rounds)
    if chunk < 1:
        raise ValueError(f"chunk={chunk} out of range (>= 1)")
    if rounds.size == 0:
        return None
    return float(np.mean([rounds[lo:lo + chunk].max()
                          for lo in range(0, len(rounds), chunk)]))


def wasted_lane_fraction(rounds: np.ndarray, chunk: int) -> float | None:
    """Fraction of device lane-rounds the straggler effect wastes:
    ``1 − Σ per-instance rounds / Σ chunk-cost``, where a chunk's cost is
    its max rounds × the full compiled chunk width (the tail chunk is padded
    to ``chunk`` — backends/base.py — so the device really pays full width).
    0 = every executed lane-round was an undecided instance's own round;
    the docs/PERF.md round-1 accounting (mean max-rounds 2.08 vs mean rounds
    1.42) is this metric's numerator/denominator read off by hand.
    """
    rounds = np.asarray(rounds)
    if chunk < 1:
        raise ValueError(f"chunk={chunk} out of range (>= 1)")
    if rounds.size == 0:
        return None
    device = sum(int(rounds[lo:lo + chunk].max()) * chunk
                 for lo in range(0, len(rounds), chunk))
    if device == 0:
        return 0.0
    return float(round(1.0 - int(rounds.sum()) / device, 6))


def summary(res: SimResult, walls=None, device=None, chunk=None) -> dict:
    """One dict answering the first triage questions: did it decide
    (``decided_fraction``), how fast in rounds (``mean_rounds_decided``), and
    — when the timing legs are passed — how fast on the clock.

    ``walls``: the timed-run list from utils/timing.timed_best_of; adds the
    best-of wall, the full ``walls_s`` + spread, and recomputes
    ``instances_per_sec`` from the unrounded best. ``device``: the
    utils/timing.device_busy dict; adds ``device_busy_s`` or its honest
    ``device_busy_error`` (absence-of-signal 0.0s are errors, never
    measurements — VERDICT r5 weak #1). Both default to None, leaving the
    plain result-surface summary unchanged.

    ``chunk``: the backend's instances-per-dispatch; adds the standard
    straggler metrics (``wasted_lane_fraction``, ``mean_max_rounds_per_
    chunk`` — docs/PERF.md round 1's hand-derived accounting as a first-
    class metric; ISSUE 6 satellite).
    """
    decided = res.decision != 2
    dh = decision_histogram(res)
    n_inst = int(len(res.inst_ids))
    out = {
        "protocol": res.config.protocol,
        "n": res.config.n,
        "f": res.config.f,
        "adversary": res.config.adversary,
        "coin": res.config.coin,
        "delivery": res.config.delivery,
        "faults": res.config.faults,
        "seed": res.config.seed,
        "instances": n_inst,
        "decided": int(decided.sum()),
        "decided_fraction": round(int(decided.sum()) / n_inst, 6) if n_inst else None,
        "undecided_at_cap": int(dh[2]),
        "round_cap": res.config.round_cap,
        "mean_rounds_decided": float(res.rounds[decided].mean()) if decided.any() else None,
        "max_rounds": int(res.rounds.max()) if len(res.rounds) else 0,
        # Exact nearest-rank percentiles over ALL instances (capped ones sit
        # at round_cap — the tail a p99 exists to expose), shared with the
        # trace digests via the one percentiles() implementation.
        **dict(zip(("rounds_p50", "rounds_p90", "rounds_p99"),
                   percentiles(res.rounds, (50, 90, 99)))),
        "decision_histogram": dh.tolist(),
        "wall_s": res.wall_s,
        "instances_per_sec": res.instances_per_sec if res.wall_s else None,
    }
    if chunk is not None:
        out["chunk"] = int(chunk)
        out["wasted_lane_fraction"] = wasted_lane_fraction(res.rounds, chunk)
        out["mean_max_rounds_per_chunk"] = mean_max_rounds_per_chunk(
            res.rounds, chunk)
    if walls is not None or device is not None:
        from byzantinerandomizedconsensus_tpu.obs import record

        out.update(record.timing_block(walls or [res.wall_s], device))
        if walls:
            out["instances_per_sec"] = round(n_inst / min(walls), 1)
    return out


def dump_summary(res: SimResult) -> str:
    return json.dumps(summary(res))
