"""Metrics, histograms, and sweep checkpointing (SURVEY.md C8, §5)."""
