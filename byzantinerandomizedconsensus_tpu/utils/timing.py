"""Shared wall-clock methodology for the headline bench and product artifacts.

One implementation of the measurement discipline docs/PERF.md prescribes for
the tunnelled TPU (bench.py and tools/product.py must not diverge):

- compile OUTSIDE the timed window — one warm-up run at the exact chunk shape
  the timed run uses (a smaller warm-up batch would compile a different
  program and leave the real compile inside the timing). Warm-up happens only
  for backends that actually jit (``needs_warmup``) — the pure-host numpy/
  cpu/native paths have nothing to compile (ADVICE r3);
- best-of-N timed full runs, N=5 by default (VERDICT r3 weak #2: tunnel
  latency varies ±10-15% run-to-run, and a best-of-2 sample from that
  distribution false-negatives real ~20% regressions routinely; five runs put
  the best-of estimate's spread well under the 15% explain-or-noise rule);
- artifacts record the full ``walls_s`` list so best AND dispersion are on
  the record;
- rates computed from the unrounded minimum (rounding first can zero a
  sub-millisecond leg);
- a **device-busy** leg next to the walls (VERDICT r4 #2): the profiler's
  summed device program time is bit-stable across captures while tunnel
  walls swing 40-80% in bad windows (docs/PERF.md round 4), so artifacts
  carry both signals and :func:`regression_verdict` encodes which one a
  regression claim may key on.
"""

from __future__ import annotations

import pathlib
import time

import numpy as np

DEFAULT_REPEATS = 5

# Above this (max-min)/min wall dispersion, wall-based vs_prev_round is
# uninformative for sub-second runs (docs/PERF.md round 4: spreads of 41-76%
# observed while the profiler device time was bit-identical) — regression
# verdicts must key on device-busy time instead.
NOISY_WALLS_SPREAD = 0.3

# The explain-or-noise bound on the authoritative ratio (VERDICT r2 #4 /
# docs/PERF.md): tunnel variance is ±10-15%, so |ratio - 1| > 0.15 is a real
# change that must be explained in PERF.md — and what the ledger's
# regression sentinel (`brc-tpu ledger --check`) fails on mechanically when
# a committed chain link drops below 1 - REGRESSION_THRESHOLD.
REGRESSION_THRESHOLD = 0.15


def timed_best_of(be, cfg, repeats: int = DEFAULT_REPEATS):
    """(result, walls) — warmed, ``repeats`` timed full runs of ``cfg``.

    ``be`` is a backend instance; the warm-up run happens only when the
    backend jits (``needs_warmup``), at the exact chunk shape of the run.
    """
    if be.needs_warmup:
        chunk = min(be._chunk_size(cfg), cfg.instances)
        be.run(cfg, np.arange(chunk, dtype=np.int64))
    walls, res = [], None
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        res = be.run(cfg)
        walls.append(time.perf_counter() - t0)
    return res, walls


def spread(walls) -> float:
    """(max-min)/min of a timed-run list — the dispersion recorded next to the
    best-of figure so 'within tunnel noise' claims are checkable."""
    w = sorted(walls)
    return (w[-1] - w[0]) / w[0] if w and w[0] > 0 else 0.0


def trace_snapshot(trace_dir) -> dict:
    """{path: (mtime_ns, size)} of every trace file currently under
    ``trace_dir`` — taken *before* a capture so parse_trace can tell this
    run's output apart from leftovers in a reused dir. Keyed on
    (st_mtime_ns, st_size), not bare mtime: an overwrite landing in the same
    coarse-mtime quantum must still count as fresh (ADVICE r4)."""
    d = pathlib.Path(trace_dir)
    if not d.exists():
        return {}
    return {p: (p.stat().st_mtime_ns, p.stat().st_size)
            for p in d.rglob("*.trace.json.gz")}


def parse_trace(trace_dir, before: dict | None = None) -> dict:
    """Device busy time + top device ops from the newest trace.json.gz under
    ``trace_dir`` that this run produced: a file counts iff it is a new path
    or its (mtime_ns, size) changed vs the ``before`` snapshot
    (trace_snapshot). A failed capture must surface as an error, never
    silently reparse a stale trace — and an overwrite of a previous run's
    path still counts as fresh. Durations are summed per op name over
    device-pid complete events; ``device_busy_s`` sums the top-level jit
    program executions (child events nest inside them, so summing everything
    would double-count)."""
    import collections
    import gzip
    import json

    before = before or {}
    paths = sorted(
        (p for p in pathlib.Path(trace_dir).rglob("*.trace.json.gz")
         if p not in before
         or (p.stat().st_mtime_ns, p.stat().st_size) != before[p]),
        key=lambda p: p.stat().st_mtime_ns)
    if not paths:
        return {"error": "no new trace.json.gz produced by this run"}
    with gzip.open(paths[-1]) as fh:
        doc = json.load(fh)
    ev = doc.get("traceEvents", [])
    dev_pids = {e["pid"] for e in ev
                if e.get("ph") == "M" and e.get("name") == "process_name"
                and "TPU" in str(e.get("args", {}).get("name", ""))}
    per_op = collections.Counter()
    busy = 0.0
    n_dev_events = 0
    for e in ev:
        if e.get("ph") == "X" and e.get("pid") in dev_pids:
            n_dev_events += 1
            name = e.get("name", "?")
            per_op[name] += e.get("dur", 0)
            if name.startswith("jit_"):
                busy += e.get("dur", 0)
    out = {
        "source": str(paths[-1]),
        "device_busy_s": round(busy / 1e6, 4),
        "top_device_ops_s": {k: round(v / 1e6, 4)
                             for k, v in per_op.most_common(8)},
    }
    if busy == 0.0:
        # A zero here is absence of signal unless proven otherwise — the
        # silent-0.0 failure VERDICT r5 weak #1 targeted. Distinguish the
        # three ways the signal can be absent so the artifact says why, and
        # so regression_verdict's >0 guard refuses the ratio.
        if not dev_pids:
            # CPU-only session, or a capture that missed the device.
            out["device_busy_suspect"] = (
                "no TPU device pids in trace (CPU-only session?) — "
                "device_busy_s is NOT a measurement")
        elif n_dev_events:
            # Device events exist but none match the jit_ program-name
            # convention: PJRT/plugin op-naming drift.
            out["device_busy_suspect"] = (
                f"{n_dev_events} device X events but 0 'jit_'-prefixed "
                "matches — PJRT op-naming drift? device_busy_s is NOT a "
                "measurement")
        else:
            # TPU pids registered but zero complete events: the dispatch
            # fell outside the captured window.
            out["device_busy_suspect"] = (
                "TPU device pids present but zero X events — empty capture "
                "window? device_busy_s is NOT a measurement")
    return out


def device_busy(be, cfg, trace_dir=None) -> dict:
    """Profiler-measured device-busy time of one warmed full run of ``cfg``.

    The noise-immune half of the perf record (VERDICT r4 #2): dispatches the
    backend's own chunked program under ``jax.profiler`` and parses the trace.
    Assumes the program is already compiled (call after timed_best_of).
    Returns ``{"device_busy_s": ...}`` or ``{"error": ...}`` — host-only
    backends and failed captures degrade to an error entry, never raise.
    """
    if not getattr(be, "needs_warmup", False):
        return {"error": f"backend {be.name!r} runs on host; no device trace"}
    import contextlib
    import tempfile

    import jax

    from byzantinerandomizedconsensus_tpu.utils import profiling

    cleanup = contextlib.nullcontext(trace_dir) if trace_dir \
        else tempfile.TemporaryDirectory(prefix="device_busy_")
    try:
        with cleanup as tdir:
            ids = np.arange(cfg.instances, dtype=np.int64)
            chunk = be._clamp_chunk(cfg,
                                    min(be._chunk_size(cfg), max(1, len(ids))))
            fn = be._fn(cfg)
            extra = be._extra_args(cfg)
            before = trace_snapshot(tdir)
            # _device_ctx: device-pinned backends (jax_cpu) must be profiled
            # on THEIR device, not the JAX default the bare dispatch would use.
            with be._device_ctx(), profiling.trace(tdir):
                jax.block_until_ready(be._dispatch_chunks(fn, ids, chunk, extra))
            out = parse_trace(tdir, before=before)
        out.pop("top_device_ops_s", None)  # bench/product records stay small
        if not trace_dir:
            # The TemporaryDirectory is gone by now — a 'source' path into it
            # would be a dangling reference in the artifact (ADVICE r5 #3).
            # Kept only when the caller supplied a persistent trace_dir.
            out.pop("source", None)
        return out
    except Exception as e:  # tunnel profilers can be unsupported
        return {"error": repr(e)}


def regression_verdict(walls, prev_wall_rate=None, rate=None,
                       device_busy_s=None, prev_device_busy_s=None) -> dict:
    """Machine-readable explain-or-noise record (VERDICT r4 #2).

    Encodes the PERF.md rule: when the wall spread exceeds
    ``NOISY_WALLS_SPREAD``, wall-based ``vs_prev_round`` is uninformative and
    the regression signal is the device-busy ratio (when both rounds have
    one); otherwise the wall ratio stands. Returns a dict to merge into the
    artifact: ``regression_signal`` names the authoritative field.
    """
    sp = spread(walls)
    out = {"walls_spread": round(sp, 3)}
    if rate is not None and prev_wall_rate:
        out["vs_prev_round"] = round(rate / prev_wall_rate, 3)
    # Strictly-positive check, not truthiness: a sub-50µs device leg rounds to
    # 0.0 (a valid measurement, but no ratio can be formed from it).
    if (device_busy_s or 0) > 0 and (prev_device_busy_s or 0) > 0:
        # device ratio oriented like the wall ratio: >1 = faster than prev.
        out["vs_prev_round_device"] = round(prev_device_busy_s / device_busy_s, 3)
    if sp > NOISY_WALLS_SPREAD:
        out["regression_signal"] = (
            "vs_prev_round_device" if "vs_prev_round_device" in out
            else "none: walls too noisy "
                 f"(spread {sp:.2f} > {NOISY_WALLS_SPREAD}) and no device-busy "
                 "comparison available")
    elif "vs_prev_round" in out:
        out["regression_signal"] = "vs_prev_round"
    return out
