"""Shared wall-clock methodology for the headline bench and product artifacts.

One implementation of the measurement discipline docs/PERF.md prescribes for
the tunnelled TPU (bench.py and tools/product.py must not diverge):

- compile OUTSIDE the timed window — one warm-up run at the exact chunk shape
  the timed run uses (a smaller warm-up batch would compile a different
  program and leave the real compile inside the timing);
- best-of-N timed full runs (tunnel latency varies ±10-15% run-to-run and the
  program's throughput is the quantity of interest);
- rates computed from the unrounded minimum (rounding first can zero a
  sub-millisecond leg).
"""

from __future__ import annotations

import time

import numpy as np


def timed_best_of(be, cfg, repeats: int = 2):
    """(result, walls) — warmed, ``repeats`` timed full runs of ``cfg``.

    ``be`` is a backend instance. Backends without a ``_chunk_size`` (the
    pure-host cpu/native paths) have nothing to compile, so they skip the
    warm-up instead of paying a full extra run.
    """
    chunk_size = getattr(be, "_chunk_size", None)
    if chunk_size is not None:
        chunk = min(chunk_size(cfg), cfg.instances)
        be.run(cfg, np.arange(chunk, dtype=np.int64))
    walls, res = [], None
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        res = be.run(cfg)
        walls.append(time.perf_counter() - t0)
    return res, walls
