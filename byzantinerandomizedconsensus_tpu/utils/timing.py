"""Shared wall-clock methodology for the headline bench and product artifacts.

One implementation of the measurement discipline docs/PERF.md prescribes for
the tunnelled TPU (bench.py and tools/product.py must not diverge):

- compile OUTSIDE the timed window — one warm-up run at the exact chunk shape
  the timed run uses (a smaller warm-up batch would compile a different
  program and leave the real compile inside the timing). Warm-up happens only
  for backends that actually jit (``needs_warmup``) — the pure-host numpy/
  cpu/native paths have nothing to compile (ADVICE r3);
- best-of-N timed full runs, N=5 by default (VERDICT r3 weak #2: tunnel
  latency varies ±10-15% run-to-run, and a best-of-2 sample from that
  distribution false-negatives real ~20% regressions routinely; five runs put
  the best-of estimate's spread well under the 15% explain-or-noise rule);
- artifacts record the full ``walls_s`` list so best AND dispersion are on
  the record;
- rates computed from the unrounded minimum (rounding first can zero a
  sub-millisecond leg).
"""

from __future__ import annotations

import time

import numpy as np

DEFAULT_REPEATS = 5


def timed_best_of(be, cfg, repeats: int = DEFAULT_REPEATS):
    """(result, walls) — warmed, ``repeats`` timed full runs of ``cfg``.

    ``be`` is a backend instance; the warm-up run happens only when the
    backend jits (``needs_warmup``), at the exact chunk shape of the run.
    """
    if be.needs_warmup:
        chunk = min(be._chunk_size(cfg), cfg.instances)
        be.run(cfg, np.arange(chunk, dtype=np.int64))
    walls, res = [], None
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        res = be.run(cfg)
        walls.append(time.perf_counter() - t0)
    return res, walls


def spread(walls) -> float:
    """(max-min)/min of a timed-run list — the dispersion recorded next to the
    best-of figure so 'within tunnel noise' claims are checkable."""
    w = sorted(walls)
    return (w[-1] - w[0]) / w[0] if w and w[0] > 0 else 0.0
