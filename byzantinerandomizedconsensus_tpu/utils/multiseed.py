"""Multi-seed sharding for runs beyond the PRF packing limit (spec §2).

The counter packing caps one seed at 2^17 instances (2^16 under the §2 v2
wide-n law); larger Monte-Carlo totals shard across *derived seeds* — shard k
simulates ``instances_k ≤`` the cap under ``seed_k = splitmix64(seed + k)``,
and per-shard results remain
individually bit-matchable (a shard is just an ordinary run of its derived
config). SplitMix64 (Steele et al., OOPSLA 2014) is the standard seed-spacing
finaliser; consecutive inputs map to statistically independent outputs.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from byzantinerandomizedconsensus_tpu.backends.base import SimResult, get_backend
from byzantinerandomizedconsensus_tpu.config import SimConfig
from byzantinerandomizedconsensus_tpu.ops import prf

_MASK64 = (1 << 64) - 1


def splitmix64(x: int) -> int:
    """SplitMix64 finaliser — uint64 in, uint64 out."""
    x = (x + 0x9E3779B97F4A7C15) & _MASK64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _MASK64
    return x ^ (x >> 31)


def shard_seed(seed: int, k: int) -> int:
    return splitmix64((seed & _MASK64) + k)


def run_large(cfg: SimConfig, total_instances: int, backend: str = "jax",
              shard_instances: int = 0, progress=None):
    """Run ``total_instances`` Monte-Carlo trials of ``cfg`` across derived seeds.

    Returns ``(result, shards)``: ``result`` is a merged :class:`SimResult`
    (``inst_ids`` globally numbered 0..total-1; its config is the *user's*
    ``cfg`` with ``instances=total_instances``, so summaries report the base
    seed — per-shard derived seeds live in ``shards``) and ``shards`` the
    list of per-shard ``SimConfig``s for reproducing any shard standalone
    (e.g. to bit-match a sampled subset against the oracle).
    """
    if total_instances <= 0:
        raise ValueError("total_instances must be positive")
    # The per-seed instance ceiling depends on the spec §2 packing law the
    # config draws under (v2 narrows the instance field); 0 = "the cap".
    per_seed_cap = prf.MAX_INSTANCES if cfg.pack_version == 1 \
        else prf.V2_MAX_INSTANCES
    shard_instances = min(shard_instances or per_seed_cap, per_seed_cap)
    be = get_backend(backend)
    rounds, decisions, shards = [], [], []
    k = 0
    done = 0
    wall = 0.0
    while done < total_instances:
        count = min(shard_instances, total_instances - done)
        sub = dataclasses.replace(cfg, seed=shard_seed(cfg.seed, k),
                                  instances=count).validate()
        res = be.timed_run(sub)
        wall += res.wall_s
        shards.append(sub)
        rounds.append(res.rounds)
        decisions.append(res.decision)
        if progress is not None:
            progress(f"shard {k}: {count} instances, "
                     f"{res.instances_per_sec:.0f} inst/s")
        done += count
        k += 1
    # Not .validate()d: total_instances may legitimately exceed the per-seed
    # packing limit — that is the whole point of multi-seed sharding.
    merged = SimResult(
        config=dataclasses.replace(cfg, instances=total_instances),
        inst_ids=np.arange(total_instances, dtype=np.int64),
        rounds=np.concatenate(rounds),
        decision=np.concatenate(decisions),
        wall_s=wall,
    )
    return merged, shards
