"""Config-5 sweep driver (SURVEY.md §3.5, C9): n in {128..1024}, f = (n-1)//3,
adaptive adversary, round-distribution as the artifact. Resumable via checkpoint
shards; instances are chunked so an interrupted point restarts mid-way, not from 0.
"""

from __future__ import annotations

import pathlib
import re
from typing import Iterable, Optional

import numpy as np

from byzantinerandomizedconsensus_tpu.backends.base import get_backend
from byzantinerandomizedconsensus_tpu.config import (
    DEFAULT_ROUND_CAP, PRODUCT_DELIVERY, SWEEP_INSTANCES, SWEEP_NS, sweep_point)
from byzantinerandomizedconsensus_tpu.utils import checkpoint, metrics


def run_sweep(
    out_dir: pathlib.Path,
    backend: str = "jax",
    ns: Iterable[int] = SWEEP_NS,
    instances: int = SWEEP_INSTANCES,
    seed: int = 0,
    shard_instances: int = 500,
    coin: str = "shared",
    delivery: str = PRODUCT_DELIVERY,
    round_cap: int | None = None,
    batched: bool = False,
    progress=print,
) -> dict:
    """Run (or resume) the sweep; returns {n: summary-with-round-histogram}.

    ``batched`` routes each shard row through the shape-bucketed lane runner
    (backends/batch.py) when the backend supports it: sweep points whose n
    pads to one tier (e.g. 384 with 512; 640/768/896 with 1024) share one
    compiled program and one dispatch per shard, bit-identically. Checkpoint
    shards stay per-(n, shard) and resume exactly as before; a batched
    shard's recorded wall is the dispatch wall split evenly across the lanes
    it served (per-lane walls do not exist in one fused dispatch).
    """
    import dataclasses

    be = get_backend(backend)
    eff_cap = DEFAULT_ROUND_CAP if round_cap is None else round_cap
    _warn_stale_shards(out_dir, delivery, eff_cap, progress)

    def point_cfg(n):
        cfg = sweep_point(n, seed=seed, instances=instances)
        if coin != cfg.coin or delivery != cfg.delivery or \
                (round_cap is not None and round_cap != cfg.round_cap):
            cfg = dataclasses.replace(
                cfg, coin=coin, delivery=delivery,
                round_cap=cfg.round_cap if round_cap is None else round_cap,
            ).validate()
        return cfg

    ns = list(ns)
    cfgs = {n: point_cfg(n) for n in ns}
    shards_by_n: dict = {n: {} for n in ns}

    if batched and hasattr(be, "run_many"):
        from byzantinerandomizedconsensus_tpu.backends import batch as _batch

        for lo in range(0, instances, shard_instances):
            hi = min(lo + shard_instances, instances)
            missing = []
            for n in ns:
                cfg = cfgs[n]
                if checkpoint.have_shard(out_dir, cfg, lo, hi):
                    shards_by_n[n][lo] = checkpoint.load_shard(
                        out_dir / checkpoint.shard_name(cfg, lo, hi))
                else:
                    missing.append(n)
            if not missing:
                continue
            ids = np.arange(lo, hi, dtype=np.int64)
            import time as _time

            t0 = _time.perf_counter()
            results, _report = _batch.run_many(
                be, [cfgs[n] for n in missing],
                inst_ids=[ids] * len(missing))
            wall = _time.perf_counter() - t0
            for n, res in zip(missing, results):
                res.wall_s = wall / len(missing)
                checkpoint.save_shard(out_dir, cfgs[n], res)
                shards_by_n[n][lo] = res
            progress(f"sweep shard [{lo},{hi}) batched over n={missing}: "
                     f"{(hi - lo) * len(missing) / max(wall, 1e-9):.0f} "
                     "inst/s aggregate")
    else:
        for n in ns:
            cfg = cfgs[n]
            for lo in range(0, instances, shard_instances):
                hi = min(lo + shard_instances, instances)
                if checkpoint.have_shard(out_dir, cfg, lo, hi):
                    shards_by_n[n][lo] = checkpoint.load_shard(
                        out_dir / checkpoint.shard_name(cfg, lo, hi))
                    continue
                res = be.timed_run(cfg, np.arange(lo, hi, dtype=np.int64))
                checkpoint.save_shard(out_dir, cfg, res)
                shards_by_n[n][lo] = res
                progress(f"sweep n={n}: instances [{lo},{hi}) "
                         f"{res.instances_per_sec:.0f} inst/s")

    out = {}
    for n in ns:
        shards = [shards_by_n[n][lo] for lo in sorted(shards_by_n[n])]
        merged = _merge(cfgs[n], shards)
        s = metrics.summary(merged)
        s["round_histogram"] = metrics.round_histogram(merged).tolist()
        out[n] = s
    return out


def sweep_record(points: dict, backend: str, delivery: str) -> dict:
    """Wrap a :func:`run_sweep` result in the unified run-record head
    (obs/record.py): the sweep artifact the CLI emits carries the same
    ``record_version``/``kind``/``env`` fingerprint as every other tool's,
    with the per-n summaries under ``points`` (keys stringified, as any
    JSON round-trip would)."""
    from byzantinerandomizedconsensus_tpu.obs import record

    return {
        **record.new_record("sweep"),
        "backend": backend,
        "delivery": delivery,
        "points": {str(n): s for n, s in points.items()},
    }


def _warn_stale_shards(out_dir: pathlib.Path, delivery: str, round_cap: int,
                       progress) -> None:
    """Surface checkpoint shards that cannot resume under the current delivery
    model, round cap, or packing version — e.g. keys-named shards from before
    the urn default flip, cap-128 shards against a cap-256 sweep, or wide-n
    shards whose "_pN" token names a different spec §2 packing law than the
    current code derives for their n. They are ignored (shard names encode all
    three fields — see checkpoint.shard_name), which silently restarts the
    sweep from zero unless the user is told."""
    from byzantinerandomizedconsensus_tpu.ops import prf

    if not out_dir.is_dir():
        return
    stale = []
    pack_stale = []
    for p in out_dir.glob("*.npz"):
        if "_urn3_" in p.name:
            named_delivery = "urn3"
        elif "_urn2_" in p.name:
            named_delivery = "urn2"
        elif "_urn_" in p.name:
            named_delivery = "urn"
        else:
            named_delivery = "keys"  # legacy names carry no delivery token
        m = re.search(r"_c(\d+)_", p.name)
        named_cap = int(m.group(1)) if m else DEFAULT_ROUND_CAP  # legacy names
        # Packing-version token: legacy (token-less) names are v1 shards. A
        # shard whose token disagrees with what pack_version(n) derives today
        # was written under a different §2 law and may never resume.
        m_p = re.search(r"_p(\d+)_s", p.name)
        named_pack = int(m_p.group(1)) if m_p else 1
        m_n = re.search(r"_n(\d+)_", p.name)
        try:
            current_pack = prf.pack_version(int(m_n.group(1))) if m_n else 1
        except ValueError:  # n beyond any law this code knows — stale by definition
            current_pack = -1
        if named_pack != current_pack:
            pack_stale.append(p.name)
        elif delivery != named_delivery or named_cap != round_cap:
            stale.append(p.name)
    if stale:
        progress(
            f"warning: {len(stale)} checkpoint shard(s) in {out_dir} belong to a "
            f"different delivery model or round cap (e.g. {stale[0]}) and will "
            f"NOT resume this delivery={delivery!r} round_cap={round_cap} sweep; "
            "pass matching --delivery/--round-cap or use a fresh --out directory")
    if pack_stale:
        progress(
            f"warning: {len(pack_stale)} checkpoint shard(s) in {out_dir} carry "
            f"a stale spec §2 packing-version token (e.g. {pack_stale[0]}): "
            "they were written under a different packing law than the current "
            "code uses at their n and will NOT resume; re-run those points in "
            "a fresh --out directory")


def _merge(cfg, shards):
    from byzantinerandomizedconsensus_tpu.backends.base import SimResult

    return SimResult(
        config=cfg,
        inst_ids=np.concatenate([s.inst_ids for s in shards]),
        rounds=np.concatenate([s.rounds for s in shards]),
        decision=np.concatenate([s.decision for s in shards]),
        wall_s=sum(s.wall_s for s in shards),
    )
