"""Tracing/profiling (SURVEY.md §5): jax.profiler traces around the jit'd round
kernel, viewable in TensorBoard/Perfetto, plus a no-op fallback when profiling is
unavailable (e.g. interpret-mode CI). The headline instances/sec counter itself is
part of SimResult/metrics (timed_run), not of this module.
"""

from __future__ import annotations

import contextlib
import pathlib
import sys


@contextlib.contextmanager
def trace(out_dir=None):
    """Context manager: profile the enclosed device work into ``out_dir``.

    ``None`` disables profiling (no-op), so call sites can thread a CLI flag
    straight through. Trace directories are TensorBoard-/Perfetto-loadable.

    Same guarded fallback as :func:`annotate` when jax is unavailable (the
    module's no-op contract): warn on stderr and still yield, instead of
    dying on the import — a ``--profile DIR`` run in an interpret-mode/no-jax
    environment must degrade to an unprofiled run, not a crash.
    """
    if out_dir is None:
        yield
        return
    try:
        import jax
    except Exception:  # no-op fallback, same contract as annotate's
        print(f"[profiling] jax unavailable: --profile {out_dir} disabled "
              "(running unprofiled)", file=sys.stderr)
        yield
        return

    out = pathlib.Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    with jax.profiler.trace(str(out)):
        yield


def annotate(name: str):
    """Named sub-span — phase labels for both trace surfaces, with the same
    guarded no-op fallback as :func:`trace` when jax is unavailable (the
    module's contract; previously ``annotate`` alone imported jax
    unconditionally and broke the interpret-mode/no-jax promise).

    Enters two scopes at once because they label different timelines:
    ``jax.named_scope`` tags the *traced* ops, so spans opened inside a jit'd
    round body (models/benor.py, models/bracha.py) name the compiled HLO and
    show up on the Perfetto *device* rows of a ``--profile``/trace-dir
    capture; ``jax.profiler.TraceAnnotation`` emits a host TraceMe span,
    which is what labels eager (numpy-backend) phases.
    """
    try:
        import jax
    except Exception:  # no-op fallback, same contract as trace(None)
        return contextlib.nullcontext()
    stack = contextlib.ExitStack()
    stack.enter_context(jax.named_scope(name))
    stack.enter_context(jax.profiler.TraceAnnotation(name))
    return stack
