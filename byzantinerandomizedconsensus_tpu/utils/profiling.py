"""Tracing/profiling (SURVEY.md §5): jax.profiler traces around the jit'd round
kernel, viewable in TensorBoard/Perfetto, plus a no-op fallback when profiling is
unavailable (e.g. interpret-mode CI). The headline instances/sec counter itself is
part of SimResult/metrics (timed_run), not of this module.
"""

from __future__ import annotations

import contextlib
import pathlib


@contextlib.contextmanager
def trace(out_dir=None):
    """Context manager: profile the enclosed device work into ``out_dir``.

    ``None`` disables profiling (no-op), so call sites can thread a CLI flag
    straight through. Trace directories are TensorBoard-/Perfetto-loadable.
    """
    if out_dir is None:
        yield
        return
    import jax

    out = pathlib.Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    with jax.profiler.trace(str(out)):
        yield


def annotate(name: str):
    """Named sub-span inside a trace (shows up on the TraceMe timeline)."""
    import jax

    return jax.profiler.TraceAnnotation(name)
