"""Round-distribution plots (SURVEY.md §5 metrics artifacts; config-5 deliverable).

Renders the sweep's per-n round histograms (the reported artifact of BASELINE.json
config 5) to a PNG/SVG. matplotlib is imported lazily and the functions degrade to a
clear error when it is absent.
"""

from __future__ import annotations

import pathlib
from typing import Mapping


def plot_sweep(sweep_out: Mapping, path, log_y: bool = True, max_round=None) -> None:
    """``sweep_out``: {n: summary-with-round_histogram} as produced by
    utils/sweep.run_sweep (keys may be int or str). Writes the figure to ``path``."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(8, 5))
    for n_key in sorted(sweep_out, key=int):
        s = sweep_out[n_key]
        hist = s["round_histogram"]
        hi = max_round or max(i for i, c in enumerate(hist) if c) + 1
        xs = range(1, hi + 1)
        ys = hist[1:hi + 1]
        ax.plot(xs, ys, marker="o", markersize=3,
                label=f"n={n_key} (f={s['f']})")
    if log_y:
        ax.set_yscale("symlog")
    ax.set_xlabel("rounds to decision")
    ax.set_ylabel("instances")
    ax.set_title(f"round distribution — {s['protocol']}, {s['adversary']} adversary, "
                 f"{s['coin']} coin")
    ax.legend(fontsize=8)
    ax.grid(True, alpha=0.3)
    fig.tight_layout()
    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fig.savefig(path, dpi=150)
    plt.close(fig)
