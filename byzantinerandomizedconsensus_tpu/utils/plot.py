"""Round-distribution plots (SURVEY.md §5 metrics artifacts; config-5 deliverable).

Renders the sweep's per-n round histograms (the reported artifact of BASELINE.json
config 5) to a PNG/SVG. matplotlib is imported lazily and the functions degrade to a
clear error when it is absent.
"""

from __future__ import annotations

import pathlib
from typing import Mapping


def plot_sweep(sweep_out: Mapping, path, log_y: bool = True, max_round=None) -> None:
    """``sweep_out``: {n: summary-with-round_histogram} as produced by
    utils/sweep.run_sweep (keys may be int or str). Writes the figure to ``path``."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(8, 5))
    # Title fields (protocol/adversary/coin) are common across a sweep; read
    # them from the first point rather than whatever the loop last touched.
    first = sweep_out[min(sweep_out, key=int)]
    for n_key in sorted(sweep_out, key=int):
        s = sweep_out[n_key]
        hist = s["round_histogram"]
        hi = max_round or max(i for i, c in enumerate(hist) if c) + 1
        ys = hist[1:hi + 1]  # may stop short of hi when the cap bucket is last
        ax.plot(range(1, 1 + len(ys)), ys, marker="o", markersize=3,
                label=f"n={n_key} (f={s['f']})")
    if log_y:
        ax.set_yscale("symlog")
    ax.set_xlabel("rounds to decision")
    ax.set_ylabel("instances")
    ax.set_title(f"round distribution — {first['protocol']}, {first['adversary']} "
                 f"adversary, {first['coin']} coin")
    ax.legend(fontsize=8)
    ax.grid(True, alpha=0.3)
    fig.tight_layout()
    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fig.savefig(path, dpi=150)
    plt.close(fig)


def plot_round_panels(panels, path, label_fn=None, max_round=None) -> None:
    """Shared multi-panel round-distribution renderer.

    ``panels``: sequence of (title_suffix, {n: summary-with-round_histogram});
    ``label_fn(n_key, summary) -> str`` customises the per-curve legend.
    Used by :func:`plot_coin_contrast` and tools/slack.py.
    """
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    if label_fn is None:
        label_fn = lambda n_key, s: f"n={n_key}"  # noqa: E731
    fig, axes = plt.subplots(1, len(panels), figsize=(6 * len(panels), 5),
                             sharey=True, squeeze=False)
    for ax, (title, out) in zip(axes[0], panels):
        first = out[min(out, key=int)]
        for n_key in sorted(out, key=int):
            s = out[n_key]
            hist = s["round_histogram"]
            hi = max_round or max(i for i, c in enumerate(hist) if c) + 1
            ys = hist[1:hi + 1]
            ax.plot(range(1, 1 + len(ys)), ys, marker="o", markersize=3,
                    label=label_fn(n_key, s))
        ax.set_yscale("symlog")
        ax.set_xlabel("rounds to decision")
        ax.set_title(f"{first['protocol']}, {first['adversary']} — {title}")
        ax.legend(fontsize=8)
        ax.grid(True, alpha=0.3)
    axes[0][0].set_ylabel("instances")
    fig.tight_layout()
    path = pathlib.Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fig.savefig(path, dpi=150)
    plt.close(fig)


def plot_coin_contrast(shared_out: Mapping, local_out: Mapping, path,
                       max_round=None) -> None:
    """Side-by-side round distributions: shared coin (expected O(1) rounds)
    vs local coin (round-cap saturation at f = Θ(n) — SURVEY.md §3.4, the
    reason config 4's shared-coin variant exists)."""
    plot_round_panels([("shared coin", shared_out), ("local coin", local_out)],
                      path, max_round=max_round)
