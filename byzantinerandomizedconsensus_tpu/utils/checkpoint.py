"""Resumable sweep checkpointing (SURVEY.md §5 checkpoint/resume).

Sweeps write one ``.npz`` shard per (config-point, seed-chunk); an interrupted sweep
resumes by skipping shards already on disk. Shard files carry the per-instance arrays
(the bit-match surface), so partial sweeps remain fully auditable.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib

import numpy as np

from byzantinerandomizedconsensus_tpu.backends.base import SimResult
from byzantinerandomizedconsensus_tpu.config import DEFAULT_ROUND_CAP, SimConfig


def shard_name(cfg: SimConfig, lo: int, hi: int) -> str:
    # delivery and round_cap joined the config surface after the original
    # naming scheme; keys / the default cap keep the legacy name so existing
    # sweep checkpoints stay resumable. A non-default cap MUST be encoded:
    # round histograms and the overflow bucket depend on it, so a resumed
    # sweep may never reuse shards computed under a different cap. Likewise
    # the spec §2 packing version: v1 (every n ≤ 1024 config) keeps the
    # legacy name; a v2 config carries the "_p2" token so that if the v2 law
    # ever revs, stale wide-n shards are detectable instead of silently
    # resuming a different draw sequence (utils/sweep._warn_stale_shards).
    deliv = "" if cfg.delivery == "keys" else f"_{cfg.delivery}"
    cap = "" if cfg.round_cap == DEFAULT_ROUND_CAP else f"_c{cfg.round_cap}"
    pack = "" if cfg.pack_version == 1 else f"_p{cfg.pack_version}"
    return (f"{cfg.protocol}_n{cfg.n}_f{cfg.f}_{cfg.adversary}_{cfg.coin}"
            f"{deliv}{cap}{pack}_s{cfg.seed}_i{lo}-{hi}.npz")


def save_shard(out_dir: pathlib.Path, cfg: SimConfig, res: SimResult) -> pathlib.Path:
    out_dir.mkdir(parents=True, exist_ok=True)
    lo, hi = int(res.inst_ids.min()), int(res.inst_ids.max()) + 1
    path = out_dir / shard_name(cfg, lo, hi)
    tmp = path.with_suffix(".tmp.npz")
    np.savez_compressed(
        tmp,
        inst_ids=res.inst_ids,
        rounds=res.rounds,
        decision=res.decision,
        config=np.frombuffer(json.dumps(dataclasses.asdict(cfg)).encode(), dtype=np.uint8),
        wall_s=np.float64(res.wall_s),
    )
    tmp.rename(path)  # atomic publish: partial writes never count as done
    return path


def load_shard(path: pathlib.Path) -> SimResult:
    data = np.load(path)
    cfg = SimConfig(**json.loads(bytes(data["config"]).decode()))
    return SimResult(
        config=cfg,
        inst_ids=data["inst_ids"],
        rounds=data["rounds"],
        decision=data["decision"],
        wall_s=float(data["wall_s"]),
    )


def have_shard(out_dir: pathlib.Path, cfg: SimConfig, lo: int, hi: int) -> bool:
    return (out_dir / shard_name(cfg, lo, hi)).exists()
