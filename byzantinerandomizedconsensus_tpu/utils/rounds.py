"""Round bookkeeping for the round-over-round regression guards.

The driver names per-round artifacts ``BENCH_r{N}.json`` (and this repo names
``artifacts/product_r{N}.json`` / ``acceptance_r{N}.json`` the same way).
"Previous round" is anchored on VERDICT.md's heading — the newest artifact on
disk may be the *current* round's (the driver writes it right before a judge
rerun), and comparing against it would always read ~1.0 and mask regressions
(VERDICT r2 #4). ADVICE r3: when VERDICT.md exists but its heading cannot be
parsed, warn and omit the comparison instead of silently falling back to the
newest artifact.
"""

from __future__ import annotations

import json
import pathlib
import re
import sys
from typing import Optional


def repo_root() -> pathlib.Path:
    return pathlib.Path(__file__).resolve().parents[2]


def verdict_round(root=None) -> tuple[bool, Optional[int]]:
    """(verdict_exists, judged_round); judged_round is None when the heading
    cannot be parsed."""
    p = pathlib.Path(root or repo_root()) / "VERDICT.md"
    try:
        text = p.read_text()
    except OSError:
        return False, None
    m = re.search(r"VERDICT\s*[—-]+\s*round\s+(\d+)", text)
    return True, (int(m.group(1)) if m else None)


def this_round(root=None) -> Optional[int]:
    """The build round in progress: VERDICT's judged round + 1 (round 1 when no
    VERDICT exists yet); None when VERDICT exists but is unparseable."""
    exists, judged = verdict_round(root)
    if not exists:
        return 1
    return None if judged is None else judged + 1


def default_artifact(stem: str, root=None) -> str:
    """Round-stamped default artifact path: ``artifacts/{stem}_r{N}.json``,
    falling back to an unstamped name when the round is unknown (unparseable
    VERDICT heading). Single source for every tool's ``--out`` default so the
    naming scheme and :func:`prev_round_artifact`'s lookup cannot drift apart."""
    rnd = this_round(root)
    return (f"artifacts/{stem}_r{rnd}.json" if rnd
            else f"artifacts/{stem}.json")


def prev_round_artifact(stem: str, root=None, subdir: str = "", usable=None):
    """(name, round, parsed_json) of the newest ``{stem}_r*.json`` eligible as
    "previous round" (round ≤ VERDICT's judged round), or None.

    ``usable(doc) -> bool`` filters artifacts that parsed but carry no usable
    payload (e.g. a failed driver capture with no value): the search falls back
    to the next-older round instead of returning a dead artifact and silently
    disabling the regression guard.

    When VERDICT.md exists but its round heading cannot be parsed, emits a
    stderr warning and returns None — never the newest artifact, which right
    after a driver capture is the current run itself (ADVICE r3).
    """
    root = pathlib.Path(root or repo_root())
    exists, cap = verdict_round(root)
    if exists and cap is None:
        print(f"warning: VERDICT.md present but its round heading is "
              f"unparseable; omitting the {stem} vs_prev_round comparison "
              f"(falling back to the newest artifact risks self-comparison)",
              file=sys.stderr)
        return None
    candidates = []
    for p in (root / subdir if subdir else root).glob(f"{stem}_r*.json"):
        m = re.match(rf"{re.escape(stem)}_r0*(\d+)\.json", p.name)
        if not m:
            continue
        rnd = int(m.group(1))
        if cap is None or rnd <= cap:
            candidates.append((rnd, p))
    for rnd, p in sorted(candidates, reverse=True):
        try:
            doc = json.loads(p.read_text())
        except (OSError, ValueError):
            continue
        if usable is not None and not usable(doc):
            continue
        return (p.name, rnd, doc)
    return None
